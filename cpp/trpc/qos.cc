#include "trpc/qos.h"

#include <algorithm>
#include <cstdlib>
#include <sstream>

#include "tbase/flags.h"
#include "tbase/logging.h"
#include "tbase/time.h"
#include "tfiber/butex.h"
#include "tnet/fault_injection.h"

// The multi-tenant QoS tier is OFF by default: with no quotas configured
// and the flag off, a request pays one relaxed load and the dispatch
// path is byte-identical to the raw-speed round. Quotas configured via
// Server::SetTenantQuota or -rpc_tenant_quotas enable it implicitly.
DEFINE_bool(rpc_qos_enabled, false,
            "enable the multi-tenant fair-dispatch/overload tier even "
            "with no per-tenant quotas configured");
DEFINE_string(rpc_tenant_quotas, "",
              "per-tenant quotas: 'name:qps=300,burst=64,w=1,conc=8;...' "
              "(qps/conc 0 = unlimited; w = weighted-fair share)");
DEFINE_int32(rpc_fair_queue_highwater, 1024,
             "fair dispatch queue depth BACKSTOP for lowest-priority-"
             "first shedding (the primary shed signal is the measured "
             "queue delay; see -rpc_queue_delay_target_ms)");
DEFINE_int32(rpc_overload_backoff_ms, 50,
             "FLOOR of the server-suggested client backoff attached to "
             "TERR_OVERLOAD sheds; queue sheds derive the actual hint "
             "from the cost backlog over the measured drain rate "
             "(rate-quota sheds compute theirs from the refill time)");
DEFINE_int32(rpc_max_tenants, 64,
             "distinct tenant label values tracked; newcomers beyond "
             "this fold into the 'other' tenant (metric-cardinality "
             "bound)");
// ---- work-priced admission (ISSUE 15) ----
DEFINE_int32(rpc_cost_ref_us, 1000,
             "handler service-time microseconds that equal one cost "
             "unit (the time half of the cost model)");
DEFINE_int32(rpc_cost_ref_kb, 16,
             "logical payload KiB (inline + descriptor-exempt) that "
             "equal one cost unit (the bytes half of the cost model)");
DEFINE_int32(rpc_cost_max_methods, 32,
             "distinct methods tracked per tenant by the cost model; "
             "newcomers beyond this fold into one overflow bucket");
DEFINE_bool(rpc_tenant_gradient_limit, true,
            "tenants without an explicit conc= share get their own "
            "gradient (auto) concurrency limiter that converges from "
            "observed latency — no manual -max_concurrency tuning");
DEFINE_int32(rpc_queue_delay_target_ms, 20,
             "fair-queue sojourn target: when the MINIMUM sojourn over "
             "a full interval stays above this, arrivals shed (CoDel-"
             "style overload signal derived from measurement, not a "
             "static depth)");
DEFINE_int32(rpc_queue_delay_interval_ms, 100,
             "queue-delay observation interval (and the drain-rate "
             "estimation window)");
DEFINE_double(rpc_spill_cost_multiplier, 2.0,
              "admission-cost multiplier for cross-zone spill arrivals "
              "(request meta zone != -rpc_zone): a partitioned pod's "
              "overflow is priced above local work and sheds first "
              "within its priority level");
// Pod identity of THIS process (ISSUE 14; definition moved here in
// ISSUE 15 so the pb-free standalone qos suite links without the LB
// layer). Clients stamp it on the request meta; receivers price
// mismatching arrivals as spills; the zone-aware LB reads it too.
DEFINE_string(rpc_zone, "",
              "locality zone (pod) of this process; naming entries "
              "tagged zone=OTHER are treated as cross-pod (dcn tier, "
              "spill-only LB), and arrivals stamped with another zone "
              "are priced as spills. Empty = zoneless");

namespace tpurpc {

namespace {

// Labelled per-tenant families ({tenant="name"}), process-lifetime,
// created on first QoS use (runtime, never static-init) — the same
// pattern as the dispatcher's per-loop families.
LabelledMetric<IntCell>* tenant_admitted() {
    static auto* m =
        new LabelledMetric<IntCell>("rpc_tenant_admitted", {"tenant"});
    return m;
}
LabelledMetric<IntCell>* tenant_shed() {
    static auto* m =
        new LabelledMetric<IntCell>("rpc_tenant_shed", {"tenant"});
    return m;
}
LabelledMetric<IntCell>* tenant_queued() {
    static auto* m =
        new LabelledMetric<IntCell>("rpc_tenant_queued", {"tenant"});
    return m;
}
LabelledMetric<LatencyRecorder>* tenant_latency() {
    static auto* m = new LabelledMetric<LatencyRecorder>(
        "rpc_tenant_latency_us", {"tenant"});
    return m;
}
// Work-priced admission families (ISSUE 15): estimated milli-cost
// admitted/shed per tenant, the measured per-request cost distribution,
// and the gradient limiter's live limit.
LabelledMetric<IntCell>* tenant_cost_admitted() {
    static auto* m =
        new LabelledMetric<IntCell>("rpc_tenant_cost_admitted", {"tenant"});
    return m;
}
LabelledMetric<IntCell>* tenant_cost_shed() {
    static auto* m =
        new LabelledMetric<IntCell>("rpc_tenant_cost_shed", {"tenant"});
    return m;
}
LabelledMetric<LatencyRecorder>* tenant_cost_units() {
    static auto* m = new LabelledMetric<LatencyRecorder>(
        "rpc_tenant_cost_units", {"tenant"});
    return m;
}
LabelledMetric<IntCell>* tenant_gradient_limit() {
    static auto* m = new LabelledMetric<IntCell>(
        "rpc_tenant_gradient_limit", {"tenant"});
    return m;
}

// Process-wide overload accounting (the soak's cross-tenant asserts).
LazyAdder g_overload_sheds("rpc_server_overload_sheds");
LazyAdder g_overload_evictions("rpc_server_overload_evictions");
// Process-wide cost totals (milli-units; the mesh_node REPORT reads
// them by name so a dying incarnation's numbers survive its portal).
LazyAdder g_cost_admitted_milli("rpc_server_cost_admitted");
LazyAdder g_cost_shed_milli("rpc_server_cost_shed");

// Measured fair-queue sojourn distribution (the soak asserts its p99;
// exposed eagerly from the first Configure so the lint sees the family
// on an idle qos-enabled node).
LatencyRecorder* queue_delay_recorder() {
    static LatencyRecorder* r = [] {
        auto* x = new LatencyRecorder;
        x->expose("rpc_server_queue_delay_us");
        return x;
    }();
    return r;
}

// Eager 0-valued exposure (lint contract: a 0-valued family is data, a
// missing one is not).
void ExposeCostVars() {
    *g_cost_admitted_milli << 0;
    *g_cost_shed_milli << 0;
    queue_delay_recorder();
}

uint64_t mix64(uint64_t k) {
    k ^= k >> 33;
    k *= 0xff51afd7ed558ccdULL;
    k ^= k >> 33;
    k *= 0xc4ceb9fe1a85ec53ULL;
    k ^= k >> 33;
    return k;
}

uint64_t hash_key(uint64_t seed, const std::string& s) {
    uint64_t h = seed;
    for (char c : s) h = mix64(h ^ (uint8_t)c);
    return mix64(h);
}

// Cost-model bounds: one sample is capped at 1024 units so a wedged
// handler cannot park its tenant's bucket in unbounded debt; the DRR
// charge is capped lower still so a single item's deficit repayment
// stays within one bounded grant loop.
constexpr int64_t kMaxCostMilli = 1024 * kCostUnitMilli;
constexpr int64_t kDrrMaxChargeMilli = 64 * kCostUnitMilli;
// One DRR grant round adds weight * this to a tenant's deficit.
constexpr int64_t kDrrQuantumMilli = kCostUnitMilli;
// Grant-round bound per Pop: enough to repay the biggest chargeable
// item at weight 1, plus slack (pure in-memory math, so cheap).
constexpr int kMaxDrrGrantRounds =
    (int)(kDrrMaxChargeMilli / kDrrQuantumMilli) + 8;
// EWMA smoothing for the per-method cost model: fast enough that a
// chaos cost_inflate plan visibly moves the estimate within a soak
// phase, slow enough that one outlier doesn't reprice the tenant.
constexpr int kCostEwmaShift = 2;  // new = old + (sample - old) / 4
// Method-cost overflow bucket (cardinality bound).
const char kOtherMethod[] = "other";

}  // namespace

// ---------------- cost model ----------------

int64_t ComputeCostMilli(int64_t svc_us, int64_t logical_bytes) {
    const int64_t ref_us =
        std::max(1, FLAGS_rpc_cost_ref_us.get());
    const int64_t ref_bytes =
        (int64_t)std::max(1, FLAGS_rpc_cost_ref_kb.get()) * 1024;
    int64_t m = 0;
    if (svc_us > 0) m += svc_us * kCostUnitMilli / ref_us;
    if (logical_bytes > 0) {
        m += logical_bytes * kCostUnitMilli / ref_bytes;
    }
    if (m < kCostUnitMilli) return kCostUnitMilli;
    if (m > kMaxCostMilli) return kMaxCostMilli;
    return m;
}

bool SpillArrival(const std::string& peer_zone) {
    if (peer_zone.empty()) return false;
    const std::string my_zone = FLAGS_rpc_zone.get();
    return !my_zone.empty() && peer_zone != my_zone;
}

int64_t SpillAdjustedCostMilli(int64_t cost_milli) {
    const double mult =
        std::max(1.0, FLAGS_rpc_spill_cost_multiplier.get());
    const double adj = (double)cost_milli * mult;
    return adj > (double)kMaxCostMilli ? kMaxCostMilli : (int64_t)adj;
}

// ---------------- quota spec ----------------

bool ParseQuotaSpec(const std::string& spec,
                    std::map<std::string, TenantQuota>* out) {
    bool clean = true;
    size_t pos = 0;
    while (pos < spec.size()) {
        size_t semi = spec.find(';', pos);
        if (semi == std::string::npos) semi = spec.size();
        const std::string entry = spec.substr(pos, semi - pos);
        pos = semi + 1;
        if (entry.empty()) continue;
        const size_t colon = entry.find(':');
        if (colon == std::string::npos || colon == 0) {
            clean = false;
            continue;
        }
        const std::string name = entry.substr(0, colon);
        TenantQuota q;
        size_t kpos = colon + 1;
        while (kpos < entry.size()) {
            size_t comma = entry.find(',', kpos);
            if (comma == std::string::npos) comma = entry.size();
            const std::string kv = entry.substr(kpos, comma - kpos);
            kpos = comma + 1;
            const size_t eq = kv.find('=');
            if (eq == std::string::npos) {
                if (!kv.empty()) clean = false;
                continue;
            }
            const std::string k = kv.substr(0, eq);
            const char* v = kv.c_str() + eq + 1;
            char* end = nullptr;
            const double num = strtod(v, &end);
            if (end == v || *end != '\0') {
                clean = false;
                continue;
            }
            if (k == "qps") {
                q.qps = num;
            } else if (k == "burst") {
                q.burst = (int64_t)num;
            } else if (k == "w" || k == "weight") {
                q.weight = std::max(1, (int)num);
            } else if (k == "conc") {
                q.max_concurrency = (int64_t)num;
            } else {
                clean = false;
            }
        }
        (*out)[name] = q;
    }
    return clean;
}

// ---------------- token bucket ----------------

void TokenBucket::Configure(double rate_per_s, int64_t burst) {
    rate_milli_per_s_.store(
        rate_per_s > 0 ? (int64_t)(rate_per_s * 1000) : 0,
        std::memory_order_relaxed);
    if (burst <= 0) {
        burst = std::max<int64_t>((int64_t)(rate_per_s / 10), 8);
    }
    burst_milli_.store(burst * 1000, std::memory_order_relaxed);
    tokens_milli_.store(burst * 1000, std::memory_order_relaxed);
    last_refill_us_.store(monotonic_time_us(), std::memory_order_relaxed);
}

void TokenBucket::RefillLocked(int64_t now_us) {
    const int64_t last = last_refill_us_.load(std::memory_order_relaxed);
    const int64_t elapsed_us = now_us - last;
    if (elapsed_us < 1000) return;  // sub-ms refills round to nothing
    std::lock_guard<std::mutex> g(refill_mu_);
    const int64_t last2 = last_refill_us_.load(std::memory_order_relaxed);
    if (now_us - last2 < 1000) return;  // another admitter refilled
    const int64_t add_milli =
        (now_us - last2) *
        rate_milli_per_s_.load(std::memory_order_relaxed) / 1000000;
    if (add_milli <= 0) return;
    last_refill_us_.store(now_us, std::memory_order_relaxed);
    const int64_t burst = burst_milli_.load(std::memory_order_relaxed);
    int64_t cur = tokens_milli_.load(std::memory_order_relaxed);
    while (cur < burst) {
        const int64_t next = std::min(burst, cur + add_milli);
        if (tokens_milli_.compare_exchange_weak(cur, next,
                                                std::memory_order_relaxed)) {
            break;
        }
    }
}

bool TokenBucket::TryWithdrawCost(int64_t now_us, int64_t cost_milli,
                                  int64_t* wait_ms) {
    const int64_t rate = rate_milli_per_s_.load(std::memory_order_relaxed);
    if (rate <= 0) return true;
    if (cost_milli < 1) cost_milli = 1;
    RefillLocked(now_us);
    // A cost above the burst depth could never see `tokens >= cost`:
    // admit it at a FULL bucket instead and let the balance go negative
    // (debt) — the call is rate-priced exactly, never starved forever.
    const int64_t burst = burst_milli_.load(std::memory_order_relaxed);
    const int64_t need = std::min(cost_milli, std::max<int64_t>(burst, 1));
    int64_t cur = tokens_milli_.load(std::memory_order_relaxed);
    while (cur >= need) {
        if (tokens_milli_.compare_exchange_weak(cur, cur - cost_milli,
                                                std::memory_order_relaxed)) {
            return true;
        }
    }
    if (wait_ms != nullptr) {
        // Time until the required tokens accrue at the configured rate,
        // clamped to something a client can reasonably sleep.
        const int64_t deficit_milli = need - std::min<int64_t>(cur, need);
        int64_t ms = deficit_milli * 1000 / std::max<int64_t>(rate, 1);
        *wait_ms = std::min<int64_t>(std::max<int64_t>(ms, 1), 2000);
    }
    return false;
}

// ---------------- rendezvous subsetting ----------------

std::vector<size_t> RendezvousSubset(uint64_t seed,
                                     const std::vector<std::string>& keys,
                                     size_t k) {
    std::vector<size_t> out;
    if (k == 0 || keys.empty()) return out;
    if (keys.size() <= k) {
        out.resize(keys.size());
        for (size_t i = 0; i < keys.size(); ++i) out[i] = i;
        return out;
    }
    std::vector<std::pair<uint64_t, size_t>> scored;
    scored.reserve(keys.size());
    for (size_t i = 0; i < keys.size(); ++i) {
        scored.emplace_back(hash_key(seed, keys[i]), i);
    }
    // Top-k by score; ties broken by index for determinism.
    std::partial_sort(scored.begin(), scored.begin() + k, scored.end(),
                      [](const std::pair<uint64_t, size_t>& a,
                         const std::pair<uint64_t, size_t>& b) {
                          return a.first != b.first ? a.first > b.first
                                                    : a.second < b.second;
                      });
    out.reserve(k);
    for (size_t i = 0; i < k; ++i) out.push_back(scored[i].second);
    return out;
}

// ---------------- QosDispatcher ----------------

QosDispatcher::QosDispatcher() { wake_butex_ = butex_create(); }

QosDispatcher::~QosDispatcher() {
    StopDrainer();
    butex_destroy(wake_butex_);
}

namespace {
// Apply a quota onto a live tenant: the display copy under the
// registry's exclusive lock, the dispatch-gating fields as atomics.
void ApplyQuota(QosDispatcher::TenantState* t, const TenantQuota& q) {
    t->quota = q;
    t->weight.store(std::max(1, q.weight), std::memory_order_relaxed);
    t->max_concurrency.store(q.max_concurrency, std::memory_order_relaxed);
    t->bucket.Configure(q.qps, q.burst);
}
}  // namespace

void QosDispatcher::Configure(const std::map<std::string, TenantQuota>& quotas,
                              bool force_enable) {
    std::unique_lock<std::shared_mutex> g(tenants_mu_);
    // Merged view: the flag's quotas, with explicit SetTenantQuota
    // entries layered on top — "explicit calls override the flag per
    // tenant", including calls made BEFORE Start.
    configured_ = quotas;
    for (const auto& [name, q] : explicit_) configured_[name] = q;
    for (const auto& [name, q] : configured_) {
        auto it = tenants_.find(name);
        if (it != tenants_.end()) ApplyQuota(it->second.get(), q);
    }
    const bool on = force_enable || !configured_.empty();
    if (on) ExposeCostVars();
    enabled_.store(on, std::memory_order_release);
}

void QosDispatcher::SetTenantQuota(const std::string& tenant,
                                   const TenantQuota& q) {
    std::unique_lock<std::shared_mutex> g(tenants_mu_);
    const std::string name = tenant.empty() ? "default" : tenant;
    explicit_[name] = q;
    configured_[name] = q;
    auto it = tenants_.find(name);
    if (it != tenants_.end()) ApplyQuota(it->second.get(), q);
    ExposeCostVars();
    enabled_.store(true, std::memory_order_release);
}

QosDispatcher::TenantState* QosDispatcher::Acquire(
    const std::string& tenant) {
    std::string name = tenant.empty() ? "default" : tenant;
    {
        // Fast path: the tenant exists (every request after the first) —
        // a shared lock keeps the admission paths of the sharded event
        // loops from serializing on this registry.
        std::shared_lock<std::shared_mutex> g(tenants_mu_);
        auto it = tenants_.find(name);
        if (it != tenants_.end()) return it->second.get();
    }
    std::unique_lock<std::shared_mutex> g(tenants_mu_);
    auto it = tenants_.find(name);
    if (it == tenants_.end()) {
        // Cardinality bound: an attacker minting fresh tenant names per
        // request must not flood the metric registry or the DRR table.
        // Known (configured) tenants always get their own slot.
        if ((int64_t)tenants_.size() >=
                (int64_t)FLAGS_rpc_max_tenants.get() &&
            configured_.find(name) == configured_.end() &&
            name != "other") {
            name = "other";
            it = tenants_.find(name);
        }
    }
    if (it == tenants_.end()) {
        auto st = std::make_unique<TenantState>();
        st->name = name;
        auto cit = configured_.find(name);
        if (cit != configured_.end()) ApplyQuota(st.get(), cit->second);
        st->admitted = tenant_admitted()->get_stats({name});
        st->shed = tenant_shed()->get_stats({name});
        st->queued = tenant_queued()->get_stats({name});
        st->latency = tenant_latency()->get_stats({name});
        st->cost_admitted = tenant_cost_admitted()->get_stats({name});
        st->cost_shed = tenant_cost_shed()->get_stats({name});
        st->cost_units = tenant_cost_units()->get_stats({name});
        st->gradient_limit_cell = tenant_gradient_limit()->get_stats({name});
        // Every tenant carries a gradient limiter; it only GATES when no
        // explicit conc= share is configured (TenantConcurrencyLimit),
        // so a runtime re-quota flips cleanly between the two without a
        // lifetime race on the dispatch paths.
        st->gradient =
            std::make_unique<AutoConcurrencyLimiter>(gradient_opts_);
        st->gradient_limit_cell->set(st->gradient->MaxConcurrency());
        it = tenants_.emplace(name, std::move(st)).first;
    }
    return it->second.get();
}

void QosDispatcher::SetGradientOptions(
    const AutoConcurrencyLimiter::Options& opt) {
    std::unique_lock<std::shared_mutex> g(tenants_mu_);
    gradient_opts_ = opt;
}

int64_t QosDispatcher::EstimateCostMilli(TenantState* t,
                                         const std::string& method) const {
    std::shared_lock<std::shared_mutex> g(t->cost_mu);
    auto it = t->method_cost_milli.find(method);
    if (it == t->method_cost_milli.end()) {
        it = t->method_cost_milli.find(kOtherMethod);
        if (it == t->method_cost_milli.end()) return kCostUnitMilli;
    }
    return it->second;
}

bool QosDispatcher::AdmitCost(TenantState* t, int64_t now_us,
                              int64_t cost_milli, int64_t* backoff_ms) {
    if (t->bucket.TryWithdrawCost(now_us, cost_milli, backoff_ms)) {
        return true;
    }
    CountShed(t, cost_milli);
    return false;
}

int64_t QosDispatcher::TenantConcurrencyLimit(const TenantState* t) const {
    const int64_t maxc = t->max_concurrency.load(std::memory_order_relaxed);
    if (maxc > 0) return maxc;  // explicit share wins
    if (t->gradient != nullptr && FLAGS_rpc_tenant_gradient_limit.get()) {
        return t->gradient->MaxConcurrency();
    }
    return 0;
}

bool QosDispatcher::TryDirectDispatch(TenantState* t, int64_t cost_milli) {
    if (depth_.load(std::memory_order_relaxed) != 0) {
        return false;  // fairness first: join the queue behind the others
    }
    const int64_t limit = TenantConcurrencyLimit(t);
    const int64_t cur =
        t->inflight.fetch_add(1, std::memory_order_relaxed) + 1;
    if (limit > 0 && cur > limit) {
        t->inflight.fetch_sub(1, std::memory_order_relaxed);
        return false;  // over its limit: queue (drainer re-checks)
    }
    t->admitted->add(1);
    t->cost_admitted->add(cost_milli);
    *g_cost_admitted_milli << cost_milli;
    return true;
}

void QosDispatcher::BeginServed(TenantState* t, int64_t cost_milli) {
    t->inflight.fetch_add(1, std::memory_order_relaxed);
    t->admitted->add(1);
    t->cost_admitted->add(cost_milli);
    *g_cost_admitted_milli << cost_milli;
}

void QosDispatcher::OnDone(TenantState* t, int64_t latency_us,
                           const CompletionInfo& info) {
    t->inflight.fetch_sub(1, std::memory_order_relaxed);
    *t->latency << latency_us;
    // Gradient feedback: only while the gradient actually gates (an
    // explicit conc= share wins) — its estimate then converges from the
    // tenant's own observed latency, failures punishing the average.
    if (t->gradient != nullptr &&
        t->max_concurrency.load(std::memory_order_relaxed) <= 0 &&
        FLAGS_rpc_tenant_gradient_limit.get()) {
        t->gradient->OnResponded(info.error_code, latency_us);
        t->gradient_limit_cell->set(t->gradient->MaxConcurrency());
    }
    // Cost observation: fold the measured work into the (tenant,
    // method) EWMA the NEXT request of this shape is charged.
    if (info.method != nullptr) {
        int64_t measured =
            ComputeCostMilli(latency_us, info.logical_bytes);
        // Chaos seam (ISSUE 15): a cost_inflate plan inflates the
        // MEASURED cost so soaks can reprice a method without moving
        // real bytes (deterministic, per-peer scopable like every
        // other chaos decision).
        if (__builtin_expect(fault_injection_enabled(), 0)) {
            const FaultAction fault = FaultInjection::Decide(
                FaultOp::kCostMeasure, info.peer,
                (size_t)info.logical_bytes);
            if (fault.kind == FaultAction::kInflate) {
                measured = std::min<int64_t>(
                    kMaxCostMilli,
                    measured * std::max<int64_t>(2, (int64_t)fault.aux));
            }
        }
        *t->cost_units << measured;
        std::unique_lock<std::shared_mutex> g(t->cost_mu);
        std::string key = *info.method;
        auto it = t->method_cost_milli.find(key);
        if (it == t->method_cost_milli.end() &&
            (int64_t)t->method_cost_milli.size() >=
                (int64_t)std::max(1, FLAGS_rpc_cost_max_methods.get())) {
            key = kOtherMethod;  // cardinality bound, like tenants
            it = t->method_cost_milli.find(key);
        }
        if (it == t->method_cost_milli.end()) {
            t->method_cost_milli[key] = measured;
        } else {
            it->second += (measured - it->second) >> kCostEwmaShift;
        }
    }
    // A freed concurrency share may unblock this tenant's queued work.
    if (depth_.load(std::memory_order_relaxed) > 0) WakeDrainer();
}

void QosDispatcher::CountShed(TenantState* t, int64_t cost_milli) {
    t->shed->add(1);
    t->cost_shed->add(cost_milli);
    *g_overload_sheds << 1;
    *g_cost_shed_milli << cost_milli;
}

int64_t QosDispatcher::SuggestedBackoffMs() const {
    const int64_t floor_ms = std::max(1, FLAGS_rpc_overload_backoff_ms.get());
    // Drain-derived hint: time until the current cost backlog drains at
    // the measured rate — "come back when the queue has emptied", not a
    // static guess. Cold queue (no drain measurement yet): the floor.
    const int64_t rate =
        drain_rate_milli_per_s_.load(std::memory_order_relaxed);
    const int64_t backlog =
        backlog_cost_milli_.load(std::memory_order_relaxed);
    int64_t ms = floor_ms;
    if (rate > 0 && backlog > 0) {
        ms = std::max(ms, backlog * 1000 / rate);
    }
    return std::min<int64_t>(ms, 2000);
}

void QosDispatcher::AccountDequeueLocked(const Item& it, int64_t now_us,
                                         bool served) {
    backlog_cost_milli_.fetch_sub(it.cost_milli,
                                  std::memory_order_relaxed);
    if (!served) return;
    // Sojourn measurement (the CoDel signal): how long this item really
    // waited. Evictions are excluded — a shed's wait says nothing about
    // the speed of the SERVING path.
    const int64_t sojourn_us =
        it.enqueue_us > 0 ? std::max<int64_t>(0, now_us - it.enqueue_us)
                          : 0;
    int64_t ewma = queue_delay_ewma_us_.load(std::memory_order_relaxed);
    queue_delay_ewma_us_.store(ewma + ((sojourn_us - ewma) >> 3),
                               std::memory_order_relaxed);
    const int64_t interval_us =
        (int64_t)std::max(1, FLAGS_rpc_queue_delay_interval_ms.get()) *
        1000;
    const int64_t target_us =
        (int64_t)std::max(1, FLAGS_rpc_queue_delay_target_ms.get()) * 1000;
    if (interval_start_us_ == 0) interval_start_us_ = now_us;
    if (interval_min_sojourn_us_ < 0 ||
        sojourn_us < interval_min_sojourn_us_) {
        interval_min_sojourn_us_ = sojourn_us;
    }
    if (now_us - interval_start_us_ >= interval_us) {
        // A whole interval where even the BEST-off dequeue waited past
        // the target = standing queue (overload); one good interval (or
        // an empty queue, below) clears it.
        over_target_.store(interval_min_sojourn_us_ > target_us,
                           std::memory_order_relaxed);
        interval_start_us_ = now_us;
        interval_min_sojourn_us_ = -1;
    }
    // Drain-rate window: cost served per second, EWMA-folded.
    if (drain_window_start_us_ == 0) drain_window_start_us_ = now_us;
    drain_window_cost_milli_ += it.cost_milli;
    const int64_t elapsed = now_us - drain_window_start_us_;
    if (elapsed >= interval_us) {
        const int64_t rate = drain_window_cost_milli_ * 1000000 / elapsed;
        int64_t cur =
            drain_rate_milli_per_s_.load(std::memory_order_relaxed);
        drain_rate_milli_per_s_.store(
            cur <= 0 ? rate : cur + ((rate - cur) >> 2),
            std::memory_order_relaxed);
        drain_window_start_us_ = now_us;
        drain_window_cost_milli_ = 0;
    }
    if (depth_.load(std::memory_order_relaxed) == 0) {
        // Empty queue = no standing delay, whatever the last interval
        // said.
        over_target_.store(false, std::memory_order_relaxed);
        interval_start_us_ = 0;
        interval_min_sojourn_us_ = -1;
    }
    // Recorder write is cheap (TLS cell) and safe under mu_.
    *queue_delay_recorder() << sojourn_us;
}

bool QosDispatcher::EvictLowestLocked(int limit_prio,
                                      std::vector<Item>* out_shed,
                                      std::vector<TenantState*>* out_owners) {
    for (int p = kMinPriority; p < limit_prio; ++p) {
        Level& lvl = levels_[p];
        if (lvl.active.empty()) continue;
        // Spills shed first within a level (ISSUE 15d): a partitioned
        // pod's overflow must not survive at local work's expense. The
        // deepest spill-HOLDING queue loses its NEWEST spill item; the
        // per-tenant spill_count keeps the (common) no-spill case from
        // walking any queue's items under mu_.
        TenantState* victim = nullptr;
        size_t victim_idx = 0;
        for (TenantState* t : lvl.active) {
            if (t->spill_count[p] <= 0) continue;
            if (victim == nullptr ||
                t->q[p].size() > victim->q[p].size()) {
                victim = t;
            }
        }
        if (victim != nullptr) {
            for (size_t i = victim->q[p].size(); i-- > 0;) {
                if (victim->q[p][i].spill) {
                    victim_idx = i;  // newest spill of the victim
                    break;
                }
            }
        }
        if (victim == nullptr) {
            // No spills: the deepest queue at this level sheds first —
            // under a flood that is the flooder, so a polite
            // same-priority tenant keeps its (short) backlog. Newest
            // first (LIFO shed): the oldest queued request is closest
            // to being served; the newest has waited least and its
            // client retries latest.
            for (TenantState* t : lvl.active) {
                if (t->q[p].empty()) continue;
                if (victim == nullptr ||
                    t->q[p].size() > victim->q[p].size()) {
                    victim = t;
                }
            }
            if (victim == nullptr) continue;
            victim_idx = victim->q[p].size() - 1;
        }
        const Item it = victim->q[p][victim_idx];
        out_shed->push_back(it);
        out_owners->push_back(victim);
        victim->q[p].erase(victim->q[p].begin() + (ptrdiff_t)victim_idx);
        victim->queued->add(-1);
        if (it.spill) --victim->spill_count[p];
        depth_.fetch_sub(1, std::memory_order_relaxed);
        AccountDequeueLocked(it, monotonic_time_us(), /*served=*/false);
        return true;
    }
    return false;
}

bool QosDispatcher::Enqueue(TenantState* t, int priority, const Item& item) {
    const int p = ClampPriority(priority);
    std::vector<Item> to_shed;
    std::vector<TenantState*> shed_owners;
    bool self_shed = false;
    {
        std::lock_guard<std::mutex> g(mu_);
        if (stop_.load(std::memory_order_acquire)) {
            self_shed = true;  // draining dispatcher: answer, don't hold
        } else {
            // Shed signal (ISSUE 15c): the MEASURED queue delay — a
            // standing sojourn above the target for a whole interval —
            // with the static high-water kept only as the absolute
            // depth backstop. Either way the eviction ordering stays
            // lowest-priority-first (spills before local work).
            const int64_t hw =
                std::max(1, FLAGS_rpc_fair_queue_highwater.get());
            const int64_t depth = depth_.load(std::memory_order_relaxed);
            const bool overloaded =
                depth >= hw ||
                (depth > 0 &&
                 over_target_.load(std::memory_order_relaxed));
            if (overloaded &&
                !EvictLowestLocked(p, &to_shed, &shed_owners)) {
                self_shed = true;  // nothing below this priority: shed self
            }
        }
        if (!self_shed) {
            Item stamped = item;
            if (stamped.enqueue_us == 0) {
                stamped.enqueue_us = monotonic_time_us();
            }
            t->q[p].push_back(stamped);
            t->queued->add(1);
            if (stamped.spill) ++t->spill_count[p];
            depth_.fetch_add(1, std::memory_order_relaxed);
            backlog_cost_milli_.fetch_add(stamped.cost_milli,
                                          std::memory_order_relaxed);
            if (!t->in_active[p]) {
                levels_[p].active.push_back(t);
                t->in_active[p] = true;
            }
        }
    }
    const int64_t backoff = SuggestedBackoffMs();
    for (size_t i = 0; i < to_shed.size(); ++i) {
        CountShed(shed_owners[i], to_shed[i].cost_milli);
        *g_overload_evictions << 1;
        to_shed[i].shed(to_shed[i].arg, backoff);
    }
    if (self_shed) {
        CountShed(t, item.cost_milli);
        item.shed(item.arg, backoff);
        return false;
    }
    WakeDrainer();
    return true;
}

bool QosDispatcher::EvictOneBelow(int priority) {
    std::vector<Item> to_shed;
    std::vector<TenantState*> owners;
    {
        std::lock_guard<std::mutex> g(mu_);
        if (!EvictLowestLocked(ClampPriority(priority), &to_shed, &owners)) {
            return false;
        }
    }
    const int64_t backoff = SuggestedBackoffMs();
    CountShed(owners[0], to_shed[0].cost_milli);
    *g_overload_evictions << 1;
    to_shed[0].shed(to_shed[0].arg, backoff);
    return true;
}

bool QosDispatcher::PopLocked(Item* out, TenantState** owner,
                              int* priority) {
    for (int p = kMaxPriority; p >= kMinPriority; --p) {
        Level& lvl = levels_[p];
        if (lvl.active.empty()) continue;
        // Cost-DRR (ISSUE 15a): a tenant serves when its deficit covers
        // its head item's (capped) cost; a pass where nothing is
        // servable grants every eligible tenant weight * quantum and
        // tries again — so one heavy dequeue burns many turns' worth of
        // deficit and the tenant waits proportionally before its next.
        // Bounded: grant rounds repay the biggest chargeable item in
        // <= kMaxDrrGrantRounds passes of pure in-memory math.
        for (int round = 0; round < kMaxDrrGrantRounds; ++round) {
            bool any_eligible = false;
            size_t n = lvl.active.size();
            for (size_t i = 0; i < n && !lvl.active.empty(); ++i) {
                TenantState* t = lvl.active.front();
                if (t->q[p].empty()) {
                    lvl.active.pop_front();
                    t->in_active[p] = false;
                    t->deficit[p] = 0;
                    continue;
                }
                const int64_t limit = TenantConcurrencyLimit(t);
                if (limit > 0 &&
                    t->inflight.load(std::memory_order_relaxed) >= limit) {
                    // Over its concurrency limit: rotate so the other
                    // tenants at this level aren't blocked behind it
                    // (OnDone re-wakes the drainer when a share frees).
                    lvl.active.pop_front();
                    lvl.active.push_back(t);
                    continue;
                }
                any_eligible = true;
                const int64_t charge = std::min(
                    t->q[p].front().cost_milli, kDrrMaxChargeMilli);
                if (t->deficit[p] < charge) {
                    lvl.active.pop_front();
                    lvl.active.push_back(t);
                    continue;  // not this tenant's turn yet
                }
                *out = t->q[p].front();
                t->q[p].pop_front();
                t->queued->add(-1);
                if (out->spill) --t->spill_count[p];
                depth_.fetch_sub(1, std::memory_order_relaxed);
                t->deficit[p] -= charge;
                if (t->q[p].empty()) {
                    lvl.active.pop_front();
                    t->in_active[p] = false;
                    t->deficit[p] = 0;  // classic DRR: no hoarding
                } else if (t->deficit[p] <
                           std::min(t->q[p].front().cost_milli,
                                    kDrrMaxChargeMilli)) {
                    lvl.active.pop_front();
                    lvl.active.push_back(t);
                }
                *owner = t;
                *priority = p;
                return true;
            }
            if (!any_eligible) break;  // level drained / all blocked
            // Nothing servable with current deficits: one grant round.
            for (TenantState* t : lvl.active) {
                if (t->q[p].empty()) continue;
                const int64_t limit = TenantConcurrencyLimit(t);
                if (limit > 0 &&
                    t->inflight.load(std::memory_order_relaxed) >= limit) {
                    continue;  // blocked tenants don't accrue deficit
                }
                t->deficit[p] +=
                    (int64_t)t->weight.load(std::memory_order_relaxed) *
                    kDrrQuantumMilli;
            }
        }
    }
    return false;
}

bool QosDispatcher::Pop(Item* out, TenantState** owner, int* priority) {
    std::lock_guard<std::mutex> g(mu_);
    if (!PopLocked(out, owner, priority)) return false;
    // Popped = admitted to service: same accounting as direct dispatch,
    // plus the sojourn/drain measurements the shed signal and the
    // backoff hint derive from.
    (*owner)->inflight.fetch_add(1, std::memory_order_relaxed);
    (*owner)->admitted->add(1);
    (*owner)->cost_admitted->add(out->cost_milli);
    *g_cost_admitted_milli << out->cost_milli;
    AccountDequeueLocked(*out, monotonic_time_us(), /*served=*/true);
    return true;
}

void QosDispatcher::WakeDrainer() {
    butex_word(wake_butex_)->fetch_add(1, std::memory_order_release);
    butex_wake_all(wake_butex_);
}

void* QosDispatcher::DrainerThunk(void* arg) {
    ((QosDispatcher*)arg)->DrainerLoop();
    return nullptr;
}

void QosDispatcher::DrainerLoop() {
    while (true) {
        const int seq =
            butex_word(wake_butex_)->load(std::memory_order_acquire);
        Item it;
        TenantState* t = nullptr;
        int p = 0;
        if (Pop(&it, &t, &p)) {
            // run() spawns the handler in the BACKGROUND (never inline:
            // user code on this fiber would serialize the whole queue
            // behind one handler).
            it.run(it.arg);
            continue;
        }
        if (stop_.load(std::memory_order_acquire)) return;
        // Backstop timeout covers the wake-before-wait race exactly like
        // Server::JoinUntil; the wake path is the enqueue/OnDone bump.
        const int64_t abst = monotonic_time_us() + 100 * 1000;
        butex_wait(wake_butex_, seq, &abst);
    }
}

void QosDispatcher::StartDrainer() {
    std::lock_guard<std::mutex> g(drainer_mu_);
    if (drainer_running_) return;
    stop_.store(false, std::memory_order_release);
    if (fiber_start_background(&drainer_, nullptr, DrainerThunk, this) ==
        0) {
        drainer_running_ = true;
    } else {
        LOG(ERROR) << "QoS drainer fiber failed to start";
    }
}

void QosDispatcher::StopDrainer() {
    bool was_running;
    {
        std::lock_guard<std::mutex> g(drainer_mu_);
        was_running = drainer_running_;
        drainer_running_ = false;
    }
    stop_.store(true, std::memory_order_release);
    if (was_running) {
        WakeDrainer();
        fiber_join(drainer_, nullptr);
    }
    // Shed everything still queued — even when the drainer never ran
    // (a runtime-enabled tier racing Stop): each item holds a counted
    // admission (BeginRequest), and leaking one would hang Server::Join
    // forever.
    while (true) {
        std::vector<Item> items;
        std::vector<TenantState*> owners;
        {
            std::lock_guard<std::mutex> g(mu_);
            for (int p = kMinPriority; p <= kMaxPriority; ++p) {
                for (TenantState* t : levels_[p].active) {
                    while (!t->q[p].empty()) {
                        items.push_back(t->q[p].front());
                        backlog_cost_milli_.fetch_sub(
                            t->q[p].front().cost_milli,
                            std::memory_order_relaxed);
                        owners.push_back(t);
                        t->q[p].pop_front();
                        t->queued->add(-1);
                        depth_.fetch_sub(1, std::memory_order_relaxed);
                    }
                    t->in_active[p] = false;
                    t->deficit[p] = 0;
                    t->spill_count[p] = 0;
                }
                levels_[p].active.clear();
            }
            over_target_.store(false, std::memory_order_relaxed);
            interval_start_us_ = 0;
            interval_min_sojourn_us_ = -1;
        }
        if (items.empty()) break;
        for (size_t i = 0; i < items.size(); ++i) {
            CountShed(owners[i], items[i].cost_milli);
            items[i].shed(items[i].arg, SuggestedBackoffMs());
        }
    }
}

std::string QosDispatcher::DescribeText() const {
    std::ostringstream os;
    os << "multi-tenant QoS: "
       << (enabled() ? "enabled" : "disabled (set -rpc_qos_enabled or "
                                   "-rpc_tenant_quotas)")
       << "\nfair queue depth: " << queue_depth()
       << " (highwater " << FLAGS_rpc_fair_queue_highwater.get()
       << " backstop)"
       << "\nqueue delay: ewma " << QueueDelayEwmaUs() << "us, p99 "
       << queue_delay_recorder()->latency_percentile(0.99)
       << "us (target " << FLAGS_rpc_queue_delay_target_ms.get()
       << "ms, over_target " << (OverDelayTarget() ? 1 : 0) << ")"
       << "\ndrain rate: " << DrainRateCostPerS()
       << " cost units/s; cost backlog: "
       << backlog_cost_milli_.load(std::memory_order_relaxed) /
              kCostUnitMilli
       << " units; suggested backoff: " << SuggestedBackoffMs()
       << "ms\n\n";
    char line[320];
    snprintf(line, sizeof(line),
             "%-16s %6s %8s %6s %6s %6s %9s %10s %10s %8s %10s %10s %10s "
             "%9s\n",
             "tenant", "weight", "cost_cap", "burst", "conc", "glimit",
             "inflight", "admitted", "shed", "queued", "p99_us",
             "cost_adm", "cost_shed", "est_cost");
    os << line;
    std::shared_lock<std::shared_mutex> g(tenants_mu_);
    for (const auto& [name, t] : tenants_) {
        // est_cost: the priciest method EWMA this tenant has taught the
        // model (whole units); glimit: the gradient limit actually
        // gating (0 = explicit share or unlimited).
        int64_t est = kCostUnitMilli;
        {
            std::shared_lock<std::shared_mutex> cg(t->cost_mu);
            for (const auto& [m, c] : t->method_cost_milli) {
                est = std::max(est, c);
            }
        }
        const int64_t maxc =
            t->max_concurrency.load(std::memory_order_relaxed);
        snprintf(line, sizeof(line),
                 "%-16s %6d %8.0f %6lld %6lld %6lld %9lld %10lld %10lld "
                 "%8lld %10lld %10lld %10lld %9lld\n",
                 name.c_str(),
                 t->weight.load(std::memory_order_relaxed), t->quota.qps,
                 (long long)t->quota.burst, (long long)maxc,
                 (long long)(maxc > 0 ? 0 : TenantConcurrencyLimit(t.get())),
                 (long long)t->inflight.load(std::memory_order_relaxed),
                 (long long)t->admitted->get(), (long long)t->shed->get(),
                 (long long)t->queued->get(),
                 (long long)t->latency->latency_percentile(0.99),
                 (long long)(t->cost_admitted->get() / kCostUnitMilli),
                 (long long)(t->cost_shed->get() / kCostUnitMilli),
                 (long long)(est / kCostUnitMilli));
        os << line;
    }
    return os.str();
}

std::string QosDispatcher::DescribeJson() const {
    std::ostringstream os;
    os << "{\"enabled\":" << (enabled() ? 1 : 0)
       << ",\"queue_depth\":" << queue_depth()
       << ",\"queue_delay_ewma_us\":" << QueueDelayEwmaUs()
       << ",\"queue_delay_p99_us\":"
       << queue_delay_recorder()->latency_percentile(0.99)
       << ",\"over_delay_target\":" << (OverDelayTarget() ? 1 : 0)
       << ",\"drain_rate_cost_per_s\":" << DrainRateCostPerS()
       << ",\"cost_backlog_milli\":"
       << backlog_cost_milli_.load(std::memory_order_relaxed)
       << ",\"suggested_backoff_ms\":" << SuggestedBackoffMs()
       << ",\"tenants\":{";
    std::shared_lock<std::shared_mutex> g(tenants_mu_);
    bool first = true;
    for (const auto& [name, t] : tenants_) {
        if (!first) os << ",";
        first = false;
        // Tenant names reaching here are header/meta strings: strip the
        // two JSON-breaking characters instead of trusting the wire.
        std::string safe = name;
        for (char& c : safe) {
            if (c == '"' || c == '\\' || (unsigned char)c < 0x20) c = '_';
        }
        int64_t est = kCostUnitMilli;
        {
            std::shared_lock<std::shared_mutex> cg(t->cost_mu);
            for (const auto& [m, c] : t->method_cost_milli) {
                est = std::max(est, c);
            }
        }
        const int64_t maxc =
            t->max_concurrency.load(std::memory_order_relaxed);
        const bool gradient_gates =
            maxc <= 0 && t->gradient != nullptr &&
            FLAGS_rpc_tenant_gradient_limit.get();
        os << "\"" << safe << "\":{"
           << "\"weight\":" << t->weight.load(std::memory_order_relaxed)
           << ",\"qps_cap\":" << (int64_t)t->quota.qps
           << ",\"max_concurrency\":" << maxc
           << ",\"gradient_limit\":"
           << (gradient_gates ? t->gradient->MaxConcurrency() : 0)
           << ",\"gradient_updates\":"
           << (t->gradient != nullptr ? t->gradient->update_count() : 0)
           << ",\"inflight\":"
           << t->inflight.load(std::memory_order_relaxed)
           << ",\"admitted\":" << t->admitted->get()
           << ",\"shed\":" << t->shed->get()
           << ",\"queued\":" << t->queued->get()
           << ",\"cost_admitted_milli\":" << t->cost_admitted->get()
           << ",\"cost_shed_milli\":" << t->cost_shed->get()
           << ",\"cost_ewma_milli\":" << est
           << ",\"p50_us\":" << t->latency->latency_percentile(0.5)
           << ",\"p99_us\":" << t->latency->latency_percentile(0.99)
           << ",\"count\":" << t->latency->count() << "}";
    }
    os << "}}";
    return os.str();
}

}  // namespace tpurpc
