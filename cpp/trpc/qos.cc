#include "trpc/qos.h"

#include <algorithm>
#include <cstdlib>
#include <sstream>

#include "tbase/flags.h"
#include "tbase/logging.h"
#include "tbase/time.h"
#include "tfiber/butex.h"

// The multi-tenant QoS tier is OFF by default: with no quotas configured
// and the flag off, a request pays one relaxed load and the dispatch
// path is byte-identical to the raw-speed round. Quotas configured via
// Server::SetTenantQuota or -rpc_tenant_quotas enable it implicitly.
DEFINE_bool(rpc_qos_enabled, false,
            "enable the multi-tenant fair-dispatch/overload tier even "
            "with no per-tenant quotas configured");
DEFINE_string(rpc_tenant_quotas, "",
              "per-tenant quotas: 'name:qps=300,burst=64,w=1,conc=8;...' "
              "(qps/conc 0 = unlimited; w = weighted-fair share)");
DEFINE_int32(rpc_fair_queue_highwater, 1024,
             "fair dispatch queue depth that triggers lowest-priority-"
             "first shedding");
DEFINE_int32(rpc_overload_backoff_ms, 50,
             "server-suggested client backoff attached to TERR_OVERLOAD "
             "sheds (rate-quota sheds compute their own from the refill "
             "time)");
DEFINE_int32(rpc_max_tenants, 64,
             "distinct tenant label values tracked; newcomers beyond "
             "this fold into the 'other' tenant (metric-cardinality "
             "bound)");

namespace tpurpc {

namespace {

// Labelled per-tenant families ({tenant="name"}), process-lifetime,
// created on first QoS use (runtime, never static-init) — the same
// pattern as the dispatcher's per-loop families.
LabelledMetric<IntCell>* tenant_admitted() {
    static auto* m =
        new LabelledMetric<IntCell>("rpc_tenant_admitted", {"tenant"});
    return m;
}
LabelledMetric<IntCell>* tenant_shed() {
    static auto* m =
        new LabelledMetric<IntCell>("rpc_tenant_shed", {"tenant"});
    return m;
}
LabelledMetric<IntCell>* tenant_queued() {
    static auto* m =
        new LabelledMetric<IntCell>("rpc_tenant_queued", {"tenant"});
    return m;
}
LabelledMetric<LatencyRecorder>* tenant_latency() {
    static auto* m = new LabelledMetric<LatencyRecorder>(
        "rpc_tenant_latency_us", {"tenant"});
    return m;
}

// Process-wide overload accounting (the soak's cross-tenant asserts).
LazyAdder g_overload_sheds("rpc_server_overload_sheds");
LazyAdder g_overload_evictions("rpc_server_overload_evictions");

uint64_t mix64(uint64_t k) {
    k ^= k >> 33;
    k *= 0xff51afd7ed558ccdULL;
    k ^= k >> 33;
    k *= 0xc4ceb9fe1a85ec53ULL;
    k ^= k >> 33;
    return k;
}

uint64_t hash_key(uint64_t seed, const std::string& s) {
    uint64_t h = seed;
    for (char c : s) h = mix64(h ^ (uint8_t)c);
    return mix64(h);
}

}  // namespace

// ---------------- quota spec ----------------

bool ParseQuotaSpec(const std::string& spec,
                    std::map<std::string, TenantQuota>* out) {
    bool clean = true;
    size_t pos = 0;
    while (pos < spec.size()) {
        size_t semi = spec.find(';', pos);
        if (semi == std::string::npos) semi = spec.size();
        const std::string entry = spec.substr(pos, semi - pos);
        pos = semi + 1;
        if (entry.empty()) continue;
        const size_t colon = entry.find(':');
        if (colon == std::string::npos || colon == 0) {
            clean = false;
            continue;
        }
        const std::string name = entry.substr(0, colon);
        TenantQuota q;
        size_t kpos = colon + 1;
        while (kpos < entry.size()) {
            size_t comma = entry.find(',', kpos);
            if (comma == std::string::npos) comma = entry.size();
            const std::string kv = entry.substr(kpos, comma - kpos);
            kpos = comma + 1;
            const size_t eq = kv.find('=');
            if (eq == std::string::npos) {
                if (!kv.empty()) clean = false;
                continue;
            }
            const std::string k = kv.substr(0, eq);
            const char* v = kv.c_str() + eq + 1;
            char* end = nullptr;
            const double num = strtod(v, &end);
            if (end == v || *end != '\0') {
                clean = false;
                continue;
            }
            if (k == "qps") {
                q.qps = num;
            } else if (k == "burst") {
                q.burst = (int64_t)num;
            } else if (k == "w" || k == "weight") {
                q.weight = std::max(1, (int)num);
            } else if (k == "conc") {
                q.max_concurrency = (int64_t)num;
            } else {
                clean = false;
            }
        }
        (*out)[name] = q;
    }
    return clean;
}

// ---------------- token bucket ----------------

void TokenBucket::Configure(double rate_per_s, int64_t burst) {
    rate_milli_per_s_.store(
        rate_per_s > 0 ? (int64_t)(rate_per_s * 1000) : 0,
        std::memory_order_relaxed);
    if (burst <= 0) {
        burst = std::max<int64_t>((int64_t)(rate_per_s / 10), 8);
    }
    burst_milli_.store(burst * 1000, std::memory_order_relaxed);
    tokens_milli_.store(burst * 1000, std::memory_order_relaxed);
    last_refill_us_.store(monotonic_time_us(), std::memory_order_relaxed);
}

void TokenBucket::RefillLocked(int64_t now_us) {
    const int64_t last = last_refill_us_.load(std::memory_order_relaxed);
    const int64_t elapsed_us = now_us - last;
    if (elapsed_us < 1000) return;  // sub-ms refills round to nothing
    std::lock_guard<std::mutex> g(refill_mu_);
    const int64_t last2 = last_refill_us_.load(std::memory_order_relaxed);
    if (now_us - last2 < 1000) return;  // another admitter refilled
    const int64_t add_milli =
        (now_us - last2) *
        rate_milli_per_s_.load(std::memory_order_relaxed) / 1000000;
    if (add_milli <= 0) return;
    last_refill_us_.store(now_us, std::memory_order_relaxed);
    const int64_t burst = burst_milli_.load(std::memory_order_relaxed);
    int64_t cur = tokens_milli_.load(std::memory_order_relaxed);
    while (cur < burst) {
        const int64_t next = std::min(burst, cur + add_milli);
        if (tokens_milli_.compare_exchange_weak(cur, next,
                                                std::memory_order_relaxed)) {
            break;
        }
    }
}

bool TokenBucket::TryWithdraw(int64_t now_us, int64_t* wait_ms) {
    const int64_t rate = rate_milli_per_s_.load(std::memory_order_relaxed);
    if (rate <= 0) return true;
    RefillLocked(now_us);
    int64_t cur = tokens_milli_.load(std::memory_order_relaxed);
    while (cur >= 1000) {
        if (tokens_milli_.compare_exchange_weak(cur, cur - 1000,
                                                std::memory_order_relaxed)) {
            return true;
        }
    }
    if (wait_ms != nullptr) {
        // Time until one whole token accrues at the configured rate,
        // clamped to something a client can reasonably sleep.
        const int64_t deficit_milli = 1000 - std::max<int64_t>(cur, 0);
        int64_t ms = deficit_milli * 1000 / std::max<int64_t>(rate, 1);
        *wait_ms = std::min<int64_t>(std::max<int64_t>(ms, 1), 2000);
    }
    return false;
}

// ---------------- rendezvous subsetting ----------------

std::vector<size_t> RendezvousSubset(uint64_t seed,
                                     const std::vector<std::string>& keys,
                                     size_t k) {
    std::vector<size_t> out;
    if (k == 0 || keys.empty()) return out;
    if (keys.size() <= k) {
        out.resize(keys.size());
        for (size_t i = 0; i < keys.size(); ++i) out[i] = i;
        return out;
    }
    std::vector<std::pair<uint64_t, size_t>> scored;
    scored.reserve(keys.size());
    for (size_t i = 0; i < keys.size(); ++i) {
        scored.emplace_back(hash_key(seed, keys[i]), i);
    }
    // Top-k by score; ties broken by index for determinism.
    std::partial_sort(scored.begin(), scored.begin() + k, scored.end(),
                      [](const std::pair<uint64_t, size_t>& a,
                         const std::pair<uint64_t, size_t>& b) {
                          return a.first != b.first ? a.first > b.first
                                                    : a.second < b.second;
                      });
    out.reserve(k);
    for (size_t i = 0; i < k; ++i) out.push_back(scored[i].second);
    return out;
}

// ---------------- QosDispatcher ----------------

QosDispatcher::QosDispatcher() { wake_butex_ = butex_create(); }

QosDispatcher::~QosDispatcher() {
    StopDrainer();
    butex_destroy(wake_butex_);
}

namespace {
// Apply a quota onto a live tenant: the display copy under the
// registry's exclusive lock, the dispatch-gating fields as atomics.
void ApplyQuota(QosDispatcher::TenantState* t, const TenantQuota& q) {
    t->quota = q;
    t->weight.store(std::max(1, q.weight), std::memory_order_relaxed);
    t->max_concurrency.store(q.max_concurrency, std::memory_order_relaxed);
    t->bucket.Configure(q.qps, q.burst);
}
}  // namespace

void QosDispatcher::Configure(const std::map<std::string, TenantQuota>& quotas,
                              bool force_enable) {
    std::unique_lock<std::shared_mutex> g(tenants_mu_);
    // Merged view: the flag's quotas, with explicit SetTenantQuota
    // entries layered on top — "explicit calls override the flag per
    // tenant", including calls made BEFORE Start.
    configured_ = quotas;
    for (const auto& [name, q] : explicit_) configured_[name] = q;
    for (const auto& [name, q] : configured_) {
        auto it = tenants_.find(name);
        if (it != tenants_.end()) ApplyQuota(it->second.get(), q);
    }
    enabled_.store(force_enable || !configured_.empty(),
                   std::memory_order_release);
}

void QosDispatcher::SetTenantQuota(const std::string& tenant,
                                   const TenantQuota& q) {
    std::unique_lock<std::shared_mutex> g(tenants_mu_);
    const std::string name = tenant.empty() ? "default" : tenant;
    explicit_[name] = q;
    configured_[name] = q;
    auto it = tenants_.find(name);
    if (it != tenants_.end()) ApplyQuota(it->second.get(), q);
    enabled_.store(true, std::memory_order_release);
}

QosDispatcher::TenantState* QosDispatcher::Acquire(
    const std::string& tenant) {
    std::string name = tenant.empty() ? "default" : tenant;
    {
        // Fast path: the tenant exists (every request after the first) —
        // a shared lock keeps the admission paths of the sharded event
        // loops from serializing on this registry.
        std::shared_lock<std::shared_mutex> g(tenants_mu_);
        auto it = tenants_.find(name);
        if (it != tenants_.end()) return it->second.get();
    }
    std::unique_lock<std::shared_mutex> g(tenants_mu_);
    auto it = tenants_.find(name);
    if (it == tenants_.end()) {
        // Cardinality bound: an attacker minting fresh tenant names per
        // request must not flood the metric registry or the DRR table.
        // Known (configured) tenants always get their own slot.
        if ((int64_t)tenants_.size() >=
                (int64_t)FLAGS_rpc_max_tenants.get() &&
            configured_.find(name) == configured_.end() &&
            name != "other") {
            name = "other";
            it = tenants_.find(name);
        }
    }
    if (it == tenants_.end()) {
        auto st = std::make_unique<TenantState>();
        st->name = name;
        auto cit = configured_.find(name);
        if (cit != configured_.end()) ApplyQuota(st.get(), cit->second);
        st->admitted = tenant_admitted()->get_stats({name});
        st->shed = tenant_shed()->get_stats({name});
        st->queued = tenant_queued()->get_stats({name});
        st->latency = tenant_latency()->get_stats({name});
        it = tenants_.emplace(name, std::move(st)).first;
    }
    return it->second.get();
}

bool QosDispatcher::AdmitQps(TenantState* t, int64_t now_us,
                             int64_t* backoff_ms) {
    if (t->bucket.TryWithdraw(now_us, backoff_ms)) return true;
    CountShed(t);
    return false;
}

bool QosDispatcher::TryDirectDispatch(TenantState* t) {
    if (depth_.load(std::memory_order_relaxed) != 0) {
        return false;  // fairness first: join the queue behind the others
    }
    const int64_t maxc = t->max_concurrency.load(std::memory_order_relaxed);
    if (maxc > 0) {
        const int64_t cur =
            t->inflight.fetch_add(1, std::memory_order_relaxed) + 1;
        if (cur > maxc) {
            t->inflight.fetch_sub(1, std::memory_order_relaxed);
            return false;  // over its share: queue (drainer re-checks)
        }
    } else {
        t->inflight.fetch_add(1, std::memory_order_relaxed);
    }
    t->admitted->add(1);
    return true;
}

void QosDispatcher::BeginServed(TenantState* t) {
    t->inflight.fetch_add(1, std::memory_order_relaxed);
    t->admitted->add(1);
}

void QosDispatcher::OnDone(TenantState* t, int64_t latency_us) {
    t->inflight.fetch_sub(1, std::memory_order_relaxed);
    *t->latency << latency_us;
    // A freed concurrency share may unblock this tenant's queued work.
    if (depth_.load(std::memory_order_relaxed) > 0) WakeDrainer();
}

void QosDispatcher::CountShed(TenantState* t) {
    t->shed->add(1);
    *g_overload_sheds << 1;
}

int64_t QosDispatcher::SuggestedBackoffMs() const {
    return std::max(1, FLAGS_rpc_overload_backoff_ms.get());
}

bool QosDispatcher::EvictLowestLocked(int limit_prio,
                                      std::vector<Item>* out_shed,
                                      std::vector<TenantState*>* out_owners) {
    for (int p = kMinPriority; p < limit_prio; ++p) {
        Level& lvl = levels_[p];
        if (lvl.active.empty()) continue;
        // The deepest queue at this level sheds first: under a flood
        // that is the flooder, so a polite same-priority tenant keeps
        // its (short) backlog.
        TenantState* victim = nullptr;
        for (TenantState* t : lvl.active) {
            if (t->q[p].empty()) continue;
            if (victim == nullptr || t->q[p].size() > victim->q[p].size()) {
                victim = t;
            }
        }
        if (victim == nullptr) continue;
        // Newest first (LIFO shed): the oldest queued request is closest
        // to being served; the newest has waited least and its client
        // retries latest.
        out_shed->push_back(victim->q[p].back());
        out_owners->push_back(victim);
        victim->q[p].pop_back();
        victim->queued->add(-1);
        depth_.fetch_sub(1, std::memory_order_relaxed);
        return true;
    }
    return false;
}

bool QosDispatcher::Enqueue(TenantState* t, int priority, const Item& item) {
    const int p = ClampPriority(priority);
    std::vector<Item> to_shed;
    std::vector<TenantState*> shed_owners;
    bool self_shed = false;
    {
        std::lock_guard<std::mutex> g(mu_);
        if (stop_.load(std::memory_order_acquire)) {
            self_shed = true;  // draining dispatcher: answer, don't hold
        } else {
            const int64_t hw =
                std::max(1, FLAGS_rpc_fair_queue_highwater.get());
            if (depth_.load(std::memory_order_relaxed) >= hw &&
                !EvictLowestLocked(p, &to_shed, &shed_owners)) {
                self_shed = true;  // nothing below this priority: shed self
            }
        }
        if (!self_shed) {
            t->q[p].push_back(item);
            t->queued->add(1);
            depth_.fetch_add(1, std::memory_order_relaxed);
            if (!t->in_active[p]) {
                levels_[p].active.push_back(t);
                t->in_active[p] = true;
            }
        }
    }
    const int64_t backoff = SuggestedBackoffMs();
    for (size_t i = 0; i < to_shed.size(); ++i) {
        CountShed(shed_owners[i]);
        *g_overload_evictions << 1;
        to_shed[i].shed(to_shed[i].arg, backoff);
    }
    if (self_shed) {
        CountShed(t);
        item.shed(item.arg, backoff);
        return false;
    }
    WakeDrainer();
    return true;
}

bool QosDispatcher::EvictOneBelow(int priority) {
    std::vector<Item> to_shed;
    std::vector<TenantState*> owners;
    {
        std::lock_guard<std::mutex> g(mu_);
        if (!EvictLowestLocked(ClampPriority(priority), &to_shed, &owners)) {
            return false;
        }
    }
    const int64_t backoff = SuggestedBackoffMs();
    CountShed(owners[0]);
    *g_overload_evictions << 1;
    to_shed[0].shed(to_shed[0].arg, backoff);
    return true;
}

bool QosDispatcher::PopLocked(Item* out, TenantState** owner,
                              int* priority) {
    for (int p = kMaxPriority; p >= kMinPriority; --p) {
        Level& lvl = levels_[p];
        // Bounded walk: each active tenant is visited at most twice per
        // call (once for a possible rotation, once for service) before
        // we conclude the level is drained or concurrency-blocked.
        size_t walk = lvl.active.size() * 2 + 2;
        while (!lvl.active.empty() && walk-- > 0) {
            TenantState* t = lvl.active.front();
            if (t->q[p].empty()) {
                lvl.active.pop_front();
                t->in_active[p] = false;
                t->deficit[p] = 0;
                continue;
            }
            const int64_t maxc =
                t->max_concurrency.load(std::memory_order_relaxed);
            if (maxc > 0 &&
                t->inflight.load(std::memory_order_relaxed) >= maxc) {
                // Over its concurrency share: rotate so the other
                // tenants at this level aren't blocked behind it
                // (OnDone re-wakes the drainer when a share frees).
                lvl.active.pop_front();
                lvl.active.push_back(t);
                continue;
            }
            // DRR: a fresh turn grants `weight` cost-1 service slots;
            // the tenant keeps the head until they're spent.
            if (t->deficit[p] <= 0) {
                t->deficit[p] = t->weight.load(std::memory_order_relaxed);
            }
            *out = t->q[p].front();
            t->q[p].pop_front();
            t->queued->add(-1);
            depth_.fetch_sub(1, std::memory_order_relaxed);
            if (--t->deficit[p] <= 0 || t->q[p].empty()) {
                lvl.active.pop_front();
                lvl.active.push_back(t);
                t->deficit[p] = std::max(t->deficit[p], 0);
            }
            *owner = t;
            *priority = p;
            return true;
        }
    }
    return false;
}

bool QosDispatcher::Pop(Item* out, TenantState** owner, int* priority) {
    std::lock_guard<std::mutex> g(mu_);
    if (!PopLocked(out, owner, priority)) return false;
    // Popped = admitted to service: same accounting as direct dispatch.
    (*owner)->inflight.fetch_add(1, std::memory_order_relaxed);
    (*owner)->admitted->add(1);
    return true;
}

void QosDispatcher::WakeDrainer() {
    butex_word(wake_butex_)->fetch_add(1, std::memory_order_release);
    butex_wake_all(wake_butex_);
}

void* QosDispatcher::DrainerThunk(void* arg) {
    ((QosDispatcher*)arg)->DrainerLoop();
    return nullptr;
}

void QosDispatcher::DrainerLoop() {
    while (true) {
        const int seq =
            butex_word(wake_butex_)->load(std::memory_order_acquire);
        Item it;
        TenantState* t = nullptr;
        int p = 0;
        if (Pop(&it, &t, &p)) {
            // run() spawns the handler in the BACKGROUND (never inline:
            // user code on this fiber would serialize the whole queue
            // behind one handler).
            it.run(it.arg);
            continue;
        }
        if (stop_.load(std::memory_order_acquire)) return;
        // Backstop timeout covers the wake-before-wait race exactly like
        // Server::JoinUntil; the wake path is the enqueue/OnDone bump.
        const int64_t abst = monotonic_time_us() + 100 * 1000;
        butex_wait(wake_butex_, seq, &abst);
    }
}

void QosDispatcher::StartDrainer() {
    std::lock_guard<std::mutex> g(drainer_mu_);
    if (drainer_running_) return;
    stop_.store(false, std::memory_order_release);
    if (fiber_start_background(&drainer_, nullptr, DrainerThunk, this) ==
        0) {
        drainer_running_ = true;
    } else {
        LOG(ERROR) << "QoS drainer fiber failed to start";
    }
}

void QosDispatcher::StopDrainer() {
    bool was_running;
    {
        std::lock_guard<std::mutex> g(drainer_mu_);
        was_running = drainer_running_;
        drainer_running_ = false;
    }
    stop_.store(true, std::memory_order_release);
    if (was_running) {
        WakeDrainer();
        fiber_join(drainer_, nullptr);
    }
    // Shed everything still queued — even when the drainer never ran
    // (a runtime-enabled tier racing Stop): each item holds a counted
    // admission (BeginRequest), and leaking one would hang Server::Join
    // forever.
    while (true) {
        std::vector<Item> items;
        std::vector<TenantState*> owners;
        {
            std::lock_guard<std::mutex> g(mu_);
            for (int p = kMinPriority; p <= kMaxPriority; ++p) {
                for (TenantState* t : levels_[p].active) {
                    while (!t->q[p].empty()) {
                        items.push_back(t->q[p].front());
                        owners.push_back(t);
                        t->q[p].pop_front();
                        t->queued->add(-1);
                        depth_.fetch_sub(1, std::memory_order_relaxed);
                    }
                    t->in_active[p] = false;
                    t->deficit[p] = 0;
                }
                levels_[p].active.clear();
            }
        }
        if (items.empty()) break;
        for (size_t i = 0; i < items.size(); ++i) {
            CountShed(owners[i]);
            items[i].shed(items[i].arg, SuggestedBackoffMs());
        }
    }
}

std::string QosDispatcher::DescribeText() const {
    std::ostringstream os;
    os << "multi-tenant QoS: "
       << (enabled() ? "enabled" : "disabled (set -rpc_qos_enabled or "
                                   "-rpc_tenant_quotas)")
       << "\nfair queue depth: " << queue_depth()
       << " (highwater " << FLAGS_rpc_fair_queue_highwater.get() << ")\n\n";
    char line[256];
    snprintf(line, sizeof(line),
             "%-16s %6s %8s %6s %6s %9s %10s %10s %8s %10s\n", "tenant",
             "weight", "qps_cap", "burst", "conc", "inflight", "admitted",
             "shed", "queued", "p99_us");
    os << line;
    std::shared_lock<std::shared_mutex> g(tenants_mu_);
    for (const auto& [name, t] : tenants_) {
        snprintf(line, sizeof(line),
                 "%-16s %6d %8.0f %6lld %6lld %9lld %10lld %10lld %8lld "
                 "%10lld\n",
                 name.c_str(),
                 t->weight.load(std::memory_order_relaxed), t->quota.qps,
                 (long long)t->quota.burst,
                 (long long)t->max_concurrency.load(
                     std::memory_order_relaxed),
                 (long long)t->inflight.load(std::memory_order_relaxed),
                 (long long)t->admitted->get(), (long long)t->shed->get(),
                 (long long)t->queued->get(),
                 (long long)t->latency->latency_percentile(0.99));
        os << line;
    }
    return os.str();
}

std::string QosDispatcher::DescribeJson() const {
    std::ostringstream os;
    os << "{\"enabled\":" << (enabled() ? 1 : 0)
       << ",\"queue_depth\":" << queue_depth() << ",\"tenants\":{";
    std::shared_lock<std::shared_mutex> g(tenants_mu_);
    bool first = true;
    for (const auto& [name, t] : tenants_) {
        if (!first) os << ",";
        first = false;
        // Tenant names reaching here are header/meta strings: strip the
        // two JSON-breaking characters instead of trusting the wire.
        std::string safe = name;
        for (char& c : safe) {
            if (c == '"' || c == '\\' || (unsigned char)c < 0x20) c = '_';
        }
        os << "\"" << safe << "\":{"
           << "\"weight\":" << t->weight.load(std::memory_order_relaxed)
           << ",\"qps_cap\":" << (int64_t)t->quota.qps
           << ",\"max_concurrency\":"
           << t->max_concurrency.load(std::memory_order_relaxed)
           << ",\"inflight\":"
           << t->inflight.load(std::memory_order_relaxed)
           << ",\"admitted\":" << t->admitted->get()
           << ",\"shed\":" << t->shed->get()
           << ",\"queued\":" << t->queued->get()
           << ",\"p50_us\":" << t->latency->latency_percentile(0.5)
           << ",\"p99_us\":" << t->latency->latency_percentile(0.99)
           << ",\"count\":" << t->latency->count() << "}";
    }
    os << "}}";
    return os.str();
}

}  // namespace tpurpc
