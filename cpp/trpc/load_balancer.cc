// Load balancer policies: rr, wrr, random, consistent-hash ring, and
// locality-aware. Reference policy set: src/brpc/global.cpp:384-392 and
// src/brpc/policy/{round_robin,weighted_round_robin,randomized,
// consistent_hashing,locality_aware}_load_balancer.*.
#include "trpc/load_balancer.h"

#include <algorithm>
#include <atomic>
#include <cstdio>
#include <map>
#include <mutex>
#include <numeric>
#include <unordered_map>

#include "tbase/doubly_buffered_data.h"
#include "tbase/endpoint.h"
#include "tbase/fast_rand.h"
#include "tbase/flags.h"
#include "tbase/logging.h"
#include "tbase/time.h"
#include "trpc/outlier.h"
#include "tvar/reducer.h"

// Pod identity of THIS process (ISSUE 14). Naming entries tagged with a
// different zone are cross-pod: reached over the dcn transport tier and
// spilled to only when the local zone cannot serve. The flag itself
// lives in trpc/qos.cc (ISSUE 15: admission prices spill arrivals, and
// the qos tier links into the pb-free standalone suites this file
// doesn't).
DECLARE_string(rpc_zone);
DEFINE_int32(lb_zone_spill_dead_pct, 100,
             "prefer a cross-zone live replica over a degraded local "
             "pick once at least this percent of the local zone's "
             "members are dead (unaddressable; draining still counts "
             "as alive). 100 = only when the whole local zone is dead");

namespace tpurpc {

// Spill accounting (ISSUE 14): every cross-zone pick is a deliberate,
// countable event — the two-pod soak asserts these fire during a
// whole-pod partition and stay quiet while the local zone is healthy.
static LazyAdder g_zone_spills("rpc_lb_zone_spills");
static LazyAdder g_zone_local_picks("rpc_lb_zone_local_picks");

void ExposeZoneLbVars() {
    *g_zone_spills << 0;
    *g_zone_local_picks << 0;
}

void LoadBalancer::Describe(std::string* out) const {
    out->append(name());
}

// Shared server-list state for list-based policies.
struct ServerList {
    std::vector<ServerNode> list;
    std::map<SocketId, size_t> index;  // id -> position in list

    bool Add(const ServerNode& s) {
        if (index.count(s.id)) return false;
        index[s.id] = list.size();
        list.push_back(s);
        return true;
    }
    bool Remove(SocketId id) {
        auto it = index.find(id);
        if (it == index.end()) return false;
        const size_t pos = it->second;
        index.erase(it);
        // Swap-with-last keeps removal O(1).
        if (pos + 1 < list.size()) {
            list[pos] = list.back();
            index[list[pos].id] = pos;
        }
        list.pop_back();
        return true;
    }
};

int SelectFromList(const std::vector<ServerNode>& list, size_t start,
                   const SelectIn& in, SelectOut* out) {
    const size_t n = list.size();
    if (n == 0) return ENODATA;
    bool saw_draining = false;
    for (size_t i = 0; i < n; ++i) {
        const ServerNode& node = list[(start + i) % n];
        if (in.excluded != nullptr && in.excluded->IsExcluded(node.id)) {
            continue;
        }
        Socket* s = Socket::Address(node.id);
        if (s == nullptr) continue;
        if (s->Draining()) {
            // Peer announced a graceful shutdown: steer new calls away
            // (the whole point of the GOAWAY — the reroute costs no
            // retry token and trips no breaker).
            saw_draining = true;
            s->Dereference();
            continue;
        }
        out->ptr = SocketUniquePtr(s);
        out->skipped_draining = saw_draining;
        return 0;
    }
    // Fallback 1: every non-draining candidate is excluded/failed — a
    // draining server still SERVES (it only asked politely); better that
    // than failing the call or re-hitting an already-tried server.
    for (size_t i = 0; i < n; ++i) {
        const ServerNode& node = list[(start + i) % n];
        if (in.excluded != nullptr && in.excluded->IsExcluded(node.id)) {
            continue;
        }
        Socket* s = Socket::Address(node.id);
        if (s == nullptr) continue;
        out->ptr = SocketUniquePtr(s);
        return 0;
    }
    // Fallback 2: everything excluded/failed: as a last resort allow an
    // excluded-but-live server (better to retry a tried server than to
    // fail outright — reference round_robin_load_balancer.cpp falls back
    // the same way).
    for (size_t i = 0; i < n; ++i) {
        const ServerNode& node = list[(start + i) % n];
        Socket* s = Socket::Address(node.id);
        if (s == nullptr) continue;
        out->ptr = SocketUniquePtr(s);
        return 0;
    }
    return EHOSTDOWN;
}

// ---------------- round robin ----------------

class RoundRobinLoadBalancer : public LoadBalancer {
public:
    bool AddServer(const ServerNode& s) override {
        return db_.Modify([&](ServerList& sl) { return sl.Add(s); }) != 0;
    }
    bool RemoveServer(SocketId id) override {
        return db_.Modify([&](ServerList& sl) { return sl.Remove(id); }) != 0;
    }
    int SelectServer(const SelectIn& in, SelectOut* out) override {
        DoublyBufferedData<ServerList>::ScopedPtr ptr;
        if (db_.Read(&ptr) != 0) return ENOMEM;
        const size_t start =
            next_.fetch_add(1, std::memory_order_relaxed);
        return SelectFromList(ptr->list, start, in, out);
    }
    const char* name() const override { return "rr"; }

private:
    DoublyBufferedData<ServerList> db_;
    std::atomic<size_t> next_{0};
};

// ---------------- random ----------------

class RandomizedLoadBalancer : public LoadBalancer {
public:
    bool AddServer(const ServerNode& s) override {
        return db_.Modify([&](ServerList& sl) { return sl.Add(s); }) != 0;
    }
    bool RemoveServer(SocketId id) override {
        return db_.Modify([&](ServerList& sl) { return sl.Remove(id); }) != 0;
    }
    int SelectServer(const SelectIn& in, SelectOut* out) override {
        DoublyBufferedData<ServerList>::ScopedPtr ptr;
        if (db_.Read(&ptr) != 0) return ENOMEM;
        if (ptr->list.empty()) return ENODATA;
        return SelectFromList(ptr->list, fast_rand_less_than(ptr->list.size()),
                              in, out);
    }
    const char* name() const override { return "random"; }

private:
    DoublyBufferedData<ServerList> db_;
};

// ---------------- weighted round robin ----------------
// The foreground copy carries a precomputed schedule (weights reduced by
// their gcd, entries interleaved) walked by an atomic cursor — selection
// stays wait-free (reference weighted_round_robin_load_balancer.cpp keeps
// per-thread stride state; a shared schedule is simpler and as fair).

struct WrrList : ServerList {
    std::vector<size_t> schedule;  // indexes into list

    void Rebuild() {
        schedule.clear();
        if (list.empty()) return;
        int g = 0;
        for (const auto& s : list) g = std::gcd(g, std::max(s.weight, 1));
        std::vector<int64_t> remain(list.size());
        int64_t total = 0;
        for (size_t i = 0; i < list.size(); ++i) {
            remain[i] = std::max(list[i].weight, 1) / g;
            total += remain[i];
        }
        if (total > 65536) {  // clamp pathological weight ratios
            for (auto& r : remain) {
                r = std::max<int64_t>(1, r * 65536 / total);
            }
        }
        // Interleave: repeatedly emit each server still owed slots.
        bool more = true;
        while (more) {
            more = false;
            for (size_t i = 0; i < list.size(); ++i) {
                if (remain[i] > 0) {
                    schedule.push_back(i);
                    if (--remain[i] > 0) more = true;
                }
            }
        }
    }
};

class WeightedRoundRobinLoadBalancer : public LoadBalancer {
public:
    bool AddServer(const ServerNode& s) override {
        return db_.Modify([&](WrrList& sl) {
            if (!sl.Add(s)) return false;
            sl.Rebuild();
            return true;
        }) != 0;
    }
    bool RemoveServer(SocketId id) override {
        return db_.Modify([&](WrrList& sl) {
            if (!sl.Remove(id)) return false;
            sl.Rebuild();
            return true;
        }) != 0;
    }
    int SelectServer(const SelectIn& in, SelectOut* out) override {
        DoublyBufferedData<WrrList>::ScopedPtr ptr;
        if (db_.Read(&ptr) != 0) return ENOMEM;
        const auto& sched = ptr->schedule;
        if (sched.empty()) return ENODATA;
        const size_t n = sched.size();
        size_t start = next_.fetch_add(1, std::memory_order_relaxed) % n;
        bool saw_draining = false;
        for (size_t i = 0; i < n; ++i) {
            const ServerNode& node = ptr->list[sched[(start + i) % n]];
            if (in.excluded && in.excluded->IsExcluded(node.id)) continue;
            Socket* s = Socket::Address(node.id);
            if (s == nullptr) continue;
            if (s->Draining()) {
                saw_draining = true;
                s->Dereference();
                continue;
            }
            out->ptr = SocketUniquePtr(s);
            out->skipped_draining = saw_draining;
            return 0;
        }
        return SelectFromList(ptr->list, start, in, out);
    }
    const char* name() const override { return "wrr"; }

private:
    DoublyBufferedData<WrrList> db_;
    std::atomic<size_t> next_{0};
};

// ---------------- consistent hashing (ketama ring) ----------------
// Each server contributes `weight * kReplicasPerServer` virtual nodes at
// hash("ip:port-i"); requests map to the first ring point >= hash(request
// _code). Reference: src/brpc/policy/consistent_hashing_load_balancer.*.

static uint64_t fmix64(uint64_t k) {
    // 64-bit avalanche finalizer (murmur3-style).
    k ^= k >> 33;
    k *= 0xff51afd7ed558ccdULL;
    k ^= k >> 33;
    k *= 0xc4ceb9fe1a85ec53ULL;
    k ^= k >> 33;
    return k;
}

static uint64_t hash_bytes(const std::string& s, uint64_t seed) {
    uint64_t h = seed;
    for (char c : s) h = fmix64(h ^ (uint8_t)c);
    return h;
}

struct HashRing {
    struct Point {
        uint64_t hash;
        SocketId id;
        bool operator<(const Point& o) const { return hash < o.hash; }
    };
    std::vector<Point> ring;
    std::map<SocketId, ServerNode> members;

    static constexpr int kReplicasPerServer = 100;

    void Rebuild(uint64_t seed) {
        ring.clear();
        for (const auto& [id, node] : members) {
            // Ring keys come from registration-time data only, so both
            // DoublyBufferedData copies and every rebuild agree regardless
            // of the socket's momentary health.
            const std::string key = node.ep.port != 0
                                        ? endpoint2str(node.ep)
                                        : std::to_string(id);
            const int replicas = kReplicasPerServer * std::max(node.weight, 1);
            for (int i = 0; i < replicas; ++i) {
                ring.push_back(
                    {hash_bytes(key + "-" + std::to_string(i), seed), id});
            }
        }
        std::sort(ring.begin(), ring.end());
    }
};

class ConsistentHashLoadBalancer : public LoadBalancer {
public:
    explicit ConsistentHashLoadBalancer(uint64_t seed, const char* name)
        : seed_(seed), name_(name) {}

    bool AddServer(const ServerNode& s) override {
        return db_.Modify([&](HashRing& r) {
            if (r.members.count(s.id)) return false;
            r.members[s.id] = s;
            r.Rebuild(seed_);
            return true;
        }) != 0;
    }
    bool RemoveServer(SocketId id) override {
        return db_.Modify([&](HashRing& r) {
            if (r.members.erase(id) == 0) return false;
            r.Rebuild(seed_);
            return true;
        }) != 0;
    }
    int SelectServer(const SelectIn& in, SelectOut* out) override {
        DoublyBufferedData<HashRing>::ScopedPtr ptr;
        if (db_.Read(&ptr) != 0) return ENOMEM;
        const auto& ring = ptr->ring;
        if (ring.empty()) return ENODATA;
        const uint64_t h =
            in.has_request_code ? fmix64(in.request_code) : fast_rand();
        HashRing::Point probe{h, 0};
        auto it = std::lower_bound(ring.begin(), ring.end(), probe);
        const size_t start = it == ring.end() ? 0 : it - ring.begin();
        // Walk the ring until a live, non-excluded, non-draining server
        // is found. Draining nodes are skipped exactly like failed ones —
        // a draining ring member's keys flow to its ring successor, the
        // same redistribution a removal would cause — but remembered as
        // a better fallback than an excluded (already-tried) server.
        SocketId last_live = INVALID_VREF_ID;
        SocketId last_draining = INVALID_VREF_ID;
        bool saw_draining = false;
        for (size_t i = 0; i < ring.size(); ++i) {
            const SocketId id = ring[(start + i) % ring.size()].id;
            Socket* s = Socket::Address(id);
            if (s == nullptr) continue;
            if (in.excluded && in.excluded->IsExcluded(id)) {
                if (last_live == INVALID_VREF_ID) last_live = id;
                s->Dereference();
                continue;
            }
            if (s->Draining()) {
                if (last_draining == INVALID_VREF_ID) last_draining = id;
                saw_draining = true;
                s->Dereference();
                continue;
            }
            out->ptr = SocketUniquePtr(s);
            out->skipped_draining = saw_draining;
            return 0;
        }
        // Draining beats excluded: it still serves and was not yet tried
        // by this RPC.
        if (last_draining != INVALID_VREF_ID) {
            Socket* s = Socket::Address(last_draining);
            if (s != nullptr) {
                out->ptr = SocketUniquePtr(s);
                return 0;
            }
        }
        if (last_live != INVALID_VREF_ID) {
            Socket* s = Socket::Address(last_live);
            if (s != nullptr) {
                out->ptr = SocketUniquePtr(s);
                return 0;
            }
        }
        return EHOSTDOWN;
    }
    const char* name() const override { return name_; }

private:
    DoublyBufferedData<HashRing> db_;
    const uint64_t seed_;
    const char* name_;
};

// ---------------- locality-aware ----------------
// Weight each server by expected goodness 1/(ema_latency * (inflight+1))
// and pick weighted-random. The reference's la (src/brpc/policy/
// locality_aware_load_balancer.*, docs lalb.md) maintains a weight tree
// updated through an ExecutionQueue; this design keeps per-server atomics
// and recomputes the CDF on read — O(n) per select but n is small and the
// arithmetic is branch-free.

class LocalityAwareLoadBalancer : public LoadBalancer {
    struct Stats {
        std::atomic<int64_t> ema_latency_us{0};  // 0 = no data yet
        std::atomic<int32_t> inflight{0};
        std::atomic<int32_t> recent_errors{0};
    };

public:
    bool AddServer(const ServerNode& s) override {
        {
            std::lock_guard<std::mutex> g(stats_mu_);
            if (!stats_.count(s.id)) {
                stats_[s.id] = std::make_shared<Stats>();
            }
        }
        return db_.Modify([&](ServerList& sl) { return sl.Add(s); }) != 0;
    }
    bool RemoveServer(SocketId id) override {
        bool removed =
            db_.Modify([&](ServerList& sl) { return sl.Remove(id); }) != 0;
        if (removed) {
            std::lock_guard<std::mutex> g(stats_mu_);
            stats_.erase(id);
        }
        return removed;
    }
    int SelectServer(const SelectIn& in, SelectOut* out) override {
        DoublyBufferedData<ServerList>::ScopedPtr ptr;
        if (db_.Read(&ptr) != 0) return ENOMEM;
        const auto& list = ptr->list;
        if (list.empty()) return ENODATA;
        // Two passes: compute weights, then pick by weighted random.
        double weights[kMaxInline];
        const size_t n = std::min(list.size(), (size_t)kMaxInline);
        double total = 0;
        bool saw_draining = false;
        {
            std::lock_guard<std::mutex> g(stats_mu_);
            for (size_t i = 0; i < n; ++i) {
                const SocketId id = list[i].id;
                double w = 0;
                bool draining = false;
                {
                    // Draining nodes get weight 0 (steered away like
                    // excluded ones); liveness itself is still resolved
                    // at pick time below.
                    Socket* probe = Socket::Address(id);
                    if (probe != nullptr) {
                        draining = probe->Draining();
                        probe->Dereference();
                    }
                }
                if (draining) {
                    saw_draining = true;
                } else if (!(in.excluded && in.excluded->IsExcluded(id))) {
                    auto it = stats_.find(id);
                    if (it != stats_.end()) {
                        const int64_t lat =
                            it->second->ema_latency_us.load(
                                std::memory_order_relaxed);
                        // Clamp: transient pick/feedback races must never
                        // drive the weight negative or divide by zero.
                        const int32_t inflight =
                            std::max(it->second->inflight.load(
                                         std::memory_order_relaxed),
                                     0);
                        // Unprobed servers get the optimistic base weight so
                        // they attract traffic and build an estimate.
                        const double base =
                            lat > 0 ? 1e6 / (double)lat : kInitialWeight;
                        w = base / (inflight + 1);
                    } else {
                        w = kInitialWeight;
                    }
                }
                weights[i] = w;
                total += w;
            }
        }
        if (total <= 0) {
            // All excluded: fall back to plain scan.
            const int rc =
                SelectFromList(list, fast_rand_less_than(list.size()), in, out);
            if (rc == 0) OnPicked(out->ptr->id());  // keep inflight balanced
            return rc;
        }
        double pick = fast_rand_double() * total;
        for (size_t i = 0; i < n; ++i) {
            pick -= weights[i];
            if (pick <= 0 && weights[i] > 0) {
                Socket* s = Socket::Address(list[i].id);
                if (s != nullptr) {
                    out->ptr = SocketUniquePtr(s);
                    out->skipped_draining = saw_draining;
                    OnPicked(list[i].id);
                    return 0;
                }
            }
        }
        const int rc =
            SelectFromList(list, fast_rand_less_than(list.size()), in, out);
        if (rc == 0) OnPicked(out->ptr->id());
        return rc;
    }
    void DiscardPick(SocketId id) override {
        // Un-count a select-time inflight whose RPC never issued (the
        // zone layer's unused side pick): weight state only, no
        // latency signal.
        std::lock_guard<std::mutex> g(stats_mu_);
        auto it = stats_.find(id);
        if (it != stats_.end()) {
            it->second->inflight.fetch_sub(1, std::memory_order_relaxed);
        }
    }
    void Feedback(const CallInfo& info) override {
        std::shared_ptr<Stats> st;
        {
            std::lock_guard<std::mutex> g(stats_mu_);
            auto it = stats_.find(info.server_id);
            if (it == stats_.end()) return;
            st = it->second;
        }
        st->inflight.fetch_sub(1, std::memory_order_relaxed);
        if (info.error_code == 0) {
            // EMA with alpha = 1/8.
            int64_t prev = st->ema_latency_us.load(std::memory_order_relaxed);
            int64_t next = prev == 0
                               ? info.latency_us
                               : prev + (info.latency_us - prev) / 8;
            st->ema_latency_us.store(std::max<int64_t>(next, 1),
                                     std::memory_order_relaxed);
        } else {
            // Penalize errors: double the latency estimate.
            int64_t prev = st->ema_latency_us.load(std::memory_order_relaxed);
            st->ema_latency_us.store(prev == 0 ? 100000 : prev * 2,
                                     std::memory_order_relaxed);
        }
    }
    const char* name() const override { return "la"; }

private:
    static constexpr int kMaxInline = 1024;
    static constexpr double kInitialWeight = 100.0;  // ~10ms equivalent

    void OnPicked(SocketId id) {
        std::lock_guard<std::mutex> g(stats_mu_);
        auto it = stats_.find(id);
        if (it != stats_.end()) {
            it->second->inflight.fetch_add(1, std::memory_order_relaxed);
        }
    }

    DoublyBufferedData<ServerList> db_;
    mutable std::mutex stats_mu_;
    std::unordered_map<SocketId, std::shared_ptr<Stats>> stats_;
};

// ---------------- locality-zone two-level wrapper ----------------

ZoneAwareLoadBalancer::ZoneAwareLoadBalancer(LoadBalancer* local,
                                             LoadBalancer* remote)
    : local_(local), remote_(remote) {}

ZoneAwareLoadBalancer::~ZoneAwareLoadBalancer() = default;

bool ZoneAwareLoadBalancer::AddServer(const ServerNode& s) {
    // Zoneless members (and everything, in a zoneless process) are
    // local: the wrapper is a passthrough until both sides exist.
    const std::string my_zone = FLAGS_rpc_zone.get();
    const bool local =
        my_zone.empty() || s.zone.empty() || s.zone == my_zone;
    const bool added =
        local ? local_->AddServer(s) : remote_->AddServer(s);
    if (added) {
        std::lock_guard<std::mutex> g(mu_);
        side_[s.id] = local;
        if (local) {
            nlocal_.fetch_add(1, std::memory_order_relaxed);
        } else {
            nremote_.fetch_add(1, std::memory_order_relaxed);
        }
    }
    return added;
}

bool ZoneAwareLoadBalancer::RemoveServer(SocketId id) {
    bool local = true;
    {
        std::lock_guard<std::mutex> g(mu_);
        auto it = side_.find(id);
        if (it == side_.end()) return false;
        local = it->second;
        side_.erase(it);
        (local ? nlocal_ : nremote_)
            .fetch_sub(1, std::memory_order_relaxed);
    }
    return local ? local_->RemoveServer(id) : remote_->RemoveServer(id);
}

bool ZoneAwareLoadBalancer::LocalZoneMostlyDead() const {
    const int pct = FLAGS_lb_zone_spill_dead_pct.get();
    size_t total = 0, dead = 0;
    {
        std::lock_guard<std::mutex> g(mu_);
        for (const auto& [id, local] : side_) {
            if (!local) continue;
            ++total;
            // Dead = unaddressable (failed/recycled). A DRAINING member
            // still serves — it keeps the zone "alive" on purpose, per
            // the local-draining > remote-live ordering.
            Socket* s = Socket::Address(id);
            if (s == nullptr) {
                ++dead;
            } else {
                s->Dereference();
            }
        }
    }
    if (total == 0) return true;  // no local members at all
    return dead * 100 >= total * (size_t)std::max(pct, 1);
}

int ZoneAwareLoadBalancer::SelectServer(const SelectIn& in,
                                        SelectOut* out) {
    const size_t nlocal = nlocal_.load(std::memory_order_relaxed);
    const size_t nremote = nremote_.load(std::memory_order_relaxed);
    if (nremote == 0) {
        // Pure passthrough (the common, zoneless case): no counters, no
        // health sweep.
        return local_->SelectServer(in, out);
    }
    if (nlocal == 0) {
        const int rc = remote_->SelectServer(in, out);
        if (rc == 0) {
            out->zone_spilled = true;
            *g_zone_spills << 1;
        }
        return rc;
    }
    const auto excluded = [&](const SelectOut& o) {
        return in.excluded != nullptr && o.ptr &&
               in.excluded->IsExcluded(o.ptr->id());
    };
    SelectOut lout;
    const int lrc = local_->SelectServer(in, &lout);
    // A clean local pick: live, not draining, not already tried by an
    // earlier attempt of this RPC (the policies fall back to excluded
    // members as a last resort — a retry should reach the OTHER pod
    // before re-hitting a tried local server).
    const bool local_clean = lrc == 0 && !lout.ptr->Draining() &&
                             !excluded(lout);
    // Dead-percent sweep, evaluated LAZILY: at the default threshold
    // (100) a clean local pick already proves at least one local
    // member alive, so the common healthy-zone path pays no O(zone)
    // Socket::Address walk per pick. Only a degraded pick — or an
    // explicit sub-100 threshold — pays for the sweep.
    const bool spill_threshold =
        (!local_clean || FLAGS_lb_zone_spill_dead_pct.get() < 100) &&
        LocalZoneMostlyDead();
    if (local_clean && !spill_threshold) {
        *out = std::move(lout);
        *g_zone_local_picks << 1;
        return 0;
    }
    SelectOut rout;
    const int rrc = remote_->SelectServer(in, &rout);
    const bool remote_clean = rrc == 0 && !rout.ptr->Draining() &&
                              !excluded(rout);
    // Exactly one of the two picks issues; the other must be handed
    // back to its policy (la counts inflight at select time — a
    // silently dropped pick would leak it and skew that side's weights
    // forever).
    const auto use_local = [&] {
        if (rrc == 0 && rout.ptr) remote_->DiscardPick(rout.ptr->id());
        *out = std::move(lout);
        *g_zone_local_picks << 1;
        return 0;
    };
    const auto use_remote = [&] {
        if (lrc == 0 && lout.ptr) local_->DiscardPick(lout.ptr->id());
        *out = std::move(rout);
        out->zone_spilled = true;
        *g_zone_spills << 1;
        return 0;
    };
    // Threshold breach: the local zone is (mostly) dead — remote-live
    // wins even over a nominally-clean local pick.
    if (spill_threshold && remote_clean) return use_remote();
    if (local_clean) return use_local();
    // local-draining (still serving, untried) beats remote-live.
    if (lrc == 0 && !excluded(lout)) return use_local();
    if (remote_clean) return use_remote();
    // Everything degraded: any local pick (excluded fallback), then any
    // remote one.
    if (lrc == 0) return use_local();
    if (rrc == 0) return use_remote();
    return lrc != ENODATA ? lrc : rrc;
}

void ZoneAwareLoadBalancer::Feedback(const CallInfo& info) {
    if (nremote_.load(std::memory_order_relaxed) == 0) {
        local_->Feedback(info);  // passthrough: no side lookup, no lock
        return;
    }
    bool local = true;
    {
        std::lock_guard<std::mutex> g(mu_);
        auto it = side_.find(info.server_id);
        if (it == side_.end()) return;
        local = it->second;
    }
    if (local) {
        local_->Feedback(info);
    } else {
        remote_->Feedback(info);
    }
}

void ZoneAwareLoadBalancer::Describe(std::string* out) const {
    local_->Describe(out);
    const size_t nremote = nremote_.load(std::memory_order_relaxed);
    if (nremote > 0) {
        char buf[64];
        snprintf(buf, sizeof(buf), " [zone local=%zu remote=%zu]",
                 nlocal_.load(std::memory_order_relaxed), nremote);
        out->append(buf);
    }
}

const char* ZoneAwareLoadBalancer::name() const { return local_->name(); }

size_t ZoneAwareLoadBalancer::local_count() const {
    return nlocal_.load(std::memory_order_relaxed);
}

size_t ZoneAwareLoadBalancer::remote_count() const {
    return nremote_.load(std::memory_order_relaxed);
}

// ---------------- factory ----------------

static LoadBalancer* NewPolicy(const std::string& name) {
    if (name == "rr") return new RoundRobinLoadBalancer;
    if (name == "random") return new RandomizedLoadBalancer;
    if (name == "wrr") return new WeightedRoundRobinLoadBalancer;
    if (name == "c_murmurhash" || name == "ch") {
        return new ConsistentHashLoadBalancer(0x9e3779b97f4a7c15ULL,
                                              "c_murmurhash");
    }
    if (name == "c_md5") {
        return new ConsistentHashLoadBalancer(0x517cc1b727220a95ULL, "c_md5");
    }
    if (name == "la") return new LocalityAwareLoadBalancer;
    return nullptr;
}

LoadBalancer* LoadBalancer::New(const std::string& name) {
    LoadBalancer* local = NewPolicy(name);
    if (local == nullptr) return nullptr;
    // Always wrapped: the zone wrapper is a strict passthrough until a
    // cross-zone member shows up, and every policy gets the two-level
    // zone pick for free — no per-policy zone forks (ISSUE 14). The
    // outlier wrapper sits OUTERMOST (ISSUE 20): ejection skips and
    // reinstatement probes compose over the zone fallback ordering,
    // and cost one relaxed load while every backend is healthy.
    return new outlier::OutlierLoadBalancer(
        new ZoneAwareLoadBalancer(local, NewPolicy(name)));
}

}  // namespace tpurpc
