#include "trpc/collective.h"

#include <algorithm>
#include <cstring>
#include <set>

#include "tbase/crc32c.h"
#include "tbase/errno.h"
#include "tbase/flight_recorder.h"
#include "tbase/logging.h"
#include "tbase/time.h"
#include "tfiber/fiber.h"
#include "tici/block_pool.h"
#include "tici/verbs.h"
#include "trpc/channel.h"
#include "trpc/combo_channels.h"
#include "trpc/controller.h"
#include "tvar/multi_dimension.h"
#include "tvar/reducer.h"

namespace tpurpc {

namespace {

// Subsystem observability (ISSUE 13): completed ops, chunk RPCs,
// attempt re-runs, membership re-forms, payload bytes pushed, and the
// chunks that fell back to inline bytes (should stay 0 on
// descriptor-capable meshes — the bench's zero-inline proof).
static LazyAdder g_ops("rpc_collective_ops");
static LazyAdder g_steps("rpc_collective_steps");
static LazyAdder g_retries("rpc_collective_retries");
static LazyAdder g_reforms("rpc_collective_reforms");
static LazyAdder g_bytes("rpc_collective_bytes");
static LazyAdder g_desc_fallbacks("rpc_collective_desc_fallbacks");

// Flight-recorder step event (ROADMAP item 4: the overlap metric needs
// step-timestamped events): a=round seq, b packs kind/step/chunk.
static inline void RecordStepEvent(const CollWire& w) {
    flight::Record(flight::kCollStep, w.seq,
                   ((uint64_t)w.kind << 48) | ((uint64_t)w.step << 32) |
                       (uint64_t)w.chunk);
}
// Verbs lane (ISSUE 18): ring steps that moved as one scatter-gather
// REMOTE_WRITE + doorbell, and the chunks that had to ride the
// per-chunk RPC path although the verbs lane was requested (lane grant
// refused, stale window, doorbell failure). A healthy verbs mesh keeps
// fallbacks at 0 — the bench's verbs-vs-chunks proof.
static LazyAdder g_verb_steps("rpc_collective_verb_steps");
static LazyAdder g_verb_fallbacks("rpc_collective_verb_fallbacks");

// wr_id namespace tag for collective verb posts (uniqueness among
// pending posts is process-wide; the mesh traffic fiber uses its own
// tag).
constexpr uint64_t kCollWrTag = 0x434Full << 48;
std::atomic<uint64_t> g_coll_wr{1};

// Per-algorithm bus bandwidth of the most recent completed round
// (NCCL-style busbw: the payload-derived rate every algorithm can be
// compared on): rpc_collective_busbw_mbps{alg="allreduce"|...}.
LabelledMetric<IntCell>* BusbwFamily() {
    static LabelledMetric<IntCell>* f = new LabelledMetric<IntCell>(
        "rpc_collective_busbw_mbps", {"alg"});
    return f;
}

uint32_t RoundFamily(uint32_t kind) {
    switch (kind) {
        case COLL_ALLREDUCE:
            return COLL_ALLREDUCE;
        case COLL_ALLGATHER:
            return COLL_ALLGATHER;
        case COLL_ALLTOALL:
            return COLL_ALLTOALL;
        case COLL_SERIAL_PUSH:
        case COLL_SERIAL_PULL:
            return COLL_SERIAL_PUSH;
        case COLL_BCAST:
            return COLL_BCAST;
        default:
            return 0;
    }
}

// Round family: the kind folded with the membership scope (ISSUE 14) —
// an intra-zone hierarchical phase and a flat global round of the same
// seq live in different key namespaces on BOTH sides of the wire.
uint32_t FamilyOf(uint32_t rkind, uint32_t scope) {
    return rkind | (scope << 4);
}

uint64_t RoundKey(uint32_t family, uint64_t seq) {
    return ((uint64_t)family << 56) | (seq & 0x00FFFFFFFFFFFFFFull);
}

uint64_t PackChunk(uint32_t src, uint32_t step, uint32_t chunk) {
    return ((uint64_t)src << 48) | ((uint64_t)(step & 0xFFFFFF) << 24) |
           (chunk & 0xFFFFFF);
}

uint64_t HashKeys(const std::vector<CollectiveMembership::Member>& m) {
    uint64_t h = 1469598103934665603ull;  // FNV-1a 64
    for (const auto& mem : m) {
        uint64_t k = mem.key;
        for (int i = 0; i < 8; ++i) {
            h ^= (k >> (i * 8)) & 0xFF;
            h *= 1099511628211ull;
        }
    }
    return h;
}

// Ring schedule: the shard rank `rank` SENDS at `step` (phase 1 steps
// 0..n-2 reduce-scatter, phase 2 steps n-1..2n-3 all-gather). The shard
// it RECEIVES at `step` is OutShard(pred, step) — which equals
// OutShard(rank, step+1), the classic "forward what you just got"
// dependency that makes the pipeline overlap transfers with reduces.
uint32_t OutShard(uint32_t rank, uint32_t step, uint32_t n) {
    if (step < n - 1) {
        return (rank + n - (step % n)) % n;
    }
    const uint32_t t = step - (n - 1);
    return (rank + 1 + n - (t % n)) % n;
}

// Word range of shard k when nwords split over n ranks.
void ShardRange(uint64_t nwords, uint32_t n, uint32_t k, uint64_t* w0,
                uint64_t* wn) {
    const uint64_t q = nwords / n, rem = nwords % n;
    *w0 = (uint64_t)k * q + std::min<uint64_t>(k, rem);
    *wn = q + (k < rem ? 1 : 0);
}

uint32_t ChunksOf(uint64_t shard_words, uint64_t chunk_words) {
    if (shard_words == 0) return 0;
    return (uint32_t)((shard_words + chunk_words - 1) / chunk_words);
}

void AddWordsWraparound(char* dst, const char* src, size_t nbytes) {
    for (size_t i = 0; i + 4 <= nbytes; i += 4) {
        uint32_t a, b;
        memcpy(&a, dst + i, 4);
        memcpy(&b, src + i, 4);
        a += b;
        memcpy(dst + i, &a, 4);
    }
}

}  // namespace

// ---------------- round state ----------------

struct CollectiveEngine::Round {
    uint32_t rkind = 0;
    uint32_t scope = SCOPE_GLOBAL;  // immutable after creation
    uint64_t seq = 0;
    uint64_t member_hash = 0;
    uint32_t nranks = 0;
    uint32_t my_rank = 0;
    std::vector<CollectiveMembership::Member> members;
    uint64_t total_bytes = 0;
    std::string buf;    // working/result buffer
    std::string input;  // immutable per-attempt input (restarts + pulls)
    std::set<uint64_t> applied;  // exactly-once chunk application
    bool complete = false;
    uint64_t attempt = 0;  // bumped per (re)run; stale callbacks ignore
    int fail_error = 0;    // sticky abort of the current attempt
    uint32_t sends_inflight = 0;
    FiberMutex mu;
    FiberCond cv;
};

// ---------------- async chunk send ----------------

struct CollectiveEngine::SendCtx {
    std::shared_ptr<Round> round;
    uint64_t attempt = 0;
    std::unique_ptr<google::protobuf::Message> req;
    std::unique_ptr<google::protobuf::Message> rsp;
    Controller cntl;

    static void Done(SendCtx* c) {
        {
            FiberMutexGuard g(c->round->mu);
            if (c->round->attempt == c->attempt) {
                if (c->round->sends_inflight > 0) {
                    c->round->sends_inflight--;
                }
                if (c->cntl.Failed() && c->round->fail_error == 0) {
                    c->round->fail_error = c->cntl.ErrorCode();
                }
                c->round->cv.notify_all();
            }
        }
        delete c;
    }
};

void CollectiveEngine::SendChunkAsync(const std::shared_ptr<Round>& round,
                                      uint64_t attempt, const CollWire& w,
                                      Result* r) {
    auto* c = new SendCtx;
    c->round = round;
    c->attempt = attempt;
    c->req.reset(codec_->NewRequest(w));
    c->rsp.reset(codec_->NewResponse());
    c->cntl.set_timeout_ms(opts_.step_timeout_ms);
    c->cntl.set_max_retry(opts_.max_chunk_retries);
    std::shared_ptr<google::protobuf::RpcChannel> chan;
    {
        FiberMutexGuard g(round->mu);
        if (round->attempt != attempt || round->fail_error != 0) {
            delete c;
            return;
        }
        const uint32_t peer = (round->my_rank + 1) % round->nranks;
        chan = round->members[peer].chan;
        const char* src = round->buf.data() + w.offset;
        IOBuf pbuf;
        if (opts_.pool_descriptors &&
            IciBlockPool::AllocatePoolAttachmentCopy(src, (size_t)w.len,
                                                     &pbuf)) {
            // The pin rides the existing lease machinery: exactly-once
            // release at EndRPC, reaper + peer-death as backstops.
            c->cntl.set_request_pool_attachment(std::move(pbuf));
        } else {
            c->cntl.request_attachment().append(src, (size_t)w.len);
            if (r != nullptr) r->desc_fallback_chunks++;
            *g_desc_fallbacks << 1;
        }
        round->sends_inflight++;
    }
    *g_steps << 1;
    RecordStepEvent(w);
    *g_bytes << (int64_t)w.len;
    if (r != nullptr) r->moved_bytes += w.len;
    chan->CallMethod(codec_->method(), &c->cntl, c->req.get(), c->rsp.get(),
                     google::protobuf::NewCallback(&SendCtx::Done, c));
}

// ---------------- engine lifecycle ----------------

CollectiveEngine::CollectiveEngine(CollectiveMembership* membership,
                                   CollectiveCodec* codec,
                                   const CollectiveOptions& opts)
    : membership_(membership), codec_(codec), opts_(opts) {
    if (opts_.chunk_bytes < 4) opts_.chunk_bytes = 4;
}

CollectiveEngine::~CollectiveEngine() { Shutdown(); }

void CollectiveEngine::Shutdown() {
    FiberMutexGuard g(mu_);
    shutdown_ = true;
    cv_.notify_all();
    for (auto& kv : rounds_) {
        FiberMutexGuard rg(kv.second->mu);
        if (kv.second->fail_error == 0) {
            kv.second->fail_error = TERR_CLOSE;
        }
        kv.second->cv.notify_all();
    }
}

bool CollectiveEngine::ProbeMembers(
    uint32_t scope, std::vector<CollectiveMembership::Member>* members,
    uint32_t* my_rank, uint64_t* hash) {
    members->clear();
    membership_->GetMembers(members);
    if (scope == SCOPE_ZONE || scope == SCOPE_ZONE_BCAST) {
        // My zone only. Every node filters its OWN view the same way,
        // so agreeing views produce agreeing hashes (the convergence
        // machinery resolves the rest).
        std::string my_zone;
        for (const auto& m : *members) {
            if (m.self) my_zone = m.zone;
        }
        members->erase(
            std::remove_if(members->begin(), members->end(),
                           [&](const CollectiveMembership::Member& m) {
                               return m.zone != my_zone;
                           }),
            members->end());
    } else if (scope == SCOPE_LEADERS) {
        // Lowest-key member per zone. Valid only when self IS a leader
        // (the self-missing check below fails otherwise).
        std::map<std::string, uint64_t> min_key;
        for (const auto& m : *members) {
            auto it = min_key.find(m.zone);
            if (it == min_key.end() || m.key < it->second) {
                min_key[m.zone] = m.key;
            }
        }
        members->erase(
            std::remove_if(members->begin(), members->end(),
                           [&](const CollectiveMembership::Member& m) {
                               return min_key[m.zone] != m.key;
                           }),
            members->end());
    }
    std::sort(members->begin(), members->end(),
              [](const CollectiveMembership::Member& a,
                 const CollectiveMembership::Member& b) {
                  return a.key < b.key;
              });
    int self = -1;
    for (size_t i = 0; i < members->size(); ++i) {
        if ((*members)[i].self) self = (int)i;
    }
    // Scoped phases may be single-member (a 1-node zone; the only
    // surviving leader) — the drivers turn those into local no-ops.
    const size_t min_members = scope == SCOPE_GLOBAL ? 2 : 1;
    if (members->size() < min_members || self < 0) return false;
    *my_rank = (uint32_t)self;
    *hash = HashKeys(*members);
    return true;
}

std::shared_ptr<CollectiveEngine::Round> CollectiveEngine::GetOrCreateRound(
    uint32_t rkind, uint32_t scope, uint64_t seq,
    std::vector<CollectiveMembership::Member>&& members, uint32_t my_rank,
    uint64_t hash, const std::string& input, size_t base_bytes, Result* r) {
    const uint32_t nranks = (uint32_t)members.size();
    auto reset_buffers = [&](Round* rd) {
        rd->input = input;
        switch (rkind) {
            case COLL_ALLREDUCE:
            case COLL_SERIAL_PUSH:
                rd->total_bytes = input.size();
                rd->buf = input;
                break;
            case COLL_ALLGATHER:
                // input = my block; buf = nranks blocks in rank order.
                rd->total_bytes = (uint64_t)base_bytes * nranks;
                rd->buf.assign((size_t)rd->total_bytes, '\0');
                memcpy(&rd->buf[(size_t)base_bytes * my_rank], input.data(),
                       base_bytes);
                break;
            case COLL_ALLTOALL:
                // input = nranks outbound blocks; buf = inbound blocks.
                rd->total_bytes = (uint64_t)base_bytes * nranks;
                rd->buf.assign((size_t)rd->total_bytes, '\0');
                memcpy(&rd->buf[(size_t)base_bytes * my_rank],
                       input.data() + (size_t)base_bytes * my_rank,
                       base_bytes);
                break;
            case COLL_BCAST:
                // Root: input = the payload, servable immediately
                // (complete gates the pulls). Non-roots receive.
                rd->total_bytes = base_bytes;
                if (!input.empty()) {
                    rd->buf = input;
                    rd->complete = true;
                } else {
                    rd->buf.assign(base_bytes, '\0');
                }
                break;
            default:
                break;
        }
    };

    FiberMutexGuard g(mu_);
    if (shutdown_) return nullptr;
    const uint64_t key = RoundKey(FamilyOf(rkind, scope), seq);
    auto it = rounds_.find(key);
    if (it != rounds_.end()) {
        std::shared_ptr<Round> rd = it->second;
        FiberMutexGuard rg(rd->mu);
        rd->attempt++;
        rd->fail_error = 0;
        rd->sends_inflight = 0;
        if (rd->member_hash != hash) {
            // RE-FORM: the membership changed — renumber over the
            // survivors and restart the round from its kept input.
            rd->members = std::move(members);
            rd->nranks = nranks;
            rd->my_rank = my_rank;
            rd->member_hash = hash;
            rd->applied.clear();
            rd->complete = false;
            reset_buffers(rd.get());
            if (r != nullptr) r->reforms++;
            *g_reforms << 1;
            flight::Record(flight::kCollReform, rd->member_hash,
                           (uint64_t)rd->nranks);
        } else {
            // Transient failure with the same membership: keep the
            // applied set and buffer, re-issue outgoing work only
            // (duplicates dedupe server-side). DO adopt the probe's
            // channels — identical keys mean identical rank order, but
            // the mesh may have replaced a reconnected peer's channel
            // underneath the old pointer.
            rd->members = std::move(members);
            if (r != nullptr) r->retries++;
            *g_retries << 1;
        }
        rd->cv.notify_all();
        return rd;
    }
    auto rd = std::make_shared<Round>();
    rd->rkind = rkind;
    rd->scope = scope;
    rd->seq = seq;
    rd->member_hash = hash;
    rd->nranks = nranks;
    rd->my_rank = my_rank;
    rd->members = std::move(members);
    rd->attempt = 1;
    reset_buffers(rd.get());
    rounds_[key] = rd;
    // GC older rounds of this (kind, scope) family, keeping the
    // immediate predecessor alive for late duplicate acks / straggler
    // pulls.
    for (auto gc = rounds_.begin(); gc != rounds_.end();) {
        if (gc->second->rkind == rkind && gc->second->scope == scope &&
            gc->second->seq + 2 <= seq) {
            gc = rounds_.erase(gc);
        } else {
            ++gc;
        }
    }
    cv_.notify_all();  // handler fibers parked on "round not started yet"
    return rd;
}

void CollectiveEngine::FinishRound(const std::shared_ptr<Round>& round,
                                   int err) {
    if (round == nullptr) return;
    if (err == 0) {
        FiberMutexGuard g(mu_);
        uint64_t& mark =
            completed_seq_[FamilyOf(round->rkind, round->scope)];
        if (round->seq > mark) mark = round->seq;
    }
    FiberMutexGuard rg(round->mu);
    if (err == 0) round->complete = true;
    round->cv.notify_all();
}

int CollectiveEngine::WaitRound(Round* rd, uint64_t attempt,
                                int64_t deadline_us,
                                bool (*pred)(Round*, void*), void* arg) {
    FiberMutexGuard g(rd->mu);
    for (;;) {
        if (rd->attempt != attempt) return TERR_STALE_EPOCH;
        if (rd->fail_error != 0) return rd->fail_error;
        if (pred(rd, arg)) return 0;
        if (rd->cv.wait_until(rd->mu, deadline_us) == ETIMEDOUT) {
            return TERR_RPC_TIMEDOUT;
        }
    }
}

// ---------------- ring all-reduce ----------------

namespace {
struct KeyWait {
    uint64_t key;
};
struct KeySetWait {
    const std::vector<uint64_t>* keys;
    bool need_sends_drained;
};
bool PredKeyApplied(CollectiveEngine::Round* rd, void* arg) {
    auto* kw = (KeyWait*)arg;
    return rd->applied.count(kw->key) != 0;
}
bool PredKeysAppliedAndDrained(CollectiveEngine::Round* rd, void* arg) {
    auto* ks = (KeySetWait*)arg;
    if (ks->need_sends_drained && rd->sends_inflight != 0) return false;
    for (uint64_t k : *ks->keys) {
        if (rd->applied.count(k) == 0) return false;
    }
    return true;
}
}  // namespace

int CollectiveEngine::RunRingAttempt(const std::shared_ptr<Round>& round,
                                     int64_t attempt_deadline_us,
                                     Result* r) {
    uint64_t attempt;
    uint32_t n, me;
    uint64_t nwords;
    {
        FiberMutexGuard g(round->mu);
        attempt = round->attempt;
        n = round->nranks;
        me = round->my_rank;
        nwords = round->total_bytes / 4;
    }
    const uint32_t pred_rank = (me + n - 1) % n;
    const uint64_t chunk_words = std::max<uint64_t>(1, opts_.chunk_bytes / 4);

    // ---- verbs lane setup (ISSUE 18) ----
    // One leased window on the ring SUCCESSOR, sized to the largest
    // shard: every step REMOTE_WRITEs its whole shard there with one
    // scatter-gather verb, then rings the doorbell with a payload-free
    // CollChunk RPC. Lane setup needs the successor's pinned socket
    // (the grant exchange and emulated verbs ride that connection);
    // anything missing falls back to the per-chunk path, counted.
    const bool verbs_wanted = opts_.verbs_lane && nwords > 0 && n >= 2;
    bool use_verbs = false;
    verbs::CompletionQueue lane_cq;
    verbs::RemoteWindow lane;
    if (verbs_wanted) {
        const uint32_t succ = (me + 1) % n;
        uint64_t lane_sid = 0;
        auto* ch =
            dynamic_cast<Channel*>(round->members[succ].chan.get());
        if (ch != nullptr) lane_sid = ch->pinned_socket();
        const uint64_t max_shard_bytes =
            (nwords / n + (nwords % n != 0 ? 1 : 0)) * 4;
        if (lane_sid != 0 && lane_sid != INVALID_VREF_ID &&
            verbs::RequestWindow(lane_sid, max_shard_bytes,
                                 verbs::kWinWrite, opts_.step_timeout_ms,
                                 &lane) == 0) {
            use_verbs = true;
        }
    }

    for (uint32_t step = 0; step + 1 < 2 * n - 1; ++step) {
        const uint32_t oshard = OutShard(me, step, n);
        uint64_t w0 = 0, wn = 0;
        ShardRange(nwords, n, oshard, &w0, &wn);
        const uint32_t nchunks = ChunksOf(wn, chunk_words);
        if (use_verbs && nchunks > 0) {
            const int verr =
                VerbsRingStep(round, attempt, step, w0, wn, nchunks,
                              chunk_words, &lane_cq, lane,
                              attempt_deadline_us, r);
            if (verr == 0) continue;
            if (verr > 0) return verr;
            // Lane died (stale window, post/doorbell failure): the
            // remaining steps — starting with a resend of THIS one —
            // ride the per-chunk path; key dedupe absorbs any overlap
            // with verb work that did land.
            use_verbs = false;
        }
        for (uint32_t c = 0; c < nchunks; ++c) {
            if (verbs_wanted && !use_verbs) {
                if (r != nullptr) r->verb_fallback_chunks++;
                *g_verb_fallbacks << 1;
            }
            if (step > 0) {
                // The bytes about to go out were produced by the
                // step-1 incoming chunk: wait for its application.
                // Transfers of later chunks keep flowing meanwhile —
                // this is the communication/compute overlap.
                KeyWait kw{PackChunk(pred_rank, step - 1, c)};
                const int err = WaitRound(round.get(), attempt,
                                          attempt_deadline_us,
                                          &PredKeyApplied, &kw);
                if (err != 0) return err;
            }
            const uint64_t cw0 = w0 + (uint64_t)c * chunk_words;
            const uint64_t clen =
                std::min<uint64_t>(chunk_words, wn - (uint64_t)c *
                                                         chunk_words);
            CollWire w;
            w.seq = round->seq;
            w.kind = COLL_ALLREDUCE;
            w.scope = round->scope;
            w.step = step;
            w.chunk = c;
            w.src_rank = me;
            w.nranks = n;
            w.member_hash = round->member_hash;
            w.total_bytes = nwords * 4;
            w.offset = cw0 * 4;
            w.len = clen * 4;
            SendChunkAsync(round, attempt, w, r);
        }
    }

    // Completion: every incoming chunk of every step applied, and our
    // own sends drained.
    std::vector<uint64_t> expect;
    for (uint32_t step = 0; step + 1 < 2 * n - 1; ++step) {
        const uint32_t ishard = OutShard(pred_rank, step, n);
        uint64_t w0 = 0, wn = 0;
        ShardRange(nwords, n, ishard, &w0, &wn);
        const uint32_t nchunks = ChunksOf(wn, chunk_words);
        for (uint32_t c = 0; c < nchunks; ++c) {
            expect.push_back(PackChunk(pred_rank, step, c));
        }
    }
    KeySetWait ks{&expect, true};
    return WaitRound(round.get(), attempt, attempt_deadline_us,
                     &PredKeysAppliedAndDrained, &ks);
}

int CollectiveEngine::VerbsRingStep(const std::shared_ptr<Round>& round,
                                    uint64_t attempt, uint32_t step,
                                    uint64_t w0, uint64_t wn,
                                    uint32_t nchunks, uint64_t chunk_words,
                                    verbs::CompletionQueue* cq,
                                    const verbs::RemoteWindow& lane,
                                    int64_t attempt_deadline_us,
                                    Result* r) {
    uint32_t n, me, pred_rank;
    {
        FiberMutexGuard g(round->mu);
        if (round->attempt != attempt) return TERR_STALE_EPOCH;
        if (round->fail_error != 0) return round->fail_error;
        n = round->nranks;
        me = round->my_rank;
    }
    pred_rank = (me + n - 1) % n;
    // The bytes about to go out were produced by the step-1 incoming
    // applies: wait for ALL of them (the SGL write moves the whole
    // shard at once, so the per-chunk overlap of the RPC path becomes
    // per-step here — the verb itself is the bulk win).
    if (step > 0) {
        std::vector<uint64_t> deps;
        deps.reserve(nchunks);
        for (uint32_t c = 0; c < nchunks; ++c) {
            deps.push_back(PackChunk(pred_rank, step - 1, c));
        }
        KeySetWait ks{&deps, false};
        const int err = WaitRound(round.get(), attempt, attempt_deadline_us,
                                  &PredKeysAppliedAndDrained, &ks);
        if (err != 0) return err;
    }
    // Snapshot wire fields + the shard base under the lock. The buffer
    // never reallocates during a round and the dep wait above ordered
    // the producer writes, so gathering from it lock-free is safe.
    CollWire w;
    char* base = nullptr;
    {
        FiberMutexGuard g(round->mu);
        if (round->attempt != attempt) return TERR_STALE_EPOCH;
        if (round->fail_error != 0) return round->fail_error;
        base = &round->buf[(size_t)(w0 * 4)];
        w.seq = round->seq;
        w.scope = round->scope;
        w.member_hash = round->member_hash;
        w.total_bytes = round->total_bytes;
    }
    w.kind = COLL_ALLREDUCE;
    w.step = step;
    w.chunk = kVerbDoorbellChunk;
    w.src_rank = me;
    w.nranks = n;
    w.offset = w0 * 4;
    w.len = wn * 4;
    w.verb_window = lane.window_id;
    w.verb_nchunks = nchunks;
    w.verb_epoch = lane.epoch;

    // One scatter-gather WRITE covering the step's chunks (window
    // offset 0 every step — the sync doorbell below orders the reuse).
    std::vector<verbs::Sge> sgl;
    sgl.reserve(nchunks);
    for (uint32_t c = 0; c < nchunks; ++c) {
        const uint64_t cw = std::min<uint64_t>(
            chunk_words, wn - (uint64_t)c * chunk_words);
        verbs::Sge sg;
        sg.addr = base + (size_t)c * chunk_words * 4;
        sg.len = cw * 4;
        sgl.push_back(sg);
    }
    const uint64_t wr = kCollWrTag | g_coll_wr.fetch_add(1);
    if (verbs::PostWrite(cq, wr, lane, 0, sgl.data(),
                         (uint32_t)sgl.size()) != 0) {
        return -1;
    }
    // The completion ALWAYS arrives while we park (the CQ drives the
    // pending-post reaper; a dropped verb retries a bounded number of
    // times and then completes TERR_RPC_TIMEDOUT) — and it MUST be
    // collected before returning: the CQ is the attempt's stack frame.
    verbs::Completion comp;
    for (;;) {
        if (!cq->Park(&comp, 8 * 1000 * 1000)) return TERR_INTERNAL;
        if (comp.wr_id == wr) break;  // stray: an older step's retry
    }
    if (comp.status != 0) return -1;
    w.verb_crc = crc32c_extend(0, base, (size_t)(wn * 4));

    // Ring the doorbell: a payload-free chunk RPC through the normal
    // funnel (its retries absorb receiver round skew the same way the
    // chunk path's do).
    std::shared_ptr<google::protobuf::RpcChannel> chan;
    {
        FiberMutexGuard g(round->mu);
        if (round->attempt != attempt) return TERR_STALE_EPOCH;
        if (round->fail_error != 0) return round->fail_error;
        chan = round->members[(me + 1) % n].chan;
    }
    std::unique_ptr<google::protobuf::Message> req(codec_->NewRequest(w));
    std::unique_ptr<google::protobuf::Message> rsp(codec_->NewResponse());
    Controller cntl;
    cntl.set_timeout_ms(std::max<int64_t>(
        1, std::min(opts_.step_timeout_ms,
                    (attempt_deadline_us - monotonic_time_us()) / 1000)));
    cntl.set_max_retry(opts_.max_chunk_retries + 4);
    chan->CallMethod(codec_->method(), &cntl, req.get(), rsp.get(),
                     nullptr);
    *g_steps << 1;
    RecordStepEvent(w);
    *g_verb_steps << 1;
    *g_bytes << (int64_t)(wn * 4);
    if (r != nullptr) {
        r->moved_bytes += wn * 4;
        r->verb_steps++;
    }
    if (cntl.Failed()) return -1;
    return 0;
}

// ---------------- fan-out phases (ParallelChannel reuse) ----------------

// One sub-call per (peer, chunk): the mapper builds the chunk request
// (+ outbound block bytes for all-to-all, posted as pool descriptors),
// the observer applies the reply bytes (pull/exchange payload —
// response descriptors on capable links) into the round buffer.
class CollectiveEngine::FanMapper : public CallMapper,
                                    public SubCallObserver {
public:
    struct Item {
        uint32_t peer_rank = 0;
        uint32_t chunk_index = 0;  // per-block chunk ordinal (wire)
        uint64_t off = 0;          // block-relative
        uint64_t len = 0;
    };

    CollectiveEngine* eng = nullptr;
    std::shared_ptr<Round> round;
    uint64_t attempt = 0;
    uint32_t kind = 0;
    uint64_t block_bytes = 0;
    std::vector<Item> items;
    Result* res = nullptr;  // driver-fiber only (Map runs there)

    SubCall Map(int channel_index, int, const
                google::protobuf::MethodDescriptor*,
                const google::protobuf::Message*,
                google::protobuf::Message*) override {
        const Item& it = items[channel_index];
        CollWire w;
        w.seq = round->seq;
        w.kind = kind;
        w.scope = round->scope;
        w.step = 0;
        w.chunk = it.chunk_index;
        w.src_rank = round->my_rank;
        w.nranks = round->nranks;
        w.member_hash = round->member_hash;
        w.total_bytes = round->total_bytes;
        w.offset = it.off;
        w.len = it.len;
        SubCall s;
        s.method = eng->codec_->method();
        s.request = eng->codec_->NewRequest(w);
        s.owns_request = true;
        s.response = eng->codec_->NewResponse();
        s.owns_response = true;
        s.observer = this;
        if (kind == COLL_ALLTOALL) {
            // Outbound block chunk for this peer rides the sub-call.
            const char* src = round->input.data() +
                              (size_t)(block_bytes * it.peer_rank + it.off);
            IOBuf pbuf;
            if (eng->opts_.pool_descriptors &&
                IciBlockPool::AllocatePoolAttachmentCopy(
                    src, (size_t)it.len, &pbuf)) {
                s.request_attachment.swap(pbuf);
                s.pool_descriptor = true;
            } else {
                s.request_attachment.append(src, (size_t)it.len);
                if (res != nullptr) res->desc_fallback_chunks++;
                *g_desc_fallbacks << 1;
            }
            *g_bytes << (int64_t)it.len;
            if (res != nullptr) res->moved_bytes += it.len;
        }
        *g_steps << 1;
        RecordStepEvent(w);
        return s;
    }

    void OnSubCallDone(int channel_index, Controller& sub) override {
        if (sub.Failed()) return;  // the parent's fail_limit reports it
        const Item& it = items[channel_index];
        const char* data = nullptr;
        uint64_t len = 0;
        std::string inline_copy;
        if (sub.has_response_pool_attachment_view()) {
            data = sub.response_pool_attachment().data;
            len = sub.response_pool_attachment().length;
        } else {
            inline_copy = sub.response_attachment().to_string();
            data = inline_copy.data();
            len = inline_copy.size();
        }
        FiberMutexGuard g(round->mu);
        if (round->attempt != attempt) return;
        if (len != it.len) {
            if (round->fail_error == 0) round->fail_error = TERR_RESPONSE;
        } else {
            memcpy(&round->buf[(size_t)(block_bytes * it.peer_rank +
                                        it.off)],
                   data, (size_t)len);
        }
        round->cv.notify_all();
    }
};

int CollectiveEngine::RunFanoutAttempt(const std::shared_ptr<Round>& round,
                                       uint32_t kind,
                                       int64_t attempt_deadline_us,
                                       Result* r) {
    uint64_t attempt;
    uint32_t n, me;
    uint64_t block;
    {
        FiberMutexGuard g(round->mu);
        attempt = round->attempt;
        n = round->nranks;
        me = round->my_rank;
        block = round->total_bytes / n;
    }
    auto mapper = std::make_shared<FanMapper>();
    mapper->eng = this;
    mapper->round = round;
    mapper->attempt = attempt;
    mapper->kind = kind;
    mapper->block_bytes = block;
    mapper->res = r;
    const uint64_t chunk = std::max<uint64_t>(4, opts_.chunk_bytes & ~3ull);
    for (uint32_t p = 0; p < n; ++p) {
        if (p == me) continue;
        // All-to-all pairs exchange once: the LOWER rank initiates and
        // receives the reciprocal block in the same call's response.
        if (kind == COLL_ALLTOALL && p < me) continue;
        uint32_t c = 0;
        for (uint64_t off = 0; off < block; off += chunk, ++c) {
            FanMapper::Item it;
            it.peer_rank = p;
            it.chunk_index = c;
            it.off = off;
            it.len = std::min<uint64_t>(chunk, block - off);
            mapper->items.push_back(it);
        }
    }

    if (!mapper->items.empty()) {
        const int64_t remaining_ms =
            std::max<int64_t>(1, (attempt_deadline_us -
                                  monotonic_time_us()) / 1000);
        ParallelChannelOptions po;
        po.fail_limit = 1;  // any lost chunk fails the attempt -> re-form
        po.timeout_ms = remaining_ms;
        ParallelChannel pc(&po);
        for (const FanMapper::Item& it : mapper->items) {
            pc.AddChannelShared(round->members[it.peer_rank].chan.get(),
                                mapper, nullptr);
        }
        std::unique_ptr<google::protobuf::Message> preq(
            codec_->NewRequest(CollWire()));
        std::unique_ptr<google::protobuf::Message> prsp(
            codec_->NewResponse());
        Controller pcntl;
        pcntl.set_timeout_ms(remaining_ms);
        pcntl.set_max_retry(opts_.max_chunk_retries);
        pc.CallMethod(codec_->method(), &pcntl, preq.get(), prsp.get(),
                      nullptr);  // sync: per-chunk funnel retries inside
        if (pcntl.Failed()) {
            FiberMutexGuard g(round->mu);
            return round->fail_error != 0 ? round->fail_error
                                          : pcntl.ErrorCode();
        }
        {
            // A reply shorter than asked surfaced through the observer.
            FiberMutexGuard g(round->mu);
            if (round->fail_error != 0) return round->fail_error;
            if (round->attempt != attempt) return TERR_STALE_EPOCH;
        }
    }

    if (kind == COLL_ALLTOALL) {
        // Lower-ranked peers initiated toward us (the lower rank of
        // each pair drives the exchange): wait for their pushes.
        std::vector<uint64_t> expect;
        for (uint32_t q = 0; q < me; ++q) {
            uint32_t c = 0;
            for (uint64_t off = 0; off < block; off += chunk, ++c) {
                expect.push_back(PackChunk(q, 0, c));
            }
        }
        KeySetWait ks{&expect, false};
        return WaitRound(round.get(), attempt, attempt_deadline_us,
                         &PredKeysAppliedAndDrained, &ks);
    }
    return 0;
}

// ---------------- pull broadcast (hier phase 3) ----------------

int CollectiveEngine::RunBcastAttempt(const std::shared_ptr<Round>& round,
                                      int64_t attempt_deadline_us,
                                      Result* r) {
    uint64_t attempt;
    uint32_t n, me;
    uint64_t total;
    {
        FiberMutexGuard g(round->mu);
        attempt = round->attempt;
        n = round->nranks;
        me = round->my_rank;
        total = round->total_bytes;
    }
    const uint64_t chunk = std::max<uint64_t>(4, opts_.chunk_bytes & ~3ull);
    if (me == 0) {
        // Root: serve (the handler does the work) until every member
        // pulled every chunk.
        std::vector<uint64_t> expect;
        for (uint32_t q = 1; q < n; ++q) {
            uint32_t c = 0;
            for (uint64_t off = 0; off < total; off += chunk, ++c) {
                expect.push_back(PackChunk(q, 0, c));
            }
        }
        KeySetWait ks{&expect, false};
        return WaitRound(round.get(), attempt, attempt_deadline_us,
                         &PredKeysAppliedAndDrained, &ks);
    }
    // Non-root: chunked parallel pulls from rank 0, applied at the
    // absolute offset (peer_rank 0 zeroes the FanMapper's block base).
    auto mapper = std::make_shared<FanMapper>();
    mapper->eng = this;
    mapper->round = round;
    mapper->attempt = attempt;
    mapper->kind = COLL_BCAST;
    mapper->block_bytes = total;
    mapper->res = r;
    uint32_t c = 0;
    for (uint64_t off = 0; off < total; off += chunk, ++c) {
        FanMapper::Item it;
        it.peer_rank = 0;
        it.chunk_index = c;
        it.off = off;
        it.len = std::min<uint64_t>(chunk, total - off);
        mapper->items.push_back(it);
    }
    const int64_t remaining_ms = std::max<int64_t>(
        1, (attempt_deadline_us - monotonic_time_us()) / 1000);
    ParallelChannelOptions po;
    po.fail_limit = 1;
    po.timeout_ms = remaining_ms;
    ParallelChannel pc(&po);
    for (size_t i = 0; i < mapper->items.size(); ++i) {
        pc.AddChannelShared(round->members[0].chan.get(), mapper, nullptr);
    }
    std::unique_ptr<google::protobuf::Message> preq(
        codec_->NewRequest(CollWire()));
    std::unique_ptr<google::protobuf::Message> prsp(codec_->NewResponse());
    Controller pcntl;
    pcntl.set_timeout_ms(remaining_ms);
    pcntl.set_max_retry(opts_.max_chunk_retries);
    pc.CallMethod(codec_->method(), &pcntl, preq.get(), prsp.get(),
                  nullptr);
    FiberMutexGuard g(round->mu);
    if (round->fail_error != 0) return round->fail_error;
    if (round->attempt != attempt) return TERR_STALE_EPOCH;
    if (pcntl.Failed()) return pcntl.ErrorCode();
    return 0;
}

// ---------------- serial baseline ----------------

int CollectiveEngine::RunSerialAttempt(const std::shared_ptr<Round>& round,
                                       int64_t attempt_deadline_us,
                                       Result* r) {
    uint64_t attempt;
    uint32_t n, me;
    uint64_t total;
    {
        FiberMutexGuard g(round->mu);
        attempt = round->attempt;
        n = round->nranks;
        me = round->my_rank;
        total = round->total_bytes;
    }
    if (me == 0) {
        // Root: every non-root pushes its whole payload (reduced by the
        // handler), then pulls the whole result. Completion = all
        // pushed AND all pulled — root-side serving is inside the
        // measured window, as a serial fan-in/fan-out should be.
        std::vector<uint64_t> expect;
        for (uint32_t q = 1; q < n; ++q) expect.push_back(PackChunk(q, 0, 0));
        KeySetWait ks{&expect, false};
        int err = WaitRound(round.get(), attempt, attempt_deadline_us,
                            &PredKeysAppliedAndDrained, &ks);
        if (err != 0) return err;
        {
            FiberMutexGuard g(round->mu);
            round->complete = true;  // pulls may now be served
            round->cv.notify_all();
        }
        std::vector<uint64_t> pulls;
        for (uint32_t q = 1; q < n; ++q) pulls.push_back(PackChunk(q, 1, 0));
        KeySetWait ks2{&pulls, false};
        return WaitRound(round.get(), attempt, attempt_deadline_us,
                         &PredKeysAppliedAndDrained, &ks2);
    }
    // Non-root: inline push, then inline pull. Deliberately ONE
    // unchunked, undescriptored, unpipelined call each way.
    std::shared_ptr<google::protobuf::RpcChannel> root =
        round->members[0].chan;
    CollWire w;
    w.seq = round->seq;
    w.kind = COLL_SERIAL_PUSH;
    w.scope = round->scope;
    w.src_rank = me;
    w.nranks = n;
    w.member_hash = round->member_hash;
    w.total_bytes = total;
    w.offset = 0;
    w.len = total;
    {
        std::unique_ptr<google::protobuf::Message> req(
            codec_->NewRequest(w));
        std::unique_ptr<google::protobuf::Message> rsp(
            codec_->NewResponse());
        Controller cntl;
        cntl.set_timeout_ms(std::max<int64_t>(
            1, (attempt_deadline_us - monotonic_time_us()) / 1000));
        cntl.set_max_retry(opts_.max_chunk_retries + 4);
        cntl.request_attachment().append(round->input.data(),
                                         round->input.size());
        root->CallMethod(codec_->method(), &cntl, req.get(), rsp.get(),
                         nullptr);
        *g_steps << 1;
        RecordStepEvent(w);
        *g_bytes << (int64_t)total;
        if (r != nullptr) r->moved_bytes += total;
        if (cntl.Failed()) return cntl.ErrorCode();
    }
    w.kind = COLL_SERIAL_PULL;
    std::unique_ptr<google::protobuf::Message> req(codec_->NewRequest(w));
    std::unique_ptr<google::protobuf::Message> rsp(codec_->NewResponse());
    Controller cntl;
    cntl.set_timeout_ms(std::max<int64_t>(
        1, (attempt_deadline_us - monotonic_time_us()) / 1000));
    cntl.set_max_retry(opts_.max_chunk_retries + 4);
    root->CallMethod(codec_->method(), &cntl, req.get(), rsp.get(),
                     nullptr);
    *g_steps << 1;
    RecordStepEvent(w);
    if (cntl.Failed()) return cntl.ErrorCode();
    std::string result = cntl.response_attachment().to_string();
    if (result.size() != total) return TERR_RESPONSE;
    FiberMutexGuard g(round->mu);
    if (round->attempt != attempt) return TERR_STALE_EPOCH;
    round->buf.assign(result);
    return 0;
}

// ---------------- public ops ----------------

namespace {

// Bench-only algorithm tag for the hierarchical composition (not a
// wire kind — rounds of the hier phases record under their own op).
constexpr uint32_t kAlgHierAllReduce = 100;
// Bench-only tag for a ring all-reduce whose every step rode the verbs
// lane (ISSUE 18) — recorded apart from the chunked ring so the bench
// can gate verbs-vs-chunks directly off the two gauges.
constexpr uint32_t kAlgVerbsAllReduce = 101;

double BusbwFactor(uint32_t rkind, uint32_t n) {
    if (rkind == COLL_ALLREDUCE || rkind == COLL_SERIAL_PUSH ||
        rkind == kAlgHierAllReduce || rkind == kAlgVerbsAllReduce) {
        return 2.0 * (n - 1) / n;
    }
    return (double)(n - 1) / n;
}

const char* AlgName(uint32_t rkind) {
    switch (rkind) {
        case COLL_ALLREDUCE:
            return "allreduce";
        case COLL_ALLGATHER:
            return "allgather";
        case COLL_ALLTOALL:
            return "alltoall";
        case COLL_SERIAL_PUSH:
            return "allreduce_serial";
        case kAlgHierAllReduce:
            return "hier_allreduce";
        case kAlgVerbsAllReduce:
            return "allreduce_verbs";
        default:
            return "unknown";
    }
}

// Fills Result::busbw_mbps and the per-algorithm gauge — the one place
// the busbw formula lives (drivers print Result, never re-derive).
void RecordBusbw(uint32_t rkind, uint64_t payload_bytes,
                 CollectiveEngine::Result* r) {
    const double secs = r->elapsed_us / 1e6;
    if (secs <= 0 || r->nranks < 2) return;
    r->busbw_mbps =
        BusbwFactor(rkind, r->nranks) * payload_bytes / secs / 1e6;
    BusbwFamily()->get_stats({AlgName(rkind)})->set(
        (int64_t)r->busbw_mbps);
}

}  // namespace

// The ring all-reduce driver body, parameterized by membership scope
// (ISSUE 14): the flat public op runs it SCOPE_GLOBAL; the hierarchical
// phases run it SCOPE_ZONE / SCOPE_ZONE_BCAST. A single-member scoped
// round is a local no-op (nothing to exchange — the 1-node zone, or the
// only surviving leader after a whole-pod partition).
int CollectiveEngine::ScopedAllReduce(uint32_t scope, uint64_t seq,
                                      uint32_t* words, size_t nwords,
                                      Result* r) {
    const int64_t op_deadline =
        monotonic_time_us() + opts_.op_timeout_ms * 1000;
    const std::string input((const char*)words, nwords * 4);
    int err = TERR_INTERNAL;
    std::shared_ptr<Round> round;
    for (int attempt = 0;
         attempt < opts_.max_attempts && monotonic_time_us() < op_deadline;
         ++attempt) {
        std::vector<CollectiveMembership::Member> members;
        uint32_t my_rank = 0;
        uint64_t hash = 0;
        if (!ProbeMembers(scope, &members, &my_rank, &hash)) {
            err = TERR_INTERNAL;
            fiber_usleep(200 * 1000);  // mesh may be healing
            continue;
        }
        if (members.size() == 1) {
            r->nranks = 1;
            r->my_rank = 0;
            r->member_keys.assign(1, members[0].key);
            return 0;
        }
        round = GetOrCreateRound(COLL_ALLREDUCE, scope, seq,
                                 std::move(members), my_rank, hash, input,
                                 input.size(), r);
        if (round == nullptr) {
            err = TERR_CLOSE;
            break;
        }
        const int64_t attempt_deadline = std::min(
            op_deadline,
            monotonic_time_us() + opts_.attempt_timeout_ms * 1000);
        err = RunRingAttempt(round, attempt_deadline, r);
        if (err == 0) break;
        fiber_usleep(100 * 1000);
    }
    if (err == 0 && round != nullptr) {
        FiberMutexGuard g(round->mu);
        memcpy(words, round->buf.data(), nwords * 4);
        r->nranks = round->nranks;
        r->my_rank = round->my_rank;
        r->member_keys.clear();
        for (const auto& m : round->members) {
            r->member_keys.push_back(m.key);
        }
    }
    FinishRound(round, err);
    return err;
}

int CollectiveEngine::AllReduce(uint64_t seq, uint32_t* words,
                                size_t nwords, Result* r) {
    Result local;
    if (r == nullptr) r = &local;
    if (words == nullptr || nwords == 0) {
        return r->error = TERR_REQUEST;
    }
    const int64_t t0 = monotonic_time_us();
    const int err = ScopedAllReduce(SCOPE_GLOBAL, seq, words, nwords, r);
    r->error = err;
    r->elapsed_us = monotonic_time_us() - t0;
    if (err == 0) {
        *g_ops << 1;
        // A round whose EVERY step rode the verbs lane records under
        // its own gauge (the bench's verbs-vs-chunks numerator); any
        // fallback taints the sample back onto the chunked gauge.
        RecordBusbw(r->verb_steps > 0 && r->verb_fallback_chunks == 0
                        ? kAlgVerbsAllReduce
                        : COLL_ALLREDUCE,
                    nwords * 4, r);
    }
    return err;
}

// The all-gather driver body, parameterized by membership scope: the
// flat public op runs it SCOPE_GLOBAL; hier phase 2 runs it
// SCOPE_LEADERS, where every leader's block is the SAME size (zone-key
// header padded to a fixed width + the zone-sum payload) and a
// single-member scope — every other pod gone, or there never was one —
// degrades to out = input.
int CollectiveEngine::ScopedAllGather(uint32_t scope, uint64_t seq,
                                      const std::string& input,
                                      std::string* out, Result* r) {
    const int64_t op_deadline =
        monotonic_time_us() + opts_.op_timeout_ms * 1000;
    int err = TERR_INTERNAL;
    std::shared_ptr<Round> round;
    for (int attempt = 0;
         attempt < opts_.max_attempts && monotonic_time_us() < op_deadline;
         ++attempt) {
        std::vector<CollectiveMembership::Member> members;
        uint32_t my_rank = 0;
        uint64_t hash = 0;
        if (!ProbeMembers(scope, &members, &my_rank, &hash)) {
            err = TERR_INTERNAL;
            fiber_usleep(200 * 1000);
            continue;
        }
        if (members.size() == 1) {
            out->assign(input);
            r->nranks = 1;
            r->my_rank = 0;
            r->member_keys.assign(1, members[0].key);
            return 0;
        }
        round = GetOrCreateRound(COLL_ALLGATHER, scope, seq,
                                 std::move(members), my_rank, hash, input,
                                 input.size(), r);
        if (round == nullptr) {
            err = TERR_CLOSE;
            break;
        }
        const int64_t attempt_deadline = std::min(
            op_deadline,
            monotonic_time_us() + opts_.attempt_timeout_ms * 1000);
        err = RunFanoutAttempt(round, COLL_ALLGATHER, attempt_deadline, r);
        if (err == 0) break;
        fiber_usleep(100 * 1000);
    }
    if (err == 0 && round != nullptr) {
        FiberMutexGuard g(round->mu);
        out->assign(round->buf);
        r->nranks = round->nranks;
        r->my_rank = round->my_rank;
        r->member_keys.clear();
        for (const auto& m : round->members) {
            r->member_keys.push_back(m.key);
        }
    }
    FinishRound(round, err);
    return err;
}

// Chunked pull broadcast within a scope (hier phase 3): rank 0 serves
// its payload, everyone else pulls. A caller whose leadership view
// disagrees with the live probe (leadership moved mid-op) fails
// retriable — the hier driver restarts all phases.
int CollectiveEngine::ScopedBroadcast(uint32_t scope, uint64_t seq,
                                      char* bytes, size_t nbytes,
                                      bool leader, Result* r) {
    const int64_t op_deadline =
        monotonic_time_us() + opts_.op_timeout_ms * 1000;
    const std::string input(leader ? std::string(bytes, nbytes)
                                   : std::string());
    int err = TERR_INTERNAL;
    std::shared_ptr<Round> round;
    for (int attempt = 0;
         attempt < opts_.max_attempts && monotonic_time_us() < op_deadline;
         ++attempt) {
        std::vector<CollectiveMembership::Member> members;
        uint32_t my_rank = 0;
        uint64_t hash = 0;
        if (!ProbeMembers(scope, &members, &my_rank, &hash)) {
            err = TERR_INTERNAL;
            fiber_usleep(200 * 1000);
            continue;
        }
        if (members.size() == 1) {
            r->nranks = 1;
            r->my_rank = 0;
            r->member_keys.assign(1, members[0].key);
            return leader ? 0 : TERR_STALE_EPOCH;  // lone non-leader?
        }
        if (leader != (my_rank == 0)) {
            // Leadership moved between the caller's phase-2 view and
            // this probe: retriable, the hier driver re-runs phase 1.
            return TERR_STALE_EPOCH;
        }
        round = GetOrCreateRound(COLL_BCAST, scope, seq,
                                 std::move(members), my_rank, hash, input,
                                 nbytes, r);
        if (round == nullptr) {
            err = TERR_CLOSE;
            break;
        }
        const int64_t attempt_deadline = std::min(
            op_deadline,
            monotonic_time_us() + opts_.attempt_timeout_ms * 1000);
        err = RunBcastAttempt(round, attempt_deadline, r);
        if (err == 0) break;
        fiber_usleep(100 * 1000);
    }
    if (err == 0 && round != nullptr) {
        FiberMutexGuard g(round->mu);
        if (!leader) memcpy(bytes, round->buf.data(), nbytes);
        r->nranks = round->nranks;
        r->my_rank = round->my_rank;
        r->member_keys.clear();
        for (const auto& m : round->members) {
            r->member_keys.push_back(m.key);
        }
    }
    FinishRound(round, err);
    return err;
}

namespace {
// Phase-3 payload header: [u32 nkeys][u64 key * kMaxHierKeys] as uint32
// words, followed by the delta payload the leader broadcasts (pull
// bcast — non-leaders receive it verbatim, no reduce, half the ring's
// byte volume).
constexpr size_t kMaxHierKeys = 64;
constexpr size_t kHierHdrWords = 1 + 2 * kMaxHierKeys;

void PackHierKeys(uint32_t* w, const std::vector<uint64_t>& keys) {
    w[0] = (uint32_t)keys.size();
    for (size_t i = 0; i < keys.size() && i < kMaxHierKeys; ++i) {
        w[1 + 2 * i] = (uint32_t)(keys[i] & 0xFFFFFFFFu);
        w[2 + 2 * i] = (uint32_t)(keys[i] >> 32);
    }
}

bool UnpackHierKeys(const uint32_t* w, std::vector<uint64_t>* keys) {
    const uint32_t nk = w[0];
    if (nk == 0 || nk > kMaxHierKeys) return false;
    keys->clear();
    for (uint32_t i = 0; i < nk; ++i) {
        keys->push_back((uint64_t)w[1 + 2 * i] |
                        ((uint64_t)w[2 + 2 * i] << 32));
    }
    return true;
}
}  // namespace

int CollectiveEngine::HierAllReduce(uint64_t seq, uint32_t* words,
                                    size_t nwords, Result* r) {
    Result local;
    if (r == nullptr) r = &local;
    if (words == nullptr || nwords == 0) {
        return r->error = TERR_REQUEST;
    }
    const int64_t t0 = monotonic_time_us();
    const int64_t op_deadline = t0 + opts_.op_timeout_ms * 1000;
    int err = TERR_INTERNAL;
    const auto fold = [&](const Result& ph) {
        r->retries += ph.retries;
        r->reforms += ph.reforms;
        r->desc_fallback_chunks += ph.desc_fallback_chunks;
        r->moved_bytes += ph.moved_bytes;
    };
    for (int attempt = 0;
         attempt < opts_.max_attempts && monotonic_time_us() < op_deadline;
         ++attempt) {
        // Phase 1: zone-sum over the fast intra-pod tier. Restarted
        // attempts begin from the ORIGINAL input again (a completed
        // phase with unchanged membership re-converges instantly
        // through the round's dedupe state).
        std::vector<uint32_t> zsum(words, words + nwords);
        Result ph1;
        err = ScopedAllReduce(SCOPE_ZONE, seq, zsum.data(), nwords, &ph1);
        fold(ph1);
        if (err != 0) {
            fiber_usleep(100 * 1000);
            continue;
        }
        const std::vector<uint64_t>& zone_keys = ph1.member_keys;
        const uint64_t my_key = zone_keys[ph1.my_rank];
        if (zone_keys.size() > kMaxHierKeys) {
            // Permanent topology bound (the phase-2/3 key header holds
            // kMaxHierKeys) — EVERY rank fails fast here; a leader-only
            // check would leave non-leaders spinning to op timeout.
            err = TERR_REQUEST;
            break;
        }

        // Phase 2 (zone leader only): exchange [zone keys | zone sum]
        // blocks with the other pods' leaders — the ONLY bytes that
        // cross the pod boundary.
        std::vector<uint32_t> p3(kHierHdrWords + nwords, 0);
        const bool is_leader = my_key == zone_keys.front();
        if (is_leader) {
            std::string block((kHierHdrWords + nwords) * 4, '\0');
            auto* bw = (uint32_t*)&block[0];
            PackHierKeys(bw, zone_keys);
            memcpy(bw + kHierHdrWords, zsum.data(), nwords * 4);
            std::string gathered;
            Result ph2;
            err = ScopedAllGather(SCOPE_LEADERS, seq, block,
                                  &gathered, &ph2);
            fold(ph2);
            if (err != 0) {
                fiber_usleep(100 * 1000);
                continue;
            }
            const size_t bwords = kHierHdrWords + nwords;
            const size_t nblocks = gathered.size() / (bwords * 4);
            std::vector<uint32_t> gsum(nwords, 0);
            std::set<uint64_t> contrib;
            bool bad = nblocks == 0;
            for (size_t b = 0; b < nblocks && !bad; ++b) {
                const auto* gw =
                    (const uint32_t*)(gathered.data() + b * bwords * 4);
                std::vector<uint64_t> keys;
                if (!UnpackHierKeys(gw, &keys)) {
                    bad = true;
                    break;
                }
                contrib.insert(keys.begin(), keys.end());
                for (size_t i = 0; i < nwords; ++i) {
                    gsum[i] += gw[kHierHdrWords + i];
                }
            }
            if (contrib.size() > kMaxHierKeys) {
                err = TERR_REQUEST;  // total membership past the bound
                break;               // — permanent, don't burn attempts
            }
            if (bad) {
                err = TERR_STALE_EPOCH;  // mid-exchange membership churn
                fiber_usleep(100 * 1000);
                continue;
            }
            // Broadcast payload: the contributing-key union + the
            // delta my zone still needs (wraparound-exact).
            std::vector<uint64_t> contrib_sorted(contrib.begin(),
                                                 contrib.end());
            PackHierKeys(p3.data(), contrib_sorted);
            for (size_t i = 0; i < nwords; ++i) {
                p3[kHierHdrWords + i] = gsum[i] - zsum[i];
            }
        }

        // Phase 3: pull-broadcast [contributing keys | delta] back
        // through the zone over the fast tier — no reduce, each
        // non-leader pulls exactly one payload's worth of bytes.
        Result ph3;
        err = ScopedBroadcast(SCOPE_ZONE_BCAST, seq, (char*)p3.data(),
                              p3.size() * 4, is_leader, &ph3);
        fold(ph3);
        if (err != 0) {
            fiber_usleep(100 * 1000);
            continue;
        }
        if (ph3.member_keys != ph1.member_keys) {
            // Zone membership moved between the phases: the delta was
            // computed against a different zone sum. Restart.
            err = TERR_STALE_EPOCH;
            r->reforms++;
            *g_reforms << 1;
            flight::Record(flight::kCollReform, seq,
                           (uint64_t)ph3.member_keys.size());
            continue;
        }
        std::vector<uint64_t> contrib;
        if (!UnpackHierKeys(p3.data(), &contrib)) {
            // Leader churn mid-phase-3 (no one contributed a header, or
            // two did): retriable.
            err = TERR_STALE_EPOCH;
            fiber_usleep(100 * 1000);
            continue;
        }
        std::sort(contrib.begin(), contrib.end());
        for (size_t i = 0; i < nwords; ++i) {
            words[i] = zsum[i] + p3[kHierHdrWords + i];
        }
        r->nranks = (uint32_t)contrib.size();
        r->my_rank = (uint32_t)(std::find(contrib.begin(), contrib.end(),
                                          my_key) -
                                contrib.begin());
        r->member_keys = std::move(contrib);
        err = 0;
        break;
    }
    r->error = err;
    r->elapsed_us = monotonic_time_us() - t0;
    if (err == 0) {
        *g_ops << 1;
        RecordBusbw(kAlgHierAllReduce, nwords * 4, r);
    }
    return err;
}

int CollectiveEngine::AllGather(uint64_t seq, const void* mine,
                                size_t my_bytes, std::string* out,
                                Result* r) {
    Result local;
    if (r == nullptr) r = &local;
    if (mine == nullptr || my_bytes == 0 || out == nullptr) {
        return r->error = TERR_REQUEST;
    }
    const int64_t t0 = monotonic_time_us();
    const std::string input((const char*)mine, my_bytes);
    const int err = ScopedAllGather(SCOPE_GLOBAL, seq, input, out, r);
    r->error = err;
    r->elapsed_us = monotonic_time_us() - t0;
    if (err == 0) {
        *g_ops << 1;
        RecordBusbw(COLL_ALLGATHER, out->size(), r);
    }
    return err;
}

int CollectiveEngine::AllToAll(
    uint64_t seq, const std::map<uint64_t, std::string>& blocks_by_key,
    size_t block_bytes, std::string* out, Result* r) {
    Result local;
    if (r == nullptr) r = &local;
    if (block_bytes == 0 || out == nullptr) {
        return r->error = TERR_REQUEST;
    }
    const int64_t t0 = monotonic_time_us();
    const int64_t op_deadline = t0 + opts_.op_timeout_ms * 1000;
    int err = TERR_INTERNAL;
    std::shared_ptr<Round> round;
    for (int attempt = 0;
         attempt < opts_.max_attempts && monotonic_time_us() < op_deadline;
         ++attempt) {
        std::vector<CollectiveMembership::Member> members;
        uint32_t my_rank = 0;
        uint64_t hash = 0;
        if (!ProbeMembers(SCOPE_GLOBAL, &members, &my_rank, &hash)) {
            err = TERR_INTERNAL;
            fiber_usleep(200 * 1000);
            continue;
        }
        // Outbound blocks in the (possibly re-formed) rank order; keyed
        // by member identity so survivors keep their intended payloads.
        std::string input;
        input.reserve(block_bytes * members.size());
        bool missing = false;
        for (const auto& m : members) {
            auto it = blocks_by_key.find(m.key);
            if (it == blocks_by_key.end() ||
                it->second.size() != block_bytes) {
                missing = true;
                break;
            }
            input.append(it->second);
        }
        if (missing) {
            err = TERR_REQUEST;
            break;
        }
        round = GetOrCreateRound(COLL_ALLTOALL, SCOPE_GLOBAL, seq,
                                 std::move(members),
                                 my_rank, hash, input, block_bytes, r);
        if (round == nullptr) {
            err = TERR_CLOSE;
            break;
        }
        const int64_t attempt_deadline = std::min(
            op_deadline,
            monotonic_time_us() + opts_.attempt_timeout_ms * 1000);
        err = RunFanoutAttempt(round, COLL_ALLTOALL, attempt_deadline, r);
        if (err == 0) break;
        fiber_usleep(100 * 1000);
    }
    uint64_t total = 0;
    if (err == 0 && round != nullptr) {
        FiberMutexGuard g(round->mu);
        out->assign(round->buf);
        total = round->total_bytes;
        r->nranks = round->nranks;
        r->my_rank = round->my_rank;
        r->member_keys.clear();
        for (const auto& m : round->members) {
            r->member_keys.push_back(m.key);
        }
    }
    FinishRound(round, err);
    r->error = err;
    r->elapsed_us = monotonic_time_us() - t0;
    if (err == 0) {
        *g_ops << 1;
        RecordBusbw(COLL_ALLTOALL, total, r);
    }
    return err;
}

int CollectiveEngine::SerialAllReduce(uint64_t seq, uint32_t* words,
                                      size_t nwords, Result* r) {
    Result local;
    if (r == nullptr) r = &local;
    if (words == nullptr || nwords == 0) {
        return r->error = TERR_REQUEST;
    }
    const int64_t t0 = monotonic_time_us();
    const int64_t op_deadline = t0 + opts_.op_timeout_ms * 1000;
    const std::string input((const char*)words, nwords * 4);
    int err = TERR_INTERNAL;
    std::shared_ptr<Round> round;
    for (int attempt = 0;
         attempt < opts_.max_attempts && monotonic_time_us() < op_deadline;
         ++attempt) {
        std::vector<CollectiveMembership::Member> members;
        uint32_t my_rank = 0;
        uint64_t hash = 0;
        if (!ProbeMembers(SCOPE_GLOBAL, &members, &my_rank, &hash)) {
            err = TERR_INTERNAL;
            fiber_usleep(200 * 1000);
            continue;
        }
        round = GetOrCreateRound(COLL_SERIAL_PUSH, SCOPE_GLOBAL, seq,
                                 std::move(members),
                                 my_rank, hash, input, input.size(), r);
        if (round == nullptr) {
            err = TERR_CLOSE;
            break;
        }
        const int64_t attempt_deadline = std::min(
            op_deadline,
            monotonic_time_us() + opts_.attempt_timeout_ms * 1000);
        err = RunSerialAttempt(round, attempt_deadline, r);
        if (err == 0) break;
        fiber_usleep(100 * 1000);
    }
    if (err == 0 && round != nullptr) {
        FiberMutexGuard g(round->mu);
        memcpy(words, round->buf.data(), nwords * 4);
        r->nranks = round->nranks;
        r->my_rank = round->my_rank;
        r->member_keys.clear();
        for (const auto& m : round->members) {
            r->member_keys.push_back(m.key);
        }
    }
    FinishRound(round, err);
    r->error = err;
    r->elapsed_us = monotonic_time_us() - t0;
    if (err == 0) {
        *g_ops << 1;
        RecordBusbw(COLL_SERIAL_PUSH, nwords * 4, r);
    }
    return err;
}

// ---------------- server side ----------------

int CollectiveEngine::HandleIncoming(const CollWire& w, const char* data,
                                     size_t len, IOBuf* reply,
                                     int64_t wait_budget_us,
                                     int64_t* backoff_ms, int* applied) {
    *applied = 0;
    *backoff_ms = 0;
    const uint32_t rkind = RoundFamily(w.kind);
    if (rkind == 0 || w.nranks < 2 || w.src_rank >= w.nranks ||
        w.scope > SCOPE_ZONE_BCAST) {
        return TERR_REQUEST;
    }
    // Record the mesh's round position even for chunks we can't serve
    // yet — a rejoining node fast-forwards its own driver from this.
    uint64_t prev = observed_seq_.load(std::memory_order_relaxed);
    while (w.seq > prev && !observed_seq_.compare_exchange_weak(
                               prev, w.seq, std::memory_order_relaxed)) {
    }
    // Park up to handler_wait_ms, bounded by the caller's remaining
    // budget; an expired budget (<= 0) means answer NOW — parking for
    // a caller that already timed out only amplifies the skew.
    int64_t wait_us = opts_.handler_wait_ms * 1000;
    if (wait_budget_us < wait_us) wait_us = wait_budget_us;
    if (wait_us < 0) wait_us = 0;
    const int64_t deadline_us = monotonic_time_us() + wait_us;
    const uint32_t family = FamilyOf(rkind, w.scope);
    const uint64_t key = RoundKey(family, w.seq);
    std::shared_ptr<Round> round;
    {
        FiberMutexGuard g(mu_);
        for (;;) {
            if (shutdown_) return TERR_CLOSE;
            auto it = rounds_.find(key);
            if (it != rounds_.end()) {
                round = it->second;
                break;
            }
            const auto done_it = completed_seq_.find(family);
            if (done_it != completed_seq_.end() &&
                w.seq <= done_it->second) {
                // Round completed and collected. Pushes are duplicates
                // of applied work; pulls can no longer be served (the
                // input is gone) — the straggler re-forms upstream.
                if (w.kind == COLL_ALLGATHER ||
                    w.kind == COLL_SERIAL_PULL) {
                    *backoff_ms = 20;
                    return TERR_OVERLOAD;
                }
                *applied = 2;
                return 0;
            }
            // We have not started this round yet: park briefly for our
            // driver, then push the skew back through the retry funnel.
            if (cv_.wait_until(mu_, deadline_us) == ETIMEDOUT) {
                *backoff_ms = 25;
                return TERR_OVERLOAD;
            }
        }
    }

    FiberMutexGuard g(round->mu);
    if (round->member_hash != w.member_hash ||
        round->nranks != w.nranks) {
        // Divergent membership views: retriable — both sides converge
        // on the survivor set through their own failure detection.
        return TERR_STALE_EPOCH;
    }
    if (round->total_bytes != w.total_bytes) {
        return TERR_REQUEST;
    }
    const uint64_t block =
        round->nranks != 0 ? round->total_bytes / round->nranks : 0;

    switch (w.kind) {
        case COLL_ALLREDUCE: {
            if (w.verb_nchunks > 0 && w.chunk == kVerbDoorbellChunk) {
                // Verbs doorbell (ISSUE 18): the step's shard bytes
                // were already REMOTE_WRITTEN into OUR granted window —
                // validate the window (lease + epoch fence, counted
                // stale rejects inside WindowPtr), crc the span, apply
                // it whole, and mark every chunk key the driver's
                // completion wait expects. Chunk size must agree with
                // ours or the key accounting diverges.
                const uint64_t cw =
                    std::max<uint64_t>(1, opts_.chunk_bytes / 4);
                if (w.offset % 4 != 0 || w.len % 4 != 0 || w.len == 0 ||
                    w.offset > round->total_bytes ||
                    w.len > round->total_bytes - w.offset ||
                    ChunksOf(w.len / 4, cw) != w.verb_nchunks) {
                    return TERR_REQUEST;
                }
                const uint64_t k0 = PackChunk(w.src_rank, w.step, 0);
                if (round->applied.count(k0) != 0) {
                    *applied = 2;
                    return 0;
                }
                char* p = nullptr;
                const int vrc = verbs::WindowPtr(
                    w.verb_window, 0, w.len, w.verb_epoch,
                    verbs::kWinWrite, &p);
                if (vrc != 0) return vrc;  // stale window: retriable
                if (crc32c_extend(0, p, (size_t)w.len) != w.verb_crc) {
                    return TERR_REQUEST;
                }
                char* dst = &round->buf[(size_t)w.offset];
                if (w.step + 1 < round->nranks) {
                    AddWordsWraparound(dst, p, (size_t)w.len);
                } else {
                    memcpy(dst, p, (size_t)w.len);
                }
                for (uint32_t c = 0; c < w.verb_nchunks; ++c) {
                    round->applied.insert(
                        PackChunk(w.src_rank, w.step, c));
                }
                round->cv.notify_all();
                *applied = 1;
                return 0;
            }
            if (w.offset % 4 != 0 || w.len % 4 != 0 ||
                w.offset > round->total_bytes ||
                w.len > round->total_bytes - w.offset || len != w.len) {
                return TERR_REQUEST;
            }
            const uint64_t k = PackChunk(w.src_rank, w.step, w.chunk);
            if (round->applied.count(k) != 0) {
                *applied = 2;
                return 0;
            }
            char* dst = &round->buf[(size_t)w.offset];
            if (w.step + 1 < round->nranks) {
                AddWordsWraparound(dst, data, (size_t)w.len);  // reduce
            } else {
                memcpy(dst, data, (size_t)w.len);  // all-gather phase
            }
            round->applied.insert(k);
            round->cv.notify_all();
            *applied = 1;
            return 0;
        }
        case COLL_ALLGATHER: {
            if (w.offset > round->input.size() ||
                w.len > round->input.size() - w.offset ||
                reply == nullptr) {
                return TERR_REQUEST;
            }
            const char* src = round->input.data() + (size_t)w.offset;
            if (!opts_.pool_descriptors ||
                !IciBlockPool::AllocatePoolAttachmentCopy(
                    src, (size_t)w.len, reply)) {
                reply->append(src, (size_t)w.len);
            }
            *applied = 1;
            return 0;
        }
        case COLL_ALLTOALL: {
            if (w.offset > block || w.len > block - w.offset ||
                len != w.len || w.src_rank == round->my_rank ||
                reply == nullptr) {
                return TERR_REQUEST;
            }
            const uint64_t k = PackChunk(w.src_rank, 0, w.chunk);
            if (round->applied.count(k) == 0) {
                memcpy(&round->buf[(size_t)(block * w.src_rank +
                                            w.offset)],
                       data, (size_t)w.len);
                round->applied.insert(k);
                round->cv.notify_all();
                *applied = 1;
            } else {
                *applied = 2;
            }
            // Reply with the reciprocal chunk of OUR block for the
            // caller — the response-direction descriptor of the pair
            // exchange.
            const char* src = round->input.data() +
                              (size_t)(block * w.src_rank + w.offset);
            if (!opts_.pool_descriptors ||
                !IciBlockPool::AllocatePoolAttachmentCopy(
                    src, (size_t)w.len, reply)) {
                reply->append(src, (size_t)w.len);
            }
            return 0;
        }
        case COLL_SERIAL_PUSH: {
            if (round->my_rank != 0 || w.len != round->total_bytes ||
                len != w.len) {
                return TERR_REQUEST;
            }
            const uint64_t k = PackChunk(w.src_rank, 0, 0);
            if (round->applied.count(k) != 0) {
                *applied = 2;
                return 0;
            }
            AddWordsWraparound(&round->buf[0], data, (size_t)len);
            round->applied.insert(k);
            round->cv.notify_all();
            *applied = 1;
            return 0;
        }
        case COLL_BCAST: {
            if (round->my_rank != 0 || reply == nullptr ||
                w.offset > round->total_bytes ||
                w.len > round->total_bytes - w.offset ||
                w.src_rank == 0) {
                return TERR_REQUEST;
            }
            // Servable from creation on the root (complete is set with
            // the payload); a racing pull that beat the local driver's
            // round creation parks above, never here.
            while (!round->complete) {
                if (round->fail_error != 0) return round->fail_error;
                if (round->cv.wait_until(round->mu, deadline_us) ==
                    ETIMEDOUT) {
                    *backoff_ms = 25;
                    return TERR_OVERLOAD;
                }
            }
            const char* src = round->buf.data() + (size_t)w.offset;
            if (!opts_.pool_descriptors ||
                !IciBlockPool::AllocatePoolAttachmentCopy(
                    src, (size_t)w.len, reply)) {
                reply->append(src, (size_t)w.len);
            }
            round->applied.insert(PackChunk(w.src_rank, 0, w.chunk));
            round->cv.notify_all();
            *applied = 1;
            return 0;
        }
        case COLL_SERIAL_PULL: {
            if (round->my_rank != 0 || reply == nullptr ||
                w.offset > round->total_bytes ||
                w.len > round->total_bytes - w.offset) {
                return TERR_REQUEST;
            }
            // The result is only servable once every push reduced in.
            while (!round->complete) {
                if (round->fail_error != 0) return round->fail_error;
                if (round->cv.wait_until(round->mu, deadline_us) ==
                    ETIMEDOUT) {
                    *backoff_ms = 25;
                    return TERR_OVERLOAD;
                }
            }
            // Serial baseline stays inline by design.
            reply->append(round->buf.data() + (size_t)w.offset,
                          (size_t)w.len);
            round->applied.insert(PackChunk(w.src_rank, 1, 0));
            round->cv.notify_all();
            *applied = 1;
            return 0;
        }
        default:
            return TERR_REQUEST;
    }
}

// ---------------- helpers ----------------

void CollectiveEngine::ExposeVars() {
    *g_ops << 0;
    *g_steps << 0;
    *g_retries << 0;
    *g_reforms << 0;
    *g_bytes << 0;
    *g_desc_fallbacks << 0;
    *g_verb_steps << 0;
    *g_verb_fallbacks << 0;
    BusbwFamily()->get_stats({"allreduce"});
    BusbwFamily()->get_stats({"allgather"});
    BusbwFamily()->get_stats({"alltoall"});
    BusbwFamily()->get_stats({"allreduce_serial"});
    BusbwFamily()->get_stats({"hier_allreduce"});
    BusbwFamily()->get_stats({"allreduce_verbs"});
}

void CollectiveEngine::FillDeterministic(uint64_t seq, uint64_t key,
                                         uint32_t* w, size_t n) {
    const uint32_t a = 0x9E3779B1u * (uint32_t)seq;
    const uint32_t b = 0x85EBCA77u * (uint32_t)key;
    for (size_t i = 0; i < n; ++i) {
        w[i] = a + b + 0xC2B2AE35u * (uint32_t)i;
    }
}

uint32_t CollectiveEngine::Checksum(const uint32_t* w, size_t n) {
    // Twin of brpc_tpu.parallel.collective_echo._adler_frame_checksum:
    // interleaved 16-bit halves, uint32 WRAPAROUND cumulative sum, then
    // the two mod-65521 reductions. The wraparound is part of the
    // definition — both sides must compute it identically.
    const uint32_t kMod = 65521;
    uint32_t s1 = 0;          // wrapping cumsum
    uint64_t b_acc = 0;       // sum of (s1 % kMod), reduced at the end
    for (size_t i = 0; i < n; ++i) {
        const uint32_t lo = w[i] & 0xFFFFu;
        const uint32_t hi = w[i] >> 16;
        s1 += lo;
        b_acc += s1 % kMod;
        s1 += hi;
        b_acc += s1 % kMod;
    }
    const uint32_t a = s1 % kMod;
    const uint32_t b = (uint32_t)(b_acc % kMod);
    return (b << 16) | a;
}

}  // namespace tpurpc
