// NamingServiceThread + LoadBalancerWithNaming: the glue between naming
// services and load balancers.
//
// Modeled on reference src/brpc/details/naming_service_thread.h:59 (one
// shared polling fiber per naming URL, fanning diffs out to watchers) and
// src/brpc/details/load_balancer_with_naming.* (the object a Channel holds
// when Init'ed with a naming URL + LB name).
#pragma once

#include <map>
#include <atomic>
#include <memory>
#include <mutex>
#include <set>
#include <string>
#include <vector>

#include "trpc/load_balancer.h"
#include "trpc/naming_service.h"
#include "trpc/outlier.h"

namespace tpurpc {

// One fiber per distinct naming URL, shared by every channel using it.
// Owns one Socket per resolved server (ref held, health-checked) and
// notifies watchers with add/remove diffs.
class NamingServiceThread {
public:
    class Watcher {
    public:
        virtual ~Watcher() = default;
        virtual void OnServersChanged(const std::vector<ServerNode>& added,
                                      const std::vector<SocketId>& removed) = 0;
    };

    ~NamingServiceThread();

    // Shared instance for `url` ("list://h1:p1,h2:p2"). Starts the polling
    // fiber on first use. Returns nullptr for unknown schemes.
    static std::shared_ptr<NamingServiceThread> GetOrCreate(
        const std::string& url);

    // Registers and immediately replays the current server set as "added".
    void AddWatcher(Watcher* w);
    void RemoveWatcher(Watcher* w);

    // Block (fiber-aware) until the first ResetServers arrived, up to
    // timeout. Returns 0 if servers are known.
    int WaitForFirstBatch(int64_t timeout_ms);

    // Current endpoint of a server id (diagnostics).
    std::string url() const { return url_; }

private:
    friend class NamingActions;
    NamingServiceThread(std::string url, NamingService* ns, std::string rest);
    static void* RunThunk(void* arg);

    // Diff a freshly-resolved list against current entries.
    void ResetServers(const std::vector<NSNode>& servers);

    const std::string url_;
    std::unique_ptr<NamingService> ns_;
    const std::string rest_;  // after scheme://

    std::mutex mu_;
    // Id only — no ref held: a held ref would block the health-check
    // fiber's sole-owner revive condition. Hc-enabled sockets can't recycle
    // until StopHealthCheck, so ids stay resolvable for removal.
    std::map<NSNode, SocketId> entries_;
    std::set<Watcher*> watchers_;
    void* first_batch_butex_ = nullptr;  // word flips 0->1 once
};

// What Channel::Init(naming_url, lb_name) creates: LB fed by a (shared)
// naming thread — through the deterministic-subsetting layer when
// -subset_size is on (ISSUE 8): the LB then holds only this client's
// rendezvous-hashed subset of the naming set, so a fleet of millions of
// clients doesn't full-mesh every server. The subset is stable under
// node churn (HRW scores are per-member), recomputed when draining
// marks or member death shrink the LIVE subset below -min_subset
// (never hammer the survivors), and falls back to the full set when a
// retry has already excluded every subset member or too few members
// are live at all.
class LoadBalancerWithNaming : public NamingServiceThread::Watcher {
public:
    ~LoadBalancerWithNaming() override;

    // Returns 0 on success (unknown scheme/LB name: -1).
    int Init(const std::string& naming_url, const std::string& lb_name);

    int SelectServer(const SelectIn& in, SelectOut* out);
    void Feedback(const LoadBalancer::CallInfo& info) {
        lb_->Feedback(info);
    }
    LoadBalancer* lb() const { return lb_.get(); }

    void OnServersChanged(const std::vector<ServerNode>& added,
                          const std::vector<SocketId>& removed) override;

    // Introspection for tests: ids currently fed to the LB policy.
    std::vector<SocketId> CurrentLbMembers() const;

private:
    // Cluster recovery gating (reference cluster_recover_policy.{h,cpp}
    // DefaultClusterRecoverPolicy): after ALL servers went down, servers
    // revive one by one — sending the whole cluster's load to the first
    // revived instance would knock it down again (circuit breaker) and
    // the cluster could flap forever. While "recovering", a request is
    // accepted with probability usable/min_working; recovery ends once
    // the usable count has been stable for the hold period.
    size_t CountUsableServers();
    bool RejectedByClusterRecovery();

    // ---- deterministic subsetting (ISSUE 8) ----
    // Recompute the desired member set (subset or full-set fallback)
    // and diff it into lb_. force_full pins the full set for this pass
    // (a retry excluded every subset member).
    void ApplySubset(bool force_full);
    // Cheap per-select health check, rate-limited: recomputes when the
    // live subset shrank below the floor.
    void MaybeRefreshSubset(const SelectIn& in);

    std::unique_ptr<LoadBalancer> lb_;
    // Typed view of lb_'s outermost (outlier) layer — owned by lb_.
    outlier::OutlierLoadBalancer* outlier_lb_ = nullptr;
    std::shared_ptr<NamingServiceThread> ns_thread_;
    std::mutex servers_mu_;
    std::vector<SocketId> server_ids_;  // mirror for usable counting

    mutable std::mutex subset_mu_;
    std::map<SocketId, ServerNode> all_nodes_;  // full naming set
    std::set<SocketId> in_lb_;                  // what lb_ holds now
    uint64_t subset_seed_ = 0;
    bool subset_full_ = true;  // lb_ currently holds the full set
    std::atomic<int64_t> last_subset_check_us_{0};
    std::atomic<bool> recovering_{false};
    std::mutex recover_mu_;
    size_t last_usable_ = 0;
    int64_t last_usable_change_us_ = 0;
};

}  // namespace tpurpc
