// Adaptive hedge-delay model (ISSUE 20 bugfix; extracted from
// tools/tpu_router.cc so the starvation path is unit-testable).
//
// The router hedges a forward when it outlives a per-(tenant,method)
// delay derived from an EWMA of the key's windowed p99. Clean un-hedged
// completions teach the EWMA; hedged completions are normally ignored —
// a hedge-truncated latency would drag the p99 down and make hedging
// self-amplifying.
//
// The bug that ignoring them unconditionally creates: when the backend
// slows past the current delay, EVERY forward gets hedged, no clean
// sample ever arrives, and the estimate is frozen at the stale (low)
// value — the router hedges 100% of traffic forever, doubling load on a
// mesh that is already slow. The fix is a RAISE-ONLY refresh: once the
// model has been starved of clean samples for kStarvedRefreshUs, a
// hedged completion's elapsed time (a lower bound on the un-hedged
// latency — the first try had at least that long and hadn't answered,
// or the answer took that long) may fold in, but only upward. The delay
// grows until calls complete un-hedged again, at which point the clean
// path resumes ownership.
#pragma once

#include <atomic>
#include <cstdint>

namespace tpurpc {

class HedgeDelayModel {
public:
    // No clean sample for this long => hedged completions may refresh.
    static constexpr int64_t kStarvedRefreshUs = 1000 * 1000;

    // Clean un-hedged completion: fold the caller's current windowed p99
    // into the EWMA (alpha 1/8) and reset the starvation clock.
    void FeedClean(int64_t windowed_p99_us, int64_t now_us) {
        last_clean_feed_us_.store(now_us, std::memory_order_relaxed);
        if (windowed_p99_us <= 0) return;
        const int64_t prev = ewma_p99_us_.load(std::memory_order_relaxed);
        ewma_p99_us_.store(
            prev == 0 ? windowed_p99_us : (prev * 7 + windowed_p99_us) / 8,
            std::memory_order_relaxed);
    }

    // Hedged completion: no-op unless the model is starved AND the
    // elapsed time would raise the estimate. Returns whether it taught.
    bool FeedHedged(int64_t elapsed_us, int64_t now_us) {
        if (elapsed_us <= 0) return false;
        const int64_t last =
            last_clean_feed_us_.load(std::memory_order_relaxed);
        if (last != 0 && now_us - last < kStarvedRefreshUs) return false;
        const int64_t prev = ewma_p99_us_.load(std::memory_order_relaxed);
        if (elapsed_us <= prev) return false;  // raise-only
        ewma_p99_us_.store(prev == 0 ? elapsed_us
                                     : (prev * 7 + elapsed_us) / 8,
                           std::memory_order_relaxed);
        starved_refreshes_.fetch_add(1, std::memory_order_relaxed);
        return true;
    }

    // The hedge delay: EWMA scaled by mult_pct, floored at floor_ms
    // (with no samples yet the floor alone drives — a cold caller hedges
    // only calls already slower than the floor).
    int64_t DelayMs(int mult_pct, int floor_ms) const {
        const int64_t derived_ms =
            ewma_p99_us_.load(std::memory_order_relaxed) * mult_pct / 100 /
            1000;
        return derived_ms > floor_ms ? derived_ms : (int64_t)floor_ms;
    }

    int64_t ewma_p99_us() const {
        return ewma_p99_us_.load(std::memory_order_relaxed);
    }
    int64_t starved_refreshes() const {
        return starved_refreshes_.load(std::memory_order_relaxed);
    }

private:
    std::atomic<int64_t> ewma_p99_us_{0};
    std::atomic<int64_t> last_clean_feed_us_{0};
    std::atomic<int64_t> starved_refreshes_{0};
};

}  // namespace tpurpc
