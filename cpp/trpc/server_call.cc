#include "trpc/server_call.h"

#include <cerrno>
#include <map>
#include <mutex>
#include <utility>
#include <vector>

#include "tbase/time.h"
#include "tfiber/fiber.h"
#include "tfiber/fiber_key.h"
#include "tfiber/timer_thread.h"
#include "tici/block_lease.h"
#include "tici/verbs.h"
#include "tvar/reducer.h"

namespace tpurpc {

namespace {

fiber_key_t g_current_call_key = INVALID_FIBER_KEY;
std::once_flag g_key_once;

void EnsureKey() {
    // No destructor: the scope object owns the value's lifetime; the
    // fiber-local slot only ever holds borrowed pointers.
    std::call_once(g_key_once,
                   [] { fiber_key_create(&g_current_call_key, nullptr); });
}

}  // namespace

Controller* CurrentServerCall() {
    EnsureKey();
    return (Controller*)fiber_getspecific(g_current_call_key);
}

ServerCallScope::ServerCallScope(Controller* cntl) {
    EnsureKey();
    prev_ = (Controller*)fiber_getspecific(g_current_call_key);
    fiber_setspecific(g_current_call_key, cntl);
}

ServerCallScope::~ServerCallScope() {
    fiber_setspecific(g_current_call_key, prev_);
}

namespace server_call {

namespace {

// (socket, wire key) -> server-call CallId. An std::map ordered by the
// pair gives CancelAllOnSocket a cheap per-socket range scan. One global
// mutex: every op is a few map touches with no user code under the lock
// (cancel delivery happens through id_error AFTER the lock drops).
std::mutex g_mu;
std::map<std::pair<SocketId, uint64_t>, CallId> g_calls;

static LazyAdder g_expired("rpc_server_expired_requests");
static LazyAdder g_shed("rpc_server_shed_requests");
static LazyAdder g_canceled("rpc_server_canceled_calls");

void* CancelAllFiber(void* arg) {
    CancelAllOnSocket((SocketId)(uintptr_t)arg);
    return nullptr;
}
void CancelAllTimerCb(void* arg) { CancelAllFiber(arg); }

}  // namespace

void Register(SocketId sid, uint64_t key, CallId scid) {
    std::lock_guard<std::mutex> g(g_mu);
    g_calls[{sid, key}] = scid;
}

void Unregister(SocketId sid, uint64_t key) {
    std::lock_guard<std::mutex> g(g_mu);
    g_calls.erase({sid, key});
}

void Cancel(SocketId sid, uint64_t key) {
    CallId scid = INVALID_CALL_ID;
    {
        std::lock_guard<std::mutex> g(g_mu);
        auto it = g_calls.find({sid, key});
        if (it == g_calls.end()) return;  // already finished: drop
        scid = it->second;
        // Leave the entry: the done closure owns its removal, and a
        // duplicate cancel is a stale-safe no-op on the id.
    }
    id_error(scid, ECANCELED);
}

void CancelAllOnSocket(SocketId sid) {
    std::vector<CallId> scids;
    {
        std::lock_guard<std::mutex> g(g_mu);
        auto it = g_calls.lower_bound({sid, 0});
        while (it != g_calls.end() && it->first.first == sid) {
            scids.push_back(it->second);
            it = g_calls.erase(it);
        }
    }
    for (CallId scid : scids) {
        id_error(scid, ECANCELED);
    }
}

void OnSocketFailed(SocketId sid) {
    // Peer-death pin reclamation (ISSUE 10a): every pool block pinned
    // for a descriptor posted ON this socket is released — the peer
    // that was entitled to read it can never read again, so holding
    // the slab would be a pure leak. (A retrying call whose lease
    // vanishes under it fails that try with TERR_STALE_EPOCH instead
    // of reading recycled bytes — see Controller::IssueRPC.) This runs
    // before the registered-call fast path below: CLIENT sockets carry
    // leases but never registered server calls.
    block_lease::ReleasePeer((uint64_t)sid);
    // Verb-plane reclamation (ISSUE 18): windows granted to this link
    // drop (their leases release exactly-once underneath) and pending
    // posts / grant waits against it fail TERR_FAILED_SOCKET — a
    // SIGKILLed peer mid-verb strands zero pins.
    verbs::OnPeerDead((uint64_t)sid);
    {
        // Fast path: most failed sockets (client conns, idle server
        // conns) have nothing registered — don't pay a fiber for them.
        std::lock_guard<std::mutex> g(g_mu);
        auto it = g_calls.lower_bound({sid, 0});
        if (it == g_calls.end() || it->first.first != sid) return;
    }
    fiber_t tid;
    if (fiber_start_background(&tid, nullptr, CancelAllFiber,
                               (void*)(uintptr_t)sid) != 0) {
        // NEVER inline: OnFailed may run under arbitrary locks and the
        // cascade runs user closures. The timer thread is lock-free
        // context; EndRPC already keeps user done closures off it.
        TimerThread::singleton()->schedule(CancelAllTimerCb,
                                           (void*)(uintptr_t)sid,
                                           monotonic_time_us());
    }
}

void CountExpired() { *g_expired << 1; }
void CountShed() { *g_shed << 1; }
void CountCanceled() { *g_canceled << 1; }

}  // namespace server_call

}  // namespace tpurpc
