#include "trpc/concurrency_limiter.h"

#include "tbase/fast_rand.h"
#include "tbase/time.h"

namespace tpurpc {

void AutoConcurrencyLimiter::OnResponded(int error_code, int64_t latency_us) {
    const int64_t now_us = monotonic_time_us();
    // Rate-limit sampling: one sample per sampling_interval (reference
    // AddSample checks _last_sampling_time_us the same way) so the hot
    // path is one atomic load + compare for most requests.
    int64_t last = last_sampling_time_us_.load(std::memory_order_relaxed);
    if (now_us - last < opt_.sampling_interval_us) {
        return;
    }
    if (!last_sampling_time_us_.compare_exchange_strong(
            last, now_us, std::memory_order_relaxed)) {
        return;  // another responder sampled this tick
    }

    std::lock_guard<std::mutex> g(sw_mu_);
    if (reset_latency_us_ > 0) {
        // Remeasure probe in progress: ignore responses admitted under the
        // old (higher) limit until they drain, then restart the estimate.
        if (now_us < reset_latency_us_) {
            return;
        }
        reset_latency_us_ = 0;
        min_latency_us_ = -1;
    }
    if (sw_.start_time_us == 0) {
        sw_.start_time_us = now_us;
    }
    if (error_code == 0) {
        ++sw_.succ_count;
        sw_.total_succ_us += latency_us;
    } else {
        ++sw_.failed_count;
        sw_.total_failed_us += latency_us;
    }
    const int32_t n = sw_.succ_count + sw_.failed_count;
    const int64_t elapsed = now_us - sw_.start_time_us;
    if (elapsed < opt_.sample_window_us && n < opt_.max_sample_count) {
        return;  // window still filling
    }
    if (n < opt_.min_sample_count) {
        // Sparse window (low-QPS service): too few samples to act on.
        // Updating here would read the tiny window's QPS as the service's
        // capacity and collapse the limit (reference resets and skips).
        ResetSampleWindow(now_us);
        return;
    }
    if (sw_.succ_count > 0) {
        UpdateMaxConcurrency(now_us);
    } else {
        // Every request in the window failed: halve.
        const int64_t cur = max_concurrency_.load(std::memory_order_relaxed);
        max_concurrency_.store(
            std::max(opt_.min_max_concurrency, cur / 2),
            std::memory_order_relaxed);
        nupdates_.fetch_add(1, std::memory_order_relaxed);
    }
    ResetSampleWindow(now_us);
}

void AutoConcurrencyLimiter::ResetSampleWindow(int64_t now_us) {
    sw_.start_time_us = now_us;
    sw_.succ_count = 0;
    sw_.failed_count = 0;
    sw_.total_failed_us = 0;
    sw_.total_succ_us = 0;
}

void AutoConcurrencyLimiter::UpdateMaxConcurrency(int64_t now_us) {
    const double failed_punish =
        (double)sw_.total_failed_us * opt_.fail_punish_ratio;
    const int64_t avg_latency = (int64_t)std::ceil(
        (failed_punish + (double)sw_.total_succ_us) / sw_.succ_count);
    const double qps = 1e6 * (sw_.succ_count + sw_.failed_count) /
                       (double)std::max<int64_t>(1, now_us - sw_.start_time_us);

    // EMA of the window-minimum latency: only lower observations move it
    // (and slowly), so transient congestion can't inflate the baseline.
    if (min_latency_us_ <= 0) {
        min_latency_us_ = avg_latency;
    } else if (avg_latency < min_latency_us_) {
        min_latency_us_ = (int64_t)(avg_latency * opt_.alpha_ema +
                                    min_latency_us_ * (1 - opt_.alpha_ema));
    }
    // EMA of peak throughput: jumps up instantly, decays slowly.
    if (qps >= ema_max_qps_) {
        ema_max_qps_ = qps;
    } else {
        const double f = opt_.alpha_ema / 10;
        ema_max_qps_ = qps * f + ema_max_qps_ * (1 - f);
    }

    if (remeasure_start_us_ == 0) {
        // First completed window: schedule the first probe one interval
        // out (jittered). Probing immediately would cut the limit and
        // discard the estimate that was just built.
        remeasure_start_us_ =
            now_us + opt_.remeasure_interval_us / 2 +
            (int64_t)(fast_rand() %
                      (uint64_t)(opt_.remeasure_interval_us / 2 + 1));
    }
    int64_t next;
    if (opt_.remeasure_interval_us > 1 && remeasure_start_us_ <= now_us) {
        // Periodic no-load remeasure: drop the limit, flag the drain
        // period, clear min_latency once drained.
        reset_latency_us_ = now_us + avg_latency * 2;
        remeasure_start_us_ =
            now_us + (opt_.remeasure_interval_us / 2 +
                      (int64_t)(fast_rand() %
                                (uint64_t)(opt_.remeasure_interval_us / 2)));
        next = (int64_t)std::ceil(ema_max_qps_ * min_latency_us_ / 1e6 *
                                  opt_.remeasure_reduce_ratio);
    } else {
        // Steady state: explore upward while latency stays near the
        // no-load baseline, back off as congestion shows up.
        if (avg_latency <=
                min_latency_us_ * (1.0 + opt_.min_explore_ratio) ||
            qps <= ema_max_qps_ / (1.0 + opt_.min_explore_ratio)) {
            explore_ratio_ = std::min(opt_.max_explore_ratio,
                                      explore_ratio_ +
                                          opt_.explore_change_step);
        } else {
            explore_ratio_ = std::max(opt_.min_explore_ratio,
                                      explore_ratio_ -
                                          opt_.explore_change_step);
        }
        next = (int64_t)(min_latency_us_ * ema_max_qps_ / 1e6 *
                         (1 + explore_ratio_));
    }
    max_concurrency_.store(std::max(opt_.min_max_concurrency, next),
                           std::memory_order_relaxed);
    nupdates_.fetch_add(1, std::memory_order_relaxed);
}

}  // namespace tpurpc
