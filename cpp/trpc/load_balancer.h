// LoadBalancer: pluggable server selection over a read-mostly server list.
//
// Modeled on reference src/brpc/load_balancer.h:35-77 (interface
// SelectServer/AddServer/RemoveServer/Feedback over DoublyBufferedData) and
// the policy set registered in src/brpc/global.cpp:384-392 (rr, wrr,
// random, wr, consistent-hash variants, locality-aware). Server identity is
// a SocketId whose validity survives failure: health check revives the same
// id (reference src/brpc/socket.h:469 HealthCheck + Revive), so lists don't
// churn on transient failures.
#pragma once

#include <atomic>
#include <cstdint>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

#include "tbase/endpoint.h"
#include "tnet/socket.h"

namespace tpurpc {

// Servers tried by earlier attempts of the same RPC, excluded on retry
// (reference src/brpc/excluded_servers.h — fixed small array, linear scan).
class ExcludedServers {
public:
    void Add(SocketId id) {
        if (count_ < kMax) ids_[count_++] = id;
    }
    bool IsExcluded(SocketId id) const {
        for (int i = 0; i < count_; ++i) {
            if (ids_[i] == id) return true;
        }
        return false;
    }
    int size() const { return count_; }

private:
    static constexpr int kMax = 8;
    SocketId ids_[kMax];
    int count_ = 0;
};

struct SelectIn {
    // Hash key for consistent-hashing policies (reference
    // Controller::set_request_code).
    uint64_t request_code = 0;
    bool has_request_code = false;
    const ExcludedServers* excluded = nullptr;  // may be null
};

struct SelectOut {
    // On success the chosen server with a held ref (guaranteed alive and
    // non-failed at selection time).
    SocketUniquePtr ptr;
    // At least one live-but-DRAINING server (peer announced a graceful
    // shutdown) was passed over to pick `ptr`. The controller annotates
    // the call's span ("server draining, re-routed") so reroutes are
    // visible in stitched traces.
    bool skipped_draining = false;
    // The pick SPILLED across the zone boundary (ISSUE 14): the local
    // zone had no usable replica (dead, fully draining+excluded, or
    // past -lb_zone_spill_dead_pct) and `ptr` is a cross-pod server.
    // Counted (rpc_lb_zone_spills) and span-annotated by the
    // controller.
    bool zone_spilled = false;
    // Outlier tier (ISSUE 20): at least one EJECTED backend was passed
    // over to pick `ptr` — a budget-free re-route like a draining skip.
    // `outlier_note` carries the first skipped backend's ejection
    // reason ("ejected: latency outlier 8.2x median") for the span.
    bool skipped_ejected = false;
    std::string outlier_note;
    // `ptr` is a reinstatement probe diverted to an ejected backend
    // whose window expired: one real rpc, deliberately routed there.
    bool outlier_probe = false;
};

// A server as registered by the naming layer: stable socket id + weight
// (from naming tags like "host:port w=10") + endpoint (captured at
// registration so consistent-hash ring keys never depend on transient
// socket liveness) + locality zone ("zone=A" tag; "" = zoneless).
struct ServerNode {
    SocketId id = INVALID_VREF_ID;
    int weight = 1;
    EndPoint ep;
    std::string zone;
};

class LoadBalancer {
public:
    virtual ~LoadBalancer() = default;

    virtual bool AddServer(const ServerNode& server) = 0;
    virtual bool RemoveServer(SocketId id) = 0;
    // Returns number added.
    virtual size_t AddServersInBatch(const std::vector<ServerNode>& servers) {
        size_t n = 0;
        for (const auto& s : servers) n += AddServer(s);
        return n;
    }
    virtual size_t RemoveServersInBatch(const std::vector<SocketId>& ids) {
        size_t n = 0;
        for (SocketId id : ids) n += RemoveServer(id);
        return n;
    }

    // Pick a live server. Returns 0 on success, ENODATA when the list is
    // empty, EHOSTDOWN when every candidate is failed/excluded.
    virtual int SelectServer(const SelectIn& in, SelectOut* out) = 0;

    // RPC completion feedback (latency in us; error_code 0 = success).
    // Only locality-aware uses it; default no-op.
    struct CallInfo {
        SocketId server_id = INVALID_VREF_ID;
        int64_t latency_us = 0;
        int error_code = 0;
    };
    virtual void Feedback(const CallInfo&) {}

    // A pick returned by SelectServer that will NOT be issued (the
    // zone layer selects from both sides of the pod boundary and keeps
    // one): policies holding select-time state (la's inflight count)
    // release it here — no RPC means no Feedback will ever arrive.
    virtual void DiscardPick(SocketId) {}

    // Describe current servers (diagnostics / builtin portal).
    virtual void Describe(std::string* out) const;

    virtual const char* name() const = 0;

    // Factory over the registered policy set ("rr", "wrr", "random",
    // "c_murmurhash", "c_md5"(alias to murmur ring w/ different seed),
    // "la"). Returns nullptr for unknown names. Every policy comes back
    // wrapped in the locality-zone layer (ZoneAwareLoadBalancer) — a
    // free passthrough until a ServerNode carries a zone different from
    // this process's -rpc_zone — and, outermost, in the outlier-
    // ejection layer (OutlierLoadBalancer, ISSUE 20) — one relaxed
    // load of passthrough while every backend is healthy.
    static LoadBalancer* New(const std::string& name);
};

// Locality-zone two-level pick (ISSUE 14): one instance of the SAME
// policy per side of the pod boundary — `local` holds same-zone (and
// zoneless) members, `remote` holds cross-pod ones — so every policy
// (rr/wrr/random/c-hash/la) is zone-aware without per-policy forks, and
// a breaker storm in one pod cannot isolate picks in the other (each
// side's candidates, exclusions and ring keys never mix).
//
// Fallback ordering (asserted by tlb ZoneAware* tests):
//   local-live > local-draining > remote-live > remote-draining/any
// with one exception: when at least -lb_zone_spill_dead_pct percent of
// the local zone's members are DEAD (unaddressable — a draining member
// still serves and counts as alive), remote-live is preferred over a
// degraded local pick (the whole-pod-outage / breaker-storm spill).
// Every cross-zone pick sets SelectOut::zone_spilled and bumps
// rpc_lb_zone_spills; local picks bump rpc_lb_zone_local_picks.
class ZoneAwareLoadBalancer : public LoadBalancer {
public:
    // Takes ownership of both policies (same concrete type).
    ZoneAwareLoadBalancer(LoadBalancer* local, LoadBalancer* remote);
    ~ZoneAwareLoadBalancer() override;

    bool AddServer(const ServerNode& server) override;
    bool RemoveServer(SocketId id) override;
    int SelectServer(const SelectIn& in, SelectOut* out) override;
    void Feedback(const CallInfo& info) override;
    void Describe(std::string* out) const override;
    const char* name() const override;

    // Introspection (tests/portal): members per side.
    size_t local_count() const;
    size_t remote_count() const;

private:
    bool LocalZoneMostlyDead() const;

    std::unique_ptr<LoadBalancer> local_;
    std::unique_ptr<LoadBalancer> remote_;
    mutable std::mutex mu_;
    // id -> is-local side (routes RemoveServer/Feedback) + the
    // local-side ids the dead-percent sweep walks.
    std::map<SocketId, bool> side_;
    // Mirrors of the side_ partition sizes: the hot SelectServer path
    // reads these WITHOUT the mutex — the common zoneless/passthrough
    // pick must stay as lock-free as the wrapped policy itself.
    std::atomic<size_t> nlocal_{0};
    std::atomic<size_t> nremote_{0};
};

// Register the rpc_lb_zone_* counters eagerly (idempotent) so /metrics
// and the lint see them 0-valued before the first pick.
void ExposeZoneLbVars();

// Common helper: try up to all candidates starting at `start`, skipping
// excluded and failed ids; holds the first addressable live one.
int SelectFromList(const std::vector<ServerNode>& list, size_t start,
                   const SelectIn& in, SelectOut* out);

}  // namespace tpurpc
