#include "trpc/compress.h"

#include <zlib.h>

#include <cstring>
#include <string>

#include "tbase/crc32c.h"
#include "tbase/logging.h"

namespace tpurpc {

namespace {

constexpr size_t kMaxDecompressed = 256u << 20;  // matches frame limit

// Both paths stream IOBuf blocks straight into zlib — no flattening copy
// of the (up to 256MB) payload on the RPC hot path.
bool GzipCompress(const IOBuf& in, IOBuf* out) {
    z_stream zs;
    memset(&zs, 0, sizeof(zs));
    // windowBits 15+16 = gzip wrapper (interoperable with `gzip`).
    if (deflateInit2(&zs, Z_DEFAULT_COMPRESSION, Z_DEFLATED, 15 + 16, 8,
                     Z_DEFAULT_STRATEGY) != Z_OK) {
        return false;
    }
    char buf[16 * 1024];
    const size_t nblocks = in.backing_block_num();
    for (size_t i = 0; i <= nblocks; ++i) {
        size_t len = 0;
        const char* data = i < nblocks ? in.backing_block_data(i, &len)
                                       : nullptr;
        zs.next_in = (Bytef*)data;
        zs.avail_in = (uInt)len;
        const int flush = i == nblocks ? Z_FINISH : Z_NO_FLUSH;
        int rc;
        do {
            zs.next_out = (Bytef*)buf;
            zs.avail_out = sizeof(buf);
            rc = deflate(&zs, flush);
            if (rc == Z_STREAM_ERROR) {
                deflateEnd(&zs);
                return false;
            }
            out->append(buf, sizeof(buf) - zs.avail_out);
        } while (zs.avail_in > 0 ||
                 (flush == Z_FINISH && rc != Z_STREAM_END));
    }
    deflateEnd(&zs);
    return true;
}

bool GzipDecompress(const IOBuf& in, IOBuf* out) {
    z_stream zs;
    memset(&zs, 0, sizeof(zs));
    if (inflateInit2(&zs, 15 + 16) != Z_OK) return false;
    char buf[16 * 1024];
    size_t total = 0;
    int rc = Z_OK;
    const size_t nblocks = in.backing_block_num();
    for (size_t i = 0; i < nblocks && rc != Z_STREAM_END; ++i) {
        size_t len = 0;
        const char* data = in.backing_block_data(i, &len);
        zs.next_in = (Bytef*)data;
        zs.avail_in = (uInt)len;
        do {
            zs.next_out = (Bytef*)buf;
            zs.avail_out = sizeof(buf);
            rc = inflate(&zs, Z_NO_FLUSH);
            if (rc != Z_OK && rc != Z_STREAM_END) {
                inflateEnd(&zs);
                return false;  // corrupt stream
            }
            const size_t produced = sizeof(buf) - zs.avail_out;
            total += produced;
            if (total > kMaxDecompressed) {  // zip bomb guard
                inflateEnd(&zs);
                return false;
            }
            out->append(buf, produced);
        } while (zs.avail_in > 0 && rc != Z_STREAM_END);
    }
    inflateEnd(&zs);
    return rc == Z_STREAM_END;
}

}  // namespace

uint32_t crc32c_iobuf(uint32_t crc, const IOBuf& buf) {
    for (size_t i = 0; i < buf.backing_block_num(); ++i) {
        size_t len = 0;
        const char* data = buf.backing_block_data(i, &len);
        crc = crc32c_extend(crc, data, len);
    }
    return crc;
}

bool CompressBody(int compress_type, const IOBuf& in, IOBuf* out) {
    switch (compress_type) {
        case COMPRESS_NONE:
            out->append(in);
            return true;
        case COMPRESS_GZIP:
            return GzipCompress(in, out);
        default:
            LOG(ERROR) << "unknown compress_type " << compress_type;
            return false;
    }
}

bool DecompressBody(int compress_type, const IOBuf& in, IOBuf* out) {
    switch (compress_type) {
        case COMPRESS_NONE:
            out->append(in);
            return true;
        case COMPRESS_GZIP:
            return GzipDecompress(in, out);
        default:
            LOG(ERROR) << "unknown compress_type " << compress_type;
            return false;
    }
}

}  // namespace tpurpc
