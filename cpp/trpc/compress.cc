#include "trpc/compress.h"

#include <dlfcn.h>
#include <zlib.h>

#include <cstring>
#include <string>

#include "tbase/crc32c.h"
#include "tbase/logging.h"

namespace tpurpc {

namespace {

constexpr size_t kMaxDecompressed = 256u << 20;  // matches frame limit

// Both paths stream IOBuf blocks straight into zlib — no flattening copy
// of the (up to 256MB) payload on the RPC hot path.
bool GzipCompress(const IOBuf& in, IOBuf* out) {
    z_stream zs;
    memset(&zs, 0, sizeof(zs));
    // windowBits 15+16 = gzip wrapper (interoperable with `gzip`).
    if (deflateInit2(&zs, Z_DEFAULT_COMPRESSION, Z_DEFLATED, 15 + 16, 8,
                     Z_DEFAULT_STRATEGY) != Z_OK) {
        return false;
    }
    char buf[16 * 1024];
    const size_t nblocks = in.backing_block_num();
    for (size_t i = 0; i <= nblocks; ++i) {
        size_t len = 0;
        const char* data = i < nblocks ? in.backing_block_data(i, &len)
                                       : nullptr;
        zs.next_in = (Bytef*)data;
        zs.avail_in = (uInt)len;
        const int flush = i == nblocks ? Z_FINISH : Z_NO_FLUSH;
        int rc;
        do {
            zs.next_out = (Bytef*)buf;
            zs.avail_out = sizeof(buf);
            rc = deflate(&zs, flush);
            if (rc == Z_STREAM_ERROR) {
                deflateEnd(&zs);
                return false;
            }
            out->append(buf, sizeof(buf) - zs.avail_out);
        } while (zs.avail_in > 0 ||
                 (flush == Z_FINISH && rc != Z_STREAM_END));
    }
    deflateEnd(&zs);
    return true;
}

bool GzipDecompress(const IOBuf& in, IOBuf* out) {
    z_stream zs;
    memset(&zs, 0, sizeof(zs));
    if (inflateInit2(&zs, 15 + 16) != Z_OK) return false;
    char buf[16 * 1024];
    size_t total = 0;
    int rc = Z_OK;
    const size_t nblocks = in.backing_block_num();
    for (size_t i = 0; i < nblocks && rc != Z_STREAM_END; ++i) {
        size_t len = 0;
        const char* data = in.backing_block_data(i, &len);
        zs.next_in = (Bytef*)data;
        zs.avail_in = (uInt)len;
        do {
            zs.next_out = (Bytef*)buf;
            zs.avail_out = sizeof(buf);
            rc = inflate(&zs, Z_NO_FLUSH);
            if (rc != Z_OK && rc != Z_STREAM_END) {
                inflateEnd(&zs);
                return false;  // corrupt stream
            }
            const size_t produced = sizeof(buf) - zs.avail_out;
            total += produced;
            if (total > kMaxDecompressed) {  // zip bomb guard
                inflateEnd(&zs);
                return false;
            }
            out->append(buf, produced);
        } while (zs.avail_in > 0 && rc != Z_STREAM_END);
    }
    inflateEnd(&zs);
    return rc == Z_STREAM_END;
}

}  // namespace

uint32_t crc32c_iobuf(uint32_t crc, const IOBuf& buf) {
    for (size_t i = 0; i < buf.backing_block_num(); ++i) {
        size_t len = 0;
        const char* data = buf.backing_block_data(i, &len);
        crc = crc32c_extend(crc, data, len);
    }
    return crc;
}

// ---- snappy via dlopen (reference policy/snappy_compress.cpp) ----
// The image ships libsnappy.so.1 but not its headers; the snappy-c ABI
// (4 functions, plain C) is declared here and resolved at runtime. When
// the library is absent, snappy compression fails cleanly.

struct SnappyApi {
    // snappy_status: 0 ok, 1 invalid input, 2 buffer too small.
    int (*compress)(const char* input, size_t input_len, char* out,
                    size_t* out_len);
    int (*uncompress)(const char* in, size_t in_len, char* out,
                      size_t* out_len);
    size_t (*max_compressed_length)(size_t source_len);
    int (*uncompressed_length)(const char* in, size_t in_len,
                               size_t* result);
};

const SnappyApi* snappy_api() {
    static const SnappyApi* api = []() -> const SnappyApi* {
        void* h = dlopen("libsnappy.so.1", RTLD_NOW);
        if (h == nullptr) h = dlopen("libsnappy.so", RTLD_NOW);
        if (h == nullptr) return nullptr;
        auto* a = new SnappyApi;
        a->compress = (decltype(a->compress))dlsym(h, "snappy_compress");
        a->uncompress =
            (decltype(a->uncompress))dlsym(h, "snappy_uncompress");
        a->max_compressed_length = (decltype(a->max_compressed_length))dlsym(
            h, "snappy_max_compressed_length");
        a->uncompressed_length = (decltype(a->uncompressed_length))dlsym(
            h, "snappy_uncompressed_length");
        if (a->compress == nullptr || a->uncompress == nullptr ||
            a->max_compressed_length == nullptr ||
            a->uncompressed_length == nullptr) {
            dlclose(h);
            delete a;
            return nullptr;
        }
        return a;
    }();
    return api;
}

// snappy-c wants contiguous buffers (no streaming interface): flatten.
bool SnappyCompress(const IOBuf& in, IOBuf* out) {
    const SnappyApi* a = snappy_api();
    if (a == nullptr) {
        LOG(ERROR) << "snappy requested but libsnappy is not available";
        return false;
    }
    const std::string flat = in.to_string();
    std::string buf;
    size_t out_len = a->max_compressed_length(flat.size());
    buf.resize(out_len);
    if (a->compress(flat.data(), flat.size(), &buf[0], &out_len) != 0) {
        return false;
    }
    out->append(buf.data(), out_len);
    return true;
}

bool SnappyDecompress(const IOBuf& in, IOBuf* out) {
    const SnappyApi* a = snappy_api();
    if (a == nullptr) return false;
    const std::string flat = in.to_string();
    size_t out_len = 0;
    if (a->uncompressed_length(flat.data(), flat.size(), &out_len) != 0 ||
        out_len > kMaxDecompressed) {
        return false;  // corrupt or bomb
    }
    std::string buf;
    buf.resize(out_len);
    if (a->uncompress(flat.data(), flat.size(), &buf[0], &out_len) != 0) {
        return false;
    }
    out->append(buf.data(), out_len);
    return true;
}

bool SnappyAvailable() { return snappy_api() != nullptr; }

bool CompressBody(int compress_type, const IOBuf& in, IOBuf* out) {
    switch (compress_type) {
        case COMPRESS_NONE:
            out->append(in);
            return true;
        case COMPRESS_GZIP:
            return GzipCompress(in, out);
        case COMPRESS_SNAPPY:
            return SnappyCompress(in, out);
        default:
            LOG(ERROR) << "unknown compress_type " << compress_type;
            return false;
    }
}

bool DecompressBody(int compress_type, const IOBuf& in, IOBuf* out) {
    switch (compress_type) {
        case COMPRESS_NONE:
            out->append(in);
            return true;
        case COMPRESS_GZIP:
            return GzipDecompress(in, out);
        case COMPRESS_SNAPPY:
            return SnappyDecompress(in, out);
        default:
            LOG(ERROR) << "unknown compress_type " << compress_type;
            return false;
    }
}

}  // namespace tpurpc
