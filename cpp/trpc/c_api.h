// C ABI surface of the framework: the pieces the Python/JAX side drives
// directly (ctypes over libtpurpc.so), so the multi-chip dryrun and the
// device-path benchmark exercise the FRAMEWORK's bytes — real tpu_std
// framing (policy_tpu_std.cc), real crc32c (tbase/crc32c.cc), staging
// buffers from the registered-memory ICI block pool (tici/block_pool.cc)
// — instead of a Python re-implementation.
//
// Reference parity: this plays the role the RDMA-registered IOBuf
// allocator plays in /root/reference/src/brpc/rdma/block_pool.h — the
// transport pool hands out the memory payloads are framed into, and the
// device DMA (jax.device_put on this side, ibv_post_send there) reads
// straight from it.
#pragma once

#include <stddef.h>
#include <stdint.h>

extern "C" {

// One-time framework init (protocol registry + ICI block pool). Returns 0.
int tpurpc_global_init();

// The framework's crc32c (slice-by-8, RFC 3720 polynomial).
uint32_t tpurpc_crc32c(uint32_t init, const void* data, size_t n);

// Registered-memory staging buffers from the ICI block pool. Allocation
// routes through the slab-class allocator (recyclable; ISSUE 9c) for
// class-sized requests and falls back to carve-only registered chunks
// above the largest class.
void* tpurpc_block_alloc(size_t n);
void tpurpc_block_free(void* p);
// 1 if p lies inside the registered region (diagnostic for tests).
int tpurpc_block_is_registered(const void* p);

// Slab-class allocator stats (zero-copy / recycle proof for tests).
long tpurpc_slab_allocated();
long tpurpc_slab_recycled();
// Identity of this process's shared pool (the pool_id of one-sided
// descriptors); 0 when the pool is anonymous.
uint64_t tpurpc_pool_id();

// ---- device staging ring (ISSUE 9a) ----
// A depth-N ring of registered staging slots for the pipelined device
// data path (see tici/block_pool.h DeviceStagingRing). Acquire hands
// out slots in FIFO order, blocking up to timeout_us (<0 = forever)
// while all slots are in flight; Complete releases them (out-of-order
// completes are held until the predecessors finish).
// Acquire returns the slot index, -1 on timeout, -2 once the ring is
// aborted (poisoned): a device-stream error must unblock parked Python
// threads instead of wedging them forever (ISSUE 10c).
void* tpurpc_ring_create(uint32_t depth, size_t slot_bytes);
void tpurpc_ring_destroy(void* ring);
int tpurpc_ring_acquire(void* ring, long timeout_us);
int tpurpc_ring_complete(void* ring, uint32_t slot);
// Poison the ring: every parked and future acquire returns -2.
void tpurpc_ring_abort(void* ring);
int tpurpc_ring_aborted(void* ring);
void* tpurpc_ring_slot(void* ring, uint32_t slot);
size_t tpurpc_ring_slot_bytes(void* ring);
uint32_t tpurpc_ring_depth(void* ring);
int tpurpc_ring_registered(void* ring);
uint64_t tpurpc_ring_inflight_highwater(void* ring);

// ---- block leases (ISSUE 10a) ----
// Crash-safety counters of the pinned-block lease registry
// (tici/block_lease.h): live pins, expiry-reaped pins, and the local
// pool's current epoch — the leak/staleness evidence the device-ring
// tests and bench.py record.
uint64_t tpurpc_lease_pinned();
uint64_t tpurpc_lease_reaped();
uint64_t tpurpc_pool_epoch();

// ---- one-sided verbs (ISSUE 18) ----
// Counters of the verb plane (tici/verbs.h): posted/completed verbs,
// bytes moved by REMOTE_READ/REMOTE_WRITE, stale-epoch rejects, and CQ
// parks — plus the live window / pending-post gauges the soak uses as
// leak evidence (a healthy run ends with both at 0).
long tpurpc_verbs_posted();
long tpurpc_verbs_completed();
long tpurpc_verbs_bytes();
long tpurpc_verbs_stale_rejects();
long tpurpc_verbs_cq_parks();
long tpurpc_verbs_windows();
long tpurpc_verbs_pending();

// ---- transport tier registry (ISSUE 12) ----
// Introspection of the first-class Transport seam (tnet/transport.h):
// how many endpoint types are registered, their names, and their
// capabilities — so the Python side can assert the uniform tier story
// (tcp/ici/shm_xproc/device) without parsing a portal page.
int tpurpc_transport_tier_count();
// Copies the tier's name into out[0..cap) (NUL-terminated, truncated to
// cap-1). Returns the name length, or -1 for a bad tier id.
long tpurpc_transport_tier_name(int tier, char* out, size_t cap);
// 1/0 capability bits; -1 for a bad tier id.
int tpurpc_transport_tier_descriptor_capable(int tier);
int tpurpc_transport_tier_zero_copy(int tier);
int tpurpc_transport_tier_cross_process(int tier);
// One-sided verb plane (ISSUE 18): does the tier take REMOTE_READ /
// REMOTE_WRITE against leased pool windows, and how many scatter-gather
// entries fit in one verb (0 = one-sided-incapable).
int tpurpc_transport_tier_one_sided(int tier);
long tpurpc_transport_tier_sgl_max(int tier);
// Per-tier attribution counters (ops for the device tier's staging-ring
// completes; bytes for socket-attached tiers).
long tpurpc_transport_tier_ops(int tier);

// Frame `payload` as one tpu_std frame: "TRPC" header + RpcMeta
// {correlation_id, body_checksum=crc32c(payload)} + payload as raw
// attachment. Writes into out[0..out_cap). Returns the frame size in
// bytes, or -1 if out_cap is too small. When `payload` ALREADY sits at
// the frame's attachment position inside `out` (exact aliasing), the
// payload memcpy is skipped — header + meta write + crc only.
long tpurpc_frame(uint64_t correlation_id, const void* payload, size_t n,
                  void* out, size_t out_cap);

// In-place framing for pool-resident payloads (ISSUE 9 satellite): the
// payload ALREADY lives at buf[payload_off .. payload_off+payload_len);
// the header + meta are written right-justified immediately before it,
// so the finished frame occupies buf[*frame_off .. payload_off+
// payload_len) with NO payload copy. Requires payload_off >= the
// header+meta size (~64 bytes is always enough). Returns the frame
// length, sets *frame_off, and (when non-null) *crc_out = the crc32c
// embedded in the meta — so callers can verify round-tripped payload
// bytes without re-parsing the frame. Returns -1 when the prefix space
// is too small.
long tpurpc_frame_in_place(uint64_t correlation_id, void* buf,
                           size_t payload_off, size_t payload_len,
                           size_t* frame_off, uint32_t* crc_out);

// Parse ONE frame at buf[0..n): verifies the header, meta, and
// body_checksum. On success returns bytes consumed and sets *cid,
// *payload_off, *payload_len (payload bytes live at buf+*payload_off).
// Returns -1 if more bytes are needed, -2 if the frame is corrupt
// (bad magic/bounds/meta/checksum).
long tpurpc_unframe(const void* buf, size_t n, uint64_t* cid,
                    size_t* payload_off, size_t* payload_len);

}  // extern "C"
