// C ABI surface of the framework: the pieces the Python/JAX side drives
// directly (ctypes over libtpurpc.so), so the multi-chip dryrun and the
// device-path benchmark exercise the FRAMEWORK's bytes — real tpu_std
// framing (policy_tpu_std.cc), real crc32c (tbase/crc32c.cc), staging
// buffers from the registered-memory ICI block pool (tici/block_pool.cc)
// — instead of a Python re-implementation.
//
// Reference parity: this plays the role the RDMA-registered IOBuf
// allocator plays in /root/reference/src/brpc/rdma/block_pool.h — the
// transport pool hands out the memory payloads are framed into, and the
// device DMA (jax.device_put on this side, ibv_post_send there) reads
// straight from it.
#pragma once

#include <stddef.h>
#include <stdint.h>

extern "C" {

// One-time framework init (protocol registry + ICI block pool). Returns 0.
int tpurpc_global_init();

// The framework's crc32c (slice-by-8, RFC 3720 polynomial).
uint32_t tpurpc_crc32c(uint32_t init, const void* data, size_t n);

// Registered-memory staging buffers from the ICI block pool.
void* tpurpc_block_alloc(size_t n);
void tpurpc_block_free(void* p);
// 1 if p lies inside the registered region (diagnostic for tests).
int tpurpc_block_is_registered(const void* p);

// Frame `payload` as one tpu_std frame: "TRPC" header + RpcMeta
// {correlation_id, body_checksum=crc32c(payload)} + payload as raw
// attachment. Writes into out[0..out_cap). Returns the frame size in
// bytes, or -1 if out_cap is too small.
long tpurpc_frame(uint64_t correlation_id, const void* payload, size_t n,
                  void* out, size_t out_cap);

// Parse ONE frame at buf[0..n): verifies the header, meta, and
// body_checksum. On success returns bytes consumed and sets *cid,
// *payload_off, *payload_len (payload bytes live at buf+*payload_off).
// Returns -1 if more bytes are needed, -2 if the frame is corrupt
// (bad magic/bounds/meta/checksum).
long tpurpc_unframe(const void* buf, size_t n, uint64_t* cid,
                    size_t* payload_off, size_t* payload_len);

}  // extern "C"
