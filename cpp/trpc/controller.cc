#include "trpc/auth.h"
#include "trpc/controller.h"

#include <google/protobuf/descriptor.h>

#include <algorithm>
#include <cstdarg>
#include <cstdint>

#include "tvar/reducer.h"

#include "rpc_meta.pb.h"
#include "tbase/errno.h"
#include "thttp/http2_client.h"
#include "tbase/flags.h"
#include "tbase/flight_recorder.h"
#include "tbase/logging.h"
#include "tbase/time.h"
#include "tfiber/fiber.h"
#include "tnet/socket_map.h"
#include "trpc/channel.h"
#include "trpc/lb_with_naming.h"
#include "tici/block_lease.h"
#include "tici/block_pool.h"
#include "tnet/fault_injection.h"
#include "trpc/pb_compat.h"
#include "trpc/retry_policy.h"
#include "trpc/policy_tpu_std.h"
#include "tbase/crc32c.h"
#include "trpc/compress.h"
#include "trpc/span.h"
#include "trpc/stream.h"

DEFINE_bool(rpc_checksum, false,
            "crc32c-protect tpu_std frame bodies (verified when present)");
DECLARE_bool(chaos_enabled);
DECLARE_string(rpc_zone);

#include "trpc/server_call.h"

namespace tpurpc {

// Client-side re-issue observability: the chaos soak bounds total
// re-issues (retries + backups) against the configured retry budget.
static LazyAdder g_client_retries("rpc_client_retries");
static LazyAdder g_client_backups("rpc_client_backup_requests");
static LazyAdder g_budget_exhausted("rpc_retry_budget_exhausted");
// Drain steering: new calls routed around a draining server (LB skip),
// and re-issues of calls a draining server provably never processed.
// Both are budget-free — the rolling-restart soak asserts zero retry
// tokens spent across a full mesh restart.
static LazyAdder g_drain_reroutes("rpc_client_drain_reroutes");

// Shared with the combo-channel retry loops (controller.h client_stats):
// one process-wide adder per name, whoever drives the re-issue.
namespace client_stats {
void CountRetry() { *g_client_retries << 1; }
void CountBudgetExhausted() { *g_budget_exhausted << 1; }
}  // namespace client_stats
// One-sided descriptor sends (ISSUE 9): calls whose attachment crossed
// the wire as a (pool_id, offset, len, crc) reference — and the logical
// bytes that never entered the frame/copy path because of it.
static LazyAdder g_pool_desc_sends("rpc_pool_descriptor_sends");
static LazyAdder g_pool_desc_bytes("rpc_pool_descriptor_send_bytes");
// Ineligible set_request_pool_attachment calls folded back to the
// inline path (multi-block or non-shared memory).
static LazyAdder g_pool_desc_fallbacks("rpc_pool_descriptor_fallbacks");
// Leases released by EndRPC that were ALREADY reclaimed underneath the
// call (expiry reaper / peer death): the stale-descriptor signature.
static LazyAdder g_pool_lease_gone("rpc_pool_lease_already_reclaimed");
// Tries whose pinned request attachment went INLINE because the try's
// transport tier cannot carry a descriptor (plain TCP pick by the LB):
// same payload on the wire, copied — eligibility decided at the
// Transport seam instead of failing on the server (ISSUE 12).
static LazyAdder g_pool_desc_wire_fallbacks(
    "rpc_pool_descriptor_wire_fallbacks");

void Controller::set_request_pool_attachment(IOBuf&& buf) {
    // A second call replaces the first attachment: release the prior
    // lease or its pin would be orphaned for good (overwriting the id
    // alone leaks the slab slot).
    ReleasePoolLease();
    // Eligibility is decided HERE, once, not per retry: the bytes must
    // be one contiguous block ref inside the shared registered pool so
    // a single (offset, len) names them all. Anything else falls back
    // to the inline attachment — same payload on the wire, just copied.
    uint64_t off = 0;
    size_t flen = 0;
    const char* data =
        buf.backing_block_num() == 1 ? buf.backing_block_data(0, &flen)
                                     : nullptr;
    if (data != nullptr && flen == buf.size() &&
        IciBlockPool::OffsetOf(data, &off) &&
        IciBlockPool::pool_id() != 0) {
        // Stash the resolved descriptor (crc computed ONCE — retries
        // re-send the same reference without re-reading the bytes) and
        // hand the pin to the lease registry: from here the block's
        // lifetime is crash-safe (exactly-once release, expiry reaper,
        // peer-death reclamation) instead of riding this controller.
        pool_attachment_.data = data;
        pool_attachment_.length = flen;
        pool_attachment_.pool_id = IciBlockPool::pool_id();
        pool_attachment_.offset = off;
        pool_attachment_.crc32c = crc32c_extend(0, data, flen);
        pool_attachment_.pool_epoch = IciBlockPool::pool_epoch();
        pool_lease_id_ = block_lease::Pin(std::move(buf));
        return;
    }
    *g_pool_desc_fallbacks << 1;
    request_attachment_.append(std::move(buf));
}

// One-sided completion (ISSUE 10a): release the pinned block back to
// the owner's pool — the descriptor analog of the shm ring's released_-
// counter advance. Exactly-once across every termination path (EndRPC,
// Reset-for-reuse, destruction, retry/backup re-issues): the lease
// registry arbitrates, so a pin the reaper or peer-death path already
// reclaimed is a counted no-op here, never a double free. The chaos
// leak simulation (chaos_pool pool_leak) "forgets" this release so the
// soak can prove the reaper reclaims orphaned pins.
void Controller::ReleasePoolLease() {
    if (pool_lease_id_ == 0) return;
    const uint64_t id = pool_lease_id_;
    pool_lease_id_ = 0;
    if (__builtin_expect(fault_injection_enabled(), 0)) {
        const FaultAction fault = FaultInjection::Decide(
            FaultOp::kLeaseRelease, remote_side_, 0);
        if (fault.kind == FaultAction::kDrop) {
            return;  // leaked on purpose: the reaper must reclaim it
        }
    }
    if (!block_lease::Release(id)) {
        *g_pool_lease_gone << 1;
    }
}

// Response-direction twin of set_request_pool_attachment (ISSUE 12):
// the handler answers with a pool-block reference. Eligibility adds one
// check the request side decides at IssueRPC time instead — the CALL's
// connection must ride a descriptor-capable transport tier (the client
// either mapped our pool at handshake or is this process); on an
// ineligible shape or tier the bytes fall back to the inline response
// attachment, so handlers never need to know the transport.
void Controller::set_response_pool_attachment(IOBuf&& buf) {
    // Replacing a prior response attachment releases its pin first.
    if (rsp_pool_lease_id_ != 0) {
        block_lease::Release(rsp_pool_lease_id_);
        rsp_pool_lease_id_ = 0;
        rsp_pool_stash_ = PoolAttachment();
    }
    uint64_t off = 0;
    size_t flen = 0;
    const char* data =
        buf.backing_block_num() == 1 ? buf.backing_block_data(0, &flen)
                                     : nullptr;
    bool tier_ok = false;
    if (server_socket_ != INVALID_VREF_ID) {
        SocketUniquePtr s;
        if (Socket::AddressSocket(server_socket_, &s) == 0) {
            tier_ok = TransportDescriptorCapable(s.get());
        }
    }
    if (tier_ok && data != nullptr && flen == buf.size() &&
        IciBlockPool::OffsetOf(data, &off) &&
        IciBlockPool::pool_id() != 0) {
        rsp_pool_stash_.data = data;
        rsp_pool_stash_.length = flen;
        rsp_pool_stash_.pool_id = IciBlockPool::pool_id();
        rsp_pool_stash_.offset = off;
        rsp_pool_stash_.crc32c = crc32c_extend(0, data, flen);
        rsp_pool_stash_.pool_epoch = IciBlockPool::pool_epoch();
        rsp_pool_lease_id_ = block_lease::Pin(std::move(buf), "rsp");
        return;
    }
    rsp_desc::CountFallback();
    response_attachment_.append(std::move(buf));
}

void Controller::ReleaseResponsePoolState() {
    // Server role: a pin whose ownership the response closure never
    // took (failed call, handler ran on a non-tpu_std protocol whose
    // response path ignores descriptors) must not outlive the
    // controller. Exactly-once through the registry as always.
    if (rsp_pool_lease_id_ != 0) {
        block_lease::Release(rsp_pool_lease_id_);
        rsp_pool_lease_id_ = 0;
    }
    rsp_pool_stash_ = PoolAttachment();
    // Client role: releasing the view acks the server's pin. Best-
    // effort — a dead connection drops the ack and the server's reaper
    // reclaims instead.
    if (rsp_ack_sid_ != INVALID_VREF_ID && rsp_ack_cid_ != 0) {
        SendTpuStdDescAck(rsp_ack_sid_, rsp_ack_cid_,
                          rsp_pool_view_.ack_token);
    }
    rsp_pool_view_ = PoolAttachment();
    rsp_ack_sid_ = INVALID_VREF_ID;
    rsp_ack_cid_ = 0;
}

Controller::~Controller() {
    RunCancelClosure();  // contract: an unfired closure still runs once
    ReleasePoolLease();  // a pin must not outlive its controller
    ReleaseResponsePoolState();  // ack the peer's pin / drop our own
    delete excluded_;
    delete span_;  // non-null only if the RPC never reached EndRPC/submit
}

void Controller::Reset() {
    RunCancelClosure();  // reuse ends the previous RPC: fire if unfired
    error_code_ = 0;
    error_text_.clear();
    timeout_ms_ = -1;   // -1: use the channel default
    max_retry_ = -1;
    log_id_ = 0;
    canceled_.store(false, std::memory_order_relaxed);
    request_attachment_.clear();
    response_attachment_.clear();
    ReleasePoolLease();  // reuse ends the previous RPC's pin
    pool_attachment_ = PoolAttachment();
    ReleaseResponsePoolState();  // reuse acks/releases the rsp direction
    remote_side_ = EndPoint();
    local_side_ = EndPoint();
    latency_us_ = 0;
    channel_ = nullptr;
    method_ = nullptr;
    response_ = nullptr;
    done_ = nullptr;
    correlation_id_ = INVALID_CALL_ID;
    current_cid_ = INVALID_CALL_ID;
    unfinished_cid_ = INVALID_CALL_ID;
    backup_timer_ = INVALID_TIMER_ID;
    backup_request_ms_ = -1;
    request_buf_.clear();
    current_try_ = 0;
    start_us_ = 0;
    deadline_us_ = 0;
    timeout_timer_ = INVALID_TIMER_ID;
    single_server_id_ = INVALID_VREF_ID;
    current_server_id_ = INVALID_VREF_ID;
    try_start_us_ = 0;
    request_code_ = 0;
    has_request_code_ = false;
    request_compress_type_ = 0;
    response_compress_type_ = 0;
    tenant_.clear();
    priority_ = -1;
    session_.clear();
    suggested_backoff_ms_ = 0;
    unfinished_server_id_ = INVALID_VREF_ID;
    backup_issued_ = false;
    backup_won_ = false;
    current_fly_sid_ = INVALID_VREF_ID;
    unfinished_fly_sid_ = INVALID_VREF_ID;
    reusable_fly_sid_ = INVALID_VREF_ID;
    auth_fight_sid_ = INVALID_VREF_ID;
    delete excluded_;
    excluded_ = nullptr;
    request_stream_ = INVALID_VREF_ID;
    request_stream_window_ = 0;
    request_stream_bound_ = false;
    has_remote_stream_ = false;
    remote_stream_id_ = 0;
    remote_stream_window_ = 0;
    accepted_stream_ = INVALID_VREF_ID;
    accepted_stream_window_ = 0;
    push_open_id_ = 0;
    push_open_rx_window_ = 0;
    push_open_resume_from_ = 0;
    has_push_open_ = false;
    accepted_push_stream_ = 0;
    server_socket_ = INVALID_VREF_ID;
    server_ = nullptr;
    server_deadline_us_ = 0;
    server_call_id_ = INVALID_CALL_ID;
    {
        std::lock_guard<std::mutex> g(child_mu_);
        child_calls_.clear();
    }
    span_ = nullptr;
    sampled_trace_id_ = 0;
}

void Controller::SetFailed(const std::string& reason) {
    error_code_ = TERR_INTERNAL;
    error_text_ = reason;
}

void Controller::SetFailed(int error_code, const char* fmt, ...) {
    error_code_ = error_code;
    char buf[512];
    va_list ap;
    va_start(ap, fmt);
    vsnprintf(buf, sizeof(buf), fmt, ap);
    va_end(ap);
    error_text_ = buf;
}

void Controller::StartCancel() {
    canceled_.store(true, std::memory_order_release);
    if (correlation_id_ != INVALID_CALL_ID) {
        // HandleError(ECANCELED) sends the wire CANCEL for the in-flight
        // tries under the id lock and finishes the RPC.
        id_error(correlation_id_, ECANCELED);
    }
}

void Controller::NotifyOnCancel(google::protobuf::Closure* closure) {
    if (closure == nullptr) return;
    if (canceled_.load(std::memory_order_acquire)) {
        closure->Run();  // already canceled: notify immediately
        return;
    }
    google::protobuf::Closure* prev =
        on_cancel_.exchange(closure, std::memory_order_acq_rel);
    if (prev != nullptr) {
        prev->Run();  // replaced: the displaced closure still runs once
    }
    if (canceled_.load(std::memory_order_acquire)) {
        RunCancelClosure();  // lost a race with a concurrent cancel
    }
}

void Controller::RunCancelClosure() {
    google::protobuf::Closure* c =
        on_cancel_.exchange(nullptr, std::memory_order_acq_rel);
    if (c != nullptr) c->Run();
}

bool Controller::AddChildCall(CallId cid) {
    std::lock_guard<std::mutex> g(child_mu_);
    if (canceled_.load(std::memory_order_acquire)) return false;
    // Children are never individually deregistered (id_error on a
    // completed id is a free no-op), so a long-lived handler issuing
    // thousands of sequential calls would grow this without bound:
    // compact the dead ids once the list gets big. RANGE existence, not
    // strict: a child that retried (version bump) is still live and
    // still cancelable through its original id value.
    if (child_calls_.size() >= 256) {
        child_calls_.erase(
            std::remove_if(child_calls_.begin(), child_calls_.end(),
                           [](CallId c) { return !id_exists_range(c); }),
            child_calls_.end());
    }
    child_calls_.push_back(cid);
    return true;
}

// ---------------- server-side cancellation ----------------

int64_t Controller::remaining_server_budget_us() const {
    if (server_deadline_us_ <= 0) return INT64_MAX;
    return server_deadline_us_ - monotonic_time_us();
}

namespace {
// Deferred cascade delivery (plain CallId VALUES: stale-safe, never
// touches the possibly-already-freed parent controller).
void* CancelChildrenFiber(void* arg) {
    auto* children = (std::vector<CallId>*)arg;
    for (CallId c : *children) {
        id_error(c, ECANCELED);
    }
    delete children;
    return nullptr;
}
void CancelChildrenTimerCb(void* arg) { CancelChildrenFiber(arg); }
}  // namespace

void Controller::HandleServerCancel() {
    if (canceled_.exchange(true, std::memory_order_acq_rel)) {
        return;  // duplicate delivery (second CANCEL meta, RST + death)
    }
    server_call::CountCanceled();
    RunCancelClosure();
    // Cascade into the handler's downstream calls. canceled_ was set
    // BEFORE taking child_mu_, so a racing AddChildCall either landed in
    // the swapped list or observes canceled_ and self-cancels.
    //
    // Delivery happens OFF this fiber: we run under the server-call id
    // lock, and a child's inline completion can re-enter the SERVER
    // call's done closure (async proxy handlers), whose
    // DestroyServerCallId would then block on the very lock this fiber
    // holds — a self-deadlock. A fresh fiber (timer thread as backstop)
    // takes the child ids by value, so the parent may die freely.
    auto* children = new std::vector<CallId>;
    {
        std::lock_guard<std::mutex> g(child_mu_);
        children->swap(child_calls_);
    }
    if (children->empty()) {
        delete children;
        return;
    }
    fiber_t tid;
    if (fiber_start_background(&tid, nullptr, CancelChildrenFiber,
                               children) != 0) {
        TimerThread::singleton()->schedule(CancelChildrenTimerCb, children,
                                           monotonic_time_us());
    }
}

int Controller::HandleServerCancelThunk(CallId id, void* data, int) {
    ((Controller*)data)->HandleServerCancel();
    return id_unlock(id);  // the call stays live; done destroys the id
}

void Controller::DestroyServerCallId() {
    if (server_call_id_ == INVALID_CALL_ID) return;
    void* unused;
    // Serializes behind an in-flight cancel delivery (the thunk holds the
    // lock while touching this controller), then drops any still-queued
    // cancels — the response is already on its way out.
    if (id_lock(server_call_id_, &unused) == 0) {
        id_unlock_and_destroy(server_call_id_);
    }
    server_call_id_ = INVALID_CALL_ID;
}

void Controller::SendWireCancel() {
    if (channel_ == nullptr) return;
    const bool grpc = channel_->options().protocol == "grpc";
    const auto send_one = [&](CallId cid, SocketId fly_sid,
                              SocketId server_sid) {
        if (cid == INVALID_CALL_ID) return;
        SocketId sid = fly_sid;
        if (sid == INVALID_VREF_ID) sid = server_sid;
        if (sid == INVALID_VREF_ID) sid = single_server_id_;
        if (sid == INVALID_VREF_ID) return;
        if (grpc) {
            H2ClientCancel(sid, cid);
        } else {
            SendTpuStdCancel(sid, cid);
        }
    };
    send_one(current_cid_, current_fly_sid_, current_server_id_);
    // The unfinished (pre-backup) try lives on ITS OWN server: the
    // backup's FeedbackToLB cleared current_server_id_, so the saved
    // unfinished_server_id_ is the only address that still names it.
    send_one(unfinished_cid_, unfinished_fly_sid_, unfinished_server_id_);
}

// ---------------- client call machinery ----------------

int Controller::HandleErrorThunk(CallId id, void* data, int error) {
    return ((Controller*)data)->HandleError(id, error);
}

static bool is_retryable(int error) {
    // The default retry policy (reference src/brpc/retry_policy.cpp
    // DefaultRetryPolicy: EFAILEDSOCKET/EEOF/EHOSTDOWN/...): connection-
    // level failures retry, server-side/user errors and timeouts don't.
    switch (error) {
        case TERR_FAILED_SOCKET:
        case TERR_EOF:
        case TERR_OVERCROWDED:
        case ECONNREFUSED:
        case ECONNRESET:
        case EPIPE:
        case EHOSTDOWN:  // LB found only failed servers; retry re-selects
        case TERR_DRAINING:  // peer draining, call provably unprocessed
        // Priority-aware overload shed: the server never ran the
        // handler, so a re-issue (elsewhere, after the suggested
        // backoff) is safe — but it SPENDS retry budget, because under
        // overload re-issues amplify the very load being shed.
        case TERR_OVERLOAD:
        // Stale zero-copy reference (pool epoch fence): the server
        // refused to resolve a descriptor minted under an old pool
        // generation — the handler never saw the bytes, so a re-issue
        // is safe; the remap/re-handshake underneath the retry carries
        // the fresh generation.
        case TERR_STALE_EPOCH:
            return true;
        default:
            return false;
    }
}

bool DefaultRetryPolicy::DoRetry(const Controller* cntl) const {
    return is_retryable(cntl->ErrorCode());
}

const DefaultRetryPolicy* DefaultRetryPolicy::instance() {
    static const DefaultRetryPolicy p;
    return &p;
}

int Controller::HandleError(CallId id, int error) {
    // Runs with the id locked.
    if (id != current_cid_ && id == unfinished_cid_ && is_retryable(error)) {
        // A connection-level failure of the NON-current in-flight call
        // (the original behind a backup request): only that call dies;
        // the current call may still complete the RPC.
        unfinished_cid_ = INVALID_CALL_ID;
        unfinished_server_id_ = INVALID_VREF_ID;
        if (unfinished_fly_sid_ != INVALID_VREF_ID) {
            Socket::SetFailedById(unfinished_fly_sid_);
            unfinished_fly_sid_ = INVALID_VREF_ID;
        }
        return id_unlock(id);
    }
    if (id == current_cid_ && unfinished_cid_ != INVALID_CALL_ID &&
        is_retryable(error)) {
        // The backup's connection died while the original is still
        // pending: fall back to waiting on the original instead of
        // failing the whole RPC.
        current_cid_ = unfinished_cid_;
        unfinished_cid_ = INVALID_CALL_ID;
        if (current_fly_sid_ != INVALID_VREF_ID) {
            Socket::SetFailedById(current_fly_sid_);
        }
        current_fly_sid_ = unfinished_fly_sid_;
        unfinished_fly_sid_ = INVALID_VREF_ID;
        // The original is current again — restore its server id so
        // EndRPC's final LB feedback (and any wire CANCEL) attributes
        // the verdict to the server actually handling the call, not to
        // the dead backup's.
        current_server_id_ = unfinished_server_id_;
        unfinished_server_id_ = INVALID_VREF_ID;
        backup_won_ = false;  // the backup did NOT complete the RPC
        return id_unlock(id);
    }
    // Cancellation (StartCancel, or the cascade from a canceled upstream
    // server call): tell the server(s) to stop working on the in-flight
    // tries before finishing locally — the whole point of the cascade is
    // that an abandoned call frees CPU all the way down.
    if (error == ECANCELED) {
        canceled_.store(true, std::memory_order_release);
        if (span_ != nullptr) {
            span_->Annotate("canceled: wire CANCEL sent to in-flight tries");
        }
        SendWireCancel();
    }
    // The failing try's dedicated connection is dead weight from here
    // (retry opens a fresh one; terminal failure closes it in EndRPC).
    if (current_fly_sid_ != INVALID_VREF_ID && is_retryable(error)) {
        Socket::SetFailedById(current_fly_sid_);
        current_fly_sid_ = INVALID_VREF_ID;
    }
    const int effective_max_retry =
        max_retry_ >= 0 ? max_retry_
                        : (channel_ ? channel_->options().max_retry : 0);
    FeedbackToLB(error);  // per-try completion (the retry is a new pick)
    // Pluggable retry decision (reference retry_policy.h:28-68): the
    // policy inspects the failed try's error on the controller.
    const RetryPolicy* rp =
        channel_ != nullptr && channel_->options().retry_policy != nullptr
            ? channel_->options().retry_policy
            : DefaultRetryPolicy::instance();
    SetFailed(error, "%s", terror(error));
    if (rp->DoRetry(this) && current_try_ < effective_max_retry &&
        (deadline_us_ == 0 || monotonic_time_us() < deadline_us_)) {
        // Draining peers are a special retry class: the server announced
        // a planned shutdown and provably never processed this try, so
        // re-issuing elsewhere cannot amplify load — it spends NO budget
        // token (the zero-downtime contract: a rolling restart costs no
        // retry budget and trips no breaker).
        const bool budget_free = (error == TERR_DRAINING);
        if (budget_free && span_ != nullptr) {
            span_->Annotate("server draining, re-routed");
        }
        if (budget_free) *g_drain_reroutes << 1;
        // Retry throttling (gRPC-style retry budget, channel.h): under a
        // correlated failure every caller retrying independently is the
        // retry storm that amplifies overload — once the per-channel
        // bucket is dry, fail now with the try's own error instead.
        if (!budget_free && channel_ != nullptr &&
            !channel_->retry_budget().Withdraw()) {
            *g_budget_exhausted << 1;
            if (span_ != nullptr) {
                span_->Annotate(
                    "retry budget exhausted: failing with this try's error");
            }
        } else {
            const CallId next = id_next_version(current_cid_);
            if (next == INVALID_CALL_ID && !budget_free &&
                channel_ != nullptr) {
                // The re-issue never went out: the token goes back.
                channel_->retry_budget().Refund();
            }
            if (next != INVALID_CALL_ID) {
                ++current_try_;
                current_cid_ = next;
                *g_client_retries << 1;
                int64_t backoff_ms = rp->BackoffMs(this);
                // An overloaded server suggested when to come back:
                // honor it with jitter in [s/2, s] — synchronized
                // retries arriving exactly at s would re-create the
                // thundering herd the backoff exists to spread. The
                // policy's own (longer) backoff wins if larger.
                if (error == TERR_OVERLOAD && suggested_backoff_ms_ > 0) {
                    const int64_t s = suggested_backoff_ms_;
                    int64_t jittered =
                        s / 2 + (int64_t)(fast_rand() %
                                          (uint64_t)(s / 2 + 1));
                    // Capped by the call's remaining deadline budget
                    // (ISSUE 15 satellite): a suggestion past the
                    // deadline used to fall through the overshoot
                    // guard below and re-issue IMMEDIATELY at a server
                    // that just said "not now" — hammering it AND
                    // burning the try. Sleep the useful fraction of
                    // what's left (7/8, so the retry itself still has
                    // budget to run) instead.
                    if (deadline_us_ > 0) {
                        const int64_t remaining_ms =
                            (deadline_us_ - monotonic_time_us()) / 1000;
                        const int64_t cap =
                            remaining_ms -
                            std::max<int64_t>(1, remaining_ms / 8);
                        if (jittered > cap) {
                            jittered = std::max<int64_t>(cap, 0);
                            if (span_ != nullptr) {
                                span_->Annotate(
                                    "overload backoff clamped to "
                                    "deadline budget: " +
                                    std::to_string(jittered) +
                                    "ms (server suggested " +
                                    std::to_string(s) + "ms)");
                            }
                        }
                    }
                    backoff_ms = std::max<int64_t>(backoff_ms, jittered);
                }
                error_code_ = 0;  // a later try owns the final verdict
                error_text_.clear();
                if (backoff_ms > 0 &&
                    (deadline_us_ == 0 ||
                     monotonic_time_us() + backoff_ms * 1000 <
                         deadline_us_)) {
                    // Issue after the backoff; the timer holds only the
                    // NEW cid value (stale-safe, like every other timer
                    // here).
                    TimerThread::singleton()->schedule(
                        &Controller::HandleBackoffThunk,
                        (void*)(uintptr_t)current_cid_,
                        monotonic_time_us() + backoff_ms * 1000);
                } else {
                    IssueRPC();
                }
                return id_unlock(id);
            }
        }
    }
    EndRPC(id);
    return 0;
}

// Backoff expiry: re-issue the already-bumped try (the id value alone is
// carried; a completed/canceled RPC makes the lock fail harmlessly).
void Controller::HandleBackoffThunk(void* arg) {
    const CallId cid = (CallId)(uintptr_t)arg;
    void* data = nullptr;
    if (id_lock_range(cid, &data) != 0) return;
    auto* cntl = (Controller*)data;
    if (cid == cntl->current_cid_) {
        cntl->IssueRPC();
    }
    id_unlock(cid);
}

void Controller::FeedbackToLB(int error) {
    if (channel_ == nullptr || current_server_id_ == INVALID_VREF_ID) return;
    LoadBalancerWithNaming* lb = channel_->lb();
    const int64_t try_latency_us = monotonic_time_us() - try_start_us_;
    if (lb != nullptr) {
        LoadBalancer::CallInfo info;
        info.server_id = current_server_id_;
        // Per-try latency: charging earlier failed tries' time to the
        // final server would invert locality-aware ranking.
        info.latency_us = try_latency_us;
        info.error_code = error;
        lb->Feedback(info);
        // Circuit breaker: chronic/bursty error rates isolate the server
        // (SetFailed -> health check revives it later with fresh windows;
        // reference Call::OnComplete -> Socket::FeedbackCircuitBreaker).
        SocketUniquePtr s = SocketUniquePtr::FromId(current_server_id_);
        if (s && !s->circuit_breaker().OnCallEnd(error, try_latency_us)) {
            LOG(WARNING) << "circuit breaker isolating "
                         << endpoint2str(s->remote_side()) << " (short "
                         << s->circuit_breaker().short_window_error_percent()
                         << "%, long "
                         << s->circuit_breaker().long_window_error_percent()
                         << "%)";
            s->SetFailedWithError(EHOSTDOWN);
        }
    }
    current_server_id_ = INVALID_VREF_ID;
}

void Controller::IssueRPC() {
    try_start_us_ = monotonic_time_us();
    SocketUniquePtr s;
    if (channel_->lb() != nullptr) {
        // LB mode: pick a live server, excluding ones tried by earlier
        // attempts of this RPC (reference controller.cpp:1098 SelectServer
        // + ExcludedServers controller.cpp:644-680).
        SelectIn in;
        in.request_code = request_code_;
        in.has_request_code = has_request_code_;
        in.excluded = excluded_;
        SelectOut out;
        const int rc = channel_->lb()->SelectServer(in, &out);
        if (rc != 0) {
            id_error(current_cid_, rc);
            return;
        }
        if (out.skipped_draining) {
            // A draining node was passed over for this pick: visible in
            // stitched traces and countable mesh-wide.
            *g_drain_reroutes << 1;
            if (span_ != nullptr) {
                span_->Annotate("server draining, re-routed");
            }
        }
        if (out.zone_spilled && span_ != nullptr) {
            // Cross-pod spill (ISSUE 14): the local zone could not serve
            // this pick — the counter lives in the zone LB layer, the
            // trace evidence here.
            span_->Annotate("cross-zone spill to " +
                            endpoint2str(out.ptr->remote_side()));
        }
        if (out.skipped_ejected && span_ != nullptr) {
            // An ejected outlier was passed over (ISSUE 20): the note
            // carries WHY ("ejected: latency outlier 8.2x median") so a
            // trace reader sees the routing shift without the portal.
            span_->Annotate(out.outlier_note.empty()
                                ? "outlier ejected, re-routed"
                                : out.outlier_note + ", re-routed");
        }
        if (out.outlier_probe && span_ != nullptr) {
            // This call IS the reinstatement probe for an ejected node.
            span_->Annotate("outlier reinstatement probe to " +
                            endpoint2str(out.ptr->remote_side()));
        }
        s = std::move(out.ptr);
        current_server_id_ = s->id();
        if (excluded_ == nullptr) excluded_ = new ExcludedServers;
        excluded_->Add(s->id());
    } else {
        SocketId sid = channel_->AcquirePinnedSocket();
        if (sid == INVALID_VREF_ID &&
            SocketMap::singleton()->GetOrCreate(
                channel_->server(), Channel::client_messenger(), &sid,
                channel_->transport_tier()) != 0) {
            id_error(current_cid_, TERR_FAILED_SOCKET);
            return;
        }
        single_server_id_ = sid;
        if (Socket::AddressSocket(sid, &s) != 0) {
            id_error(current_cid_, TERR_FAILED_SOCKET);
            return;
        }
    }
    remote_side_ = s->remote_side();

    // Connection selection (reference controller.cpp:1135-1173): pooled
    // and short modes write on a dedicated connection instead of the
    // shared main socket; the main socket still carries LB identity,
    // circuit-breaker state and health checks.
    // Streaming RPCs always ride the shared single connection: the
    // stream binds to the connection that carried the establishing RPC,
    // which must be neither pooled (a later RPC would interleave with
    // stream frames) nor closed at EndRPC (reference streams ride the
    // main socket for the same reason).
    // grpc channels always ride their pinned h2 connection: pooled/short
    // fly sockets come from endpoint-keyed shared pools that tpu_std
    // channels use too, and an h2 session installed there would corrupt
    // the other protocol's traffic (h2 multiplexes concurrent calls on
    // one connection anyway — pooling adds nothing).
    const ConnectionType ct =
        request_stream_ != INVALID_VREF_ID ||
                channel_->options().protocol == "grpc"
            ? CONNECTION_TYPE_SINGLE
            : channel_->options().connection_type;
    if (ct != CONNECTION_TYPE_SINGLE) {
        SocketId fly = INVALID_VREF_ID;
        int rc2;
        // Fly connections inherit the main socket's forced tier: a dcn
        // LB member's pooled/short connections are dcn too (and pool
        // under the (endpoint, tier) key, never mixing with tcp).
        const int fly_tier =
            s->transport() == nullptr ? s->forced_transport_tier() : -1;
        if (ct == CONNECTION_TYPE_POOLED) {
            rc2 = SocketPool::singleton()->Get(s->remote_side(),
                                               Channel::client_messenger(),
                                               &fly, fly_tier);
        } else {  // SHORT: fresh connection, closed after the response
            rc2 = CreateClientSocket(s->remote_side(),
                                     Channel::client_messenger(), &fly,
                                     fly_tier);
        }
        if (rc2 != 0) {
            id_error(current_cid_, TERR_FAILED_SOCKET);
            return;
        }
        SocketUniquePtr fly_ptr;
        if (Socket::AddressSocket(fly, &fly_ptr) != 0) {
            id_error(current_cid_, TERR_FAILED_SOCKET);
            return;
        }
        current_fly_sid_ = fly;
        s = std::move(fly_ptr);
    }

    // Sender-side frame limit: the receiver rejects >256MB frames as a
    // PROTOCOL error (failing the whole connection); catch it here so only
    // this one RPC fails (also guards the uint32 length field).
    if (request_buf_.size() + request_attachment_.size() > (200u << 20)) {
        id_error(current_cid_, TERR_REQUEST);
        return;
    }

    if (channel_->options().protocol == "grpc") {
        // gRPC over h2c: the h2 client session multiplexes this call as
        // a new stream; the response completes the RPC via
        // CompleteClientUnaryResponse (thttp/http2_client.cc). Retry,
        // backup, timeout, and LB machinery above are protocol-agnostic.
        if (span_ != nullptr) {
            span_->sent_us = monotonic_time_us();
        }
        std::string authorization;
        if (channel_->options().auth != nullptr &&
            channel_->options().auth->GenerateCredential(&authorization) !=
                0) {
            id_error(current_cid_, TERR_AUTH);
            return;
        }
        const std::string path = "/" + method_->service()->full_name() +
                                 "/" + method_->name();
        if (H2ClientSendUnary(s.get(), current_cid_, path,
                              endpoint2str(remote_side_), request_buf_,
                              deadline_us_, authorization, tenant_,
                              priority_, session_) != 0) {
            id_error(current_cid_, errno != 0 ? errno : TERR_FAILED_SOCKET);
        }
        return;
    }

    // tpu_std auth fight (reference socket.h:515): the first caller on a
    // fresh connection attaches the credential; concurrent first-writers
    // wait for its outcome instead of re-authenticating. A PREVIOUS try
    // of this RPC that won the fight but died releases it first so this
    // try (or another caller) can re-fight.
    if (auth_fight_sid_ != INVALID_VREF_ID) {
        SocketUniquePtr prev;
        if (Socket::AddressSocket(auth_fight_sid_, &prev) == 0) {
            prev->AbortAuthentication();
        }
        auth_fight_sid_ = INVALID_VREF_ID;
    }
    std::string auth_data;
    bool send_auth = false;
    if (channel_->options().auth != nullptr) {
        while (!s->authenticated()) {
            if (s->FightAuthentication() == 0) {
                if (channel_->options().auth->GenerateCredential(
                        &auth_data) != 0) {
                    s->AbortAuthentication();
                    id_error(current_cid_, TERR_AUTH);
                    return;
                }
                send_auth = true;
                auth_fight_sid_ = s->id();
                break;
            }
            if (s->WaitAuthenticated(deadline_us_) != 0) {
                // Distinguish a dead connection from a slow/wedged
                // authenticator for the caller's diagnosis.
                id_error(current_cid_, s->Failed() ? TERR_FAILED_SOCKET
                                                   : TERR_RPC_TIMEDOUT);
                return;
            }
            // Resolved: either authenticated (loop exits) or the winner
            // aborted (loop re-fights).
        }
    }

    rpc::RpcMeta meta;
    auto* req_meta = meta.mutable_request();
    req_meta->set_service_name(method_->service()->full_name());
    req_meta->set_method_name(method_->name());
    if (deadline_us_ > 0) {
        // Remaining budget, floored at 1ms while any budget truly
        // remains: plain /1000 truncation would stamp a live sub-ms
        // budget as 0, which the server rejects as expired-on-arrival.
        // 0 is reserved for "the deadline has really passed" (the server
        // sheds without executing).
        const int64_t remaining_us = deadline_us_ - monotonic_time_us();
        req_meta->set_timeout_ms(
            remaining_us > 0 ? std::max<int64_t>(1, remaining_us / 1000)
                             : 0);
    }
    if (log_id_ != 0) req_meta->set_log_id(log_id_);
    // QoS identity: resolved (explicit or inherited) by CallMethod; an
    // unset pair costs no meta bytes and the server classes the call as
    // the default tenant/priority.
    if (!tenant_.empty()) req_meta->set_tenant(tenant_);
    if (priority_ >= 0) req_meta->set_priority(priority_);
    // Sticky-session identity (ISSUE 16): named so an L7 front door can
    // pin the whole session to one backend; hop-to-hop like tenant.
    if (!session_.empty()) req_meta->set_session(session_);
    // Pod identity (ISSUE 15d): a zone-tagged sender announces itself
    // so the receiver can price cross-pod spill arrivals above local
    // work (and shed them first within a priority level).
    {
        const std::string my_zone = FLAGS_rpc_zone.get();
        if (!my_zone.empty()) req_meta->set_zone(my_zone);
    }
    if (span_ != nullptr) {
        req_meta->set_trace_id(span_->trace_id);
        req_meta->set_span_id(span_->span_id);
        if (span_->parent_span_id != 0) {
            req_meta->set_parent_span_id(span_->parent_span_id);
        }
        span_->remote_side = remote_side_;
        span_->retries = current_try_;
        if (current_try_ > 0) {
            span_->Annotate("re-issued try " + std::to_string(current_try_) +
                            " to " + endpoint2str(remote_side_));
        }
    }
    meta.set_correlation_id(current_cid_);
    if (send_auth) {
        meta.set_auth_data(auth_data);
    }
    if (request_compress_type_ != COMPRESS_NONE) {
        meta.set_compress_type(request_compress_type_);
    }
    // The wire attachment: the user's inline bytes, plus — when this
    // try's transport tier cannot carry a one-sided reference — the
    // pinned pool bytes appended inline. Eligibility is the Transport
    // seam's verdict (ISSUE 12): an LB that picks a plain-TCP replica
    // for one try of a descriptor-pinned call degrades that try to
    // inline instead of failing it on the server. The common paths (no
    // pinned attachment, or a capable tier) pay no IOBuf copy — the
    // combined buffer is materialized only inside the fallback branch.
    const IOBuf* wire_att = &request_attachment_;
    IOBuf inline_fallback_att;
    // One-sided pool attachment (ISSUE 9): the frame carries ONLY the
    // header + meta (+ inline payload pb); the attachment crosses the
    // seam as a block reference the receiver maps in place. The pin is
    // a lease (released exactly once at EndRPC; reaper/peer-death are
    // the crash backstops). Arm it with this try's identity: owning
    // call id, expiry derived from the propagated RPC deadline, and the
    // socket the descriptor rides — so a SIGKILLed peer releases
    // exactly the pins posted toward it (server_call::OnSocketFailed).
    if (pool_lease_id_ != 0) {
        // Arm is the liveness check AND the re-key, in one registry
        // lock acquisition (a separate Alive() probe would leave a
        // window where reclamation lands between check and arm). A
        // false return means the pin was reclaimed underneath us
        // (lease expired, or a previous try's peer died and took the
        // pin with it): the referenced bytes may already be recycled,
        // and the ONLY copy of the payload was that block — so every
        // subsequent try must keep failing with the stale-reference
        // error (lease id deliberately NOT cleared: a later try that
        // silently framed without the attachment would hand the
        // server an empty payload and report success — data loss).
        // Bounded by max_retry/deadline like any other retriable
        // failure; the terminal error is TERR_STALE_EPOCH.
        // A backup re-issue ADDS this try's socket to the lease's
        // entitled peers (the original try — still in flight — may be
        // mid-read on its own socket); a plain retry replaces it.
        const bool backup_in_flight =
            unfinished_cid_ != INVALID_CALL_ID;
        if (!block_lease::Arm(pool_lease_id_, (uint64_t)correlation_id_,
                              deadline_us_, (uint64_t)s->id(),
                              backup_in_flight)) {
            id_error(current_cid_, TERR_STALE_EPOCH);
            return;
        }
        if (TransportDescriptorCapable(s.get())) {
            // Re-issues restamp the CURRENT pool generation: the pin
            // (and its offset) is still valid — the lease holds it — so
            // a retry after a TERR_STALE_EPOCH re-handshake carries the
            // epoch the receiver's fresh mapping expects.
            pool_attachment_.pool_epoch = IciBlockPool::pool_epoch();
            auto* pd = meta.mutable_pool_attachment();
            pd->set_pool_id(pool_attachment_.pool_id);
            pd->set_offset(pool_attachment_.offset);
            pd->set_length(pool_attachment_.length);
            pd->set_crc32c(pool_attachment_.crc32c);
            pd->set_pool_epoch(pool_attachment_.pool_epoch);
            *g_pool_desc_sends << 1;
            *g_pool_desc_bytes << (int64_t)pool_attachment_.length;
            transport_stats::AddDescOut(s->transport_tier(),
                                        (int64_t)pool_attachment_.length);
        } else {
            // Descriptor-incapable tier for THIS try: the Arm above
            // proved the pin (and therefore the stashed view) is still
            // live, so the bytes go inline — the payload arrives either
            // way, the zero-copy win is simply unavailable on this
            // transport.
            inline_fallback_att.append(request_attachment_);
            inline_fallback_att.append(pool_attachment_.data,
                                       pool_attachment_.length);
            wire_att = &inline_fallback_att;
            *g_pool_desc_wire_fallbacks << 1;
        }
    }
    meta.set_attachment_size((uint32_t)wire_att->size());
    if (FLAGS_rpc_checksum.get()) {
        uint32_t crc = crc32c_iobuf(0, request_buf_);
        crc = crc32c_iobuf(crc, *wire_att);
        meta.set_body_checksum(crc);
    }
    if (request_stream_ != INVALID_VREF_ID) {
        auto* ss = meta.mutable_stream_settings();
        ss->set_stream_id(request_stream_);
        ss->set_window_size(request_stream_window_);
    } else if (push_open_id_ != 0 && !has_push_open_) {
        // push_stream open/resume (ISSUE 17): client side only —
        // has_push_open_ means this Controller is serving a push open,
        // not issuing one.
        auto* ss = meta.mutable_stream_settings();
        ss->set_stream_id(push_open_id_);
        ss->set_version(push_stream::kStreamVersion);
        ss->set_rx_window(push_open_rx_window_);
        ss->set_resume_from_seq(push_open_resume_from_);
        ss->set_push(true);
    }
    IOBuf meta_buf;
    SerializePbToIOBuf(meta, &meta_buf);
    IOBuf frame;
    PackTpuStdFrame(&frame, meta_buf, request_buf_, *wire_att);
    if (span_ != nullptr) {
        span_->request_bytes = (int64_t)frame.size();
        span_->sent_us = monotonic_time_us();
    }
    if (s->Write(&frame, current_cid_) != 0) {
        // Queue full or failed socket: deliver the error (may retry).
        id_error(current_cid_, errno != 0 ? errno : TERR_FAILED_SOCKET);
    }
}

void* Controller::RunDoneThunk(void* arg) {
    ((google::protobuf::Closure*)arg)->Run();
    return nullptr;
}

// ---------------- backup requests ----------------

// Timer callback: holds only the base CallId VALUE (a finished RPC makes
// the lock fail — same hazard discipline as HandleTimeoutCb).
void Controller::HandleBackupThunk(void* arg) {
    const CallId cid = (CallId)(uintptr_t)arg;
    void* data = nullptr;
    if (id_lock_range(cid, &data) != 0) {
        return;  // RPC already completed
    }
    ((Controller*)data)->MaybeIssueBackup();
    id_unlock(cid);
}

void Controller::MaybeIssueBackup() {
    // Runs with the id locked.
    if (Failed() || canceled_ || unfinished_cid_ != INVALID_CALL_ID) {
        return;  // already failed / already one backup out
    }
    if (channel_ != nullptr &&
        channel_->options().backup_request_policy != nullptr &&
        !channel_->options().backup_request_policy->DoBackup(this)) {
        return;  // the policy vetoed hedging this call
    }
    const int effective_max_retry =
        max_retry_ >= 0 ? max_retry_
                        : (channel_ ? channel_->options().max_retry : 0);
    if (current_try_ >= effective_max_retry) {
        return;  // backup consumes retry budget (reference semantics)
    }
    // Hedging is a re-issue too: an exhausted retry budget vetoes the
    // backup (under overload, doubling the traffic is the last thing the
    // fleet needs — same rationale as the retry path).
    if (channel_ != nullptr && !channel_->retry_budget().Withdraw()) {
        *g_budget_exhausted << 1;
        if (span_ != nullptr) {
            span_->Annotate("retry budget exhausted: backup request vetoed");
        }
        return;
    }
    const CallId next = id_next_version(current_cid_);
    if (next == INVALID_CALL_ID) {
        if (channel_ != nullptr) channel_->retry_budget().Refund();
        return;
    }
    // The original call STAYS live (ranged id): record it so its response
    // can still win and its socket errors fail only it. Feed the LB a
    // slow-but-ok data point for the original's server (elapsed latency,
    // no error — the locality-aware policy deprioritizes it; the breaker
    // sees no failure). The winner's stats land in EndRPC.
    unfinished_cid_ = current_cid_;
    unfinished_fly_sid_ = current_fly_sid_;
    current_fly_sid_ = INVALID_VREF_ID;
    // Save the original's server BEFORE the feedback clears
    // current_server_id_: the loser-cancel at EndRPC (and the fall-back
    // when the backup's connection dies) still needs its address.
    unfinished_server_id_ = current_server_id_;
    FeedbackToLB(0);
    current_cid_ = next;
    ++current_try_;
    backup_issued_ = true;
    *g_client_backups << 1;
    IssueRPC();
}

// Pooled mode returns response-delivering connections to the pool; every
// other pooled/short connection of this RPC (abandoned original behind a
// winning backup, timed-out try, short-lived conn) is closed — it may
// carry an orphan in-flight response and must never serve another call.
void Controller::ReleaseFlySockets() {
    if (channel_ == nullptr) return;
    const ConnectionType ct = channel_->options().connection_type;
    if (ct == CONNECTION_TYPE_SINGLE) return;
    if (reusable_fly_sid_ != INVALID_VREF_ID) {
        if (ct == CONNECTION_TYPE_POOLED) {
            SocketPool::singleton()->Return(reusable_fly_sid_);
        } else {
            Socket::SetFailedById(reusable_fly_sid_);
        }
        reusable_fly_sid_ = INVALID_VREF_ID;
    }
    if (current_fly_sid_ != INVALID_VREF_ID) {
        Socket::SetFailedById(current_fly_sid_);
        current_fly_sid_ = INVALID_VREF_ID;
    }
    if (unfinished_fly_sid_ != INVALID_VREF_ID) {
        Socket::SetFailedById(unfinished_fly_sid_);
        unfinished_fly_sid_ = INVALID_VREF_ID;
    }
}

void Controller::EndRPC(CallId locked_id) {
    latency_us_ = monotonic_time_us() - start_us_;
    // One-sided completion (ISSUE 9/10): the response (or terminal
    // failure) means the peer will never again read our posted
    // descriptor — release the lease, returning the pinned block to the
    // owner's pool. Exactly-once even across retry/backup re-issues and
    // against the reaper/peer-death reclamation paths (block_lease.h).
    ReleasePoolLease();
    pool_attachment_ = PoolAttachment();
    // The RPC is over: an unfired NotifyOnCancel closure runs now
    // (protobuf contract — exactly once whether or not canceled).
    RunCancelClosure();
    // A success refills the retry budget by the configured ratio (the
    // gRPC token-bucket shape: sustained failure drains it, recovery
    // earns re-issue capacity back).
    if (channel_ != nullptr && error_code_ == 0) {
        channel_->retry_budget().OnSuccess();
    }
    // A failed auth-carrying call releases the fight it won (success
    // paths already resolved it via SetAuthenticated on the response).
    if (auth_fight_sid_ != INVALID_VREF_ID) {
        if (Failed()) {
            SocketUniquePtr s;
            if (Socket::AddressSocket(auth_fight_sid_, &s) == 0) {
                s->AbortAuthentication();
            }
        }
        auth_fight_sid_ = INVALID_VREF_ID;
    }
    // Hedge loser cancel (ISSUE 16): the RPC completed but the OTHER try
    // is still live on its server — a wire CANCEL stops that server from
    // burning CPU on a call nobody waits for, and lets it ack/release any
    // descriptor lease the abandoned try carried. Skip when the whole RPC
    // was canceled (SendWireCancel already covered both tries).
    if (unfinished_cid_ != INVALID_CALL_ID &&
        !canceled_.load(std::memory_order_relaxed) && channel_ != nullptr) {
        SocketId sid = unfinished_fly_sid_;
        if (sid == INVALID_VREF_ID) sid = unfinished_server_id_;
        if (sid == INVALID_VREF_ID) sid = single_server_id_;
        if (sid != INVALID_VREF_ID) {
            if (channel_->options().protocol == "grpc") {
                H2ClientCancel(sid, unfinished_cid_);
            } else {
                SendTpuStdCancel(sid, unfinished_cid_);
            }
        }
    }
    ReleaseFlySockets();
    if (span_ != nullptr) {
        if (error_code_ != 0) {
            // The terminal verdict rides the span so a stitched timeline
            // shows WHY a hop died (shed, expired, canceled, refused)
            // even when the downstream produced no span of its own.
            span_->Annotate("failed: " + error_text_);
            if (FLAGS_chaos_enabled.get()) {
                span_->Annotate("note: local chaos injection is enabled");
            }
        }
        span_->end_us = monotonic_time_us();
        span_->error_code = error_code_;
        Collector::singleton()->submit(span_);
        span_ = nullptr;
    }
    FeedbackToLB(error_code_);
    // A client stream that never got bound to a connection must be failed
    // here — EndRPC is the single funnel every termination path (success
    // without stream settings, server error, timeout, socket failure)
    // passes through, so the stream's creation/rx refs can't leak.
    if (request_stream_ != INVALID_VREF_ID && !request_stream_bound_) {
        stream_internal::FailStream(request_stream_);
    }
    if (timeout_timer_ != INVALID_TIMER_ID) {
        // Best-effort: if the callback is running it will find the id
        // destroyed (it only holds the id VALUE, never this pointer).
        TimerThread::singleton()->unschedule(timeout_timer_, false);
        timeout_timer_ = INVALID_TIMER_ID;
    }
    if (backup_timer_ != INVALID_TIMER_ID) {
        TimerThread::singleton()->unschedule(backup_timer_, false);
        backup_timer_ = INVALID_TIMER_ID;
    }
    google::protobuf::Closure* done = done_;
    id_unlock_and_destroy(locked_id);
    // `this` may be deleted by done from here on.
    if (done != nullptr) {
        if (is_running_on_fiber_worker()) {
            done->Run();
        } else {
            // Never run user code on the timer thread.
            fiber_t tid;
            if (fiber_start_background(&tid, nullptr, RunDoneThunk, done) !=
                0) {
                done->Run();
            }
        }
    }
}

// ---------------- client response path ----------------

void ProcessTpuStdResponse(TpuStdMessage* msg, const rpc::RpcMeta& meta) {
    const CallId cid = meta.correlation_id();
    // A dropped response that carried a pool descriptor still acks: the
    // server pinned a block for us, and nobody will ever resolve this
    // copy of the reference — without the ack the pin would sit until
    // the deadline-derived reaper. Covers the finished-RPC and
    // abandoned-try drops below (a late response behind a timeout or a
    // backup winner is exactly descriptor-heavy load's common case).
    const auto ack_dropped_descriptor = [&] {
        if (meta.response().has_pool_attachment()) {
            SendTpuStdDescAck(msg->socket_id, cid,
                              meta.response().pool_attachment()
                                  .ack_token());
        }
    };
    void* data = nullptr;
    // Ranged lock: with a backup request out, TWO versions are in flight
    // and either response may win. Versions outside the live set (retried
    // tries, duplicates, finished RPCs) are dropped below / by the lock.
    if (id_lock_range(cid, &data) != 0) {
        // destroyed (finished) or stale beyond the range: drop
        ack_dropped_descriptor();
        return;
    }
    Controller* cntl = (Controller*)data;
    if (cid != cntl->current_cid_ && cid != cntl->unfinished_cid_) {
        id_unlock(cid);  // an abandoned try's late response
        ack_dropped_descriptor();
        return;
    }
    // Hedge winner normalization (ISSUE 16): whichever live try delivered
    // THIS response is the winner — relabel it "current" so every
    // termination path below (fly-sid reuse, LB feedback, the loser
    // cancel at EndRPC) uniformly treats "unfinished" as the loser.
    if (cid == cntl->unfinished_cid_) {
        std::swap(cntl->current_cid_, cntl->unfinished_cid_);
        std::swap(cntl->current_fly_sid_, cntl->unfinished_fly_sid_);
        std::swap(cntl->current_server_id_, cntl->unfinished_server_id_);
    } else if (cntl->unfinished_cid_ != INVALID_CALL_ID) {
        // The BACKUP try's response is completing the RPC (cleared again
        // in HandleError if this response is a retryable error and the
        // call falls back to the still-live original).
        cntl->backup_won_ = true;
    }
    if (cntl->span_ != nullptr) {
        cntl->span_->received_us = monotonic_time_us();
        cntl->span_->response_bytes = (int64_t)msg->body.size();
    }
    // Pooled/short: the connection that delivered THIS response is clean
    // (no orphan response pending) and may be pooled again at EndRPC.
    if (cid == cntl->current_cid_ &&
        cntl->current_fly_sid_ != INVALID_VREF_ID) {
        cntl->reusable_fly_sid_ = cntl->current_fly_sid_;
        cntl->current_fly_sid_ = INVALID_VREF_ID;
    } else if (cid == cntl->unfinished_cid_ &&
               cntl->unfinished_fly_sid_ != INVALID_VREF_ID) {
        cntl->reusable_fly_sid_ = cntl->unfinished_fly_sid_;
        cntl->unfinished_fly_sid_ = INVALID_VREF_ID;
    }
    const auto& rmeta = meta.response();
    flight::Record(flight::kRpcRespRecv, cid, (uint64_t)rmeta.error_code());
    // Any NON-auth-error response proves the server accepted this
    // connection's credential: release the auth-fight waiters (a bad
    // credential fails the connection instead, waking them with an
    // error).
    if (rmeta.error_code() != TERR_AUTH) {
        SocketUniquePtr rs;
        if (Socket::AddressSocket(msg->socket_id, &rs) == 0 &&
            !rs->authenticated()) {
            rs->SetAuthenticated("");
        }
    }
    if (rmeta.error_code() != 0) {
        // An error response never hands user code the descriptor view:
        // ack a piggybacked response pool attachment NOW so the server's
        // pin frees without waiting for the reaper (satellite-1 audit —
        // these terminal paths used to strand the pin).
        ack_dropped_descriptor();
        if (rmeta.error_code() == TERR_OVERLOAD ||
            rmeta.error_code() == TERR_OVERCROWDED ||
            rmeta.error_code() == TERR_STALE_EPOCH) {
            // The handler never ran — a priority-aware shed, a socket
            // too crowded to enqueue the work, or an epoch fence
            // refusing a stale zero-copy reference. Route through the
            // ERROR funnel (we hold the id lock — HandleError's
            // contract) so the standard retry machinery applies: budget
            // token spent, backoff honored, LB re-selects via
            // ExcludedServers; a stale-epoch re-issue re-arms the lease
            // and restamps the current pool generation. Without the
            // OVERCROWDED arm a server-side pushback that is_retryable
            // says to retry was terminal anyway — a degraded node's
            // refusals became lost completions instead of re-routes.
            if (rmeta.error_code() == TERR_OVERLOAD &&
                rmeta.has_backoff_ms()) {
                cntl->set_suggested_backoff_ms(rmeta.backoff_ms());
            }
            cntl->HandleError(cid, rmeta.error_code());
            return;
        }
        cntl->SetFailed(rmeta.error_code(), "%s", rmeta.error_text().c_str());
        cntl->EndRPC(cid);
        return;
    }
    if (meta.has_body_checksum() &&
        crc32c_iobuf(0, msg->body) != meta.body_checksum()) {
        ack_dropped_descriptor();  // corrupt response: view never taken
        cntl->SetFailed(TERR_RESPONSE, "response body checksum mismatch");
        cntl->EndRPC(cid);
        return;
    }
    // Split payload/attachment and deserialize.
    const uint32_t att_size = meta.attachment_size();
    if ((size_t)att_size > msg->body.size()) {
        ack_dropped_descriptor();  // malformed response: view never taken
        cntl->SetFailed(TERR_RESPONSE, "attachment_size %u > body %zu",
                        att_size, msg->body.size());
        cntl->EndRPC(cid);
        return;
    }
    IOBuf payload;
    msg->body.cutn(&payload, msg->body.size() - att_size);
    cntl->response_attachment().clear();
    cntl->response_attachment().swap(msg->body);
    if (meta.compress_type() != COMPRESS_NONE) {
        IOBuf raw;
        if (!DecompressBody(meta.compress_type(), payload, &raw)) {
            ack_dropped_descriptor();  // failing call: view never taken
            cntl->SetFailed(TERR_RESPONSE, "decompress response failed");
            cntl->EndRPC(cid);
            return;
        }
        payload.swap(raw);
    }
    // Response-direction descriptor (ISSUE 12): the server answered with
    // a reference into ITS registered pool — resolve it against the
    // mapping this connection's handshake made of that pool, fence the
    // epoch, verify the crc, and hand user code the in-place view with
    // zero inline payload bytes. Scope is the Transport seam's verdict:
    // only the handshake-mapped pool (or our own, on an in-process
    // link) resolves. Every never-will-read path acks immediately so
    // the server's pin frees without waiting for the reaper.
    if (rmeta.has_pool_attachment()) {
        const auto& pd = rmeta.pool_attachment();
        SocketUniquePtr ds;
        const bool have_sock =
            Socket::AddressSocket(msg->socket_id, &ds) == 0;
        const char* pool_base = nullptr;
        size_t pool_size = 0;
        uint64_t map_epoch = 0;
        if (!have_sock ||
            !TransportDescriptorScopeOk(ds.get(), pd.pool_id()) ||
            !pool_registry::Resolve(pd.pool_id(), &pool_base, &pool_size,
                                    &map_epoch) ||
            pd.offset() > pool_size ||
            pd.length() > pool_size - pd.offset()) {
            rsp_desc::CountReject();
            SendTpuStdDescAck(msg->socket_id, cid, pd.ack_token());
            cntl->SetFailed(TERR_RESPONSE,
                            "unresolvable response pool descriptor "
                            "(server pool not mapped on this link, or "
                            "out of bounds)");
            cntl->EndRPC(cid);
            return;
        }
        // Epoch fence BEFORE the crc read — the symmetric twin of the
        // request direction: a stale generation may point at recycled
        // bytes; fail ONLY this call with the retriable error (the
        // re-handshake under the retry refreshes the mapping).
        if (pd.has_pool_epoch() && pd.pool_epoch() != 0 &&
            pd.pool_epoch() != map_epoch) {
            rsp_desc::CountReject();
            SendTpuStdDescAck(msg->socket_id, cid, pd.ack_token());
            cntl->HandleError(cid, TERR_STALE_EPOCH);
            return;
        }
        if (pd.has_crc32c() &&
            crc32c_extend(0, pool_base + pd.offset(), pd.length()) !=
                pd.crc32c()) {
            rsp_desc::CountReject();
            SendTpuStdDescAck(msg->socket_id, cid, pd.ack_token());
            cntl->SetFailed(TERR_RESPONSE,
                            "response pool descriptor crc32c mismatch");
            cntl->EndRPC(cid);
            return;
        }
        Controller::PoolAttachment view;
        view.data = pool_base + pd.offset();
        view.length = pd.length();
        view.pool_id = pd.pool_id();
        view.offset = pd.offset();
        view.crc32c = pd.crc32c();
        view.pool_epoch = pd.pool_epoch();
        view.ack_token = pd.ack_token();
        cntl->SetResponsePoolAttachmentView(view, msg->socket_id, cid);
        rsp_desc::CountResolve((int64_t)pd.length());
        // The logical bytes are this connection's data-plane
        // throughput even though they never crossed the fd/ring.
        ds->add_descriptor_bytes_read((int64_t)pd.length());
        transport_stats::AddDescIn(ds->transport_tier(),
                                   (int64_t)pd.length());
    }
    if (cntl->response_ != nullptr &&
        !ParsePbFromIOBuf(cntl->response_, payload)) {
        cntl->SetFailed(TERR_RESPONSE, "parse response failed");
    }
    // Stream establishment: the server accepted (its settings ride the
    // response meta) — bind the client stream to this connection. Any
    // not-bound stream (including the early-return error paths above) is
    // failed centrally by EndRPC.
    if (cntl->request_stream() != INVALID_VREF_ID && !cntl->Failed() &&
        meta.has_stream_settings()) {
        if (stream_internal::ConnectClientStream(
                cntl->request_stream(), msg->socket_id,
                meta.stream_settings().stream_id(),
                meta.stream_settings().window_size()) == 0) {
            cntl->set_request_stream_bound();
        }
    }
    cntl->EndRPC(cid);
}

void CompleteClientUnaryResponse(uint64_t cid, int error_code,
                                 const std::string& error_text,
                                 IOBuf* payload_pb) {
    void* data = nullptr;
    if (id_lock_range(cid, &data) != 0) {
        return;  // finished or stale beyond the live range: drop
    }
    Controller* cntl = (Controller*)data;
    if (cid != cntl->current_cid_ && cid != cntl->unfinished_cid_) {
        id_unlock(cid);  // an abandoned try's late response
        return;
    }
    // Hedge winner normalization — the h2 twin of the tpu_std path.
    if (cid == cntl->unfinished_cid_) {
        std::swap(cntl->current_cid_, cntl->unfinished_cid_);
        std::swap(cntl->current_fly_sid_, cntl->unfinished_fly_sid_);
        std::swap(cntl->current_server_id_, cntl->unfinished_server_id_);
    } else if (cntl->unfinished_cid_ != INVALID_CALL_ID) {
        cntl->backup_won_ = true;
    }
    if (cntl->span_ != nullptr) {
        cntl->span_->received_us = monotonic_time_us();
        cntl->span_->response_bytes =
            payload_pb != nullptr ? (int64_t)payload_pb->size() : 0;
    }
    if (cid == cntl->current_cid_ &&
        cntl->current_fly_sid_ != INVALID_VREF_ID) {
        cntl->reusable_fly_sid_ = cntl->current_fly_sid_;
        cntl->current_fly_sid_ = INVALID_VREF_ID;
    } else if (cid == cntl->unfinished_cid_ &&
               cntl->unfinished_fly_sid_ != INVALID_VREF_ID) {
        cntl->reusable_fly_sid_ = cntl->unfinished_fly_sid_;
        cntl->unfinished_fly_sid_ = INVALID_VREF_ID;
    }
    if (error_code != 0) {
        cntl->SetFailed(error_code, "%s", error_text.c_str());
    } else if (cntl->response_ != nullptr && payload_pb != nullptr &&
               !ParsePbFromIOBuf(cntl->response_, *payload_pb)) {
        cntl->SetFailed(TERR_RESPONSE, "parse response failed");
    }
    cntl->EndRPC(cid);
}

}  // namespace tpurpc
