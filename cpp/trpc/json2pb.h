// json <-> protobuf transcoding for HTTP-as-RPC.
//
// Reference: src/json2pb/ (json_to_pb.{h,cpp}, pb_to_json.{h,cpp}, ~2k LoC
// of rapidjson glue). The modern protobuf runtime ships the same
// capability as util/json_util; wrapping it keeps the surface identical
// while dropping the hand-rolled codec.
#pragma once

#include <google/protobuf/message.h>

#include <string>

namespace tpurpc {

// Lenient parse (unknown json fields ignored, like the reference's
// json2pb). Returns false with *error set on malformed json / type
// mismatches.
bool JsonToPb(const std::string& json, google::protobuf::Message* msg,
              std::string* error);

// Serialize with original proto field names (not lowerCamel).
bool PbToJson(const google::protobuf::Message& msg, std::string* json,
              std::string* error);

}  // namespace tpurpc
