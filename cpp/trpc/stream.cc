#include "trpc/stream.h"

#include <arpa/inet.h>

#include <cerrno>
#include <cstring>
#include <mutex>

#include "tbase/errno.h"
#include "tbase/logging.h"
#include "tbase/time.h"
#include "tfiber/butex.h"
#include "tfiber/execution_queue.h"
#include "tnet/input_messenger.h"
#include "tnet/socket.h"
#include "trpc/controller.h"

namespace tpurpc {

namespace {

// STRM frame: magic + u32 payload_size + u64 stream_id + u8 type.
constexpr char kStreamMagic[4] = {'S', 'T', 'R', 'M'};
constexpr size_t kStreamHeaderLen = 4 + 4 + 8 + 1;

enum FrameType : uint8_t {
    FRAME_DATA = 0,
    FRAME_FEEDBACK = 1,
    FRAME_CLOSE = 2,
};

void PackStreamFrame(IOBuf* out, uint64_t peer_stream_id, uint8_t type,
                     IOBuf* payload) {
    char header[kStreamHeaderLen];
    memcpy(header, kStreamMagic, 4);
    const uint32_t size = htonl((uint32_t)(payload ? payload->size() : 0));
    memcpy(header + 4, &size, 4);
    // stream_id rides little-endian (TPU-VM hosts are homogeneous x86/arm
    // LE; revisit with a cross-arch DCN transport).
    memcpy(header + 8, &peer_stream_id, 8);
    header[16] = (char)type;
    out->append(header, kStreamHeaderLen);
    if (payload != nullptr) out->append(std::move(*payload));
}

}  // namespace

// The stream object. Addressed by versioned StreamId; one per direction
// endpoint (each side of a stream has its own).
class Stream : public VersionedRefWithId<Stream> {
public:
    void OnFailed();
    void OnRecycle();

    // ---- configuration ----
    StreamOptions options;

    // ---- connection binding ----
    std::atomic<VRefId> host_socket{INVALID_VREF_ID};
    std::atomic<uint64_t> peer_stream_id{0};
    std::atomic<bool> connected{false};

    // ---- write-side flow control ----
    std::atomic<int64_t> peer_window{2 * 1024 * 1024};
    std::atomic<int64_t> written_bytes{0};
    std::atomic<int64_t> peer_consumed{0};  // from FEEDBACK frames
    void* writable_butex = nullptr;

    // ---- read side ----
    ExecutionQueue<IOBuf>* rx_queue = nullptr;
    std::atomic<int64_t> delivered_bytes{0};
    std::atomic<int64_t> feedback_sent_at{0};
    std::atomic<bool> close_seen{false};

    int64_t writable_budget() const {
        return peer_window.load(std::memory_order_relaxed) -
               (written_bytes.load(std::memory_order_relaxed) -
                peer_consumed.load(std::memory_order_acquire));
    }

    void WakeWriters() {
        butex_word(writable_butex)->fetch_add(1, std::memory_order_release);
        butex_wake_all(writable_butex);
    }

    void SendFrameToPeer(uint8_t type, IOBuf* payload);
    static int RxConsume(void* meta, ExecutionQueue<IOBuf>::TaskIterator& it);
};

using StreamUniquePtr = VRefPtr<Stream>;

void Stream::SendFrameToPeer(uint8_t type, IOBuf* payload) {
    const VRefId sid = host_socket.load(std::memory_order_acquire);
    if (sid == INVALID_VREF_ID) return;
    SocketUniquePtr s;
    if (Socket::AddressSocket(sid, &s) != 0) return;
    IOBuf frame;
    PackStreamFrame(&frame, peer_stream_id.load(std::memory_order_relaxed),
                    type, payload);
    s->Write(&frame);
}

// ExecutionQueue consumer: deliver batches to the handler, then send
// window feedback when enough was consumed (reference SendFeedback
// stream.cpp:631 — consumption IS handler return here).
int Stream::RxConsume(void* meta, ExecutionQueue<IOBuf>::TaskIterator& it) {
    Stream* st = (Stream*)meta;
    int64_t batch_bytes = 0;
    std::vector<IOBuf*> batch;
    while (it) {
        batch.clear();
        for (; it && batch.size() < st->options.messages_in_batch; ++it) {
            batch.push_back(&*it);
            batch_bytes += (int64_t)it->size();
        }
        if (!batch.empty() && st->options.handler != nullptr) {
            st->options.handler->on_received_messages(st->vref_id(),
                                                      batch.data(),
                                                      batch.size());
        }
    }
    const int64_t delivered =
        st->delivered_bytes.fetch_add(batch_bytes,
                                      std::memory_order_relaxed) +
        batch_bytes;
    // Feedback once half a window has been consumed since the last one.
    const int64_t last = st->feedback_sent_at.load(std::memory_order_relaxed);
    if (delivered - last >= st->options.window_size / 2) {
        st->feedback_sent_at.store(delivered, std::memory_order_relaxed);
        IOBuf fb;
        int64_t be = delivered;
        fb.append(&be, sizeof(be));
        st->SendFrameToPeer(FRAME_FEEDBACK, &fb);
    }
    if (it.is_queue_stopped()) {
        if (st->options.handler != nullptr) {
            st->options.handler->on_closed(st->vref_id());
        }
        // Balances the ref held by the rx queue (taken at stream setup).
        st->Dereference();
    }
    return 0;
}

void Stream::OnFailed() {
    connected.store(false, std::memory_order_release);
    WakeWriters();
    if (rx_queue != nullptr) {
        rx_queue->stop();  // drains, then delivers the stopped iteration
    }
}

void Stream::OnRecycle() {
    // Two-party release (ExecutionQueue::release): deletion happens only
    // after BOTH the stop-delivering consumer run finished touching the
    // queue AND no stream ref (hence no late execute()) remains — recycle
    // is exactly that point on the stream side. (Previously leaked here;
    // the reference solves the same lifetime with pooled versioned
    // execution-queue ids, bthread/execution_queue.h.)
    if (rx_queue != nullptr) {
        rx_queue->release();
        rx_queue = nullptr;
    }
    if (writable_butex != nullptr) {
        butex_destroy(writable_butex);
        writable_butex = nullptr;
    }
    options = StreamOptions();
    host_socket.store(INVALID_VREF_ID, std::memory_order_relaxed);
    peer_stream_id.store(0, std::memory_order_relaxed);
    connected.store(false, std::memory_order_relaxed);
    written_bytes.store(0, std::memory_order_relaxed);
    peer_consumed.store(0, std::memory_order_relaxed);
    delivered_bytes.store(0, std::memory_order_relaxed);
    feedback_sent_at.store(0, std::memory_order_relaxed);
    close_seen.store(false, std::memory_order_relaxed);
}

namespace {

int NewStream(StreamId* id, const StreamOptions* options) {
    Stream* st = nullptr;
    if (Stream::Create(id, &st) != 0) return -1;
    if (options != nullptr) st->options = *options;
    if (st->writable_butex == nullptr) st->writable_butex = butex_create();
    st->rx_queue = new ExecutionQueue<IOBuf>();
    st->rx_queue->enable_self_release();
    st->rx_queue->start(&Stream::RxConsume, st);
    // The rx queue's stopped-iteration callback dereferences this ref.
    Stream* self = Stream::Address(*id);
    CHECK(self != nullptr);
    return 0;
}

}  // namespace

int StreamCreate(StreamId* id, Controller* cntl,
                 const StreamOptions* options) {
    if (id == nullptr || cntl == nullptr) {
        errno = EINVAL;
        return -1;
    }
    if (NewStream(id, options) != 0) return -1;
    Stream* st;
    {
        StreamUniquePtr ptr = StreamUniquePtr::FromId(*id);
        st = ptr.get();
        CHECK(st != nullptr);
    }
    cntl->set_request_stream(*id, st->options.window_size);
    return 0;
}

int StreamAccept(StreamId* id, Controller* cntl,
                 const StreamOptions* options) {
    if (id == nullptr || cntl == nullptr || !cntl->has_remote_stream()) {
        errno = EINVAL;
        return -1;
    }
    if (NewStream(id, options) != 0) return -1;
    StreamUniquePtr ptr = StreamUniquePtr::FromId(*id);
    Stream* st = ptr.get();
    st->host_socket.store(cntl->server_socket(), std::memory_order_release);
    st->peer_stream_id.store(cntl->remote_stream_id(),
                             std::memory_order_relaxed);
    st->peer_window.store(cntl->remote_stream_window(),
                          std::memory_order_relaxed);
    st->connected.store(true, std::memory_order_release);
    cntl->set_accepted_stream(*id, st->options.window_size);
    return 0;
}

int StreamWrite(StreamId id, IOBuf* data) {
    // errno is assigned AFTER the VRefPtr releases: dropping the last ref
    // runs the recycle chain, whose frees may clobber errno between the
    // assignment and the caller's read.
    int err = 0;
    {
        StreamUniquePtr ptr = StreamUniquePtr::FromId(id);
        Stream* st = ptr.get();
        if (st == nullptr) {
            err = EINVAL;
        } else if (!st->connected.load(std::memory_order_acquire)) {
            err = st->close_seen.load(std::memory_order_relaxed) ? EPIPE
                                                                 : EAGAIN;
        } else if (st->writable_budget() < (int64_t)data->size()) {
            err = EAGAIN;
        } else {
            st->written_bytes.fetch_add((int64_t)data->size(),
                                        std::memory_order_relaxed);
            st->SendFrameToPeer(FRAME_DATA, data);
        }
    }
    if (err != 0) {
        errno = err;
        return -1;
    }
    return 0;
}

int StreamWait(StreamId id, int64_t abstime_us) {
    while (true) {
        int err = 0;
        bool timed_out = false;
        {
            StreamUniquePtr ptr = StreamUniquePtr::FromId(id);
            Stream* st = ptr.get();
            if (st == nullptr) {
                err = EINVAL;
            } else {
                std::atomic<int>* word = butex_word(st->writable_butex);
                const int expected =
                    word->load(std::memory_order_acquire);
                if (!st->connected.load(std::memory_order_acquire)) {
                    err = EPIPE;
                } else if (st->writable_budget() > 0) {
                    return 0;
                } else {
                    const int64_t abst =
                        abstime_us > 0
                            ? abstime_us
                            : monotonic_time_us() + (int64_t)3600e6;
                    const int rc =
                        butex_wait(st->writable_butex, expected, &abst);
                    timed_out = rc == ETIMEDOUT && abstime_us > 0;
                }
            }
        }
        // Error code returned DIRECTLY (errno set best-effort only): the
        // fiber may have resumed on another worker, where the caller's
        // possibly-CSE'd errno location is the wrong thread's.
        if (err != 0) {
            errno = err;
            return err;
        }
        if (timed_out) {
            errno = ETIMEDOUT;
            return ETIMEDOUT;
        }
    }
}

int StreamClose(StreamId id) {
    StreamUniquePtr ptr = StreamUniquePtr::FromId(id);
    Stream* st = ptr.get();
    if (st == nullptr) {
        errno = EINVAL;
        return -1;
    }
    st->SendFrameToPeer(FRAME_CLOSE, nullptr);
    ptr.reset();
    Stream::SetFailedById(id);
    return 0;
}

// ---------------- internals ----------------

namespace stream_internal {

int ConnectClientStream(StreamId id, VRefId socket_id, uint64_t peer_id,
                        int64_t peer_window) {
    StreamUniquePtr ptr = StreamUniquePtr::FromId(id);
    Stream* st = ptr.get();
    if (st == nullptr) return -1;
    st->host_socket.store(socket_id, std::memory_order_release);
    st->peer_stream_id.store(peer_id, std::memory_order_relaxed);
    if (peer_window > 0) {
        st->peer_window.store(peer_window, std::memory_order_relaxed);
    }
    st->connected.store(true, std::memory_order_release);
    st->WakeWriters();
    return 0;
}

void FailStream(StreamId id) { Stream::SetFailedById(id); }

void OnStreamData(uint64_t stream_id, IOBuf* payload) {
    StreamUniquePtr ptr = StreamUniquePtr::FromId(stream_id);
    Stream* st = ptr.get();
    if (st == nullptr) return;
    if (st->rx_queue != nullptr) {
        st->rx_queue->execute(std::move(*payload));
    }
}

void OnStreamFeedback(uint64_t stream_id, int64_t consumed) {
    StreamUniquePtr ptr = StreamUniquePtr::FromId(stream_id);
    Stream* st = ptr.get();
    if (st == nullptr) return;
    int64_t cur = st->peer_consumed.load(std::memory_order_relaxed);
    while (consumed > cur &&
           !st->peer_consumed.compare_exchange_weak(
               cur, consumed, std::memory_order_release)) {
    }
    st->WakeWriters();
}

void OnStreamClose(uint64_t stream_id) {
    {
        StreamUniquePtr ptr = StreamUniquePtr::FromId(stream_id);
        Stream* st = ptr.get();
        if (st == nullptr) return;
        st->close_seen.store(true, std::memory_order_relaxed);
    }
    Stream::SetFailedById(stream_id);
}

// ---------------- STRM wire protocol ----------------

namespace {

struct StreamFrameMessage : public InputMessageBase {
    uint64_t stream_id = 0;
    uint8_t type = FRAME_DATA;
    IOBuf payload;
};

ParseResult ParseStreamFrame(IOBuf* source, Socket* socket, bool read_eof,
                             const void* arg) {
    (void)socket;
    (void)read_eof;
    (void)arg;
    if (source->size() < kStreamHeaderLen) {
        char head[4];
        const size_t n = source->copy_to(head, 4);
        if (memcmp(head, kStreamMagic, n) != 0) {
            return ParseResult::make(ParseError::TRY_OTHERS);
        }
        return ParseResult::make(ParseError::NOT_ENOUGH_DATA);
    }
    char header[kStreamHeaderLen];
    source->copy_to(header, kStreamHeaderLen);
    if (memcmp(header, kStreamMagic, 4) != 0) {
        return ParseResult::make(ParseError::TRY_OTHERS);
    }
    uint32_t payload_size;
    memcpy(&payload_size, header + 4, 4);
    payload_size = ntohl(payload_size);
    if (payload_size > (64u << 20)) {
        return ParseResult::make(ParseError::ERROR);
    }
    if (source->size() < kStreamHeaderLen + payload_size) {
        return ParseResult::make(ParseError::NOT_ENOUGH_DATA);
    }
    auto* msg = new StreamFrameMessage;
    memcpy(&msg->stream_id, header + 8, 8);
    msg->type = (uint8_t)header[16];
    source->pop_front(kStreamHeaderLen);
    source->cutn(&msg->payload, payload_size);
    return ParseResult::make_ok(msg);
}

void ProcessStreamFrame(InputMessageBase* raw) {
    std::unique_ptr<StreamFrameMessage> msg((StreamFrameMessage*)raw);
    switch (msg->type) {
        case FRAME_DATA:
            OnStreamData(msg->stream_id, &msg->payload);
            break;
        case FRAME_FEEDBACK: {
            int64_t consumed = 0;
            if (msg->payload.size() >= sizeof(consumed)) {
                msg->payload.copy_to(&consumed, sizeof(consumed));
                OnStreamFeedback(msg->stream_id, consumed);
            }
            break;
        }
        case FRAME_CLOSE:
            OnStreamClose(msg->stream_id);
            break;
        default:
            break;
    }
}

int g_stream_protocol_index = -1;

}  // namespace

void RegisterStreamProtocolOrDie() {
    static std::once_flag once;
    std::call_once(once, [] {
        Protocol p;
        p.parse = ParseStreamFrame;
        p.process = ProcessStreamFrame;
        p.name = "tpu_strm";
        // STRM frames have no correlation ids: delivery order IS frame
        // order, so processing must stay on the input fiber (a fiber per
        // frame could enqueue the burst's last frame before its first).
        // Cheap anyway: process just pushes into the stream's
        // ExecutionQueue.
        p.process_in_order = true;
        g_stream_protocol_index = RegisterProtocol(p);
    });
}

int StreamProtocolIndex() { return g_stream_protocol_index; }

}  // namespace stream_internal

}  // namespace tpurpc
