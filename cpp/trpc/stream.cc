#include "trpc/stream.h"

#include <arpa/inet.h>

#include <algorithm>
#include <cerrno>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <sstream>
#include <unordered_map>
#include <utility>
#include <vector>

#include "tbase/errno.h"
#include "tbase/fast_rand.h"
#include "tbase/flags.h"
#include "tbase/flight_recorder.h"
#include "tbase/logging.h"
#include "tbase/time.h"
#include "tfiber/butex.h"
#include "tfiber/execution_queue.h"
#include "tfiber/fiber.h"
#include "tnet/fault_injection.h"
#include "tnet/input_messenger.h"
#include "tnet/socket.h"
#include "trpc/controller.h"
#include "trpc/policy_tpu_std.h"
#include "tvar/latency_recorder.h"
#include "tvar/reducer.h"

namespace tpurpc {

namespace {

// STRM frame: magic + u32 payload_size + u64 stream_id + u8 type.
constexpr char kStreamMagic[4] = {'S', 'T', 'R', 'M'};
constexpr size_t kStreamHeaderLen = 4 + 4 + 8 + 1;

enum FrameType : uint8_t {
    FRAME_DATA = 0,
    FRAME_FEEDBACK = 1,
    FRAME_CLOSE = 2,
};

void PackStreamFrame(IOBuf* out, uint64_t peer_stream_id, uint8_t type,
                     IOBuf* payload) {
    char header[kStreamHeaderLen];
    memcpy(header, kStreamMagic, 4);
    const uint32_t size = htonl((uint32_t)(payload ? payload->size() : 0));
    memcpy(header + 4, &size, 4);
    // stream_id rides little-endian (TPU-VM hosts are homogeneous x86/arm
    // LE; revisit with a cross-arch DCN transport).
    memcpy(header + 8, &peer_stream_id, 8);
    header[16] = (char)type;
    out->append(header, kStreamHeaderLen);
    if (payload != nullptr) out->append(std::move(*payload));
}

}  // namespace

// The stream object. Addressed by versioned StreamId; one per direction
// endpoint (each side of a stream has its own).
class Stream : public VersionedRefWithId<Stream> {
public:
    void OnFailed();
    void OnRecycle();

    // ---- configuration ----
    StreamOptions options;

    // ---- connection binding ----
    std::atomic<VRefId> host_socket{INVALID_VREF_ID};
    std::atomic<uint64_t> peer_stream_id{0};
    std::atomic<bool> connected{false};

    // ---- write-side flow control ----
    std::atomic<int64_t> peer_window{2 * 1024 * 1024};
    std::atomic<int64_t> written_bytes{0};
    std::atomic<int64_t> peer_consumed{0};  // from FEEDBACK frames
    void* writable_butex = nullptr;

    // ---- read side ----
    ExecutionQueue<IOBuf>* rx_queue = nullptr;
    std::atomic<int64_t> delivered_bytes{0};
    std::atomic<int64_t> feedback_sent_at{0};
    std::atomic<bool> close_seen{false};

    int64_t writable_budget() const {
        return peer_window.load(std::memory_order_relaxed) -
               (written_bytes.load(std::memory_order_relaxed) -
                peer_consumed.load(std::memory_order_acquire));
    }

    void WakeWriters() {
        butex_word(writable_butex)->fetch_add(1, std::memory_order_release);
        butex_wake_all(writable_butex);
    }

    void SendFrameToPeer(uint8_t type, IOBuf* payload);
    static int RxConsume(void* meta, ExecutionQueue<IOBuf>::TaskIterator& it);
};

using StreamUniquePtr = VRefPtr<Stream>;

void Stream::SendFrameToPeer(uint8_t type, IOBuf* payload) {
    const VRefId sid = host_socket.load(std::memory_order_acquire);
    if (sid == INVALID_VREF_ID) return;
    SocketUniquePtr s;
    if (Socket::AddressSocket(sid, &s) != 0) return;
    IOBuf frame;
    PackStreamFrame(&frame, peer_stream_id.load(std::memory_order_relaxed),
                    type, payload);
    s->Write(&frame);
}

// ExecutionQueue consumer: deliver batches to the handler, then send
// window feedback when enough was consumed (reference SendFeedback
// stream.cpp:631 — consumption IS handler return here).
int Stream::RxConsume(void* meta, ExecutionQueue<IOBuf>::TaskIterator& it) {
    Stream* st = (Stream*)meta;
    int64_t batch_bytes = 0;
    std::vector<IOBuf*> batch;
    while (it) {
        batch.clear();
        for (; it && batch.size() < st->options.messages_in_batch; ++it) {
            batch.push_back(&*it);
            batch_bytes += (int64_t)it->size();
        }
        if (!batch.empty() && st->options.handler != nullptr) {
            st->options.handler->on_received_messages(st->vref_id(),
                                                      batch.data(),
                                                      batch.size());
        }
    }
    const int64_t delivered =
        st->delivered_bytes.fetch_add(batch_bytes,
                                      std::memory_order_relaxed) +
        batch_bytes;
    // Feedback once half a window has been consumed since the last one.
    const int64_t last = st->feedback_sent_at.load(std::memory_order_relaxed);
    if (delivered - last >= st->options.window_size / 2) {
        st->feedback_sent_at.store(delivered, std::memory_order_relaxed);
        IOBuf fb;
        int64_t be = delivered;
        fb.append(&be, sizeof(be));
        st->SendFrameToPeer(FRAME_FEEDBACK, &fb);
    }
    if (it.is_queue_stopped()) {
        if (st->options.handler != nullptr) {
            st->options.handler->on_closed(st->vref_id());
        }
        // Balances the ref held by the rx queue (taken at stream setup).
        st->Dereference();
    }
    return 0;
}

void Stream::OnFailed() {
    connected.store(false, std::memory_order_release);
    WakeWriters();
    if (rx_queue != nullptr) {
        rx_queue->stop();  // drains, then delivers the stopped iteration
    }
}

void Stream::OnRecycle() {
    // Two-party release (ExecutionQueue::release): deletion happens only
    // after BOTH the stop-delivering consumer run finished touching the
    // queue AND no stream ref (hence no late execute()) remains — recycle
    // is exactly that point on the stream side. (Previously leaked here;
    // the reference solves the same lifetime with pooled versioned
    // execution-queue ids, bthread/execution_queue.h.)
    if (rx_queue != nullptr) {
        rx_queue->release();
        rx_queue = nullptr;
    }
    if (writable_butex != nullptr) {
        butex_destroy(writable_butex);
        writable_butex = nullptr;
    }
    options = StreamOptions();
    host_socket.store(INVALID_VREF_ID, std::memory_order_relaxed);
    peer_stream_id.store(0, std::memory_order_relaxed);
    connected.store(false, std::memory_order_relaxed);
    written_bytes.store(0, std::memory_order_relaxed);
    peer_consumed.store(0, std::memory_order_relaxed);
    delivered_bytes.store(0, std::memory_order_relaxed);
    feedback_sent_at.store(0, std::memory_order_relaxed);
    close_seen.store(false, std::memory_order_relaxed);
}

namespace {

int NewStream(StreamId* id, const StreamOptions* options) {
    Stream* st = nullptr;
    if (Stream::Create(id, &st) != 0) return -1;
    if (options != nullptr) st->options = *options;
    if (st->writable_butex == nullptr) st->writable_butex = butex_create();
    st->rx_queue = new ExecutionQueue<IOBuf>();
    st->rx_queue->enable_self_release();
    st->rx_queue->start(&Stream::RxConsume, st);
    // The rx queue's stopped-iteration callback dereferences this ref.
    Stream* self = Stream::Address(*id);
    CHECK(self != nullptr);
    return 0;
}

}  // namespace

int StreamCreate(StreamId* id, Controller* cntl,
                 const StreamOptions* options) {
    if (id == nullptr || cntl == nullptr) {
        errno = EINVAL;
        return -1;
    }
    if (NewStream(id, options) != 0) return -1;
    Stream* st;
    {
        StreamUniquePtr ptr = StreamUniquePtr::FromId(*id);
        st = ptr.get();
        CHECK(st != nullptr);
    }
    cntl->set_request_stream(*id, st->options.window_size);
    return 0;
}

int StreamAccept(StreamId* id, Controller* cntl,
                 const StreamOptions* options) {
    if (id == nullptr || cntl == nullptr || !cntl->has_remote_stream()) {
        errno = EINVAL;
        return -1;
    }
    if (NewStream(id, options) != 0) return -1;
    StreamUniquePtr ptr = StreamUniquePtr::FromId(*id);
    Stream* st = ptr.get();
    st->host_socket.store(cntl->server_socket(), std::memory_order_release);
    st->peer_stream_id.store(cntl->remote_stream_id(),
                             std::memory_order_relaxed);
    st->peer_window.store(cntl->remote_stream_window(),
                          std::memory_order_relaxed);
    st->connected.store(true, std::memory_order_release);
    cntl->set_accepted_stream(*id, st->options.window_size);
    return 0;
}

int StreamWrite(StreamId id, IOBuf* data) {
    // errno is assigned AFTER the VRefPtr releases: dropping the last ref
    // runs the recycle chain, whose frees may clobber errno between the
    // assignment and the caller's read.
    int err = 0;
    {
        StreamUniquePtr ptr = StreamUniquePtr::FromId(id);
        Stream* st = ptr.get();
        if (st == nullptr) {
            err = EINVAL;
        } else if (!st->connected.load(std::memory_order_acquire)) {
            err = st->close_seen.load(std::memory_order_relaxed) ? EPIPE
                                                                 : EAGAIN;
        } else if (st->writable_budget() < (int64_t)data->size()) {
            err = EAGAIN;
        } else {
            st->written_bytes.fetch_add((int64_t)data->size(),
                                        std::memory_order_relaxed);
            st->SendFrameToPeer(FRAME_DATA, data);
        }
    }
    if (err != 0) {
        errno = err;
        return -1;
    }
    return 0;
}

int StreamWait(StreamId id, int64_t abstime_us) {
    while (true) {
        int err = 0;
        bool timed_out = false;
        {
            StreamUniquePtr ptr = StreamUniquePtr::FromId(id);
            Stream* st = ptr.get();
            if (st == nullptr) {
                err = EINVAL;
            } else {
                std::atomic<int>* word = butex_word(st->writable_butex);
                const int expected =
                    word->load(std::memory_order_acquire);
                if (!st->connected.load(std::memory_order_acquire)) {
                    err = EPIPE;
                } else if (st->writable_budget() > 0) {
                    return 0;
                } else {
                    const int64_t abst =
                        abstime_us > 0
                            ? abstime_us
                            : monotonic_time_us() + (int64_t)3600e6;
                    const int rc =
                        butex_wait(st->writable_butex, expected, &abst);
                    timed_out = rc == ETIMEDOUT && abstime_us > 0;
                }
            }
        }
        // Error code returned DIRECTLY (errno set best-effort only): the
        // fiber may have resumed on another worker, where the caller's
        // possibly-CSE'd errno location is the wrong thread's.
        if (err != 0) {
            errno = err;
            return err;
        }
        if (timed_out) {
            errno = ETIMEDOUT;
            return ETIMEDOUT;
        }
    }
}

int StreamClose(StreamId id) {
    StreamUniquePtr ptr = StreamUniquePtr::FromId(id);
    Stream* st = ptr.get();
    if (st == nullptr) {
        errno = EINVAL;
        return -1;
    }
    st->SendFrameToPeer(FRAME_CLOSE, nullptr);
    ptr.reset();
    Stream::SetFailedById(id);
    return 0;
}

// ---------------- internals ----------------

namespace stream_internal {

int ConnectClientStream(StreamId id, VRefId socket_id, uint64_t peer_id,
                        int64_t peer_window) {
    StreamUniquePtr ptr = StreamUniquePtr::FromId(id);
    Stream* st = ptr.get();
    if (st == nullptr) return -1;
    st->host_socket.store(socket_id, std::memory_order_release);
    st->peer_stream_id.store(peer_id, std::memory_order_relaxed);
    if (peer_window > 0) {
        st->peer_window.store(peer_window, std::memory_order_relaxed);
    }
    st->connected.store(true, std::memory_order_release);
    st->WakeWriters();
    return 0;
}

void FailStream(StreamId id) { Stream::SetFailedById(id); }

void OnStreamData(uint64_t stream_id, IOBuf* payload) {
    StreamUniquePtr ptr = StreamUniquePtr::FromId(stream_id);
    Stream* st = ptr.get();
    if (st == nullptr) return;
    if (st->rx_queue != nullptr) {
        st->rx_queue->execute(std::move(*payload));
    }
}

void OnStreamFeedback(uint64_t stream_id, int64_t consumed) {
    StreamUniquePtr ptr = StreamUniquePtr::FromId(stream_id);
    Stream* st = ptr.get();
    if (st == nullptr) return;
    int64_t cur = st->peer_consumed.load(std::memory_order_relaxed);
    while (consumed > cur &&
           !st->peer_consumed.compare_exchange_weak(
               cur, consumed, std::memory_order_release)) {
    }
    st->WakeWriters();
}

void OnStreamClose(uint64_t stream_id) {
    {
        StreamUniquePtr ptr = StreamUniquePtr::FromId(stream_id);
        Stream* st = ptr.get();
        if (st == nullptr) return;
        st->close_seen.store(true, std::memory_order_relaxed);
    }
    Stream::SetFailedById(stream_id);
}

// ---------------- STRM wire protocol ----------------

namespace {

struct StreamFrameMessage : public InputMessageBase {
    uint64_t stream_id = 0;
    uint8_t type = FRAME_DATA;
    IOBuf payload;
};

ParseResult ParseStreamFrame(IOBuf* source, Socket* socket, bool read_eof,
                             const void* arg) {
    (void)socket;
    (void)read_eof;
    (void)arg;
    if (source->size() < kStreamHeaderLen) {
        char head[4];
        const size_t n = source->copy_to(head, 4);
        if (memcmp(head, kStreamMagic, n) != 0) {
            return ParseResult::make(ParseError::TRY_OTHERS);
        }
        return ParseResult::make(ParseError::NOT_ENOUGH_DATA);
    }
    char header[kStreamHeaderLen];
    source->copy_to(header, kStreamHeaderLen);
    if (memcmp(header, kStreamMagic, 4) != 0) {
        return ParseResult::make(ParseError::TRY_OTHERS);
    }
    uint32_t payload_size;
    memcpy(&payload_size, header + 4, 4);
    payload_size = ntohl(payload_size);
    if (payload_size > (64u << 20)) {
        return ParseResult::make(ParseError::ERROR);
    }
    if (source->size() < kStreamHeaderLen + payload_size) {
        return ParseResult::make(ParseError::NOT_ENOUGH_DATA);
    }
    auto* msg = new StreamFrameMessage;
    memcpy(&msg->stream_id, header + 8, 8);
    msg->type = (uint8_t)header[16];
    source->pop_front(kStreamHeaderLen);
    source->cutn(&msg->payload, payload_size);
    return ParseResult::make_ok(msg);
}

void ProcessStreamFrame(InputMessageBase* raw) {
    std::unique_ptr<StreamFrameMessage> msg((StreamFrameMessage*)raw);
    switch (msg->type) {
        case FRAME_DATA:
            OnStreamData(msg->stream_id, &msg->payload);
            break;
        case FRAME_FEEDBACK: {
            int64_t consumed = 0;
            if (msg->payload.size() >= sizeof(consumed)) {
                msg->payload.copy_to(&consumed, sizeof(consumed));
                OnStreamFeedback(msg->stream_id, consumed);
            }
            break;
        }
        case FRAME_CLOSE:
            OnStreamClose(msg->stream_id);
            break;
        default:
            break;
    }
}

int g_stream_protocol_index = -1;

}  // namespace

void RegisterStreamProtocolOrDie() {
    static std::once_flag once;
    std::call_once(once, [] {
        Protocol p;
        p.parse = ParseStreamFrame;
        p.process = ProcessStreamFrame;
        p.name = "tpu_strm";
        // STRM frames have no correlation ids: delivery order IS frame
        // order, so processing must stay on the input fiber (a fiber per
        // frame could enqueue the burst's last frame before its first).
        // Cheap anyway: process just pushes into the stream's
        // ExecutionQueue.
        p.process_in_order = true;
        g_stream_protocol_index = RegisterProtocol(p);
    });
}

int StreamProtocolIndex() { return g_stream_protocol_index; }

}  // namespace stream_internal

// ===================== server-push streams (ISSUE 17) =====================

// Receiver-granted chunk credits announced in a push-stream open: the
// server may have at most this many unconsumed chunks toward the client.
DEFINE_int32(stream_rx_window, 32,
             "push-stream flow-control window, in chunks");
// Bounded per-stream replay ring of unacked chunks (memory backstop on
// top of the credit window; resumes replay from here).
DEFINE_int32(stream_replay_ring, 128,
             "push-stream replay ring capacity, in chunks");
// A server stream whose connection died is kept registered (awaiting a
// resume) for this long before the parked writer aborts.
DEFINE_int32(stream_registry_ttl_ms, 15000,
             "ms an unbound push-stream awaits a resume before aborting");

namespace push_stream {

namespace {

// ---- metrics (eagerly exposed 0-valued by ExposeVars) ----
LazyAdder g_opens("rpc_stream_open");
LazyAdder g_resumed("rpc_stream_resumed");
LazyAdder g_replayed("rpc_stream_replayed_chunks");
LazyAdder g_credit_stalls("rpc_stream_credit_stalls");
LazyAdder g_aborts("rpc_stream_aborts");

LatencyRecorder* ttft_recorder() {
    static LatencyRecorder* r = [] {
        auto* x = new LatencyRecorder;
        x->expose("rpc_stream_ttft_us");
        return x;
    }();
    return r;
}

// Process-wide replay-ring occupancy high-water (all streams).
std::atomic<int64_t> g_ring_hw{0};
void NoteRingSize(size_t n) {
    int64_t cur = g_ring_hw.load(std::memory_order_relaxed);
    while ((int64_t)n > cur &&
           !g_ring_hw.compare_exchange_weak(cur, (int64_t)n)) {
    }
}

// Retransmit pacing: min gap between ring replays for one stream, and
// max entries per replay burst.
constexpr int64_t kRetxMinGapUs = 20 * 1000;
constexpr size_t kRetxBurstCap = 64;
// Client-side NAK pacing (gap detected) and stall-probe period.
constexpr int64_t kNakMinGapUs = 20 * 1000;
constexpr int64_t kStallProbeUs = 150 * 1000;

}  // namespace

// Server half of one push stream. `mu` guards everything except the
// atomics; the writer fiber parks on `wbutex` while credits, ring space
// or a bound connection are missing.
//
// LOCK ORDER: g_srv_mu may take st->mu, NEVER the reverse — completion
// flags are collected under st->mu and the registry erase happens after
// release.
struct ServerStreamState {
    uint64_t id = 0;
    std::string session;       // sticky-session owner (resume identity)
    int64_t open_rx_window = 0;

    std::atomic<VRefId> socket{INVALID_VREF_ID};

    std::mutex mu;
    // Unacked chunks, ascending seq — the replay ring. Bounded by
    // ring_cap; normally bounded tighter by the credit window.
    std::deque<std::pair<uint64_t, std::string>> ring;
    uint64_t last_sent = 0;  // highest seq assigned
    uint64_t acked = 0;      // receiver's contiguous-arrival floor
    int64_t credits = 0;     // receiver-granted sends remaining
    uint64_t eos_seq = 0;    // 0 = not yet written
    uint64_t resume_from = 0;
    bool resumed_in_place = false;
    bool aborted = false;
    int error = 0;
    bool first_write_done = false;  // TTFT latch
    int64_t open_us = 0;
    int64_t last_retx_us = 0;
    int64_t unbound_since_us = 0;  // 0 = bound
    size_t ring_cap = 0;

    void* wbutex = nullptr;

    ServerStreamState() : wbutex(butex_create()) {}
    ~ServerStreamState() { butex_destroy(wbutex); }
    void Wake() {
        butex_word(wbutex)->fetch_add(1, std::memory_order_release);
        butex_wake_all(wbutex);
    }
};

// Client half: reorder + dedupe state for one logical stream across any
// number of resumes.
struct ReceiverState {
    uint64_t id = 0;
    std::atomic<VRefId> src_socket{INVALID_VREF_ID};

    std::mutex mu;
    std::map<uint64_t, std::string> pending;  // out-of-order arrivals
    std::deque<std::pair<uint64_t, std::string>> ready;  // contiguous
    uint64_t delivered = 0;  // last contiguous seq ARRIVED (ack floor)
    uint64_t read_upto = 0;  // last seq handed to Read
    uint64_t eos_seq = 0;
    uint64_t dups = 0;       // deduped arrivals (exactly-once proof)
    int close_error = 0;
    bool closed = false;
    int64_t rx_window = 0;
    int64_t consumed_since_grant = 0;
    int64_t last_nak_us = 0;
    int64_t last_progress_us = 0;

    void* rbutex = nullptr;

    ReceiverState() : rbutex(butex_create()) {}
    ~ReceiverState() { butex_destroy(rbutex); }
    void Wake() {
        butex_word(rbutex)->fetch_add(1, std::memory_order_release);
        butex_wake_all(rbutex);
    }
};

namespace {

std::mutex g_srv_mu;
std::unordered_map<uint64_t, std::shared_ptr<ServerStreamState>>&
ServerRegistry() {
    static auto* m =
        new std::unordered_map<uint64_t, std::shared_ptr<ServerStreamState>>;
    return *m;
}

std::mutex g_rx_mu;
std::unordered_map<uint64_t, std::shared_ptr<ReceiverState>>&
RxRegistry() {
    static auto* m =
        new std::unordered_map<uint64_t, std::shared_ptr<ReceiverState>>;
    return *m;
}

std::shared_ptr<ServerStreamState> FindServer(uint64_t id) {
    std::lock_guard<std::mutex> g(g_srv_mu);
    auto& reg = ServerRegistry();
    auto it = reg.find(id);
    return it == reg.end() ? nullptr : it->second;
}

std::shared_ptr<ReceiverState> FindReceiver(uint64_t id) {
    std::lock_guard<std::mutex> g(g_rx_mu);
    auto& reg = RxRegistry();
    auto it = reg.find(id);
    return it == reg.end() ? nullptr : it->second;
}

void UnregisterServer(uint64_t id) {
    std::lock_guard<std::mutex> g(g_srv_mu);
    ServerRegistry().erase(id);
}

// Mark st aborted (under its mu), wake the writer, best-effort CLOSE the
// peer. Caller unregisters.
void AbortLocked(const std::shared_ptr<ServerStreamState>& st, int err) {
    VRefId sid = INVALID_VREF_ID;
    {
        std::lock_guard<std::mutex> g(st->mu);
        if (st->aborted) return;
        st->aborted = true;
        st->error = err;
        sid = st->socket.load(std::memory_order_acquire);
    }
    *g_aborts << 1;
    if (sid != INVALID_VREF_ID) {
        SendTpuStdStreamClose(sid, st->id, err);
    }
    st->Wake();
}

}  // namespace

// Registry lookup keyed by stream_id, identity-checked by session:
//  - hit + same session  -> in-place resume: trim ring <= resume_from,
//    rebind deferred to Activate, the parked writer continues, the ring
//    replays — the ORIGINAL generator covers continuation.
//  - hit + other session -> stale owner: abort the old stream, fresh.
//  - miss                -> fresh; resume_from>0 means the process
//    restarted and the handler must REGENERATE from that offset.
std::shared_ptr<ServerStreamState> AcceptOpen(uint64_t id,
                                              const std::string& session,
                                              int64_t rx_window,
                                              uint64_t resume_from) {
    *g_opens << 1;
    const int64_t now = monotonic_time_us();
    std::shared_ptr<ServerStreamState> st;
    std::shared_ptr<ServerStreamState> stale;
    {
        std::lock_guard<std::mutex> g(g_srv_mu);
        auto& reg = ServerRegistry();
        auto it = reg.find(id);
        if (it != reg.end() && it->second->session == session) {
            st = it->second;
            std::lock_guard<std::mutex> g2(st->mu);
            st->socket.store(INVALID_VREF_ID, std::memory_order_release);
            st->credits = 0;
            st->unbound_since_us = now;
            st->resume_from = resume_from;
            st->resumed_in_place = true;
            st->open_rx_window = rx_window;
            if (resume_from > st->acked) st->acked = resume_from;
            while (!st->ring.empty() && st->ring.front().first <= st->acked) {
                st->ring.pop_front();
            }
            *g_resumed << 1;
            flight::Record(flight::kStreamResume, id, resume_from);
            return st;
        }
        if (it != reg.end()) {
            stale = it->second;  // session mismatch: new owner wins
            reg.erase(it);
        }
        st = std::make_shared<ServerStreamState>();
        st->id = id;
        st->session = session;
        st->open_rx_window = rx_window;
        st->last_sent = resume_from;
        st->acked = resume_from;
        st->resume_from = resume_from;
        st->resumed_in_place = false;
        st->open_us = now;
        st->unbound_since_us = now;
        st->ring_cap = (size_t)std::max<int32_t>(
            1, FLAGS_stream_replay_ring.get());
        reg[id] = st;
    }
    if (stale != nullptr) AbortLocked(stale, TERR_CLOSE);
    if (resume_from > 0) {
        *g_resumed << 1;
        flight::Record(flight::kStreamResume, id, resume_from);
    }
    return st;
}

void Activate(uint64_t stream_id, VRefId socket_id) {
    std::shared_ptr<ServerStreamState> st = FindServer(stream_id);
    if (st == nullptr) return;
    std::vector<std::pair<uint64_t, std::string>> replay;
    uint64_t eos_seq = 0;
    {
        std::lock_guard<std::mutex> g(st->mu);
        if (st->aborted) return;
        st->socket.store(socket_id, std::memory_order_release);
        st->credits = st->open_rx_window;
        st->unbound_since_us = 0;
        eos_seq = st->eos_seq;
        for (const auto& e : st->ring) {
            if (e.first > st->acked) replay.push_back(e);
        }
        st->credits -= (int64_t)replay.size();
    }
    for (const auto& e : replay) {
        *g_replayed << 1;
        SendTpuStdStreamData(socket_id, stream_id, e.first,
                             e.first == eos_seq ? kFlagEos : 0, e.second);
    }
    st->Wake();
}

void AbortServerStream(uint64_t stream_id, int error_code) {
    std::shared_ptr<ServerStreamState> st = FindServer(stream_id);
    if (st == nullptr) return;
    AbortLocked(st, error_code);
    UnregisterServer(stream_id);
}

// ---- StreamWriter ----

StreamWriter::StreamWriter(std::shared_ptr<ServerStreamState> st)
    : state_(std::move(st)) {}

uint64_t StreamWriter::stream_id() const {
    return state_ ? state_->id : 0;
}

uint64_t StreamWriter::resume_from() const {
    return state_ ? state_->resume_from : 0;
}

bool StreamWriter::resumed_in_place() const {
    return state_ != nullptr && state_->resumed_in_place;
}

uint64_t StreamWriter::last_seq() const {
    if (state_ == nullptr) return 0;
    std::lock_guard<std::mutex> g(state_->mu);
    return state_->last_sent;
}

int StreamWriter::Write(const std::string& chunk, bool eos) {
    if (state_ == nullptr) return TERR_INTERNAL;
    const std::shared_ptr<ServerStreamState>& st = state_;
    bool stall_counted = false;  // one credit_stall per park episode
    for (;;) {
        const int expected =
            butex_word(st->wbutex)->load(std::memory_order_acquire);
        VRefId sid = INVALID_VREF_ID;
        uint64_t seq = 0;
        uint32_t flags = 0;
        {
            std::lock_guard<std::mutex> g(st->mu);
            if (st->aborted) {
                return st->error != 0 ? st->error : TERR_CLOSE;
            }
            sid = st->socket.load(std::memory_order_acquire);
            if (sid != INVALID_VREF_ID && st->credits > 0 &&
                st->ring.size() < st->ring_cap) {
                seq = ++st->last_sent;
                st->ring.emplace_back(seq, chunk);
                NoteRingSize(st->ring.size());
                st->credits--;
                if (eos) {
                    st->eos_seq = seq;
                    flags |= kFlagEos;
                }
                if (!st->first_write_done) {
                    st->first_write_done = true;
                    *ttft_recorder() << monotonic_time_us() - st->open_us;
                }
            } else if (sid != INVALID_VREF_ID) {
                // Bound but out of credits/ring: the consumer is slow —
                // park (this is the backpressure that bounds memory).
                if (!stall_counted) {
                    *g_credit_stalls << 1;
                    flight::Record(flight::kStreamCreditStall, st->id,
                                   st->last_sent);
                    stall_counted = true;
                }
            } else if (st->unbound_since_us > 0 &&
                       monotonic_time_us() - st->unbound_since_us >
                           (int64_t)FLAGS_stream_registry_ttl_ms.get() *
                               1000) {
                // No resume arrived in time: give up.
                st->aborted = true;
                st->error = TERR_RPC_TIMEDOUT;
            }
        }
        if (seq != 0) {
            flight::Record(flight::kStreamChunk, st->id, seq);
            if (fault_injection_enabled()) {
                EndPoint peer;
                {
                    SocketUniquePtr s;
                    if (Socket::AddressSocket(sid, &s) == 0) {
                        peer = s->remote_side();
                    }
                }
                const FaultAction a = FaultInjection::Decide(
                    FaultOp::kStreamWrite, peer, chunk.size());
                if (a.kind == FaultAction::kDelay) {
                    fiber_usleep(a.delay_us);
                } else if (a.kind == FaultAction::kDrop) {
                    // Never sent, but it IS in the ring: the receiver's
                    // gap-NAK / stall-probe retransmit path recovers it.
                    return 0;
                }
            }
            // First sends may ride as pool descriptors on capable links
            // (ISSUE 18 satellite); replay/retransmit paths stay inline.
            if (SendTpuStdStreamData(sid, st->id, seq, flags, chunk,
                                     /*try_desc=*/true) != 0) {
                // Connection died under us; the chunk stays ringed for
                // the resume. Start the registry TTL.
                std::lock_guard<std::mutex> g(st->mu);
                if (st->socket.load(std::memory_order_acquire) == sid) {
                    st->socket.store(INVALID_VREF_ID,
                                     std::memory_order_release);
                    st->unbound_since_us = monotonic_time_us();
                }
            }
            return 0;
        }
        const int64_t abst = monotonic_time_us() + 100 * 1000;
        butex_wait(st->wbutex, expected, &abst);
    }
}

void StreamWriter::Abort(int error_code) {
    if (state_ == nullptr) return;
    AbortLocked(state_, error_code);
    UnregisterServer(state_->id);
}

// ---- frame handlers ----

namespace {

void HandleAck(const std::shared_ptr<ServerStreamState>& st,
               uint64_t ack_seq, int64_t credits) {
    std::vector<std::pair<uint64_t, std::string>> retx;
    VRefId sid = INVALID_VREF_ID;
    uint64_t eos_seq = 0;
    bool complete = false;
    {
        std::lock_guard<std::mutex> g(st->mu);
        sid = st->socket.load(std::memory_order_acquire);
        bool advanced = false;
        if (ack_seq > st->acked) {
            st->acked = ack_seq;
            advanced = true;
        }
        while (!st->ring.empty() && st->ring.front().first <= st->acked) {
            st->ring.pop_front();
        }
        st->credits += credits;
        eos_seq = st->eos_seq;
        const int64_t now = monotonic_time_us();
        if (!advanced && credits == 0 && ack_seq < st->last_sent &&
            sid != INVALID_VREF_ID && !st->aborted &&
            now - st->last_retx_us > kRetxMinGapUs) {
            // Non-advancing zero-credit ack = NAK/stall probe: the
            // receiver is missing everything past ack_seq.
            st->last_retx_us = now;
            for (const auto& e : st->ring) {
                if (e.first > ack_seq && retx.size() < kRetxBurstCap) {
                    retx.push_back(e);
                }
            }
        }
        if (st->eos_seq != 0 && st->acked >= st->eos_seq) complete = true;
    }
    for (const auto& e : retx) {
        *g_replayed << 1;
        SendTpuStdStreamData(sid, st->id, e.first,
                             e.first == eos_seq ? kFlagEos : 0, e.second);
    }
    st->Wake();
    if (complete) UnregisterServer(st->id);
}

void HandleData(const std::shared_ptr<ReceiverState>& rx, VRefId sid,
                uint64_t seq, uint32_t flags, IOBuf* payload) {
    bool nak = false;
    uint64_t nak_floor = 0;
    {
        std::lock_guard<std::mutex> g(rx->mu);
        rx->src_socket.store(sid, std::memory_order_release);
        if (flags & kFlagAbort) {
            rx->closed = true;
            rx->close_error = TERR_CLOSE;
        } else {
            if (flags & kFlagEos) rx->eos_seq = seq;
            if (seq <= rx->delivered || rx->pending.count(seq) != 0) {
                // Exactly-once: replays/retransmits of delivered or
                // buffered seqs are dropped (and NOT re-acked — the
                // periodic grant/probe acks carry the floor, avoiding
                // ack-storm retransmit loops).
                rx->dups++;
            } else {
                rx->pending[seq] = payload->to_string();
                auto it = rx->pending.find(rx->delivered + 1);
                while (it != rx->pending.end()) {
                    rx->ready.emplace_back(it->first,
                                           std::move(it->second));
                    rx->delivered = it->first;
                    rx->pending.erase(it);
                    it = rx->pending.find(rx->delivered + 1);
                }
                rx->last_progress_us = monotonic_time_us();
            }
            if (!rx->pending.empty()) {
                // Gap: NAK the contiguous floor (rate-limited).
                const int64_t now = monotonic_time_us();
                if (now - rx->last_nak_us > kNakMinGapUs) {
                    rx->last_nak_us = now;
                    nak = true;
                    nak_floor = rx->delivered;
                }
            }
        }
    }
    if (nak) SendTpuStdStreamAck(sid, rx->id, nak_floor, 0);
    rx->Wake();
}

}  // namespace

void OnFrame(VRefId socket_id, uint64_t stream_id, int kind, uint64_t seq,
             uint32_t flags, uint64_t ack_seq, int64_t credits,
             int error_code, IOBuf* payload) {
    switch (kind) {
        case KIND_DATA: {
            std::shared_ptr<ReceiverState> rx = FindReceiver(stream_id);
            if (rx == nullptr) {
                // No such receiver (caller gone): tell the sender to
                // stop pushing.
                SendTpuStdStreamClose(socket_id, stream_id, TERR_CLOSE);
                return;
            }
            HandleData(rx, socket_id, seq, flags, payload);
            return;
        }
        case KIND_ACK: {
            std::shared_ptr<ServerStreamState> st = FindServer(stream_id);
            if (st == nullptr) return;  // late ack after completion: drop
            HandleAck(st, ack_seq, credits);
            return;
        }
        case KIND_CLOSE: {
            std::shared_ptr<ServerStreamState> st = FindServer(stream_id);
            if (st != nullptr) {
                AbortLocked(st,
                            error_code != 0 ? error_code : TERR_CLOSE);
                UnregisterServer(stream_id);
                return;
            }
            std::shared_ptr<ReceiverState> rx = FindReceiver(stream_id);
            if (rx != nullptr) {
                {
                    std::lock_guard<std::mutex> g(rx->mu);
                    rx->closed = true;
                    rx->close_error = error_code;
                }
                rx->Wake();
            }
            return;
        }
        default:
            // Unknown frame kind: a version-skewed peer. Fail the
            // STREAM, never the connection.
            *g_aborts << 1;
            SendTpuStdStreamClose(socket_id, stream_id, TERR_REQUEST);
            return;
    }
}

// ---- StreamCall (client) ----

uint64_t NewClientStreamId() {
    // Random seed + odd golden-ratio stride: ids from different client
    // processes collide with negligible probability, and the SAME call
    // object keeps its id across resumes.
    static std::atomic<uint64_t> g_next{fast_rand() | 1};
    uint64_t id = g_next.fetch_add(0x9E3779B97F4A7C15ull,
                                   std::memory_order_relaxed);
    if (id == 0) {
        id = g_next.fetch_add(0x9E3779B97F4A7C15ull,
                              std::memory_order_relaxed);
    }
    return id;
}

StreamCall::StreamCall() : id_(NewClientStreamId()) {
    rx_ = std::make_shared<ReceiverState>();
    rx_->id = id_;
    rx_->rx_window =
        std::max<int64_t>(1, FLAGS_stream_rx_window.get());
    rx_->last_progress_us = monotonic_time_us();
    std::lock_guard<std::mutex> g(g_rx_mu);
    RxRegistry()[id_] = rx_;
}

StreamCall::~StreamCall() {
    {
        std::lock_guard<std::mutex> g(g_rx_mu);
        RxRegistry().erase(id_);
    }
    const VRefId sid = rx_->src_socket.load(std::memory_order_acquire);
    if (sid != INVALID_VREF_ID) {
        SendTpuStdStreamClose(sid, id_, TERR_CLOSE);
    }
}

uint64_t StreamCall::last_seq() const {
    std::lock_guard<std::mutex> g(rx_->mu);
    return rx_->delivered;
}

uint64_t StreamCall::duplicates() const {
    std::lock_guard<std::mutex> g(rx_->mu);
    return rx_->dups;
}

void StreamCall::SeedResume(uint64_t from) {
    std::lock_guard<std::mutex> g(rx_->mu);
    if (rx_->delivered == 0 && rx_->read_upto == 0 && rx_->ready.empty() &&
        rx_->pending.empty()) {
        rx_->delivered = from;
        rx_->read_upto = from;
    }
}

void StreamCall::PrepareOpen(Controller* cntl) {
    uint64_t from = 0;
    {
        std::lock_guard<std::mutex> g(rx_->mu);
        from = rx_->delivered;
        rx_->closed = false;
        rx_->close_error = 0;
        rx_->consumed_since_grant = 0;
        rx_->last_nak_us = 0;
        rx_->last_progress_us = monotonic_time_us();
        rx_->src_socket.store(INVALID_VREF_ID, std::memory_order_release);
    }
    cntl->set_push_stream_request(id_, rx_->rx_window, from);
}

int StreamCall::Read(std::string* chunk, uint64_t* seq, int timeout_ms) {
    const std::shared_ptr<ReceiverState>& rx = rx_;
    const int64_t deadline =
        monotonic_time_us() + (int64_t)timeout_ms * 1000;
    for (;;) {
        const int expected =
            butex_word(rx->rbutex)->load(std::memory_order_acquire);
        VRefId sid = INVALID_VREF_ID;
        int64_t grant = 0;
        uint64_t floor = 0;
        bool probe = false;
        int rc = -1;
        {
            std::lock_guard<std::mutex> g(rx->mu);
            sid = rx->src_socket.load(std::memory_order_acquire);
            if (!rx->ready.empty()) {
                auto& f = rx->ready.front();
                *seq = f.first;
                *chunk = std::move(f.second);
                rx->ready.pop_front();
                rx->read_upto = *seq;
                rx->consumed_since_grant++;
                const bool final_read =
                    rx->eos_seq != 0 && rx->read_upto >= rx->eos_seq;
                if (rx->consumed_since_grant >=
                        std::max<int64_t>(1, rx->rx_window / 2) ||
                    final_read) {
                    // Consumption-based credit grant: this is what a
                    // slow consumer WITHHOLDS, parking the writer.
                    grant = rx->consumed_since_grant;
                    rx->consumed_since_grant = 0;
                    floor = rx->delivered;
                }
                rc = 0;
            } else if (rx->eos_seq != 0 && rx->read_upto >= rx->eos_seq) {
                rc = 1;  // complete
            } else if (rx->closed) {
                rc = rx->close_error != 0 ? rx->close_error : TERR_EOF;
            } else if (sid != INVALID_VREF_ID) {
                // Mid-stream silence: probe with a non-advancing
                // zero-credit ack — if the tail chunk was lost, the
                // server's ring retransmits it.
                const int64_t now = monotonic_time_us();
                if (now - rx->last_progress_us > kStallProbeUs &&
                    now - rx->last_nak_us > kStallProbeUs) {
                    rx->last_nak_us = now;
                    probe = true;
                    floor = rx->delivered;
                }
            }
        }
        if (grant > 0 && sid != INVALID_VREF_ID) {
            SendTpuStdStreamAck(sid, rx->id, floor, grant);
        } else if (probe) {
            SendTpuStdStreamAck(sid, rx->id, floor, 0);
        }
        if (rc >= 0) return rc;
        if (sid != INVALID_VREF_ID) {
            SocketUniquePtr s;
            if (Socket::AddressSocket(sid, &s) != 0) {
                // Source connection died: resume via PrepareOpen.
                return TERR_EOF;
            }
        }
        const int64_t now = monotonic_time_us();
        if (now >= deadline) return TERR_RPC_TIMEDOUT;
        const int64_t abst = std::min(deadline, now + 50 * 1000);
        butex_wait(rx->rbutex, expected, &abst);
    }
}

// ---- portal / metrics surface ----

void ExposeVars() {
    *g_opens << 0;
    *g_resumed << 0;
    *g_replayed << 0;
    *g_credit_stalls << 0;
    *g_aborts << 0;
    ttft_recorder();
}

int64_t RingHighwater() {
    return g_ring_hw.load(std::memory_order_relaxed);
}
int64_t Opens() { return (*g_opens).get_value(); }
int64_t Resumed() { return (*g_resumed).get_value(); }
int64_t ReplayedChunks() { return (*g_replayed).get_value(); }
int64_t CreditStalls() { return (*g_credit_stalls).get_value(); }
int64_t Aborts() { return (*g_aborts).get_value(); }

std::string DescribeText() {
    std::ostringstream os;
    os << "push streams (resumable server-push tier)\n"
       << "open " << Opens() << "\nresumed " << Resumed()
       << "\nreplayed_chunks " << ReplayedChunks() << "\ncredit_stalls "
       << CreditStalls() << "\naborts " << Aborts() << "\nring_highwater "
       << RingHighwater() << "\n";
    {
        std::lock_guard<std::mutex> g(g_srv_mu);
        for (const auto& kv : ServerRegistry()) {
            const auto& st = kv.second;
            std::lock_guard<std::mutex> g2(st->mu);
            os << "server_stream " << kv.first << " session="
               << st->session << " last_sent=" << st->last_sent
               << " acked=" << st->acked << " credits=" << st->credits
               << " ring=" << st->ring.size()
               << " bound=" << (st->socket.load() != INVALID_VREF_ID)
               << " eos=" << st->eos_seq << "\n";
        }
    }
    {
        std::lock_guard<std::mutex> g(g_rx_mu);
        for (const auto& kv : RxRegistry()) {
            const auto& rx = kv.second;
            std::lock_guard<std::mutex> g2(rx->mu);
            os << "client_stream " << kv.first << " delivered="
               << rx->delivered << " read_upto=" << rx->read_upto
               << " pending=" << rx->pending.size()
               << " dups=" << rx->dups << " eos=" << rx->eos_seq << "\n";
        }
    }
    return os.str();
}

std::string DescribeJson() {
    std::ostringstream os;
    os << "{\"open\":" << Opens() << ",\"resumed\":" << Resumed()
       << ",\"replayed_chunks\":" << ReplayedChunks()
       << ",\"credit_stalls\":" << CreditStalls()
       << ",\"aborts\":" << Aborts()
       << ",\"ring_highwater\":" << RingHighwater()
       << ",\"server_streams\":[";
    {
        std::lock_guard<std::mutex> g(g_srv_mu);
        bool first = true;
        for (const auto& kv : ServerRegistry()) {
            const auto& st = kv.second;
            std::lock_guard<std::mutex> g2(st->mu);
            if (!first) os << ",";
            first = false;
            os << "{\"id\":" << kv.first << ",\"last_sent\":"
               << st->last_sent << ",\"acked\":" << st->acked
               << ",\"credits\":" << st->credits
               << ",\"ring\":" << st->ring.size() << ",\"bound\":"
               << (st->socket.load() != INVALID_VREF_ID ? "true"
                                                        : "false")
               << ",\"eos\":" << st->eos_seq << "}";
        }
    }
    os << "],\"client_streams\":[";
    {
        std::lock_guard<std::mutex> g(g_rx_mu);
        bool first = true;
        for (const auto& kv : RxRegistry()) {
            const auto& rx = kv.second;
            std::lock_guard<std::mutex> g2(rx->mu);
            if (!first) os << ",";
            first = false;
            os << "{\"id\":" << kv.first << ",\"delivered\":"
               << rx->delivered << ",\"read_upto\":" << rx->read_upto
               << ",\"pending\":" << rx->pending.size()
               << ",\"dups\":" << rx->dups << ",\"eos\":" << rx->eos_seq
               << "}";
        }
    }
    os << "]}";
    return os.str();
}

}  // namespace push_stream

// Defined here (not controller.cc) so the Controller surface stays free
// of push_stream internals.
push_stream::StreamWriter Controller::accept_stream() {
    if (!has_push_open_ || push_open_id_ == 0) {
        return push_stream::StreamWriter();
    }
    accepted_push_stream_ = push_open_id_;
    return push_stream::StreamWriter(push_stream::AcceptOpen(
        push_open_id_, session_, push_open_rx_window_,
        push_open_resume_from_));
}

}  // namespace tpurpc
