// rpc_dump: sampled capture of live requests to recordio files, replayed
// by tools/rpc_replay (and loadable by tools/rpc_press).
//
// Reference: src/brpc/rpc_dump.{h,cpp} (SampledRequest objects ride the
// bvar Collector's sampling pipeline to a background dumper) +
// tools/rpc_replay. Enable with the live flag -rpc_dump; files land in
// -rpc_dump_dir as requests.<pid>.dump. Each record's payload is
//   u32 meta_len, RpcMeta bytes (the original request meta), body bytes
// so a replayer can rewrite the correlation id and resend the frame
// verbatim.
#pragma once

#include <cstdint>
#include <string>

#include "tbase/endpoint.h"
#include "tbase/iobuf.h"

namespace tpurpc {

// Capture hook (server side): called with the parsed request meta bytes +
// body ONLY when dumping is on and the sampling gate opens. Cheap when
// off (one flag load).
bool IsRpcDumpSampled();
void SubmitRpcDump(const IOBuf& meta_bytes, const IOBuf& body);

// Replay `path` against `server` `times` times over one connection.
// Returns the number of successful responses, or -1 when the file or the
// connection is unusable. Used by tools/rpc_replay and tests.
int ReplayDumpFile(const std::string& path, const EndPoint& server,
                   int times);

// Where the current process dumps (for tests/tools).
std::string RpcDumpFilePath();

}  // namespace tpurpc
