// Redis (RESP2) protocol: client + server, pipelined on one connection.
//
// Reference parity: src/brpc/policy/redis_protocol.cpp (the canonical
// consumer of Socket's pipelined-info correlation, socket.h:532) +
// src/brpc/redis.{h,cpp} (RedisRequest/RedisResponse/RedisReply and the
// server-side RedisService command handlers).
//
// Client: a Channel with options.protocol="redis"; one RedisRequest may
// carry N commands (one pipelined batch, N replies in order). Concurrent
// callers on the same connection correlate via the socket's FIFO
// pipelined-info queue.
// Server: Server::set_redis_service(RedisService*) serves RESP on the
// same port as every other protocol (sniffed by the leading '*').
#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "tbase/iobuf.h"

namespace tpurpc {

class Channel;
class Controller;

// One RESP value (reply side).
struct RedisReply {
    enum Type {
        NIL,      // $-1
        STATUS,   // +OK
        ERROR,    // -ERR ...
        INTEGER,  // :123
        STRING,   // $N bulk
        ARRAY,    // *N
    };
    Type type = NIL;
    std::string str;     // STATUS/ERROR/STRING payload
    int64_t integer = 0;
    std::vector<RedisReply> elements;  // ARRAY

    bool is_error() const { return type == ERROR; }
};

// A pipelined batch of commands.
class RedisRequest {
public:
    // AddCommand("SET", "key", "value") — arguments are sent verbatim as
    // bulk strings (binary-safe).
    void AddCommand(const std::vector<std::string>& args);
    size_t command_count() const { return ncommands_; }
    const IOBuf& wire() const { return wire_; }
    void Clear() {
        wire_.clear();
        ncommands_ = 0;
    }

private:
    IOBuf wire_;
    size_t ncommands_ = 0;
};

class RedisResponse {
public:
    size_t reply_count() const { return replies_.size(); }
    const RedisReply& reply(size_t i) const { return replies_[i]; }
    std::vector<RedisReply>* mutable_replies() { return &replies_; }
    void Clear() { replies_.clear(); }

private:
    std::vector<RedisReply> replies_;
};

// Execute one pipelined batch on `channel` (protocol must be "redis").
// Synchronous; cntl carries timeout/error. All commands of the batch
// share the connection write atomically (one pipelined unit).
void RedisCall(Channel* channel, Controller* cntl,
               const RedisRequest& request, RedisResponse* response);

// ---- server side ----

// Handler for one command name (uppercased). Fill *out; return value is
// the reply (errors via out->type = ERROR).
class RedisCommandHandler {
public:
    virtual ~RedisCommandHandler() = default;
    virtual void Run(const std::vector<std::string>& args,
                     RedisReply* out) = 0;
};

// Command table the server dispatches RESP arrays into (reference
// RedisService, src/brpc/redis.h). Unknown commands get -ERR.
class RedisService {
public:
    RedisService();  // out-of-line: KvState is incomplete here
    virtual ~RedisService();
    // Takes ownership of the handler.
    void AddCommandHandler(const std::string& name,
                           RedisCommandHandler* handler);
    RedisCommandHandler* FindCommandHandler(const std::string& name) const;

    // Register a built-in in-memory KV command set — PING, ECHO, GET,
    // SET, DEL over a service-owned map (fiber-safe). The demo/example
    // backend (reference example/redis_c++/redis_server.cpp ships the
    // same starter set); real applications add their own handlers.
    void AddBasicKvCommands();

    struct KvState;  // public: the built-in handlers reach it

private:
    std::map<std::string, std::unique_ptr<RedisCommandHandler>> handlers_;
    std::unique_ptr<KvState> kv_;  // backs AddBasicKvCommands
};

// ---- codec (exposed for tests/fuzzing) ----

// Serialize one command as a RESP array of bulk strings.
void RedisSerializeCommand(const std::vector<std::string>& args, IOBuf* out);
// Parse ONE reply from `source`. Returns 1 = parsed (consumed), 0 = need
// more bytes (source untouched), -1 = protocol corruption.
int RedisParseReply(IOBuf* source, RedisReply* out);
// Serialize one reply.
void RedisSerializeReply(const RedisReply& r, std::string* out);

// Protocol registration (GlobalInitializeOrDie).
void RegisterRedisProtocols();
int RedisServerProtocolIndex();
int RedisClientProtocolIndex();

}  // namespace tpurpc
