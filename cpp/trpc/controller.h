// Controller: the per-RPC state machine and user knob surface, client and
// server side.
//
// Modeled on reference src/brpc/controller.h / controller.cpp: IssueRPC
// (:1047) picks the server + connection and writes the packed request;
// OnVersionedRPCReturned (:598) is the response/failure funnel handling
// retries via versioned call ids (:1059-1065) and timeouts (:593);
// Call::OnComplete (:780) feeds the load balancer. Implements
// google::protobuf::RpcController so generated stubs work unchanged.
#pragma once

#include <google/protobuf/message.h>
#include <google/protobuf/service.h>

#include <atomic>
#include <mutex>
#include <string>
#include <vector>

#include "tbase/endpoint.h"
#include "tbase/iobuf.h"
#include "tfiber/call_id.h"
#include "tfiber/timer_thread.h"
#include "tnet/socket.h"

namespace tpurpc {

namespace rpc {
class RpcMeta;
}

class Channel;
class Server;
namespace push_stream {
class StreamWriter;
}

class Controller : public google::protobuf::RpcController {
public:
    Controller() : excluded_(nullptr) { Reset(); }
    ~Controller() override;

    // ---- client-side knobs ----
    void set_timeout_ms(int64_t t) { timeout_ms_ = t; }
    int64_t timeout_ms() const { return timeout_ms_; }
    void set_max_retry(int r) { max_retry_ = r; }
    int max_retry() const { return max_retry_; }
    void set_log_id(int64_t id) { log_id_ = id; }
    int64_t log_id() const { return log_id_; }
    // Hash key for consistent-hashing load balancers (reference
    // Controller::set_request_code).
    void set_request_code(uint64_t code) {
        request_code_ = code;
        has_request_code_ = true;
    }
    // ---- multi-tenant QoS identity (ISSUE 8) ----
    // Client side: stamped into the request meta (tpu_std tenant/
    // priority fields; x-tpu-tenant/x-tpu-priority h2 headers). Server
    // side: parsed from the wire. Unset values inherit from the upstream
    // server call (Channel::CallMethod), so identity propagates
    // hop-to-hop alongside the deadline/trace context.
    void set_tenant(const std::string& t) { tenant_ = t; }
    const std::string& tenant() const { return tenant_; }
    // Priority class 0..7 (0 = most sheddable). Unset (-1) resolves to
    // the upstream call's class, else the middle class (qos.h
    // kDefaultPriority).
    void set_priority(int p) { priority_ = p; }
    int priority() const { return priority_; }
    bool has_priority() const { return priority_ >= 0; }
    // Sticky-session identity (ISSUE 16): names the client session this
    // call belongs to, so an L7 front door can pin the whole session to
    // one backend (rendezvous-hashed) and re-pin it atomically when that
    // backend drains. Rides the tpu_std request meta / the x-tpu-session
    // h2+HTTP header; propagates hop-to-hop like tenant/priority.
    void set_session(const std::string& s) { session_ = s; }
    const std::string& session() const { return session_; }
    // Server-suggested backoff attached to a TERR_OVERLOAD shed; on the
    // client it steers the retry delay (jittered), on the server the
    // response path copies it into the response meta.
    void set_suggested_backoff_ms(int64_t ms) { suggested_backoff_ms_ = ms; }
    int64_t suggested_backoff_ms() const { return suggested_backoff_ms_; }
    // Attachment bytes carried outside the pb payload (zero-copy).
    IOBuf& request_attachment() { return request_attachment_; }
    IOBuf& response_attachment() { return response_attachment_; }

    // ---- one-sided pool attachment (ISSUE 9) ----
    // Client: send `buf` as a (pool_id, offset, len, crc32c, epoch)
    // descriptor instead of inline frame bytes. Eligible when buf is one
    // contiguous block inside this process's SHARED registered pool (any
    // IOBuf block is, after IciBlockPool::Init, until it spills past the
    // primary region); ineligible bytes fall back to the inline
    // attachment transparently. The pin is held as a block LEASE
    // (tici/block_lease.h, ISSUE 10): the registry owns the block ref
    // until the RPC completes; EndRPC's release is exactly-once by
    // construction, the expiry reaper reclaims the pin if the call
    // wedges past its deadline, and peer death releases it through the
    // socket failure observer — the slab can never leak. Descriptors
    // only resolve on ici/shm links whose HANDSHAKE mapped our pool: the
    // receiver binds resolution to the connection's registered peer
    // pool (Socket::peer_pool_id), so a plain-TCP peer — or any
    // connection naming a pool that is not its own — answers
    // TERR_REQUEST; an epoch mismatch answers the retriable
    // TERR_STALE_EPOCH.
    void set_request_pool_attachment(IOBuf&& buf);
    bool has_request_pool_attachment() const {
        return pool_lease_id_ != 0;
    }
    // Lease handle of the pinned request attachment (0 = none/released);
    // tests assert exactly-once release through it.
    uint64_t pool_lease_id() const { return pool_lease_id_; }
    // Server: the resolved zero-copy view of a descriptor attachment —
    // bytes read IN PLACE from the receiver's mapping of the sender's
    // pool. Valid until the done closure runs; handlers must not retain
    // it past the response.
    struct PoolAttachment {
        const char* data = nullptr;
        uint64_t length = 0;
        uint64_t pool_id = 0;
        uint64_t offset = 0;
        uint32_t crc32c = 0;
        // Pool generation the descriptor was minted under (epoch fence).
        uint64_t pool_epoch = 0;
        // Response direction only: the completion token the view's
        // release echoes in its desc_ack (0 = token-less).
        uint64_t ack_token = 0;
    };
    const PoolAttachment& request_pool_attachment() const {
        return pool_attachment_;
    }
    bool has_request_pool_attachment_view() const {
        return pool_attachment_.data != nullptr;
    }
    // Server-protocol internal: install the resolved view.
    void SetRequestPoolAttachmentView(const PoolAttachment& view) {
        pool_attachment_ = view;
    }

    // ---- response-direction pool attachment (ISSUE 12) ----
    // Server handler side: answer with `buf` as a pool descriptor — the
    // symmetric twin of set_request_pool_attachment. Eligible when buf
    // is one contiguous block inside this process's shared pool AND the
    // call's connection rides a descriptor-capable transport tier
    // (tnet/transport.h — the client mapped our pool at handshake, or
    // is this process); anything else falls back to inline
    // response_attachment bytes transparently. The pin is a "rsp"
    // lease: the response closure arms it (owner = the wire correlation
    // id, expiry = the client's propagated deadline + grace, peer = the
    // server-side socket) and hands ownership to the registry — the
    // client's desc_ack releases it exactly once; the expiry reaper and
    // peer-death reclamation (a SIGKILLed client's socket failure)
    // are the crash-safe backstops.
    void set_response_pool_attachment(IOBuf&& buf);
    bool has_response_pool_attachment() const {
        return rsp_pool_lease_id_ != 0;
    }
    uint64_t response_pool_lease_id() const { return rsp_pool_lease_id_; }
    // Server-protocol internal: the stashed descriptor fields of the
    // pinned response attachment (valid while the lease lives).
    const PoolAttachment& response_pool_descriptor() const {
        return rsp_pool_stash_;
    }
    // Server-protocol internal: move the pin's ownership out of the
    // controller and into the wire/ack path (the response closure calls
    // this once it emits the descriptor; the controller's teardown then
    // no longer releases the pin — the ack/reaper/peer-death paths own
    // it). Returns 0 when there is nothing to take.
    uint64_t TakeResponsePoolLease() {
        const uint64_t id = rsp_pool_lease_id_;
        rsp_pool_lease_id_ = 0;
        return id;
    }
    // Client side: the resolved zero-copy view of a response descriptor
    // — bytes read IN PLACE from this process's mapping of the server's
    // pool. Valid until Reset()/destruction/reuse: releasing the view
    // sends the desc_ack that lets the server unpin the block, so user
    // code may read it after the call completes (sync callers included).
    // CAVEAT — the server's pin is deadline-bounded: its lease expires
    // at this call's propagated deadline + the server's
    // -pool_lease_grace_ms (or -pool_lease_default_ms for deadline-less
    // calls), after which the reaper may recycle the block even though
    // the view is still held. Consume the view promptly after the call
    // completes; a reader that dawdles past its own RPC deadline + the
    // grace window may observe recycled bytes (copy out early if you
    // must hold data longer).
    const PoolAttachment& response_pool_attachment() const {
        return rsp_pool_view_;
    }
    bool has_response_pool_attachment_view() const {
        return rsp_pool_view_.data != nullptr;
    }
    // Client-protocol internal: install the resolved view + the ack
    // identity (the socket the response arrived on and its wire
    // correlation id).
    void SetResponsePoolAttachmentView(const PoolAttachment& view,
                                       SocketId sid, uint64_t wire_cid) {
        rsp_pool_view_ = view;
        rsp_ack_sid_ = sid;
        rsp_ack_cid_ = wire_cid;
    }
    // Payload compression (reference set_request_compress_type /
    // set_response_compress_type; see trpc/compress.h). Attachments stay
    // raw. Client sets request_*; server handlers set response_*.
    void set_request_compress_type(int t) { request_compress_type_ = t; }
    int request_compress_type() const { return request_compress_type_; }
    void set_response_compress_type(int t) { response_compress_type_ = t; }
    int response_compress_type() const { return response_compress_type_; }

    // ---- results ----
    bool Failed() const override { return error_code_ != 0; }
    std::string ErrorText() const override { return error_text_; }
    int ErrorCode() const { return error_code_; }
    void SetFailed(const std::string& reason) override;
    void SetFailed(int error_code, const char* fmt, ...);
    int64_t latency_us() const { return latency_us_; }
    EndPoint remote_side() const { return remote_side_; }
    EndPoint local_side() const { return local_side_; }
    int retried_count() const { return current_try_; }
    // Hedge telemetry (ISSUE 16): whether a backup request actually went
    // out for this call, and whether the BACKUP try's response completed
    // the RPC (false when the original outran it, or the backup's
    // connection died and the call fell back to the original). An L7
    // router reads these after each forwarded call to account
    // rpc_router_hedges / rpc_router_hedge_wins without guessing from
    // global counters.
    bool backup_issued() const { return backup_issued_; }
    bool backup_won() const { return backup_won_; }
    // Combo-channel propagation hook: a SelectiveChannel sub-call runs
    // the backup machinery on its own sub-controller and mirrors the
    // telemetry onto the user-visible parent here.
    void set_backup_telemetry(bool issued, bool won) {
        backup_issued_ = issued;
        backup_won_ = won;
    }

    // The correlation id of this RPC (join it to wait for async calls).
    CallId call_id() const { return correlation_id_; }

    // Trace id of this call's rpcz span (0 = unsampled). Survives EndRPC
    // (the span itself is handed to the SpanDB) so a caller can chase the
    // call across the mesh at /rpcz/trace/<id>.
    uint64_t trace_id() const { return sampled_trace_id_; }

    // ---- protobuf::RpcController surface ----
    void Reset() override;
    void StartCancel() override;
    bool IsCanceled() const override {
        return canceled_.load(std::memory_order_acquire);
    }
    // Register `closure` to run when this call is canceled. Protobuf
    // contract: the closure runs EXACTLY once, whether or not
    // cancellation ever happens — an unfired closure runs at EndRPC /
    // Reset / destruction. Server side it may run on the connection's
    // input fiber, so it must be fast and must not block.
    void NotifyOnCancel(google::protobuf::Closure* closure) override;

    // ---- server side ----
    bool is_server_side() const { return server_ != nullptr; }
    Server* server() const { return server_; }
    // Called by the server-side protocol when building the call context.
    void InitServerSide(Server* server, const EndPoint& remote) {
        server_ = server;
        remote_side_ = remote;
    }
    // ---- server-side deadline (the client's propagated remaining
    // budget, parsed from tpu_std timeout_ms / h2 grpc-timeout) ----
    void set_server_deadline_us(int64_t d) { server_deadline_us_ = d; }
    bool has_server_deadline() const { return server_deadline_us_ > 0; }
    int64_t server_deadline_us() const { return server_deadline_us_; }
    // Remaining budget of this server call; INT64_MAX when the client
    // sent no deadline. May be <= 0 (already expired).
    int64_t remaining_server_budget_us() const;
    // ---- server-side cancellation (trpc/server_call.h registry) ----
    // The cancelable handle of this server call; its on_error handler is
    // HandleServerCancelThunk. Destroyed by the done closure.
    void set_server_call_id(CallId id) { server_call_id_ = id; }
    CallId server_call_id() const { return server_call_id_; }
    void DestroyServerCallId();
    // Mark this server call canceled: runs the NotifyOnCancel closure and
    // cascades ECANCELED into every downstream call the handler issued
    // under this context (stale-safe: completed children drop it).
    // Idempotent.
    void HandleServerCancel();
    static int HandleServerCancelThunk(CallId id, void* data, int error);

    // ---- streaming plumbing (see trpc/stream.h) ----
    // Client: StreamCreate records the local stream to announce in the
    // request meta; the response path connects or fails it.
    void set_request_stream(VRefId id, int64_t window) {
        request_stream_ = id;
        request_stream_window_ = window;
    }
    VRefId request_stream() const { return request_stream_; }
    int64_t request_stream_window() const { return request_stream_window_; }
    // Set once the response path bound the stream to a connection; EndRPC
    // fails any still-unbound stream so every termination path (timeout,
    // socket failure, server error, parse error) releases it (reference:
    // Controller::EndRPC -> HandleStreamConnection fails _request_stream).
    void set_request_stream_bound() { request_stream_bound_ = true; }
    // Server: the requester's announced stream (from request meta).
    void SetRemoteStream(uint64_t id, int64_t window) {
        remote_stream_id_ = id;
        remote_stream_window_ = window;
        has_remote_stream_ = true;
    }
    bool has_remote_stream() const { return has_remote_stream_; }
    uint64_t remote_stream_id() const { return remote_stream_id_; }
    int64_t remote_stream_window() const { return remote_stream_window_; }
    SocketId server_socket() const { return server_socket_; }
    void set_server_socket(SocketId sid) { server_socket_ = sid; }
    // Server: StreamAccept's local stream to announce in the response.
    void set_accepted_stream(VRefId id, int64_t window) {
        accepted_stream_ = id;
        accepted_stream_window_ = window;
    }
    VRefId accepted_stream() const { return accepted_stream_; }
    int64_t accepted_stream_window() const {
        return accepted_stream_window_;
    }

    // ---- server-push streams (ISSUE 17, push_stream tier) ----
    // Client: stamp a push-stream open/resume on the request meta
    // (StreamSettings{push=true, version, rx_window, resume_from_seq}).
    // StreamCall::PrepareOpen is the normal entry.
    void set_push_stream_request(uint64_t id, int64_t rx_window,
                                 uint64_t resume_from) {
        push_open_id_ = id;
        push_open_rx_window_ = rx_window;
        push_open_resume_from_ = resume_from;
    }
    // Server: the open parsed from the request meta (push=true).
    void SetPushStreamOpen(uint64_t id, int64_t rx_window,
                           uint64_t resume_from) {
        push_open_id_ = id;
        push_open_rx_window_ = rx_window;
        push_open_resume_from_ = resume_from;
        has_push_open_ = true;
    }
    bool has_push_stream_open() const { return has_push_open_; }
    uint64_t push_stream_id() const { return push_open_id_; }
    int64_t push_rx_window() const { return push_open_rx_window_; }
    uint64_t push_resume_from() const { return push_open_resume_from_; }
    // Accept the push open INSIDE the handler: registers (or resumes)
    // the server stream keyed by (session, stream_id) and returns the
    // writer. Chunks written before the response goes out queue in the
    // replay ring; the response closure binds the connection
    // (push_stream::Activate) and the writer starts/continues pushing.
    // Defined in stream.cc.
    push_stream::StreamWriter accept_stream();
    void set_accepted_push_stream(uint64_t id) {
        accepted_push_stream_ = id;
    }
    uint64_t accepted_push_stream() const { return accepted_push_stream_; }

private:
    friend class Channel;
    friend class Server;
    friend void ProcessTpuStdResponse(class TpuStdMessage* msg,
                                      const rpc::RpcMeta& meta);
    friend void CompleteClientUnaryResponse(uint64_t cid, int error_code,
                                            const std::string& error_text,
                                            IOBuf* payload_pb);

public:
    // Arm a backup request for this call at the given delay (overrides
    // ChannelOptions::backup_request_ms; <0 disables).
    void set_backup_request_ms(int64_t ms) { backup_request_ms_ = ms; }
    int64_t backup_request_ms() const { return backup_request_ms_; }

private:

    // Client call machinery (used by Channel).
    static int HandleErrorThunk(CallId id, void* data, int error);
    int HandleError(CallId id, int error);   // runs with the id locked
    void IssueRPC();                          // (re)send the current try
    void EndRPC(CallId locked_id);            // finalize: done/join wakeup
    static void* RunDoneThunk(void* arg);
    // Backup request machinery (reference controller.cpp:344-358,625-638
    // HandleBackupRequest): the timer fires at backup_request_ms; if the
    // RPC is still pending, a second call goes out on the next id version
    // while the original stays live — first response wins.
    static void HandleBackupThunk(void* arg);  // arg = base CallId value
    void MaybeIssueBackup();                   // runs with the id locked
    static void HandleBackoffThunk(void* arg);  // arg = retry's CallId
    // Report the finished try to the LB (latency + error feed the
    // locality-aware policy; reference Call::OnComplete controller.cpp:780).
    void FeedbackToLB(int error);
    // Pool-return / close this RPC's pooled/short connections (EndRPC).
    void ReleaseFlySockets();
    // Exactly-once release of the pinned pool-attachment lease (see
    // set_request_pool_attachment); safe on every termination path.
    void ReleasePoolLease();
    // Response-direction teardown, both roles: a server-side pin whose
    // ownership was never taken by the response closure (failed call,
    // non-tpu_std protocol) releases through the registry; a client-side
    // view sends the desc_ack that unpins the server's block. Runs on
    // Reset/reuse/destruction — never on EndRPC, so a sync caller can
    // still read the view after the call returns.
    void ReleaseResponsePoolState();
    // Best-effort wire CANCEL for the in-flight tries (tpu_std CANCEL
    // meta / h2 RST_STREAM) so the server stops burning CPU on a call
    // nobody waits for. Runs with the id locked.
    void SendWireCancel();
    // Run-once delivery of the NotifyOnCancel closure.
    void RunCancelClosure();
    // Downstream call registration for the cancellation cascade: returns
    // false when this (server-side) controller is already canceled — the
    // caller then cancels the fresh call instead of registering it.
    bool AddChildCall(CallId cid);

    // --- shared fields ---
    int error_code_;
    std::string error_text_;
    int64_t timeout_ms_;
    int max_retry_;
    int64_t log_id_;
    // Written by the cancel paths (client StartCancel; server: CANCEL
    // meta / RST_STREAM / connection death on the input fiber) and read
    // by the handler's fiber via IsCanceled().
    std::atomic<bool> canceled_{false};
    // NotifyOnCancel closure; exchanged to null on the (single) run.
    std::atomic<google::protobuf::Closure*> on_cancel_{nullptr};
    IOBuf request_attachment_;
    IOBuf response_attachment_;
    // One-sided descriptor state: the lease of the pinned pool block
    // (client; the block_lease registry owns the ref — EndRPC releases
    // it exactly once, the reaper/peer-death paths are the crash-safe
    // backstops) and the resolved in-place view (server).
    uint64_t pool_lease_id_ = 0;
    PoolAttachment pool_attachment_;
    // Response-direction descriptor state (ISSUE 12). Server role: the
    // "rsp" lease of the handler's pinned answer + its stashed
    // descriptor fields. Client role: the resolved in-place view and
    // the (socket, wire cid) identity its release acks.
    uint64_t rsp_pool_lease_id_ = 0;
    PoolAttachment rsp_pool_stash_;
    PoolAttachment rsp_pool_view_;
    SocketId rsp_ack_sid_ = INVALID_VREF_ID;
    uint64_t rsp_ack_cid_ = 0;
    EndPoint remote_side_;
    EndPoint local_side_;
    int64_t latency_us_;

    // --- client call state ---
    Channel* channel_;
    const google::protobuf::MethodDescriptor* method_;
    google::protobuf::Message* response_;
    google::protobuf::Closure* done_;
    CallId correlation_id_;   // base id (create version)
    CallId current_cid_;      // wire id of the current try
    // The still-live other in-flight call once a backup went out (the
    // reference's _unfinished_call): its response may win; its socket
    // errors kill only it.
    CallId unfinished_cid_;
    TimerId backup_timer_;
    int64_t backup_request_ms_;  // per-call override; <0 = channel default
    IOBuf request_buf_;       // serialized request payload (pb bytes)
    int current_try_;
    int64_t start_us_;
    int64_t deadline_us_;
    TimerId timeout_timer_;
    SocketId single_server_id_;
    SocketId current_server_id_;  // server of the in-flight try (LB mode)
    // Server of the still-live unfinished try once a backup went out:
    // FeedbackToLB(0) clears current_server_id_ when the backup issues,
    // so this keeps the loser's server addressable for the wire CANCEL
    // at EndRPC, and restores current_server_id_ when the backup's
    // connection dies and the call falls back to the original.
    SocketId unfinished_server_id_;
    bool backup_issued_;  // a backup try actually went out
    bool backup_won_;     // the backup try's response completed the RPC
    int64_t try_start_us_;        // start of the current try (LB feedback)
    uint64_t request_code_;
    bool has_request_code_;
    int request_compress_type_;
    int response_compress_type_;
    // QoS identity (shared by both sides; see the accessors above).
    std::string tenant_;
    int priority_;  // -1 = unset
    std::string session_;  // sticky-session id (empty = none)
    int64_t suggested_backoff_ms_;
    // Pooled/short connection of the current try and of the still-live
    // original behind a backup (INVALID in single mode). A socket whose
    // call received a response is moved to reusable_fly_sid_ and returned
    // to the pool at EndRPC; anything else is closed (reference: a call
    // that fails without a response never reuses its pooled connection).
    SocketId current_fly_sid_;
    SocketId unfinished_fly_sid_;
    SocketId reusable_fly_sid_;
    // Socket whose auth fight THIS RPC's current try won (tpu_std);
    // aborted on retry/terminal failure so the connection can't wedge
    // with waiters parked behind a dead authenticator.
    SocketId auth_fight_sid_;
    class ExcludedServers* excluded_;  // servers tried by earlier attempts

    // --- streaming state ---
    VRefId request_stream_;
    int64_t request_stream_window_;
    bool request_stream_bound_;
    bool has_remote_stream_;
    uint64_t remote_stream_id_;
    int64_t remote_stream_window_;
    VRefId accepted_stream_;
    int64_t accepted_stream_window_;
    // push_stream tier (ISSUE 17): the open parsed from / stamped into
    // the request meta, and the stream id accepted by the handler.
    uint64_t push_open_id_;
    int64_t push_open_rx_window_;
    uint64_t push_open_resume_from_;
    bool has_push_open_;
    uint64_t accepted_push_stream_;
    SocketId server_socket_;

    // --- server call state ---
    Server* server_;
    // Absolute deadline propagated by the client (0 = none).
    int64_t server_deadline_us_ = 0;
    // Cancelable handle registered in server_call::Register.
    CallId server_call_id_ = INVALID_CALL_ID;
    // Downstream calls issued by the handler under this server context
    // (CallId VALUES only — cancellation via id_error is stale-safe, so
    // completed children need no deregistration).
    std::mutex child_mu_;
    std::vector<CallId> child_calls_;

public:
    // rpcz span of this RPC; null when unsampled. Client side: owned by
    // the controller from CallMethod until EndRPC submits it (all touches
    // run under the id lock). Server side: owned by the request pipeline
    // (request fiber -> user fiber -> done closure, strictly sequential).
    struct Span* span_ = nullptr;
    // The span's trace id, retained past span submission (trace_id()).
    uint64_t sampled_trace_id_ = 0;
};

// Generic client-side unary completion for protocols that frame outside
// tpu_std (h2/gRPC): locks `cid` (ranged, so backup winners work), moves
// the delivering pooled connection to reusable, records the error or
// parses `payload_pb` into the response message, and EndRPCs. Safe to
// call with a stale/finished cid (drops silently, like a late response).
void CompleteClientUnaryResponse(uint64_t cid, int error_code,
                                 const std::string& error_text,
                                 IOBuf* payload_pb);

// Shared client-side re-issue accounting (the single process-wide
// rpc_client_retries / rpc_retry_budget_exhausted adders live in
// controller.cc): combo channels route their own cross-channel retry
// loops through the same counters as the in-channel funnel.
namespace client_stats {
void CountRetry();            // rpc_client_retries
void CountBudgetExhausted();  // rpc_retry_budget_exhausted
}  // namespace client_stats

}  // namespace tpurpc
