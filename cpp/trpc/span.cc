#include "trpc/span.h"

#include <unistd.h>

#include <cinttypes>
#include <cstdio>

#include "tbase/errno.h"
#include "tbase/flags.h"
#include "tbase/time.h"

// Live-settable through /flags (reference -enable_rpcz works the same).
DEFINE_bool(enable_rpcz, false, "collect per-RPC spans, browse at /rpcz");

namespace tpurpc {

void Span::Annotate(const std::string& text) {
    notes.push_back(Note{monotonic_time_us(), text});
}

void Span::dispatch() { SpanDB::singleton()->Add(std::move(*this)); }

SpanDB* SpanDB::singleton() {
    static SpanDB* db = new SpanDB;
    return db;
}

void SpanDB::Add(Span&& s) {
    std::lock_guard<std::mutex> g(mu_);
    spans_.push_back(std::move(s));
    while (spans_.size() > kCapacity) {
        spans_.pop_front();
    }
}

std::vector<Span> SpanDB::Recent(size_t limit, uint64_t trace_id) const {
    std::lock_guard<std::mutex> g(mu_);
    std::vector<Span> out;
    for (auto it = spans_.rbegin(); it != spans_.rend() && out.size() < limit;
         ++it) {
        if (trace_id == 0 || it->trace_id == trace_id) {
            out.push_back(*it);
        }
    }
    return out;
}

bool IsRpczSampled() {
    return FLAGS_enable_rpcz.get() && Collector::singleton()->sample();
}

bool IsRpczEnabled() { return FLAGS_enable_rpcz.get(); }

namespace {
// Fallback identity (no server started yet): hostname + pid — unique
// across machines AND across processes on one machine, since the
// stitcher keys clock ownership on exact string equality.
std::string* rpcz_host() {
    static std::string* h = [] {
        char hostname[256] = "localhost";
        gethostname(hostname, sizeof(hostname) - 1);
        return new std::string(std::string(hostname) + ":pid:" +
                               std::to_string(getpid()));
    }();
    return h;
}
}  // namespace

void SetRpczHost(const std::string& host) {
    static bool set = false;
    if (set) return;  // first server wins (a process restart re-Starts)
    *rpcz_host() = host;
    set = true;
}

const std::string& RpczHost() { return *rpcz_host(); }

namespace {
std::string JsonEscape(const std::string& s) {
    std::string out;
    out.reserve(s.size() + 8);
    for (char c : s) {
        if (c == '"' || c == '\\') {
            out += '\\';
            out += c;
        } else if ((unsigned char)c < 0x20) {
            char buf[8];
            snprintf(buf, sizeof(buf), "\\u%04x", (unsigned char)c);
            out += buf;
        } else {
            out += c;
        }
    }
    return out;
}
}  // namespace

std::string RenderRpczJson(uint64_t trace_id_filter) {
    const std::vector<Span> spans =
        SpanDB::singleton()->Recent(trace_id_filter != 0 ? 256 : 64,
                                    trace_id_filter);
    std::string out = "{\"host\":\"" + JsonEscape(RpczHost()) +
                      "\",\"spans\":[";
    char buf[512];
    bool first = true;
    for (const Span& s : spans) {
        if (!first) out += ",";
        first = false;
        // uint64 ids go out as STRINGS: JSON doubles lose integers above
        // 2^53 and span ids use the full 64 bits.
        snprintf(buf, sizeof(buf),
                 "{\"trace_id\":\"%" PRIu64 "\",\"span_id\":\"%" PRIu64
                 "\",\"parent_span_id\":\"%" PRIu64 "\",\"kind\":\"%s\","
                 "\"error_code\":%d,\"retries\":%d,"
                 "\"request_bytes\":%" PRId64 ",\"response_bytes\":%" PRId64
                 ",\"start_us\":%" PRId64 ",\"sent_us\":%" PRId64
                 ",\"received_us\":%" PRId64 ",\"process_start_us\":%" PRId64
                 ",\"process_end_us\":%" PRId64 ",\"end_us\":%" PRId64,
                 s.trace_id, s.span_id, s.parent_span_id,
                 s.kind == Span::SERVER ? "SERVER" : "CLIENT", s.error_code,
                 s.retries, s.request_bytes, s.response_bytes, s.start_us,
                 s.sent_us, s.received_us, s.process_start_us,
                 s.process_end_us, s.end_us);
        out += buf;
        out += ",\"method\":\"" + JsonEscape(s.method) + "\"";
        out += ",\"remote\":\"" + JsonEscape(endpoint2str(s.remote_side)) +
               "\"";
        out += ",\"notes\":[";
        for (size_t i = 0; i < s.notes.size(); ++i) {
            if (i > 0) out += ",";
            snprintf(buf, sizeof(buf), "%+" PRId64 "us ",
                     s.notes[i].at_us - s.start_us);
            out += "\"" + JsonEscape(buf + s.notes[i].text) + "\"";
        }
        out += "]}";
    }
    out += "]}";
    return out;
}

std::string RenderRpcz(uint64_t trace_id_filter) {
    const std::vector<Span> spans =
        SpanDB::singleton()->Recent(trace_id_filter != 0 ? 256 : 64,
                                    trace_id_filter);
    std::string out;
    char line[512];
    snprintf(line, sizeof(line),
             "rpcz: %zu span(s)%s  (enable with /flags/enable_rpcz"
             "?setvalue=1; filter with /rpcz?trace_id=N)\n\n",
             spans.size(), trace_id_filter != 0 ? " [filtered]" : "");
    out += line;
    for (const Span& s : spans) {
        const int64_t total =
            s.end_us > s.start_us ? s.end_us - s.start_us : 0;
        snprintf(line, sizeof(line),
                 "trace=%" PRIu64 " span=%" PRIu64 " parent=%" PRIu64
                 " %s %s remote=%s total=%" PRId64 "us error=%d req=%" PRId64
                 "B res=%" PRId64 "B retries=%d\n",
                 s.trace_id, s.span_id, s.parent_span_id,
                 s.kind == Span::SERVER ? "SERVER" : "CLIENT",
                 s.method.c_str(), endpoint2str(s.remote_side).c_str(),
                 total, s.error_code, s.request_bytes, s.response_bytes,
                 s.retries);
        out += line;
        // Phase timeline, offsets from start. A phase whose timestamps
        // were never reached (early failure paths) prints as 0, not a
        // nonsense negative offset.
        auto phase = [](int64_t from, int64_t to) -> int64_t {
            return (from > 0 && to >= from) ? to - from : 0;
        };
        if (s.kind == Span::SERVER) {
            snprintf(line, sizeof(line),
                     "  received +0us  queued %" PRId64 "us  process %" PRId64
                     "us  write %" PRId64 "us\n",
                     phase(s.start_us, s.process_start_us),
                     phase(s.process_start_us, s.process_end_us),
                     phase(s.process_end_us, s.end_us));
        } else {
            snprintf(line, sizeof(line),
                     "  issued +0us  sent %" PRId64 "us  response %" PRId64
                     "us  done %" PRId64 "us\n",
                     phase(s.start_us, s.sent_us),
                     phase(s.sent_us, s.received_us),
                     s.received_us > 0 ? phase(s.received_us, s.end_us)
                                       : phase(s.sent_us, s.end_us));
        }
        out += line;
        for (const Span::Note& n : s.notes) {
            snprintf(line, sizeof(line), "  @%+" PRId64 "us %s\n",
                     n.at_us - s.start_us, n.text.c_str());
            out += line;
        }
    }
    return out;
}

}  // namespace tpurpc
