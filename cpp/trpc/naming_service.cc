// Naming service implementations: list://, file://, dns://.
// Reference impls: src/brpc/policy/{list,file,domain}_naming_service.*.
#include "trpc/naming_service.h"

#include <netdb.h>

#include <cstring>
#include <fstream>
#include <sstream>

#include "tbase/flags.h"
#include "tbase/logging.h"
#include "tfiber/fiber.h"

DEFINE_int32(ns_refresh_interval_ms, 5000,
             "Interval between naming-service refreshes (file mtime poll, "
             "DNS re-resolve)");

namespace tpurpc {

int ParseNamingLine(const std::string& raw, NSNode* out) {
    // Strip comments and whitespace; split "endpoint tag".
    std::string line = raw;
    const size_t hash = line.find('#');
    if (hash != std::string::npos) line.resize(hash);
    std::istringstream iss(line);
    std::string ep_str, tag;
    if (!(iss >> ep_str)) return -1;  // blank
    std::getline(iss, tag);
    // Trim tag.
    const size_t b = tag.find_first_not_of(" \t");
    tag = b == std::string::npos ? "" : tag.substr(b);
    const size_t e = tag.find_last_not_of(" \t\r");
    if (e != std::string::npos) tag.resize(e + 1);
    if (hostname2endpoint(ep_str.c_str(), &out->ep) != 0) return -1;
    out->tag = tag;
    return 0;
}

// One tag token by prefix ("w=", "zone="), scanning the space-separated
// list; "" when absent.
static std::string TagToken(const std::string& tag, const char* prefix) {
    const size_t plen = strlen(prefix);
    size_t pos = 0;
    while (pos < tag.size()) {
        size_t sp = tag.find(' ', pos);
        if (sp == std::string::npos) sp = tag.size();
        if (sp - pos > plen && tag.compare(pos, plen, prefix) == 0) {
            return tag.substr(pos + plen, sp - pos - plen);
        }
        pos = sp + 1;
    }
    return "";
}

int WeightFromTag(const std::string& tag) {
    const std::string w = TagToken(tag, "w=");
    if (!w.empty()) {
        const int n = atoi(w.c_str());
        if (n > 0) return n;
    }
    return 1;
}

std::string ZoneFromTag(const std::string& tag) {
    return TagToken(tag, "zone=");
}

// ---------------- periodic base ----------------

int PeriodicNamingService::RunNamingService(const char* service_name,
                                            NamingServiceActions* actions) {
    std::vector<NSNode> servers;
    while (!stop_.load(std::memory_order_acquire)) {
        servers.clear();
        if (GetServers(service_name, &servers) == 0) {
            actions->ResetServers(servers);
        }
        const int64_t interval_ms = FLAGS_ns_refresh_interval_ms.get();
        // Sleep in small slices so Destroy() takes effect quickly.
        for (int64_t slept = 0;
             slept < interval_ms && !stop_.load(std::memory_order_acquire);
             slept += 100) {
            fiber_usleep(100 * 1000);
        }
    }
    return 0;
}

void PeriodicNamingService::Destroy() {
    stop_.store(true, std::memory_order_release);
}

// ---------------- list:// ----------------
// "list://h1:p1,h2:p2 w=3,h3:p3" — static, pushed once.

class ListNamingService : public NamingService {
public:
    int RunNamingService(const char* service_name,
                         NamingServiceActions* actions) override {
        std::vector<NSNode> servers;
        std::string rest(service_name);
        size_t pos = 0;
        while (pos <= rest.size()) {
            size_t comma = rest.find(',', pos);
            if (comma == std::string::npos) comma = rest.size();
            NSNode node;
            if (ParseNamingLine(rest.substr(pos, comma - pos), &node) == 0) {
                servers.push_back(node);
            }
            pos = comma + 1;
        }
        actions->ResetServers(servers);
        return 0;
    }
    const char* scheme() const override { return "list"; }
};

// ---------------- file:// ----------------
// One server per line; re-read when mtime changes.

class FileNamingService : public PeriodicNamingService {
public:
    const char* scheme() const override { return "file"; }

protected:
    int GetServers(const char* service_name,
                   std::vector<NSNode>* out) override {
        std::ifstream in(service_name);
        if (!in) {
            LOG(WARNING) << "cannot open naming file " << service_name;
            return -1;
        }
        std::string line;
        while (std::getline(in, line)) {
            NSNode node;
            if (ParseNamingLine(line, &node) == 0) out->push_back(node);
        }
        return 0;
    }
};

// ---------------- dns:// ----------------
// "host:port" re-resolved every interval; every A record becomes a server.

class DomainNamingService : public PeriodicNamingService {
public:
    const char* scheme() const override { return "dns"; }

protected:
    int GetServers(const char* service_name,
                   std::vector<NSNode>* out) override {
        std::string host(service_name);
        int port = 80;
        const size_t colon = host.rfind(':');
        if (colon != std::string::npos) {
            port = atoi(host.c_str() + colon + 1);
            host.resize(colon);
        }
        addrinfo hints{};
        hints.ai_family = AF_INET;
        hints.ai_socktype = SOCK_STREAM;
        addrinfo* res = nullptr;
        if (getaddrinfo(host.c_str(), nullptr, &hints, &res) != 0) {
            LOG(WARNING) << "DNS resolve failed for " << host;
            return -1;
        }
        for (addrinfo* p = res; p != nullptr; p = p->ai_next) {
            NSNode node;
            node.ep.ip = ((sockaddr_in*)p->ai_addr)->sin_addr;
            node.ep.port = port;
            out->push_back(node);
        }
        freeaddrinfo(res);
        return 0;
    }
};

// ---------------- factory ----------------

NamingService* NamingService::New(const std::string& scheme) {
    if (scheme == "list") return new ListNamingService;
    if (scheme == "file") return new FileNamingService;
    if (scheme == "dns" || scheme == "http") return new DomainNamingService;
    return nullptr;
}

}  // namespace tpurpc
