// Payload compression for the native protocol.
//
// Reference: src/brpc/policy/gzip_compress.{h,cpp} + src/brpc/compress.h
// (a registry of compress handlers keyed by the wire's compress_type).
// The wire declares compress_type in RpcMeta (rpc_meta.proto:4); only the
// pb payload is compressed — attachments stay raw (zero-copy; same rule
// as the reference's baidu_std).
#pragma once

#include "tbase/iobuf.h"

namespace tpurpc {

enum CompressType {
    COMPRESS_NONE = 0,
    COMPRESS_GZIP = 1,
    // snappy via the runtime library (dlopen'd; reference
    // policy/snappy_compress.cpp). Check SnappyAvailable() on images
    // without libsnappy.
    COMPRESS_SNAPPY = 2,
};

bool SnappyAvailable();

// Compress/decompress `in` into `*out` (appended). Return false on error
// (corrupt input, unknown type). Decompressed size is capped to guard
// against zip bombs.
bool CompressBody(int compress_type, const IOBuf& in, IOBuf* out);
bool DecompressBody(int compress_type, const IOBuf& in, IOBuf* out);

// crc32c over every byte of an IOBuf without flattening (frame checksum).
uint32_t crc32c_iobuf(uint32_t crc, const IOBuf& buf);

}  // namespace tpurpc
