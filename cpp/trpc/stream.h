// Streaming RPC: unbounded ordered byte/message flow established by an
// RPC, with windowed flow control.
//
// Modeled on reference src/brpc/stream.{h,cpp} + stream_impl.h:
//  - StreamCreate attaches stream settings to an RPC's meta
//    (stream.cpp:47-122); StreamAccept answers server-side; data then
//    flows as STRM frames on the SAME connection
//    (policy/streaming_rpc_protocol.cpp:61-156).
//  - The reference wraps a "fake Socket" so stream writes reuse the
//    wait-free write queue; here Stream writes frames through the real
//    host Socket directly — the same queue, one object fewer.
//  - Receiving side runs handler callbacks in an ExecutionQueue (ordered,
//    batched: messages_in_batch); flow control is a window of unconsumed
//    bytes with explicit feedback frames (stream.h:55-88, SendFeedback
//    stream.cpp:631); writers block in StreamWait until the window opens
//    (stream.cpp:429-474 Wait/on_writable).
#pragma once

#include <atomic>
#include <cstdint>

#include "tbase/iobuf.h"
#include "tbase/versioned_ref.h"

namespace tpurpc {

class Controller;
using StreamId = VRefId;
constexpr StreamId INVALID_STREAM_ID = INVALID_VREF_ID;

// Receiving-side callbacks. Called on an ExecutionQueue consumer fiber —
// ordered, never concurrent for one stream.
class StreamInputHandler {
public:
    virtual ~StreamInputHandler() = default;
    virtual int on_received_messages(StreamId id, IOBuf* const messages[],
                                     size_t size) = 0;
    virtual void on_closed(StreamId id) = 0;
};

struct StreamOptions {
    // Bytes of unconsumed data we allow the PEER to have in flight toward
    // us (announced in the handshake; reference max_buf_size, default 2MB).
    int64_t window_size = 2 * 1024 * 1024;
    // Max messages per handler callback (reference messages_in_batch).
    size_t messages_in_batch = 128;
    StreamInputHandler* handler = nullptr;  // not owned
};

// ---- establishment (reference stream.h StreamCreate/StreamAccept) ----

// Client side, BEFORE issuing the RPC whose cntl is passed: creates the
// local stream and attaches settings to the RPC. The stream becomes
// writable once the RPC response accepts it; it fails if the RPC fails
// or the server does not accept.
int StreamCreate(StreamId* id, Controller* cntl,
                 const StreamOptions* options);

// Server side, INSIDE the service method, before done->Run(): accepts the
// requester's stream.
int StreamAccept(StreamId* id, Controller* cntl,
                 const StreamOptions* options);

// ---- data plane ----

// Queue one message; zero-copy moves *data. Returns 0, or -1 with errno:
// EAGAIN (peer window full — StreamWait then retry), EINVAL (bad id),
// EPIPE (closed).
int StreamWrite(StreamId id, IOBuf* data);

// Block the calling fiber until the stream is writable (or failed).
// abstime_us 0 = wait forever. Returns 0 when (likely) writable, else the
// POSITIVE error code (EPIPE peer/local close, EINVAL dead id, ETIMEDOUT).
// NOTE the direct return instead of the reference's -1+errno: a parked
// fiber can resume on a different worker thread, and compilers legally
// cache __errno_location() across calls — errno read by the CALLER after
// a suspending call may address the old thread's errno. Suspending APIs
// here therefore return their error code (errno is still set best-effort).
int StreamWait(StreamId id, int64_t abstime_us);

// Close: sends a CLOSE frame, fails the local stream; the peer's handler
// gets on_closed after delivering queued data. Idempotent-ish.
int StreamClose(StreamId id);

// ---- internals shared with the protocol layer ----

namespace stream_internal {

// Bind the client's half-open stream to the connection + peer settings
// (called by the response path).
int ConnectClientStream(StreamId id, VRefId socket_id, uint64_t peer_id,
                        int64_t peer_window);
void FailStream(StreamId id);  // RPC failed / peer vanished

// Frame handlers (called by the STRM protocol).
void OnStreamData(uint64_t stream_id, IOBuf* payload);
void OnStreamFeedback(uint64_t stream_id, int64_t consumed);
void OnStreamClose(uint64_t stream_id);

void RegisterStreamProtocolOrDie();  // idempotent; index for messengers
int StreamProtocolIndex();

}  // namespace stream_internal

}  // namespace tpurpc
