// Streaming RPC: unbounded ordered byte/message flow established by an
// RPC, with windowed flow control.
//
// Modeled on reference src/brpc/stream.{h,cpp} + stream_impl.h:
//  - StreamCreate attaches stream settings to an RPC's meta
//    (stream.cpp:47-122); StreamAccept answers server-side; data then
//    flows as STRM frames on the SAME connection
//    (policy/streaming_rpc_protocol.cpp:61-156).
//  - The reference wraps a "fake Socket" so stream writes reuse the
//    wait-free write queue; here Stream writes frames through the real
//    host Socket directly — the same queue, one object fewer.
//  - Receiving side runs handler callbacks in an ExecutionQueue (ordered,
//    batched: messages_in_batch); flow control is a window of unconsumed
//    bytes with explicit feedback frames (stream.h:55-88, SendFeedback
//    stream.cpp:631); writers block in StreamWait until the window opens
//    (stream.cpp:429-474 Wait/on_writable).
#pragma once

#include <atomic>
#include <cstdint>
#include <memory>
#include <string>

#include "tbase/iobuf.h"
#include "tbase/versioned_ref.h"

namespace tpurpc {

class Controller;
using StreamId = VRefId;
constexpr StreamId INVALID_STREAM_ID = INVALID_VREF_ID;

// Receiving-side callbacks. Called on an ExecutionQueue consumer fiber —
// ordered, never concurrent for one stream.
class StreamInputHandler {
public:
    virtual ~StreamInputHandler() = default;
    virtual int on_received_messages(StreamId id, IOBuf* const messages[],
                                     size_t size) = 0;
    virtual void on_closed(StreamId id) = 0;
};

struct StreamOptions {
    // Bytes of unconsumed data we allow the PEER to have in flight toward
    // us (announced in the handshake; reference max_buf_size, default 2MB).
    int64_t window_size = 2 * 1024 * 1024;
    // Max messages per handler callback (reference messages_in_batch).
    size_t messages_in_batch = 128;
    StreamInputHandler* handler = nullptr;  // not owned
};

// ---- establishment (reference stream.h StreamCreate/StreamAccept) ----

// Client side, BEFORE issuing the RPC whose cntl is passed: creates the
// local stream and attaches settings to the RPC. The stream becomes
// writable once the RPC response accepts it; it fails if the RPC fails
// or the server does not accept.
int StreamCreate(StreamId* id, Controller* cntl,
                 const StreamOptions* options);

// Server side, INSIDE the service method, before done->Run(): accepts the
// requester's stream.
int StreamAccept(StreamId* id, Controller* cntl,
                 const StreamOptions* options);

// ---- data plane ----

// Queue one message; zero-copy moves *data. Returns 0, or -1 with errno:
// EAGAIN (peer window full — StreamWait then retry), EINVAL (bad id),
// EPIPE (closed).
int StreamWrite(StreamId id, IOBuf* data);

// Block the calling fiber until the stream is writable (or failed).
// abstime_us 0 = wait forever. Returns 0 when (likely) writable, else the
// POSITIVE error code (EPIPE peer/local close, EINVAL dead id, ETIMEDOUT).
// NOTE the direct return instead of the reference's -1+errno: a parked
// fiber can resume on a different worker thread, and compilers legally
// cache __errno_location() across calls — errno read by the CALLER after
// a suspending call may address the old thread's errno. Suspending APIs
// here therefore return their error code (errno is still set best-effort).
int StreamWait(StreamId id, int64_t abstime_us);

// Close: sends a CLOSE frame, fails the local stream; the peer's handler
// gets on_closed after delivering queued data. Idempotent-ish.
int StreamClose(StreamId id);

// ---- server-push streams (ISSUE 17) ----
//
// A second, durable stream tier alongside the legacy STRM side channel
// above: chunks ride STREAM_DATA metas of the tpu_std protocol itself
// (RpcMeta.stream_frame), flow-controlled by receiver-granted chunk
// credits, and the stream is a RESUMABLE object — the client holds
// (stream_id, last contiguous seq) and re-issues the open with
// resume_from_seq on GOAWAY/EOF/backend death; the server replays from
// a bounded per-stream ring (same process) or regenerates
// deterministically from the offset (restarted process). Exactly-once
// delivery at the client by seq dedupe + reorder.
//
// Shape: reference brpc streaming RPC (StreamSettings handshake riding
// the rpc meta, data on the same connection) + the staged bounded-buffer
// orchestration of DMA-streaming-style token planes: a stalled consumer
// parks the WRITER fiber — queues never grow unbounded.

namespace push_stream {

constexpr int kStreamVersion = 1;

// StreamFrame.kind values (rpc_meta.proto).
enum FrameKind { KIND_DATA = 1, KIND_ACK = 2, KIND_CLOSE = 3 };
// StreamFrame.flags bits on DATA.
constexpr uint32_t kFlagEos = 1u;
constexpr uint32_t kFlagAbort = 2u;

struct ServerStreamState;
struct ReceiverState;

// Handler-facing writer returned by Controller::accept_stream(). Cheap
// shared handle; Write parks the calling fiber while the receiver's
// credit window or the replay ring is exhausted and while the stream is
// awaiting (re)binding to a connection.
class StreamWriter {
public:
    StreamWriter() = default;
    explicit StreamWriter(std::shared_ptr<ServerStreamState> st);
    bool valid() const { return state_ != nullptr; }
    uint64_t stream_id() const;
    // Client-held last contiguous seq at (re)open: generate/replay from
    // resume_from()+1. 0 = fresh stream.
    uint64_t resume_from() const;
    // Same-process resume rebind: the original generator fiber still
    // owns this stream (parked on the dead socket) — the handler must
    // NOT start a second generator; the replay ring + the woken writer
    // cover continuation.
    bool resumed_in_place() const;
    // Queue + send one chunk (seq auto-assigned). Parks until credits,
    // ring space and a bound connection are available. Returns 0, or a
    // TERR_* code once the stream is aborted/expired.
    int Write(const std::string& chunk, bool eos = false);
    uint64_t last_seq() const;  // highest seq handed to Write
    void Abort(int error_code);

private:
    std::shared_ptr<ServerStreamState> state_;
};

// Client-side stream call: owns the receiver registration for one
// logical stream across open + any number of resumes (SAME stream_id —
// the server's resume registry and the client's dedupe state key on it).
class StreamCall {
public:
    StreamCall();
    ~StreamCall();
    StreamCall(const StreamCall&) = delete;
    StreamCall& operator=(const StreamCall&) = delete;
    uint64_t stream_id() const { return id_; }
    uint64_t last_seq() const;     // last contiguous seq delivered
    uint64_t duplicates() const;   // deduped chunk arrivals (exactly-once)
    // Seed the resume origin of a FRESH call (relay use: a front door
    // resuming a client's offset against a new backend): PrepareOpen
    // stamps resume_from = `from` and delivery starts at from+1. No-op
    // once anything has arrived.
    void SeedResume(uint64_t from);
    // Stamp open/resume settings (push=true, version, -stream_rx_window,
    // resume_from = last_seq()) on the RPC about to be issued. Call
    // before EVERY open attempt, including resumes.
    void PrepareOpen(Controller* cntl);
    // Next contiguous chunk. Returns 0 (chunk+seq filled), 1 = stream
    // complete (EOS delivered), or a TERR_* code — on a retriable code
    // (TERR_EOF / TERR_RPC_TIMEDOUT) re-issue the open via PrepareOpen
    // to resume.
    int Read(std::string* chunk, uint64_t* seq, int timeout_ms);

private:
    uint64_t id_ = 0;
    std::shared_ptr<ReceiverState> rx_;
};

// ---- internals shared with policy_tpu_std / the portal ----

// One STREAM_* frame arrived on `socket_id` (DATA payload in *payload).
void OnFrame(VRefId socket_id, uint64_t stream_id, int kind, uint64_t seq,
             uint32_t flags, uint64_t ack_seq, int64_t credits,
             int error_code, IOBuf* payload);
// The accept response for `stream_id` was written to `socket_id`: bind
// the stream, grant the open's credit window, replay unacked ring
// entries, wake the writer.
void Activate(uint64_t stream_id, VRefId socket_id);
// The open's call failed after accept_stream(): abort without a bind.
void AbortServerStream(uint64_t stream_id, int error_code);
uint64_t NewClientStreamId();
void ExposeVars();              // rpc_stream_* families, 0-valued
int64_t RingHighwater();        // process-wide replay-ring high-water
std::string DescribeText();     // /streams
std::string DescribeJson();     // /streams?format=json
int64_t Opens();
int64_t Resumed();
int64_t ReplayedChunks();
int64_t CreditStalls();
int64_t Aborts();

}  // namespace push_stream

// ---- internals shared with the protocol layer ----

namespace stream_internal {

// Bind the client's half-open stream to the connection + peer settings
// (called by the response path).
int ConnectClientStream(StreamId id, VRefId socket_id, uint64_t peer_id,
                        int64_t peer_window);
void FailStream(StreamId id);  // RPC failed / peer vanished

// Frame handlers (called by the STRM protocol).
void OnStreamData(uint64_t stream_id, IOBuf* payload);
void OnStreamFeedback(uint64_t stream_id, int64_t consumed);
void OnStreamClose(uint64_t stream_id);

void RegisterStreamProtocolOrDie();  // idempotent; index for messengers
int StreamProtocolIndex();

}  // namespace stream_internal

}  // namespace tpurpc
