// protobuf <-> IOBuf glue.
// The reference bridges via IOBufAsZeroCopy{In,Out}putStream
// (src/butil/iobuf.h:163-195); we start with a copy-based path (payload pbs
// are small — bulk bytes ride attachments zero-copy) and will add the
// zero-copy streams in a perf pass.
#pragma once

#include <google/protobuf/message_lite.h>

#include <string>

#include "tbase/iobuf.h"

namespace tpurpc {

inline bool SerializePbToIOBuf(const google::protobuf::MessageLite& msg,
                               IOBuf* out) {
    std::string s;
    if (!msg.SerializeToString(&s)) return false;
    out->append(s);
    return true;
}

inline bool ParsePbFromIOBuf(google::protobuf::MessageLite* msg,
                             const IOBuf& buf) {
    return msg->ParseFromString(buf.to_string());
}

}  // namespace tpurpc
