#include "trpc/lb_with_naming.h"

#include <unordered_map>

#include "tbase/fast_rand.h"
#include "tbase/flags.h"
#include "tbase/logging.h"
#include "tbase/time.h"
#include "tfiber/butex.h"
#include "tfiber/fiber.h"
#include "trpc/channel.h"
#include "trpc/qos.h"

DEFINE_int32(ns_health_check_interval_ms, 1000,
             "Failed naming-resolved servers are probed this often and "
             "revived in place (0 disables)");
// Reference cluster_recover_policy.cpp DefaultClusterRecoverPolicy: gate
// traffic while a fully-down cluster revives one server at a time.
DEFINE_int32(cluster_recover_min_working_instances, 0,
             "enable cluster-recovery gating: while recovering, accept "
             "with probability usable/this (0 disables)");
DEFINE_int32(cluster_recover_hold_ms, 1000,
             "recovery ends once the usable-server count has been stable "
             "this long");
// Deterministic subsetting (ISSUE 8): each client talks to a
// rendezvous-hashed subset of the naming set instead of full-meshing
// every server (fleet-scale connection count drops from clients *
// servers to clients * subset_size). 0 disables.
DEFINE_int32(subset_size, 0,
             "deterministic client subsetting: connect to this many "
             "servers of the naming set (0 = all)");
DEFINE_int32(min_subset, 0,
             "recompute/fall back to the full set when fewer than this "
             "many subset members are live (0 = half of -subset_size, "
             "rounded up)");
DEFINE_int64(subset_seed, 0,
             "rendezvous seed for -subset_size (0 = random per process; "
             "fixed values make subsets reproducible for tests)");
// This process's pod identity (defined in load_balancer.cc): naming
// entries tagged with another zone get dcn-tier sockets here and land
// on the remote side of every ZoneAwareLoadBalancer.
DECLARE_string(rpc_zone);

namespace tpurpc {

// Adapter pushing naming results into the thread (lets RunNamingService
// stay ignorant of NamingServiceThread).
class NamingActions : public NamingServiceActions {
public:
    explicit NamingActions(NamingServiceThread* t) : t_(t) {}
    void ResetServers(const std::vector<NSNode>& servers) override {
        t_->ResetServers(servers);
    }

private:
    NamingServiceThread* t_;
};

NamingServiceThread::NamingServiceThread(std::string url, NamingService* ns,
                                         std::string rest)
    : url_(std::move(url)), ns_(ns), rest_(std::move(rest)) {
    first_batch_butex_ = butex_create();
}

// Stop a server socket for good: no more revives, then fail it so refs
// drain and the slot recycles.
static void RetireServerSocket(SocketId id) {
    Socket* s = Socket::UnsafeAddress(id);
    if (s != nullptr) s->StopHealthCheck();
    Socket::SetFailedById(id);
}

NamingServiceThread::~NamingServiceThread() {
    // Unreached in practice (registry keeps these alive process-wide).
    ns_->Destroy();
    std::lock_guard<std::mutex> g(mu_);
    for (auto& [node, id] : entries_) RetireServerSocket(id);
    entries_.clear();
    butex_destroy(first_batch_butex_);
}

void* NamingServiceThread::RunThunk(void* arg) {
    // The registry keeps NamingServiceThread objects alive for the whole
    // process (shared polling threads are few and channel-independent —
    // same lifetime the reference gives them in practice), so a raw
    // pointer is safe here.
    auto* t = (NamingServiceThread*)arg;
    NamingActions actions(t);
    t->ns_->RunNamingService(t->rest_.c_str(), &actions);
    return nullptr;
}

static std::mutex g_nst_mu;
static std::unordered_map<std::string,
                          std::shared_ptr<NamingServiceThread>>* g_nst_map;

std::shared_ptr<NamingServiceThread> NamingServiceThread::GetOrCreate(
    const std::string& url) {
    const size_t sep = url.find("://");
    if (sep == std::string::npos) return nullptr;
    const std::string scheme = url.substr(0, sep);
    const std::string rest = url.substr(sep + 3);

    std::lock_guard<std::mutex> g(g_nst_mu);
    if (g_nst_map == nullptr) {
        g_nst_map = new std::unordered_map<
            std::string, std::shared_ptr<NamingServiceThread>>;
    }
    auto it = g_nst_map->find(url);
    if (it != g_nst_map->end()) return it->second;
    NamingService* ns = NamingService::New(scheme);
    if (ns == nullptr) {
        LOG(ERROR) << "unknown naming scheme: " << scheme;
        return nullptr;
    }
    std::shared_ptr<NamingServiceThread> t(
        new NamingServiceThread(url, ns, rest));
    (*g_nst_map)[url] = t;
    fiber_t tid;
    if (fiber_start_background(&tid, nullptr, RunThunk, t.get()) != 0) {
        g_nst_map->erase(url);
        return nullptr;
    }
    return t;
}

void NamingServiceThread::ResetServers(const std::vector<NSNode>& servers) {
    std::vector<ServerNode> added;
    std::vector<SocketId> removed;
    std::set<Watcher*> watchers_snapshot;
    {
        std::lock_guard<std::mutex> g(mu_);
        const std::set<NSNode> fresh(servers.begin(), servers.end());
        // Removals: present here, absent in fresh.
        for (auto it = entries_.begin(); it != entries_.end();) {
            if (fresh.count(it->first) == 0) {
                removed.push_back(it->second);
                RetireServerSocket(it->second);
                it = entries_.erase(it);
            } else {
                ++it;
            }
        }
        // Additions: in fresh, not yet tracked.
        const std::string my_zone = FLAGS_rpc_zone.get();
        for (const NSNode& node : fresh) {
            if (entries_.count(node)) continue;
            const std::string zone = ZoneFromTag(node.tag);
            SocketOptions opts;
            opts.fd = -1;
            opts.remote_side = node.ep;
            opts.on_edge_triggered_events = &InputMessenger::OnNewMessages;
            opts.user = Channel::client_messenger();
            opts.health_check_interval_ms =
                FLAGS_ns_health_check_interval_ms.get();
            // Cross-pod entries ride the dcn tier (ISSUE 14): the
            // forced tier flips descriptor eligibility off, attributes
            // bytes to rpc_transport_*{transport="dcn"}, and subjects
            // the connection to the -dcn_emu_* WAN shaping.
            if (!zone.empty() && !my_zone.empty() && zone != my_zone) {
                opts.forced_transport_tier = TierDcn();
            }
            SocketId id;
            if (Socket::Create(opts, &id) != 0) {
                LOG(ERROR) << "Socket::Create failed for "
                           << endpoint2str(node.ep);
                continue;
            }
            entries_[node] = id;
            added.push_back({id, WeightFromTag(node.tag), node.ep, zone});
        }
        watchers_snapshot = watchers_;
    }
    for (Watcher* w : watchers_snapshot) {
        if (!added.empty() || !removed.empty()) {
            w->OnServersChanged(added, removed);
        }
    }
    // Signal first batch.
    std::atomic<int>* word = butex_word(first_batch_butex_);
    if (word->load(std::memory_order_acquire) == 0) {
        word->store(1, std::memory_order_release);
        butex_wake_all(first_batch_butex_);
    }
}

void NamingServiceThread::AddWatcher(Watcher* w) {
    std::vector<ServerNode> current;
    {
        std::lock_guard<std::mutex> g(mu_);
        watchers_.insert(w);
        for (const auto& [node, id] : entries_) {
            current.push_back({id, WeightFromTag(node.tag), node.ep,
                               ZoneFromTag(node.tag)});
        }
    }
    if (!current.empty()) w->OnServersChanged(current, {});
}

void NamingServiceThread::RemoveWatcher(Watcher* w) {
    std::lock_guard<std::mutex> g(mu_);
    watchers_.erase(w);
}

int NamingServiceThread::WaitForFirstBatch(int64_t timeout_ms) {
    std::atomic<int>* word = butex_word(first_batch_butex_);
    const int64_t deadline = monotonic_time_us() + timeout_ms * 1000;
    while (word->load(std::memory_order_acquire) == 0) {
        if (monotonic_time_us() >= deadline) return -1;
        butex_wait(first_batch_butex_, 0, &deadline);
    }
    return 0;
}

// ---------------- LoadBalancerWithNaming ----------------

LoadBalancerWithNaming::~LoadBalancerWithNaming() {
    if (ns_thread_) ns_thread_->RemoveWatcher(this);
}

int LoadBalancerWithNaming::Init(const std::string& naming_url,
                                 const std::string& lb_name) {
    lb_.reset(LoadBalancer::New(lb_name));
    if (!lb_) {
        LOG(ERROR) << "unknown load balancer: " << lb_name;
        return -1;
    }
    // The factory's outermost layer is the outlier wrapper (ISSUE 20):
    // keep a typed handle so subset recomputes can feed its ejection
    // floor (never eject below the per-zone subset minimum).
    outlier_lb_ = static_cast<outlier::OutlierLoadBalancer*>(lb_.get());
    // Per-client rendezvous identity: every client fleet member draws a
    // DIFFERENT subset (that is what spreads load), unless a fixed
    // -subset_seed pins it for reproducibility.
    const int64_t seed_flag = FLAGS_subset_seed.get();
    subset_seed_ = seed_flag != 0 ? (uint64_t)seed_flag : fast_rand();
    ns_thread_ = NamingServiceThread::GetOrCreate(naming_url);
    if (!ns_thread_) return -1;
    ns_thread_->AddWatcher(this);
    // Give the first resolution a chance so immediate calls see servers
    // (list:// resolves instantly; dns may take a beat).
    ns_thread_->WaitForFirstBatch(1000);
    return 0;
}

void LoadBalancerWithNaming::OnServersChanged(
    const std::vector<ServerNode>& added,
    const std::vector<SocketId>& removed) {
    if (FLAGS_subset_size.get() > 0) {
        // Subsetting layer: track the FULL naming set here; ApplySubset
        // diffs the rendezvous-chosen members into the LB policy.
        {
            std::lock_guard<std::mutex> g(subset_mu_);
            for (const ServerNode& s : added) all_nodes_[s.id] = s;
            for (SocketId id : removed) {
                all_nodes_.erase(id);
                if (in_lb_.erase(id) != 0) lb_->RemoveServer(id);
            }
        }
        ApplySubset(false);
    } else {
        if (!added.empty()) lb_->AddServersInBatch(added);
        if (!removed.empty()) lb_->RemoveServersInBatch(removed);
        std::lock_guard<std::mutex> g(subset_mu_);
        for (const ServerNode& s : added) {
            all_nodes_[s.id] = s;
            in_lb_.insert(s.id);
        }
        for (SocketId id : removed) {
            all_nodes_.erase(id);
            in_lb_.erase(id);
        }
    }
    std::lock_guard<std::mutex> g(servers_mu_);
    for (const ServerNode& s : added) server_ids_.push_back(s.id);
    for (SocketId id : removed) {
        for (size_t i = 0; i < server_ids_.size(); ++i) {
            if (server_ids_[i] == id) {
                server_ids_[i] = server_ids_.back();
                server_ids_.pop_back();
                break;
            }
        }
    }
}

std::vector<SocketId> LoadBalancerWithNaming::CurrentLbMembers() const {
    std::lock_guard<std::mutex> g(subset_mu_);
    return std::vector<SocketId>(in_lb_.begin(), in_lb_.end());
}

void LoadBalancerWithNaming::ApplySubset(bool force_full) {
    const int k = FLAGS_subset_size.get();
    std::lock_guard<std::mutex> g(subset_mu_);
    // Grouped by zone (ISSUE 14): the subset target and the live floor
    // apply PER ZONE, so a dying pod's recompute swaps members within
    // that pod only — the other pod's chosen members (and their warm
    // connections) never churn because of a remote breaker storm.
    // Live = addressable and not draining; the ring of candidates the
    // rendezvous hash scores. Keys come from registration-time endpoints
    // so every fleet member scores the same server identically.
    struct ZoneGroup {
        std::vector<SocketId> ids;       // every member of the zone
        std::vector<SocketId> live_ids;  // addressable + not draining
        std::vector<std::string> live_keys;
    };
    std::map<std::string, ZoneGroup> groups;
    for (const auto& [id, node] : all_nodes_) {
        ZoneGroup& grp = groups[node.zone];
        grp.ids.push_back(id);
        Socket* s = Socket::Address(id);
        if (s == nullptr) continue;
        const bool draining = s->Draining();
        s->Dereference();
        if (draining) continue;
        grp.live_ids.push_back(id);
        grp.live_keys.push_back(endpoint2str(node.ep));
    }
    const int eff_min = FLAGS_min_subset.get() > 0
                            ? FLAGS_min_subset.get()
                            : (k + 1) / 2;
    // Outlier-ejection floor (ISSUE 20): the detectors may never hold
    // more backends out of the pick set than would leave a zone's
    // subset below its live minimum.
    if (outlier_lb_ != nullptr) {
        outlier_lb_->tracker()->set_min_unejected(k > 0 ? eff_min : 1);
    }
    std::set<SocketId> desired;
    bool any_subsetted = false;
    for (auto& [zone, grp] : groups) {
        if (force_full || k <= 0 || (int)grp.ids.size() <= k ||
            (int)grp.live_ids.size() < eff_min) {
            // Full-set fallback FOR THIS ZONE: too few live members to
            // subset (or a retry already burned through the subset) —
            // better to spread over everything than to hammer the
            // survivors. A zone below its floor (e.g. freshly dead)
            // falls back alone; healthy zones keep their subsets.
            for (SocketId id : grp.ids) desired.insert(id);
        } else {
            // Rendezvous over the LIVE members only: a dead/draining
            // chosen member is replaced by the next-highest scorer
            // while every other choice stays put (HRW stability).
            for (size_t idx :
                 RendezvousSubset(subset_seed_, grp.live_keys,
                                  (size_t)k)) {
                desired.insert(grp.live_ids[idx]);
            }
            any_subsetted = true;
        }
    }
    subset_full_ = !any_subsetted;
    // Diff into the LB policy; in_lb_ itself is simply replaced below.
    for (SocketId id : desired) {
        if (in_lb_.count(id) == 0) {
            auto it = all_nodes_.find(id);
            if (it != all_nodes_.end()) lb_->AddServer(it->second);
        }
    }
    for (SocketId id : in_lb_) {
        if (desired.count(id) == 0) lb_->RemoveServer(id);
    }
    in_lb_ = std::move(desired);
}

void LoadBalancerWithNaming::MaybeRefreshSubset(const SelectIn& in) {
    if (FLAGS_subset_size.get() <= 0) return;
    // A retry that already tried every subset member must reach BEYOND
    // the subset instead of re-hitting tried servers: pin the full set
    // for now (the next healthy refresh shrinks back).
    bool force_full = false;
    {
        std::lock_guard<std::mutex> g(subset_mu_);
        if (in.excluded != nullptr && !subset_full_ &&
            in.excluded->size() >= (int)in_lb_.size()) {
            force_full = true;
        }
    }
    if (!force_full) {
        // Rate-limited health sweep: recompute only when the LIVE
        // subset shrank below the floor (kill/drain of chosen members
        // must spread load over the fallback set, not the survivors).
        const int64_t now = monotonic_time_us();
        int64_t last = last_subset_check_us_.load(std::memory_order_relaxed);
        if (now - last < 20 * 1000) return;
        if (!last_subset_check_us_.compare_exchange_strong(
                last, now, std::memory_order_relaxed)) {
            return;  // another selector is checking this tick
        }
        {
            std::lock_guard<std::mutex> g(subset_mu_);
            const int k = FLAGS_subset_size.get();
            const int eff_min = FLAGS_min_subset.get() > 0
                                    ? FLAGS_min_subset.get()
                                    : (k + 1) / 2;
            // Per-zone sweep (ISSUE 14): a zone whose chosen members
            // fell below the floor triggers the recompute even while
            // the other zone is perfectly healthy — and a healthy
            // zone's subset never churns because a remote one died.
            struct ZoneHealth {
                int live = 0;    // addressable + not draining, in lb
                int in_lb = 0;   // members this zone holds in the LB
                int total = 0;   // members this zone has in naming
            };
            std::map<std::string, ZoneHealth> zones;
            for (const auto& [id, node] : all_nodes_) {
                zones[node.zone].total++;
            }
            for (SocketId id : in_lb_) {
                auto node_it = all_nodes_.find(id);
                if (node_it == all_nodes_.end()) continue;
                // Touch the zone's entry even when this member is dead:
                // a zone whose members ALL died must still read as
                // live=0 below the floor, not vanish from the sweep.
                ZoneHealth& z = zones[node_it->second.zone];
                z.in_lb++;
                Socket* s = Socket::Address(id);
                if (s == nullptr) continue;
                const bool draining = s->Draining();
                s->Dereference();
                if (!draining) ++z.live;
            }
            bool recompute = zones.empty();
            for (const auto& [zone, z] : zones) {
                if (z.in_lb > 0 && z.live < eff_min) {
                    recompute = true;  // chosen members dying
                }
                // Shrink-back: a zone sitting in FULL-set fallback
                // (more members in the LB than the subset target) that
                // has healed above the floor should return to its
                // k-member subset — per zone, so one zone's recovery
                // never waits on (or churns) another.
                if (k > 0 && z.total > k && z.in_lb > k &&
                    z.live >= eff_min) {
                    recompute = true;
                }
            }
            if (!recompute) return;
        }
    }
    ApplySubset(force_full);
}

size_t LoadBalancerWithNaming::CountUsableServers() {
    std::lock_guard<std::mutex> g(servers_mu_);
    size_t usable = 0;
    for (SocketId id : server_ids_) {
        Socket* s = Socket::Address(id);
        if (s != nullptr) {
            s->Dereference();
            ++usable;
        }
    }
    return usable;
}

bool LoadBalancerWithNaming::RejectedByClusterRecovery() {
    const int min_working =
        FLAGS_cluster_recover_min_working_instances.get();
    if (min_working <= 0 || !recovering_.load(std::memory_order_acquire)) {
        return false;
    }
    const size_t usable = CountUsableServers();
    {
        std::lock_guard<std::mutex> g(recover_mu_);
        const int64_t now = monotonic_time_us();
        if (usable != last_usable_) {
            last_usable_ = usable;
            last_usable_change_us_ = now;
        } else if (usable > 0 && last_usable_change_us_ != 0 &&
                   now - last_usable_change_us_ >
                       (int64_t)FLAGS_cluster_recover_hold_ms.get() * 1000) {
            // Usable set stable long enough: the cluster has recovered.
            recovering_.store(false, std::memory_order_release);
            last_usable_ = 0;
            last_usable_change_us_ = 0;
            return false;
        }
    }
    // Accept with probability usable/min_working (reference DoReject).
    if (usable >= (size_t)min_working) return false;
    return fast_rand_less_than((uint64_t)min_working) >= usable;
}

int LoadBalancerWithNaming::SelectServer(const SelectIn& in,
                                         SelectOut* out) {
    if (RejectedByClusterRecovery()) {
        return EHOSTDOWN;  // held back while the cluster refills
    }
    // Deterministic subsetting upkeep (no-op unless -subset_size is on):
    // shrink-detection, excluded-exhaustion fallback, full-set recovery.
    MaybeRefreshSubset(in);
    const int rc = lb_->SelectServer(in, out);
    if ((rc == EHOSTDOWN || rc == ENODATA) &&
        FLAGS_cluster_recover_min_working_instances.get() > 0) {
        // Every server is down: revivals trickle in one by one — start
        // gating so the first one back is not crushed.
        recovering_.store(true, std::memory_order_release);
    }
    return rc;
}

}  // namespace tpurpc
