#include "trpc/policy_tpu_std.h"

#include <arpa/inet.h>
#include <csignal>

#include <cstring>
#include <mutex>

#include "rpc_meta.pb.h"
#include "tbase/errno.h"
#include "tbase/fast_rand.h"
#include "tbase/flags.h"
#include "tbase/flight_recorder.h"
#include "tbase/logging.h"
#include "tbase/time.h"
#include "tfiber/fiber.h"
#include "thttp/http2_client.h"
#include "thttp/http2_protocol.h"
#include "thttp/http_protocol.h"
#include "tici/block_lease.h"
#include "tici/block_pool.h"
#include "tici/shm_link.h"
#include "tici/verbs.h"
#include "tnet/transport.h"
#include "tnet/fault_injection.h"
#include "tnet/input_messenger.h"
#include "trpc/auth.h"
#include "trpc/controller.h"
#include "tbase/crc32c.h"
#include "trpc/compress.h"
#include "trpc/pb_compat.h"
#include "trpc/redis.h"
#include "trpc/rpc_dump.h"
#include "trpc/server.h"
#include "trpc/server_call.h"
#include "trpc/span.h"
#include "trpc/stream.h"
#include "tvar/reducer.h"

DECLARE_bool(rpc_checksum);

// Reference details/usercode_backup_pool.h: above this many in-flight
// user handlers, new ones run on an isolated worker pool (tag 63) so
// pthread-blocking user code cannot starve the IO path. <=0 disables.
DEFINE_int32(usercode_backup_threshold, 512,
             "in-flight user handlers before overflow is isolated");

// Push-stream descriptor eligibility (ISSUE 18 satellite): chunks at or
// above this ride descriptor-capable links as pool references instead
// of inline frame bytes; smaller chunks are not worth the pin+ack.
DEFINE_int64(stream_desc_min_bytes, 4096,
             "min push-stream chunk size sent as a pool descriptor on "
             "descriptor-capable links (first sends only; replays stay "
             "inline)");

namespace tpurpc {

namespace {
constexpr char kMagic[4] = {'T', 'R', 'P', 'C'};
constexpr size_t kHeaderLen = 12;
int g_tpu_std_index = -1;
}  // namespace

// Drain announcements received from peers (a GOAWAY meta marked this
// client's connection draining).
static LazyAdder g_drain_notices("rpc_client_drain_notices");
// One-sided descriptor resolution (ISSUE 9): attachments delivered as
// in-place views of a mapped sender pool — zero bytes copied.
static LazyAdder g_pool_desc_resolves("rpc_pool_descriptor_resolves");
static LazyAdder g_pool_desc_resolve_bytes(
    "rpc_pool_descriptor_resolve_bytes");
static LazyAdder g_pool_desc_rejects("rpc_pool_descriptor_rejects");
// Epoch-fence rejections (ISSUE 10b): descriptors minted under a pool
// generation this mapping no longer matches — answered with the
// retriable TERR_STALE_EPOCH, never a connection failure.
static LazyAdder g_pool_epoch_rejects("rpc_pool_epoch_rejects");
// Response-direction descriptor families (ISSUE 12): handlers answering
// with pool-block references — the symmetric twin of the request-side
// rpc_pool_descriptor_* counters.
static LazyAdder g_rsp_desc_sends("rpc_pool_desc_rsp_sends");
static LazyAdder g_rsp_desc_send_bytes("rpc_pool_desc_rsp_send_bytes");
static LazyAdder g_rsp_desc_fallbacks("rpc_pool_desc_rsp_fallbacks");
static LazyAdder g_rsp_desc_resolves("rpc_pool_desc_rsp_resolves");
static LazyAdder g_rsp_desc_resolve_bytes(
    "rpc_pool_desc_rsp_resolve_bytes");
static LazyAdder g_rsp_desc_rejects("rpc_pool_desc_rsp_rejects");
static LazyAdder g_rsp_desc_acks("rpc_pool_desc_rsp_acks");
// Push-stream chunks as descriptors (ISSUE 18 satellite): chunk sends
// that rode as pool references, shapes that fell back to inline bytes,
// receiver-side in-place resolves, and references the receiver could
// not honor (dropped frame — the stream's gap-NAK retransmit recovers
// the chunk inline).
static LazyAdder g_stream_desc_chunks("rpc_stream_desc_chunks");
static LazyAdder g_stream_desc_fallbacks("rpc_stream_desc_fallbacks");
static LazyAdder g_stream_desc_resolves("rpc_stream_desc_resolves");
static LazyAdder g_stream_desc_rejects("rpc_stream_desc_rejects");

namespace rsp_desc {
void CountSend(int64_t bytes) {
    *g_rsp_desc_sends << 1;
    *g_rsp_desc_send_bytes << bytes;
}
void CountFallback() { *g_rsp_desc_fallbacks << 1; }
void CountResolve(int64_t bytes) {
    *g_rsp_desc_resolves << 1;
    *g_rsp_desc_resolve_bytes << bytes;
}
void CountReject() { *g_rsp_desc_rejects << 1; }
void CountAck() { *g_rsp_desc_acks << 1; }
}  // namespace rsp_desc

int TpuStdProtocolIndex() { return g_tpu_std_index; }

ParseResult ParseTpuStdMessage(IOBuf* source, Socket* socket, bool read_eof,
                               const void* arg) {
    if (source->size() < kHeaderLen) {
        char head[4];
        const size_t n = source->copy_to(head, 4);
        if (memcmp(head, kMagic, n) != 0) {
            return ParseResult::make(ParseError::TRY_OTHERS);
        }
        return ParseResult::make(ParseError::NOT_ENOUGH_DATA);
    }
    char aux[kHeaderLen];
    const char* header = (const char*)source->fetch(aux, kHeaderLen);
    if (memcmp(header, kMagic, 4) != 0) {
        return ParseResult::make(ParseError::TRY_OTHERS);
    }
    uint32_t body_size, meta_size;
    memcpy(&body_size, header + 4, 4);
    memcpy(&meta_size, header + 8, 4);
    body_size = ntohl(body_size);
    meta_size = ntohl(meta_size);
    if (meta_size > body_size || body_size > (256u << 20)) {
        return ParseResult::make(ParseError::ERROR);
    }
    if (source->size() < kHeaderLen + body_size) {
        return ParseResult::make(ParseError::NOT_ENOUGH_DATA);
    }
    source->pop_front(kHeaderLen);
    auto* msg = new TpuStdMessage;
    source->cutn(&msg->meta, meta_size);
    source->cutn(&msg->body, body_size - meta_size);
    msg->byte_size = kHeaderLen + body_size;  // inline-dispatch size gate
    return ParseResult::make_ok(msg);
}

// Zero-cut fast path (ISSUE 7): classify the next frame of a sticky
// connection from the 12 contiguous header bytes — the messenger then
// waits for the announced frame size and calls parse exactly once, so a
// partially-arrived message costs no cutn and no re-parse per read.
int64_t PeekTpuStdFrame(const char* hdr, Socket*) {
    if (memcmp(hdr, kMagic, 4) != 0) return 0;  // re-sniff
    uint32_t body_size, meta_size;
    memcpy(&body_size, hdr + 4, 4);
    memcpy(&meta_size, hdr + 8, 4);
    body_size = ntohl(body_size);
    meta_size = ntohl(meta_size);
    if (meta_size > body_size || body_size > (256u << 20)) {
        return -1;  // corrupt: fail the connection
    }
    return (int64_t)kHeaderLen + body_size;
}

void SendTpuStdGoaway(Socket* s) {
    rpc::RpcMeta meta;
    meta.set_goaway(true);
    IOBuf meta_buf;
    SerializePbToIOBuf(meta, &meta_buf);
    IOBuf frame;
    PackTpuStdFrame(&frame, meta_buf, IOBuf(), IOBuf());
    s->Write(&frame);
}

void SendTpuStdCancel(SocketId sid, uint64_t cid) {
    rpc::RpcMeta meta;
    meta.set_correlation_id(cid);
    meta.set_cancel(true);
    IOBuf meta_buf;
    SerializePbToIOBuf(meta, &meta_buf);
    IOBuf frame;
    PackTpuStdFrame(&frame, meta_buf, IOBuf(), IOBuf());
    SocketUniquePtr s;
    if (Socket::AddressSocket(sid, &s) == 0) {
        s->Write(&frame);
    }
}

void SendTpuStdDescAck(SocketId sid, uint64_t cid, uint64_t ack_token) {
    rpc::RpcMeta meta;
    meta.set_correlation_id(cid);
    meta.set_desc_ack(true);
    if (ack_token != 0) meta.set_desc_ack_token(ack_token);
    IOBuf meta_buf;
    SerializePbToIOBuf(meta, &meta_buf);
    IOBuf frame;
    PackTpuStdFrame(&frame, meta_buf, IOBuf(), IOBuf());
    SocketUniquePtr s;
    if (Socket::AddressSocket(sid, &s) == 0) {
        s->Write(&frame);
    }
}

// ---- push-stream frames (ISSUE 17): meta-only frames with stream_frame
// set; DATA's chunk bytes ride as the frame payload.

int SendTpuStdStreamData(SocketId sid, uint64_t stream_id, uint64_t seq,
                         uint32_t flags, const std::string& chunk,
                         bool try_desc) {
    rpc::RpcMeta meta;
    auto* sf = meta.mutable_stream_frame();
    sf->set_stream_id(stream_id);
    sf->set_kind(1);  // KIND_DATA
    sf->set_seq(seq);
    if (flags != 0) sf->set_flags(flags);
    SocketUniquePtr s;
    if (Socket::AddressSocket(sid, &s) != 0) return -1;
    // Descriptor-eligible chunk (ISSUE 18 satellite): pin a pool copy
    // and send the REFERENCE; the receiver resolves in place and
    // desc_acks with correlation id = seq (the lease's armed call id).
    // Every failure mode falls back to inline bytes — and a pin whose
    // frame never reaches the peer is freed by the lease reaper.
    IOBuf payload;
    bool desc_sent = false;
    if (try_desc && !chunk.empty() &&
        (int64_t)chunk.size() >= FLAGS_stream_desc_min_bytes.get() &&
        TransportDescriptorCapable(s.get())) {
        IOBuf pin;
        if (IciBlockPool::AllocatePoolAttachmentCopy(
                chunk.data(), chunk.size(), &pin)) {
            size_t blen = 0;
            const char* bdata = pin.backing_block_data(0, &blen);
            uint64_t off = 0;
            if (blen == chunk.size() &&
                IciBlockPool::OffsetOf(bdata, &off)) {
                const uint32_t crc = crc32c_extend(0, bdata, blen);
                const uint64_t lease =
                    block_lease::Pin(std::move(pin), "rsp");
                if (block_lease::Arm(lease, seq, 0, (uint64_t)sid)) {
                    auto* pd = sf->mutable_pool_attachment();
                    pd->set_pool_id(IciBlockPool::pool_id());
                    pd->set_offset(off);
                    pd->set_length(chunk.size());
                    pd->set_crc32c(crc);
                    pd->set_pool_epoch(IciBlockPool::pool_epoch());
                    pd->set_ack_token(lease);
                    desc_sent = true;
                    *g_stream_desc_chunks << 1;
                    transport_stats::AddDescOut(s->transport_tier(),
                                                (int64_t)chunk.size());
                } else {
                    block_lease::Release(lease);
                }
            }
        }
        if (!desc_sent) *g_stream_desc_fallbacks << 1;
    }
    if (!desc_sent) payload.append(chunk);
    IOBuf meta_buf;
    SerializePbToIOBuf(meta, &meta_buf);
    IOBuf frame;
    PackTpuStdFrame(&frame, meta_buf, payload, IOBuf());
    return s->Write(&frame);
}

int SendTpuStdStreamAck(SocketId sid, uint64_t stream_id, uint64_t ack_seq,
                        int64_t credits) {
    rpc::RpcMeta meta;
    auto* sf = meta.mutable_stream_frame();
    sf->set_stream_id(stream_id);
    sf->set_kind(2);  // KIND_ACK
    sf->set_ack_seq(ack_seq);
    if (credits != 0) sf->set_credits(credits);
    IOBuf meta_buf;
    SerializePbToIOBuf(meta, &meta_buf);
    IOBuf frame;
    PackTpuStdFrame(&frame, meta_buf, IOBuf(), IOBuf());
    SocketUniquePtr s;
    if (Socket::AddressSocket(sid, &s) != 0) return -1;
    return s->Write(&frame);
}

int SendTpuStdStreamClose(SocketId sid, uint64_t stream_id,
                          int error_code) {
    rpc::RpcMeta meta;
    auto* sf = meta.mutable_stream_frame();
    sf->set_stream_id(stream_id);
    sf->set_kind(3);  // KIND_CLOSE
    if (error_code != 0) sf->set_error_code(error_code);
    IOBuf meta_buf;
    SerializePbToIOBuf(meta, &meta_buf);
    IOBuf frame;
    PackTpuStdFrame(&frame, meta_buf, IOBuf(), IOBuf());
    SocketUniquePtr s;
    if (Socket::AddressSocket(sid, &s) != 0) return -1;
    return s->Write(&frame);
}

// ---- one-sided verbs (ISSUE 18): meta-only grant/verb frames and the
// hooks the pb-free tici/verbs layer calls through. WindowGrant frames
// correlate by correlation_id; verb frames correlate by wr_id.

namespace {

int SendVerbGrantRequest(uint64_t sid, uint64_t token, uint64_t length,
                         uint32_t mode, int64_t lease_ms) {
    rpc::RpcMeta meta;
    meta.set_correlation_id(token);
    auto* wg = meta.mutable_window_grant();
    wg->set_kind(1);  // REQUEST
    wg->set_length(length);
    wg->set_mode(mode);
    if (lease_ms > 0) wg->set_lease_ms(lease_ms);
    IOBuf meta_buf;
    SerializePbToIOBuf(meta, &meta_buf);
    IOBuf frame;
    PackTpuStdFrame(&frame, meta_buf, IOBuf(), IOBuf());
    SocketUniquePtr s;
    if (Socket::AddressSocket((SocketId)sid, &s) != 0) return -1;
    return s->Write(&frame);
}

// The wire emulation of one posted verb (verb-incapable tiers, and
// capable tiers whose mapping went stale): WRITE's gathered bytes ride
// as the frame body; READ is meta-only out, bytes come back on the
// completion frame.
int SendVerbWire(uint64_t sid, int op, uint64_t wr_id,
                 uint64_t window_id, uint64_t offset, uint64_t len,
                 uint64_t epoch, uint32_t crc, const IOBuf& payload) {
    rpc::RpcMeta meta;
    auto* vp = meta.mutable_verb_post();
    vp->set_op(op);
    vp->set_wr_id(wr_id);
    vp->set_window_id(window_id);
    vp->set_offset(offset);
    vp->set_length(len);
    vp->set_pool_epoch(epoch);
    if (crc != 0 || op == verbs::kRemoteWrite) vp->set_crc32c(crc);
    IOBuf meta_buf;
    SerializePbToIOBuf(meta, &meta_buf);
    IOBuf frame;
    PackTpuStdFrame(&frame, meta_buf, payload, IOBuf());
    SocketUniquePtr s;
    if (Socket::AddressSocket((SocketId)sid, &s) != 0) return -1;
    return s->Write(&frame);
}

bool VerbOneSidedProbe(uint64_t sid) {
    SocketUniquePtr s;
    if (Socket::AddressSocket((SocketId)sid, &s) != 0) return false;
    return TransportOneSided(s.get());
}

uint32_t VerbSglMaxProbe(uint64_t sid) {
    SocketUniquePtr s;
    if (Socket::AddressSocket((SocketId)sid, &s) != 0) return 0;
    return TransportSglMax(s.get());
}

}  // namespace

void PackTpuStdFrame(IOBuf* out, const IOBuf& meta_pb, const IOBuf& payload,
                     const IOBuf& attachment) {
    char header[kHeaderLen];
    memcpy(header, kMagic, 4);
    const uint32_t body =
        htonl((uint32_t)(meta_pb.size() + payload.size() + attachment.size()));
    const uint32_t meta = htonl((uint32_t)meta_pb.size());
    memcpy(header + 4, &body, 4);
    memcpy(header + 8, &meta, 4);
    out->append(header, kHeaderLen);
    out->append(meta_pb);
    out->append(payload);
    out->append(attachment);
}

// ---------------- server side ----------------

namespace {

// done-closure finishing one server call: serialize + respond + stats.
class SendResponseClosure : public google::protobuf::Closure {
public:
    SendResponseClosure(Server* server, Server::MethodCallGuard* guard,
                        Controller* cntl, google::protobuf::Message* req,
                        google::protobuf::Message* res, SocketId sid,
                        uint64_t cid)
        : server_(server),
          guard_(guard),
          cntl_(cntl),
          req_(req),
          res_(res),
          sid_(sid),
          cid_(cid) {}

    // Multi-tenant accounting (ISSUE 8): `counted` becomes true once the
    // request is admitted to service (direct dispatch or fair-queue
    // pop) — only then does Run() report completion to the QoS tier. A
    // queued item shed before service runs this closure with counted
    // still false: its shed was already counted at the eviction site,
    // and its latency must not pollute the tenant's served-p99 or teach
    // the cost model. `method`/`bytes`/`peer` feed the work-priced cost
    // model (ISSUE 15): measured service time + logical payload bytes
    // (inline + descriptor-exempt) fold into the estimate the NEXT
    // request of this (tenant, method) is charged.
    void set_qos(QosDispatcher* qos, QosDispatcher::TenantState* tenant,
                 int64_t start_us, const std::string& method,
                 int64_t logical_bytes, const EndPoint& peer) {
        qos_ = qos;
        qos_tenant_ = tenant;
        qos_start_us_ = start_us;
        qos_method_ = method;
        qos_bytes_ = logical_bytes;
        qos_peer_ = peer;
    }
    void set_qos_counted() { qos_counted_ = true; }
    uint64_t wire_cid() const { return cid_; }

    void Run() override {
        flight::Record(flight::kRpcHandlerOut, cid_,
                       (uint64_t)cntl_->ErrorCode());
        if (cntl_->span_ != nullptr) {
            cntl_->span_->process_end_us = monotonic_time_us();
            // Annotated HERE, not in the cancel delivery path: the span is
            // owned by this strictly-sequential pipeline, and the cancel
            // thunk may race with span submission below.
            if (cntl_->IsCanceled()) {
                cntl_->span_->Annotate(
                    "canceled: upstream gave up (cascade delivered)");
            }
        }
        rpc::RpcMeta meta;
        auto* rmeta = meta.mutable_response();
        rmeta->set_error_code(cntl_->ErrorCode());
        if (cntl_->Failed()) {
            rmeta->set_error_text(cntl_->ErrorText());
            // Overload sheds tell the client when to come back; the
            // client jitters the value and spends a retry token.
            if (cntl_->ErrorCode() == TERR_OVERLOAD &&
                cntl_->suggested_backoff_ms() > 0) {
                rmeta->set_backoff_ms(cntl_->suggested_backoff_ms());
            }
        }
        meta.set_correlation_id(cid_);
        if (cntl_->accepted_stream() != INVALID_VREF_ID) {
            auto* ss = meta.mutable_stream_settings();
            ss->set_stream_id(cntl_->accepted_stream());
            ss->set_window_size(cntl_->accepted_stream_window());
        } else if (cntl_->accepted_push_stream() != 0) {
            // Push-stream accept echo (ISSUE 17): confirm the stream the
            // handler accepted; DATA starts flowing only after this
            // response is on the wire (Activate below).
            auto* ss = meta.mutable_stream_settings();
            ss->set_stream_id(cntl_->accepted_push_stream());
            ss->set_version(push_stream::kStreamVersion);
            ss->set_push(true);
        }
        IOBuf payload;
        if (!cntl_->Failed()) {
            if (!SerializePbToIOBuf(*res_, &payload)) {
                rmeta->set_error_code(TERR_RESPONSE);
                rmeta->set_error_text("serialize response failed");
                payload.clear();
            } else if (cntl_->response_compress_type() != COMPRESS_NONE) {
                IOBuf compressed;
                if (CompressBody(cntl_->response_compress_type(), payload,
                                 &compressed)) {
                    payload.swap(compressed);
                    meta.set_compress_type(cntl_->response_compress_type());
                }  // else: send uncompressed (compress_type stays unset)
            }
        }
        // Response-direction descriptor (ISSUE 12): the handler pinned a
        // pool block — arm its "rsp" lease with this call's identity
        // (owner = wire cid, expiry = the client's propagated deadline +
        // grace, peer = this connection) and emit the REFERENCE instead
        // of bytes. Ownership moves to the registry the moment the
        // descriptor goes on the wire: the client's desc_ack releases it
        // exactly once; a SIGKILLed client frees it through the socket
        // failure observer (server_call::OnSocketFailed -> ReleasePeer),
        // and the reaper covers a client that never acks.
        SocketUniquePtr s;
        const bool have_sock = Socket::AddressSocket(sid_, &s) == 0;
        if (cntl_->has_response_pool_attachment()) {
            const uint64_t rsp_lease = cntl_->TakeResponsePoolLease();
            const Controller::PoolAttachment& st =
                cntl_->response_pool_descriptor();
            const int64_t deadline = cntl_->has_server_deadline()
                                         ? cntl_->server_deadline_us()
                                         : 0;
            if (!cntl_->Failed() && have_sock &&
                block_lease::Arm(rsp_lease, cid_, deadline,
                                 (uint64_t)sid_)) {
                auto* pd = rmeta->mutable_pool_attachment();
                pd->set_pool_id(st.pool_id);
                pd->set_offset(st.offset);
                pd->set_length(st.length);
                pd->set_crc32c(st.crc32c);
                // Stamped at SEND time: a remap between the handler's
                // pin and this response carries the generation the
                // client's (re-)handshaken mapping expects.
                pd->set_pool_epoch(IciBlockPool::pool_epoch());
                // Completion token = the lease id: the ack releases by
                // direct lookup (call + connection still validated).
                pd->set_ack_token(rsp_lease);
                rsp_desc::CountSend((int64_t)st.length);
                transport_stats::AddDescOut(s->transport_tier(),
                                            (int64_t)st.length);
            } else {
                // Failed call, dead connection, or a pin the reaper
                // reclaimed under a wedged call: no reference may go
                // out. Drop the pin (exactly-once; a reaped lease is a
                // counted no-op) and — when the call would otherwise
                // report success — fail it with the retriable
                // stale-reference error instead of silently answering
                // without the attachment (data loss).
                block_lease::Release(rsp_lease);
                if (!cntl_->Failed()) {
                    rmeta->set_error_code(TERR_STALE_EPOCH);
                    rmeta->set_error_text(
                        "response pool pin reclaimed before send: "
                        "remap and retry");
                    payload.clear();
                }
            }
        }
        const IOBuf& att = cntl_->response_attachment();
        meta.set_attachment_size((uint32_t)att.size());
        if (FLAGS_rpc_checksum.get()) {
            uint32_t crc = crc32c_iobuf(0, payload);
            crc = crc32c_iobuf(crc, att);
            meta.set_body_checksum(crc);
        }
        IOBuf meta_buf;
        SerializePbToIOBuf(meta, &meta_buf);
        IOBuf frame;
        PackTpuStdFrame(&frame, meta_buf, payload, att);
        int wrc = -1;
        if (have_sock) {
            wrc = s->Write(&frame);
        }
        flight::Record(flight::kRpcWrite, cid_, payload.size());
        // Push-stream bind point (ISSUE 17): the accept echo is on the
        // wire — bind the stream to this connection, grant the open's
        // credit window and replay unacked ring entries. A failed call
        // or a dead connection aborts the open instead (without
        // unregistering an in-place resume's live generator: a fresh
        // resume re-open can still rescue it).
        if (cntl_->accepted_push_stream() != 0) {
            if (!cntl_->Failed() && wrc == 0) {
                push_stream::Activate(cntl_->accepted_push_stream(), sid_);
            } else {
                push_stream::AbortServerStream(
                    cntl_->accepted_push_stream(),
                    cntl_->Failed() ? cntl_->ErrorCode()
                                    : TERR_FAILED_SOCKET);
            }
        }
        if (cntl_->span_ != nullptr) {
            cntl_->span_->response_bytes = (int64_t)payload.size();
            cntl_->span_->end_us = monotonic_time_us();
            Collector::singleton()->submit(cntl_->span_);
            cntl_->span_ = nullptr;
        }
        // Cancellation teardown: deregister BEFORE destroying the id so
        // no new cancel can find a dying handle; DestroyServerCallId
        // serializes behind any in-flight cancel delivery (the thunk
        // holds the id lock while touching the controller).
        server_call::Unregister(sid_, cid_);
        cntl_->DestroyServerCallId();
        // Per-tenant completion BEFORE Finish: OnDone touches the
        // Server's QoS tier, and Finish must stay the LAST touch. The
        // completion info teaches the cost model and the tenant's
        // gradient limiter (failures punish the latency average).
        if (qos_tenant_ != nullptr && qos_counted_) {
            QosDispatcher::CompletionInfo ci;
            ci.error_code = cntl_->ErrorCode();
            ci.method = &qos_method_;
            ci.logical_bytes = qos_bytes_;
            ci.peer = qos_peer_;
            qos_->OnDone(qos_tenant_,
                         monotonic_time_us() - qos_start_us_, ci);
        }
        // Stats + limiter + Join wakeup; Finish is the LAST touch of
        // Server memory (the Server may be destroyed right after).
        guard_->Finish(cntl_->ErrorCode());
        delete guard_;
        delete req_;
        delete res_;
        delete cntl_;
        delete this;
    }

private:
    Server* server_;
    Server::MethodCallGuard* guard_;
    Controller* cntl_;
    google::protobuf::Message* req_;
    google::protobuf::Message* res_;
    SocketId sid_;
    uint64_t cid_;
    QosDispatcher* qos_ = nullptr;
    QosDispatcher::TenantState* qos_tenant_ = nullptr;
    int64_t qos_start_us_ = 0;
    bool qos_counted_ = false;
    std::string qos_method_;   // cost-model key ("Service.Method")
    int64_t qos_bytes_ = 0;    // inline + descriptor-exempt payload
    EndPoint qos_peer_;        // chaos cost_inflate scoping
};

// Carries one parsed request to its user-code fiber.
struct UserCallArgs {
    Server::MethodProperty* mp;
    Controller* cntl;
    google::protobuf::Message* req;
    google::protobuf::Message* res;
    google::protobuf::Closure* done;
    bool counted_default = false;  // holds a default-pool inflight count
};

// Usercode overload isolation (reference details/usercode_backup_pool.h
// TooManyUserCode): when too many user handlers occupy the DEFAULT pool
// — the hazard being handlers that BLOCK their worker pthread — the
// excess is routed to a reserved isolated tag pool so blocked user code
// can never consume every default worker and starve the IO fibers under
// it. Only default-pool residents are counted: once they drain below
// the threshold, new handlers use the default pool's free workers again
// instead of queueing behind the isolated backlog.
std::atomic<int64_t> g_usercode_default_inflight{0};
// kUsercodeBackupTag (policy_tpu_std.h): tag 63, reserved for this pool;
// Server::Start enforces the reservation.

// Last line of the expired-shed defense: the deadline may pass while the
// request waits for a handler fiber (queueing under overload is exactly
// when budgets die). True = the caller must run `done` WITHOUT invoking
// the service method.
bool ShedIfExpired(Server::MethodProperty* mp, Controller* cntl) {
    if (!cntl->has_server_deadline() ||
        monotonic_time_us() < cntl->server_deadline_us()) {
        return false;
    }
    mp->status->nexpired.fetch_add(1, std::memory_order_relaxed);
    server_call::CountExpired();
    if (cntl->span_ != nullptr) {
        cntl->span_->Annotate(
            "deadline shed: expired before handler dispatch");
    }
    cntl->SetFailed(TERR_RPC_TIMEDOUT,
                    "deadline expired before handler dispatch");
    return true;
}

// Invoke the service method with the fiber-local server-call context
// published (Channel::CallMethod inside the handler inherits the
// remaining deadline and registers for the cancel cascade through it).
void CallUserMethod(Server::MethodProperty* mp, Controller* cntl,
                    google::protobuf::Message* req,
                    google::protobuf::Message* res,
                    google::protobuf::Closure* done) {
    if (ShedIfExpired(mp, cntl)) {
        done->Run();
        return;
    }
    // Grey-failure chaos seam (ISSUE 20): AFTER admission/shedding so
    // the fault degrades only what the server actually accepted —
    // health probes, QoS and the connection stay perfect; nothing but a
    // latency/error-observing client (the outlier tier) can tell.
    if (__builtin_expect(fault_injection_enabled(), 0)) {
        const FaultAction fa = FaultInjection::Decide(
            FaultOp::kHandler, cntl->remote_side(), 0);
        if (fa.kind == FaultAction::kFail) {
            // Synthetic post-admission failure WITHOUT running the
            // handler. TERR_OVERCROWDED: retriable (the soak must lose
            // zero completions — the client re-issues elsewhere) yet a
            // hard error to the breaker and the outlier detector
            // (unlike TERR_OVERLOAD, which admission control owns).
            cntl->SetFailed(TERR_OVERCROWDED,
                            "chaos: synthetic handler failure");
            done->Run();
            return;
        }
        if (fa.kind == FaultAction::kDelay) {
            // Service-time inflation: the node is SLOW, not dead.
            fiber_usleep(fa.delay_us);
        }
    }
    // Within this protocol `done` is always the SendResponseClosure built
    // in ProcessTpuStdRequest — the only holder of the wire cid here.
    const uint64_t wire_cid =
        static_cast<SendResponseClosure*>(done)->wire_cid();
    flight::Record(flight::kRpcHandlerIn, wire_cid,
                   cntl->span_ != nullptr ? cntl->span_->trace_id : 0);
    ServerCallScope scope(cntl);
    mp->service->CallMethod(mp->method, cntl, req, res, done);
    // kRpcHandlerOut is recorded by SendResponseClosure::Run — a
    // synchronous handler has already run `done` (and freed cntl) here.
}

void* RunUserCall(void* arg) {
    auto* a = (UserCallArgs*)arg;
    if (a->cntl->span_ != nullptr) {
        a->cntl->span_->process_start_us = monotonic_time_us();
    }
    const bool counted = a->counted_default;
    CallUserMethod(a->mp, a->cntl, a->req, a->res, a->done);
    delete a;
    if (counted) {
        g_usercode_default_inflight.fetch_sub(1, std::memory_order_relaxed);
    }
    return nullptr;
}

// Usercode overflow-isolation routing shared by the direct and queued
// dispatch paths: count default-pool residents, overflow past the
// threshold onto the reserved backup tag.
FiberAttr UserCallAttr(Server* server, UserCallArgs* uc) {
    FiberAttr attr = FIBER_ATTR_NORMAL;
    attr.tag = server->options().fiber_tag;
    const int32_t backup_at = FLAGS_usercode_backup_threshold.get();
    if (attr.tag == 0 && backup_at > 0) {
        const int64_t inflight = g_usercode_default_inflight.fetch_add(
                                     1, std::memory_order_relaxed) +
                                 1;
        if (inflight > backup_at) {
            g_usercode_default_inflight.fetch_sub(
                1, std::memory_order_relaxed);
            attr.tag = kUsercodeBackupTag;  // overflow: isolated pool
        } else {
            uc->counted_default = true;
        }
    }
    return attr;
}

void SendErrorResponse(SocketId sid, uint64_t cid, int err,
                       const std::string& text, int64_t backoff_ms = 0) {
    rpc::RpcMeta meta;
    meta.mutable_response()->set_error_code(err);
    meta.mutable_response()->set_error_text(text);
    if (backoff_ms > 0) {
        meta.mutable_response()->set_backoff_ms(backoff_ms);
    }
    meta.set_correlation_id(cid);
    IOBuf meta_buf;
    SerializePbToIOBuf(meta, &meta_buf);
    IOBuf frame;
    PackTpuStdFrame(&frame, meta_buf, IOBuf(), IOBuf());
    SocketUniquePtr s;
    if (Socket::AddressSocket(sid, &s) == 0) {
        s->Write(&frame);
    }
}

// ---- fair-queue dispatch units (ISSUE 8) ----
// A request parked in the weighted-fair queue, ready for either service
// (drainer pop -> background handler fiber) or a priority shed.
struct QueuedCall {
    Server* server;
    Server::MethodProperty* mp;
    Controller* cntl;
    google::protobuf::Message* req;
    google::protobuf::Message* res;
    SendResponseClosure* done;
};

void RunQueuedCall(void* arg) {
    auto* qd = (QueuedCall*)arg;
    // Popped = admitted (the dispatcher accounted it): completions now
    // report to the QoS tier.
    qd->done->set_qos_counted();
    auto* uc = new UserCallArgs{qd->mp, qd->cntl, qd->req, qd->res,
                                qd->done};
    FiberAttr attr = UserCallAttr(qd->server, uc);
    fiber_t tid;
    // Always BACKGROUND from the drainer: an urgent handoff would park
    // the drainer fiber behind this handler and serialize the queue.
    if (fiber_start_background(&tid, &attr, RunUserCall, uc) != 0) {
        const bool counted = uc->counted_default;
        delete uc;
        if (counted) {
            g_usercode_default_inflight.fetch_sub(
                1, std::memory_order_relaxed);
        }
        // Fiber system saturated/shutting down — the overload case
        // itself. Running the handler INLINE here would head-of-line-
        // block the single drainer fiber and stall every queued tenant
        // (the opposite of the isolation guarantee): shed instead. The
        // closure still settles accounting (it was counted at pop).
        qd->cntl->set_suggested_backoff_ms(
            qd->server->qos()->SuggestedBackoffMs());
        qd->cntl->SetFailed(TERR_OVERLOAD,
                            "no worker fiber available for dispatch");
        qd->done->Run();
    }
    delete qd;
}

void ShedQueuedCall(void* arg, int64_t backoff_ms) {
    auto* qd = (QueuedCall*)arg;
    // The closure answers TERR_OVERLOAD (+ suggested backoff in the
    // response meta) and settles admission/stats/cancel-registry — the
    // same single funnel a served request uses.
    qd->cntl->set_suggested_backoff_ms(backoff_ms);
    qd->cntl->SetFailed(TERR_OVERLOAD,
                        "shed under overload: evicted from the fair "
                        "queue (lowest priority first)");
    if (qd->cntl->span_ != nullptr) {
        qd->cntl->span_->Annotate("overload shed: evicted from fair queue");
    }
    qd->done->Run();
    delete qd;
}

void ProcessTpuStdRequest(TpuStdMessage* msg, const rpc::RpcMeta& meta) {
    const SocketId sid = msg->socket_id;
    const uint64_t cid = meta.correlation_id();
    flight::Record(flight::kRpcDispatch, cid, msg->body.size());
    // rpc_dump: capture the raw meta+body of sampled requests (reference
    // rpc_dump.cpp via the bvar Collector; appending IOBufs only bumps
    // block refcounts, so the hot path pays two flag/gate loads).
    if (IsRpcDumpSampled()) {
        SubmitRpcDump(msg->meta, msg->body);
    }
    SocketUniquePtr s;
    if (Socket::AddressSocket(sid, &s) != 0) return;
    InputMessenger* m = (InputMessenger*)s->user();
    Server* server = m != nullptr ? (Server*)m->context : nullptr;
    if (server == nullptr) {
        return;  // no server bound (shutting down)
    }
    // Connection-level authentication (the Protocol `verify` hook,
    // reference protocol.h:77-172): the FIRST request must carry a valid
    // credential; the connection is trusted afterwards. Bad credentials
    // fail the whole connection, not just the call.
    if (server->options().auth != nullptr && !s->authenticated()) {
        AuthContext actx;
        if (!meta.has_auth_data() ||
            server->options().auth->VerifyCredential(
                meta.auth_data(), s->remote_side(), &actx) != 0) {
            SendErrorResponse(sid, cid, TERR_AUTH, "authentication failed");
            s->SetFailedWithError(TERR_AUTH);
            return;
        }
        s->SetAuthenticated(actx.user());
    }
    const auto& req_meta = meta.request();
    Server::MethodProperty* mp =
        server->FindMethod(req_meta.service_name(), req_meta.method_name());
    if (mp == nullptr) {
        SendErrorResponse(sid, cid, TERR_NO_METHOD,
                          "no such method " + req_meta.service_name() + "." +
                              req_meta.method_name());
        return;
    }
    // Server-side deadline: the meta carries the client's REMAINING
    // budget at send time (IssueRPC stamps (deadline - now)/1000, so a
    // caller that has already given up stamps <= 0). Shed expired
    // requests here — before admission, before parse, before a handler
    // fiber — executing them is pure waste the client will never read.
    const int64_t arrival_us = monotonic_time_us();
    int64_t deadline_us = 0;
    if (req_meta.has_timeout_ms()) {
        if (req_meta.timeout_ms() <= 0) {
            mp->status->nexpired.fetch_add(1, std::memory_order_relaxed);
            server_call::CountExpired();
            SendErrorResponse(sid, cid, TERR_RPC_TIMEDOUT,
                              "deadline already expired on arrival");
            return;
        }
        deadline_us = arrival_us + req_meta.timeout_ms() * 1000;
    }
    // Multi-tenant QoS stage 1 (ISSUE 8 + 15): identity + WORK-PRICED
    // rate quota. The tenant's token bucket answers BEFORE admission,
    // parse, or any allocation — charged this (tenant, method)'s
    // measured cost estimate, not a flat request count, so a tenant
    // inside its request rate cannot sink the server with
    // few-but-heavy calls. Cross-zone spill arrivals pay the
    // -rpc_spill_cost_multiplier on top. A flooding tenant is shed at
    // the cost of one bucket CAS, with TERR_OVERLOAD and a computed
    // "come back in N ms" that the client jitters (deadline-capped)
    // while spending retry budget.
    QosDispatcher* qos = server->qos();
    const bool qos_on = qos->enabled();
    QosDispatcher::TenantState* tstate = nullptr;
    const int priority = ClampPriority(
        req_meta.has_priority() ? req_meta.priority() : kDefaultPriority);
    const std::string method_key =
        req_meta.service_name() + "." + req_meta.method_name();
    int64_t cost_milli = kCostUnitMilli;
    bool spill = false;
    if (qos_on) {
        tstate = qos->Acquire(req_meta.tenant());
        cost_milli = qos->EstimateCostMilli(tstate, method_key);
        if (req_meta.has_zone() && SpillArrival(req_meta.zone())) {
            spill = true;
            cost_milli = SpillAdjustedCostMilli(cost_milli);
        }
        int64_t backoff_ms = 0;
        if (!qos->AdmitCost(tstate, arrival_us, cost_milli, &backoff_ms)) {
            SendErrorResponse(sid, cid, TERR_OVERLOAD,
                              "tenant '" + tstate->name +
                                  "' over its cost quota",
                              backoff_ms);
            return;
        }
    }
    // Admission control (reference ConcurrencyLimiter::OnRequested —
    // constant or gradient "auto" per ServerOptions). The remaining
    // budget rides along so the timeout limiter can shed requests that
    // cannot finish in time (AdmitWithBudget probes per priority class).
    auto* guard = new Server::MethodCallGuard(
        server, mp, deadline_us > 0 ? deadline_us - arrival_us : -1,
        priority);
    if (guard->rejected() && !guard->shed() && qos_on &&
        qos->EvictOneBelow(priority)) {
        // Priority-aware relief: a lower-priority queued request was
        // evicted (answered TERR_OVERLOAD); this request takes its place
        // with the concurrency check waived — net concurrency unchanged,
        // lowest priority shed first instead of first-come-first-served.
        delete guard;
        guard = new Server::MethodCallGuard(
            server, mp, deadline_us > 0 ? deadline_us - arrival_us : -1,
            priority, /*forced=*/true);
    }
    if (guard->rejected()) {
        const bool shed = guard->shed();
        delete guard;
        if (shed) {
            server_call::CountShed();
            SendErrorResponse(sid, cid, TERR_LIMIT_EXCEEDED,
                              "remaining deadline budget below observed "
                              "service time");
        } else if (qos_on) {
            // Overload, and nothing below this priority to evict: shed
            // with the retriable-with-backoff error so well-behaved
            // clients spread their re-issues.
            qos->CountShed(tstate, cost_milli);
            SendErrorResponse(sid, cid, TERR_OVERLOAD,
                              "overloaded: concurrency limit, no lower-"
                              "priority work to shed",
                              qos->SuggestedBackoffMs());
        } else {
            SendErrorResponse(sid, cid, TERR_LIMIT_EXCEEDED,
                              "concurrency limit");
        }
        return;
    }

    // Split payload / attachment.
    const uint32_t att_size = meta.attachment_size();
    if ((size_t)att_size > msg->body.size()) {
        guard->Finish(TERR_REQUEST);
        delete guard;
        SendErrorResponse(sid, cid, TERR_REQUEST,
                          "attachment_size exceeds body");
        return;
    }
    if (meta.has_body_checksum() &&
        crc32c_iobuf(0, msg->body) != meta.body_checksum()) {
        guard->Finish(TERR_REQUEST);
        delete guard;
        SendErrorResponse(sid, cid, TERR_REQUEST, "body checksum mismatch");
        return;
    }
    IOBuf payload;
    IOBuf attachment;
    const size_t payload_size = msg->body.size() - att_size;
    msg->body.cutn(&payload, payload_size);
    attachment.swap(msg->body);
    if (meta.compress_type() != COMPRESS_NONE) {
        IOBuf raw;
        if (!DecompressBody(meta.compress_type(), payload, &raw)) {
            guard->Finish(TERR_REQUEST);
            delete guard;
            SendErrorResponse(sid, cid, TERR_REQUEST,
                              "decompress request failed");
            return;
        }
        payload.swap(raw);
    }
    // One-sided pool attachment (ISSUE 9b): the meta names (pool_id,
    // offset, len, crc) in the SENDER's registered pool; resolve it
    // against our mapping of that pool (registered at the ICI
    // handshake) and hand the handler an in-place view — the payload
    // bytes are never copied host-side. Unknown pool = the sender used
    // descriptors on a link whose handshake never mapped its pool
    // (plain TCP): fail the call, not the connection.
    Controller::PoolAttachment pool_view;
    if (meta.has_pool_attachment()) {
        const auto& pd = meta.pool_attachment();
        // Scope check BEFORE the registry — now the Transport seam's
        // verdict (ISSUE 12): a connection may only reference the pool
        // its OWN handshake mapped (or, on an in-process transport
        // link, this process's pool), and only on a descriptor-capable
        // tier. The global registry alone must never authorize — any
        // connection could otherwise name another tenant's mapped pool,
        // or a plain-TCP peer this server's own, and read memory it was
        // never handed.
        const bool in_scope =
            TransportDescriptorScopeOk(s.get(), pd.pool_id());
        const char* pool_base = nullptr;
        size_t pool_size = 0;
        uint64_t map_epoch = 0;
        if (!in_scope ||
            !pool_registry::Resolve(pd.pool_id(), &pool_base,
                                    &pool_size, &map_epoch) ||
            pd.offset() > pool_size ||
            pd.length() > pool_size - pd.offset()) {
            *g_pool_desc_rejects << 1;
            guard->Finish(TERR_REQUEST);
            delete guard;
            SendErrorResponse(sid, cid, TERR_REQUEST,
                              "unresolvable pool descriptor (sender pool "
                              "not mapped on this link, or out of "
                              "bounds)");
            return;
        }
        // Chaos seam (chaos_pool, ISSUE 10d): crc corruption and stale-
        // epoch injection on the resolve path — both must fail ONLY
        // this call while the connection (and every other in-flight
        // descriptor) keeps working.
        bool chaos_corrupt = false;
        bool chaos_stale = false;
        if (__builtin_expect(fault_injection_enabled(), 0)) {
            const FaultAction fault = FaultInjection::Decide(
                FaultOp::kPoolResolve, s->remote_side(), pd.length());
            chaos_corrupt = fault.kind == FaultAction::kCorrupt;
            chaos_stale = fault.kind == FaultAction::kStaleEpoch;
        }
        // Epoch fence BEFORE the crc read: a descriptor minted under an
        // older (or injected-stale) generation may point at recycled
        // bytes — reject it as the RETRIABLE stale-reference error
        // without touching the memory. Absent/0 epoch = pre-epoch
        // sender, fence skipped (mixed-version caveat).
        if ((pd.has_pool_epoch() && pd.pool_epoch() != 0 &&
             pd.pool_epoch() != map_epoch) ||
            chaos_stale) {
            *g_pool_epoch_rejects << 1;
            guard->Finish(TERR_STALE_EPOCH);
            delete guard;
            SendErrorResponse(sid, cid, TERR_STALE_EPOCH,
                              "stale pool descriptor epoch (mapping at " +
                                  std::to_string(map_epoch) +
                                  "): remap and retry");
            return;
        }
        if ((pd.has_crc32c() &&
             crc32c_extend(0, pool_base + pd.offset(), pd.length()) !=
                 pd.crc32c()) ||
            chaos_corrupt) {
            *g_pool_desc_rejects << 1;
            guard->Finish(TERR_REQUEST);
            delete guard;
            SendErrorResponse(sid, cid, TERR_REQUEST,
                              "pool descriptor crc32c mismatch");
            return;
        }
        pool_view.data = pool_base + pd.offset();
        pool_view.length = pd.length();
        pool_view.pool_id = pd.pool_id();
        pool_view.offset = pd.offset();
        pool_view.crc32c = pd.crc32c();
        pool_view.pool_epoch = pd.pool_epoch();
        *g_pool_desc_resolves << 1;
        *g_pool_desc_resolve_bytes << (int64_t)pd.length();
        // The logical payload is exempt from the inline-dispatch byte
        // budget (only the tiny wire frame was charged — the referenced
        // bytes never pass through the message path), and it IS this
        // connection's data-plane throughput: attribute it.
        if (inline_dispatch::RoundArmed()) {
            inline_dispatch::ExemptDescriptorBytes(pd.length());
        }
        s->add_descriptor_bytes_read((int64_t)pd.length());
        transport_stats::AddDescIn(s->transport_tier(),
                                   (int64_t)pd.length());
    }

    const int64_t start_us = monotonic_time_us();
    auto* req = mp->service->GetRequestPrototype(mp->method).New();
    auto* res = mp->service->GetResponsePrototype(mp->method).New();
    auto* cntl = new Controller;
    cntl->InitServerSide(server, s->remote_side());
    cntl->set_server_socket(sid);
    cntl->set_server_deadline_us(deadline_us);
    // Expose the request's compression to the handler (reference
    // Controller::request_compress_type); the response defaults to none
    // unless the handler opts in.
    cntl->set_request_compress_type(meta.compress_type());
    // QoS identity on the call context: handler-issued child calls
    // inherit it (Channel::CallMethod), so a tenant's class follows its
    // traffic through the mesh.
    if (req_meta.has_tenant()) cntl->set_tenant(req_meta.tenant());
    cntl->set_priority(priority);
    if (req_meta.has_session()) cntl->set_session(req_meta.session());
    // Interceptor (reference interceptor.h:30 Interceptor::Accept runs
    // before the service method; rejection answers the error directly).
    if (server->options().interceptor != nullptr) {
        int err = 0;
        std::string etext;
        if (!server->options().interceptor->Accept(cntl, &err, &etext)) {
            guard->Finish(err != 0 ? err : TERR_REQUEST);
            delete guard;
            delete cntl;
            delete req;
            delete res;
            SendErrorResponse(sid, cid, err != 0 ? err : TERR_REQUEST,
                              etext.empty() ? "rejected by interceptor"
                                            : etext);
            return;
        }
    }
    // rpcz: with rpcz locally enabled, an upstream-sampled trace is
    // always continued (skipping the rate gate); otherwise the local gate
    // may start one. A disabled server NEVER allocates spans — peers must
    // not control that cost (reference span.h:236-240 enable_rpcz).
    if (IsRpczEnabled() && (req_meta.has_trace_id() || IsRpczSampled())) {
        auto* span = new Span;
        span->kind = Span::SERVER;
        span->trace_id =
            req_meta.has_trace_id() ? req_meta.trace_id() : fast_rand();
        span->parent_span_id =
            req_meta.has_span_id() ? req_meta.span_id() : 0;
        span->span_id = fast_rand();
        span->method =
            req_meta.service_name() + "." + req_meta.method_name();
        span->remote_side = s->remote_side();
        span->start_us = start_us;
        span->request_bytes = (int64_t)payload_size + att_size;
        cntl->span_ = span;
    }
    if (meta.has_stream_settings()) {
        const auto& ss = meta.stream_settings();
        if (ss.push()) {
            // Push-stream open/resume (ISSUE 17). A version newer than
            // ours is rejected below (fails the CALL — retriable at the
            // caller — never the connection).
            if (ss.version() <= push_stream::kStreamVersion) {
                cntl->SetPushStreamOpen(ss.stream_id(), ss.rx_window(),
                                        ss.resume_from_seq());
            }
        } else {
            cntl->SetRemoteStream(ss.stream_id(), ss.window_size());
        }
    }
    cntl->request_attachment() = attachment;
    if (pool_view.data != nullptr) {
        cntl->SetRequestPoolAttachmentView(pool_view);
    }
    // Cancelable handle: a tpu_std CANCEL meta, an h2 RST, or this
    // connection's death reaches the controller through the registry
    // (trpc/server_call.h); the done closure tears both down. Every path
    // from here runs the done closure, so the registration cannot leak.
    CallId scid = INVALID_CALL_ID;
    if (id_create(&scid, cntl, &Controller::HandleServerCancelThunk) == 0) {
        cntl->set_server_call_id(scid);
        server_call::Register(sid, cid, scid);
    }
    auto* done = new SendResponseClosure(server, guard, cntl, req, res, sid,
                                         cid);
    if (qos_on) {
        // Logical payload = inline body + attachment + the descriptor-
        // exempt referenced bytes (they never rode the message path but
        // they ARE the work this request represents).
        const int64_t logical_bytes =
            (int64_t)payload_size + (int64_t)att_size +
            (pool_view.data != nullptr ? (int64_t)pool_view.length : 0);
        done->set_qos(qos, tstate, arrival_us, method_key, logical_bytes,
                      s->remote_side());
    }
    if (!ParsePbFromIOBuf(req, payload)) {
        cntl->SetFailed(TERR_REQUEST, "parse request failed");
        done->Run();
        return;
    }
    if (meta.has_stream_settings() && meta.stream_settings().push() &&
        meta.stream_settings().version() > push_stream::kStreamVersion) {
        // Version-skewed push open: answer the call with a clean error
        // (the handler never runs, the connection stays healthy).
        cntl->SetFailed(TERR_REQUEST,
                        "unsupported push-stream version");
        done->Run();
        return;
    }
    // Multi-tenant QoS stage 3 (ISSUE 8): the weighted-fair dispatch
    // queue sits in front of handler spawn. Uncontended (queue empty,
    // tenant under its concurrency share) the request dispatches
    // DIRECTLY below — the PR-6 inline fast path stays legal exactly
    // then, so fairness never regresses the raw-speed win on
    // uncontended sockets. Contended, the request parks under
    // (priority, tenant-DRR) and the drainer fiber spawns handlers in
    // fair order; past the high-water the lowest-priority queued
    // request is shed first.
    if (qos_on) {
        if (!qos->TryDirectDispatch(tstate, cost_milli)) {
            auto* qd = new QueuedCall{server, mp, cntl, req, res, done};
            QosDispatcher::Item item;
            item.run = RunQueuedCall;
            item.shed = ShedQueuedCall;
            item.arg = qd;
            // The queued item carries its estimated (spill-adjusted)
            // charge: the DRR dequeue burns it against the tenant's
            // deficit, and spill items shed first within their level.
            item.cost_milli = cost_milli;
            item.spill = spill;
            qos->Enqueue(tstate, priority, item);
            return;
        }
        done->set_qos_counted();
    }
    // User code normally runs on its OWN fiber, never this one: a slow
    // handler on the input fiber would head-of-line-block the connection —
    // the backup request riding the same socket would not even be PARSED
    // until the original finished (reference keeps user code off the input
    // path: baidu_rpc_protocol.cpp:758,839-849,
    // details/usercode_backup_pool.h).
    //
    // Run-to-completion exception (ISSUE 7): a method flagged inline-safe
    // (Server::SetMethodInlineSafe — its handler promises to be cheap and
    // to NEVER block) runs right here. On the input fiber that means
    // read -> parse -> handler -> response write in one go, with the
    // response joining the round's coalesced writev.
    const bool method_inline =
        mp->inline_safe.load(std::memory_order_relaxed);
    if (server->options().usercode_inline || method_inline) {
        if (method_inline) inline_dispatch::CountHandlerInline();
        CallUserMethod(mp, cntl, req, res, done);
        return;
    }
    auto* uc = new UserCallArgs{mp, cntl, req, res, done};
    fiber_t tid;
    FiberAttr attr = UserCallAttr(server, uc);
    // Mid-burst (running on the input fiber with MORE bytes already read
    // and waiting in the cut loop): spawn in the BACKGROUND — an urgent
    // handoff would park the input fiber and serialize the whole burst
    // behind this handler. Give the budget unit back; this message fanned
    // out after all. The last/solo message of a wake (read_buf drained —
    // the classic single-request case) keeps the urgent path: the handler
    // takes this worker NOW, the input fiber has at most a read-EAGAIN
    // left (the reference's run-bthread-immediately ProcessEvent/usercode
    // spawns). read_buf is input-fiber-owned, and RoundArmed() is only
    // true ON the input fiber, so the read is race-free.
    const bool mid_burst =
        inline_dispatch::RoundArmed() && !s->read_buf.empty();
    if (mid_burst) inline_dispatch::Refund();
    const int spawn_rc =
        mid_burst ? fiber_start_background(&tid, &attr, RunUserCall, uc)
                  : fiber_start_urgent(&tid, &attr, RunUserCall, uc);
    if (spawn_rc != 0) {
        const bool counted = uc->counted_default;
        delete uc;  // fall back inline (fiber system saturated/shut down)
        if (counted) {
            g_usercode_default_inflight.fetch_sub(
                1, std::memory_order_relaxed);
        }
        CallUserMethod(mp, cntl, req, res, done);
    }
}

}  // namespace

// ---------------- client side ----------------

void ProcessTpuStdResponse(TpuStdMessage* msg, const rpc::RpcMeta& meta);

void ProcessTpuStdMessage(InputMessageBase* raw) {
    std::unique_ptr<TpuStdMessage> msg((TpuStdMessage*)raw);
    rpc::RpcMeta meta;
    if (!ParsePbFromIOBuf(&meta, msg->meta)) {
        SocketUniquePtr s;
        if (Socket::AddressSocket(msg->socket_id, &s) == 0) {
            s->SetFailedWithError(TERR_REQUEST);
        }
        return;
    }
    if (meta.goaway()) {
        // Drain announcement (the tpu_std GOAWAY): the peer is shutting
        // down deliberately. Mark the connection draining — in-flight
        // calls on it complete normally (the server keeps serving through
        // its drain window); NEW calls steer away (LB skips draining
        // nodes, pinned channels re-create).
        SocketUniquePtr s;
        if (Socket::AddressSocket(msg->socket_id, &s) == 0 &&
            !s->Draining()) {
            s->SetDraining();
            *g_drain_notices << 1;
        }
        return;
    }
    if (meta.cancel()) {
        // Cancel notification: mark the in-flight server call canceled
        // (stale-safe — a completed call's registry entry is gone).
        server_call::Cancel(msg->socket_id, meta.correlation_id());
        return;
    }
    if (meta.desc_ack()) {
        // Response-descriptor completion (ISSUE 12): the client finished
        // reading the descriptor we answered correlation_id with — drop
        // the pin. Scoped to the delivering connection (correlation ids
        // are only unique per client process) and exactly-once through
        // the lease registry: a duplicate or post-reap ack finds nothing
        // and is a no-op. Token-carrying acks release by direct lookup
        // (still call+connection validated); token-less acks pay the
        // ledger scan.
        if (meta.has_desc_ack_token() && meta.desc_ack_token() != 0) {
            block_lease::ReleaseAcked(meta.desc_ack_token(),
                                      meta.correlation_id(),
                                      (uint64_t)msg->socket_id);
        } else {
            block_lease::ReleaseByCall(meta.correlation_id(),
                                       (uint64_t)msg->socket_id);
        }
        rsp_desc::CountAck();
        return;
    }
    if (meta.has_window_grant()) {
        // Verb window grant exchange (ISSUE 18): REQUEST carves + pins
        // a window and answers GRANT on the same connection; GRANT
        // wakes the RequestWindow waiter by correlation token. Both
        // are meta-only frames.
        const auto& wg = meta.window_grant();
        if (wg.kind() == 1) {
            verbs::WindowInfo info;
            const int rc = verbs::HandleGrantRequest(
                (uint64_t)msg->socket_id, wg.length(), wg.mode(),
                wg.has_lease_ms() ? wg.lease_ms() : 0, &info);
            rpc::RpcMeta rsp;
            rsp.set_correlation_id(meta.correlation_id());
            auto* out = rsp.mutable_window_grant();
            out->set_kind(2);  // GRANT
            if (rc != 0) {
                out->set_status(rc);
            } else {
                out->set_window_id(info.window_id);
                out->set_pool_id(info.pool_id);
                out->set_offset(info.offset);
                out->set_length(info.length);
                out->set_pool_epoch(info.epoch);
                out->set_mode(info.mode);
                out->set_lease_ms(info.lease_ms);
            }
            IOBuf meta_buf;
            SerializePbToIOBuf(rsp, &meta_buf);
            IOBuf frame;
            PackTpuStdFrame(&frame, meta_buf, IOBuf(), IOBuf());
            SocketUniquePtr s;
            if (Socket::AddressSocket(msg->socket_id, &s) == 0) {
                s->Write(&frame);
            }
        } else {
            verbs::WindowInfo info;
            info.window_id = wg.window_id();
            info.pool_id = wg.pool_id();
            info.offset = wg.offset();
            info.length = wg.length();
            info.epoch = wg.pool_epoch();
            info.mode = wg.mode();
            info.lease_ms = wg.lease_ms();
            verbs::HandleGrantResponse(meta.correlation_id(),
                                       wg.status(), info);
        }
        return;
    }
    if (meta.has_verb_post()) {
        // Emulated two-sided verb at the TARGET (ISSUE 18): validate
        // against the granted window (epoch/lease/bounds/crc) and
        // answer a completion frame — READ's bytes ride back as its
        // body. A stale window answers TERR_STALE_EPOCH in the
        // completion status; the connection never fails.
        const auto& vp = meta.verb_post();
        IOBuf back;
        uint32_t crc = 0;
        const int rc = verbs::HandleWireVerb(
            (int)vp.op(), vp.wr_id(), vp.window_id(), vp.offset(),
            vp.length(), vp.pool_epoch(), vp.crc32c(), msg->body, &back,
            &crc);
        rpc::RpcMeta rsp;
        auto* vc = rsp.mutable_verb_completion();
        vc->set_wr_id(vp.wr_id());
        if (rc != 0) {
            vc->set_status(rc);
            back.clear();
        } else {
            vc->set_bytes(vp.length());
            if (!back.empty()) vc->set_crc32c(crc);
        }
        IOBuf meta_buf;
        SerializePbToIOBuf(rsp, &meta_buf);
        IOBuf frame;
        PackTpuStdFrame(&frame, meta_buf, back, IOBuf());
        SocketUniquePtr s;
        if (Socket::AddressSocket(msg->socket_id, &s) == 0) {
            s->Write(&frame);
        }
        return;
    }
    if (meta.has_verb_completion()) {
        const auto& vc = meta.verb_completion();
        verbs::HandleWireCompletion(vc.wr_id(), (int)vc.status(),
                                    msg->body, vc.crc32c());
        return;
    }
    if (meta.has_stream_frame() && !meta.has_request() &&
        !meta.has_response()) {
        // Push-stream tier frame (ISSUE 17): DATA/ACK/CLOSE keyed by
        // stream_id, not correlation_id. DATA's chunk bytes are the
        // frame body. Unknown kinds fail the STREAM inside OnFrame,
        // never this connection.
        const auto& sf = meta.stream_frame();
        if (sf.has_pool_attachment() &&
            (sf.kind() == 0 || sf.kind() == 1)) {
            // Descriptor-carried DATA chunk (ISSUE 18 satellite):
            // resolve the reference in place (scope -> registry ->
            // epoch -> crc, same fences as request descriptors), copy
            // into the frame body the stream layer expects, and ack so
            // the sender's pin drops. Any failure drops the FRAME only
            // — the stream's gap-NAK retransmit recovers the chunk
            // inline, and the sender's reaper frees the orphan pin.
            const auto& pd = sf.pool_attachment();
            bool ok = false;
            SocketUniquePtr s;
            if (Socket::AddressSocket(msg->socket_id, &s) == 0 &&
                TransportDescriptorScopeOk(s.get(), pd.pool_id())) {
                const char* base = nullptr;
                size_t size = 0;
                uint64_t ep = 0;
                if (pool_registry::Resolve(pd.pool_id(), &base, &size,
                                           &ep) &&
                    pd.offset() <= size &&
                    pd.length() <= size - pd.offset() &&
                    (!pd.has_pool_epoch() || pd.pool_epoch() == 0 ||
                     pd.pool_epoch() == ep) &&
                    (!pd.has_crc32c() ||
                     crc32c_extend(0, base + pd.offset(),
                                   pd.length()) == pd.crc32c())) {
                    msg->body.clear();
                    msg->body.append(base + pd.offset(),
                                     (size_t)pd.length());
                    *g_stream_desc_resolves << 1;
                    transport_stats::AddDescIn(s->transport_tier(),
                                               (int64_t)pd.length());
                    SendTpuStdDescAck(msg->socket_id, sf.seq(),
                                      pd.ack_token());
                    ok = true;
                }
            }
            if (!ok) {
                *g_stream_desc_rejects << 1;
                return;
            }
        }
        push_stream::OnFrame(msg->socket_id, sf.stream_id(),
                             sf.kind() == 0 ? 1 : sf.kind(), sf.seq(),
                             sf.flags(), sf.ack_seq(), sf.credits(),
                             sf.error_code(), &msg->body);
        return;
    }
    if (meta.has_request()) {
        ProcessTpuStdRequest(msg.get(), meta);
    } else {
        ProcessTpuStdResponse(msg.get(), meta);
    }
}

void GlobalInitializeOrDie() {
    static std::once_flag once;
    std::call_once(once, [] {
        // A peer closing mid-write must surface as EPIPE from the write,
        // not kill the process (reference global.cpp:333-337 ignores
        // SIGPIPE the same way; first bitten here by SSL_write on a
        // connection curl had already torn down). Respect a handler the
        // application installed itself.
        struct sigaction oldact;
        if (sigaction(SIGPIPE, nullptr, &oldact) != 0 ||
            (oldact.sa_handler == nullptr &&
             oldact.sa_sigaction == nullptr)) {
            CHECK(SIG_ERR != signal(SIGPIPE, SIG_IGN));
        }
        // Connection death cancels the server calls still in flight on
        // it (the observer hops to a fresh fiber before running any
        // cancellation, so SetFailed's callers never execute user code).
        Socket::set_failure_observer(&server_call::OnSocketFailed);
        // Epoch-fence + response-direction descriptor + transport-tier
        // families visible from the first scrape (lint contract: a
        // 0-valued counter is data; a missing one is not).
        *g_pool_epoch_rejects << 0;
        *g_rsp_desc_sends << 0;
        *g_rsp_desc_send_bytes << 0;
        *g_rsp_desc_fallbacks << 0;
        *g_rsp_desc_resolves << 0;
        *g_rsp_desc_resolve_bytes << 0;
        *g_rsp_desc_rejects << 0;
        *g_rsp_desc_acks << 0;
        *g_stream_desc_chunks << 0;
        *g_stream_desc_fallbacks << 0;
        *g_stream_desc_resolves << 0;
        *g_stream_desc_rejects << 0;
        transport_stats::ExposeVars();
        push_stream::ExposeVars();
        // One-sided verb plane (ISSUE 18): the pb-free tici layer moves
        // data; the wire seams (grant exchange + emulated two-sided
        // fallback) live here where the pb runtime is.
        verbs::SetGrantRequestSender(&SendVerbGrantRequest);
        verbs::SetVerbWireSender(&SendVerbWire);
        verbs::SetOneSidedProbe(&VerbOneSidedProbe);
        verbs::SetSglMaxProbe(&VerbSglMaxProbe);
        verbs::ExposeVars();
        Protocol p;
        p.parse = ParseTpuStdMessage;
        p.process = ProcessTpuStdMessage;
        p.name = "tpu_std";
        // Run-to-completion (ISSUE 7): small frames process on the input
        // fiber (responses complete RPCs; requests still fan their
        // handler out unless the method is flagged inline-safe), and the
        // 12-byte header peek skips the cut/re-parse loop on sticky
        // connections.
        p.inline_safe = true;
        p.peek = PeekTpuStdFrame;
        p.peek_len = kHeaderLen;
        g_tpu_std_index = RegisterProtocol(p);
        stream_internal::RegisterStreamProtocolOrDie();
        RegisterIciHandshakeProtocol();
        RegisterHttp2Protocol();
        RegisterHttp2ClientProtocol();
        RegisterHttpProtocol();
        RegisterRedisProtocols();
    });
}

}  // namespace tpurpc
