#include "trpc/combo_channels.h"

#include <algorithm>
#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <memory>

#include "tbase/errno.h"
#include "tbase/flags.h"
#include "tbase/logging.h"
#include "tbase/time.h"
#include "tfiber/fiber_sync.h"
#include "trpc/controller.h"
#include "trpc/naming_service.h"
#include "trpc/server_call.h"

// The channel-wide retry-budget defaults (defined in channel.cc);
// SelectiveChannel's cross-channel retry loop draws on the same knobs.
DECLARE_int32(rpc_retry_budget_tokens);
DECLARE_double(rpc_retry_budget_ratio);

namespace tpurpc {

namespace {

// Sub-call context inheritance (ISSUE 13 satellite): combo-channel
// sub-calls carry the PARENT controller's QoS identity and run under
// the parent's remaining deadline, exactly like Channel::CallMethod
// child calls. deadline_us = 0 means "parent set no deadline".
void InheritSubCallContext(Controller* parent, Controller* sub,
                           int64_t parent_deadline_us,
                           int64_t fallback_timeout_ms) {
    int64_t timeout_ms = fallback_timeout_ms;
    if (parent_deadline_us > 0) {
        const int64_t remaining_ms =
            (parent_deadline_us - monotonic_time_us()) / 1000;
        // Floor at 1ms (the live-budget floor the deadline stamp uses):
        // an already-expired parent still issues and fails fast through
        // the normal expiry path instead of hanging deadline-less.
        timeout_ms = remaining_ms > 1 ? remaining_ms : 1;
    }
    sub->set_timeout_ms(timeout_ms);
    if (!parent->tenant().empty() && sub->tenant().empty()) {
        sub->set_tenant(parent->tenant());
    }
    if (parent->has_priority() && !sub->has_priority()) {
        sub->set_priority(parent->priority());
    }
    if (!parent->session().empty() && sub->session().empty()) {
        sub->set_session(parent->session());
    }
    // Per-call hedge override (ISSUE 16): the router arms an adaptive
    // backup delay on the PARENT controller; the sub-call that actually
    // rides the wire must carry it or hedging silently never fires.
    if (parent->backup_request_ms() >= 0) {
        sub->set_backup_request_ms(parent->backup_request_ms());
    }
}

// The parent call's own absolute deadline: its timeout (or the combo
// option default), capped at the upstream server call's remaining
// budget when issued inside a handler (PR-2 inheritance).
int64_t ComboDeadlineUs(Controller* cntl, int64_t default_timeout_ms) {
    const int64_t timeout_ms =
        cntl->timeout_ms() >= 0 ? cntl->timeout_ms() : default_timeout_ms;
    int64_t deadline_us =
        timeout_ms > 0 ? monotonic_time_us() + timeout_ms * 1000 : 0;
    Controller* up = CurrentServerCall();
    if (up != nullptr && up->has_server_deadline()) {
        const int64_t upstream = up->server_deadline_us();
        if (deadline_us == 0 || upstream < deadline_us) {
            deadline_us = upstream;
        }
    }
    return deadline_us;
}

}  // namespace

// ---------------- ParallelChannel ----------------

ParallelChannel::ParallelChannel(const ParallelChannelOptions* options) {
    if (options != nullptr) options_ = *options;
}

ParallelChannel::~ParallelChannel() = default;

int ParallelChannel::AddChannel(google::protobuf::RpcChannel* sub,
                                CallMapper* mapper, ResponseMerger* merger) {
    return AddChannelShared(sub, std::shared_ptr<CallMapper>(mapper),
                            std::shared_ptr<ResponseMerger>(merger));
}

int ParallelChannel::AddChannelShared(google::protobuf::RpcChannel* sub,
                                      std::shared_ptr<CallMapper> mapper,
                                      std::shared_ptr<ResponseMerger> merger) {
    if (sub == nullptr) return -1;
    Sub s;
    s.chan = sub;
    s.mapper = std::move(mapper);
    s.merger = std::move(merger);
    subs_.push_back(std::move(s));
    return 0;
}

namespace {

// Aggregation state of one fanned-out call (reference
// ParallelChannelDone, parallel_channel.cpp:40-172). Heap-allocated;
// the LAST sub-completion finalizes the parent and deletes it.
struct FanoutCtx {
    struct SubState {
        Controller cntl;
        CallMapper::SubCall call;
        ResponseMerger* merger = nullptr;  // borrowed from the channel
        bool skipped = false;
    };

    Controller* parent = nullptr;
    google::protobuf::Message* response = nullptr;
    google::protobuf::Closure* done = nullptr;  // null = sync
    CountdownEvent sync_wait{0};
    // deque: SubState holds a (non-movable) Controller; elements are
    // constructed in place and never relocated.
    std::deque<SubState> subs;
    std::atomic<int> nleft{0};
    int fail_limit = 0;

    static void SubDone(FanoutCtx* ctx, int index) {
        // Per-sub-call observer BEFORE the parent can complete: the sub
        // Controller (and its response attachment / descriptor view) is
        // alive exactly until Finish runs.
        SubState& s = ctx->subs[index];
        if (s.call.observer != nullptr) {
            s.call.observer->OnSubCallDone(index, s.cntl);
        }
        if (ctx->nleft.fetch_sub(1, std::memory_order_acq_rel) == 1) {
            ctx->Finish();
        }
    }

    void Finish() {
        // All sub-calls done. Count failures FIRST: once the call is known
        // failed, the user's response must stay untouched — no partial
        // merge beside a SetFailed controller (reference
        // parallel_channel.cpp:313-319 counts then merges).
        int nfailed = 0;
        int first_error = 0;
        std::string first_text;
        int nran = 0;
        for (SubState& s : subs) {
            if (s.skipped) continue;
            ++nran;
            if (s.cntl.Failed()) {
                ++nfailed;
                if (first_error == 0) {
                    first_error = s.cntl.ErrorCode();
                    first_text = s.cntl.ErrorText();
                }
            }
        }
        // Unset (<=0) fail_limit matches the reference default: the parent
        // fails only when ALL sub-calls failed (parallel_channel.h:165-167).
        // Clamp to nran: a limit above the ran count must not report total
        // failure as success.
        const int limit = std::min(fail_limit > 0 ? fail_limit : nran,
                                   nran > 0 ? nran : 1);
        if (nfailed < limit && response != nullptr) {
            // Call so far succeeded: fold successful sub-responses in
            // sub-channel index order (deterministic merge, independent of
            // completion order). Merge into a scratch message so a merger
            // rejection that pushes the call over the limit leaves the
            // user's response untouched (no partial merge beside a failed
            // controller).
            std::unique_ptr<google::protobuf::Message> scratch(
                response->New());
            scratch->CopyFrom(*response);
            for (SubState& s : subs) {
                if (s.skipped || s.cntl.Failed()) continue;
                if (s.call.response == nullptr) continue;
                int rc = 0;
                if (s.merger != nullptr) {
                    rc = s.merger->Merge(scratch.get(), s.call.response);
                } else if (response != s.call.response) {
                    scratch->MergeFrom(*s.call.response);
                }
                if (rc < 0) {
                    ++nfailed;
                    if (first_error == 0) {
                        first_error = TERR_RESPONSE;
                        first_text = "response merger failed";
                    }
                }
            }
            if (nfailed < limit) {
                response->GetReflection()->Swap(response, scratch.get());
            }
        }
        if (nran == 0) {
            parent->SetFailed(TERR_INTERNAL, "all sub-calls skipped");
        } else if (nfailed >= limit) {
            parent->SetFailed(first_error != 0 ? first_error : TERR_INTERNAL,
                              "%d/%d sub-calls failed: %s", nfailed, nran,
                              first_text.c_str());
        }
        // Release owned sub-messages.
        for (SubState& s : subs) {
            if (s.call.owns_request) delete s.call.request;
            if (s.call.owns_response) delete s.call.response;
        }
        google::protobuf::Closure* user_done = done;
        if (user_done != nullptr) {
            delete this;
            user_done->Run();
        } else {
            sync_wait.signal();  // CallMethod's stack frame deletes us
        }
    }
};

}  // namespace

void ParallelChannel::CallMethod(
    const google::protobuf::MethodDescriptor* method,
    google::protobuf::RpcController* controller,
    const google::protobuf::Message* request,
    google::protobuf::Message* response, google::protobuf::Closure* done) {
    Controller* cntl = static_cast<Controller*>(controller);
    if (subs_.empty()) {
        cntl->SetFailed(TERR_INTERNAL, "ParallelChannel has no sub-channels");
        if (done != nullptr) done->Run();
        return;
    }
    auto* ctx = new FanoutCtx;
    ctx->parent = cntl;
    ctx->response = response;
    ctx->done = done;
    ctx->fail_limit = options_.fail_limit;
    ctx->subs.resize(subs_.size());
    // Parent deadline: own timeout capped at the upstream server call's
    // remaining budget (PR-2 semantics); every sub-call runs under it.
    const int64_t deadline_us = ComboDeadlineUs(cntl, options_.timeout_ms);
    const int64_t timeout_ms =
        cntl->timeout_ms() >= 0 ? cntl->timeout_ms() : options_.timeout_ms;

    // Map every sub-call first (so nleft is exact before any completion).
    int nactive = 0;
    for (size_t i = 0; i < subs_.size(); ++i) {
        FanoutCtx::SubState& s = ctx->subs[i];
        s.merger = subs_[i].merger.get();
        if (subs_[i].mapper != nullptr) {
            s.call = subs_[i].mapper->Map((int)i, (int)subs_.size(), method,
                                          request, response);
            if (s.call.skip) {
                s.skipped = true;
                continue;
            }
            if (s.call.method == nullptr) s.call.method = method;
            if (s.call.request == nullptr) s.call.request = request;
        } else {
            s.call.method = method;
            s.call.request = request;
        }
        if (s.call.response == nullptr) {
            s.call.response = response->New();
            s.call.owns_response = true;
        }
        ++nactive;
    }
    if (nactive == 0) {
        cntl->SetFailed(TERR_INTERNAL, "all sub-calls skipped");
        delete ctx;
        if (done != nullptr) done->Run();
        return;
    }
    ctx->nleft.store(nactive, std::memory_order_release);
    const bool sync = done == nullptr;
    if (sync) ctx->sync_wait.reset(1);

    // Snapshot the issue list BEFORE issuing anything: once the last
    // ACTIVE sub-call completes (possibly inline), ctx is gone — the loop
    // must not touch it again even to skip trailing mapped-out entries.
    struct Issue {
        google::protobuf::RpcChannel* chan;
        const google::protobuf::MethodDescriptor* method;
        Controller* cntl;
        const google::protobuf::Message* request;
        google::protobuf::Message* response;
        int index;
    };
    std::vector<Issue> issues;
    issues.reserve(nactive);
    for (size_t i = 0; i < subs_.size(); ++i) {
        FanoutCtx::SubState& s = ctx->subs[i];
        if (s.skipped) continue;
        // Sub-calls inherit the parent's remaining deadline, tenant and
        // priority (ISSUE 13 satellite); the trace span and cancel
        // cascade parent on the upstream server call via the issue
        // fiber's ServerCallScope, exactly like direct child calls.
        InheritSubCallContext(cntl, &s.cntl, deadline_us, timeout_ms);
        s.cntl.set_max_retry(cntl->max_retry());
        if (!s.call.request_attachment.empty()) {
            if (s.call.pool_descriptor) {
                s.cntl.set_request_pool_attachment(
                    std::move(s.call.request_attachment));
            } else {
                s.cntl.request_attachment().swap(
                    s.call.request_attachment);
            }
        }
        issues.push_back(Issue{subs_[i].chan, s.call.method, &s.cntl,
                               s.call.request, s.call.response, (int)i});
    }
    for (const Issue& is : issues) {
        is.chan->CallMethod(
            is.method, is.cntl, is.request, is.response,
            google::protobuf::NewCallback(&FanoutCtx::SubDone, ctx,
                                          is.index));
    }
    if (sync) {
        ctx->sync_wait.wait();
        delete ctx;
    }
}

// ---------------- PartitionParser ----------------

bool PartitionParser::ParseFromTag(const std::string& tag, Partition* out) {
    // "N/M": partition N of M.
    int index = -1, count = 0;
    if (sscanf(tag.c_str(), "%d/%d", &index, &count) != 2) return false;
    if (index < 0 || count <= 0 || index >= count) return false;
    out->index = index;
    out->count = count;
    return true;
}

// ---------------- PartitionChannel ----------------

PartitionChannel::PartitionChannel() = default;
PartitionChannel::~PartitionChannel() = default;

namespace {

// One-shot resolution through the registered naming service: stop the
// polling loop right after its first push.
class CollectActions : public NamingServiceActions {
public:
    explicit CollectActions(NamingService* ns) : ns_(ns) {}
    void ResetServers(const std::vector<NSNode>& servers) override {
        nodes = servers;
        got = true;
        ns_->Destroy();  // first push is all we need
    }
    NamingService* ns_;
    std::vector<NSNode> nodes;
    bool got = false;
};

int ResolveOnce(const char* naming_url, std::vector<NSNode>* out) {
    const char* sep = strstr(naming_url, "://");
    if (sep == nullptr) return -1;
    std::string scheme(naming_url, sep - naming_url);
    std::unique_ptr<NamingService> ns(NamingService::New(scheme));
    if (ns == nullptr) {
        LOG(ERROR) << "unknown naming scheme in " << naming_url;
        return -1;
    }
    CollectActions actions(ns.get());
    if (ns->RunNamingService(sep + 3, &actions) != 0 || !actions.got) {
        return -1;
    }
    *out = std::move(actions.nodes);
    return 0;
}

}  // namespace

int PartitionChannel::Init(const char* naming_url, const char* lb_name,
                           PartitionParser* parser,
                           const PartitionChannelOptions* options) {
    parser_.reset(parser != nullptr ? parser : new PartitionParser);
    PartitionChannelOptions opts;
    if (options != nullptr) opts = *options;
    // Ownership transfers at the call, not at success: wrap before any
    // early return or a failed Init leaks the caller's mapper/merger.
    std::shared_ptr<CallMapper> mapper(opts.call_mapper);
    std::shared_ptr<ResponseMerger> merger(opts.response_merger);

    std::vector<NSNode> nodes;
    if (ResolveOnce(naming_url, &nodes) != 0) return -1;

    // Partition membership by tag.
    std::map<int, std::string> members;  // index -> "ep,ep,..."
    int count = 0;
    for (const NSNode& n : nodes) {
        PartitionParser::Partition p;
        if (!parser_->ParseFromTag(n.tag, &p)) {
            LOG(WARNING) << "unparsable partition tag '" << n.tag << "' for "
                         << endpoint2str(n.ep);
            continue;
        }
        if (count == 0) count = p.count;
        if (p.count != count) {
            LOG(WARNING) << "mixed partition counts " << p.count << " vs "
                         << count << "; skipping " << endpoint2str(n.ep);
            continue;
        }
        std::string& list = members[p.index];
        if (!list.empty()) list += ",";
        list += endpoint2str(n.ep);
    }
    if (count == 0 || (int)members.size() != count) {
        LOG(ERROR) << "partition scheme incomplete: have " << members.size()
                   << " of " << count << " partitions";
        return -1;
    }

    ParallelChannelOptions popts = opts;
    fanout_.reset(new ParallelChannel(&popts));
    ChannelOptions chopts;
    chopts.timeout_ms = opts.timeout_ms;
    chopts.max_retry = opts.max_retry;
    for (int i = 0; i < count; ++i) {
        auto ch = std::make_unique<Channel>();
        const std::string url = "list://" + members[i];
        if (ch->Init(url.c_str(), lb_name, &chopts) != 0) return -1;
        if (fanout_->AddChannelShared(ch.get(), mapper, merger) != 0) {
            return -1;
        }
        parts_.push_back(std::move(ch));
    }
    nparts_ = count;
    return 0;
}

void PartitionChannel::CallMethod(
    const google::protobuf::MethodDescriptor* method,
    google::protobuf::RpcController* controller,
    const google::protobuf::Message* request,
    google::protobuf::Message* response, google::protobuf::Closure* done) {
    if (fanout_ == nullptr) {
        auto* cntl = static_cast<Controller*>(controller);
        cntl->SetFailed(TERR_INTERNAL, "PartitionChannel not initialized");
        if (done != nullptr) done->Run();
        return;
    }
    fanout_->CallMethod(method, controller, request, response, done);
}

// ---------------- SelectiveChannel ----------------

int SelectiveChannel::AddChannel(google::protobuf::RpcChannel* sub) {
    if (sub == nullptr) return -1;
    // Flag-default budget established at setup time (first AddChannel);
    // an explicit ConfigureRetryBudget — before OR after AddChannel,
    // but like AddChannel itself it must precede the first call —
    // overrides it. Keeping all configuration in the setup phase means
    // the hot path never races Configure against Withdraw.
    EnsureBudget();
    subs_.push_back(sub);
    return 0;
}

void SelectiveChannel::EnsureBudget() {
    if (!budget_configured_.exchange(true, std::memory_order_acq_rel)) {
        retry_budget_.Configure(FLAGS_rpc_retry_budget_tokens.get(),
                                FLAGS_rpc_retry_budget_ratio.get());
    }
}

// Per-call retry driver: issues on one sub-channel; a failure triggers the
// next sub-channel (the reference takes over IssueRPC via the _sender
// hook, selective_channel.cpp; the retry-on-another-channel semantics are
// the same). Cross-channel hops run through the channel's RetryBudget and
// the shared retry counters — the same funnel as in-channel re-issues.
struct SelectiveCallCtx {
    SelectiveChannel* chan;
    const google::protobuf::MethodDescriptor* method;
    Controller* parent;
    const google::protobuf::Message* request;
    google::protobuf::Message* response;
    google::protobuf::Closure* done;  // null = sync
    CountdownEvent sync_wait{1};
    Controller sub_cntl;
    int tries_left = 0;
    uint32_t next_index = 0;
    // Parent context captured at CallMethod: the absolute deadline every
    // hop runs under, and the upstream server call whose scope re-issues
    // replay (a retry fires on the completion fiber, where the caller's
    // fiber-local scope is gone — without the replay the hop would lose
    // trace parenting, the deadline cap and the cancel cascade). Valid
    // until the handler's done->Run(), same contract as Channel.
    int64_t deadline_us = 0;
    Controller* upstream = nullptr;

    void IssueOne() {
        sub_cntl.Reset();
        InheritSubCallContext(parent, &sub_cntl, deadline_us,
                              parent->timeout_ms());
        // Attachment bridge (ISSUE 16): a front door forwards the client's
        // inline attachment bytes; without the copy the backend would see
        // an empty attachment on every routed call. (Copy, not swap — a
        // cross-channel retry re-issues from the parent's intact buffer.)
        if (!parent->request_attachment().empty()) {
            sub_cntl.request_attachment() = parent->request_attachment();
        }
        const uint32_t idx = next_index++ % (uint32_t)chan->subs_.size();
        // Re-publish the upstream server call for the issue (no-op when
        // null or already current): the sub-channel's CallMethod then
        // parents its span, caps at the upstream budget and registers
        // for the cancel cascade exactly like any handler-issued call.
        ServerCallScope scope(upstream);
        chan->subs_[idx]->CallMethod(
            method, &sub_cntl, request, response,
            google::protobuf::NewCallback(&SelectiveCallCtx::OneDone, this));
    }

    static void OneDone(SelectiveCallCtx* ctx) {
        // Mirror hedge telemetry BEFORE any re-issue resets the
        // sub-controller: "a backup went out" is sticky across hops.
        if (ctx->sub_cntl.backup_issued()) {
            ctx->parent->set_backup_telemetry(
                true,
                ctx->parent->backup_won() || ctx->sub_cntl.backup_won());
        }
        if (ctx->sub_cntl.Failed() && ctx->tries_left-- > 0) {
            // TERR_DRAINING re-issues are budget-free (the draining
            // server provably never processed the call); everything
            // else withdraws a token like the in-channel funnel.
            const bool budget_free =
                ctx->sub_cntl.ErrorCode() == TERR_DRAINING;
            if (budget_free || ctx->chan->retry_budget_.Withdraw()) {
                if (!budget_free) client_stats::CountRetry();
                ctx->IssueOne();
                return;
            }
            client_stats::CountBudgetExhausted();
        }
        if (ctx->sub_cntl.Failed()) {
            ctx->parent->SetFailed(ctx->sub_cntl.ErrorCode(), "%s",
                                   ctx->sub_cntl.ErrorText().c_str());
            // Shed verdicts carry the server's backoff hint through to
            // the caller (the router forwards it to ITS client).
            if (ctx->sub_cntl.suggested_backoff_ms() > 0) {
                ctx->parent->set_suggested_backoff_ms(
                    ctx->sub_cntl.suggested_backoff_ms());
            }
        } else {
            ctx->chan->retry_budget_.OnSuccess();
            // Response-attachment bridge: hand the backend's attachment
            // bytes to the parent (move — the sub-controller is done).
            if (!ctx->sub_cntl.response_attachment().empty()) {
                ctx->parent->response_attachment().swap(
                    ctx->sub_cntl.response_attachment());
            }
        }
        google::protobuf::Closure* user_done = ctx->done;
        if (user_done != nullptr) {
            delete ctx;
            user_done->Run();
        } else {
            ctx->sync_wait.signal();
        }
    }
};

void SelectiveChannel::CallMethod(
    const google::protobuf::MethodDescriptor* method,
    google::protobuf::RpcController* controller,
    const google::protobuf::Message* request,
    google::protobuf::Message* response, google::protobuf::Closure* done) {
    Controller* cntl = static_cast<Controller*>(controller);
    if (subs_.empty()) {
        cntl->SetFailed(TERR_INTERNAL, "SelectiveChannel has no sub-channels");
        if (done != nullptr) done->Run();
        return;
    }
    auto* ctx = new SelectiveCallCtx;
    ctx->chan = this;
    ctx->method = method;
    ctx->parent = cntl;
    ctx->request = request;
    ctx->response = response;
    ctx->done = done;
    ctx->tries_left = cntl->max_retry();
    ctx->next_index = rr_.fetch_add(1, std::memory_order_relaxed);
    ctx->deadline_us = ComboDeadlineUs(cntl, cntl->timeout_ms());
    ctx->upstream = CurrentServerCall();
    const bool sync = done == nullptr;
    ctx->IssueOne();
    if (sync) {
        ctx->sync_wait.wait();
        delete ctx;
    }
}

// ---------------- DynamicPartitionChannel ----------------

int DynamicPartitionChannel::Init(const std::vector<std::string>& naming_urls,
                                  const char* lb_name,
                                  const PartitionChannelOptions* options) {
    // mapper/merger ownership is per-PartitionChannel; forwarding one raw
    // pointer into several schemes would double-free it. Schemes use the
    // defaults — custom ones are not supported here yet, and the caller's
    // objects must still be freed (ownership transferred at the call).
    PartitionChannelOptions per_scheme;
    if (options != nullptr) per_scheme = *options;
    if (per_scheme.call_mapper != nullptr ||
        per_scheme.response_merger != nullptr) {
        LOG(WARNING) << "DynamicPartitionChannel ignores custom "
                        "call_mapper/response_merger (schemes use defaults)";
        delete per_scheme.call_mapper;
        delete per_scheme.response_merger;
    }
    per_scheme.call_mapper = nullptr;
    per_scheme.response_merger = nullptr;
    for (const std::string& url : naming_urls) {
        std::vector<NSNode> nodes;
        int cap = 0;
        if (ResolveOnce(url.c_str(), &nodes) == 0) cap = (int)nodes.size();
        auto pc = std::make_unique<PartitionChannel>();
        if (cap > 0 &&
            pc->Init(url.c_str(), lb_name, nullptr, &per_scheme) == 0) {
            capacities_.push_back(cap);
            schemes_.push_back(std::move(pc));
        } else {
            capacities_.push_back(0);
            schemes_.push_back(nullptr);
        }
    }
    // Route to the scheme with the most servers (capacity-weighted
    // migration narrows to "pick max" with Init-time capacities).
    for (size_t i = 0; i < capacities_.size(); ++i) {
        if (schemes_[i] != nullptr &&
            (chosen_ < 0 || capacities_[i] > capacities_[chosen_])) {
            chosen_ = (int)i;
        }
    }
    return chosen_ >= 0 ? 0 : -1;
}

void DynamicPartitionChannel::CallMethod(
    const google::protobuf::MethodDescriptor* method,
    google::protobuf::RpcController* controller,
    const google::protobuf::Message* request,
    google::protobuf::Message* response, google::protobuf::Closure* done) {
    if (chosen_ < 0) {
        auto* cntl = static_cast<Controller*>(controller);
        cntl->SetFailed(TERR_INTERNAL, "no usable partition scheme");
        if (done != nullptr) done->Run();
        return;
    }
    schemes_[chosen_]->CallMethod(method, controller, request, response,
                                  done);
}

}  // namespace tpurpc
