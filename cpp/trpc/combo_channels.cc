#include "trpc/combo_channels.h"

#include <algorithm>
#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <memory>

#include "tbase/errno.h"
#include "tbase/logging.h"
#include "tfiber/fiber_sync.h"
#include "trpc/controller.h"
#include "trpc/naming_service.h"

namespace tpurpc {

// ---------------- ParallelChannel ----------------

ParallelChannel::ParallelChannel(const ParallelChannelOptions* options) {
    if (options != nullptr) options_ = *options;
}

ParallelChannel::~ParallelChannel() = default;

int ParallelChannel::AddChannel(google::protobuf::RpcChannel* sub,
                                CallMapper* mapper, ResponseMerger* merger) {
    return AddChannelShared(sub, std::shared_ptr<CallMapper>(mapper),
                            std::shared_ptr<ResponseMerger>(merger));
}

int ParallelChannel::AddChannelShared(google::protobuf::RpcChannel* sub,
                                      std::shared_ptr<CallMapper> mapper,
                                      std::shared_ptr<ResponseMerger> merger) {
    if (sub == nullptr) return -1;
    Sub s;
    s.chan = sub;
    s.mapper = std::move(mapper);
    s.merger = std::move(merger);
    subs_.push_back(std::move(s));
    return 0;
}

namespace {

// Aggregation state of one fanned-out call (reference
// ParallelChannelDone, parallel_channel.cpp:40-172). Heap-allocated;
// the LAST sub-completion finalizes the parent and deletes it.
struct FanoutCtx {
    struct SubState {
        Controller cntl;
        CallMapper::SubCall call;
        ResponseMerger* merger = nullptr;  // borrowed from the channel
        bool skipped = false;
    };

    Controller* parent = nullptr;
    google::protobuf::Message* response = nullptr;
    google::protobuf::Closure* done = nullptr;  // null = sync
    CountdownEvent sync_wait{0};
    // deque: SubState holds a (non-movable) Controller; elements are
    // constructed in place and never relocated.
    std::deque<SubState> subs;
    std::atomic<int> nleft{0};
    int fail_limit = 0;

    static void SubDone(FanoutCtx* ctx, int index) {
        if (ctx->nleft.fetch_sub(1, std::memory_order_acq_rel) == 1) {
            ctx->Finish();
        }
        (void)index;
    }

    void Finish() {
        // All sub-calls done. Count failures FIRST: once the call is known
        // failed, the user's response must stay untouched — no partial
        // merge beside a SetFailed controller (reference
        // parallel_channel.cpp:313-319 counts then merges).
        int nfailed = 0;
        int first_error = 0;
        std::string first_text;
        int nran = 0;
        for (SubState& s : subs) {
            if (s.skipped) continue;
            ++nran;
            if (s.cntl.Failed()) {
                ++nfailed;
                if (first_error == 0) {
                    first_error = s.cntl.ErrorCode();
                    first_text = s.cntl.ErrorText();
                }
            }
        }
        // Unset (<=0) fail_limit matches the reference default: the parent
        // fails only when ALL sub-calls failed (parallel_channel.h:165-167).
        // Clamp to nran: a limit above the ran count must not report total
        // failure as success.
        const int limit = std::min(fail_limit > 0 ? fail_limit : nran,
                                   nran > 0 ? nran : 1);
        if (nfailed < limit && response != nullptr) {
            // Call so far succeeded: fold successful sub-responses in
            // sub-channel index order (deterministic merge, independent of
            // completion order). Merge into a scratch message so a merger
            // rejection that pushes the call over the limit leaves the
            // user's response untouched (no partial merge beside a failed
            // controller).
            std::unique_ptr<google::protobuf::Message> scratch(
                response->New());
            scratch->CopyFrom(*response);
            for (SubState& s : subs) {
                if (s.skipped || s.cntl.Failed()) continue;
                if (s.call.response == nullptr) continue;
                int rc = 0;
                if (s.merger != nullptr) {
                    rc = s.merger->Merge(scratch.get(), s.call.response);
                } else if (response != s.call.response) {
                    scratch->MergeFrom(*s.call.response);
                }
                if (rc < 0) {
                    ++nfailed;
                    if (first_error == 0) {
                        first_error = TERR_RESPONSE;
                        first_text = "response merger failed";
                    }
                }
            }
            if (nfailed < limit) {
                response->GetReflection()->Swap(response, scratch.get());
            }
        }
        if (nran == 0) {
            parent->SetFailed(TERR_INTERNAL, "all sub-calls skipped");
        } else if (nfailed >= limit) {
            parent->SetFailed(first_error != 0 ? first_error : TERR_INTERNAL,
                              "%d/%d sub-calls failed: %s", nfailed, nran,
                              first_text.c_str());
        }
        // Release owned sub-messages.
        for (SubState& s : subs) {
            if (s.call.owns_request) delete s.call.request;
            if (s.call.owns_response) delete s.call.response;
        }
        google::protobuf::Closure* user_done = done;
        if (user_done != nullptr) {
            delete this;
            user_done->Run();
        } else {
            sync_wait.signal();  // CallMethod's stack frame deletes us
        }
    }
};

}  // namespace

void ParallelChannel::CallMethod(
    const google::protobuf::MethodDescriptor* method,
    google::protobuf::RpcController* controller,
    const google::protobuf::Message* request,
    google::protobuf::Message* response, google::protobuf::Closure* done) {
    Controller* cntl = static_cast<Controller*>(controller);
    if (subs_.empty()) {
        cntl->SetFailed(TERR_INTERNAL, "ParallelChannel has no sub-channels");
        if (done != nullptr) done->Run();
        return;
    }
    auto* ctx = new FanoutCtx;
    ctx->parent = cntl;
    ctx->response = response;
    ctx->done = done;
    ctx->fail_limit = options_.fail_limit;
    ctx->subs.resize(subs_.size());
    const int64_t timeout_ms =
        cntl->timeout_ms() >= 0 ? cntl->timeout_ms() : options_.timeout_ms;

    // Map every sub-call first (so nleft is exact before any completion).
    int nactive = 0;
    for (size_t i = 0; i < subs_.size(); ++i) {
        FanoutCtx::SubState& s = ctx->subs[i];
        s.merger = subs_[i].merger.get();
        if (subs_[i].mapper != nullptr) {
            s.call = subs_[i].mapper->Map((int)i, (int)subs_.size(), method,
                                          request, response);
            if (s.call.skip) {
                s.skipped = true;
                continue;
            }
            if (s.call.method == nullptr) s.call.method = method;
            if (s.call.request == nullptr) s.call.request = request;
        } else {
            s.call.method = method;
            s.call.request = request;
        }
        if (s.call.response == nullptr) {
            s.call.response = response->New();
            s.call.owns_response = true;
        }
        ++nactive;
    }
    if (nactive == 0) {
        cntl->SetFailed(TERR_INTERNAL, "all sub-calls skipped");
        delete ctx;
        if (done != nullptr) done->Run();
        return;
    }
    ctx->nleft.store(nactive, std::memory_order_release);
    const bool sync = done == nullptr;
    if (sync) ctx->sync_wait.reset(1);

    // Snapshot the issue list BEFORE issuing anything: once the last
    // ACTIVE sub-call completes (possibly inline), ctx is gone — the loop
    // must not touch it again even to skip trailing mapped-out entries.
    struct Issue {
        google::protobuf::RpcChannel* chan;
        const google::protobuf::MethodDescriptor* method;
        Controller* cntl;
        const google::protobuf::Message* request;
        google::protobuf::Message* response;
        int index;
    };
    std::vector<Issue> issues;
    issues.reserve(nactive);
    for (size_t i = 0; i < subs_.size(); ++i) {
        FanoutCtx::SubState& s = ctx->subs[i];
        if (s.skipped) continue;
        s.cntl.set_timeout_ms(timeout_ms);
        s.cntl.set_max_retry(cntl->max_retry());
        issues.push_back(Issue{subs_[i].chan, s.call.method, &s.cntl,
                               s.call.request, s.call.response, (int)i});
    }
    for (const Issue& is : issues) {
        is.chan->CallMethod(
            is.method, is.cntl, is.request, is.response,
            google::protobuf::NewCallback(&FanoutCtx::SubDone, ctx,
                                          is.index));
    }
    if (sync) {
        ctx->sync_wait.wait();
        delete ctx;
    }
}

// ---------------- PartitionParser ----------------

bool PartitionParser::ParseFromTag(const std::string& tag, Partition* out) {
    // "N/M": partition N of M.
    int index = -1, count = 0;
    if (sscanf(tag.c_str(), "%d/%d", &index, &count) != 2) return false;
    if (index < 0 || count <= 0 || index >= count) return false;
    out->index = index;
    out->count = count;
    return true;
}

// ---------------- PartitionChannel ----------------

PartitionChannel::PartitionChannel() = default;
PartitionChannel::~PartitionChannel() = default;

namespace {

// One-shot resolution through the registered naming service: stop the
// polling loop right after its first push.
class CollectActions : public NamingServiceActions {
public:
    explicit CollectActions(NamingService* ns) : ns_(ns) {}
    void ResetServers(const std::vector<NSNode>& servers) override {
        nodes = servers;
        got = true;
        ns_->Destroy();  // first push is all we need
    }
    NamingService* ns_;
    std::vector<NSNode> nodes;
    bool got = false;
};

int ResolveOnce(const char* naming_url, std::vector<NSNode>* out) {
    const char* sep = strstr(naming_url, "://");
    if (sep == nullptr) return -1;
    std::string scheme(naming_url, sep - naming_url);
    std::unique_ptr<NamingService> ns(NamingService::New(scheme));
    if (ns == nullptr) {
        LOG(ERROR) << "unknown naming scheme in " << naming_url;
        return -1;
    }
    CollectActions actions(ns.get());
    if (ns->RunNamingService(sep + 3, &actions) != 0 || !actions.got) {
        return -1;
    }
    *out = std::move(actions.nodes);
    return 0;
}

}  // namespace

int PartitionChannel::Init(const char* naming_url, const char* lb_name,
                           PartitionParser* parser,
                           const PartitionChannelOptions* options) {
    parser_.reset(parser != nullptr ? parser : new PartitionParser);
    PartitionChannelOptions opts;
    if (options != nullptr) opts = *options;
    // Ownership transfers at the call, not at success: wrap before any
    // early return or a failed Init leaks the caller's mapper/merger.
    std::shared_ptr<CallMapper> mapper(opts.call_mapper);
    std::shared_ptr<ResponseMerger> merger(opts.response_merger);

    std::vector<NSNode> nodes;
    if (ResolveOnce(naming_url, &nodes) != 0) return -1;

    // Partition membership by tag.
    std::map<int, std::string> members;  // index -> "ep,ep,..."
    int count = 0;
    for (const NSNode& n : nodes) {
        PartitionParser::Partition p;
        if (!parser_->ParseFromTag(n.tag, &p)) {
            LOG(WARNING) << "unparsable partition tag '" << n.tag << "' for "
                         << endpoint2str(n.ep);
            continue;
        }
        if (count == 0) count = p.count;
        if (p.count != count) {
            LOG(WARNING) << "mixed partition counts " << p.count << " vs "
                         << count << "; skipping " << endpoint2str(n.ep);
            continue;
        }
        std::string& list = members[p.index];
        if (!list.empty()) list += ",";
        list += endpoint2str(n.ep);
    }
    if (count == 0 || (int)members.size() != count) {
        LOG(ERROR) << "partition scheme incomplete: have " << members.size()
                   << " of " << count << " partitions";
        return -1;
    }

    ParallelChannelOptions popts = opts;
    fanout_.reset(new ParallelChannel(&popts));
    ChannelOptions chopts;
    chopts.timeout_ms = opts.timeout_ms;
    chopts.max_retry = opts.max_retry;
    for (int i = 0; i < count; ++i) {
        auto ch = std::make_unique<Channel>();
        const std::string url = "list://" + members[i];
        if (ch->Init(url.c_str(), lb_name, &chopts) != 0) return -1;
        if (fanout_->AddChannelShared(ch.get(), mapper, merger) != 0) {
            return -1;
        }
        parts_.push_back(std::move(ch));
    }
    nparts_ = count;
    return 0;
}

void PartitionChannel::CallMethod(
    const google::protobuf::MethodDescriptor* method,
    google::protobuf::RpcController* controller,
    const google::protobuf::Message* request,
    google::protobuf::Message* response, google::protobuf::Closure* done) {
    if (fanout_ == nullptr) {
        auto* cntl = static_cast<Controller*>(controller);
        cntl->SetFailed(TERR_INTERNAL, "PartitionChannel not initialized");
        if (done != nullptr) done->Run();
        return;
    }
    fanout_->CallMethod(method, controller, request, response, done);
}

// ---------------- SelectiveChannel ----------------

int SelectiveChannel::AddChannel(google::protobuf::RpcChannel* sub) {
    if (sub == nullptr) return -1;
    subs_.push_back(sub);
    return 0;
}

// Per-call retry driver: issues on one sub-channel; a failure triggers the
// next sub-channel (the reference takes over IssueRPC via the _sender
// hook, selective_channel.cpp; the retry-on-another-channel semantics are
// the same).
struct SelectiveCallCtx {
    SelectiveChannel* chan;
    const google::protobuf::MethodDescriptor* method;
    Controller* parent;
    const google::protobuf::Message* request;
    google::protobuf::Message* response;
    google::protobuf::Closure* done;  // null = sync
    CountdownEvent sync_wait{1};
    Controller sub_cntl;
    int tries_left = 0;
    uint32_t next_index = 0;

    void IssueOne() {
        sub_cntl.Reset();
        sub_cntl.set_timeout_ms(parent->timeout_ms());
        const uint32_t idx = next_index++ % (uint32_t)chan->subs_.size();
        chan->subs_[idx]->CallMethod(
            method, &sub_cntl, request, response,
            google::protobuf::NewCallback(&SelectiveCallCtx::OneDone, this));
    }

    static void OneDone(SelectiveCallCtx* ctx) {
        if (ctx->sub_cntl.Failed() && ctx->tries_left-- > 0) {
            ctx->IssueOne();
            return;
        }
        if (ctx->sub_cntl.Failed()) {
            ctx->parent->SetFailed(ctx->sub_cntl.ErrorCode(), "%s",
                                   ctx->sub_cntl.ErrorText().c_str());
        }
        google::protobuf::Closure* user_done = ctx->done;
        if (user_done != nullptr) {
            delete ctx;
            user_done->Run();
        } else {
            ctx->sync_wait.signal();
        }
    }
};

void SelectiveChannel::CallMethod(
    const google::protobuf::MethodDescriptor* method,
    google::protobuf::RpcController* controller,
    const google::protobuf::Message* request,
    google::protobuf::Message* response, google::protobuf::Closure* done) {
    Controller* cntl = static_cast<Controller*>(controller);
    if (subs_.empty()) {
        cntl->SetFailed(TERR_INTERNAL, "SelectiveChannel has no sub-channels");
        if (done != nullptr) done->Run();
        return;
    }
    auto* ctx = new SelectiveCallCtx;
    ctx->chan = this;
    ctx->method = method;
    ctx->parent = cntl;
    ctx->request = request;
    ctx->response = response;
    ctx->done = done;
    ctx->tries_left = cntl->max_retry();
    ctx->next_index = rr_.fetch_add(1, std::memory_order_relaxed);
    const bool sync = done == nullptr;
    ctx->IssueOne();
    if (sync) {
        ctx->sync_wait.wait();
        delete ctx;
    }
}

// ---------------- DynamicPartitionChannel ----------------

int DynamicPartitionChannel::Init(const std::vector<std::string>& naming_urls,
                                  const char* lb_name,
                                  const PartitionChannelOptions* options) {
    // mapper/merger ownership is per-PartitionChannel; forwarding one raw
    // pointer into several schemes would double-free it. Schemes use the
    // defaults — custom ones are not supported here yet, and the caller's
    // objects must still be freed (ownership transferred at the call).
    PartitionChannelOptions per_scheme;
    if (options != nullptr) per_scheme = *options;
    if (per_scheme.call_mapper != nullptr ||
        per_scheme.response_merger != nullptr) {
        LOG(WARNING) << "DynamicPartitionChannel ignores custom "
                        "call_mapper/response_merger (schemes use defaults)";
        delete per_scheme.call_mapper;
        delete per_scheme.response_merger;
    }
    per_scheme.call_mapper = nullptr;
    per_scheme.response_merger = nullptr;
    for (const std::string& url : naming_urls) {
        std::vector<NSNode> nodes;
        int cap = 0;
        if (ResolveOnce(url.c_str(), &nodes) == 0) cap = (int)nodes.size();
        auto pc = std::make_unique<PartitionChannel>();
        if (cap > 0 &&
            pc->Init(url.c_str(), lb_name, nullptr, &per_scheme) == 0) {
            capacities_.push_back(cap);
            schemes_.push_back(std::move(pc));
        } else {
            capacities_.push_back(0);
            schemes_.push_back(nullptr);
        }
    }
    // Route to the scheme with the most servers (capacity-weighted
    // migration narrows to "pick max" with Init-time capacities).
    for (size_t i = 0; i < capacities_.size(); ++i) {
        if (schemes_[i] != nullptr &&
            (chosen_ < 0 || capacities_[i] > capacities_[chosen_])) {
            chosen_ = (int)i;
        }
    }
    return chosen_ >= 0 ? 0 : -1;
}

void DynamicPartitionChannel::CallMethod(
    const google::protobuf::MethodDescriptor* method,
    google::protobuf::RpcController* controller,
    const google::protobuf::Message* request,
    google::protobuf::Message* response, google::protobuf::Closure* done) {
    if (chosen_ < 0) {
        auto* cntl = static_cast<Controller*>(controller);
        cntl->SetFailed(TERR_INTERNAL, "no usable partition scheme");
        if (done != nullptr) done->Run();
        return;
    }
    schemes_[chosen_]->CallMethod(method, controller, request, response,
                                  done);
}

}  // namespace tpurpc
