// Load balancer + naming service + health check tests.
//
// Style mirrors the reference's LB/NS suites (test/brpc_load_balancer_
// unittest.cpp, test/brpc_naming_service_unittest.cpp): policies exercised
// on fake server sockets; "distributed" behavior = N real servers on N
// loopback ports in one process.
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <map>
#include <memory>
#include <set>
#include <string>

#include "echo.pb.h"
#include "tbase/errno.h"
#include "tbase/flags.h"
#include "tfiber/fiber.h"
#include "tnet/socket.h"
#include "trpc/channel.h"
#include "trpc/controller.h"
#include "trpc/lb_with_naming.h"
#include "trpc/load_balancer.h"
#include "trpc/naming_service.h"
#include "trpc/server.h"
#include "ttest/ttest.h"

DECLARE_int32(ns_health_check_interval_ms);

using namespace tpurpc;

namespace {

// A socket that never connects (LB unit tests never write to it).
SocketId make_fake_server(int port) {
    SocketOptions opts;
    opts.fd = -1;
    str2endpoint("127.0.0.1", port, &opts.remote_side);
    SocketId id = INVALID_VREF_ID;
    Socket::Create(opts, &id);
    return id;
}

class EchoServiceImpl : public test::EchoService {
public:
    void Echo(google::protobuf::RpcController*, const test::EchoRequest* req,
              test::EchoResponse* res, google::protobuf::Closure* done) override {
        res->set_message(req->message());
        ncalls.fetch_add(1, std::memory_order_relaxed);
        done->Run();
    }
    std::atomic<int> ncalls{0};
};

struct TestServer {
    // service declared BEFORE server: ~Server (Stop+Join) must
    // drain handler fibers while the service object is still alive.
    EchoServiceImpl service;
    Server server;
    EndPoint ep;

    bool start() {
        if (server.AddService(&service) != 0) return false;
        EndPoint listen;
        str2endpoint("127.0.0.1:0", &listen);
        if (server.Start(listen, nullptr) != 0) return false;
        str2endpoint("127.0.0.1", server.listened_port(), &ep);
        return true;
    }
};

int call_echo(Channel* channel, const char* msg) {
    Controller cntl;
    test::EchoRequest req;
    test::EchoResponse res;
    req.set_message(msg);
    test::EchoService_Stub stub(channel);
    stub.Echo(&cntl, &req, &res, nullptr);
    if (cntl.Failed()) {
        fprintf(stderr, "call failed: %d %s (retried %d)\n", cntl.ErrorCode(),
                cntl.ErrorText().c_str(), cntl.retried_count());
        return cntl.ErrorCode();
    }
    return res.message() == msg ? 0 : -1;
}

}  // namespace

// ---------------- policy unit tests ----------------

TEST(LoadBalancer, RoundRobinCycles) {
    std::unique_ptr<LoadBalancer> lb(LoadBalancer::New("rr"));
    ASSERT_TRUE(lb != nullptr);
    SelectIn in;
    SelectOut out;
    EXPECT_EQ(ENODATA, lb->SelectServer(in, &out));

    std::set<SocketId> ids;
    for (int i = 0; i < 3; ++i) {
        SocketId id = make_fake_server(20000 + i);
        ids.insert(id);
        EXPECT_TRUE(lb->AddServer({id, 1}));
        EXPECT_FALSE(lb->AddServer({id, 1}));  // dup rejected
    }
    // 3 consecutive picks hit 3 distinct servers.
    std::set<SocketId> seen;
    for (int i = 0; i < 3; ++i) {
        ASSERT_EQ(0, lb->SelectServer(in, &out));
        seen.insert(out.ptr->id());
        out.ptr.reset();
    }
    EXPECT_EQ(3u, seen.size());
    for (SocketId id : ids) {
        EXPECT_TRUE(lb->RemoveServer(id));
        Socket::SetFailedById(id);
    }
    EXPECT_EQ(ENODATA, lb->SelectServer(in, &out));
}

TEST(LoadBalancer, ExcludedSkipped) {
    std::unique_ptr<LoadBalancer> lb(LoadBalancer::New("rr"));
    SocketId a = make_fake_server(20010);
    SocketId b = make_fake_server(20011);
    lb->AddServer({a, 1});
    lb->AddServer({b, 1});
    ExcludedServers excluded;
    excluded.Add(a);
    SelectIn in;
    in.excluded = &excluded;
    for (int i = 0; i < 4; ++i) {
        SelectOut out;
        ASSERT_EQ(0, lb->SelectServer(in, &out));
        EXPECT_EQ(b, out.ptr->id());
    }
    // All excluded: falls back to a tried-but-live server.
    excluded.Add(b);
    SelectOut out;
    ASSERT_EQ(0, lb->SelectServer(in, &out));
    Socket::SetFailedById(a);
    Socket::SetFailedById(b);
}

TEST(LoadBalancer, FailedServerSkipped) {
    std::unique_ptr<LoadBalancer> lb(LoadBalancer::New("rr"));
    SocketId a = make_fake_server(20020);
    SocketId b = make_fake_server(20021);
    lb->AddServer({a, 1});
    lb->AddServer({b, 1});
    Socket::SetFailedById(a);  // no health check on fake sockets: stays dead
    for (int i = 0; i < 4; ++i) {
        SelectOut out;
        SelectIn in;
        ASSERT_EQ(0, lb->SelectServer(in, &out));
        EXPECT_EQ(b, out.ptr->id());
    }
    Socket::SetFailedById(b);
    SelectIn in;
    SelectOut out;
    EXPECT_EQ(EHOSTDOWN, lb->SelectServer(in, &out));
}

TEST(LoadBalancer, WeightedRoundRobinRatio) {
    std::unique_ptr<LoadBalancer> lb(LoadBalancer::New("wrr"));
    SocketId a = make_fake_server(20030);
    SocketId b = make_fake_server(20031);
    lb->AddServer({a, 3});
    lb->AddServer({b, 1});
    std::map<SocketId, int> counts;
    SelectIn in;
    for (int i = 0; i < 400; ++i) {
        SelectOut out;
        ASSERT_EQ(0, lb->SelectServer(in, &out));
        counts[out.ptr->id()]++;
    }
    EXPECT_EQ(300, counts[a]);
    EXPECT_EQ(100, counts[b]);
    Socket::SetFailedById(a);
    Socket::SetFailedById(b);
}

TEST(LoadBalancer, RandomCoversAll) {
    std::unique_ptr<LoadBalancer> lb(LoadBalancer::New("random"));
    std::set<SocketId> ids;
    for (int i = 0; i < 4; ++i) {
        SocketId id = make_fake_server(20040 + i);
        ids.insert(id);
        lb->AddServer({id, 1});
    }
    std::set<SocketId> seen;
    SelectIn in;
    for (int i = 0; i < 200; ++i) {
        SelectOut out;
        ASSERT_EQ(0, lb->SelectServer(in, &out));
        seen.insert(out.ptr->id());
    }
    EXPECT_EQ(ids, seen);
    for (SocketId id : ids) Socket::SetFailedById(id);
}

TEST(LoadBalancer, ConsistentHashStability) {
    std::unique_ptr<LoadBalancer> lb(LoadBalancer::New("c_murmurhash"));
    std::set<SocketId> ids;
    for (int i = 0; i < 4; ++i) {
        SocketId id = make_fake_server(20050 + i);
        ids.insert(id);
        lb->AddServer({id, 1});
    }
    // Same request code -> same server, always.
    std::map<uint64_t, SocketId> assignment;
    for (uint64_t code = 0; code < 100; ++code) {
        SelectIn in;
        in.request_code = code;
        in.has_request_code = true;
        SelectOut out;
        ASSERT_EQ(0, lb->SelectServer(in, &out));
        assignment[code] = out.ptr->id();
    }
    for (uint64_t code = 0; code < 100; ++code) {
        SelectIn in;
        in.request_code = code;
        in.has_request_code = true;
        SelectOut out;
        ASSERT_EQ(0, lb->SelectServer(in, &out));
        EXPECT_EQ(assignment[code], out.ptr->id());
    }
    // Removing one server moves only its keys (consistent hashing's point).
    SocketId victim = *ids.begin();
    lb->RemoveServer(victim);
    int moved = 0;
    for (uint64_t code = 0; code < 100; ++code) {
        SelectIn in;
        in.request_code = code;
        in.has_request_code = true;
        SelectOut out;
        ASSERT_EQ(0, lb->SelectServer(in, &out));
        if (out.ptr->id() != assignment[code]) {
            EXPECT_EQ(victim, assignment[code]);
            ++moved;
        }
    }
    EXPECT_LT(moved, 60);  // far from full reshuffle
    for (SocketId id : ids) Socket::SetFailedById(id);
}

TEST(LoadBalancer, LocalityAwarePrefersFast) {
    std::unique_ptr<LoadBalancer> lb(LoadBalancer::New("la"));
    SocketId fast = make_fake_server(20060);
    SocketId slow = make_fake_server(20061);
    lb->AddServer({fast, 1});
    lb->AddServer({slow, 1});
    // Feed latencies: fast = 1ms, slow = 100ms.
    for (int i = 0; i < 50; ++i) {
        SelectIn in;
        SelectOut out;
        ASSERT_EQ(0, lb->SelectServer(in, &out));
        LoadBalancer::CallInfo info;
        info.server_id = out.ptr->id();
        info.latency_us = out.ptr->id() == fast ? 1000 : 100000;
        lb->Feedback(info);
    }
    std::map<SocketId, int> counts;
    for (int i = 0; i < 300; ++i) {
        SelectIn in;
        SelectOut out;
        ASSERT_EQ(0, lb->SelectServer(in, &out));
        counts[out.ptr->id()]++;
        LoadBalancer::CallInfo info;
        info.server_id = out.ptr->id();
        info.latency_us = out.ptr->id() == fast ? 1000 : 100000;
        lb->Feedback(info);
    }
    EXPECT_GT(counts[fast], counts[slow] * 5);
    Socket::SetFailedById(fast);
    Socket::SetFailedById(slow);
}

// ---------------- naming parsing ----------------

TEST(NamingService, ParseLine) {
    NSNode node;
    ASSERT_EQ(0, ParseNamingLine("127.0.0.1:8000", &node));
    EXPECT_EQ(8000, node.ep.port);
    EXPECT_EQ("", node.tag);
    ASSERT_EQ(0, ParseNamingLine("  127.0.0.1:8001  w=5  # comment", &node));
    EXPECT_EQ(8001, node.ep.port);
    EXPECT_EQ("w=5", node.tag);
    EXPECT_EQ(5, WeightFromTag(node.tag));
    EXPECT_EQ(1, WeightFromTag(""));
    EXPECT_EQ(-1, ParseNamingLine("# pure comment", &node));
    EXPECT_EQ(-1, ParseNamingLine("", &node));
}

TEST(NamingService, FileNaming) {
    char path[] = "/tmp/tpurpc_ns_XXXXXX";
    int fd = mkstemp(path);
    ASSERT_GE(fd, 0);
    const char* content = "127.0.0.1:9101\n127.0.0.1:9102 w=2\n# comment\n";
    (void)!write(fd, content, strlen(content));
    close(fd);

    auto t = NamingServiceThread::GetOrCreate(std::string("file://") + path);
    ASSERT_TRUE(t != nullptr);
    ASSERT_EQ(0, t->WaitForFirstBatch(3000));

    struct CountWatcher : NamingServiceThread::Watcher {
        std::atomic<int> added{0}, removed{0};
        void OnServersChanged(const std::vector<ServerNode>& a,
                              const std::vector<SocketId>& r) override {
            added += (int)a.size();
            removed += (int)r.size();
        }
    } watcher;
    t->AddWatcher(&watcher);
    EXPECT_EQ(2, watcher.added.load());
    t->RemoveWatcher(&watcher);
    unlink(path);
}

// ---------------- end-to-end over real servers ----------------

TEST(LbIntegration, RoundRobinSpreads) {
    TestServer s1, s2, s3;
    ASSERT_TRUE(s1.start());
    ASSERT_TRUE(s2.start());
    ASSERT_TRUE(s3.start());
    char url[128];
    snprintf(url, sizeof(url), "list://%s,%s,%s", endpoint2str(s1.ep).c_str(),
             endpoint2str(s2.ep).c_str(), endpoint2str(s3.ep).c_str());
    Channel channel;
    ASSERT_EQ(0, channel.Init(url, "rr", nullptr));
    for (int i = 0; i < 30; ++i) {
        ASSERT_EQ(0, call_echo(&channel, "hello"));
    }
    EXPECT_EQ(10, s1.service.ncalls.load());
    EXPECT_EQ(10, s2.service.ncalls.load());
    EXPECT_EQ(10, s3.service.ncalls.load());
    s1.server.Stop();
    s2.server.Stop();
    s3.server.Stop();
}

TEST(LbIntegration, FailoverOnDeadServer) {
    // One live server + one dead port: retries route every call to the
    // live one (reference: ExcludedServers keeps retries off tried ones).
    TestServer live;
    ASSERT_TRUE(live.start());
    char url[128];
    snprintf(url, sizeof(url), "list://%s,127.0.0.1:1",
             endpoint2str(live.ep).c_str());
    Channel channel;
    ChannelOptions opts;
    opts.timeout_ms = 2000;
    opts.max_retry = 3;
    ASSERT_EQ(0, channel.Init(url, "rr", &opts));
    int ok = 0;
    for (int i = 0; i < 10; ++i) {
        if (call_echo(&channel, "x") == 0) ++ok;
    }
    EXPECT_EQ(10, ok);
    EXPECT_EQ(10, live.service.ncalls.load());
    live.server.Stop();
}

TEST(LbIntegration, HealthCheckRevives) {
    // Start two servers, kill one, verify traffic shifts; restart a server
    // on the SAME port and verify the health checker revives the socket and
    // traffic returns.
    TestServer keep;
    ASSERT_TRUE(keep.start());
    auto dying = std::make_unique<TestServer>();
    ASSERT_TRUE(dying->start());
    const EndPoint dying_ep = dying->ep;

    char url[128];
    snprintf(url, sizeof(url), "list://%s,%s", endpoint2str(keep.ep).c_str(),
             endpoint2str(dying_ep).c_str());
    Channel channel;
    ChannelOptions opts;
    opts.timeout_ms = 2000;
    opts.max_retry = 3;
    ASSERT_EQ(0, channel.Init(url, "rr", &opts));
    for (int i = 0; i < 4; ++i) EXPECT_EQ(0, call_echo(&channel, "a"));
    EXPECT_GT(dying->service.ncalls.load(), 0);

    dying->server.Stop();
    dying->server.Join();
    dying.reset();
    usleep(100 * 1000);
    // All traffic lands on `keep` (first call may hit the dead conn and
    // retry).
    const int before = keep.service.ncalls.load();
    for (int i = 0; i < 6; ++i) EXPECT_EQ(0, call_echo(&channel, "b"));
    EXPECT_GE(keep.service.ncalls.load(), before + 6);

    // Resurrect on the same port.
    TestServer revived;
    if (revived.server.AddService(&revived.service) != 0) return;
    ASSERT_EQ(0, revived.server.Start(dying_ep, nullptr));
    // Health checker probes every FLAGS_ns_health_check_interval_ms (1s
    // default): within a few intervals the socket revives.
    int reborn_calls = 0;
    for (int wait = 0; wait < 50 && reborn_calls == 0; ++wait) {
        usleep(200 * 1000);
        for (int i = 0; i < 4; ++i) call_echo(&channel, "c");
        reborn_calls = revived.service.ncalls.load();
    }
    EXPECT_GT(reborn_calls, 0);
    keep.server.Stop();
    revived.server.Stop();
}

// ---------------- circuit breaker ----------------

TEST(CircuitBreaker, TripsOnErrorRate) {
    CircuitBreaker cb;
    // All-success never trips.
    for (int i = 0; i < 2000; ++i) {
        EXPECT_TRUE(cb.OnCallEnd(0, 1000));
    }
    EXPECT_FALSE(cb.IsBroken());
    // 100% errors trip the short window once a quarter-window of samples
    // accumulated.
    int calls_until_trip = 0;
    while (cb.OnCallEnd(ECONNRESET, 1000) && calls_until_trip < 10000) {
        ++calls_until_trip;
    }
    EXPECT_TRUE(cb.IsBroken());
    EXPECT_LT(calls_until_trip, 200);
    EXPECT_EQ(1, cb.isolated_times());
    // Reset re-arms.
    cb.Reset();
    EXPECT_FALSE(cb.IsBroken());
    EXPECT_TRUE(cb.OnCallEnd(0, 1000));
    EXPECT_EQ(1, cb.isolated_times());  // history survives reset
}

TEST(CircuitBreaker, LowErrorRateStaysClosed) {
    CircuitBreaker cb;
    // 2% errors: below both thresholds (short 30%, long 5%).
    for (int i = 0; i < 5000; ++i) {
        EXPECT_TRUE(cb.OnCallEnd(i % 50 == 0 ? ECONNRESET : 0, 1000));
    }
    EXPECT_FALSE(cb.IsBroken());
}

namespace {
class FlakyEchoServiceImpl : public test::EchoService {
public:
    void Echo(google::protobuf::RpcController* cntl_base,
              const test::EchoRequest* req, test::EchoResponse* res,
              google::protobuf::Closure* done) override {
        ncalls.fetch_add(1, std::memory_order_relaxed);
        if (fail_all.load(std::memory_order_relaxed)) {
            static_cast<Controller*>(cntl_base)
                ->SetFailed(ECONNABORTED, "injected failure");
        } else {
            res->set_message(req->message());
        }
        done->Run();
    }
    std::atomic<int> ncalls{0};
    std::atomic<bool> fail_all{false};
};
}  // namespace

TEST(CircuitBreakerIntegration, IsolatesFailingServer) {
    // One healthy server + one server failing every request at the
    // application level: the breaker isolates the failing one so traffic
    // converges on the healthy server (reference behavior:
    // CircuitBreaker::MarkAsBroken -> health check).
    //
    // Health-check revive is pinned far out: on a slow run a 1s revive of
    // the (TCP-alive) flaky server would reset the breaker mid-test and
    // break the call-count assertions.
    const int32_t old_hc = FLAGS_ns_health_check_interval_ms.get();
    FLAGS_ns_health_check_interval_ms.set(600 * 1000);
    struct HcRestore {
        int32_t old;
        ~HcRestore() { FLAGS_ns_health_check_interval_ms.set(old); }
    } restore{old_hc};
    EchoServiceImpl healthy;
    FlakyEchoServiceImpl flaky;
    Server healthy_srv, flaky_srv;
    flaky.fail_all = true;
    ASSERT_EQ(0, healthy_srv.AddService(&healthy));
    ASSERT_EQ(0, flaky_srv.AddService(&flaky));
    EndPoint any;
    str2endpoint("127.0.0.1:0", &any);
    ASSERT_EQ(0, healthy_srv.Start(any, nullptr));
    ASSERT_EQ(0, flaky_srv.Start(any, nullptr));
    EndPoint hep, fep;
    str2endpoint("127.0.0.1", healthy_srv.listened_port(), &hep);
    str2endpoint("127.0.0.1", flaky_srv.listened_port(), &fep);

    char url[128];
    snprintf(url, sizeof(url), "list://%s,%s", endpoint2str(hep).c_str(),
             endpoint2str(fep).c_str());
    Channel channel;
    ChannelOptions opts;
    opts.timeout_ms = 2000;
    opts.max_retry = 0;  // application errors are not retried anyway
    ASSERT_EQ(0, channel.Init(url, "rr", &opts));

    // Drive enough calls for the short window (30% of 100) to trip.
    int failures = 0;
    for (int i = 0; i < 300; ++i) {
        if (call_echo(&channel, "cb") != 0) ++failures;
    }
    // The flaky server got isolated: it served far fewer than its rr
    // half-share, and late-phase traffic all succeeds.
    EXPECT_LT(flaky.ncalls.load(), 100);
    EXPECT_GT(healthy.ncalls.load(), 200);
    int late_failures = 0;
    for (int i = 0; i < 20; ++i) {
        if (call_echo(&channel, "late") != 0) ++late_failures;
    }
    EXPECT_EQ(0, late_failures);
    healthy_srv.Stop();
    flaky_srv.Stop();
}

// ---------------- cluster recovery ----------------
// Reference: cluster_recover_policy.{h,cpp} — after ALL servers go down,
// traffic is gated while revivals trickle in (accept probability
// usable/min_working), and flows fully once the usable count is stable.

DECLARE_int32(cluster_recover_min_working_instances);
DECLARE_int32(cluster_recover_hold_ms);

TEST(ClusterRecovery, GatesTrafficWhileClusterRefills) {
    FLAGS_ns_health_check_interval_ms.set(100);
    FLAGS_cluster_recover_min_working_instances.set(2);
    FLAGS_cluster_recover_hold_ms.set(400);
    struct FlagsRestore {
        ~FlagsRestore() {
            FLAGS_cluster_recover_min_working_instances.set(0);
            FLAGS_cluster_recover_hold_ms.set(1000);
            FLAGS_ns_health_check_interval_ms.set(1000);
        }
    } restore;

    // Two servers; both die; one comes back.
    auto s1 = std::make_unique<TestServer>();
    auto s2 = std::make_unique<TestServer>();
    ASSERT_TRUE(s1->start());
    ASSERT_TRUE(s2->start());
    const EndPoint ep1 = s1->ep;
    char url[128];
    snprintf(url, sizeof(url), "list://%s,%s", endpoint2str(s1->ep).c_str(),
             endpoint2str(s2->ep).c_str());
    Channel ch;
    ChannelOptions opts;
    opts.timeout_ms = 1000;
    opts.max_retry = 0;
    ASSERT_EQ(0, ch.Init(url, "rr", &opts));
    EXPECT_EQ(0, call_echo(&ch, "warm"));

    s1.reset();
    s2.reset();
    // Drive calls until the LB notices both are gone (recovery arms).
    for (int i = 0; i < 50; ++i) {
        if (call_echo(&ch, "down") != 0) break;
        usleep(10000);
    }
    int failed_while_down = 0;
    for (int i = 0; i < 5; ++i) {
        if (call_echo(&ch, "down") != 0) ++failed_while_down;
    }
    EXPECT_EQ(failed_while_down, 5);

    // Revive ONE server on the same port: while recovering with
    // usable=1 < min_working=2, roughly half the calls are gated.
    TestServer revived;
    ASSERT_EQ(0, revived.server.AddService(&revived.service));
    ASSERT_EQ(0, revived.server.Start(ep1, nullptr));
    // Wait for the health checker to revive the socket.
    int first_ok = -1;
    for (int i = 0; i < 100; ++i) {
        if (call_echo(&ch, "probe") == 0) {
            first_ok = i;
            break;
        }
        usleep(20000);
    }
    ASSERT_GE(first_ok, 0);
    int ok = 0, gated = 0;
    for (int i = 0; i < 40; ++i) {
        if (call_echo(&ch, "recovering") == 0) {
            ++ok;
        } else {
            ++gated;
        }
    }
    // Both outcomes must appear (accept probability = 1/2 per call).
    EXPECT_GT(ok, 0);
    EXPECT_GT(gated, 0);

    // After the hold period with a stable usable count, the gate lifts.
    usleep(600 * 1000);
    for (int i = 0; i < 10 && call_echo(&ch, "post") != 0; ++i) {
        usleep(50 * 1000);  // consume the stability check
    }
    int post_ok = 0;
    for (int i = 0; i < 10; ++i) {
        if (call_echo(&ch, "post") == 0) ++post_ok;
    }
    EXPECT_EQ(post_ok, 10);
}

// ---------------- locality zones (ISSUE 14) ----------------

namespace {

// RAII zone flag: every ZoneAware test must leave the process zoneless
// (the rest of the suite assumes passthrough LBs).
struct ScopedZone {
    explicit ScopedZone(const char* z) { SetFlagValue("rpc_zone", z); }
    ~ScopedZone() { SetFlagValue("rpc_zone", ""); }
};

ServerNode zoned_node(SocketId id, const char* zone) {
    ServerNode n;
    n.id = id;
    n.weight = 1;
    str2endpoint("127.0.0.1", 1, &n.ep);
    n.zone = zone;
    return n;
}

void drain_socket(SocketId id) {
    Socket* s = Socket::Address(id);
    ASSERT_TRUE(s != nullptr);
    s->SetDraining();
    s->Dereference();
}

}  // namespace

// The two-level fallback ordering, identical across every policy:
// local-live > local-draining > remote-live (spill counted).
TEST(ZoneAwareLB, FallbackOrderingAcrossPolicies) {
    ScopedZone zone("A");
    const char* const policies[] = {"rr", "wrr", "random", "c_murmurhash",
                                    "la"};
    int next_port = 21500;
    for (const char* policy : policies) {
        std::unique_ptr<LoadBalancer> lb(LoadBalancer::New(policy));
        ASSERT_TRUE(lb != nullptr);
        const SocketId l1 = make_fake_server(next_port++);
        const SocketId l2 = make_fake_server(next_port++);
        const SocketId r1 = make_fake_server(next_port++);
        const SocketId r2 = make_fake_server(next_port++);
        const std::set<SocketId> locals{l1, l2}, remotes{r1, r2};
        EXPECT_TRUE(lb->AddServer(zoned_node(l1, "A")));
        EXPECT_TRUE(lb->AddServer(zoned_node(l2, "A")));
        EXPECT_TRUE(lb->AddServer(zoned_node(r1, "B")));
        EXPECT_TRUE(lb->AddServer(zoned_node(r2, "B")));
        auto* zlb = static_cast<ZoneAwareLoadBalancer*>(
            static_cast<outlier::OutlierLoadBalancer*>(lb.get())->wrapped());
        EXPECT_EQ(2u, zlb->local_count()) << policy;
        EXPECT_EQ(2u, zlb->remote_count()) << policy;
        SelectIn in;
        SelectOut out;
        // 1) local-live: every pick lands in zone A, never spilled.
        for (int i = 0; i < 16; ++i) {
            out = SelectOut();
            ASSERT_EQ(0, lb->SelectServer(in, &out));
            EXPECT_TRUE(locals.count(out.ptr->id())) << policy;
            EXPECT_FALSE(out.zone_spilled) << policy;
        }
        // 2) one local draining: picks converge on the other local.
        drain_socket(l1);
        for (int i = 0; i < 8; ++i) {
            out = SelectOut();
            ASSERT_EQ(0, lb->SelectServer(in, &out));
            EXPECT_EQ(l2, out.ptr->id()) << policy;
            EXPECT_FALSE(out.zone_spilled) << policy;
        }
        // 3) whole local zone draining: a draining LOCAL still beats a
        // live remote (it serves, and the pod boundary costs WAN).
        drain_socket(l2);
        for (int i = 0; i < 8; ++i) {
            out = SelectOut();
            ASSERT_EQ(0, lb->SelectServer(in, &out));
            EXPECT_TRUE(locals.count(out.ptr->id())) << policy;
            EXPECT_FALSE(out.zone_spilled) << policy;
        }
        // 4) local zone DEAD: spill to a live remote, marked + counted.
        Socket::SetFailedById(l1);
        Socket::SetFailedById(l2);
        for (int i = 0; i < 8; ++i) {
            out = SelectOut();
            ASSERT_EQ(0, lb->SelectServer(in, &out));
            EXPECT_TRUE(remotes.count(out.ptr->id())) << policy;
            EXPECT_TRUE(out.zone_spilled) << policy;
        }
        out = SelectOut();
        Socket::SetFailedById(r1);
        Socket::SetFailedById(r2);
    }
}

// A retry that already tried the only live local member must reach the
// OTHER pod before re-hitting it (excluded-local < remote-live).
TEST(ZoneAwareLB, RetryPrefersRemoteOverTriedLocal) {
    ScopedZone zone("A");
    std::unique_ptr<LoadBalancer> lb(LoadBalancer::New("rr"));
    const SocketId l1 = make_fake_server(21600);
    const SocketId r1 = make_fake_server(21601);
    lb->AddServer(zoned_node(l1, "A"));
    lb->AddServer(zoned_node(r1, "B"));
    ExcludedServers excluded;
    excluded.Add(l1);
    SelectIn in;
    in.excluded = &excluded;
    SelectOut out;
    ASSERT_EQ(0, lb->SelectServer(in, &out));
    EXPECT_EQ(r1, out.ptr->id());
    EXPECT_TRUE(out.zone_spilled);
    Socket::SetFailedById(l1);
    Socket::SetFailedById(r1);
}

// -lb_zone_spill_dead_pct below 100: once that fraction of the local
// zone is DEAD (draining does not count), remote-live wins even while
// a local member still serves — the breaker-storm escape hatch.
TEST(ZoneAwareLB, DeadPctThresholdSpillsEarly) {
    ScopedZone zone("A");
    SetFlagValue("lb_zone_spill_dead_pct", "50");
    std::unique_ptr<LoadBalancer> lb(LoadBalancer::New("rr"));
    const SocketId l1 = make_fake_server(21610);
    const SocketId l2 = make_fake_server(21611);
    const SocketId r1 = make_fake_server(21612);
    lb->AddServer(zoned_node(l1, "A"));
    lb->AddServer(zoned_node(l2, "A"));
    lb->AddServer(zoned_node(r1, "B"));
    SelectIn in;
    SelectOut out;
    // Healthy zone: local.
    ASSERT_EQ(0, lb->SelectServer(in, &out));
    EXPECT_FALSE(out.zone_spilled);
    // Half the zone dead (>= 50%): spill even though l2 is live.
    Socket::SetFailedById(l1);
    for (int i = 0; i < 6; ++i) {
        out = SelectOut();
        ASSERT_EQ(0, lb->SelectServer(in, &out));
        EXPECT_EQ(r1, out.ptr->id());
        EXPECT_TRUE(out.zone_spilled);
    }
    SetFlagValue("lb_zone_spill_dead_pct", "100");
    Socket::SetFailedById(l2);
    Socket::SetFailedById(r1);
}

// Zoneless processes and zoneless members: the wrapper is a strict
// passthrough (no spill accounting, identical behavior to the bare
// policy).
TEST(ZoneAwareLB, ZonelessPassthrough) {
    std::unique_ptr<LoadBalancer> lb(LoadBalancer::New("rr"));
    const SocketId a = make_fake_server(21620);
    const SocketId b = make_fake_server(21621);
    lb->AddServer(zoned_node(a, ""));
    lb->AddServer(zoned_node(b, "B"));  // zoned member, zoneless process
    auto* zlb = static_cast<ZoneAwareLoadBalancer*>(
        static_cast<outlier::OutlierLoadBalancer*>(lb.get())->wrapped());
    EXPECT_EQ(2u, zlb->local_count());
    EXPECT_EQ(0u, zlb->remote_count());
    SelectIn in;
    SelectOut out;
    std::set<SocketId> seen;
    for (int i = 0; i < 8; ++i) {
        out = SelectOut();
        ASSERT_EQ(0, lb->SelectServer(in, &out));
        EXPECT_FALSE(out.zone_spilled);
        seen.insert(out.ptr->id());
    }
    EXPECT_EQ(2u, seen.size());
    Socket::SetFailedById(a);
    Socket::SetFailedById(b);
}

// Per-zone deterministic subsetting (ISSUE 14 satellite): each zone
// keeps its own -subset_size members and its own live floor — a zone
// death recomputes THAT zone's group (full-set fallback for it alone)
// while the other zone's chosen members never churn.
TEST(ZoneAwareLB, PerZoneSubsetFloorRecompute) {
    ScopedZone zone("A");
    SetFlagValue("subset_size", "2");
    SetFlagValue("min_subset", "2");
    SetFlagValue("subset_seed", "7");
    char path[] = "/tmp/tpurpc_zone_ns_XXXXXX";
    int fd = mkstemp(path);
    ASSERT_GE(fd, 0);
    std::string content;
    for (int p = 9321; p <= 9324; ++p) {
        content += "127.0.0.1:" + std::to_string(p) + " zone=A\n";
    }
    for (int p = 9331; p <= 9334; ++p) {
        content += "127.0.0.1:" + std::to_string(p) + " zone=B\n";
    }
    (void)!write(fd, content.data(), content.size());
    close(fd);

    LoadBalancerWithNaming lbn;
    ASSERT_EQ(0, lbn.Init(std::string("file://") + path, "rr"));
    auto by_zone = [&](const std::vector<SocketId>& ids, bool want_b) {
        std::set<SocketId> out;
        for (SocketId id : ids) {
            // UnsafeAddress: dead members (the full-set fallback keeps
            // them in the LB so revives can serve again) still resolve
            // for the port read.
            Socket* s = Socket::UnsafeAddress(id);
            if (s == nullptr) continue;
            if ((s->remote_side().port >= 9331) == want_b) out.insert(id);
        }
        return out;
    };
    std::vector<SocketId> members = lbn.CurrentLbMembers();
    std::set<SocketId> a0 = by_zone(members, false);
    std::set<SocketId> b0 = by_zone(members, true);
    EXPECT_EQ(2u, a0.size()) << members.size();
    EXPECT_EQ(2u, b0.size());
    // Cross-zone members ride the dcn tier (naming created them from
    // the zone=B tags).
    for (SocketId id : b0) {
        Socket* s = Socket::Address(id);
        ASSERT_TRUE(s != nullptr);
        EXPECT_EQ(TierDcn(), s->transport_tier());
        s->Dereference();
    }

    // A retry that excluded every subset member pins the FULL set for
    // a pass; once healthy again, BOTH zones must SHRINK BACK to their
    // subsets (the per-zone shrink-back trigger — a zone must never
    // stay in full-set fan-out after it healed).
    {
        SelectIn in;
        SelectOut out;
        ExcludedServers ex;
        for (SocketId id : members) ex.Add(id);
        SelectIn exin;
        exin.excluded = &ex;
        (void)lbn.SelectServer(exin, &out);
        out = SelectOut();
        bool full_seen = false, shrunk = false;
        for (int wait = 0; wait < 100; ++wait) {
            members = lbn.CurrentLbMembers();
            if (members.size() == 8) full_seen = true;
            if (full_seen && members.size() == 4) {
                shrunk = true;
                break;
            }
            usleep(25 * 1000);  // past the refresh rate limit
            (void)lbn.SelectServer(in, &out);
            out = SelectOut();
        }
        EXPECT_TRUE(full_seen) << members.size();
        EXPECT_TRUE(shrunk) << members.size();
        members = lbn.CurrentLbMembers();
        EXPECT_EQ(a0, by_zone(members, false));
        EXPECT_EQ(b0, by_zone(members, true));
    }

    // Kill zone B's two CHOSEN members: B regains its floor from the
    // unchosen B members; A's subset must not move.
    for (SocketId id : b0) Socket::SetFailedById(id);
    SelectIn in;
    SelectOut out;
    std::set<SocketId> b1;
    for (int wait = 0; wait < 100; ++wait) {
        usleep(25 * 1000);  // the refresh sweep is rate-limited (20ms)
        (void)lbn.SelectServer(in, &out);
        out = SelectOut();
        members = lbn.CurrentLbMembers();
        b1 = by_zone(members, true);
        bool replaced = !b1.empty();
        for (SocketId id : b1) replaced &= b0.count(id) == 0;
        if (replaced && b1.size() == 2) break;
    }
    EXPECT_EQ(2u, b1.size());
    for (SocketId id : b1) {
        EXPECT_EQ(0u, b0.count(id)) << "chosen-dead member kept";
    }
    EXPECT_EQ(a0, by_zone(members, false)) << "zone A churned on B death";

    // Kill ALL of zone B: below the floor, B alone falls back to its
    // full set; A still holds its 2-member subset.
    for (SocketId id : b1) Socket::SetFailedById(id);
    std::set<SocketId> b2;
    for (int wait = 0; wait < 100; ++wait) {
        usleep(25 * 1000);
        (void)lbn.SelectServer(in, &out);
        out = SelectOut();
        members = lbn.CurrentLbMembers();
        b2 = by_zone(members, true);
        if (b2.size() == 4 && by_zone(members, false).size() == 2) break;
    }
    EXPECT_EQ(4u, b2.size()) << "dead zone did not fall back to full set";
    EXPECT_EQ(a0, by_zone(members, false));

    SetFlagValue("subset_size", "0");
    SetFlagValue("min_subset", "0");
    SetFlagValue("subset_seed", "0");
    unlink(path);
}

// The zone=... naming tag parses alongside weights, order-independent.
TEST(NamingService, ZoneTagParses) {
    NSNode node;
    ASSERT_EQ(0,
              ParseNamingLine("127.0.0.1:8002 w=3 zone=pod-a", &node));
    EXPECT_EQ(3, WeightFromTag(node.tag));
    EXPECT_EQ("pod-a", ZoneFromTag(node.tag));
    ASSERT_EQ(0, ParseNamingLine("127.0.0.1:8003 zone=b w=2", &node));
    EXPECT_EQ(2, WeightFromTag(node.tag));
    EXPECT_EQ("b", ZoneFromTag(node.tag));
    EXPECT_EQ("", ZoneFromTag("w=4"));
    EXPECT_EQ("", ZoneFromTag(""));
}

// ---------------- outlier ejection (ISSUE 20) ----------------

// Consecutive hard errors eject; TERR_OVERLOAD never counts (admission
// pushing back is not a grey failure); a health-check revive re-enters
// through PROBING — never straight back at full weight (the regression:
// ReviveAfterHealthCheck cleared DRAINING unconditionally and the LB
// would pick the node immediately) — and probe passes graduate to the
// slow-start RAMP.
TEST(OutlierLB, EjectReviveProbeRamp) {
    std::unique_ptr<LoadBalancer> lb(LoadBalancer::New("rr"));
    ASSERT_TRUE(lb != nullptr);
    auto* olb = static_cast<outlier::OutlierLoadBalancer*>(lb.get());
    const SocketId a = make_fake_server(21700);
    const SocketId b = make_fake_server(21701);
    const SocketId c = make_fake_server(21702);
    EXPECT_TRUE(lb->AddServer({a, 1}));
    EXPECT_TRUE(lb->AddServer({b, 1}));
    EXPECT_TRUE(lb->AddServer({c, 1}));

    LoadBalancer::CallInfo info;
    info.server_id = a;
    info.latency_us = 1000;
    info.error_code = TERR_FAILED_SOCKET;
    for (int i = 0; i < 4; ++i) lb->Feedback(info);
    // Overload feedback: no eject, but no streak reset either.
    info.error_code = TERR_OVERLOAD;
    lb->Feedback(info);
    EXPECT_EQ(outlier::State::kHealthy, olb->tracker()->StateOf(a));
    info.error_code = TERR_FAILED_SOCKET;
    lb->Feedback(info);  // 5th hard error
    EXPECT_EQ(outlier::State::kEjected, olb->tracker()->StateOf(a));
    EXPECT_TRUE(olb->tracker()->IsEjected(a));

    // Normal picks avoid the ejected backend and carry the reason.
    SelectIn in;
    bool saw_skip = false;
    for (int i = 0; i < 12; ++i) {
        SelectOut out;
        ASSERT_EQ(0, lb->SelectServer(in, &out));
        EXPECT_TRUE(out.ptr->id() == b || out.ptr->id() == c);
        if (out.skipped_ejected) {
            saw_skip = true;
            EXPECT_TRUE(out.outlier_note.find("consecutive errors") !=
                        std::string::npos)
                << out.outlier_note;
        }
    }
    EXPECT_TRUE(saw_skip);

    // Revive: PROBING, still withheld from normal picks.
    olb->tracker()->OnRevive(a);
    EXPECT_EQ(outlier::State::kProbing, olb->tracker()->StateOf(a));
    EXPECT_TRUE(olb->tracker()->IsEjected(a));
    int probes = 0;
    for (int i = 0; i < 12; ++i) {
        SelectOut out;
        ASSERT_EQ(0, lb->SelectServer(in, &out));
        if (out.outlier_probe) {
            EXPECT_EQ(a, out.ptr->id());
            ++probes;
        } else {
            EXPECT_TRUE(out.ptr->id() == b || out.ptr->id() == c);
        }
    }
    EXPECT_GE(probes, 1);  // the first probe diverts immediately

    // Probe passes -> RAMPING (slow start), not instant full weight.
    info.error_code = 0;
    info.latency_us = 800;
    for (int i = 0; i < 3; ++i) lb->Feedback(info);
    EXPECT_EQ(outlier::State::kRamping, olb->tracker()->StateOf(a));

    Socket::SetFailedById(a);
    Socket::SetFailedById(b);
    Socket::SetFailedById(c);
}

// A failed reinstatement probe relapses to EJECTED with a grown window.
TEST(OutlierLB, ProbeFailureRelapses) {
    SetFlagValue("outlier_ejection_ms", "1");
    std::unique_ptr<LoadBalancer> lb(LoadBalancer::New("rr"));
    auto* olb = static_cast<outlier::OutlierLoadBalancer*>(lb.get());
    const SocketId a = make_fake_server(21710);
    const SocketId b = make_fake_server(21711);
    const SocketId c = make_fake_server(21712);
    lb->AddServer({a, 1});
    lb->AddServer({b, 1});
    lb->AddServer({c, 1});
    LoadBalancer::CallInfo info;
    info.server_id = a;
    info.latency_us = 1000;
    info.error_code = TERR_FAILED_SOCKET;
    for (int i = 0; i < 5; ++i) lb->Feedback(info);
    ASSERT_EQ(outlier::State::kEjected, olb->tracker()->StateOf(a));
    usleep(5 * 1000);  // the 1ms window expires
    SelectIn in;
    SelectOut out;
    ASSERT_EQ(0, lb->SelectServer(in, &out));
    EXPECT_TRUE(out.outlier_probe);
    EXPECT_EQ(a, out.ptr->id());
    lb->Feedback(info);  // the probe fails
    EXPECT_EQ(outlier::State::kEjected, olb->tracker()->StateOf(a));
    outlier::BackendSnapshot snap;
    ASSERT_TRUE(olb->tracker()->Snapshot(a, &snap));
    EXPECT_EQ(2, snap.eject_count);  // window doubled on relapse
    SetFlagValue("outlier_ejection_ms", "2000");
    Socket::SetFailedById(a);
    Socket::SetFailedById(b);
    Socket::SetFailedById(c);
}

// The ejection budget: with 3 backends and -outlier_max_ejection_pct=40
// only one may be withheld — a second eject-worthy backend is vetoed
// and STAYS routable (a grey majority must not amputate the mesh).
TEST(OutlierLB, EjectionBoundedByMaxPct) {
    std::unique_ptr<LoadBalancer> lb(LoadBalancer::New("rr"));
    auto* olb = static_cast<outlier::OutlierLoadBalancer*>(lb.get());
    const SocketId a = make_fake_server(21720);
    const SocketId b = make_fake_server(21721);
    const SocketId c = make_fake_server(21722);
    lb->AddServer({a, 1});
    lb->AddServer({b, 1});
    lb->AddServer({c, 1});
    LoadBalancer::CallInfo info;
    info.latency_us = 1000;
    info.error_code = TERR_FAILED_SOCKET;
    info.server_id = a;
    for (int i = 0; i < 5; ++i) lb->Feedback(info);
    EXPECT_EQ(outlier::State::kEjected, olb->tracker()->StateOf(a));
    info.server_id = b;
    for (int i = 0; i < 5; ++i) lb->Feedback(info);
    EXPECT_EQ(outlier::State::kHealthy, olb->tracker()->StateOf(b));
    EXPECT_EQ(1u, olb->tracker()->ejected_now());
    // Every pick still succeeds (b and c carry the traffic).
    SelectIn in;
    for (int i = 0; i < 6; ++i) {
        SelectOut out;
        ASSERT_EQ(0, lb->SelectServer(in, &out));
        EXPECT_TRUE(out.ptr->id() == b || out.ptr->id() == c);
    }
    Socket::SetFailedById(a);
    Socket::SetFailedById(b);
    Socket::SetFailedById(c);
}
