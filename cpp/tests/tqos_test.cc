// Multi-tenant QoS unit tests (ISSUE 8): DRR fairness math, token-bucket
// refill, priority shed ordering, rendezvous-hash subset stability under
// add/remove, overload error mapping, and the per-priority probe
// regression in TimeoutConcurrencyLimiter::AdmitWithBudget.
//
// Everything here is protobuf-free: the suite also links into the
// standalone (toolchain-less container) harness alongside tnet_test.
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <set>
#include <string>
#include <vector>

#include "tbase/errno.h"
#include "tbase/flags.h"
#include "tbase/time.h"
#include "tnet/fault_injection.h"
#include "trpc/concurrency_limiter.h"
#include "trpc/qos.h"
#include "ttest/ttest.h"

using namespace tpurpc;

namespace {

// Test dispatch units: record service/shed order through plain statics
// (Pop/Enqueue run on this thread only in these tests).
std::vector<std::string>* g_ran_order = nullptr;
std::vector<std::string>* g_shed_order = nullptr;
int64_t g_last_shed_backoff = 0;

struct TestItem {
    std::string tag;
};

void RunCb(void* arg) {
    auto* it = (TestItem*)arg;
    if (g_ran_order != nullptr) g_ran_order->push_back(it->tag);
    delete it;
}

void ShedCb(void* arg, int64_t backoff_ms) {
    auto* it = (TestItem*)arg;
    if (g_shed_order != nullptr) g_shed_order->push_back(it->tag);
    g_last_shed_backoff = backoff_ms;
    delete it;
}

QosDispatcher::Item MakeItem(const std::string& tag,
                             int64_t cost_milli = kCostUnitMilli,
                             bool spill = false) {
    QosDispatcher::Item item;
    item.run = RunCb;
    item.shed = ShedCb;
    item.arg = new TestItem{tag};
    item.cost_milli = cost_milli;
    item.spill = spill;
    return item;
}

// Pop everything currently poppable, running each item's run callback.
int DrainAll(QosDispatcher* q) {
    int n = 0;
    QosDispatcher::Item it;
    QosDispatcher::TenantState* t;
    int p;
    while (q->Pop(&it, &t, &p)) {
        it.run(it.arg);
        q->OnDone(t, 10);
        ++n;
    }
    return n;
}

}  // namespace

TEST(Qos, ParseQuotaSpec) {
    std::map<std::string, TenantQuota> q;
    EXPECT_TRUE(ParseQuotaSpec(
        "bronze:qps=300,burst=64,w=1,conc=8;gold:w=8", &q));
    ASSERT_EQ(q.size(), 2u);
    EXPECT_EQ((int64_t)q["bronze"].qps, 300);
    EXPECT_EQ(q["bronze"].burst, 64);
    EXPECT_EQ(q["bronze"].weight, 1);
    EXPECT_EQ(q["bronze"].max_concurrency, 8);
    EXPECT_EQ(q["gold"].weight, 8);
    EXPECT_EQ((int64_t)q["gold"].qps, 0);  // unlimited
    // Malformed entries are reported but the valid part still lands.
    std::map<std::string, TenantQuota> q2;
    EXPECT_FALSE(ParseQuotaSpec("ok:w=2;borked;also:nope=1", &q2));
    EXPECT_EQ(q2["ok"].weight, 2);
}

TEST(Qos, ClampPriority) {
    EXPECT_EQ(ClampPriority(-5), kMinPriority);
    EXPECT_EQ(ClampPriority(99), kMaxPriority);
    EXPECT_EQ(ClampPriority(3), 3);
}

TEST(Qos, PriorityFromHeaderStrictParse) {
    // Garbage in x-tpu-priority must land in the DEFAULT class, never
    // class 0 (maximally sheddable).
    EXPECT_EQ(PriorityFromHeader(nullptr), kDefaultPriority);
    std::string s = "high";
    EXPECT_EQ(PriorityFromHeader(&s), kDefaultPriority);
    s = "3x";
    EXPECT_EQ(PriorityFromHeader(&s), kDefaultPriority);
    s = "";
    EXPECT_EQ(PriorityFromHeader(&s), kDefaultPriority);
    s = "6";
    EXPECT_EQ(PriorityFromHeader(&s), 6);
    s = "99";
    EXPECT_EQ(PriorityFromHeader(&s), kMaxPriority);
    s = "-2";
    EXPECT_EQ(PriorityFromHeader(&s), kMinPriority);
}

TEST(Qos, ExplicitQuotaSurvivesConfigure) {
    // SetTenantQuota before Start must survive the Start-time flag
    // apply (Configure), and override the flag for the same tenant.
    QosDispatcher q;
    q.SetTenantQuota("cfg_gold", TenantQuota{100, 0, 5, 0});
    std::map<std::string, TenantQuota> flag;
    flag["cfg_bronze"] = TenantQuota{250, 0, 1, 0};
    flag["cfg_gold"] = TenantQuota{7, 0, 1, 0};  // loses to the explicit
    q.Configure(flag, false);
    auto* g = q.Acquire("cfg_gold");
    EXPECT_EQ(g->weight.load(std::memory_order_relaxed), 5);
    EXPECT_EQ((int64_t)g->quota.qps, 100);
    auto* b = q.Acquire("cfg_bronze");
    EXPECT_EQ((int64_t)b->quota.qps, 250);
    EXPECT_TRUE(q.enabled());
}

TEST(Qos, TokenBucketRefill) {
    TokenBucket b;
    b.Configure(100, 10);  // 100/s, burst 10
    const int64_t t0 = monotonic_time_us();
    int64_t wait_ms = 0;
    for (int i = 0; i < 10; ++i) {
        EXPECT_TRUE(b.TryWithdraw(t0, &wait_ms));
    }
    EXPECT_FALSE(b.TryWithdraw(t0, &wait_ms));
    EXPECT_GE(wait_ms, 1);  // suggested come-back time
    // 50ms at 100/s = 5 tokens accrued.
    const int64_t t1 = t0 + 50 * 1000;
    for (int i = 0; i < 5; ++i) {
        EXPECT_TRUE(b.TryWithdraw(t1, &wait_ms));
    }
    EXPECT_FALSE(b.TryWithdraw(t1, &wait_ms));
    // A long idle stretch refills to burst, never beyond.
    const int64_t t2 = t1 + 10 * 1000 * 1000;
    for (int i = 0; i < 10; ++i) {
        EXPECT_TRUE(b.TryWithdraw(t2, &wait_ms));
    }
    EXPECT_FALSE(b.TryWithdraw(t2, &wait_ms));
    // Unconfigured bucket admits everything.
    TokenBucket open_bucket;
    EXPECT_TRUE(open_bucket.TryWithdraw(t0, &wait_ms));
}

TEST(Qos, DrrFairnessMath) {
    QosDispatcher q;
    q.SetTenantQuota("drrA", TenantQuota{0, 0, 8, 0});
    q.SetTenantQuota("drrB", TenantQuota{0, 0, 1, 0});
    auto* ta = q.Acquire("drrA");
    auto* tb = q.Acquire("drrB");
    for (int i = 0; i < 40; ++i) {
        EXPECT_TRUE(q.Enqueue(ta, kDefaultPriority, MakeItem("A")));
        EXPECT_TRUE(q.Enqueue(tb, kDefaultPriority, MakeItem("B")));
    }
    std::vector<std::string> order;
    g_ran_order = &order;
    QosDispatcher::Item it;
    QosDispatcher::TenantState* owner;
    int prio;
    for (int i = 0; i < 18; ++i) {
        ASSERT_TRUE(q.Pop(&it, &owner, &prio));
        it.run(it.arg);
        q.OnDone(owner, 10);
    }
    // Deficit round robin, cost 1, weights 8:1 — each full round serves
    // 8 A then 1 B.
    int a = 0, b = 0;
    for (const auto& tag : order) (tag == "A" ? a : b)++;
    EXPECT_EQ(a, 16);
    EXPECT_EQ(b, 2);
    // And the LAST of the first nine is the B turn (A's quantum first).
    EXPECT_EQ(order[8], "B");
    g_ran_order = nullptr;
    DrainAll(&q);
}

TEST(Qos, StrictPriorityAcrossLevels) {
    QosDispatcher q;
    auto* t = q.Acquire("prio_tenant");
    EXPECT_TRUE(q.Enqueue(t, 1, MakeItem("low")));
    EXPECT_TRUE(q.Enqueue(t, 6, MakeItem("high")));
    EXPECT_TRUE(q.Enqueue(t, 4, MakeItem("mid")));
    std::vector<std::string> order;
    g_ran_order = &order;
    EXPECT_EQ(DrainAll(&q), 3);
    ASSERT_EQ(order.size(), 3u);
    EXPECT_EQ(order[0], "high");
    EXPECT_EQ(order[1], "mid");
    EXPECT_EQ(order[2], "low");
    g_ran_order = nullptr;
}

TEST(Qos, PriorityShedOrdering) {
    SetFlagValue("rpc_fair_queue_highwater", "4");
    {
        QosDispatcher q;
        auto* lo = q.Acquire("shed_lo");
        auto* hi = q.Acquire("shed_hi");
        for (int i = 0; i < 4; ++i) {
            EXPECT_TRUE(
                q.Enqueue(lo, 1, MakeItem("lo" + std::to_string(i))));
        }
        std::vector<std::string> shed;
        g_shed_order = &shed;
        // High-priority arrival to a full queue: the NEWEST low-priority
        // item is evicted (TERR_OVERLOAD + backoff), the newcomer gets
        // its slot.
        EXPECT_TRUE(q.Enqueue(hi, 6, MakeItem("hi0")));
        ASSERT_EQ(shed.size(), 1u);
        EXPECT_EQ(shed[0], "lo3");  // LIFO shed of the flooder
        EXPECT_GE(g_last_shed_backoff, 1);
        EXPECT_EQ(q.queue_depth(), 4);
        // A low-priority arrival with nothing below it sheds ITSELF.
        EXPECT_FALSE(q.Enqueue(lo, 1, MakeItem("lo_new")));
        ASSERT_EQ(shed.size(), 2u);
        EXPECT_EQ(shed[1], "lo_new");
        // Same-priority arrival cannot evict its own class either.
        EXPECT_FALSE(q.Enqueue(hi, 1, MakeItem("hi_low_class")));
        EXPECT_EQ(shed.size(), 3u);
        // EvictOneBelow (the concurrency-limiter relief path): a prio-6
        // caller can shed one queued prio-1 item.
        EXPECT_TRUE(q.EvictOneBelow(6));
        EXPECT_EQ(shed.size(), 4u);
        EXPECT_FALSE(q.EvictOneBelow(1));  // nothing strictly below 1 left
        // Per-tenant shed counters landed on the owners.
        EXPECT_GE(lo->shed->get(), 3);
        g_shed_order = nullptr;
        DrainAll(&q);
    }
    SetFlagValue("rpc_fair_queue_highwater", "1024");
}

TEST(Qos, ConcurrencyShareGatesDispatch) {
    QosDispatcher q;
    q.SetTenantQuota("conc_t", TenantQuota{0, 0, 1, 2});
    auto* t = q.Acquire("conc_t");
    // Direct dispatch honors the share...
    EXPECT_TRUE(q.TryDirectDispatch(t));
    EXPECT_TRUE(q.TryDirectDispatch(t));
    EXPECT_FALSE(q.TryDirectDispatch(t));  // over the share: must queue
    // ...and the queue holds the tenant while it is saturated.
    EXPECT_TRUE(q.Enqueue(t, kDefaultPriority, MakeItem("queued")));
    QosDispatcher::Item it;
    QosDispatcher::TenantState* owner;
    int prio;
    EXPECT_FALSE(q.Pop(&it, &owner, &prio));  // share exhausted
    q.OnDone(t, 10);                          // one handler finished
    ASSERT_TRUE(q.Pop(&it, &owner, &prio));   // now it dispatches
    it.run(it.arg);
    q.OnDone(owner, 10);
    q.OnDone(t, 10);
    EXPECT_EQ(t->inflight.load(), 0);
}

TEST(Qos, DirectDispatchRequiresEmptyQueue) {
    QosDispatcher q;
    auto* t = q.Acquire("gate_t");
    EXPECT_TRUE(q.TryDirectDispatch(t));  // empty queue: fast path legal
    q.OnDone(t, 5);
    EXPECT_TRUE(q.Enqueue(t, kDefaultPriority, MakeItem("x")));
    // With anything queued the fast path yields to fairness.
    EXPECT_FALSE(q.TryDirectDispatch(t));
    DrainAll(&q);
    EXPECT_TRUE(q.TryDirectDispatch(t));
    q.OnDone(t, 5);
}

TEST(Qos, TenantCardinalityFoldsIntoOther) {
    SetFlagValue("rpc_max_tenants", "4");
    {
        QosDispatcher q;
        q.SetTenantQuota("known", TenantQuota{0, 0, 3, 0});
        for (int i = 0; i < 4; ++i) {
            q.Acquire("card_" + std::to_string(i));
        }
        // Past the cap, unknown names fold into "other"...
        auto* o1 = q.Acquire("card_freshly_minted");
        auto* o2 = q.Acquire("card_another_one");
        EXPECT_EQ(o1, o2);
        EXPECT_EQ(o1->name, "other");
        // ...but configured tenants always get their own slot.
        auto* k = q.Acquire("known");
        EXPECT_EQ(k->name, "known");
        EXPECT_EQ(k->quota.weight, 3);
    }
    SetFlagValue("rpc_max_tenants", "64");
}

TEST(Qos, RendezvousSubsetStability) {
    std::vector<std::string> keys;
    for (int i = 0; i < 10; ++i) {
        keys.push_back("10.0.0." + std::to_string(i) + ":8000");
    }
    const uint64_t seed = 42;
    const size_t k = 4;
    auto pick = RendezvousSubset(seed, keys, k);
    ASSERT_EQ(pick.size(), k);
    std::set<std::string> chosen;
    for (size_t idx : pick) chosen.insert(keys[idx]);
    EXPECT_EQ(chosen.size(), k);
    // Same inputs -> same subset (determinism).
    auto pick2 = RendezvousSubset(seed, keys, k);
    std::set<std::string> chosen2;
    for (size_t idx : pick2) chosen2.insert(keys[idx]);
    EXPECT_TRUE(chosen == chosen2);
    // Removing a NON-member changes nothing.
    std::vector<std::string> without_nonmember;
    for (const auto& key : keys) {
        if (chosen.count(key) == 0 && without_nonmember.size() + chosen.size()
                                          < keys.size()) {
            continue;  // drop the first non-member
        }
        without_nonmember.push_back(key);
    }
    // (rebuild precisely: all keys minus one non-member)
    without_nonmember.clear();
    bool dropped = false;
    for (const auto& key : keys) {
        if (!dropped && chosen.count(key) == 0) {
            dropped = true;
            continue;
        }
        without_nonmember.push_back(key);
    }
    std::set<std::string> after_nm;
    for (size_t idx : RendezvousSubset(seed, without_nonmember, k)) {
        after_nm.insert(without_nonmember[idx]);
    }
    EXPECT_TRUE(after_nm == chosen);
    // Removing a MEMBER pulls in exactly one replacement; every other
    // choice stays put (the HRW property the whole design rides on).
    std::vector<std::string> without_member;
    dropped = false;
    std::string dropped_member;
    for (const auto& key : keys) {
        if (!dropped && chosen.count(key) != 0) {
            dropped = true;
            dropped_member = key;
            continue;
        }
        without_member.push_back(key);
    }
    std::set<std::string> after_m;
    for (size_t idx : RendezvousSubset(seed, without_member, k)) {
        after_m.insert(without_member[idx]);
    }
    EXPECT_EQ(after_m.size(), k);
    EXPECT_EQ(after_m.count(dropped_member), 0u);
    size_t kept = 0;
    for (const auto& key : chosen) kept += after_m.count(key);
    EXPECT_EQ(kept, k - 1);  // one replacement, three survivors
    // k >= n returns everything.
    auto all = RendezvousSubset(seed, keys, 100);
    EXPECT_EQ(all.size(), keys.size());
    // Different seeds draw different subsets (different clients spread
    // over the fleet) — with 210 possible 4-subsets a collision across
    // ten seeds is astronomically unlikely to hit ALL of them.
    int distinct = 0;
    for (uint64_t s2 = 1; s2 <= 10; ++s2) {
        std::set<std::string> c2;
        for (size_t idx : RendezvousSubset(s2, keys, k)) {
            c2.insert(keys[idx]);
        }
        if (c2 != chosen) ++distinct;
    }
    EXPECT_GT(distinct, 0);
}

TEST(Qos, OverloadErrorMapping) {
    // TERR_OVERLOAD is its own retriable class: distinct code, distinct
    // operator-facing text (the soak greps for it), not the limiter's
    // plain TERR_LIMIT_EXCEEDED and not the budget-free TERR_DRAINING.
    EXPECT_EQ(TERR_OVERLOAD, 4013);
    const std::string text = terror(TERR_OVERLOAD);
    EXPECT_NE(text.find("Overload"), std::string::npos);
    EXPECT_NE(text, terror(TERR_LIMIT_EXCEEDED));
    EXPECT_NE(text, terror(TERR_DRAINING));
}

TEST(Qos, TimeoutLimiterProbePerPriority) {
    // Regression (ISSUE 8 satellite): the 1s probe escape hatch used to
    // be one global clock per method — a low-priority class's probe
    // consumed it and a latched high-priority class could never
    // re-measure. Now each priority class probes independently.
    TimeoutConcurrencyLimiter::Options opt;
    opt.timeout_ms = 100;
    opt.probe_interval_ms = 50;
    TimeoutConcurrencyLimiter lim(opt);
    // Teach a huge service time: every budget below it is doomed.
    lim.OnResponded(0, 500 * 1000);
    EXPECT_GT(lim.avg_latency_us(), 100 * 1000);
    // Inside the probe interval everything sheds (fresh success sample).
    EXPECT_FALSE(lim.AdmitWithBudget(1000, 1));
    EXPECT_FALSE(lim.AdmitWithBudget(1000, 7));
    usleep(60 * 1000);  // past the probe interval
    // Class 1 probes...
    EXPECT_TRUE(lim.AdmitWithBudget(1000, 1));
    // ...and class 7 STILL probes (its own clock — the old global clock
    // returned false here).
    EXPECT_TRUE(lim.AdmitWithBudget(1000, 7));
    // Each class's probe is consumed for the next interval.
    EXPECT_FALSE(lim.AdmitWithBudget(1000, 1));
    EXPECT_FALSE(lim.AdmitWithBudget(1000, 7));
    // Ample budget always admits, probe or not.
    EXPECT_TRUE(lim.AdmitWithBudget(1000 * 1000, 3));
}

TEST(Qos, DrainerServesQueuedItems) {
    // End-to-end through the real drainer fiber: enqueue, let the
    // drainer pop + run, verify completion accounting.
    QosDispatcher q;
    q.StartDrainer();
    auto* t = q.Acquire("drained_t");
    static std::atomic<int> ran{0};
    struct Counted {
        QosDispatcher* q;
        QosDispatcher::TenantState* t;
    };
    QosDispatcher::Item item;
    item.run = [](void* arg) {
        auto* c = (Counted*)arg;
        ran.fetch_add(1);
        c->q->OnDone(c->t, 100);
        delete c;
    };
    item.shed = [](void* arg, int64_t) { delete (Counted*)arg; };
    for (int i = 0; i < 5; ++i) {
        item.arg = new Counted{&q, t};
        q.Enqueue(t, kDefaultPriority, item);
    }
    const int64_t deadline = monotonic_time_us() + 2 * 1000 * 1000;
    while (ran.load() < 5 && monotonic_time_us() < deadline) {
        usleep(5 * 1000);
    }
    EXPECT_EQ(ran.load(), 5);
    EXPECT_EQ(q.queue_depth(), 0);
    EXPECT_EQ(t->inflight.load(), 0);
    q.StopDrainer();
}

TEST(Qos, StopDrainerShedsEvenWhenNeverStarted) {
    // Regression: a runtime-enabled tier racing Stop (drainer never
    // started) must still answer its queued items — each holds a
    // counted admission, and leaking one hangs Server::Join.
    QosDispatcher q;
    auto* t = q.Acquire("never_started_t");
    std::vector<std::string> shed;
    g_shed_order = &shed;
    for (int i = 0; i < 2; ++i) {
        EXPECT_TRUE(q.Enqueue(t, 3, MakeItem("orphan")));
    }
    q.StopDrainer();
    EXPECT_EQ(shed.size(), 2u);
    EXPECT_EQ(q.queue_depth(), 0);
    g_shed_order = nullptr;
}

// ---------------- work-priced admission (ISSUE 15) ----------------

TEST(Qos, ComputeCostMilliMath) {
    // Defaults: 1000us of service = 1 unit, 16KiB of payload = 1 unit.
    EXPECT_EQ(ComputeCostMilli(0, 0), kCostUnitMilli);       // floor
    EXPECT_EQ(ComputeCostMilli(100, 128), kCostUnitMilli);   // light call
    EXPECT_EQ(ComputeCostMilli(4000, 0), 4 * kCostUnitMilli);
    EXPECT_EQ(ComputeCostMilli(0, 64 * 1024), 4 * kCostUnitMilli);
    EXPECT_EQ(ComputeCostMilli(2000, 32 * 1024), 4 * kCostUnitMilli);
    // Capped: one pathological sample cannot mint unbounded debt.
    EXPECT_EQ(ComputeCostMilli(1 << 30, 1LL << 40),
              1024 * kCostUnitMilli);
}

TEST(Qos, SpillCostAdjustment) {
    // Zone-neutral until both ends are zone-tagged.
    EXPECT_FALSE(SpillArrival(""));
    EXPECT_FALSE(SpillArrival("B"));  // we have no zone of our own
    SetFlagValue("rpc_zone", "A");
    EXPECT_FALSE(SpillArrival("A"));  // same pod = local
    EXPECT_TRUE(SpillArrival("B"));   // cross-pod spill
    SetFlagValue("rpc_zone", "");
    // Default multiplier 2.0, capped at the model maximum.
    EXPECT_EQ(SpillAdjustedCostMilli(kCostUnitMilli), 2 * kCostUnitMilli);
    EXPECT_EQ(SpillAdjustedCostMilli(1024 * kCostUnitMilli),
              1024 * kCostUnitMilli);
}

TEST(Qos, TokenBucketCostWithdraw) {
    TokenBucket b;
    b.Configure(100, 10);  // 100 units/s, burst 10 units
    const int64_t t0 = monotonic_time_us();
    int64_t wait_ms = 0;
    // One 4-unit call burns four baseline calls' worth.
    EXPECT_TRUE(b.TryWithdrawCost(t0, 4 * kCostUnitMilli, &wait_ms));
    for (int i = 0; i < 6; ++i) {
        EXPECT_TRUE(b.TryWithdraw(t0, &wait_ms));
    }
    EXPECT_FALSE(b.TryWithdraw(t0, &wait_ms));
    EXPECT_GE(wait_ms, 1);
    // A 3-unit withdrawal when dry reports a LONGER wait than a 1-unit
    // one would (the deficit is cost-sized).
    int64_t wait3 = 0;
    EXPECT_FALSE(b.TryWithdrawCost(t0, 3 * kCostUnitMilli, &wait3));
    EXPECT_GE(wait3, wait_ms);
    // A call costing MORE than the whole burst admits at a full bucket
    // (and leaves it in debt) instead of starving forever.
    TokenBucket heavy;
    heavy.Configure(10, 4);  // burst 4 units
    const int64_t t1 = monotonic_time_us();
    EXPECT_TRUE(heavy.TryWithdrawCost(t1, 20 * kCostUnitMilli, &wait_ms));
    // Deep in debt now: even a baseline call must wait.
    EXPECT_FALSE(heavy.TryWithdraw(t1, &wait_ms));
    EXPECT_GE(wait_ms, 100);  // >= 1 unit of debt at 10 units/s
}

TEST(Qos, CostModelEwmaFoldAndEstimate) {
    QosDispatcher q;
    auto* t = q.Acquire("cost_model_t");
    const std::string echo = "svc.Echo";
    // Unmeasured method: one baseline unit.
    EXPECT_EQ(q.EstimateCostMilli(t, echo), kCostUnitMilli);
    // Teach it: 8ms of service + 64KiB of payload = ~12 units. The
    // first sample seeds the EWMA directly.
    QosDispatcher::CompletionInfo ci;
    ci.method = &echo;
    ci.logical_bytes = 64 * 1024;
    q.BeginServed(t);
    q.OnDone(t, 8000, ci);
    const int64_t est = q.EstimateCostMilli(t, echo);
    EXPECT_GE(est, 10 * kCostUnitMilli);
    EXPECT_LE(est, 14 * kCostUnitMilli);
    // A light sample folds the estimate DOWN (alpha 1/4), not to zero.
    ci.logical_bytes = 0;
    q.BeginServed(t);
    q.OnDone(t, 100, ci);
    const int64_t est2 = q.EstimateCostMilli(t, echo);
    EXPECT_LT(est2, est);
    EXPECT_GT(est2, kCostUnitMilli);
}

TEST(Qos, CostModelMethodCardinalityFolds) {
    SetFlagValue("rpc_cost_max_methods", "2");
    {
        QosDispatcher q;
        auto* t = q.Acquire("cost_card_t");
        std::string m1 = "svc.A", m2 = "svc.B", m3 = "svc.C";
        QosDispatcher::CompletionInfo ci;
        for (std::string* m : {&m1, &m2}) {
            ci.method = m;
            ci.logical_bytes = 0;
            q.BeginServed(t);
            q.OnDone(t, 100, ci);
        }
        // Past the cap, a fresh method teaches the OVERFLOW bucket —
        // and an unknown method's estimate reads it.
        ci.method = &m3;
        ci.logical_bytes = 64 * 1024;
        q.BeginServed(t);
        q.OnDone(t, 8000, ci);
        std::string m4 = "svc.D";
        EXPECT_GE(q.EstimateCostMilli(t, m4), 4 * kCostUnitMilli);
        // Known methods keep their own (light) estimates.
        EXPECT_EQ(q.EstimateCostMilli(t, m1), kCostUnitMilli);
    }
    SetFlagValue("rpc_cost_max_methods", "32");
}

TEST(Qos, AdmitCostPricesHeavyCalls) {
    QosDispatcher q;
    // 8 units/s, burst 8: within an 8-REQUEST count budget, but heavy
    // calls must still shed.
    q.SetTenantQuota("cost_admit_t", TenantQuota{8, 8, 1, 0});
    auto* t = q.Acquire("cost_admit_t");
    const int64_t now = monotonic_time_us();
    int64_t backoff = 0;
    // Two 4-unit calls drain the burst that held 8 baseline requests.
    EXPECT_TRUE(q.AdmitCost(t, now, 4 * kCostUnitMilli, &backoff));
    EXPECT_TRUE(q.AdmitCost(t, now, 4 * kCostUnitMilli, &backoff));
    EXPECT_FALSE(q.AdmitCost(t, now, kCostUnitMilli, &backoff));
    EXPECT_GE(backoff, 1);
    EXPECT_GE(t->cost_shed->get(), kCostUnitMilli);
    EXPECT_GE(t->cost_admitted->get(), 0);  // admit counts at dispatch
}

TEST(Qos, DrrCostProportionalService) {
    QosDispatcher q;
    q.SetTenantQuota("drr_heavy", TenantQuota{0, 0, 1, 0});
    q.SetTenantQuota("drr_light", TenantQuota{0, 0, 1, 0});
    auto* heavy = q.Acquire("drr_heavy");
    auto* light = q.Acquire("drr_light");
    for (int i = 0; i < 20; ++i) {
        EXPECT_TRUE(q.Enqueue(heavy, kDefaultPriority,
                              MakeItem("H", 4 * kCostUnitMilli)));
        EXPECT_TRUE(q.Enqueue(light, kDefaultPriority, MakeItem("L")));
    }
    std::vector<std::string> order;
    g_ran_order = &order;
    QosDispatcher::Item it;
    QosDispatcher::TenantState* owner;
    int prio;
    for (int i = 0; i < 10; ++i) {
        ASSERT_TRUE(q.Pop(&it, &owner, &prio));
        it.run(it.arg);
        q.OnDone(owner, 10);
    }
    // Equal weights, 4:1 cost ratio: the heavy tenant serves ~1 item
    // per 4 of the light tenant's — SERVICE IN COST UNITS stays equal,
    // so one heavy call burns proportionally more of its turn.
    int h = 0, l = 0;
    for (const auto& tag : order) (tag == "H" ? h : l)++;
    EXPECT_GE(h, 1);
    EXPECT_LE(h, 3);
    EXPECT_EQ(l, 10 - h);
    const int64_t units_h = (int64_t)h * 4, units_l = l;
    EXPECT_LE(units_h > units_l ? units_h - units_l : units_l - units_h,
              4);
    g_ran_order = nullptr;
    DrainAll(&q);
}

TEST(Qos, SpillShedsFirstWithinLevel) {
    SetFlagValue("rpc_fair_queue_highwater", "4");
    {
        QosDispatcher q;
        auto* local_t = q.Acquire("spill_local");
        auto* spill_t = q.Acquire("spill_remote");
        EXPECT_TRUE(q.Enqueue(local_t, 1, MakeItem("l0")));
        EXPECT_TRUE(q.Enqueue(spill_t, 1,
                              MakeItem("s0", 2 * kCostUnitMilli,
                                       /*spill=*/true)));
        EXPECT_TRUE(q.Enqueue(local_t, 1, MakeItem("l1")));
        EXPECT_TRUE(q.Enqueue(local_t, 1, MakeItem("l2")));
        std::vector<std::string> shed;
        g_shed_order = &shed;
        // Full queue + higher-priority arrival: the SPILL item is
        // evicted first even though local l2 is newer and local's
        // queue is deeper.
        EXPECT_TRUE(q.Enqueue(spill_t, 6, MakeItem("hi0")));
        ASSERT_EQ(shed.size(), 1u);
        EXPECT_EQ(shed[0], "s0");
        // With no spills left, eviction falls back to the newest item
        // of the deepest queue (the flooder).
        EXPECT_TRUE(q.Enqueue(spill_t, 6, MakeItem("hi1")));
        ASSERT_EQ(shed.size(), 2u);
        EXPECT_EQ(shed[1], "l2");
        g_shed_order = nullptr;
        DrainAll(&q);
    }
    SetFlagValue("rpc_fair_queue_highwater", "1024");
}

TEST(Qos, QueueDelayShedAndDrainBackoff) {
    SetFlagValue("rpc_queue_delay_target_ms", "5");
    SetFlagValue("rpc_queue_delay_interval_ms", "1");
    {
        QosDispatcher q;
        auto* t = q.Acquire("delay_t");
        // Four items that have "waited" 300ms already (pre-stamped):
        // every sojourn measurement lands far above the 5ms target.
        const int64_t stale = monotonic_time_us() - 300 * 1000;
        for (int i = 0; i < 4; ++i) {
            QosDispatcher::Item item =
                MakeItem("old" + std::to_string(i), 8 * kCostUnitMilli);
            item.enqueue_us = stale;
            EXPECT_TRUE(q.Enqueue(t, 3, item));
        }
        QosDispatcher::Item it;
        QosDispatcher::TenantState* owner;
        int prio;
        ASSERT_TRUE(q.Pop(&it, &owner, &prio));
        it.run(it.arg);
        q.OnDone(owner, 10);
        usleep(3 * 1000);  // a full observation interval elapses
        ASSERT_TRUE(q.Pop(&it, &owner, &prio));
        it.run(it.arg);
        q.OnDone(owner, 10);
        // The measured sojourn never dipped below target for a whole
        // interval: the queue is in overload — a depth of TWO (far
        // below the 1024 high-water) now sheds arrivals, because the
        // signal is the MEASURED delay, not a static depth.
        EXPECT_TRUE(q.OverDelayTarget());
        EXPECT_GE(q.QueueDelayEwmaUs(), 10 * 1000);
        std::vector<std::string> shed;
        g_shed_order = &shed;
        EXPECT_FALSE(q.Enqueue(t, 3, MakeItem("shed_me")));
        ASSERT_EQ(shed.size(), 1u);
        // The backoff hint is drain-derived: a measured rate exists and
        // the hint respects the flag floor / 2s cap.
        EXPECT_GT(q.DrainRateCostPerS(), 0);
        const int64_t hint = q.SuggestedBackoffMs();
        EXPECT_GE(hint, 1);
        EXPECT_LE(hint, 2000);
        g_shed_order = nullptr;
        // Draining to empty clears the overload verdict.
        DrainAll(&q);
        EXPECT_FALSE(q.OverDelayTarget());
        EXPECT_TRUE(q.Enqueue(t, 3, MakeItem("fine_again")));
        DrainAll(&q);
    }
    SetFlagValue("rpc_queue_delay_target_ms", "20");
    SetFlagValue("rpc_queue_delay_interval_ms", "100");
}

TEST(Qos, GradientLimitGatesDispatch) {
    QosDispatcher q;
    AutoConcurrencyLimiter::Options opt;
    opt.initial_max_concurrency = 2;
    opt.min_max_concurrency = 2;
    q.SetGradientOptions(opt);
    // NO conc= share configured: the tenant's own gradient limiter
    // gates, starting from its initial limit.
    auto* t = q.Acquire("gradient_t");
    EXPECT_EQ(q.TenantConcurrencyLimit(t), 2);
    EXPECT_TRUE(q.TryDirectDispatch(t));
    EXPECT_TRUE(q.TryDirectDispatch(t));
    EXPECT_FALSE(q.TryDirectDispatch(t));  // over the gradient limit
    q.OnDone(t, 100);
    q.OnDone(t, 100);
    // An EXPLICIT share always wins over the gradient.
    q.SetTenantQuota("gradient_t", TenantQuota{0, 0, 1, 5});
    EXPECT_EQ(q.TenantConcurrencyLimit(t), 5);
    // And the flag turns the mechanism off entirely.
    q.SetTenantQuota("gradient_t", TenantQuota{0, 0, 1, 0});
    SetFlagValue("rpc_tenant_gradient_limit", "false");
    EXPECT_EQ(q.TenantConcurrencyLimit(t), 0);  // unlimited
    SetFlagValue("rpc_tenant_gradient_limit", "true");
}

TEST(Qos, GradientConvergesFromMeasurement) {
    // The limiter the per-tenant tier instantiates: with tight windows
    // it must recompute its limit from observed latency — update_count
    // is the "converged from measurement, not hand-set" proof the soak
    // asserts through /tenants?format=json.
    AutoConcurrencyLimiter::Options opt;
    opt.initial_max_concurrency = 40;
    opt.min_max_concurrency = 4;
    opt.sampling_interval_us = 0;
    opt.sample_window_us = 1000;
    opt.min_sample_count = 5;
    opt.max_sample_count = 10;
    AutoConcurrencyLimiter lim(opt);
    EXPECT_EQ(lim.update_count(), 0);
    for (int i = 0; i < 60; ++i) {
        lim.OnResponded(0, 200);
        if (i % 10 == 9) usleep(2000);  // let windows close
    }
    EXPECT_GE(lim.update_count(), 1);
    EXPECT_GE(lim.MaxConcurrency(), opt.min_max_concurrency);
    EXPECT_GT(lim.min_latency_us(), 0);
}

TEST(Qos, CostInflateChaosPlan) {
    // Plan grammar: cost_inflate takes prob[:multiplier].
    EXPECT_TRUE(FaultInjection::ValidatePlan("cost_inflate=1:8"));
    EXPECT_TRUE(FaultInjection::ValidatePlan("cost_inflate=0.5"));
    EXPECT_FALSE(FaultInjection::ValidatePlan("cost_inflate=1:0"));
    EXPECT_FALSE(FaultInjection::ValidatePlan("cost_inflate=2:8"));
    SetFlagValue("chaos_plan", "cost_inflate=1:8");
    SetFlagValue("chaos_seed", "7");
    SetFlagValue("chaos_enabled", "true");
    // The seam decision: kCostMeasure ops inflate, byte ops do not.
    const FaultAction a =
        FaultInjection::Decide(FaultOp::kCostMeasure, EndPoint(), 128);
    EXPECT_EQ((int)a.kind, (int)FaultAction::kInflate);
    EXPECT_EQ((int64_t)a.aux, 8);
    const FaultAction w =
        FaultInjection::Decide(FaultOp::kWrite, EndPoint(), 128);
    EXPECT_NE((int)w.kind, (int)FaultAction::kInflate);
    // End to end: under the plan, one completion teaches an 8x-priced
    // estimate (measured ~1 unit -> ~8 units).
    QosDispatcher q;
    auto* t = q.Acquire("inflate_t");
    const std::string m = "svc.Inflated";
    QosDispatcher::CompletionInfo ci;
    ci.method = &m;
    ci.logical_bytes = 0;
    q.BeginServed(t);
    q.OnDone(t, 500, ci);
    EXPECT_GE(q.EstimateCostMilli(t, m), 4 * kCostUnitMilli);
    SetFlagValue("chaos_enabled", "false");
    SetFlagValue("chaos_plan", "");
}

TEST(Qos, StopDrainerShedsBacklog) {
    QosDispatcher q;
    // No drainer running: queued items must still be answered (shed) at
    // StopDrainer so admission accounting can never leak.
    q.StartDrainer();
    auto* t = q.Acquire("stop_t");
    // Saturate the tenant's concurrency share so queued items stay put.
    q.SetTenantQuota("stop_t", TenantQuota{0, 0, 1, 1});
    EXPECT_TRUE(q.TryDirectDispatch(t));  // holds the single share
    std::vector<std::string> shed;
    g_shed_order = &shed;
    for (int i = 0; i < 3; ++i) {
        EXPECT_TRUE(q.Enqueue(t, 2, MakeItem("parked")));
    }
    q.StopDrainer();
    EXPECT_EQ(shed.size(), 3u);
    EXPECT_EQ(q.queue_depth(), 0);
    g_shed_order = nullptr;
    q.OnDone(t, 5);
}
