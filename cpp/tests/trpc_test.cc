// End-to-end RPC tests over loopback: the in-process style of the
// reference's ChannelTest (test/brpc_channel_unittest.cpp:195) — real
// server, real client stack, sync/async, attachments, timeouts, retries.
#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstring>

#include <atomic>
#include <string>

#include "echo.pb.h"
#include "tbase/errno.h"
#include "tfiber/fiber.h"
#include "tfiber/fiber_sync.h"
#include "trpc/channel.h"
#include "trpc/controller.h"
#include "trpc/server.h"
#include "ttest/ttest.h"

using namespace tpurpc;

namespace {

class EchoServiceImpl : public test::EchoService {
public:
    void Echo(google::protobuf::RpcController* cntl_base,
              const test::EchoRequest* request, test::EchoResponse* response,
              google::protobuf::Closure* done) override {
        Controller* cntl = static_cast<Controller*>(cntl_base);
        if (request->sleep_us() > 0) {
            fiber_usleep(request->sleep_us());
        }
        response->set_message(request->message());
        // Echo the attachment back (zero-copy).
        cntl->response_attachment().append(cntl->request_attachment());
        ncalls.fetch_add(1, std::memory_order_relaxed);
        done->Run();
    }
    std::atomic<int> ncalls{0};
};

struct TestServer {
    // service declared BEFORE server: ~Server (Stop+Join) must
    // drain handler fibers while the service object is still alive.
    EchoServiceImpl service;
    Server server;
    EndPoint ep;

    bool start() {
        if (server.AddService(&service) != 0) return false;
        EndPoint listen;
        str2endpoint("127.0.0.1:0", &listen);
        if (server.Start(listen, nullptr) != 0) return false;
        str2endpoint("127.0.0.1", server.listened_port(), &ep);
        return true;
    }
};

}  // namespace

TEST(Rpc, SyncEcho) {
    TestServer ts;
    ASSERT_TRUE(ts.start());
    Channel channel;
    ASSERT_EQ(channel.Init(ts.ep, nullptr), 0);
    test::EchoService_Stub stub(&channel);

    Controller cntl;
    test::EchoRequest req;
    req.set_message("hello rpc");
    test::EchoResponse res;
    stub.Echo(&cntl, &req, &res, nullptr);
    ASSERT_FALSE(cntl.Failed());
    EXPECT_EQ(res.message(), "hello rpc");
    EXPECT_GT(cntl.latency_us(), 0);
    EXPECT_EQ(ts.service.ncalls.load(), 1);
}

TEST(Rpc, ManySyncCalls) {
    TestServer ts;
    ASSERT_TRUE(ts.start());
    Channel channel;
    ASSERT_EQ(channel.Init(ts.ep, nullptr), 0);
    test::EchoService_Stub stub(&channel);
    for (int i = 0; i < 100; ++i) {
        Controller cntl;
        test::EchoRequest req;
        req.set_message("m" + std::to_string(i));
        test::EchoResponse res;
        stub.Echo(&cntl, &req, &res, nullptr);
        ASSERT_FALSE(cntl.Failed());
        ASSERT_EQ(res.message(), "m" + std::to_string(i));
    }
}

namespace {
struct AsyncDone {
    Controller cntl;
    test::EchoResponse res;
    CountdownEvent* event;
};
void HandleAsyncDone(AsyncDone* d) { d->event->signal(); }
}  // namespace

TEST(Rpc, AsyncEcho) {
    TestServer ts;
    ASSERT_TRUE(ts.start());
    Channel channel;
    ASSERT_EQ(channel.Init(ts.ep, nullptr), 0);
    test::EchoService_Stub stub(&channel);

    const int kN = 50;
    CountdownEvent ev(kN);
    std::vector<AsyncDone*> dones;
    for (int i = 0; i < kN; ++i) {
        auto* d = new AsyncDone;
        d->event = &ev;
        dones.push_back(d);
        test::EchoRequest req;
        req.set_message("async" + std::to_string(i));
        stub.Echo(&d->cntl, &req, &d->res,
                  google::protobuf::NewCallback(HandleAsyncDone, d));
    }
    ASSERT_EQ(ev.wait(), 0);
    for (int i = 0; i < kN; ++i) {
        EXPECT_FALSE(dones[i]->cntl.Failed());
        EXPECT_EQ(dones[i]->res.message(), "async" + std::to_string(i));
        delete dones[i];
    }
}

TEST(Rpc, AttachmentRoundTrip) {
    TestServer ts;
    ASSERT_TRUE(ts.start());
    Channel channel;
    ASSERT_EQ(channel.Init(ts.ep, nullptr), 0);
    test::EchoService_Stub stub(&channel);

    Controller cntl;
    std::string big(512 * 1024, 'A');
    cntl.request_attachment().append(big);
    test::EchoRequest req;
    req.set_message("with attachment");
    test::EchoResponse res;
    stub.Echo(&cntl, &req, &res, nullptr);
    ASSERT_FALSE(cntl.Failed());
    EXPECT_EQ(res.message(), "with attachment");
    EXPECT_EQ(cntl.response_attachment().size(), big.size());
    EXPECT_TRUE(cntl.response_attachment().equals(big));
}

TEST(Rpc, TimeoutFails) {
    TestServer ts;
    ASSERT_TRUE(ts.start());
    Channel channel;
    ASSERT_EQ(channel.Init(ts.ep, nullptr), 0);
    test::EchoService_Stub stub(&channel);

    Controller cntl;
    cntl.set_timeout_ms(50);
    test::EchoRequest req;
    req.set_message("slow");
    req.set_sleep_us(300 * 1000);
    test::EchoResponse res;
    const int64_t t0 = monotonic_time_us();
    stub.Echo(&cntl, &req, &res, nullptr);
    const int64_t took_ms = (monotonic_time_us() - t0) / 1000;
    EXPECT_TRUE(cntl.Failed());
    EXPECT_EQ(cntl.ErrorCode(), TERR_RPC_TIMEDOUT);
    EXPECT_LT(took_ms, 250);  // returned at the deadline, not after sleep
}

TEST(Rpc, NoSuchMethod) {
    TestServer ts;
    ASSERT_TRUE(ts.start());
    Channel channel;
    ASSERT_EQ(channel.Init(ts.ep, nullptr), 0);
    test::UnusedService_Stub stub(&channel);

    Controller cntl;
    test::EchoRequest req;
    req.set_message("x");
    test::EchoResponse res;
    stub.Nothing(&cntl, &req, &res, nullptr);
    EXPECT_TRUE(cntl.Failed());
    EXPECT_EQ(cntl.ErrorCode(), TERR_NO_METHOD);
}

TEST(Rpc, DeadServerRetriesThenFails) {
    Channel channel;
    ChannelOptions opts;
    opts.timeout_ms = 2000;
    opts.max_retry = 2;
    ASSERT_EQ(channel.Init("127.0.0.1:1", &opts), 0);  // refused
    test::EchoService_Stub stub(&channel);

    Controller cntl;
    test::EchoRequest req;
    req.set_message("doomed");
    test::EchoResponse res;
    stub.Echo(&cntl, &req, &res, nullptr);
    EXPECT_TRUE(cntl.Failed());
    EXPECT_EQ(cntl.retried_count(), 2);
}

TEST(Rpc, CallFromFiber) {
    // Sync RPC issued from a fiber worker (the common server-to-server
    // pattern) must park the fiber, not the worker thread.
    TestServer ts;
    ASSERT_TRUE(ts.start());
    Channel channel;
    ASSERT_EQ(channel.Init(ts.ep, nullptr), 0);

    struct Ctx {
        Channel* ch;
        std::atomic<int> ok{0};
    } ctx{&channel, {}};
    std::vector<fiber_t> tids(8);
    for (auto& tid : tids) {
        fiber_start_background(
            &tid, nullptr,
            [](void* arg) -> void* {
                Ctx* c = (Ctx*)arg;
                test::EchoService_Stub stub(c->ch);
                Controller cntl;
                test::EchoRequest req;
                req.set_message("from fiber");
                test::EchoResponse res;
                stub.Echo(&cntl, &req, &res, nullptr);
                if (!cntl.Failed() && res.message() == "from fiber") {
                    c->ok.fetch_add(1);
                }
                return nullptr;
            },
            &ctx);
    }
    for (auto tid : tids) fiber_join(tid, nullptr);
    EXPECT_EQ(ctx.ok.load(), 8);
}

// ---------------- backup requests ----------------
// Reference semantics (src/brpc/controller.cpp:344-358,625-638 +
// docs/en/backup_request.md): after backup_request_ms without a response,
// re-issue the call on a new call-id version; first response wins; the
// backup must actually cut the tail, which requires user handlers to run
// OFF the connection's input fiber (otherwise the backup is never parsed
// while the original's handler blocks the fiber).

namespace {

// Sleeps on the FIRST call only: the original hangs, the backup (a second
// call on the same connection) returns immediately.
class SlowFirstEchoServiceImpl : public test::EchoService {
public:
    void Echo(google::protobuf::RpcController* cntl_base,
              const test::EchoRequest* request, test::EchoResponse* response,
              google::protobuf::Closure* done) override {
        (void)cntl_base;
        if (ncalls.fetch_add(1, std::memory_order_relaxed) == 0) {
            fiber_usleep(800 * 1000);
        }
        response->set_message(request->message());
        done->Run();
    }
    std::atomic<int> ncalls{0};
};

// Sleeps on EVERY call.
class AlwaysSlowEchoServiceImpl : public test::EchoService {
public:
    void Echo(google::protobuf::RpcController* cntl_base,
              const test::EchoRequest* request, test::EchoResponse* response,
              google::protobuf::Closure* done) override {
        (void)cntl_base;
        ncalls.fetch_add(1, std::memory_order_relaxed);
        fiber_usleep(sleep_us);
        response->set_message(request->message());
        done->Run();
    }
    int64_t sleep_us = 800 * 1000;
    std::atomic<int> ncalls{0};
};

}  // namespace

TEST(Backup, BackupWinsOnSlowServer) {
    // Single connection: the original call's handler sleeps 400ms; the
    // backup fires at 20ms and its response wins. Only works when user
    // code runs off the input fiber (the backup must be PARSED while the
    // original's handler sleeps).
    SlowFirstEchoServiceImpl service;
    Server server;
    ASSERT_EQ(0, server.AddService(&service));
    EndPoint listen;
    str2endpoint("127.0.0.1:0", &listen);
    ASSERT_EQ(0, server.Start(listen, nullptr));
    EndPoint ep;
    str2endpoint("127.0.0.1", server.listened_port(), &ep);

    Channel channel;
    ChannelOptions opts;
    opts.timeout_ms = 3000;
    opts.max_retry = 1;  // a backup consumes retry budget
    ASSERT_EQ(0, channel.Init(ep, &opts));
    test::EchoService_Stub stub(&channel);

    Controller cntl;
    cntl.set_backup_request_ms(20);
    test::EchoRequest req;
    req.set_message("backup-wins");
    test::EchoResponse res;
    const int64_t t0 = monotonic_time_us();
    stub.Echo(&cntl, &req, &res, nullptr);
    const int64_t took_ms = (monotonic_time_us() - t0) / 1000;
    ASSERT_FALSE(cntl.Failed());
    EXPECT_EQ(res.message(), "backup-wins");
    // Won by the backup: far sooner than the original's 800ms sleep
    // (bound leaves ~25x the 20ms backup delay for sanitizer slowdown).
    EXPECT_LT(took_ms, 500);
    // Both the original and the backup reached the server.
    for (int i = 0; i < 100 && service.ncalls.load() < 2; ++i) {
        usleep(10000);
    }
    EXPECT_EQ(service.ncalls.load(), 2);
}

TEST(Backup, BackupPicksDifferentServer) {
    // Two-server LB: one always slow, one fast. Whenever the original
    // lands on the slow server, the backup goes to the OTHER server
    // (excluded-server selection) and wins.
    AlwaysSlowEchoServiceImpl slow;
    EchoServiceImpl fast;
    Server slow_srv, fast_srv;
    ASSERT_EQ(0, slow_srv.AddService(&slow));
    ASSERT_EQ(0, fast_srv.AddService(&fast));
    EndPoint any;
    str2endpoint("127.0.0.1:0", &any);
    ASSERT_EQ(0, slow_srv.Start(any, nullptr));
    ASSERT_EQ(0, fast_srv.Start(any, nullptr));

    char url[128];
    snprintf(url, sizeof(url), "list://127.0.0.1:%d,127.0.0.1:%d",
             slow_srv.listened_port(), fast_srv.listened_port());
    Channel channel;
    ChannelOptions opts;
    opts.timeout_ms = 3000;
    opts.max_retry = 1;
    opts.backup_request_ms = 20;
    ASSERT_EQ(0, channel.Init(url, "rr", &opts));
    test::EchoService_Stub stub(&channel);

    for (int i = 0; i < 6; ++i) {
        Controller cntl;
        test::EchoRequest req;
        req.set_message("pick-other");
        test::EchoResponse res;
        const int64_t t0 = monotonic_time_us();
        stub.Echo(&cntl, &req, &res, nullptr);
        const int64_t took_ms = (monotonic_time_us() - t0) / 1000;
        ASSERT_FALSE(cntl.Failed());
        // Never pay the slow server's 800ms: the backup reroutes.
        EXPECT_LT(took_ms, 500);
    }
    EXPECT_GT(fast.ncalls.load(), 0);
}

TEST(Backup, DeadBackupFallsBackToOriginal) {
    // LB over [slow server, dead port]. If the backup is routed to the
    // dead server, its connection failure must NOT fail the RPC — the
    // original (slow but alive) still completes.
    AlwaysSlowEchoServiceImpl slow;
    slow.sleep_us = 200 * 1000;
    Server slow_srv;
    ASSERT_EQ(0, slow_srv.AddService(&slow));
    EndPoint any;
    str2endpoint("127.0.0.1:0", &any);
    ASSERT_EQ(0, slow_srv.Start(any, nullptr));

    char url[128];
    snprintf(url, sizeof(url), "list://127.0.0.1:%d,127.0.0.1:1",
             slow_srv.listened_port());
    Channel channel;
    ChannelOptions opts;
    opts.timeout_ms = 3000;
    opts.max_retry = 3;  // budget for dead-server re-picks AND the backup
    opts.backup_request_ms = 20;
    ASSERT_EQ(0, channel.Init(url, "rr", &opts));
    test::EchoService_Stub stub(&channel);

    int ok = 0;
    for (int i = 0; i < 6; ++i) {
        Controller cntl;
        test::EchoRequest req;
        req.set_message("fallback");
        test::EchoResponse res;
        stub.Echo(&cntl, &req, &res, nullptr);
        if (!cntl.Failed()) ++ok;
    }
    // Every call must eventually succeed via the live server, whether the
    // original or the backup was the one sent to the dead port.
    EXPECT_EQ(ok, 6);
    EXPECT_GT(slow.ncalls.load(), 0);
}

// ---------------- concurrency limiters ----------------
// Reference: policy/auto_concurrency_limiter.cpp — Little's-law capacity
// with explore headroom; overload sheds excess while p99 of admitted
// requests stays near the no-load latency.

TEST(AutoLimiter, ConvergesToLittlesLaw) {
    AutoConcurrencyLimiter::Options o;
    o.sampling_interval_us = 0;  // sample every response
    // Small-but-not-sparse windows: the usleep pacing below lands well
    // above min_sample_count per window (sparse windows are skipped).
    o.sample_window_us = 5000;
    o.min_sample_count = 5;
    o.max_sample_count = 10;
    o.remeasure_interval_us = (int64_t)3600 * 1000 * 1000;  // never probe
    AutoConcurrencyLimiter lim(o);
    // Steady state: 2ms latency at ~1000 qps -> capacity ~2 in flight.
    // Feed enough windows for the EMAs to settle.
    for (int w = 0; w < 60; ++w) {
        for (int i = 0; i < 12; ++i) {
            lim.OnResponded(0, 2000);
            usleep(100);  // ~10k/s offered -> windows elapse in real time
        }
    }
    EXPECT_GT(lim.min_latency_us(), 0);
    EXPECT_GT(lim.ema_max_qps(), 0.0);
    // Limit = min_lat * qps * (1+explore) >= the floor, and sane (not
    // stuck at the initial 40 with these tiny real-time windows it should
    // have re-derived something; bounds kept loose for CI timing).
    EXPECT_GE(lim.MaxConcurrency(), o.min_max_concurrency);
    EXPECT_LT(lim.MaxConcurrency(), 4000);
}

TEST(AutoLimiter, AllFailedWindowHalvesLimit) {
    AutoConcurrencyLimiter::Options o;
    o.sampling_interval_us = 0;
    o.sample_window_us = 1000;
    o.min_sample_count = 4;
    o.max_sample_count = 8;
    o.initial_max_concurrency = 64;
    o.remeasure_interval_us = (int64_t)3600 * 1000 * 1000;
    AutoConcurrencyLimiter lim(o);
    const int64_t before = lim.MaxConcurrency();
    for (int i = 0; i < 16; ++i) {
        lim.OnResponded(1, 1000);
        usleep(200);
    }
    EXPECT_LT(lim.MaxConcurrency(), before);
}

TEST(AutoLimiter, OverloadShedsAndServes) {
    // Integration: handler takes ~4ms; 32 concurrent callers offer ~8x
    // the single-core capacity. The auto limiter must reject some load
    // (TERR_LIMIT_EXCEEDED) while admitted requests keep completing.
    EchoServiceImpl service;
    Server server;
    ASSERT_EQ(0, server.AddService(&service));
    ServerOptions sopts;
    sopts.auto_concurrency = true;
    sopts.auto_cl_options.sampling_interval_us = 0;
    sopts.auto_cl_options.sample_window_us = 20 * 1000;
    sopts.auto_cl_options.min_sample_count = 20;
    sopts.auto_cl_options.max_sample_count = 40;
    sopts.auto_cl_options.initial_max_concurrency = 8;
    sopts.auto_cl_options.remeasure_interval_us =
        (int64_t)3600 * 1000 * 1000;
    EndPoint listen;
    str2endpoint("127.0.0.1:0", &listen);
    ASSERT_EQ(0, server.Start(listen, &sopts));
    EndPoint ep;
    str2endpoint("127.0.0.1", server.listened_port(), &ep);

    Channel channel;
    ChannelOptions copts;
    copts.timeout_ms = 5000;
    ASSERT_EQ(0, channel.Init(ep, &copts));

    struct Ctx {
        Channel* ch;
        std::atomic<int> ok{0};
        std::atomic<int> rejected{0};
        std::atomic<int> other{0};
    } ctx{&channel, {}, {}, {}};
    std::vector<fiber_t> tids(32);
    for (auto& tid : tids) {
        fiber_start_background(
            &tid, nullptr,
            [](void* arg) -> void* {
                Ctx* c = (Ctx*)arg;
                test::EchoService_Stub stub(c->ch);
                for (int i = 0; i < 12; ++i) {
                    Controller cntl;
                    test::EchoRequest req;
                    req.set_message("overload");
                    req.set_sleep_us(4000);
                    test::EchoResponse res;
                    stub.Echo(&cntl, &req, &res, nullptr);
                    if (!cntl.Failed()) {
                        c->ok.fetch_add(1);
                    } else if (cntl.ErrorCode() == TERR_LIMIT_EXCEEDED) {
                        c->rejected.fetch_add(1);
                    } else {
                        c->other.fetch_add(1);
                    }
                }
                return nullptr;
            },
            &ctx);
    }
    for (auto tid : tids) fiber_join(tid, nullptr);
    // Overload was shed...
    EXPECT_GT(ctx.rejected.load(), 0);
    // ...but the service kept serving (no collapse, no spurious errors).
    // Threshold is deliberately loose: under ASan the whole suite runs ~10x
    // slower and admission drops accordingly.
    EXPECT_GT(ctx.ok.load(), 10);
    EXPECT_EQ(ctx.other.load(), 0);
    EXPECT_EQ(ctx.ok.load() + ctx.rejected.load(), 32 * 12);
}

// ---------------- compression + checksum ----------------
// Reference: policy/gzip_compress.cpp (payload compression keyed by the
// wire's compress_type) + butil/crc32c / policy/crc32c_checksum (frame
// body integrity). compress_type=1 must round-trip; a corrupted frame
// must be rejected by the checksum, not parsed.

#include "rpc_meta.pb.h"
#include "tbase/crc32c.h"
#include "tbase/flags.h"
#include "trpc/compress.h"
#include "trpc/pb_compat.h"
#include "trpc/policy_tpu_std.h"

DECLARE_bool(rpc_checksum);

TEST(Crc32c, KnownVectors) {
    // RFC 3720 test vector.
    EXPECT_EQ(0xE3069283u, crc32c("123456789", 9));
    EXPECT_EQ(0u, crc32c("", 0));
    // Incremental == one-shot, across odd split points.
    const char* s = "the quick brown fox jumps over the lazy dog";
    const uint32_t whole = crc32c(s, strlen(s));
    for (size_t cut = 1; cut < strlen(s); cut += 7) {
        EXPECT_EQ(whole, crc32c_extend(crc32c(s, cut), s + cut,
                                       strlen(s) - cut));
    }
}

TEST(Compress, GzipRoundTrip) {
    std::string data;
    for (int i = 0; i < 3000; ++i) data += "compressible payload ";
    IOBuf in;
    in.append(data);
    IOBuf gz;
    ASSERT_TRUE(CompressBody(COMPRESS_GZIP, in, &gz));
    EXPECT_LT(gz.size(), in.size() / 4);  // actually compressed
    IOBuf back;
    ASSERT_TRUE(DecompressBody(COMPRESS_GZIP, gz, &back));
    EXPECT_TRUE(back.equals(data));
    // Corrupt stream fails cleanly.
    std::string corrupt = gz.to_string();
    corrupt[corrupt.size() / 2] ^= 0x5a;
    IOBuf bad;
    bad.append(corrupt);
    IOBuf out;
    EXPECT_FALSE(DecompressBody(COMPRESS_GZIP, bad, &out));
}

TEST(Compress, RpcGzipRoundTripOverTcp) {
    // Service that echoes and compresses its response.
    class GzEcho : public test::EchoService {
    public:
        void Echo(google::protobuf::RpcController* cb,
                  const test::EchoRequest* req, test::EchoResponse* res,
                  google::protobuf::Closure* done) override {
            auto* cntl = static_cast<Controller*>(cb);
            EXPECT_EQ(cntl->request_compress_type(), COMPRESS_GZIP);
            res->set_message(req->message());
            cntl->set_response_compress_type(COMPRESS_GZIP);
            done->Run();
        }
    };
    GzEcho service;
    Server server;
    ASSERT_EQ(0, server.AddService(&service));
    EndPoint listen;
    str2endpoint("127.0.0.1:0", &listen);
    ASSERT_EQ(0, server.Start(listen, nullptr));
    EndPoint ep;
    str2endpoint("127.0.0.1", server.listened_port(), &ep);
    Channel ch;
    ASSERT_EQ(0, ch.Init(ep, nullptr));
    test::EchoService_Stub stub(&ch);

    FLAGS_rpc_checksum.set(true);  // checksum over the compressed body
    std::string big(200 * 1024, 'z');
    Controller cntl;
    cntl.set_timeout_ms(3000);
    cntl.set_request_compress_type(COMPRESS_GZIP);
    test::EchoRequest req;
    req.set_message(big);
    test::EchoResponse res;
    stub.Echo(&cntl, &req, &res, nullptr);
    FLAGS_rpc_checksum.set(false);
    ASSERT_FALSE(cntl.Failed());
    EXPECT_EQ(res.message(), big);
}

TEST(Compress, CorruptedFrameRejectedByChecksum) {
    EchoServiceImpl service;
    Server server;
    ASSERT_EQ(0, server.AddService(&service));
    EndPoint listen;
    str2endpoint("127.0.0.1:0", &listen);
    ASSERT_EQ(0, server.Start(listen, nullptr));

    // Hand-craft a request frame whose checksum does NOT match the body.
    rpc::RpcMeta meta;
    auto* rm = meta.mutable_request();
    rm->set_service_name("test.EchoService");
    rm->set_method_name("Echo");
    meta.set_correlation_id(12345);
    test::EchoRequest payload_msg;
    payload_msg.set_message("tampered");
    IOBuf payload;
    ASSERT_TRUE(SerializePbToIOBuf(payload_msg, &payload));
    meta.set_attachment_size(0);
    meta.set_body_checksum(crc32c_iobuf(0, payload) ^ 0xdeadbeef);
    IOBuf meta_buf;
    ASSERT_TRUE(SerializePbToIOBuf(meta, &meta_buf));
    IOBuf frame;
    PackTpuStdFrame(&frame, meta_buf, payload, IOBuf());
    const std::string wire = frame.to_string();

    const int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in addr;
    EndPoint ep;
    str2endpoint("127.0.0.1", server.listened_port(), &ep);
    endpoint2sockaddr(ep, &addr);
    ASSERT_EQ(0, ::connect(fd, (sockaddr*)&addr, sizeof(addr)));
    ASSERT_EQ((ssize_t)wire.size(), write(fd, wire.data(), wire.size()));
    // Read the error response frame and decode its meta.
    std::string got;
    char buf[4096];
    uint32_t body_size = 0, meta_size = 0;
    for (int i = 0; i < 200; ++i) {
        if (got.size() >= 12) {
            memcpy(&body_size, got.data() + 4, 4);
            memcpy(&meta_size, got.data() + 8, 4);
            body_size = ntohl(body_size);
            meta_size = ntohl(meta_size);
            if (got.size() >= 12u + body_size) break;  // full frame
        }
        const ssize_t r = read(fd, buf, sizeof(buf));
        if (r <= 0) break;
        got.append(buf, (size_t)r);
    }
    close(fd);
    ASSERT_GE(got.size(), 12u);
    ASSERT_GE(got.size(), 12u + body_size);
    rpc::RpcMeta rsp_meta;
    ASSERT_TRUE(rsp_meta.ParseFromArray(got.data() + 12, (int)meta_size));
    EXPECT_EQ(rsp_meta.response().error_code(), TERR_REQUEST);
    EXPECT_TRUE(rsp_meta.response().error_text().find("checksum") !=
                std::string::npos);
    // The service never ran.
    EXPECT_EQ(service.ncalls.load(), 0);
}

// ---------------- pooled / short connection modes ----------------
// Reference: socket.cpp GetPooledSocket/GetShortSocket + controller.cpp
// "NOT reuse pooled connection if this call fails and no response": one
// in-flight RPC per pooled connection, returned on response, closed on
// failure; short connections close after every call.

#include "tnet/socket_map.h"

TEST(Pooled, SequentialCallsReuseOneConnection) {
    TestServer ts;
    ASSERT_TRUE(ts.start());
    Channel channel;
    ChannelOptions opts;
    opts.timeout_ms = 3000;
    opts.connection_type = CONNECTION_TYPE_POOLED;
    ASSERT_EQ(0, channel.Init(ts.ep, &opts));
    test::EchoService_Stub stub(&channel);
    for (int i = 0; i < 5; ++i) {
        Controller cntl;
        test::EchoRequest req;
        req.set_message("pooled");
        test::EchoResponse res;
        stub.Echo(&cntl, &req, &res, nullptr);
        ASSERT_FALSE(cntl.Failed());
    }
    // One pooled data connection total (returned between calls). The
    // shared "main" socket never connects in pooled mode (it only carries
    // identity), so accepted == 1.
    EXPECT_EQ(ts.server.acceptor()->accepted_count(), 1);
    EXPECT_EQ(SocketPool::singleton()->idle_count(ts.ep), 1u);
}

TEST(Pooled, ConcurrentCallsUseDistinctConnections) {
    TestServer ts;
    ASSERT_TRUE(ts.start());
    Channel channel;
    ChannelOptions opts;
    opts.timeout_ms = 3000;
    opts.connection_type = CONNECTION_TYPE_POOLED;
    ASSERT_EQ(0, channel.Init(ts.ep, &opts));

    struct Ctx {
        Channel* ch;
        std::atomic<int> ok{0};
    } ctx{&channel, {}};
    std::vector<fiber_t> tids(4);
    for (auto& tid : tids) {
        fiber_start_background(
            &tid, nullptr,
            [](void* arg) -> void* {
                Ctx* c = (Ctx*)arg;
                test::EchoService_Stub stub(c->ch);
                Controller cntl;
                test::EchoRequest req;
                req.set_message("concurrent");
                req.set_sleep_us(100 * 1000);  // overlap all four
                test::EchoResponse res;
                stub.Echo(&cntl, &req, &res, nullptr);
                if (!cntl.Failed()) c->ok.fetch_add(1);
                return nullptr;
            },
            &ctx);
    }
    for (auto tid : tids) fiber_join(tid, nullptr);
    EXPECT_EQ(ctx.ok.load(), 4);
    // Four overlapping calls -> four distinct pooled connections, all
    // idle afterwards.
    EXPECT_EQ(ts.server.acceptor()->accepted_count(), 4);
    EXPECT_EQ(SocketPool::singleton()->idle_count(ts.ep), 4u);
}

TEST(Pooled, FailedCallDoesNotReuseConnection) {
    TestServer ts;
    ASSERT_TRUE(ts.start());
    Channel channel;
    ChannelOptions opts;
    opts.timeout_ms = 100;
    opts.max_retry = 0;
    opts.connection_type = CONNECTION_TYPE_POOLED;
    ASSERT_EQ(0, channel.Init(ts.ep, &opts));
    test::EchoService_Stub stub(&channel);
    {
        Controller cntl;
        test::EchoRequest req;
        req.set_message("will-timeout");
        req.set_sleep_us(400 * 1000);
        test::EchoResponse res;
        stub.Echo(&cntl, &req, &res, nullptr);
        EXPECT_TRUE(cntl.Failed());
    }
    // The timed-out call's connection must NOT be pooled (an orphan
    // response is still coming on it).
    EXPECT_EQ(SocketPool::singleton()->idle_count(ts.ep), 0u);
    // A fresh call works on a new connection.
    for (int i = 0; i < 100; ++i) {  // wait out the orphan response
        usleep(5000);
    }
    Controller cntl;
    test::EchoRequest req;
    req.set_message("after");
    test::EchoResponse res;
    stub.Echo(&cntl, &req, &res, nullptr);
    EXPECT_FALSE(cntl.Failed());
    EXPECT_EQ(res.message(), "after");
}

TEST(Short, FreshConnectionPerCall) {
    TestServer ts;
    ASSERT_TRUE(ts.start());
    Channel channel;
    ChannelOptions opts;
    opts.timeout_ms = 3000;
    opts.connection_type = CONNECTION_TYPE_SHORT;
    ASSERT_EQ(0, channel.Init(ts.ep, &opts));
    test::EchoService_Stub stub(&channel);
    for (int i = 0; i < 3; ++i) {
        Controller cntl;
        test::EchoRequest req;
        req.set_message("short");
        test::EchoResponse res;
        stub.Echo(&cntl, &req, &res, nullptr);
        ASSERT_FALSE(cntl.Failed());
    }
    // One fresh connection per call; a contention-induced retry may add
    // more, but short mode never REUSES one (and never pools).
    EXPECT_GE(ts.server.acceptor()->accepted_count(), 3);
    EXPECT_EQ(SocketPool::singleton()->idle_count(ts.ep), 0u);
}

// ---------------- interceptor ----------------
// Reference: src/brpc/interceptor.h:30 — server-side Accept() runs before
// user code; rejection answers the error without invoking the service.

namespace {
class BlockEvens : public Interceptor {
public:
    bool Accept(const Controller* cntl, int* error_code,
                std::string* error_text) override {
        const int n = ncalls.fetch_add(1);
        if (n % 2 == 1) {
            *error_code = TERR_REQUEST;
            *error_text = "blocked by interceptor";
            return false;
        }
        (void)cntl;
        return true;
    }
    std::atomic<int> ncalls{0};
};
}  // namespace

TEST(Interceptor, RejectsBeforeUserCode) {
    EchoServiceImpl service;
    BlockEvens interceptor;
    Server server;
    ASSERT_EQ(0, server.AddService(&service));
    ServerOptions sopts;
    sopts.interceptor = &interceptor;
    EndPoint listen;
    str2endpoint("127.0.0.1:0", &listen);
    ASSERT_EQ(0, server.Start(listen, &sopts));
    EndPoint ep;
    str2endpoint("127.0.0.1", server.listened_port(), &ep);
    Channel ch;
    ChannelOptions copts;
    copts.timeout_ms = 2000;
    copts.max_retry = 0;
    ASSERT_EQ(0, ch.Init(ep, &copts));
    test::EchoService_Stub stub(&ch);

    int ok = 0, rejected = 0;
    for (int i = 0; i < 6; ++i) {
        Controller cntl;
        test::EchoRequest req;
        req.set_message("i");
        test::EchoResponse res;
        stub.Echo(&cntl, &req, &res, nullptr);
        if (!cntl.Failed()) {
            ++ok;
        } else if (cntl.ErrorText().find("interceptor") !=
                   std::string::npos) {
            ++rejected;
        }
    }
    EXPECT_EQ(ok, 3);
    EXPECT_EQ(rejected, 3);
    // Rejected calls never reached the service.
    EXPECT_EQ(service.ncalls.load(), 3);
}

// ---------------- rpc_dump / recordio / replay ----------------
// Reference: butil/recordio + brpc/rpc_dump.{h,cpp} + tools/rpc_replay —
// sampled live requests land in recordio files and replay against a
// server with rewritten correlation ids.

#include "tbase/recordio.h"
#include "trpc/rpc_dump.h"

DECLARE_bool(rpc_dump);
DECLARE_string(rpc_dump_dir);

TEST(RecordIO, RoundTripAndCorruptionDetected) {
    const std::string path =
        "/tmp/tpurpc_reciotest_" + std::to_string(getpid());
    unlink(path.c_str());
    {
        RecordWriter w(path);
        ASSERT_TRUE(w.valid());
        for (int i = 0; i < 5; ++i) {
            IOBuf rec;
            rec.append("record-" + std::to_string(i) +
                       std::string((size_t)i * 100, 'x'));
            ASSERT_TRUE(w.Write(rec));
        }
    }
    {
        RecordReader r(path);
        ASSERT_TRUE(r.valid());
        IOBuf rec;
        for (int i = 0; i < 5; ++i) {
            ASSERT_TRUE(r.Read(&rec));
            EXPECT_EQ(rec.size(), 8 + (i >= 10 ? 0 : 0) + (size_t)i * 100);
        }
        EXPECT_FALSE(r.Read(&rec));  // clean EOF
    }
    // Corrupt a payload byte: that record (and the stream) must stop.
    {
        FILE* f = fopen(path.c_str(), "r+b");
        fseek(f, 14, SEEK_SET);  // inside record 0's payload
        fputc('Z', f);
        fclose(f);
        RecordReader r(path);
        IOBuf rec;
        EXPECT_FALSE(r.Read(&rec));
    }
    unlink(path.c_str());
}

TEST(RpcDump, CaptureAndReplay) {
    TestServer ts;
    ASSERT_TRUE(ts.start());
    Channel ch;
    ASSERT_EQ(0, ch.Init(ts.ep, nullptr));
    test::EchoService_Stub stub(&ch);

    FLAGS_rpc_dump_dir.set("/tmp");
    const std::string dump_path = RpcDumpFilePath();
    unlink(dump_path.c_str());
    FLAGS_rpc_dump.set(true);
    for (int i = 0; i < 5; ++i) {
        Controller cntl;
        cntl.set_timeout_ms(3000);
        test::EchoRequest req;
        req.set_message("dump-me-" + std::to_string(i));
        test::EchoResponse res;
        stub.Echo(&cntl, &req, &res, nullptr);
        ASSERT_FALSE(cntl.Failed());
    }
    FLAGS_rpc_dump.set(false);
    // The Collector dispatches on a ~50ms cadence.
    int records = 0;
    for (int i = 0; i < 100; ++i) {
        RecordReader r(dump_path);
        records = 0;
        IOBuf rec;
        while (r.valid() && r.Read(&rec)) ++records;
        if (records >= 5) break;
        usleep(20 * 1000);
    }
    EXPECT_EQ(records, 5);

    // Replay the capture twice: the server answers each resent request.
    const int before = ts.service.ncalls.load();
    const int ok = ReplayDumpFile(dump_path, ts.ep, 2);
    EXPECT_EQ(ok, 10);
    EXPECT_EQ(ts.service.ncalls.load(), before + 10);
    unlink(dump_path.c_str());
}

// ---------------- server fiber tag ----------------
// Reference: bthread_tag server option (example/bthread_tag_echo_c++) —
// a server's user code runs on its own isolated worker pool.

#include "tfiber/task_group.h"

TEST(WorkerTags, ServerHandlersRunOnConfiguredPool) {
    class PoolCheckService : public test::EchoService {
    public:
        void Echo(google::protobuf::RpcController*,
                  const test::EchoRequest* req, test::EchoResponse* res,
                  google::protobuf::Closure* done) override {
            TaskGroup* g = TaskGroup::tls_group();
            const bool right_pool =
                g != nullptr && g->control() == TaskControl::of_tag(11);
            res->set_message(right_pool ? req->message() : "WRONG-POOL");
            done->Run();
        }
    };
    PoolCheckService service;
    Server server;
    ASSERT_EQ(0, server.AddService(&service));
    ServerOptions sopts;
    sopts.fiber_tag = 11;
    EndPoint listen;
    str2endpoint("127.0.0.1:0", &listen);
    ASSERT_EQ(0, server.Start(listen, &sopts));
    EndPoint ep;
    str2endpoint("127.0.0.1", server.listened_port(), &ep);
    Channel ch;
    ASSERT_EQ(0, ch.Init(ep, nullptr));
    test::EchoService_Stub stub(&ch);
    for (int i = 0; i < 4; ++i) {
        Controller cntl;
        test::EchoRequest req;
        req.set_message("tagged");
        test::EchoResponse res;
        stub.Echo(&cntl, &req, &res, nullptr);
        ASSERT_FALSE(cntl.Failed());
        EXPECT_EQ(res.message(), "tagged");
    }
}

TEST(WorkerTags, BackupPoolTagRejected) {
    // Tag 63 is reserved for usercode overload isolation
    // (kUsercodeBackupTag, policy_tpu_std.h): a user server there would
    // share the overflow pool and defeat the isolation. Start must
    // reject it instead of silently sharing.
    class NopService : public test::EchoService {
    public:
        void Echo(google::protobuf::RpcController*, const test::EchoRequest*,
                  test::EchoResponse*,
                  google::protobuf::Closure* done) override {
            done->Run();
        }
    };
    NopService service;
    Server server;
    ASSERT_EQ(0, server.AddService(&service));
    ServerOptions sopts;
    sopts.fiber_tag = kUsercodeBackupTag;
    EndPoint listen;
    str2endpoint("127.0.0.1:0", &listen);
    EXPECT_NE(0, server.Start(listen, &sopts));
    // An adjacent, unreserved tag still works.
    sopts.fiber_tag = kUsercodeBackupTag - 1;
    ASSERT_EQ(0, server.Start(listen, &sopts));
}

// ---------------- pluggable retry/backup + timeout limiter + snappy ----------------
// Reference: retry_policy.h:28-112, backup_request_policy.h,
// policy/timeout_concurrency_limiter.*, policy/snappy_compress.cpp.

#include "trpc/compress.h"
#include "trpc/concurrency_limiter.h"
#include "trpc/retry_policy.h"

namespace {

class CountingRetryPolicy : public RetryPolicy {
public:
    explicit CountingRetryPolicy(bool allow, int64_t backoff_ms = 0)
        : allow_(allow), backoff_ms_(backoff_ms) {}
    bool DoRetry(const Controller* cntl) const override {
        consulted_.fetch_add(1);
        last_error_ = cntl->ErrorCode();
        return allow_;
    }
    int64_t BackoffMs(const Controller*) const override {
        return backoff_ms_;
    }
    int consulted() const { return consulted_.load(); }
    int last_error() const { return last_error_; }

private:
    bool allow_;
    int64_t backoff_ms_;
    mutable std::atomic<int> consulted_{0};
    mutable int last_error_ = 0;
};

}  // namespace

TEST(RetryPolicy, PolicyDecidesAndSeesTheError) {
    // Dead port: every try fails with a connection error. A vetoing
    // policy is consulted ONCE and the RPC fails after the first try.
    Channel ch;
    ChannelOptions opts;
    opts.timeout_ms = 5000;
    opts.max_retry = 3;
    CountingRetryPolicy veto(false);
    opts.retry_policy = &veto;
    ASSERT_EQ(0, ch.Init("127.0.0.1:1", &opts));  // nothing listens on 1
    test::EchoService_Stub stub(&ch);
    Controller cntl;
    test::EchoRequest req;
    req.set_message("x");
    test::EchoResponse res;
    stub.Echo(&cntl, &req, &res, nullptr);
    EXPECT_TRUE(cntl.Failed());
    EXPECT_EQ(veto.consulted(), 1);
    EXPECT_NE(veto.last_error(), 0);
}

TEST(RetryPolicy, FixedBackoffDelaysRetries) {
    Channel ch;
    ChannelOptions opts;
    opts.timeout_ms = 10000;
    opts.max_retry = 2;
    CountingRetryPolicy backoff(true, 80);
    opts.retry_policy = &backoff;
    ASSERT_EQ(0, ch.Init("127.0.0.1:1", &opts));
    test::EchoService_Stub stub(&ch);
    Controller cntl;
    test::EchoRequest req;
    req.set_message("x");
    test::EchoResponse res;
    const int64_t t0 = monotonic_time_us();
    stub.Echo(&cntl, &req, &res, nullptr);
    const int64_t elapsed_ms = (monotonic_time_us() - t0) / 1000;
    EXPECT_TRUE(cntl.Failed());
    EXPECT_EQ(backoff.consulted(), 3);  // initial + 2 retries, all failed
    // 2 backoffs of 80ms must be observable (connect failures themselves
    // are instant on loopback).
    EXPECT_GE(elapsed_ms, 150);
}

TEST(BackupPolicy, PolicyProvidesDelayAndCanVeto) {
    struct VetoBackupPolicy : public BackupRequestPolicy {
        int64_t GetDelayMs(const Controller*) const override { return 2; }
        bool DoBackup(const Controller*) const override {
            vetoed.fetch_add(1);
            return false;
        }
        mutable std::atomic<int> vetoed{0};
    } policy;
    TestServer ts;
    ASSERT_TRUE(ts.start());
    Channel ch;
    ChannelOptions opts;
    opts.timeout_ms = 5000;
    opts.backup_request_policy = &policy;
    ASSERT_EQ(0, ch.Init(ts.ep, &opts));
    test::EchoService_Stub stub(&ch);
    Controller cntl;
    test::EchoRequest req;
    req.set_message("hedge");
    req.set_sleep_us(20 * 1000);  // slower than the 2ms backup delay
    test::EchoResponse res;
    stub.Echo(&cntl, &req, &res, nullptr);
    ASSERT_FALSE(cntl.Failed());
    EXPECT_EQ(res.message(), "hedge");
    // The timer fired and the policy vetoed the hedge: exactly one call
    // reached the server.
    EXPECT_GE(policy.vetoed.load(), 1);
    EXPECT_EQ(ts.service.ncalls.load(), 1);
}

TEST(TimeoutLimiter, RejectsWhenQueueWaitExceedsBudget) {
    TimeoutConcurrencyLimiter::Options opt;
    opt.timeout_ms = 10;
    opt.min_concurrency = 2;
    TimeoutConcurrencyLimiter lim(opt);
    // Teach it ~5ms per request.
    for (int i = 0; i < 50; ++i) lim.OnResponded(0, 5000);
    EXPECT_GE(lim.avg_latency_us(), 4000);
    EXPECT_TRUE(lim.OnRequested(1));   // within min_concurrency
    EXPECT_TRUE(lim.OnRequested(2));
    // 3 queued x 5ms > 10ms budget: shed.
    EXPECT_FALSE(lim.OnRequested(3));
    // Failures must not poison the estimate.
    lim.OnResponded(42, 10 * 1000 * 1000);
    EXPECT_LT(lim.avg_latency_us(), 10000);
}

TEST(Snappy, RoundtripAndWireEcho) {
    if (!SnappyAvailable()) {
        fprintf(stderr, "libsnappy absent; skipping\n");
        return;
    }
    IOBuf in, compressed, out;
    std::string payload;
    for (int i = 0; i < 5000; ++i) payload += "snappy wire data ";
    in.append(payload);
    ASSERT_TRUE(CompressBody(COMPRESS_SNAPPY, in, &compressed));
    EXPECT_LT(compressed.size(), in.size());
    ASSERT_TRUE(DecompressBody(COMPRESS_SNAPPY, compressed, &out));
    EXPECT_EQ(out.to_string(), payload);
    // Corrupt stream rejected.
    IOBuf bad, dummy;
    bad.append("not snappy at all");
    EXPECT_FALSE(DecompressBody(COMPRESS_SNAPPY, bad, &dummy));

    // End to end: snappy-compressed request AND response over tpu_std.
    TestServer ts;
    ASSERT_TRUE(ts.start());
    Channel ch;
    ASSERT_EQ(0, ch.Init(ts.ep, nullptr));
    test::EchoService_Stub stub(&ch);
    Controller cntl;
    cntl.set_request_compress_type(COMPRESS_SNAPPY);
    cntl.set_response_compress_type(COMPRESS_SNAPPY);
    test::EchoRequest req;
    req.set_message(payload);
    test::EchoResponse res;
    stub.Echo(&cntl, &req, &res, nullptr);
    ASSERT_FALSE(cntl.Failed());
    EXPECT_EQ(res.message(), payload);
}

// ---------------- usercode backup pool ----------------
// Reference details/usercode_backup_pool.h:46-77: pthread-BLOCKING user
// handlers beyond the threshold run on an isolated pool so they cannot
// occupy every default worker and starve the IO fibers.

DECLARE_int32(usercode_backup_threshold);

namespace {
class BlockingEchoImpl : public test::EchoService {
public:
    void Echo(google::protobuf::RpcController*,
              const test::EchoRequest* request, test::EchoResponse* response,
              google::protobuf::Closure* done) override {
        if (request->sleep_us() > 0) {
            // BLOCKS the worker pthread (not a fiber park) — the hazard
            // the backup pool exists for.
            ::usleep((useconds_t)request->sleep_us());
        }
        TaskGroup* g = TaskGroup::tls_group();
        const bool on_default =
            g != nullptr && g->control() == TaskControl::singleton();
        response->set_message(request->message() +
                              (on_default ? "@default" : "@backup"));
        done->Run();
    }
};
}  // namespace

TEST(UsercodeBackupPool, BlockingHandlersDontStarveTheIoPath) {
    // With MORE pthread-blocking handlers in flight than default
    // workers, the overflow must move to the isolated backup pool so
    // the default pool's IO fibers (parsing, portal, responses) stay
    // live. Without the isolation every default worker would be stuck
    // in ::usleep and even /health would stall for the handler time.
    const int32_t old_threshold = FLAGS_usercode_backup_threshold.get();
    FLAGS_usercode_backup_threshold.set(2);
    BlockingEchoImpl service;
    Server server;
    ASSERT_EQ(0, server.AddService(&service));
    EndPoint listen;
    str2endpoint("127.0.0.1:0", &listen);
    ASSERT_EQ(0, server.Start(listen, nullptr));
    EndPoint ep;
    str2endpoint("127.0.0.1", server.listened_port(), &ep);
    Channel ch;
    ChannelOptions copts;
    copts.timeout_ms = 10000;
    ASSERT_EQ(0, ch.Init(ep, &copts));

    // Saturate: more pthread-blocking calls than default workers.
    const int nblockers = fiber_get_worker_count() + 4;
    struct Ctx {
        Channel* ch;
        std::atomic<int> ok{0};
        std::atomic<int> on_backup{0};
    } ctx{&ch, {}, {}};
    std::vector<fiber_t> tids((size_t)nblockers);
    for (auto& tid : tids) {
        fiber_start_background(
            &tid, nullptr,
            [](void* arg) -> void* {
                Ctx* c = (Ctx*)arg;
                test::EchoService_Stub stub(c->ch);
                Controller cntl;
                test::EchoRequest req;
                req.set_message("blocker");
                req.set_sleep_us(400 * 1000);
                test::EchoResponse res;
                stub.Echo(&cntl, &req, &res, nullptr);
                if (!cntl.Failed()) {
                    c->ok.fetch_add(1);
                    if (res.message().find("@backup") != std::string::npos) {
                        c->on_backup.fetch_add(1);
                    }
                }
                return nullptr;
            },
            &ctx);
    }
    fiber_usleep(80 * 1000);  // let the blockers occupy their workers
    // The IO path must still answer promptly: /health runs inline on a
    // default-pool input fiber (no usercode spawn).
    const int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in addr;
    endpoint2sockaddr(ep, &addr);
    ASSERT_EQ(0, ::connect(fd, (sockaddr*)&addr, sizeof(addr)));
    const char hreq[] = "GET /health HTTP/1.1\r\nHost: x\r\n\r\n";
    const int64_t t0 = monotonic_time_us();
    (void)!::send(fd, hreq, sizeof(hreq) - 1, 0);
    char buf[512];
    const ssize_t r = ::recv(fd, buf, sizeof(buf), 0);
    const int64_t health_ms = (monotonic_time_us() - t0) / 1000;
    ::close(fd);
    ASSERT_GT(r, 0);
    EXPECT_NE(std::string(buf, (size_t)r).find("200"), std::string::npos);
    EXPECT_LT(health_ms, 200);  // all-workers-blocked would wait ~400ms
    for (auto tid : tids) fiber_join(tid, nullptr);
    EXPECT_EQ(ctx.ok.load(), nblockers);
    // The overflow really went to the isolated pool.
    EXPECT_GE(ctx.on_backup.load(), nblockers - 2);
    FLAGS_usercode_backup_threshold.set(old_threshold);
}
