// End-to-end RPC tests over loopback: the in-process style of the
// reference's ChannelTest (test/brpc_channel_unittest.cpp:195) — real
// server, real client stack, sync/async, attachments, timeouts, retries.
#include <unistd.h>

#include <atomic>
#include <string>

#include "echo.pb.h"
#include "tbase/errno.h"
#include "tfiber/fiber.h"
#include "tfiber/fiber_sync.h"
#include "trpc/channel.h"
#include "trpc/controller.h"
#include "trpc/server.h"
#include "ttest/ttest.h"

using namespace tpurpc;

namespace {

class EchoServiceImpl : public test::EchoService {
public:
    void Echo(google::protobuf::RpcController* cntl_base,
              const test::EchoRequest* request, test::EchoResponse* response,
              google::protobuf::Closure* done) override {
        Controller* cntl = static_cast<Controller*>(cntl_base);
        if (request->sleep_us() > 0) {
            fiber_usleep(request->sleep_us());
        }
        response->set_message(request->message());
        // Echo the attachment back (zero-copy).
        cntl->response_attachment().append(cntl->request_attachment());
        ncalls.fetch_add(1, std::memory_order_relaxed);
        done->Run();
    }
    std::atomic<int> ncalls{0};
};

struct TestServer {
    Server server;
    EchoServiceImpl service;
    EndPoint ep;

    bool start() {
        if (server.AddService(&service) != 0) return false;
        EndPoint listen;
        str2endpoint("127.0.0.1:0", &listen);
        if (server.Start(listen, nullptr) != 0) return false;
        str2endpoint("127.0.0.1", server.listened_port(), &ep);
        return true;
    }
};

}  // namespace

TEST(Rpc, SyncEcho) {
    TestServer ts;
    ASSERT_TRUE(ts.start());
    Channel channel;
    ASSERT_EQ(channel.Init(ts.ep, nullptr), 0);
    test::EchoService_Stub stub(&channel);

    Controller cntl;
    test::EchoRequest req;
    req.set_message("hello rpc");
    test::EchoResponse res;
    stub.Echo(&cntl, &req, &res, nullptr);
    ASSERT_FALSE(cntl.Failed());
    EXPECT_EQ(res.message(), "hello rpc");
    EXPECT_GT(cntl.latency_us(), 0);
    EXPECT_EQ(ts.service.ncalls.load(), 1);
}

TEST(Rpc, ManySyncCalls) {
    TestServer ts;
    ASSERT_TRUE(ts.start());
    Channel channel;
    ASSERT_EQ(channel.Init(ts.ep, nullptr), 0);
    test::EchoService_Stub stub(&channel);
    for (int i = 0; i < 100; ++i) {
        Controller cntl;
        test::EchoRequest req;
        req.set_message("m" + std::to_string(i));
        test::EchoResponse res;
        stub.Echo(&cntl, &req, &res, nullptr);
        ASSERT_FALSE(cntl.Failed());
        ASSERT_EQ(res.message(), "m" + std::to_string(i));
    }
}

namespace {
struct AsyncDone {
    Controller cntl;
    test::EchoResponse res;
    CountdownEvent* event;
};
void HandleAsyncDone(AsyncDone* d) { d->event->signal(); }
}  // namespace

TEST(Rpc, AsyncEcho) {
    TestServer ts;
    ASSERT_TRUE(ts.start());
    Channel channel;
    ASSERT_EQ(channel.Init(ts.ep, nullptr), 0);
    test::EchoService_Stub stub(&channel);

    const int kN = 50;
    CountdownEvent ev(kN);
    std::vector<AsyncDone*> dones;
    for (int i = 0; i < kN; ++i) {
        auto* d = new AsyncDone;
        d->event = &ev;
        dones.push_back(d);
        test::EchoRequest req;
        req.set_message("async" + std::to_string(i));
        stub.Echo(&d->cntl, &req, &d->res,
                  google::protobuf::NewCallback(HandleAsyncDone, d));
    }
    ASSERT_EQ(ev.wait(), 0);
    for (int i = 0; i < kN; ++i) {
        EXPECT_FALSE(dones[i]->cntl.Failed());
        EXPECT_EQ(dones[i]->res.message(), "async" + std::to_string(i));
        delete dones[i];
    }
}

TEST(Rpc, AttachmentRoundTrip) {
    TestServer ts;
    ASSERT_TRUE(ts.start());
    Channel channel;
    ASSERT_EQ(channel.Init(ts.ep, nullptr), 0);
    test::EchoService_Stub stub(&channel);

    Controller cntl;
    std::string big(512 * 1024, 'A');
    cntl.request_attachment().append(big);
    test::EchoRequest req;
    req.set_message("with attachment");
    test::EchoResponse res;
    stub.Echo(&cntl, &req, &res, nullptr);
    ASSERT_FALSE(cntl.Failed());
    EXPECT_EQ(res.message(), "with attachment");
    EXPECT_EQ(cntl.response_attachment().size(), big.size());
    EXPECT_TRUE(cntl.response_attachment().equals(big));
}

TEST(Rpc, TimeoutFails) {
    TestServer ts;
    ASSERT_TRUE(ts.start());
    Channel channel;
    ASSERT_EQ(channel.Init(ts.ep, nullptr), 0);
    test::EchoService_Stub stub(&channel);

    Controller cntl;
    cntl.set_timeout_ms(50);
    test::EchoRequest req;
    req.set_message("slow");
    req.set_sleep_us(300 * 1000);
    test::EchoResponse res;
    const int64_t t0 = monotonic_time_us();
    stub.Echo(&cntl, &req, &res, nullptr);
    const int64_t took_ms = (monotonic_time_us() - t0) / 1000;
    EXPECT_TRUE(cntl.Failed());
    EXPECT_EQ(cntl.ErrorCode(), TERR_RPC_TIMEDOUT);
    EXPECT_LT(took_ms, 250);  // returned at the deadline, not after sleep
}

TEST(Rpc, NoSuchMethod) {
    TestServer ts;
    ASSERT_TRUE(ts.start());
    Channel channel;
    ASSERT_EQ(channel.Init(ts.ep, nullptr), 0);
    test::UnusedService_Stub stub(&channel);

    Controller cntl;
    test::EchoRequest req;
    req.set_message("x");
    test::EchoResponse res;
    stub.Nothing(&cntl, &req, &res, nullptr);
    EXPECT_TRUE(cntl.Failed());
    EXPECT_EQ(cntl.ErrorCode(), TERR_NO_METHOD);
}

TEST(Rpc, DeadServerRetriesThenFails) {
    Channel channel;
    ChannelOptions opts;
    opts.timeout_ms = 2000;
    opts.max_retry = 2;
    ASSERT_EQ(channel.Init("127.0.0.1:1", &opts), 0);  // refused
    test::EchoService_Stub stub(&channel);

    Controller cntl;
    test::EchoRequest req;
    req.set_message("doomed");
    test::EchoResponse res;
    stub.Echo(&cntl, &req, &res, nullptr);
    EXPECT_TRUE(cntl.Failed());
    EXPECT_EQ(cntl.retried_count(), 2);
}

TEST(Rpc, CallFromFiber) {
    // Sync RPC issued from a fiber worker (the common server-to-server
    // pattern) must park the fiber, not the worker thread.
    TestServer ts;
    ASSERT_TRUE(ts.start());
    Channel channel;
    ASSERT_EQ(channel.Init(ts.ep, nullptr), 0);

    struct Ctx {
        Channel* ch;
        std::atomic<int> ok{0};
    } ctx{&channel, {}};
    std::vector<fiber_t> tids(8);
    for (auto& tid : tids) {
        fiber_start_background(
            &tid, nullptr,
            [](void* arg) -> void* {
                Ctx* c = (Ctx*)arg;
                test::EchoService_Stub stub(c->ch);
                Controller cntl;
                test::EchoRequest req;
                req.set_message("from fiber");
                test::EchoResponse res;
                stub.Echo(&cntl, &req, &res, nullptr);
                if (!cntl.Failed() && res.message() == "from fiber") {
                    c->ok.fetch_add(1);
                }
                return nullptr;
            },
            &ctx);
    }
    for (auto tid : tids) fiber_join(tid, nullptr);
    EXPECT_EQ(ctx.ok.load(), 8);
}
