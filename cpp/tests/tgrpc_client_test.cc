// gRPC client over h2c (thttp/http2_client.cc): Channel with
// options.protocol="grpc" calling our own gRPC-capable h2 server in
// loopback — plus error mapping and multiplexed concurrency.
// Reference parity: client half of src/brpc/policy/http2_rpc_protocol.cpp.
#include <atomic>
#include <string>
#include <vector>

#include "echo.pb.h"
#include "tbase/endpoint.h"
#include "tfiber/fiber.h"
#include "tfiber/fiber_sync.h"
#include "trpc/channel.h"
#include "trpc/controller.h"
#include "trpc/server.h"
#include "ttest/ttest.h"

using namespace tpurpc;

namespace {

class GEchoImpl : public test::EchoService {
public:
    void Echo(google::protobuf::RpcController* cntl_base,
              const test::EchoRequest* request, test::EchoResponse* response,
              google::protobuf::Closure* done) override {
        if (request->sleep_us() > 0) fiber_usleep(request->sleep_us());
        if (request->fail_with() != 0) {
            static_cast<Controller*>(cntl_base)
                ->SetFailed(request->fail_with(), "requested failure");
        } else {
            response->set_message(request->message());
        }
        done->Run();
    }
};

struct GrpcTestServer {
    GEchoImpl service;
    Server server;
    EndPoint ep;

    bool start() {
        if (server.AddService(&service) != 0) return false;
        EndPoint listen;
        str2endpoint("127.0.0.1:0", &listen);
        if (server.Start(listen, nullptr) != 0) return false;
        str2endpoint("127.0.0.1", server.listened_port(), &ep);
        return true;
    }
};

ChannelOptions grpc_options() {
    ChannelOptions opts;
    opts.protocol = "grpc";
    opts.timeout_ms = 10000;
    return opts;
}

}  // namespace

TEST(GrpcClient, UnaryEchoLoopback) {
    GrpcTestServer ts;
    ASSERT_TRUE(ts.start());
    Channel ch;
    ChannelOptions opts = grpc_options();
    ASSERT_EQ(0, ch.Init(ts.ep, &opts));
    test::EchoService_Stub stub(&ch);
    Controller cntl;
    test::EchoRequest req;
    req.set_message("grpc over h2c");
    test::EchoResponse res;
    stub.Echo(&cntl, &req, &res, nullptr);
    ASSERT_FALSE(cntl.Failed());
    EXPECT_EQ(res.message(), "grpc over h2c");
}

TEST(GrpcClient, SequentialCallsReuseConnection) {
    GrpcTestServer ts;
    ASSERT_TRUE(ts.start());
    Channel ch;
    ChannelOptions opts = grpc_options();
    ASSERT_EQ(0, ch.Init(ts.ep, &opts));
    test::EchoService_Stub stub(&ch);
    for (int i = 0; i < 50; ++i) {
        Controller cntl;
        test::EchoRequest req;
        req.set_message("m" + std::to_string(i));
        test::EchoResponse res;
        stub.Echo(&cntl, &req, &res, nullptr);
        ASSERT_FALSE(cntl.Failed());
        ASSERT_EQ(res.message(), "m" + std::to_string(i));
    }
    // One h2 connection multiplexed all 50 streams.
    EXPECT_EQ(ts.server.acceptor()->accepted_count(), 1);
}

TEST(GrpcClient, ConcurrentMultiplexedStreams) {
    GrpcTestServer ts;
    ASSERT_TRUE(ts.start());
    Channel ch;
    ChannelOptions opts = grpc_options();
    ASSERT_EQ(0, ch.Init(ts.ep, &opts));
    struct Ctx {
        Channel* ch;
        std::atomic<int> ok{0};
        std::atomic<int> failed{0};
    } ctx{&ch, {}, {}};
    std::vector<fiber_t> tids(24);
    for (size_t i = 0; i < tids.size(); ++i) {
        fiber_start_background(
            &tids[i], nullptr,
            [](void* arg) -> void* {
                Ctx* c = (Ctx*)arg;
                test::EchoService_Stub stub(c->ch);
                Controller cntl;
                test::EchoRequest req;
                req.set_message("concurrent");
                req.set_sleep_us(2000);  // overlap the streams
                test::EchoResponse res;
                stub.Echo(&cntl, &req, &res, nullptr);
                if (!cntl.Failed() && res.message() == "concurrent") {
                    c->ok.fetch_add(1);
                } else {
                    c->failed.fetch_add(1);
                }
                return nullptr;
            },
            &ctx);
    }
    for (auto tid : tids) fiber_join(tid, nullptr);
    EXPECT_EQ(ctx.ok.load(), 24);
    EXPECT_EQ(ctx.failed.load(), 0);
}

TEST(GrpcClient, ServerErrorMapsToFailedRpc) {
    GrpcTestServer ts;
    ASSERT_TRUE(ts.start());
    Channel ch;
    ChannelOptions opts = grpc_options();
    opts.max_retry = 0;  // app errors must not burn retries
    ASSERT_EQ(0, ch.Init(ts.ep, &opts));
    test::EchoService_Stub stub(&ch);
    Controller cntl;
    test::EchoRequest req;
    req.set_message("x");
    req.set_fail_with(42);
    test::EchoResponse res;
    stub.Echo(&cntl, &req, &res, nullptr);
    EXPECT_TRUE(cntl.Failed());
}

TEST(GrpcClient, LargeResponseFlowControl) {
    // >64KB response exceeds the initial stream window: the server parks
    // on our WINDOW_UPDATEs; the client must replenish and reassemble.
    GrpcTestServer ts;
    ASSERT_TRUE(ts.start());
    Channel ch;
    ChannelOptions opts = grpc_options();
    ASSERT_EQ(0, ch.Init(ts.ep, &opts));
    test::EchoService_Stub stub(&ch);
    Controller cntl;
    test::EchoRequest req;
    req.set_message(std::string(300 * 1024, 'x'));
    test::EchoResponse res;
    stub.Echo(&cntl, &req, &res, nullptr);
    ASSERT_FALSE(cntl.Failed());
    EXPECT_EQ(res.message().size(), 300u * 1024);
}

TEST(GrpcClient, ReconnectsAfterServerRestart) {
    // The channel owns its pinned h2 connection: when the server goes
    // away (connection dies / GOAWAY), the next call must recreate the
    // pin and succeed against the restarted server on the SAME port.
    GrpcTestServer* ts = new GrpcTestServer;
    ASSERT_TRUE(ts->start());
    const int port = ts->server.listened_port();
    Channel ch;
    ChannelOptions opts = grpc_options();
    opts.timeout_ms = 3000;
    ASSERT_EQ(0, ch.Init(ts->ep, &opts));
    test::EchoService_Stub stub(&ch);
    {
        Controller cntl;
        test::EchoRequest req;
        req.set_message("before");
        test::EchoResponse res;
        stub.Echo(&cntl, &req, &res, nullptr);
        ASSERT_FALSE(cntl.Failed());
    }
    delete ts;  // Stop+Join: the connection dies
    // Restart on the same port.
    GEchoImpl service2;
    Server server2;
    ASSERT_EQ(0, server2.AddService(&service2));
    EndPoint listen;
    str2endpoint("127.0.0.1", port, &listen);
    ASSERT_EQ(0, server2.Start(listen, nullptr));
    // The first call may land on the dying connection (failure is
    // acceptable); within a couple of tries the recreated pin connects.
    bool ok = false;
    for (int i = 0; i < 5 && !ok; ++i) {
        Controller cntl;
        test::EchoRequest req;
        req.set_message("after");
        test::EchoResponse res;
        stub.Echo(&cntl, &req, &res, nullptr);
        ok = !cntl.Failed() && res.message() == "after";
        if (!ok) fiber_usleep(100 * 1000);
    }
    EXPECT_TRUE(ok);
}
