// gRPC client over h2c (thttp/http2_client.cc): Channel with
// options.protocol="grpc" calling our own gRPC-capable h2 server in
// loopback — plus error mapping and multiplexed concurrency.
// Reference parity: client half of src/brpc/policy/http2_rpc_protocol.cpp.
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <string>
#include <thread>
#include <vector>

#include "echo.pb.h"
#include "tbase/endpoint.h"
#include "tbase/time.h"
#include "tfiber/fiber.h"
#include "tfiber/fiber_sync.h"
#include "thttp/h2_frames.h"
#include "thttp/hpack.h"
#include "trpc/channel.h"
#include "trpc/controller.h"
#include "trpc/server.h"
#include "ttest/ttest.h"

using namespace tpurpc;

namespace {

class GEchoImpl : public test::EchoService {
public:
    void Echo(google::protobuf::RpcController* cntl_base,
              const test::EchoRequest* request, test::EchoResponse* response,
              google::protobuf::Closure* done) override {
        if (request->sleep_us() > 0) fiber_usleep(request->sleep_us());
        if (request->fail_with() != 0) {
            static_cast<Controller*>(cntl_base)
                ->SetFailed(request->fail_with(), "requested failure");
        } else {
            response->set_message(request->message());
        }
        done->Run();
    }
};

struct GrpcTestServer {
    GEchoImpl service;
    Server server;
    EndPoint ep;

    bool start() {
        if (server.AddService(&service) != 0) return false;
        EndPoint listen;
        str2endpoint("127.0.0.1:0", &listen);
        if (server.Start(listen, nullptr) != 0) return false;
        str2endpoint("127.0.0.1", server.listened_port(), &ep);
        return true;
    }
};

ChannelOptions grpc_options() {
    ChannelOptions opts;
    opts.protocol = "grpc";
    opts.timeout_ms = 10000;
    return opts;
}

}  // namespace

TEST(GrpcClient, UnaryEchoLoopback) {
    GrpcTestServer ts;
    ASSERT_TRUE(ts.start());
    Channel ch;
    ChannelOptions opts = grpc_options();
    ASSERT_EQ(0, ch.Init(ts.ep, &opts));
    test::EchoService_Stub stub(&ch);
    Controller cntl;
    test::EchoRequest req;
    req.set_message("grpc over h2c");
    test::EchoResponse res;
    stub.Echo(&cntl, &req, &res, nullptr);
    ASSERT_FALSE(cntl.Failed());
    EXPECT_EQ(res.message(), "grpc over h2c");
}

TEST(GrpcClient, SequentialCallsReuseConnection) {
    GrpcTestServer ts;
    ASSERT_TRUE(ts.start());
    Channel ch;
    ChannelOptions opts = grpc_options();
    ASSERT_EQ(0, ch.Init(ts.ep, &opts));
    test::EchoService_Stub stub(&ch);
    for (int i = 0; i < 50; ++i) {
        Controller cntl;
        test::EchoRequest req;
        req.set_message("m" + std::to_string(i));
        test::EchoResponse res;
        stub.Echo(&cntl, &req, &res, nullptr);
        ASSERT_FALSE(cntl.Failed());
        ASSERT_EQ(res.message(), "m" + std::to_string(i));
    }
    // One h2 connection multiplexed all 50 streams.
    EXPECT_EQ(ts.server.acceptor()->accepted_count(), 1);
}

TEST(GrpcClient, ConcurrentMultiplexedStreams) {
    GrpcTestServer ts;
    ASSERT_TRUE(ts.start());
    Channel ch;
    ChannelOptions opts = grpc_options();
    ASSERT_EQ(0, ch.Init(ts.ep, &opts));
    struct Ctx {
        Channel* ch;
        std::atomic<int> ok{0};
        std::atomic<int> failed{0};
    } ctx{&ch, {}, {}};
    std::vector<fiber_t> tids(24);
    for (size_t i = 0; i < tids.size(); ++i) {
        fiber_start_background(
            &tids[i], nullptr,
            [](void* arg) -> void* {
                Ctx* c = (Ctx*)arg;
                test::EchoService_Stub stub(c->ch);
                Controller cntl;
                test::EchoRequest req;
                req.set_message("concurrent");
                req.set_sleep_us(2000);  // overlap the streams
                test::EchoResponse res;
                stub.Echo(&cntl, &req, &res, nullptr);
                if (!cntl.Failed() && res.message() == "concurrent") {
                    c->ok.fetch_add(1);
                } else {
                    c->failed.fetch_add(1);
                }
                return nullptr;
            },
            &ctx);
    }
    for (auto tid : tids) fiber_join(tid, nullptr);
    EXPECT_EQ(ctx.ok.load(), 24);
    EXPECT_EQ(ctx.failed.load(), 0);
}

TEST(GrpcClient, ServerErrorMapsToFailedRpc) {
    GrpcTestServer ts;
    ASSERT_TRUE(ts.start());
    Channel ch;
    ChannelOptions opts = grpc_options();
    opts.max_retry = 0;  // app errors must not burn retries
    ASSERT_EQ(0, ch.Init(ts.ep, &opts));
    test::EchoService_Stub stub(&ch);
    Controller cntl;
    test::EchoRequest req;
    req.set_message("x");
    req.set_fail_with(42);
    test::EchoResponse res;
    stub.Echo(&cntl, &req, &res, nullptr);
    EXPECT_TRUE(cntl.Failed());
}

TEST(GrpcClient, LargeResponseFlowControl) {
    // >64KB response exceeds the initial stream window: the server parks
    // on our WINDOW_UPDATEs; the client must replenish and reassemble.
    GrpcTestServer ts;
    ASSERT_TRUE(ts.start());
    Channel ch;
    ChannelOptions opts = grpc_options();
    ASSERT_EQ(0, ch.Init(ts.ep, &opts));
    test::EchoService_Stub stub(&ch);
    Controller cntl;
    test::EchoRequest req;
    req.set_message(std::string(300 * 1024, 'x'));
    test::EchoResponse res;
    stub.Echo(&cntl, &req, &res, nullptr);
    ASSERT_FALSE(cntl.Failed());
    EXPECT_EQ(res.message().size(), 300u * 1024);
}

TEST(GrpcClient, EarlyTrailersOnlyResponseDoesNotStallInputFiber) {
    // Regression for the h2-client input-fiber deadlock: a sender parked
    // on flow control (>64KB request vs the default 65535 window) HOLDS
    // the CallId lock; an early trailers-only response used to complete
    // the stream INLINE on the in-order input fiber, which then blocked
    // in id_lock_range — wedging frame processing (including the very
    // WINDOW_UPDATEs that would unpark the sender) until the sender's
    // 1s flow-control tick rescued it. Fixed: completion runs on a
    // background fiber; the input fiber keeps processing, so the whole
    // RPC resolves as soon as the server answers (~the 300ms scripted
    // delay below), not after a ≥1s stall.
    const int lfd = ::socket(AF_INET, SOCK_STREAM, 0);
    ASSERT_GE(lfd, 0);
    int one = 1;
    setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr;
    memset(&addr, 0, sizeof(addr));
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    ASSERT_EQ(0, ::bind(lfd, (sockaddr*)&addr, sizeof(addr)));
    ASSERT_EQ(0, ::listen(lfd, 1));
    socklen_t alen = sizeof(addr);
    ASSERT_EQ(0, getsockname(lfd, (sockaddr*)&addr, &alen));
    const int port = ntohs(addr.sin_port);

    // Scripted raw h2 server: drain the request burst, then answer
    // stream 1 with trailers-only (grpc-status 8) and open the windows.
    std::thread raw_server([lfd] {
        const int cfd = ::accept(lfd, nullptr, nullptr);
        if (cfd < 0) return;
        int oone = 1;
        setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &oone, sizeof(oone));
        auto drain_for = [cfd](int ms) {
            const int64_t end = tpurpc::monotonic_time_us() + ms * 1000ll;
            char buf[16384];
            while (tpurpc::monotonic_time_us() < end) {
                pollfd p{cfd, POLLIN, 0};
                if (::poll(&p, 1, 20) == 1) {
                    if (::recv(cfd, buf, sizeof(buf), 0) == 0) return false;
                }
            }
            return true;
        };
        if (!drain_for(300)) {  // client parks after ~64KB of DATA
            close(cfd);
            return;
        }
        using namespace tpurpc::h2;
        std::string out = BuildFrame(H2_SETTINGS, 0, 0, "");
        AppendHeadersFrames(
            &out, kFlagEndHeaders | kFlagEndStream, 1,
            EncodeHeaderBlock({{":status", "200"},
                               {"content-type", "application/grpc"},
                               {"grpc-status", "8"},
                               {"grpc-message", "early-trailers"}}));
        // Windows the parked sender is waiting for: processing them is
        // exactly what a blocked input fiber could not do.
        uint32_t inc = htonl(1u << 20);
        const std::string p((const char*)&inc, 4);
        out += BuildFrame(H2_WINDOW_UPDATE, 0, 0, p);
        out += BuildFrame(H2_WINDOW_UPDATE, 0, 1, p);
        (void)!send(cfd, out.data(), out.size(), MSG_NOSIGNAL);
        drain_for(3000);  // absorb whatever the client still sends
        close(cfd);
    });

    Channel ch;
    ChannelOptions opts = grpc_options();
    opts.timeout_ms = 5000;
    opts.max_retry = 0;  // a re-issued try would park on the window again
    EndPoint ep;
    str2endpoint("127.0.0.1", port, &ep);
    ASSERT_EQ(0, ch.Init(ep, &opts));
    test::EchoService_Stub stub(&ch);
    Controller cntl;
    test::EchoRequest req;
    req.set_message(std::string(300 * 1024, 'x'));  // >64KB: parks
    test::EchoResponse res;
    const int64_t t0 = monotonic_time_us();
    stub.Echo(&cntl, &req, &res, nullptr);
    const int64_t elapsed_ms = (monotonic_time_us() - t0) / 1000;
    EXPECT_TRUE(cntl.Failed());
    // Unfixed, the input fiber wedges until the sender's 1s rescue tick
    // (and compounding retries could ride it to the full deadline).
    EXPECT_LT(elapsed_ms, 800);
    raw_server.join();
    close(lfd);
}

TEST(GrpcClient, ReconnectsAfterServerRestart) {
    // The channel owns its pinned h2 connection: when the server goes
    // away (connection dies / GOAWAY), the next call must recreate the
    // pin and succeed against the restarted server on the SAME port.
    GrpcTestServer* ts = new GrpcTestServer;
    ASSERT_TRUE(ts->start());
    const int port = ts->server.listened_port();
    Channel ch;
    ChannelOptions opts = grpc_options();
    opts.timeout_ms = 3000;
    ASSERT_EQ(0, ch.Init(ts->ep, &opts));
    test::EchoService_Stub stub(&ch);
    {
        Controller cntl;
        test::EchoRequest req;
        req.set_message("before");
        test::EchoResponse res;
        stub.Echo(&cntl, &req, &res, nullptr);
        ASSERT_FALSE(cntl.Failed());
    }
    delete ts;  // Stop+Join: the connection dies
    // Restart on the same port.
    GEchoImpl service2;
    Server server2;
    ASSERT_EQ(0, server2.AddService(&service2));
    EndPoint listen;
    str2endpoint("127.0.0.1", port, &listen);
    ASSERT_EQ(0, server2.Start(listen, nullptr));
    // The first call may land on the dying connection (failure is
    // acceptable); within a couple of tries the recreated pin connects.
    bool ok = false;
    for (int i = 0; i < 5 && !ok; ++i) {
        Controller cntl;
        test::EchoRequest req;
        req.set_message("after");
        test::EchoResponse res;
        stub.Echo(&cntl, &req, &res, nullptr);
        ok = !cntl.Failed() && res.message() == "after";
        if (!ok) fiber_usleep(100 * 1000);
    }
    EXPECT_TRUE(ok);
}
