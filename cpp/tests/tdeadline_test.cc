// Deadline propagation, cancellation cascade, and retry budgets
// (ISSUE 2): expired-on-arrival shedding, budget-aware admission,
// hop-to-hop deadline inheritance, NotifyOnCancel exactly-once,
// client->server cancel + downstream cascade, retry/backup throttling,
// and the backoff-vs-deadline guards.
#include <arpa/inet.h>
#include <netinet/in.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <climits>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <functional>
#include <string>
#include <thread>

#include "echo.pb.h"
#include "rpc_meta.pb.h"
#include "tbase/errno.h"
#include "tbase/time.h"
#include "thttp/h2_frames.h"
#include "thttp/hpack.h"
#include "tfiber/fiber.h"
#include "tfiber/fiber_sync.h"
#include "trpc/channel.h"
#include "trpc/controller.h"
#include "trpc/pb_compat.h"
#include "trpc/policy_tpu_std.h"
#include "trpc/retry_policy.h"
#include "trpc/server.h"
#include "ttest/ttest.h"
#include "tvar/variable.h"

using namespace tpurpc;

namespace {

// Current value of a counter tvar (0 when not yet exposed); tests always
// compare deltas — the registry is process-global across the suite.
int64_t VarValue(const char* name) {
    std::string desc;
    if (!Variable::describe_exposed(name, &desc)) return 0;
    return strtoll(desc.c_str(), nullptr, 10);
}

class EchoImpl : public test::EchoService {
public:
    void Echo(google::protobuf::RpcController* cntl_base,
              const test::EchoRequest* request, test::EchoResponse* response,
              google::protobuf::Closure* done) override {
        Controller* cntl = static_cast<Controller*>(cntl_base);
        if (request->sleep_us() > 0) {
            fiber_usleep(request->sleep_us());
        }
        response->set_message(request->message());
        (void)cntl;
        ncalls.fetch_add(1, std::memory_order_relaxed);
        done->Run();
    }
    std::atomic<int> ncalls{0};
};

struct DeadlineServer {
    EchoImpl service;
    Server server;
    EndPoint ep;

    bool start(const ServerOptions* opts = nullptr) {
        if (server.AddService(&service) != 0) return false;
        EndPoint listen;
        str2endpoint("127.0.0.1:0", &listen);
        if (server.Start(listen, opts) != 0) return false;
        str2endpoint("127.0.0.1", server.listened_port(), &ep);
        return true;
    }
};

struct CountClosure : google::protobuf::Closure {
    explicit CountClosure(std::atomic<int>* n) : n_(n) {}
    void Run() override { n_->fetch_add(1, std::memory_order_relaxed); }
    std::atomic<int>* n_;
};

struct SignalDone : google::protobuf::Closure {
    CountdownEvent ev{1};
    void Run() override { ev.signal(); }
};

}  // namespace

// ---------------- retry budget ----------------

TEST(RetryBudget, TokenBucketSemantics) {
    RetryBudget b;
    b.Configure(2, 0.5);
    EXPECT_TRUE(b.enabled());
    EXPECT_TRUE(b.Withdraw());
    EXPECT_TRUE(b.Withdraw());
    EXPECT_FALSE(b.Withdraw());  // burst of 2 spent
    b.OnSuccess();               // +0.5: still below a whole token
    EXPECT_FALSE(b.Withdraw());
    b.OnSuccess();  // +0.5: one whole token available
    EXPECT_TRUE(b.Withdraw());
    // Refill is clamped at the burst cap.
    for (int i = 0; i < 100; ++i) b.OnSuccess();
    EXPECT_EQ(b.tokens(), 2);
    // tokens <= 0 disables throttling entirely.
    RetryBudget off;
    off.Configure(0, 0.1);
    EXPECT_FALSE(off.enabled());
    for (int i = 0; i < 10; ++i) EXPECT_TRUE(off.Withdraw());
}

TEST(RetryBudget, ExhaustionStopsRetries) {
    // Dead port: every try fails with ECONNREFUSED (retryable). The
    // budget, not max_retry, is the binding constraint.
    Channel channel;
    ChannelOptions opts;
    opts.timeout_ms = 2000;
    opts.max_retry = 5;
    opts.retry_budget_tokens = 2;
    opts.retry_budget_ratio = 0.0;
    ASSERT_EQ(channel.Init("127.0.0.1:1", &opts), 0);
    test::EchoService_Stub stub(&channel);

    const int64_t exhausted_before = VarValue("rpc_retry_budget_exhausted");
    {
        Controller cntl;
        test::EchoRequest req;
        req.set_message("doomed");
        test::EchoResponse res;
        stub.Echo(&cntl, &req, &res, nullptr);
        EXPECT_TRUE(cntl.Failed());
        EXPECT_EQ(cntl.retried_count(), 2);  // 2 tokens, not max_retry=5
    }
    {
        Controller cntl;
        test::EchoRequest req;
        req.set_message("doomed2");
        test::EchoResponse res;
        stub.Echo(&cntl, &req, &res, nullptr);
        EXPECT_TRUE(cntl.Failed());
        EXPECT_EQ(cntl.retried_count(), 0);  // bucket dry: no re-issues
    }
    EXPECT_GE(VarValue("rpc_retry_budget_exhausted") - exhausted_before, 2);
}

namespace {
// Sleeps when armed (and disarms): the original call hangs, the backup
// (or the next call) completes fast — the existing backup-request shape.
class SleepWhenArmedImpl : public test::EchoService {
public:
    void Echo(google::protobuf::RpcController*, const test::EchoRequest* req,
              test::EchoResponse* res,
              google::protobuf::Closure* done) override {
        ncalls.fetch_add(1, std::memory_order_relaxed);
        if (sleep_next.exchange(false, std::memory_order_acq_rel)) {
            fiber_usleep(300 * 1000);
        }
        res->set_message(req->message());
        done->Run();
    }
    std::atomic<bool> sleep_next{false};
    std::atomic<int> ncalls{0};
};
}  // namespace

TEST(RetryBudget, ExhaustionVetoesBackupRequests) {
    SleepWhenArmedImpl service;
    Server server;
    ASSERT_EQ(server.AddService(&service), 0);
    EndPoint listen;
    str2endpoint("127.0.0.1:0", &listen);
    ASSERT_EQ(server.Start(listen, nullptr), 0);
    EndPoint ep;
    str2endpoint("127.0.0.1", server.listened_port(), &ep);

    Channel channel;
    ChannelOptions opts;
    opts.timeout_ms = 2000;
    opts.max_retry = 1;
    opts.retry_budget_tokens = 1;  // exactly one re-issue in the bucket
    opts.retry_budget_ratio = 0.0;
    ASSERT_EQ(channel.Init(ep, &opts), 0);
    test::EchoService_Stub stub(&channel);

    // Call 1: original sleeps 300ms, the backup (token 1) wins fast.
    service.sleep_next.store(true, std::memory_order_release);
    {
        Controller cntl;
        cntl.set_backup_request_ms(20);
        test::EchoRequest req;
        req.set_message("hedged");
        test::EchoResponse res;
        stub.Echo(&cntl, &req, &res, nullptr);
        ASSERT_FALSE(cntl.Failed());
        EXPECT_LT(cntl.latency_us(), 250 * 1000);
    }
    // Call 2: bucket dry — the backup is vetoed, we pay the full sleep.
    service.sleep_next.store(true, std::memory_order_release);
    {
        Controller cntl;
        cntl.set_backup_request_ms(20);
        test::EchoRequest req;
        req.set_message("unhedged");
        test::EchoResponse res;
        stub.Echo(&cntl, &req, &res, nullptr);
        ASSERT_FALSE(cntl.Failed());
        EXPECT_GE(cntl.latency_us(), 280 * 1000);
    }
    EXPECT_EQ(service.ncalls.load(), 3);  // 2 originals + 1 backup only
}

// ---------------- backoff vs deadline (satellite) ----------------

TEST(Backoff, CrossingDeadlineIssuesImmediately) {
    // A backoff that would overshoot the deadline is skipped: the retry
    // goes out NOW (reference DoRetryWithBackoff guard). A dead port
    // fails each try instantly, so the whole call finishes far inside
    // the deadline despite a 5s nominal backoff.
    RetryPolicyWithFixedBackoff policy(5000);
    Channel channel;
    ChannelOptions opts;
    opts.timeout_ms = 800;
    opts.max_retry = 2;
    opts.retry_policy = &policy;
    ASSERT_EQ(channel.Init("127.0.0.1:1", &opts), 0);
    test::EchoService_Stub stub(&channel);

    Controller cntl;
    test::EchoRequest req;
    req.set_message("x");
    test::EchoResponse res;
    const int64_t t0 = monotonic_time_us();
    stub.Echo(&cntl, &req, &res, nullptr);
    const int64_t elapsed_us = monotonic_time_us() - t0;
    EXPECT_TRUE(cntl.Failed());
    EXPECT_NE(cntl.ErrorCode(), TERR_RPC_TIMEDOUT);  // refused, not hung
    EXPECT_EQ(cntl.retried_count(), 2);
    EXPECT_LT(elapsed_us, 600 * 1000);  // never waited a 5s backoff
}

TEST(Backoff, HonoredWhenItFitsTheDeadline) {
    // A backoff that fits really is waited out (regression guard for the
    // HandleBackoffThunk timer path).
    RetryPolicyWithFixedBackoff policy(80);
    Channel channel;
    ChannelOptions opts;
    opts.timeout_ms = 2000;
    opts.max_retry = 1;
    opts.retry_policy = &policy;
    ASSERT_EQ(channel.Init("127.0.0.1:1", &opts), 0);
    test::EchoService_Stub stub(&channel);

    Controller cntl;
    test::EchoRequest req;
    req.set_message("x");
    test::EchoResponse res;
    const int64_t t0 = monotonic_time_us();
    stub.Echo(&cntl, &req, &res, nullptr);
    const int64_t elapsed_us = monotonic_time_us() - t0;
    EXPECT_TRUE(cntl.Failed());
    EXPECT_EQ(cntl.retried_count(), 1);
    EXPECT_GE(elapsed_us, 75 * 1000);  // the backoff was honored
}

TEST(Backoff, DeadlineWinsOverHangingRetry) {
    // First connection is closed instantly (EOF -> retryable), the retry
    // waits out its backoff, reconnects — and then the server goes
    // silent. The armed deadline timer must fail the RPC with
    // TERR_RPC_TIMEDOUT on time; the hazard is hanging forever on a try
    // issued from the backoff path.
    const int listener = ::socket(AF_INET, SOCK_STREAM, 0);
    ASSERT_GE(listener, 0);
    sockaddr_in addr;
    memset(&addr, 0, sizeof(addr));
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    addr.sin_port = 0;
    ASSERT_EQ(::bind(listener, (sockaddr*)&addr, sizeof(addr)), 0);
    ASSERT_EQ(::listen(listener, 8), 0);
    socklen_t alen = sizeof(addr);
    ASSERT_EQ(::getsockname(listener, (sockaddr*)&addr, &alen), 0);
    const int port = ntohs(addr.sin_port);

    std::atomic<bool> stop{false};
    std::atomic<int> naccepts{0};
    // Plain pthread acceptor: close the first connection, hold the rest
    // open silently (blackhole).
    std::thread acceptor([&] {
        int held[8];
        int nheld = 0;
        while (!stop.load(std::memory_order_acquire)) {
            pollfd pfd{listener, POLLIN, 0};
            if (::poll(&pfd, 1, 50) != 1) continue;
            const int fd = ::accept(listener, nullptr, nullptr);
            if (fd < 0) continue;
            if (naccepts.fetch_add(1, std::memory_order_relaxed) == 0) {
                ::close(fd);  // EOF: the client's first try dies fast
            } else if (nheld < 8) {
                held[nheld++] = fd;  // blackhole: never respond
            } else {
                ::close(fd);
            }
        }
        for (int i = 0; i < nheld; ++i) ::close(held[i]);
    });

    RetryPolicyWithFixedBackoff policy(100);
    Channel channel;
    ChannelOptions opts;
    opts.timeout_ms = 400;
    opts.max_retry = 3;
    opts.retry_policy = &policy;
    char addr_str[32];
    snprintf(addr_str, sizeof(addr_str), "127.0.0.1:%d", port);
    ASSERT_EQ(channel.Init(addr_str, &opts), 0);
    test::EchoService_Stub stub(&channel);

    Controller cntl;
    test::EchoRequest req;
    req.set_message("x");
    test::EchoResponse res;
    const int64_t t0 = monotonic_time_us();
    stub.Echo(&cntl, &req, &res, nullptr);
    const int64_t elapsed_us = monotonic_time_us() - t0;
    EXPECT_TRUE(cntl.Failed());
    EXPECT_EQ(cntl.ErrorCode(), TERR_RPC_TIMEDOUT);
    // Not hung: finished within ~the deadline (+ scheduling slack).
    EXPECT_LT(elapsed_us, 2000 * 1000);
    EXPECT_GE(elapsed_us, 350 * 1000);

    stop.store(true, std::memory_order_release);
    acceptor.join();
    ::close(listener);
}

TEST(Backoff, OverloadSuggestionCappedByDeadline) {
    // ISSUE 15 satellite regression: a server-suggested TERR_OVERLOAD
    // backoff LARGER than the remaining deadline used to fall through
    // the overshoot guard and re-issue IMMEDIATELY — hammering the
    // server that just said "not now" and burning every retry within
    // milliseconds. The jittered hint must instead be CAPPED by the
    // remaining budget: the client waits out the useful fraction of
    // its deadline between tries.
    DeadlineServer ds;
    ASSERT_TRUE(ds.start());
    TenantQuota q;
    q.qps = 0.5;  // one token every 2s: the refill-derived backoff hint
    q.burst = 1;  // (~2000ms) always dwarfs the 400ms deadline below
    ds.server.SetTenantQuota("default", q);

    Channel channel;
    ChannelOptions opts;
    opts.timeout_ms = 400;
    opts.max_retry = 3;
    ASSERT_EQ(channel.Init(ds.ep, &opts), 0);
    test::EchoService_Stub stub(&channel);
    {
        // Burn the single token so the measured call is always shed.
        Controller warm;
        test::EchoRequest req;
        req.set_message("warm");
        test::EchoResponse res;
        stub.Echo(&warm, &req, &res, nullptr);
        ASSERT_FALSE(warm.Failed());
    }
    Controller cntl;
    test::EchoRequest req;
    req.set_message("x");
    test::EchoResponse res;
    const int64_t t0 = monotonic_time_us();
    stub.Echo(&cntl, &req, &res, nullptr);
    const int64_t elapsed_us = monotonic_time_us() - t0;
    EXPECT_TRUE(cntl.Failed());
    EXPECT_GE(cntl.retried_count(), 1);
    // The clamped backoff was really waited out (the old
    // immediate-reissue path finished in a few milliseconds)...
    EXPECT_GE(elapsed_us, 130 * 1000);
    // ...but the call never slept past its deadline and died on time.
    EXPECT_LT(elapsed_us, 1200 * 1000);
    ds.server.Stop();
    ds.server.Join();
}

// ---------------- server-side deadline ----------------

TEST(Deadline, ExpiredOnArrivalIsShedBeforeHandler) {
    DeadlineServer ts;
    ASSERT_TRUE(ts.start());

    // Hand-craft a request whose propagated remaining budget is already
    // <= 0 (a real client only produces this when it has given up — the
    // wire shape of an expired-on-arrival request).
    rpc::RpcMeta meta;
    auto* rmeta = meta.mutable_request();
    rmeta->set_service_name("test.EchoService");
    rmeta->set_method_name("Echo");
    rmeta->set_timeout_ms(0);
    meta.set_correlation_id(12345);
    test::EchoRequest req;
    req.set_message("expired");
    IOBuf meta_buf, payload;
    ASSERT_TRUE(SerializePbToIOBuf(meta, &meta_buf));
    ASSERT_TRUE(SerializePbToIOBuf(req, &payload));
    IOBuf frame;
    PackTpuStdFrame(&frame, meta_buf, payload, IOBuf());
    const std::string wire = frame.to_string();

    const int64_t expired_before = VarValue("rpc_server_expired_requests");

    const int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    ASSERT_GE(fd, 0);
    sockaddr_in addr;
    endpoint2sockaddr(ts.ep, &addr);
    ASSERT_EQ(::connect(fd, (sockaddr*)&addr, sizeof(addr)), 0);
    ASSERT_EQ((ssize_t)wire.size(),
              ::send(fd, wire.data(), wire.size(), 0));
    // Read the error response frame: 12-byte header + body.
    std::string in;
    char buf[4096];
    while (in.size() < 12) {
        const ssize_t r = ::recv(fd, buf, sizeof(buf), 0);
        ASSERT_GT(r, 0);
        in.append(buf, (size_t)r);
    }
    uint32_t body_size = 0, meta_size = 0;
    memcpy(&body_size, in.data() + 4, 4);
    memcpy(&meta_size, in.data() + 8, 4);
    body_size = ntohl(body_size);
    meta_size = ntohl(meta_size);
    while (in.size() < 12 + body_size) {
        const ssize_t r = ::recv(fd, buf, sizeof(buf), 0);
        ASSERT_GT(r, 0);
        in.append(buf, (size_t)r);
    }
    ::close(fd);
    rpc::RpcMeta res_meta;
    IOBuf res_meta_buf;
    res_meta_buf.append(in.substr(12, meta_size));
    ASSERT_TRUE(ParsePbFromIOBuf(&res_meta, res_meta_buf));
    EXPECT_EQ(res_meta.response().error_code(), TERR_RPC_TIMEDOUT);
    // The handler never ran and the shed is observable in /vars.
    EXPECT_EQ(ts.service.ncalls.load(), 0);
    EXPECT_GE(VarValue("rpc_server_expired_requests") - expired_before, 1);
}

TEST(Deadline, BudgetBelowServiceTimeIsShedAtAdmission) {
    // TimeoutConcurrencyLimiter integration: once the EMA has learned
    // the ~30ms service time, a request arriving with an 8ms budget is
    // rejected before it costs a handler.
    ServerOptions sopts;
    sopts.timeout_concurrency = true;
    sopts.timeout_cl_options.timeout_ms = 500;
    DeadlineServer ts;
    ASSERT_TRUE(ts.start(&sopts));
    Channel channel;
    ChannelOptions opts;
    opts.timeout_ms = 1000;
    ASSERT_EQ(channel.Init(ts.ep, &opts), 0);
    test::EchoService_Stub stub(&channel);

    for (int i = 0; i < 3; ++i) {  // teach the EMA the service time
        Controller cntl;
        test::EchoRequest req;
        req.set_message("warm");
        req.set_sleep_us(30 * 1000);
        test::EchoResponse res;
        stub.Echo(&cntl, &req, &res, nullptr);
        ASSERT_FALSE(cntl.Failed());
    }
    const int ncalls_before = ts.service.ncalls.load();
    const int64_t shed_before = VarValue("rpc_server_shed_requests");

    Controller cntl;
    cntl.set_timeout_ms(8);  // well below the learned ~30ms
    test::EchoRequest req;
    req.set_message("doomed");
    req.set_sleep_us(30 * 1000);
    test::EchoResponse res;
    const int64_t t0 = monotonic_time_us();
    stub.Echo(&cntl, &req, &res, nullptr);
    const int64_t elapsed_us = monotonic_time_us() - t0;
    EXPECT_TRUE(cntl.Failed());
    EXPECT_LT(elapsed_us, 25 * 1000);  // shed cheaply, not executed
    EXPECT_EQ(ts.service.ncalls.load(), ncalls_before);
    EXPECT_GE(VarValue("rpc_server_shed_requests") - shed_before, 1);
}

TEST(Deadline, ExpiredOnArrivalIsShedOnH2Grpc) {
    // The gRPC/h2 analog of the tpu_std expired-shed: a stream whose
    // grpc-timeout parses to 0 is answered with grpc-status 4
    // (DEADLINE_EXCEEDED) before admission or user code.
    DeadlineServer ts;
    ASSERT_TRUE(ts.start());
    const int64_t expired_before = VarValue("rpc_server_expired_requests");

    const int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    ASSERT_GE(fd, 0);
    sockaddr_in addr;
    endpoint2sockaddr(ts.ep, &addr);
    ASSERT_EQ(::connect(fd, (sockaddr*)&addr, sizeof(addr)), 0);
    std::string out(h2::kPreface, h2::kPrefaceLen);
    out += h2::BuildFrame(h2::H2_SETTINGS, 0, 0, "");
    const std::vector<std::pair<std::string, std::string>> hdrs = {
        {":method", "POST"},
        {":scheme", "http"},
        {":path", "/test.EchoService/Echo"},
        {":authority", "t"},
        {"content-type", "application/grpc"},
        {"te", "trailers"},
        {"grpc-timeout", "0m"},
    };
    h2::AppendHeadersFrames(
        &out, (uint8_t)(h2::kFlagEndHeaders | h2::kFlagEndStream), 1,
        h2::EncodeHeaderBlock(hdrs));
    ASSERT_EQ((ssize_t)out.size(), ::send(fd, out.data(), out.size(), 0));

    // Read frames until a HEADERS block carries grpc-status (the server
    // sends response HEADERS, then trailers; one shared decoder).
    HpackDecoder dec;
    std::string grpc_status;
    std::string in;
    const int64_t read_deadline = monotonic_time_us() + 3 * 1000 * 1000;
    while (grpc_status.empty() && monotonic_time_us() < read_deadline) {
        pollfd pfd{fd, POLLIN, 0};
        if (::poll(&pfd, 1, 100) != 1) continue;
        char buf[4096];
        const ssize_t r = ::recv(fd, buf, sizeof(buf), 0);
        if (r <= 0) break;
        in.append(buf, (size_t)r);
        while (in.size() >= h2::kFrameHeaderLen) {
            const uint32_t len = ((uint32_t)(uint8_t)in[0] << 16) |
                                 ((uint32_t)(uint8_t)in[1] << 8) |
                                 (uint32_t)(uint8_t)in[2];
            if (in.size() < h2::kFrameHeaderLen + len) break;
            const uint8_t type = (uint8_t)in[3];
            if (type == h2::H2_HEADERS) {
                std::vector<HpackHeader> decoded;
                ASSERT_TRUE(dec.Decode(
                    (const uint8_t*)in.data() + h2::kFrameHeaderLen, len,
                    &decoded));
                for (const auto& h : decoded) {
                    if (h.name == "grpc-status") grpc_status = h.value;
                }
            }
            in.erase(0, h2::kFrameHeaderLen + len);
        }
    }
    ::close(fd);
    EXPECT_EQ(grpc_status, "4");  // DEADLINE_EXCEEDED
    EXPECT_EQ(ts.service.ncalls.load(), 0);
    EXPECT_GE(VarValue("rpc_server_expired_requests") - expired_before, 1);
}

// ---------------- hop-to-hop inheritance ----------------

namespace {
// Chains to itself: request message = hops left; records the remaining
// upstream budget observed at each hop. The downstream channel's own
// timeout is huge — only inheritance can cap it.
class ChainImpl : public test::EchoService {
public:
    void Echo(google::protobuf::RpcController* cntl_base,
              const test::EchoRequest* request, test::EchoResponse* response,
              google::protobuf::Closure* done) override {
        Controller* cntl = static_cast<Controller*>(cntl_base);
        const int hops = atoi(request->message().c_str());
        const int64_t remaining = cntl->remaining_server_budget_us();
        if (hops >= 0 && hops < 3) {
            budgets[hops].store(remaining == INT64_MAX ? -1 : remaining,
                                std::memory_order_relaxed);
        }
        if (hops > 0) {
            Channel ch;
            ChannelOptions opts;
            opts.timeout_ms = 10 * 1000;  // inheritance must tighten this
            if (ch.Init(self, &opts) == 0) {
                test::EchoService_Stub stub(&ch);
                Controller down;
                test::EchoRequest dreq;
                dreq.set_message(std::to_string(hops - 1));
                test::EchoResponse dres;
                stub.Echo(&down, &dreq, &dres, nullptr);
            }
        }
        response->set_message("done");
        done->Run();
    }
    std::atomic<int64_t> budgets[3] = {};
    EndPoint self;
};
}  // namespace

TEST(Deadline, ThreeHopChainInheritsRemainingBudget) {
    ChainImpl service;
    Server server;
    ASSERT_EQ(server.AddService(&service), 0);
    EndPoint listen;
    str2endpoint("127.0.0.1:0", &listen);
    ASSERT_EQ(server.Start(listen, nullptr), 0);
    str2endpoint("127.0.0.1", server.listened_port(), &service.self);

    Channel channel;
    ChannelOptions opts;
    opts.timeout_ms = 400;  // the only deadline anywhere in the chain
    ASSERT_EQ(channel.Init(service.self, &opts), 0);
    test::EchoService_Stub stub(&channel);

    Controller cntl;
    test::EchoRequest req;
    req.set_message("2");  // hops: 2 -> 1 -> 0
    test::EchoResponse res;
    stub.Echo(&cntl, &req, &res, nullptr);
    ASSERT_FALSE(cntl.Failed());

    const int64_t b2 = service.budgets[2].load();  // first hop
    const int64_t b1 = service.budgets[1].load();
    const int64_t b0 = service.budgets[0].load();  // last hop
    // Every hop saw a REAL deadline (not the 10s channel timeout), and
    // the budget only shrinks down the chain.
    ASSERT_GT(b2, 0);
    ASSERT_GT(b1, 0);
    ASSERT_GT(b0, 0);
    EXPECT_LE(b2, 400 * 1000);
    EXPECT_LE(b1, b2);
    EXPECT_LE(b0, b1);
}

// ---------------- cancellation ----------------

TEST(Cancel, NotifyOnCancelRunsExactlyOnceWithoutCancel) {
    DeadlineServer ts;
    ASSERT_TRUE(ts.start());
    Channel channel;
    ASSERT_EQ(channel.Init(ts.ep, nullptr), 0);
    test::EchoService_Stub stub(&channel);

    std::atomic<int> fired{0};
    CountClosure closure(&fired);
    Controller cntl;
    cntl.NotifyOnCancel(&closure);
    test::EchoRequest req;
    req.set_message("ok");
    test::EchoResponse res;
    stub.Echo(&cntl, &req, &res, nullptr);
    ASSERT_FALSE(cntl.Failed());
    EXPECT_EQ(fired.load(), 1);  // fired by EndRPC despite no cancel

    // And on plain destruction of a controller that never issued a call.
    std::atomic<int> fired2{0};
    CountClosure closure2(&fired2);
    {
        Controller unused;
        unused.NotifyOnCancel(&closure2);
    }
    EXPECT_EQ(fired2.load(), 1);
}

namespace {
// Parks until canceled (or 2s); reports what it observed.
class CancelWatchImpl : public test::EchoService {
public:
    void Echo(google::protobuf::RpcController* cntl_base,
              const test::EchoRequest* request, test::EchoResponse* response,
              google::protobuf::Closure* done) override {
        Controller* cntl = static_cast<Controller*>(cntl_base);
        entered.fetch_add(1, std::memory_order_release);
        for (int i = 0; i < 400; ++i) {
            if (cntl->IsCanceled()) {
                saw_cancel.fetch_add(1, std::memory_order_relaxed);
                break;
            }
            fiber_usleep(5 * 1000);
        }
        response->set_message(request->message());
        done->Run();
    }
    std::atomic<int> entered{0};
    std::atomic<int> saw_cancel{0};
};

bool WaitFor(const std::function<bool()>& pred, int64_t timeout_ms) {
    const int64_t deadline = monotonic_time_us() + timeout_ms * 1000;
    while (monotonic_time_us() < deadline) {
        if (pred()) return true;
        usleep(5 * 1000);
    }
    return pred();
}
}  // namespace

TEST(Cancel, StartCancelReachesTheServer) {
    CancelWatchImpl service;
    Server server;
    ASSERT_EQ(server.AddService(&service), 0);
    EndPoint listen;
    str2endpoint("127.0.0.1:0", &listen);
    ASSERT_EQ(server.Start(listen, nullptr), 0);
    EndPoint ep;
    str2endpoint("127.0.0.1", server.listened_port(), &ep);

    Channel channel;
    ChannelOptions opts;
    opts.timeout_ms = 5000;
    ASSERT_EQ(channel.Init(ep, &opts), 0);
    test::EchoService_Stub stub(&channel);

    std::atomic<int> notified{0};
    CountClosure closure(&notified);
    Controller cntl;
    cntl.NotifyOnCancel(&closure);
    test::EchoRequest req;
    req.set_message("cancel-me");
    test::EchoResponse res;
    SignalDone done;
    stub.Echo(&cntl, &req, &res, &done);
    ASSERT_TRUE(WaitFor([&] { return service.entered.load() >= 1; }, 2000));

    cntl.StartCancel();
    done.ev.wait();
    EXPECT_TRUE(cntl.Failed());
    EXPECT_EQ(cntl.ErrorCode(), ECANCELED);
    EXPECT_EQ(notified.load(), 1);
    // The wire CANCEL marked the server-side controller canceled, so the
    // handler bailed out of its park loop early.
    EXPECT_TRUE(WaitFor([&] { return service.saw_cancel.load() >= 1; },
                        2000));
    server.Stop();
    server.Join();
}

namespace {
// Front tier: relays to the back tier synchronously; records the
// downstream verdict so the cascade is observable.
class RelayImpl : public test::EchoService {
public:
    void Echo(google::protobuf::RpcController*, const test::EchoRequest* req,
              test::EchoResponse* res,
              google::protobuf::Closure* done) override {
        Channel ch;
        ChannelOptions opts;
        opts.timeout_ms = 5000;
        if (ch.Init(backend, &opts) == 0) {
            test::EchoService_Stub stub(&ch);
            Controller down;
            test::EchoRequest dreq;
            dreq.set_message(req->message());
            test::EchoResponse dres;
            stub.Echo(&down, &dreq, &dres, nullptr);
            downstream_error.store(down.ErrorCode(),
                                   std::memory_order_release);
        }
        res->set_message("relayed");
        done->Run();
    }
    EndPoint backend;
    std::atomic<int> downstream_error{-1};
};
}  // namespace

TEST(Cancel, CascadesThroughTwoHops) {
    // client -> relay -> watcher. Canceling the client call cancels the
    // relay's server context, which cascades into its in-flight
    // downstream call (ECANCELED), which wire-cancels the watcher.
    CancelWatchImpl watcher;
    Server back;
    ASSERT_EQ(back.AddService(&watcher), 0);
    EndPoint listen;
    str2endpoint("127.0.0.1:0", &listen);
    ASSERT_EQ(back.Start(listen, nullptr), 0);

    RelayImpl relay;
    str2endpoint("127.0.0.1", back.listened_port(), &relay.backend);
    Server front;
    ASSERT_EQ(front.AddService(&relay), 0);
    str2endpoint("127.0.0.1:0", &listen);
    ASSERT_EQ(front.Start(listen, nullptr), 0);
    EndPoint front_ep;
    str2endpoint("127.0.0.1", front.listened_port(), &front_ep);

    Channel channel;
    ChannelOptions opts;
    opts.timeout_ms = 5000;
    ASSERT_EQ(channel.Init(front_ep, &opts), 0);
    test::EchoService_Stub stub(&channel);

    Controller cntl;
    test::EchoRequest req;
    req.set_message("cascade");
    test::EchoResponse res;
    SignalDone done;
    stub.Echo(&cntl, &req, &res, &done);
    ASSERT_TRUE(WaitFor([&] { return watcher.entered.load() >= 1; }, 2000));

    cntl.StartCancel();
    done.ev.wait();
    EXPECT_TRUE(cntl.Failed());
    EXPECT_EQ(cntl.ErrorCode(), ECANCELED);
    // The relay's downstream call was canceled by the cascade...
    EXPECT_TRUE(WaitFor(
        [&] { return relay.downstream_error.load() == ECANCELED; }, 3000));
    // ...and the cancel propagated one more hop over the wire.
    EXPECT_TRUE(WaitFor([&] { return watcher.saw_cancel.load() >= 1; },
                        2000));
    front.Stop();
    front.Join();
    back.Stop();
    back.Join();
}
