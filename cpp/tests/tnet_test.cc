// I/O core loopback tests: real sockets, real epoll, full read/write paths —
// the in-process loopback style of the reference's tests (e.g.
// test/brpc_channel_unittest.cpp:195 starts a real listener in-process).
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cstring>
#include <mutex>
#include <vector>

#include "tbase/errno.h"
#include "tfiber/fiber_sync.h"
#include "tnet/acceptor.h"
#include "tnet/event_dispatcher.h"
#include "tnet/input_messenger.h"
#include "tnet/socket.h"
#include "tnet/socket_map.h"
#include "ttest/ttest.h"

using namespace tpurpc;

namespace {

// Test protocol: "TST0" + u32le length + payload.
constexpr char kMagic[4] = {'T', 'S', 'T', '0'};

struct TestMsg : public InputMessageBase {
    IOBuf payload;
};

ParseResult test_parse(IOBuf* source, Socket* s, bool read_eof,
                       const void* arg) {
    if (source->size() < 8) {
        char head[4];
        const size_t n = source->copy_to(head, 4);
        if (memcmp(head, kMagic, n) != 0) {
            return ParseResult::make(ParseError::TRY_OTHERS);
        }
        return ParseResult::make(ParseError::NOT_ENOUGH_DATA);
    }
    char header[8];
    source->copy_to(header, 8);
    if (memcmp(header, kMagic, 4) != 0) {
        return ParseResult::make(ParseError::TRY_OTHERS);
    }
    uint32_t len;
    memcpy(&len, header + 4, 4);
    if (source->size() < 8 + (size_t)len) {
        return ParseResult::make(ParseError::NOT_ENOUGH_DATA);
    }
    source->pop_front(8);
    auto* msg = new TestMsg;
    source->cutn(&msg->payload, len);
    return ParseResult::make_ok(msg);
}

void frame(IOBuf* out, const IOBuf& payload) {
    char header[8];
    memcpy(header, kMagic, 4);
    const uint32_t len = (uint32_t)payload.size();
    memcpy(header + 4, &len, 4);
    out->append(header, 8);
    out->append(payload);
}

// Server side: echo the payload back.
void server_process(InputMessageBase* raw) {
    TestMsg* msg = (TestMsg*)raw;
    SocketUniquePtr s;
    if (Socket::AddressSocket(msg->socket_id, &s) == 0) {
        IOBuf out;
        frame(&out, msg->payload);
        s->Write(&out);
    }
    delete msg;
}

// Client side: collect responses.
struct ClientSink {
    std::mutex mu;
    std::vector<std::string> responses;
    CountdownEvent pending{0};
};
ClientSink* g_sink = nullptr;

void client_process(InputMessageBase* raw) {
    TestMsg* msg = (TestMsg*)raw;
    {
        std::lock_guard<std::mutex> g(g_sink->mu);
        g_sink->responses.push_back(msg->payload.to_string());
    }
    g_sink->pending.signal();
    delete msg;
}

int g_server_proto = -1;
int g_client_proto = -1;

void register_test_protocols() {
    static std::once_flag once;
    std::call_once(once, [] {
        Protocol sp;
        sp.parse = test_parse;
        sp.process = server_process;
        sp.name = "test_echo_server";
        g_server_proto = RegisterProtocol(sp);
        Protocol cp;
        cp.parse = test_parse;
        cp.process = client_process;
        cp.name = "test_echo_client";
        g_client_proto = RegisterProtocol(cp);
    });
}

}  // namespace

TEST(Net, LoopbackEchoSmallAndLarge) {
    register_test_protocols();
    ClientSink sink;
    g_sink = &sink;

    InputMessenger server_m({g_server_proto});
    Acceptor acceptor(&server_m);
    EndPoint listen_ep;
    str2endpoint("127.0.0.1:0", &listen_ep);
    ASSERT_EQ(acceptor.StartAccept(listen_ep), 0);
    ASSERT_GT(acceptor.listened_port(), 0);

    InputMessenger client_m({g_client_proto});
    EndPoint server_ep;
    str2endpoint("127.0.0.1", acceptor.listened_port(), &server_ep);
    SocketId cid;
    ASSERT_EQ(SocketMap::singleton()->GetOrCreate(server_ep, &client_m, &cid),
              0);

    SocketUniquePtr cs;
    ASSERT_EQ(Socket::AddressSocket(cid, &cs), 0);

    // Small message.
    {
        IOBuf payload;
        payload.append("hello tpu-rpc");
        IOBuf framed;
        frame(&framed, payload);
        sink.pending.reset(1);
        ASSERT_EQ(cs->Write(&framed), 0);
        ASSERT_EQ(sink.pending.wait(), 0);
        std::lock_guard<std::mutex> g(sink.mu);
        ASSERT_EQ(sink.responses.size(), 1u);
        EXPECT_EQ(sink.responses[0], "hello tpu-rpc");
        sink.responses.clear();
    }

    // Large (1MB) message exercising multi-block iobufs + partial writes.
    {
        std::string big(1 << 20, 'x');
        for (size_t i = 0; i < big.size(); ++i) big[i] = (char)('a' + i % 26);
        IOBuf payload;
        payload.append(big);
        IOBuf framed;
        frame(&framed, payload);
        sink.pending.reset(1);
        ASSERT_EQ(cs->Write(&framed), 0);
        ASSERT_EQ(sink.pending.wait(), 0);
        std::lock_guard<std::mutex> g(sink.mu);
        ASSERT_EQ(sink.responses.size(), 1u);
        EXPECT_TRUE(sink.responses[0] == big);
        sink.responses.clear();
    }

    // Burst of messages: ordering + batching through the write queue.
    {
        const int kN = 200;
        sink.pending.reset(kN);
        for (int i = 0; i < kN; ++i) {
            IOBuf payload;
            payload.append("msg-" + std::to_string(i));
            IOBuf framed;
            frame(&framed, payload);
            ASSERT_EQ(cs->Write(&framed), 0);
        }
        ASSERT_EQ(sink.pending.wait(), 0);
        std::lock_guard<std::mutex> g(sink.mu);
        ASSERT_EQ(sink.responses.size(), (size_t)kN);
        // Each request runs on its own fiber (reference QueueMessage), so
        // response ORDER is not guaranteed at this layer — correlation ids
        // provide matching at the RPC layer. Check the full set round-
        // tripped intact.
        std::vector<std::string> got = sink.responses;
        std::sort(got.begin(), got.end());
        std::vector<std::string> want;
        for (int i = 0; i < kN; ++i) want.push_back("msg-" + std::to_string(i));
        std::sort(want.begin(), want.end());
        EXPECT_TRUE(got == want);
        sink.responses.clear();
    }

    EXPECT_EQ(acceptor.accepted_count(), 1);  // one shared connection

    // Failure path: failed socket rejects writes.
    cs->SetFailedWithError(TERR_CLOSE);
    {
        IOBuf framed;
        frame(&framed, IOBuf());
        IOBuf copy = framed;
        EXPECT_EQ(cs->Write(&copy), -1);
        EXPECT_EQ(errno, TERR_FAILED_SOCKET);
    }
    SocketMap::singleton()->Remove(server_ep, cid);
    g_sink = nullptr;
}

TEST(Net, StaleSocketIdAddressFails) {
    SocketOptions opts;
    opts.fd = -1;
    str2endpoint("127.0.0.1:1", &opts.remote_side);
    SocketId id;
    ASSERT_EQ(Socket::Create(opts, &id), 0);
    SocketUniquePtr ptr;
    ASSERT_EQ(Socket::AddressSocket(id, &ptr), 0);
    ptr->SetFailed();
    SocketUniquePtr ptr2;
    EXPECT_EQ(Socket::AddressSocket(id, &ptr2), -1);
}

TEST(Net, ConnectFailureFailsSocket) {
    register_test_protocols();
    InputMessenger client_m({g_client_proto});
    // Port 1 on localhost: connection refused.
    EndPoint dead_ep;
    str2endpoint("127.0.0.1:1", &dead_ep);
    SocketOptions opts;
    opts.fd = -1;
    opts.remote_side = dead_ep;
    opts.on_edge_triggered_events = &InputMessenger::OnNewMessages;
    opts.user = &client_m;
    SocketId id;
    ASSERT_EQ(Socket::Create(opts, &id), 0);
    SocketUniquePtr s;
    ASSERT_EQ(Socket::AddressSocket(id, &s), 0);
    IOBuf data;
    data.append("doomed");
    EXPECT_EQ(s->Write(&data), 0);  // queued; fails async
    // The KeepWrite fiber discovers the refused connection and fails the
    // socket.
    for (int i = 0; i < 200 && !s->Failed(); ++i) {
        usleep(10000);
    }
    EXPECT_TRUE(s->Failed());
}
