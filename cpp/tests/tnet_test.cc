// I/O core loopback tests: real sockets, real epoll, full read/write paths —
// the in-process loopback style of the reference's tests (e.g.
// test/brpc_channel_unittest.cpp:195 starts a real listener in-process).
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cstring>
#include <mutex>
#include <vector>

#include "tbase/errno.h"
#include "tbase/flags.h"
#include "tfiber/fiber_sync.h"
#include "tnet/acceptor.h"
#include "tnet/event_dispatcher.h"
#include "tnet/input_messenger.h"
#include "tnet/socket.h"
#include "tnet/socket_map.h"
#include "ttest/ttest.h"

DECLARE_int32(inline_dispatch_budget);
DECLARE_int32(inline_dispatch_max_bytes);

using namespace tpurpc;

namespace {

// Test protocol: "TST0" + u32le length + payload.
constexpr char kMagic[4] = {'T', 'S', 'T', '0'};

struct TestMsg : public InputMessageBase {
    IOBuf payload;
};

ParseResult test_parse(IOBuf* source, Socket* s, bool read_eof,
                       const void* arg) {
    if (source->size() < 8) {
        char head[4];
        const size_t n = source->copy_to(head, 4);
        if (memcmp(head, kMagic, n) != 0) {
            return ParseResult::make(ParseError::TRY_OTHERS);
        }
        return ParseResult::make(ParseError::NOT_ENOUGH_DATA);
    }
    char header[8];
    source->copy_to(header, 8);
    if (memcmp(header, kMagic, 4) != 0) {
        return ParseResult::make(ParseError::TRY_OTHERS);
    }
    uint32_t len;
    memcpy(&len, header + 4, 4);
    if (source->size() < 8 + (size_t)len) {
        return ParseResult::make(ParseError::NOT_ENOUGH_DATA);
    }
    source->pop_front(8);
    auto* msg = new TestMsg;
    source->cutn(&msg->payload, len);
    msg->byte_size = 8 + (size_t)len;
    return ParseResult::make_ok(msg);
}

// Zero-cut peek for the test protocol (ISSUE 7): magic + total size from
// the contiguous 8-byte header.
int64_t test_peek(const char* hdr, Socket*) {
    if (memcmp(hdr, kMagic, 4) != 0) return 0;
    uint32_t len;
    memcpy(&len, hdr + 4, 4);
    if (len > (64u << 20)) return -1;
    return 8 + (int64_t)len;
}

void frame(IOBuf* out, const IOBuf& payload) {
    char header[8];
    memcpy(header, kMagic, 4);
    const uint32_t len = (uint32_t)payload.size();
    memcpy(header + 4, &len, 4);
    out->append(header, 8);
    out->append(payload);
}

// Server side: echo the payload back.
void server_process(InputMessageBase* raw) {
    TestMsg* msg = (TestMsg*)raw;
    SocketUniquePtr s;
    if (Socket::AddressSocket(msg->socket_id, &s) == 0) {
        IOBuf out;
        frame(&out, msg->payload);
        s->Write(&out);
    }
    delete msg;
}

// Client side: collect responses.
struct ClientSink {
    std::mutex mu;
    std::vector<std::string> responses;
    CountdownEvent pending{0};
};
ClientSink* g_sink = nullptr;

void client_process(InputMessageBase* raw) {
    TestMsg* msg = (TestMsg*)raw;
    {
        std::lock_guard<std::mutex> g(g_sink->mu);
        g_sink->responses.push_back(msg->payload.to_string());
    }
    g_sink->pending.signal();
    delete msg;
}

int g_server_proto = -1;
int g_client_proto = -1;

void register_test_protocols() {
    static std::once_flag once;
    std::call_once(once, [] {
        Protocol sp;
        sp.parse = test_parse;
        sp.process = server_process;
        sp.name = "test_echo_server";
        sp.inline_safe = true;  // echo-on-input-fiber: run-to-completion
        sp.peek = test_peek;
        sp.peek_len = 8;
        g_server_proto = RegisterProtocol(sp);
        Protocol cp;
        cp.parse = test_parse;
        cp.process = client_process;
        cp.name = "test_echo_client";
        cp.inline_safe = true;
        cp.peek = test_peek;
        cp.peek_len = 8;
        g_client_proto = RegisterProtocol(cp);
    });
}

// One served loopback connection driven by raw writes from this test:
// returns the ACCEPTED socket's echoes through `sink`.
struct EchoFixture {
    InputMessenger server_m;
    InputMessenger client_m;
    Acceptor acceptor;
    EndPoint server_ep;
    SocketId client_id = INVALID_VREF_ID;

    EchoFixture() : acceptor(&server_m) {
        register_test_protocols();
        server_m.add_protocol(g_server_proto);
        client_m.add_protocol(g_client_proto);
    }

    bool Start() {
        EndPoint listen_ep;
        str2endpoint("127.0.0.1:0", &listen_ep);
        if (acceptor.StartAccept(listen_ep) != 0) return false;
        str2endpoint("127.0.0.1", acceptor.listened_port(), &server_ep);
        return SocketMap::singleton()->GetOrCreate(server_ep, &client_m,
                                                   &client_id) == 0;
    }

    ~EchoFixture() {
        if (client_id != INVALID_VREF_ID) {
            Socket::SetFailedById(client_id);
            SocketMap::singleton()->Remove(server_ep, client_id);
        }
    }
};

}  // namespace

TEST(Net, LoopbackEchoSmallAndLarge) {
    register_test_protocols();
    ClientSink sink;
    g_sink = &sink;

    InputMessenger server_m({g_server_proto});
    Acceptor acceptor(&server_m);
    EndPoint listen_ep;
    str2endpoint("127.0.0.1:0", &listen_ep);
    ASSERT_EQ(acceptor.StartAccept(listen_ep), 0);
    ASSERT_GT(acceptor.listened_port(), 0);

    InputMessenger client_m({g_client_proto});
    EndPoint server_ep;
    str2endpoint("127.0.0.1", acceptor.listened_port(), &server_ep);
    SocketId cid;
    ASSERT_EQ(SocketMap::singleton()->GetOrCreate(server_ep, &client_m, &cid),
              0);

    SocketUniquePtr cs;
    ASSERT_EQ(Socket::AddressSocket(cid, &cs), 0);

    // Small message.
    {
        IOBuf payload;
        payload.append("hello tpu-rpc");
        IOBuf framed;
        frame(&framed, payload);
        sink.pending.reset(1);
        ASSERT_EQ(cs->Write(&framed), 0);
        ASSERT_EQ(sink.pending.wait(), 0);
        std::lock_guard<std::mutex> g(sink.mu);
        ASSERT_EQ(sink.responses.size(), 1u);
        EXPECT_EQ(sink.responses[0], "hello tpu-rpc");
        sink.responses.clear();
    }

    // Large (1MB) message exercising multi-block iobufs + partial writes.
    {
        std::string big(1 << 20, 'x');
        for (size_t i = 0; i < big.size(); ++i) big[i] = (char)('a' + i % 26);
        IOBuf payload;
        payload.append(big);
        IOBuf framed;
        frame(&framed, payload);
        sink.pending.reset(1);
        ASSERT_EQ(cs->Write(&framed), 0);
        ASSERT_EQ(sink.pending.wait(), 0);
        std::lock_guard<std::mutex> g(sink.mu);
        ASSERT_EQ(sink.responses.size(), 1u);
        EXPECT_TRUE(sink.responses[0] == big);
        sink.responses.clear();
    }

    // Burst of messages: ordering + batching through the write queue.
    {
        const int kN = 200;
        sink.pending.reset(kN);
        for (int i = 0; i < kN; ++i) {
            IOBuf payload;
            payload.append("msg-" + std::to_string(i));
            IOBuf framed;
            frame(&framed, payload);
            ASSERT_EQ(cs->Write(&framed), 0);
        }
        ASSERT_EQ(sink.pending.wait(), 0);
        std::lock_guard<std::mutex> g(sink.mu);
        ASSERT_EQ(sink.responses.size(), (size_t)kN);
        // Each request runs on its own fiber (reference QueueMessage), so
        // response ORDER is not guaranteed at this layer — correlation ids
        // provide matching at the RPC layer. Check the full set round-
        // tripped intact.
        std::vector<std::string> got = sink.responses;
        std::sort(got.begin(), got.end());
        std::vector<std::string> want;
        for (int i = 0; i < kN; ++i) want.push_back("msg-" + std::to_string(i));
        std::sort(want.begin(), want.end());
        EXPECT_TRUE(got == want);
        sink.responses.clear();
    }

    EXPECT_EQ(acceptor.accepted_count(), 1);  // one shared connection

    // Failure path: failed socket rejects writes.
    cs->SetFailedWithError(TERR_CLOSE);
    {
        IOBuf framed;
        frame(&framed, IOBuf());
        IOBuf copy = framed;
        EXPECT_EQ(cs->Write(&copy), -1);
        EXPECT_EQ(errno, TERR_FAILED_SOCKET);
    }
    SocketMap::singleton()->Remove(server_ep, cid);
    g_sink = nullptr;
}

TEST(Net, StaleSocketIdAddressFails) {
    SocketOptions opts;
    opts.fd = -1;
    str2endpoint("127.0.0.1:1", &opts.remote_side);
    SocketId id;
    ASSERT_EQ(Socket::Create(opts, &id), 0);
    SocketUniquePtr ptr;
    ASSERT_EQ(Socket::AddressSocket(id, &ptr), 0);
    ptr->SetFailed();
    SocketUniquePtr ptr2;
    EXPECT_EQ(Socket::AddressSocket(id, &ptr2), -1);
}

// ---- raw-speed round (ISSUE 7) ----

// Peek fast path: a frame whose header (and then body) is split across
// many tiny writes still cuts exactly once — the sticky connection waits
// peek-announced byte counts instead of re-parsing per read.
TEST(Net, PeekFastPathSplitHeaders) {
    ClientSink sink;
    g_sink = &sink;
    EchoFixture fx;
    ASSERT_TRUE(fx.Start());
    SocketUniquePtr cs;
    ASSERT_EQ(Socket::AddressSocket(fx.client_id, &cs), 0);

    // Whole first message: sniffs the protocol, the socket goes sticky.
    {
        IOBuf payload;
        payload.append("sniff");
        IOBuf framed;
        frame(&framed, payload);
        sink.pending.reset(1);
        ASSERT_EQ(cs->Write(&framed), 0);
        ASSERT_EQ(sink.pending.wait(), 0);
    }
    // Second message dribbled in 1-byte writes: 8 header bytes (split
    // peek), then the payload (split pending-frame wait).
    {
        const std::string body = "split-header-body";
        IOBuf payload;
        payload.append(body);
        IOBuf framed;
        frame(&framed, payload);
        std::string wire = framed.to_string();
        sink.pending.reset(1);
        for (size_t i = 0; i < wire.size(); ++i) {
            IOBuf one;
            one.append(&wire[i], 1);
            ASSERT_EQ(cs->Write(&one), 0);
            usleep(1000);  // separate reads: each byte is its own burst
        }
        ASSERT_EQ(sink.pending.wait(), 0);
        std::lock_guard<std::mutex> g(sink.mu);
        ASSERT_EQ(sink.responses.size(), 2u);
        EXPECT_EQ(sink.responses[1], body);
    }
    g_sink = nullptr;
}

// A sticky socket whose next bytes are NOT the sticky protocol's resets
// and re-sniffs (TRY_OTHERS contract); with no other protocol claiming
// the bytes the stream is broken and the connection fails.
TEST(Net, PeekStickyResetOnParseError) {
    ClientSink sink;
    g_sink = &sink;
    EchoFixture fx;
    ASSERT_TRUE(fx.Start());
    SocketUniquePtr cs;
    ASSERT_EQ(Socket::AddressSocket(fx.client_id, &cs), 0);

    IOBuf payload;
    payload.append("ok");
    IOBuf framed;
    frame(&framed, payload);
    sink.pending.reset(1);
    ASSERT_EQ(cs->Write(&framed), 0);
    ASSERT_EQ(sink.pending.wait(), 0);  // sticky now

    IOBuf garbage;
    garbage.append("GARBAGE-not-a-frame");
    ASSERT_EQ(cs->Write(&garbage), 0);
    // Server fails its accepted connection; we observe the close as a
    // client-side failure (EOF).
    for (int i = 0; i < 500 && !cs->Failed(); ++i) {
        usleep(10000);
    }
    EXPECT_TRUE(cs->Failed());
    g_sink = nullptr;
}

// TRY_OTHERS fallback still works with the peek fast path in the set: a
// fresh connection sniffs past the peek-enabled protocol to another
// parser, and a sticky peek mismatch re-sniffs instead of failing.
TEST(Net, PeekTryOthersFallback) {
    register_test_protocols();
    // Second wire format on the same server: "ALT0" + u32le len, echoed
    // back as a TST0 frame so the client sink still collects it.
    static int alt_proto = -1;
    static std::once_flag once;
    std::call_once(once, [] {
        Protocol ap;
        ap.parse = [](IOBuf* source, Socket*, bool,
                      const void*) -> ParseResult {
            if (source->size() < 8) {
                char head[4];
                const size_t n = source->copy_to(head, 4);
                if (memcmp(head, "ALT0", n) != 0) {
                    return ParseResult::make(ParseError::TRY_OTHERS);
                }
                return ParseResult::make(ParseError::NOT_ENOUGH_DATA);
            }
            char header[8];
            source->copy_to(header, 8);
            if (memcmp(header, "ALT0", 4) != 0) {
                return ParseResult::make(ParseError::TRY_OTHERS);
            }
            uint32_t len;
            memcpy(&len, header + 4, 4);
            if (source->size() < 8 + (size_t)len) {
                return ParseResult::make(ParseError::NOT_ENOUGH_DATA);
            }
            source->pop_front(8);
            auto* msg = new TestMsg;
            source->cutn(&msg->payload, len);
            msg->byte_size = 8 + (size_t)len;
            return ParseResult::make_ok(msg);
        };
        ap.process = [](InputMessageBase* raw) {
            TestMsg* msg = (TestMsg*)raw;
            SocketUniquePtr s;
            if (Socket::AddressSocket(msg->socket_id, &s) == 0) {
                IOBuf out, marked;
                marked.append("alt:");
                marked.append(msg->payload);
                frame(&out, marked);
                s->Write(&out);
            }
            delete msg;
        };
        ap.name = "test_alt";
        alt_proto = RegisterProtocol(ap);
    });

    ClientSink sink;
    g_sink = &sink;
    EchoFixture fx;
    fx.server_m.add_protocol(alt_proto);
    ASSERT_TRUE(fx.Start());
    SocketUniquePtr cs;
    ASSERT_EQ(Socket::AddressSocket(fx.client_id, &cs), 0);

    // ALT frame first: the TST0 peek protocol must yield via TRY_OTHERS.
    {
        IOBuf out;
        out.append("ALT0", 4);
        const uint32_t len = 5;
        out.append((const char*)&len, 4);
        out.append("hello", 5);
        sink.pending.reset(1);
        ASSERT_EQ(cs->Write(&out), 0);
        ASSERT_EQ(sink.pending.wait(), 0);
    }
    // The socket is now sticky on ALT; a TST0 frame makes the ALT peek
    // path (none — ALT has no peek) fall back to TRY_OTHERS re-sniffing
    // into the TST0 parser.
    {
        IOBuf payload;
        payload.append("tst-after-alt");
        IOBuf framed;
        frame(&framed, payload);
        sink.pending.reset(1);
        ASSERT_EQ(cs->Write(&framed), 0);
        ASSERT_EQ(sink.pending.wait(), 0);
    }
    std::lock_guard<std::mutex> g(sink.mu);
    ASSERT_EQ(sink.responses.size(), 2u);
    EXPECT_EQ(sink.responses[0], "alt:hello");
    EXPECT_EQ(sink.responses[1], "tst-after-alt");
    g_sink = nullptr;
}

// Run-to-completion budget: a one-writev burst far past the inline
// budget completes fully (overflow falls back to the fiber fan-out) and
// both counters move.
TEST(Net, InlineDispatchBudgetOverflow) {
    ClientSink sink;
    g_sink = &sink;
    EchoFixture fx;
    ASSERT_TRUE(fx.Start());
    SocketUniquePtr cs;
    ASSERT_EQ(Socket::AddressSocket(fx.client_id, &cs), 0);

    const int64_t inlines_before = inline_dispatch::dispatches();
    const int64_t overflows_before = inline_dispatch::overflows();
    const int kN = 200;
    IOBuf burst;
    for (int i = 0; i < kN; ++i) {
        IOBuf payload;
        payload.append("burst-" + std::to_string(i));
        frame(&burst, payload);
    }
    const int32_t old_budget = FLAGS_inline_dispatch_budget.get();
    FLAGS_inline_dispatch_budget.set(2);
    sink.pending.reset(kN);
    const int write_rc = cs->Write(&burst);
    if (write_rc != 0) {
        // Nothing queued: waiting would hang. Restore and bail.
        FLAGS_inline_dispatch_budget.set(old_budget);
    }
    ASSERT_EQ(write_rc, 0);
    const int wait_rc = sink.pending.wait();
    // Restore BEFORE any assert can return out of the test — a leaked
    // budget of 2 would warp every later test's dispatch behavior.
    FLAGS_inline_dispatch_budget.set(old_budget);
    ASSERT_EQ(wait_rc, 0);
    {
        std::lock_guard<std::mutex> g(sink.mu);
        ASSERT_EQ(sink.responses.size(), (size_t)kN);
    }
    // The burst lands in few reads: some messages ran inline, and with
    // budget 2 the rest overflowed to the scheduler.
    EXPECT_GT(inline_dispatch::dispatches(), inlines_before);
    EXPECT_GT(inline_dispatch::overflows(), overflows_before);
    g_sink = nullptr;
}

// Cross-response write coalescing: responses the server queues during
// one dispatch round leave in a single writev — the accepted socket's
// biggest write batch spans several frames and the deferred-election
// counter moves (the rpc_socket_write_batch_bytes summary feeds off the
// same per-batch sizes).
TEST(Net, WriteCoalescingAcrossResponses) {
    ClientSink sink;
    g_sink = &sink;
    EchoFixture fx;
    ASSERT_TRUE(fx.Start());
    SocketUniquePtr cs;
    ASSERT_EQ(Socket::AddressSocket(fx.client_id, &cs), 0);

    const int64_t coalesced_before = SocketCoalescedWrites();
    const int kN = 100;
    const std::string body(100, 'c');
    IOBuf burst;
    for (int i = 0; i < kN; ++i) {
        IOBuf payload;
        payload.append(body);
        frame(&burst, payload);
    }
    sink.pending.reset(kN);
    ASSERT_EQ(cs->Write(&burst), 0);
    ASSERT_EQ(sink.pending.wait(), 0);
    EXPECT_GT(SocketCoalescedWrites(), coalesced_before);
    // The server's accepted connection wrote at least one batch of
    // multiple coalesced response frames (frame = 8 + 100 bytes).
    const std::vector<SocketId> conns = fx.acceptor.connections();
    ASSERT_EQ(conns.size(), 1u);
    SocketUniquePtr acc;
    ASSERT_EQ(Socket::AddressSocket(conns[0], &acc), 0);
    EXPECT_GE(acc->max_write_batch_bytes(), 2 * (int64_t)(8 + body.size()));
    g_sink = nullptr;
}

// Pooled-connection selection round-robins (FIFO) through the idle pool
// instead of convoying on the most recently returned socket.
TEST(Net, SocketPoolRoundRobins) {
    register_test_protocols();
    InputMessenger client_m({g_client_proto});
    EndPoint remote;
    str2endpoint("127.0.0.1:39999", &remote);  // never written to
    SocketPool* pool = SocketPool::singleton();
    SocketId a, b, c;
    ASSERT_EQ(pool->Get(remote, &client_m, &a), 0);
    ASSERT_EQ(pool->Get(remote, &client_m, &b), 0);
    ASSERT_EQ(pool->Get(remote, &client_m, &c), 0);
    EXPECT_EQ(pool->idle_count(remote), 0u);
    pool->Return(a);
    pool->Return(b);
    pool->Return(c);
    ASSERT_EQ(pool->idle_count(remote), 3u);
    SocketId r1, r2, r3;
    ASSERT_EQ(pool->Get(remote, &client_m, &r1), 0);
    ASSERT_EQ(pool->Get(remote, &client_m, &r2), 0);
    ASSERT_EQ(pool->Get(remote, &client_m, &r3), 0);
    // FIFO: the least recently returned member comes back first.
    EXPECT_EQ(r1, a);
    EXPECT_EQ(r2, b);
    EXPECT_EQ(r3, c);
    Socket::SetFailedById(a);
    Socket::SetFailedById(b);
    Socket::SetFailedById(c);
}

TEST(Net, ConnectFailureFailsSocket) {
    register_test_protocols();
    InputMessenger client_m({g_client_proto});
    // Port 1 on localhost: connection refused.
    EndPoint dead_ep;
    str2endpoint("127.0.0.1:1", &dead_ep);
    SocketOptions opts;
    opts.fd = -1;
    opts.remote_side = dead_ep;
    opts.on_edge_triggered_events = &InputMessenger::OnNewMessages;
    opts.user = &client_m;
    SocketId id;
    ASSERT_EQ(Socket::Create(opts, &id), 0);
    SocketUniquePtr s;
    ASSERT_EQ(Socket::AddressSocket(id, &s), 0);
    IOBuf data;
    data.append("doomed");
    EXPECT_EQ(s->Write(&data), 0);  // queued; fails async
    // The KeepWrite fiber discovers the refused connection and fails the
    // socket.
    for (int i = 0; i < 200 && !s->Failed(); ++i) {
        usleep(10000);
    }
    EXPECT_TRUE(s->Failed());
}

// ---------------- transport tier registry (ISSUE 12) ----------------

TEST(TransportTier, RegistryBuiltinsAndIdempotence) {
    // Built-ins exist with the capability story the descriptor seam
    // relies on: tcp moves bytes only; ici/shm_xproc are zero-copy and
    // descriptor-capable; device is the staging-ring tier.
    const int tcp = TierTcp();
    const int ici = TierIci();
    const int shm = TierShmXproc();
    const int dev = TierDevice();
    ASSERT_GE(tcp, 0);
    ASSERT_NE(tcp, ici);
    ASSERT_NE(ici, shm);
    ASSERT_NE(shm, dev);
    const TransportTier* t = GetTransportTier(tcp);
    ASSERT_TRUE(t != nullptr);
    EXPECT_FALSE(t->descriptor_capable);
    EXPECT_FALSE(t->zero_copy);
    EXPECT_TRUE(t->cross_process);
    t = GetTransportTier(ici);
    ASSERT_TRUE(t != nullptr);
    EXPECT_TRUE(t->descriptor_capable);
    EXPECT_TRUE(t->zero_copy);
    EXPECT_FALSE(t->cross_process);
    t = GetTransportTier(shm);
    ASSERT_TRUE(t != nullptr);
    EXPECT_TRUE(t->descriptor_capable);
    EXPECT_TRUE(t->cross_process);
    // Registration is idempotent by name (re-register returns the
    // existing id) and lookup by name round-trips.
    EXPECT_EQ(tcp, RegisterTransportTier({"tcp", true, true, false}));
    EXPECT_EQ(ici, FindTransportTier("ici"));
    EXPECT_EQ(-1, FindTransportTier("no_such_tier"));
    EXPECT_TRUE(GetTransportTier(-1) == nullptr);
    EXPECT_TRUE(GetTransportTier(10000) == nullptr);
    EXPECT_GE(TransportTierCount(), 4);
}

TEST(TransportTier, StatsAttributeByTier) {
    const int ici = TierIci();
    const int64_t in0 = transport_stats::in_bytes(ici);
    const int64_t stalls0 = transport_stats::credit_stalls(ici);
    transport_stats::AddIn(ici, 1234);
    transport_stats::AddCreditStall(ici);
    transport_stats::AddDescOut(ici, 99);
    EXPECT_EQ(in0 + 1234, transport_stats::in_bytes(ici));
    EXPECT_EQ(stalls0 + 1, transport_stats::credit_stalls(ici));
    EXPECT_GE(transport_stats::desc_out_bytes(ici), (int64_t)99);
    // Bad ids are ignored, never a crash.
    transport_stats::AddIn(-1, 5);
    transport_stats::AddIn(9999, 5);
    EXPECT_EQ((int64_t)0, transport_stats::in_bytes(9999));
    // The /pools section renders one line per tier.
    const std::string dump = transport_stats::DebugString();
    EXPECT_TRUE(dump.find("tier tcp") != std::string::npos);
    EXPECT_TRUE(dump.find("tier ici") != std::string::npos);
    EXPECT_TRUE(dump.find("tier shm_xproc") != std::string::npos);
    EXPECT_TRUE(dump.find("tier device") != std::string::npos);
}

TEST(TransportTier, DescriptorSeamGatesOnTierAndPool) {
    // Null socket: never capable, never in scope.
    EXPECT_FALSE(TransportDescriptorCapable(nullptr));
    EXPECT_FALSE(TransportDescriptorScopeOk(nullptr, 1));
    // A plain-fd socket is the tcp tier: bytes only, no descriptors —
    // regardless of what pool id a request names.
    int fds[2];
    ASSERT_EQ(0, socketpair(AF_UNIX, SOCK_STREAM, 0, fds));
    SocketOptions opts;
    opts.fd = fds[0];
    SocketId sid;
    ASSERT_EQ(0, Socket::Create(opts, &sid));
    SocketUniquePtr s;
    ASSERT_EQ(0, Socket::AddressSocket(sid, &s));
    EXPECT_EQ(TierTcp(), s->transport_tier());
    EXPECT_FALSE(TransportDescriptorCapable(s.get()));
    EXPECT_FALSE(TransportDescriptorScopeOk(s.get(), 42));
    s->SetFailedWithError(TERR_CLOSE);
    s.reset();
    close(fds[1]);
}

TEST(TransportTier, DcnTierRegisteredAndDescriptorIncapable) {
    // The cross-pod tier (ISSUE 14): a distinct registry entry — plain
    // byte stream, descriptor-INCAPABLE (the pod boundary shares no
    // pool mapping), cross-process. A socket forced onto it reports the
    // tier and fails both descriptor seams, so a pinned try degrades to
    // inline through the one seam.
    const int dcn = TierDcn();
    ASSERT_GE(dcn, 0);
    ASSERT_NE(dcn, TierTcp());
    const TransportTier* t = GetTransportTier(dcn);
    ASSERT_TRUE(t != nullptr);
    EXPECT_FALSE(t->descriptor_capable);
    EXPECT_FALSE(t->zero_copy);
    EXPECT_TRUE(t->cross_process);
    EXPECT_EQ(dcn, FindTransportTier("dcn"));
    EXPECT_TRUE(transport_stats::DebugString().find("tier dcn") !=
                std::string::npos);

    int fds[2];
    ASSERT_EQ(0, socketpair(AF_UNIX, SOCK_STREAM, 0, fds));
    SocketOptions opts;
    opts.fd = fds[0];
    opts.forced_transport_tier = dcn;
    SocketId sid;
    ASSERT_EQ(0, Socket::Create(opts, &sid));
    SocketUniquePtr s;
    ASSERT_EQ(0, Socket::AddressSocket(sid, &s));
    EXPECT_EQ(dcn, s->transport_tier());
    EXPECT_EQ(dcn, s->forced_transport_tier());
    EXPECT_FALSE(TransportDescriptorCapable(s.get()));
    EXPECT_FALSE(TransportDescriptorScopeOk(s.get(), 42));
    s->SetFailedWithError(TERR_CLOSE);
    s.reset();
    close(fds[1]);

    // Shaping arithmetic: latency + bytes/mbps, dcn-tier only.
    SetFlagValue("dcn_emu_latency_us", "500");
    SetFlagValue("dcn_emu_mbps", "100");
    EXPECT_TRUE(DcnShapingEnabled());
    EXPECT_EQ((int64_t)500 + 1000000 / 100,
              DcnShapeDelayUs(dcn, 1000000));
    // Inbound half: bandwidth only (latency is the writer's, once per
    // message — never per read burst).
    EXPECT_EQ((int64_t)1000000 / 100, DcnShapeReadDelayUs(dcn, 1000000));
    EXPECT_EQ((int64_t)0, DcnShapeDelayUs(TierTcp(), 1000000));
    EXPECT_EQ((int64_t)0, DcnShapeReadDelayUs(TierTcp(), 1000000));
    SetFlagValue("dcn_emu_latency_us", "0");
    SetFlagValue("dcn_emu_mbps", "0");
    EXPECT_FALSE(DcnShapingEnabled());
    EXPECT_EQ((int64_t)0, DcnShapeDelayUs(dcn, 1000000));
}

TEST(TransportTier, SocketMapKeyedByEndpointAndTier) {
    // (endpoint, tier) keying (ISSUE 14 satellite): a tcp and a dcn
    // "connection" to the SAME address are different sockets with
    // independent health state — a dcn failure never poisons the tcp
    // path, and each tier reconnects independently.
    InputMessenger m;
    EndPoint ep;
    str2endpoint("127.0.0.1:1", &ep);  // never connected (no write)
    SocketId tcp_id = INVALID_VREF_ID, dcn_id = INVALID_VREF_ID;
    ASSERT_EQ(0, SocketMap::singleton()->GetOrCreate(ep, &m, &tcp_id));
    ASSERT_EQ(0, SocketMap::singleton()->GetOrCreate(ep, &m, &dcn_id,
                                                     TierDcn()));
    EXPECT_NE(tcp_id, dcn_id);
    {
        SocketUniquePtr s;
        ASSERT_EQ(0, Socket::AddressSocket(dcn_id, &s));
        EXPECT_EQ(TierDcn(), s->transport_tier());
    }
    // Lookups are sticky per tier.
    SocketId again = INVALID_VREF_ID;
    ASSERT_EQ(0, SocketMap::singleton()->GetOrCreate(ep, &m, &again));
    EXPECT_EQ(tcp_id, again);
    ASSERT_EQ(0, SocketMap::singleton()->GetOrCreate(ep, &m, &again,
                                                     TierDcn()));
    EXPECT_EQ(dcn_id, again);
    // Failing the dcn socket replaces only the dcn entry; the tcp one
    // keeps its id (health state never shared across tiers).
    Socket::SetFailedById(dcn_id);
    SocketId fresh = INVALID_VREF_ID;
    ASSERT_EQ(0, SocketMap::singleton()->GetOrCreate(ep, &m, &fresh,
                                                     TierDcn()));
    EXPECT_NE(dcn_id, fresh);
    ASSERT_EQ(0, SocketMap::singleton()->GetOrCreate(ep, &m, &again));
    EXPECT_EQ(tcp_id, again);
    Socket::SetFailedById(tcp_id);
    Socket::SetFailedById(fresh);
    SocketMap::singleton()->Remove(ep, tcp_id);
    SocketMap::singleton()->Remove(ep, fresh, TierDcn());
}
