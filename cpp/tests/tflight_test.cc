// Flight recorder (tbase/flight_recorder.h): record/dump round trips, ring
// wrap accounting, the disabled gate, and the crash black box — a forked
// child dies on a real SIGSEGV (raw, and via a chaos crash=1 plan) and the
// parent asserts the signal handler left a parseable TFRBOX1 dump behind.
//
// Fork discipline: the child never takes a lock (no flag .set, no malloc
// after the write burst) — crash-handler work is open/write/close, which is
// the async-signal-safe contract the handler itself lives under. All flag
// mutation happens in the parent, before fork.
#include <signal.h>
#include <sys/wait.h>
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "tbase/endpoint.h"
#include "tbase/flags.h"
#include "tbase/flight_recorder.h"
#include "tnet/fault_injection.h"
#include "ttest/ttest.h"

using namespace tpurpc;

DECLARE_bool(flight_recorder_enabled);
DECLARE_int64(flight_recorder_ring);
DECLARE_string(flight_blackbox_path);
DECLARE_bool(chaos_enabled);
DECLARE_int64(chaos_seed);
DECLARE_string(chaos_plan);
DECLARE_string(chaos_peers);

namespace {

// Local mirrors of the dump format (flight_recorder.cc keeps the structs
// private; the sizes are part of the TFRBOX1 wire contract with
// tools/blackbox_merge.py, so asserting them here is the point).
struct FileHeaderMirror {
    char magic[8];
    uint32_t version;
    uint32_t pid;
    int64_t wall_us;
    int64_t mono_us;
    uint64_t tsc;
    double ticks_per_us;
    int64_t dump_mono_us;
    uint64_t dump_tsc;
    uint32_t nrings;
    uint32_t reserved;
    char node[64];
};
static_assert(sizeof(FileHeaderMirror) == 136, "TFRBOX1 header wire size");

struct RingHeaderMirror {
    char magic[8];
    uint32_t tid;
    uint32_t cap;
    uint64_t next;
    uint32_t nvalid;
    uint32_t reserved;
    char name[16];
};
static_assert(sizeof(RingHeaderMirror) == 48, "TFRRING header wire size");

bool ReadFileBytes(const std::string& path, std::vector<char>* out) {
    FILE* f = fopen(path.c_str(), "rb");
    if (f == nullptr) return false;
    char buf[4096];
    size_t n;
    while ((n = fread(buf, 1, sizeof(buf), f)) > 0) {
        out->insert(out->end(), buf, buf + n);
    }
    fclose(f);
    return true;
}

// Parse a binary dump; returns every event (the same reconstruction
// blackbox_merge.py does: walk [next-nvalid, next), drop torn slots).
bool ParseDump(const std::vector<char>& data, FileHeaderMirror* hdr,
               std::vector<flight::internal::Event>* events) {
    if (data.size() < sizeof(FileHeaderMirror)) return false;
    memcpy(hdr, data.data(), sizeof(*hdr));
    if (memcmp(hdr->magic, "TFRBOX1\0", 8) != 0) return false;
    size_t off = sizeof(FileHeaderMirror);
    for (uint32_t r = 0; r < hdr->nrings; ++r) {
        if (off + sizeof(RingHeaderMirror) > data.size()) return false;
        RingHeaderMirror rh;
        memcpy(&rh, data.data() + off, sizeof(rh));
        if (memcmp(rh.magic, "TFRRING\0", 8) != 0) return false;
        off += sizeof(rh);
        std::vector<flight::internal::Event> slots(rh.nvalid);
        const size_t bytes = rh.nvalid * sizeof(flight::internal::Event);
        if (off + bytes > data.size()) return false;
        if (rh.nvalid > 0) memcpy(slots.data(), data.data() + off, bytes);
        off += bytes;
        for (uint64_t s = rh.next - rh.nvalid; s < rh.next; ++s) {
            const auto& e = slots[s & (rh.cap - 1)];
            if (e.seq == (uint32_t)s) events->push_back(e);
        }
    }
    return true;
}

std::string TempPath(const char* tag) {
    char buf[128];
    snprintf(buf, sizeof(buf), "/tmp/tflight_%s_%d.bin", tag, (int)getpid());
    return buf;
}

// Deliberate UB: the crash drills need a GENUINE SIGSEGV through the
// fatal-signal handler, so keep fatal-UBSan builds from aborting first.
#if defined(__clang__) || defined(__GNUC__)
__attribute__((no_sanitize("undefined")))
#endif
void CrashWithRealSegv() {
    *(volatile int*)0 = 0;
}

struct ChaosOff {
    ~ChaosOff() {
        FLAGS_chaos_plan.set("");
        FLAGS_chaos_peers.set("");
        FLAGS_chaos_seed.set(1);
        FLAGS_chaos_enabled.set(false);
    }
};

}  // namespace

TEST(FlightRecorder, RecordDumpRoundTrip) {
    flight::SetNodeName("tflight-unit");
    // Distinctive payloads so we can find OUR events among whatever the
    // instrumented seams of co-resident suites recorded.
    const uint64_t kA = 0xf11A57ull;
    flight::Record(flight::kLeasePin, kA, 111);
    flight::Record(flight::kLeaseRelease, kA, 222);
    std::thread t([&] { flight::Record(flight::kStreamChunk, kA, 333); });
    t.join();
    EXPECT_GE(flight::TotalEvents(), 3u);

    const std::string path = TempPath("roundtrip");
    ASSERT_TRUE(flight::DumpToFile(path));
    std::vector<char> data;
    ASSERT_TRUE(ReadFileBytes(path, &data));
    FileHeaderMirror hdr;
    std::vector<flight::internal::Event> events;
    ASSERT_TRUE(ParseDump(data, &hdr, &events));
    EXPECT_EQ(1u, hdr.version);
    EXPECT_EQ((uint32_t)getpid(), hdr.pid);
    EXPECT_EQ(0, strcmp(hdr.node, "tflight-unit"));
    EXPECT_GE(hdr.nrings, 2u);  // this thread + the spawned one
    EXPECT_TRUE(hdr.ticks_per_us > 0.0);
    int pin = 0, rel = 0, chunk = 0;
    for (const auto& e : events) {
        if (e.a != kA) continue;
        if (e.kind == flight::kLeasePin && e.b == 111) ++pin;
        if (e.kind == flight::kLeaseRelease && e.b == 222) ++rel;
        if (e.kind == flight::kStreamChunk && e.b == 333) ++chunk;
    }
    EXPECT_EQ(1, pin);
    EXPECT_EQ(1, rel);
    EXPECT_EQ(1, chunk);
    unlink(path.c_str());
}

TEST(FlightRecorder, RingWrapKeepsNewestAndCountsDropped) {
    // The ring-size flag applies to rings registered AFTER the change:
    // exercise it on a fresh thread.
    const int64_t old_ring = FLAGS_flight_recorder_ring.get();
    FLAGS_flight_recorder_ring.set(64);
    const uint64_t before_dropped = flight::TotalDropped();
    std::thread t([] {
        for (uint64_t i = 0; i < 200; ++i) {
            flight::Record(flight::kStreamChunk, 0x3A9ull, i);
        }
    });
    t.join();
    FLAGS_flight_recorder_ring.set(old_ring);
    // 200 events into a 64-slot ring: at least 136 overwritten.
    EXPECT_GE(flight::TotalDropped(), before_dropped + 136);
    EXPECT_GE(flight::RingHighwater(), 64u);

    const std::string path = TempPath("wrap");
    ASSERT_TRUE(flight::DumpToFile(path));
    std::vector<char> data;
    ASSERT_TRUE(ReadFileBytes(path, &data));
    FileHeaderMirror hdr;
    std::vector<flight::internal::Event> events;
    ASSERT_TRUE(ParseDump(data, &hdr, &events));
    uint64_t lo = UINT64_MAX, hi = 0, n = 0;
    for (const auto& e : events) {
        if (e.kind != flight::kStreamChunk || e.a != 0x3A9ull) continue;
        if (e.b < lo) lo = e.b;
        if (e.b > hi) hi = e.b;
        ++n;
    }
    // The wrapped ring holds exactly the newest 64 of the 200.
    EXPECT_EQ(64u, n);
    EXPECT_EQ(199u, hi);
    EXPECT_EQ(136u, lo);
    unlink(path.c_str());
}

TEST(FlightRecorder, DisabledGateRecordsNothing) {
    FLAGS_flight_recorder_enabled.set(false);
    flight::Record(flight::kLeaseArm, 0xD15AB1Eull, 1);
    FLAGS_flight_recorder_enabled.set(true);
    flight::Record(flight::kLeaseArm, 0xE4AB1Eull, 2);
    std::string json;
    flight::DumpJson(&json);
    EXPECT_EQ(std::string::npos, json.find("219523870"));  // 0xD15AB1E
    EXPECT_NE(std::string::npos, json.find("14986014"));   // 0xE4AB1E
}

TEST(FlightRecorder, JsonAndTextShape) {
    flight::Record(flight::kCollReform, 7, 4);
    std::string json;
    flight::DumpJson(&json);
    EXPECT_NE(std::string::npos, json.find("\"node\":"));
    EXPECT_NE(std::string::npos, json.find("\"ticks_per_us\":"));
    EXPECT_NE(std::string::npos, json.find("\"rings\":["));
    EXPECT_NE(std::string::npos, json.find("\"kind\":\"COLL_REFORM\""));
    // Balanced JSON (cheap structural check; the real parse happens in
    // tests/test_blackbox_forensics.py via json.loads).
    int depth = 0;
    bool in_str = false, esc = false;
    for (char c : json) {
        if (esc) { esc = false; continue; }
        if (c == '\\') { esc = true; continue; }
        if (c == '"') { in_str = !in_str; continue; }
        if (in_str) continue;
        if (c == '{' || c == '[') ++depth;
        if (c == '}' || c == ']') --depth;
    }
    EXPECT_EQ(0, depth);
    EXPECT_FALSE(in_str);

    std::string text;
    flight::DumpText(&text);
    EXPECT_NE(std::string::npos, text.find("flight recorder:"));
    EXPECT_NE(std::string::npos, text.find("COLL_REFORM"));
}

TEST(FlightRecorder, CrashHandlerDumpsOnSegv) {
    const std::string path = TempPath("crash");
    unlink(path.c_str());
    // Parent installs (flag .set takes a lock — never do it post-fork).
    flight::InstallCrashHandler(path);
    const pid_t pid = fork();
    ASSERT_GE(pid, 0);
    if (pid == 0) {
        flight::Record(flight::kVerbPost, 0xDEADull, (2ull << 32) | 64);
        CrashWithRealSegv();
        _exit(99);  // unreachable
    }
    int status = 0;
    ASSERT_EQ(pid, waitpid(pid, &status, 0));
    // The handler re-raises with SIG_DFL: the exit status reports the
    // ORIGINAL signal, not a masked exit code.
    ASSERT_TRUE(WIFSIGNALED(status));
    EXPECT_EQ(SIGSEGV, WTERMSIG(status));
    std::vector<char> data;
    ASSERT_TRUE(ReadFileBytes(path, &data));
    FileHeaderMirror hdr;
    std::vector<flight::internal::Event> events;
    ASSERT_TRUE(ParseDump(data, &hdr, &events));
    bool saw_post = false;
    for (const auto& e : events) {
        if (e.kind == flight::kVerbPost && e.a == 0xDEADull) saw_post = true;
    }
    EXPECT_TRUE(saw_post);
    unlink(path.c_str());
}

TEST(FlightRecorder, ChaosCrashPlanLeavesBlackBox) {
    ChaosOff off;
    const std::string path = TempPath("chaoscrash");
    unlink(path.c_str());
    flight::InstallCrashHandler(path);
    // crash=1 with a bogus peer filter: only the peer-filter-bypassing
    // ops (verb post / cq complete / ring complete) consume decisions, so
    // the child's FIRST verb-post decision fires the crash and nothing in
    // the parent (which never posts verbs here) can trip it pre-fork.
    FLAGS_chaos_plan.set("crash=1");
    FLAGS_chaos_peers.set("9.9.9.9:1");
    FLAGS_chaos_seed.set(20260807);
    FLAGS_chaos_enabled.set(true);
    const pid_t pid = fork();
    ASSERT_GE(pid, 0);
    if (pid == 0) {
        EndPoint peer;
        str2endpoint("127.0.0.1:7007", &peer);
        FaultInjection::Decide(FaultOp::kVerbPost, peer, 64);  // crashes
        _exit(99);  // unreachable: crash=1 means decision 0 fires
    }
    int status = 0;
    ASSERT_EQ(pid, waitpid(pid, &status, 0));
    FLAGS_chaos_enabled.set(false);
    ASSERT_TRUE(WIFSIGNALED(status));
    EXPECT_EQ(SIGSEGV, WTERMSIG(status));
    std::vector<char> data;
    ASSERT_TRUE(ReadFileBytes(path, &data));
    FileHeaderMirror hdr;
    std::vector<flight::internal::Event> events;
    ASSERT_TRUE(ParseDump(data, &hdr, &events));
    // The chaos event is stamped BEFORE the null write: the black box
    // must carry the injection that killed the process, with the crash
    // action kind in the packed b field.
    bool saw_chaos = false;
    for (const auto& e : events) {
        if (e.kind == flight::kChaosInject &&
            (e.b & 0xff) == (uint64_t)FaultAction::kCrash) {
            saw_chaos = true;
        }
    }
    EXPECT_TRUE(saw_chaos);
    unlink(path.c_str());
}

TEST(FlightRecorder, DumpToConfiguredPathFollowsFlag) {
    const std::string path = TempPath("configured");
    unlink(path.c_str());
    FLAGS_flight_blackbox_path.set(path);
    const uint64_t dumps_before = flight::DumpCount();
    EXPECT_TRUE(flight::DumpToConfiguredPath());
    EXPECT_EQ(dumps_before + 1, flight::DumpCount());
    std::vector<char> data;
    ASSERT_TRUE(ReadFileBytes(path, &data));
    EXPECT_GE(data.size(), sizeof(FileHeaderMirror));
    EXPECT_EQ(0, memcmp(data.data(), "TFRBOX1\0", 8));
    unlink(path.c_str());
    FLAGS_flight_blackbox_path.set("");
    EXPECT_FALSE(flight::DumpToConfiguredPath());
}
