// ICI transport tests: the fake-ICI loopback link plays the role loopback
// TCP plays in the reference's tests (SURVEY §4: "a fake/loopback ICI
// endpoint plays the role loopback TCP plays"). Covers the block pool,
// the queue-pair data path, credit flow control, event suppression, EOF,
// and a full RPC echo over the link.
#include <unistd.h>

#include <atomic>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "echo.pb.h"
#include "rpc_meta.pb.h"
#include "tbase/crc32c.h"
#include "tbase/iobuf.h"
#include "tbase/errno.h"
#include "tbase/fast_rand.h"
#include "tbase/flags.h"
#include "tbase/time.h"
#include "tfiber/fiber.h"
#include "tfiber/fiber_sync.h"
#include "tici/block_lease.h"
#include "tici/block_pool.h"
#include "tici/ici_link.h"
#include "tnet/socket.h"
#include "trpc/channel.h"
#include "trpc/controller.h"
#include "trpc/pb_compat.h"
#include "trpc/policy_tpu_std.h"
#include "trpc/server.h"
#include "ttest/ttest.h"

using namespace tpurpc;

namespace {

// Pump endpoint `e` into `portal` until `want` bytes arrived (poll-style,
// for link-level tests that bypass the dispatcher).
ssize_t pump_until(IciEndpoint* e, IOPortal* portal, size_t want) {
    ssize_t total = 0;
    for (int spins = 0; spins < 100000 && (size_t)total < want; ++spins) {
        const ssize_t nr = e->Pump(portal);
        if (nr > 0) {
            total += nr;
        } else if (nr == 0) {
            return total;  // EOF
        }
    }
    return total;
}

}  // namespace

TEST(IciBlockPool, InstallsAndServesRegisteredMemory) {
    ASSERT_EQ(0, IciBlockPool::Init());
    ASSERT_TRUE(IciBlockPool::initialized());
    // New IOBuf blocks now come from registered regions.
    IOBuf buf;
    buf.append(std::string(100, 'x'));
    size_t len = 0;
    const char* p = buf.backing_block_data(0, &len);
    EXPECT_TRUE(IciBlockPool::Contains(p));
    EXPECT_EQ(100u, len);
    // Odd-size direct allocation round-trips too.
    void* odd = IciBlockPool::Allocate(123456);
    ASSERT_TRUE(odd != nullptr);
    IciBlockPool::Deallocate(odd);
}

TEST(IciLink, BytesFlowBothWays) {
    IciLink& link = *IciLink::Create();
    IOBuf msg;
    msg.append("hello over ici");
    IOBuf* pieces[1] = {&msg};
    ASSERT_EQ((ssize_t)14, link.first()->CutFromIOBufList(pieces, 1));
    EXPECT_TRUE(msg.empty());

    IOPortal in;
    ASSERT_EQ((ssize_t)14, pump_until(link.second(), &in, 14));
    EXPECT_TRUE(in.equals("hello over ici"));

    // Reverse direction.
    IOBuf rev;
    rev.append("pong");
    IOBuf* rp[1] = {&rev};
    ASSERT_EQ((ssize_t)4, link.second()->CutFromIOBufList(rp, 1));
    IOPortal rin;
    ASSERT_EQ((ssize_t)4, pump_until(link.first(), &rin, 4));
    EXPECT_TRUE(rin.equals("pong"));
    link.first()->Release();
    link.second()->Release();
}

TEST(IciLink, LargeTransferSurvivesWindowRecycling) {
    // 8MB >> the 256-descriptor window: requires credits to recycle.
    IciLink& link = *IciLink::Create();
    const size_t kTotal = 8u << 20;
    std::string big(kTotal, 0);
    for (size_t i = 0; i < kTotal; ++i) big[i] = (char)(i * 1315423911u >> 7);
    IOBuf src;
    src.append(big);

    std::atomic<bool> done{false};
    std::string got;
    got.reserve(kTotal);
    // Consumer fiber: pump into a portal, drain to string.
    struct Ctx {
        IciLink* link;
        std::string* got;
        size_t want;
        std::atomic<bool>* done;
    } ctx{&link, &got, kTotal, &done};
    fiber_t consumer;
    fiber_start_background(
        &consumer, nullptr,
        [](void* a) -> void* {
            Ctx* c = (Ctx*)a;
            IOPortal in;
            while (c->got->size() < c->want) {
                const ssize_t nr = c->link->second()->Pump(&in);
                if (nr > 0) {
                    std::string chunk;
                    in.cutn(&chunk, in.size());
                    c->got->append(chunk);
                } else if (nr == 0) {
                    break;
                } else {
                    fiber_usleep(100);
                }
            }
            c->done->store(true);
            return nullptr;
        },
        &ctx);

    // Producer: post with window waits.
    IOBuf* pieces[1] = {&src};
    while (!src.empty()) {
        const ssize_t nw = link.first()->CutFromIOBufList(pieces, 1);
        if (nw < 0 && errno == EAGAIN) {
            ASSERT_EQ(0, link.first()->WaitWritable(monotonic_time_us() +
                                                    2 * 1000 * 1000));
        } else {
            ASSERT_GT(nw, 0);
        }
    }
    fiber_join(consumer, nullptr);
    ASSERT_TRUE(done.load());
    ASSERT_EQ(kTotal, got.size());
    EXPECT_EQ(0, memcmp(got.data(), big.data(), kTotal));
    link.first()->Release();
    link.second()->Release();
}

TEST(IciLink, EventSuppressionBatchesDoorbells) {
    IciLink& link = *IciLink::Create();
    // Burst of 50 posts with no consumer arm/drain in between: the
    // doorbell fires once for the burst, not 50 times.
    for (int i = 0; i < 50; ++i) {
        IOBuf m;
        m.append("x");
        IOBuf* p[1] = {&m};
        ASSERT_EQ((ssize_t)1, link.first()->CutFromIOBufList(p, 1));
    }
    EXPECT_EQ(1u, link.first()->signals_sent());
    IOPortal in;
    EXPECT_EQ((ssize_t)50, pump_until(link.second(), &in, 50));
    link.first()->Release();
    link.second()->Release();
}

TEST(IciLink, CloseDeliversEofAfterDrain) {
    IciLink& link = *IciLink::Create();
    IOBuf m;
    m.append("last words");
    IOBuf* p[1] = {&m};
    ASSERT_EQ((ssize_t)10, link.first()->CutFromIOBufList(p, 1));
    link.first()->Close();
    IOPortal in;
    // Data still delivered...
    ASSERT_EQ((ssize_t)10, pump_until(link.second(), &in, 10));
    EXPECT_TRUE(in.equals("last words"));
    // ...then EOF.
    EXPECT_EQ((ssize_t)0, link.second()->Pump(&in));
    // Writes now fail.
    IOBuf m2;
    m2.append("x");
    IOBuf* p2[1] = {&m2};
    EXPECT_EQ((ssize_t)-1, link.second()->CutFromIOBufList(p2, 1));
    link.first()->Release();
    link.second()->Release();
}

// ---------------- slab-class allocator (ISSUE 9c) ----------------

TEST(SlabPool, ClassesGrowAndRecycle) {
    ASSERT_EQ(0, IciBlockPool::Init());
    // Size -> class mapping across the ladder.
    EXPECT_EQ(0, IciBlockPool::SlabClassOf(1));
    EXPECT_EQ(0, IciBlockPool::SlabClassOf(8u << 10));
    EXPECT_EQ(1, IciBlockPool::SlabClassOf((8u << 10) + 1));
    EXPECT_EQ(2, IciBlockPool::SlabClassOf(100u << 10));
    EXPECT_EQ(3, IciBlockPool::SlabClassOf(1u << 20));
    EXPECT_EQ(4, IciBlockPool::SlabClassOf(4u << 20));
    EXPECT_EQ(-1, IciBlockPool::SlabClassOf((4u << 20) + 1));

    // Grow: a fresh slot, registered memory, live count up.
    const size_t live0 = IciBlockPool::slab_allocated();
    void* a = IciBlockPool::AllocateSlab(5000);
    ASSERT_TRUE(a != nullptr);
    EXPECT_TRUE(IciBlockPool::Contains(a));
    EXPECT_EQ(live0 + 1, IciBlockPool::slab_allocated());

    // Recycle: free then realloc the same class returns the cached slot
    // (TLS cache is LIFO) and bumps the recycle counter.
    const size_t rec0 = IciBlockPool::slab_recycled();
    IciBlockPool::FreeSlab(a);
    EXPECT_EQ(live0, IciBlockPool::slab_allocated());
    void* b = IciBlockPool::AllocateSlab(6000);
    EXPECT_EQ(a, b);
    EXPECT_EQ(rec0 + 1, IciBlockPool::slab_recycled());
    IciBlockPool::FreeSlab(b);

    // Distinct classes never alias each other's slots.
    void* small = IciBlockPool::AllocateSlab(100);
    void* big = IciBlockPool::AllocateSlab(60u << 10);
    EXPECT_TRUE(small != big);
    IciBlockPool::FreeSlab(small);
    IciBlockPool::FreeSlab(big);

    // Oversized requests fall back to carve-only registered chunks:
    // non-null, registered, and FreeSlab is a safe no-op on them.
    void* huge = IciBlockPool::AllocateSlab(5u << 20);
    ASSERT_TRUE(huge != nullptr);
    EXPECT_TRUE(IciBlockPool::Contains(huge));
    IciBlockPool::FreeSlab(huge);
}

TEST(SlabPool, PerThreadCacheKeepsClassMutexCold) {
    ASSERT_EQ(0, IciBlockPool::Init());
    // Prime every thread's cache, then hammer alloc/free: steady-state
    // traffic must run out of the TLS cache, not the class mutex.
    constexpr int kThreads = 8;
    constexpr int kOps = 2000;
    const size_t mu0 = IciBlockPool::slab_mutex_acquisitions();
    const size_t rec0 = IciBlockPool::slab_recycled();
    std::vector<std::thread> threads;
    for (int t = 0; t < kThreads; ++t) {
        threads.emplace_back([] {
            for (int i = 0; i < kOps; ++i) {
                void* p = IciBlockPool::AllocateSlab(4096);
                ASSERT_TRUE(p != nullptr);
                memset(p, 0xAB, 64);
                IciBlockPool::FreeSlab(p);
            }
        });
    }
    for (auto& th : threads) th.join();
    const size_t mutex_touches =
        IciBlockPool::slab_mutex_acquisitions() - mu0;
    const size_t recycled = IciBlockPool::slab_recycled() - rec0;
    // kThreads*kOps operations; all but the cold-start allocations (and
    // the thread-exit cache drains) must recycle without the mutex.
    EXPECT_GE(recycled, (size_t)(kThreads * kOps - kThreads * 2));
    EXPECT_LE(mutex_touches, (size_t)(kThreads * 4));
}

// ---------------- device staging ring (ISSUE 9a) ----------------

TEST(DeviceStagingRing, FifoAcquireCompleteOrderingUnder8Threads) {
    ASSERT_EQ(0, IciBlockPool::Init());
    DeviceStagingRing* ring = DeviceStagingRing::Create(4, 60u << 10);
    ASSERT_TRUE(ring != nullptr);
    EXPECT_EQ(4u, ring->depth());
    constexpr int kThreads = 8;
    constexpr int kPerThread = 200;
    std::atomic<int> inflight{0};
    std::atomic<int> max_inflight{0};
    std::atomic<int> failures{0};
    std::vector<std::thread> threads;
    for (int t = 0; t < kThreads; ++t) {
        threads.emplace_back([&, t] {
            for (int i = 0; i < kPerThread; ++i) {
                const int slot = ring->Acquire(5 * 1000 * 1000);
                if (slot < 0) {
                    failures.fetch_add(1);
                    return;
                }
                const int now = inflight.fetch_add(1) + 1;
                int prev = max_inflight.load();
                while (now > prev &&
                       !max_inflight.compare_exchange_weak(prev, now)) {
                }
                // Touch the slot, with jitter so completes go out of
                // acquire order routinely.
                memset(ring->slot((uint32_t)slot), t, 256);
                if (fast_rand() % 4 == 0) usleep(fast_rand() % 300);
                inflight.fetch_sub(1);
                if (ring->Complete((uint32_t)slot) != 0) {
                    failures.fetch_add(1);
                    return;
                }
            }
        });
    }
    for (auto& th : threads) th.join();
    EXPECT_EQ(0, failures.load());
    // Window never exceeded depth, and every acquire completed.
    EXPECT_LE(max_inflight.load(), 4);
    EXPECT_EQ((uint64_t)(kThreads * kPerThread), ring->acquires());
    EXPECT_EQ((uint64_t)(kThreads * kPerThread), ring->completes());
    EXPECT_LE(ring->inflight_highwater(), 4u);
    // Double-complete of an idle slot is rejected.
    EXPECT_EQ(-1, ring->Complete(0));
    delete ring;
}

// ---------------- one-sided pool descriptors (ISSUE 9b) ----------------

TEST(PoolDescriptor, MetaFrameParseRoundTrip) {
    ASSERT_EQ(0, IciBlockPool::Init());
    ASSERT_NE(0ull, IciBlockPool::pool_id());
    // Stage descriptor-eligible bytes in the shared pool.
    IOBuf att;
    char* data = nullptr;
    ASSERT_TRUE(IciBlockPool::AllocatePoolAttachment(50000, &att, &data));
    memset(data, 'd', 50000);
    uint64_t off = 0;
    ASSERT_TRUE(IciBlockPool::OffsetOf(data, &off));
    const uint32_t crc = crc32c_extend(0, data, 50000);

    // Frame a descriptor-carrying meta (header + meta ONLY — no
    // attachment bytes in the body)...
    rpc::RpcMeta meta;
    meta.set_correlation_id(77);
    auto* pd = meta.mutable_pool_attachment();
    pd->set_pool_id(IciBlockPool::pool_id());
    pd->set_offset(off);
    pd->set_length(50000);
    pd->set_crc32c(crc);
    IOBuf meta_buf;
    ASSERT_TRUE(SerializePbToIOBuf(meta, &meta_buf));
    IOBuf frame;
    PackTpuStdFrame(&frame, meta_buf, IOBuf(), IOBuf());
    EXPECT_LT(frame.size(), (size_t)256);  // tiny wire frame for 50KB

    // ...parse it back and resolve the descriptor against the registry.
    ParseResult r = ParseTpuStdMessage(&frame, nullptr, false, nullptr);
    ASSERT_TRUE(r.error == ParseError::OK);
    std::unique_ptr<TpuStdMessage> msg((TpuStdMessage*)r.msg);
    rpc::RpcMeta parsed;
    ASSERT_TRUE(ParsePbFromIOBuf(&parsed, msg->meta));
    ASSERT_TRUE(parsed.has_pool_attachment());
    EXPECT_EQ(IciBlockPool::pool_id(), parsed.pool_attachment().pool_id());
    EXPECT_EQ(off, parsed.pool_attachment().offset());
    EXPECT_EQ(50000ull, parsed.pool_attachment().length());
    const char* base = nullptr;
    size_t psize = 0;
    ASSERT_TRUE(pool_registry::Resolve(parsed.pool_attachment().pool_id(),
                                       &base, &psize));
    ASSERT_LE(parsed.pool_attachment().offset() +
                  parsed.pool_attachment().length(),
              psize);
    // The resolved view IS the staged memory (zero-copy), and its bytes
    // hash to the descriptor's crc.
    EXPECT_EQ((const void*)data,
              (const void*)(base + parsed.pool_attachment().offset()));
    EXPECT_EQ(crc, crc32c_extend(0, base + parsed.pool_attachment().offset(),
                                 parsed.pool_attachment().length()));
}

namespace {

// Echo service reading the one-sided attachment IN PLACE: proves the
// view points into this process's registered pool and that no inline
// copy of the bytes arrived, then answers with the crc it computed.
class PoolDescEchoService : public test::EchoService {
public:
    void Echo(google::protobuf::RpcController* cntl_base,
              const test::EchoRequest* req, test::EchoResponse* res,
              google::protobuf::Closure* done) override {
        Controller* cntl = static_cast<Controller*>(cntl_base);
        const Controller::PoolAttachment& pa =
            cntl->request_pool_attachment();
        last_view_in_pool.store(pa.data != nullptr &&
                                IciBlockPool::Contains(pa.data));
        last_inline_bytes.store(
            (int64_t)cntl->request_attachment().size());
        if (pa.data != nullptr) {
            res->set_message(std::to_string(
                crc32c_extend(0, pa.data, pa.length)));
        } else {
            res->set_message("no descriptor");
        }
        done->Run();
    }
    std::atomic<bool> last_view_in_pool{false};
    std::atomic<int64_t> last_inline_bytes{-1};
};

}  // namespace

TEST(PoolDescriptor, RpcZeroCopyOverIciLink) {
    ASSERT_EQ(0, IciBlockPool::Init());
    PoolDescEchoService service;
    Server server;
    ASSERT_EQ(0, server.AddService(&service));
    ASSERT_EQ(0, server.StartNoListen(nullptr));

    IciLink& link = *IciLink::Create();
    SocketOptions sopts;
    sopts.fd = link.second()->event_fd();
    sopts.transport = link.second();
    sopts.owns_transport = true;
    sopts.on_edge_triggered_events = InputMessenger::OnNewMessages;
    sopts.user = server.messenger();
    SocketId server_sid;
    ASSERT_EQ(0, Socket::Create(sopts, &server_sid));
    SocketOptions copts;
    copts.fd = link.first()->event_fd();
    copts.transport = link.first();
    copts.owns_transport = true;
    copts.on_edge_triggered_events = InputMessenger::OnNewMessages;
    copts.user = Channel::client_messenger();
    SocketId client_sid;
    ASSERT_EQ(0, Socket::Create(copts, &client_sid));
    Channel channel;
    ChannelOptions chopts;
    chopts.timeout_ms = 5000;
    ASSERT_EQ(0, channel.InitWithSocketId(client_sid, &chopts));
    test::EchoService_Stub stub(&channel);

    const size_t kBytes = 60000;
    const size_t live0 = IciBlockPool::slab_allocated();
    IOBuf att;
    char* data = nullptr;
    ASSERT_TRUE(IciBlockPool::AllocatePoolAttachment(kBytes, &att, &data));
    for (size_t i = 0; i < kBytes; ++i) data[i] = (char)(i * 31 >> 3);
    const uint32_t crc = crc32c_extend(0, data, kBytes);

    Controller cntl;
    cntl.set_request_pool_attachment(std::move(att));
    ASSERT_TRUE(cntl.has_request_pool_attachment());
    test::EchoRequest req;
    test::EchoResponse res;
    req.set_message("desc");
    stub.Echo(&cntl, &req, &res, nullptr);
    ASSERT_FALSE(cntl.Failed());
    // The server computed the crc from the IN-PLACE view (inside this
    // process's registered pool — loopback link, one address space) and
    // saw ZERO inline attachment bytes: the payload was never
    // duplicated host-side.
    EXPECT_EQ(std::to_string(crc), res.message());
    EXPECT_TRUE(service.last_view_in_pool.load());
    EXPECT_EQ((int64_t)0, service.last_inline_bytes.load());
    // Completion returned the pinned block to the owner's pool: the
    // slab live count is back at its baseline (EndRPC ran before the
    // sync stub returned).
    EXPECT_EQ(live0, IciBlockPool::slab_allocated());

    SocketUniquePtr cs;
    ASSERT_EQ(0, Socket::AddressSocket(client_sid, &cs));
    cs->SetFailedWithError(TERR_CLOSE);
    cs.reset();
    server.Stop();
    server.Join();
}

// ---------------- block leases + epoch fencing (ISSUE 10) ----------------

TEST(BlockLease, ExactlyOnceReleaseAndExpiryReap) {
    ASSERT_EQ(0, IciBlockPool::Init());
    const size_t live0 = IciBlockPool::slab_allocated();

    // Pin -> exactly-once release: the second Release is a counted
    // no-op, never a double free (the EndRPC/retry/backup guarantee).
    IOBuf att;
    char* data = nullptr;
    ASSERT_TRUE(IciBlockPool::AllocatePoolAttachment(10000, &att, &data));
    const uint64_t pinned0 = block_lease::pinned();
    const uint64_t lease = block_lease::Pin(std::move(att));
    ASSERT_NE(0ull, lease);
    EXPECT_TRUE(block_lease::Alive(lease));
    EXPECT_EQ(pinned0 + 1, block_lease::pinned());
    EXPECT_EQ(live0 + 1, IciBlockPool::slab_allocated());
    EXPECT_TRUE(block_lease::Release(lease));
    EXPECT_FALSE(block_lease::Release(lease));  // exactly once
    EXPECT_FALSE(block_lease::Alive(lease));
    EXPECT_EQ(pinned0, block_lease::pinned());
    EXPECT_EQ(live0, IciBlockPool::slab_allocated());

    // Expiry reap: an armed lease whose deadline passed is reclaimed by
    // the reaper; a later (late) Release finds nothing.
    IOBuf att2;
    ASSERT_TRUE(IciBlockPool::AllocatePoolAttachment(10000, &att2, &data));
    const uint64_t l2 = block_lease::Pin(std::move(att2));
    block_lease::Arm(l2, /*call_id=*/42,
                     monotonic_time_us() - 10 * 1000 * 1000, /*peer=*/0);
    const uint64_t reaped0 = block_lease::expired_reaped();
    EXPECT_GE(block_lease::ReapExpired(monotonic_time_us()), (size_t)1);
    EXPECT_EQ(reaped0 + 1, block_lease::expired_reaped());
    EXPECT_FALSE(block_lease::Alive(l2));
    EXPECT_FALSE(block_lease::Release(l2));  // reaper got there first
    EXPECT_EQ(live0, IciBlockPool::slab_allocated());

    // A fresh (never-Armed) pin carries the DEFAULT lifetime from the
    // moment of the pin — alive now, reapable once -pool_lease_default_ms
    // passes: there is no unreapable pin state, even when the owner
    // dies before Arm.
    IOBuf att3;
    ASSERT_TRUE(IciBlockPool::AllocatePoolAttachment(10000, &att3, &data));
    const uint64_t l3 = block_lease::Pin(std::move(att3));
    EXPECT_EQ((size_t)0, block_lease::ReapExpired(monotonic_time_us()));
    EXPECT_TRUE(block_lease::Alive(l3));
    EXPECT_GE(block_lease::ReapExpired(monotonic_time_us() +
                                       (int64_t)3600e6),
              (size_t)1);  // way past the default window
    EXPECT_FALSE(block_lease::Alive(l3));
    EXPECT_FALSE(block_lease::Release(l3));
    EXPECT_EQ(live0, IciBlockPool::slab_allocated());
}

TEST(BlockLease, BackupTryHoldsBothPeersEntitled) {
    ASSERT_EQ(0, IciBlockPool::Init());
    const size_t live0 = IciBlockPool::slab_allocated();
    char* data = nullptr;
    IOBuf att;
    ASSERT_TRUE(IciBlockPool::AllocatePoolAttachment(8000, &att, &data));
    const uint64_t l = block_lease::Pin(std::move(att));
    const int64_t dl = monotonic_time_us() + (int64_t)60e6;
    // Try 1 posts on socket 111; the backup try ADDS socket 222.
    ASSERT_TRUE(block_lease::Arm(l, 1, dl, 111, /*add_peer=*/false));
    ASSERT_TRUE(block_lease::Arm(l, 1, dl, 222, /*add_peer=*/true));
    // The backup's peer dies: the ORIGINAL try's server may still be
    // reading the block — the pin must survive.
    EXPECT_EQ((size_t)0, block_lease::ReleasePeer(222));
    EXPECT_TRUE(block_lease::Alive(l));
    // Once the last entitled peer is gone, the pin frees.
    EXPECT_EQ((size_t)1, block_lease::ReleasePeer(111));
    EXPECT_FALSE(block_lease::Alive(l));
    EXPECT_EQ(live0, IciBlockPool::slab_allocated());

    // A RETRY (add_peer=false) replaces the key: the old socket's death
    // then frees nothing.
    IOBuf att2;
    ASSERT_TRUE(IciBlockPool::AllocatePoolAttachment(8000, &att2, &data));
    const uint64_t l2 = block_lease::Pin(std::move(att2));
    ASSERT_TRUE(block_lease::Arm(l2, 2, dl, 111, false));
    ASSERT_TRUE(block_lease::Arm(l2, 2, dl, 333, false));
    EXPECT_EQ((size_t)0, block_lease::ReleasePeer(111));
    EXPECT_TRUE(block_lease::Alive(l2));
    EXPECT_EQ((size_t)1, block_lease::ReleasePeer(333));
    EXPECT_EQ(live0, IciBlockPool::slab_allocated());
}

TEST(BlockLease, LateLoserAckValidatesCallAndPeer) {
    // ISSUE 16 regression: a hedged call posts the SAME pinned request
    // block to TWO peers; the winner's ack releases the lease, and the
    // LOSING try's response can land AFTER that, on a DIFFERENT
    // connection. Its drop-path ack must validate (call, peer) and can
    // never double-release — the slab may already be repinned by a
    // fresh lease when the late ack arrives.
    ASSERT_EQ(0, IciBlockPool::Init());
    const size_t live0 = IciBlockPool::slab_allocated();
    char* data = nullptr;
    IOBuf att;
    ASSERT_TRUE(IciBlockPool::AllocatePoolAttachment(8000, &att, &data));
    const uint64_t l = block_lease::Pin(std::move(att));
    const int64_t dl = monotonic_time_us() + (int64_t)60e6;
    ASSERT_TRUE(block_lease::Arm(l, 7, dl, 111, /*add_peer=*/false));
    ASSERT_TRUE(block_lease::Arm(l, 7, dl, 222, /*add_peer=*/true));
    // Wrong call id (a forged or cross-call token): frees nothing.
    EXPECT_FALSE(block_lease::ReleaseAcked(l, 8, 222));
    EXPECT_TRUE(block_lease::Alive(l));
    // Right call, NON-entitled peer: frees nothing.
    EXPECT_FALSE(block_lease::ReleaseAcked(l, 7, 999));
    EXPECT_TRUE(block_lease::Alive(l));
    // The winner (the backup try, peer 222) acks: released exactly once.
    EXPECT_TRUE(block_lease::ReleaseAcked(l, 7, 222));
    EXPECT_FALSE(block_lease::Alive(l));
    EXPECT_EQ(live0, IciBlockPool::slab_allocated());

    // Repin a fresh block — it may reuse the very slab the winner just
    // freed — then deliver the loser's LATE ack (its own peer 111, the
    // ORIGINAL call id): it must find nothing, and the new lease must
    // be untouched even from its own entitled peer under a stale call.
    IOBuf att2;
    ASSERT_TRUE(IciBlockPool::AllocatePoolAttachment(8000, &att2, &data));
    const uint64_t l2 = block_lease::Pin(std::move(att2));
    ASSERT_TRUE(block_lease::Arm(l2, 9, dl, 111, /*add_peer=*/false));
    EXPECT_FALSE(block_lease::ReleaseAcked(l, 7, 111));   // late loser
    EXPECT_TRUE(block_lease::Alive(l2));
    EXPECT_FALSE(block_lease::ReleaseAcked(l2, 7, 111));  // stale call
    EXPECT_TRUE(block_lease::Alive(l2));
    EXPECT_TRUE(block_lease::ReleaseAcked(l2, 9, 111));
    EXPECT_FALSE(block_lease::ReleaseAcked(l2, 9, 111));  // exactly once
    EXPECT_EQ(live0, IciBlockPool::slab_allocated());
}

TEST(BlockLease, PeerDeathReleasesOnlyThatPeersPins) {
    ASSERT_EQ(0, IciBlockPool::Init());
    const size_t live0 = IciBlockPool::slab_allocated();
    char* data = nullptr;
    IOBuf a1, a2;
    ASSERT_TRUE(IciBlockPool::AllocatePoolAttachment(8000, &a1, &data));
    ASSERT_TRUE(IciBlockPool::AllocatePoolAttachment(8000, &a2, &data));
    const uint64_t l1 = block_lease::Pin(std::move(a1));
    const uint64_t l2 = block_lease::Pin(std::move(a2));
    block_lease::Arm(l1, 1, monotonic_time_us() + (int64_t)60e6, 111);
    block_lease::Arm(l2, 2, monotonic_time_us() + (int64_t)60e6, 222);
    // Peer 111 dies: exactly its pin is reclaimed.
    EXPECT_EQ((size_t)1, block_lease::ReleasePeer(111));
    EXPECT_FALSE(block_lease::Alive(l1));
    EXPECT_TRUE(block_lease::Alive(l2));
    EXPECT_EQ((size_t)0, block_lease::ReleasePeer(111));  // idempotent
    EXPECT_TRUE(block_lease::Release(l2));
    EXPECT_EQ(live0, IciBlockPool::slab_allocated());
}

TEST(BlockLease, ControllerReuseReleasesExactlyOnce) {
    ASSERT_EQ(0, IciBlockPool::Init());
    const size_t live0 = IciBlockPool::slab_allocated();
    Controller cntl;
    IOBuf att;
    char* data = nullptr;
    ASSERT_TRUE(IciBlockPool::AllocatePoolAttachment(12000, &att, &data));
    cntl.set_request_pool_attachment(std::move(att));
    ASSERT_TRUE(cntl.has_request_pool_attachment());
    const uint64_t lease = cntl.pool_lease_id();
    ASSERT_NE(0ull, lease);
    EXPECT_EQ(live0 + 1, IciBlockPool::slab_allocated());
    const uint64_t released0 = block_lease::released();
    cntl.Reset();  // reuse ends the previous RPC: the pin must go
    EXPECT_FALSE(cntl.has_request_pool_attachment());
    EXPECT_EQ(released0 + 1, block_lease::released());
    EXPECT_EQ(live0, IciBlockPool::slab_allocated());
    cntl.Reset();  // second Reset: no double release
    EXPECT_EQ(released0 + 1, block_lease::released());
    EXPECT_FALSE(block_lease::Release(lease));
}

TEST(PoolEpoch, StaleEpochFailsOnlyTheCallNotTheConnection) {
    ASSERT_EQ(0, IciBlockPool::Init());
    PoolDescEchoService service;
    Server server;
    ASSERT_EQ(0, server.AddService(&service));
    ASSERT_EQ(0, server.StartNoListen(nullptr));

    IciLink& link = *IciLink::Create();
    SocketOptions sopts;
    sopts.fd = link.second()->event_fd();
    sopts.transport = link.second();
    sopts.owns_transport = true;
    sopts.on_edge_triggered_events = InputMessenger::OnNewMessages;
    sopts.user = server.messenger();
    SocketId server_sid;
    ASSERT_EQ(0, Socket::Create(sopts, &server_sid));
    SocketOptions copts;
    copts.fd = link.first()->event_fd();
    copts.transport = link.first();
    copts.owns_transport = true;
    copts.on_edge_triggered_events = InputMessenger::OnNewMessages;
    copts.user = Channel::client_messenger();
    SocketId client_sid;
    ASSERT_EQ(0, Socket::Create(copts, &client_sid));
    Channel channel;
    ChannelOptions chopts;
    chopts.timeout_ms = 5000;
    ASSERT_EQ(0, channel.InitWithSocketId(client_sid, &chopts));
    test::EchoService_Stub stub(&channel);

    const size_t live0 = IciBlockPool::slab_allocated();
    const uint64_t my_pool = IciBlockPool::pool_id();
    const uint64_t real_epoch = IciBlockPool::pool_epoch();

    // Fence the mapping at a future generation: the in-flight
    // descriptor (minted under real_epoch) must fail with the
    // RETRIABLE stale error — and ONLY the call.
    pool_registry::SetEpoch(my_pool, real_epoch + 7);
    {
        IOBuf att;
        char* data = nullptr;
        ASSERT_TRUE(
            IciBlockPool::AllocatePoolAttachment(20000, &att, &data));
        memset(data, 'e', 20000);
        Controller cntl;
        cntl.set_max_retry(0);  // deterministic: observe the raw fence
        cntl.set_request_pool_attachment(std::move(att));
        test::EchoRequest req;
        test::EchoResponse res;
        req.set_message("stale");
        stub.Echo(&cntl, &req, &res, nullptr);
        ASSERT_TRUE(cntl.Failed());
        EXPECT_EQ(TERR_STALE_EPOCH, cntl.ErrorCode());
    }
    // The pin was released (EndRPC) despite the failure.
    EXPECT_EQ(live0, IciBlockPool::slab_allocated());

    // Restore the mapping's generation: the SAME connection serves the
    // next descriptor — a stale fence never wedges or kills the link.
    pool_registry::SetEpoch(my_pool, real_epoch);
    {
        IOBuf att;
        char* data = nullptr;
        ASSERT_TRUE(
            IciBlockPool::AllocatePoolAttachment(20000, &att, &data));
        memset(data, 'f', 20000);
        const uint32_t crc = crc32c_extend(0, data, 20000);
        Controller cntl;
        cntl.set_request_pool_attachment(std::move(att));
        test::EchoRequest req;
        test::EchoResponse res;
        req.set_message("fresh");
        stub.Echo(&cntl, &req, &res, nullptr);
        ASSERT_FALSE(cntl.Failed());
        EXPECT_EQ(std::to_string(crc), res.message());
    }
    EXPECT_EQ(live0, IciBlockPool::slab_allocated());

    SocketUniquePtr cs;
    ASSERT_EQ(0, Socket::AddressSocket(client_sid, &cs));
    cs->SetFailedWithError(TERR_CLOSE);
    cs.reset();
    server.Stop();
    server.Join();
}

TEST(PoolChaos, LeakedPinIsReapedAndStaleInjectionIsRetriable) {
    ASSERT_EQ(0, IciBlockPool::Init());
    PoolDescEchoService service;
    Server server;
    ASSERT_EQ(0, server.AddService(&service));
    ASSERT_EQ(0, server.StartNoListen(nullptr));

    IciLink& link = *IciLink::Create();
    SocketOptions sopts;
    sopts.fd = link.second()->event_fd();
    sopts.transport = link.second();
    sopts.owns_transport = true;
    sopts.on_edge_triggered_events = InputMessenger::OnNewMessages;
    sopts.user = server.messenger();
    SocketId server_sid;
    ASSERT_EQ(0, Socket::Create(sopts, &server_sid));
    SocketOptions copts;
    copts.fd = link.first()->event_fd();
    copts.transport = link.first();
    copts.owns_transport = true;
    copts.on_edge_triggered_events = InputMessenger::OnNewMessages;
    copts.user = Channel::client_messenger();
    SocketId client_sid;
    ASSERT_EQ(0, Socket::Create(copts, &client_sid));
    Channel channel;
    ChannelOptions chopts;
    chopts.timeout_ms = 5000;
    ASSERT_EQ(0, channel.InitWithSocketId(client_sid, &chopts));
    test::EchoService_Stub stub(&channel);

    const size_t live0 = IciBlockPool::slab_allocated();

    // chaos_pool pool_leak=1: EndRPC "forgets" the release; the reaper
    // must reclaim the orphaned pin (the leaked-pin simulation of the
    // soak, deterministic at probability 1).
    ASSERT_TRUE(SetFlagValue("chaos_plan", "pool_leak=1"));
    ASSERT_TRUE(SetFlagValue("chaos_enabled", "1"));
    {
        IOBuf att;
        char* data = nullptr;
        ASSERT_TRUE(
            IciBlockPool::AllocatePoolAttachment(16000, &att, &data));
        memset(data, 'l', 16000);
        Controller cntl;
        cntl.set_timeout_ms(2000);
        cntl.set_request_pool_attachment(std::move(att));
        test::EchoRequest req;
        test::EchoResponse res;
        req.set_message("leak");
        stub.Echo(&cntl, &req, &res, nullptr);
        ASSERT_FALSE(cntl.Failed());
    }
    // The pin leaked past EndRPC...
    EXPECT_EQ(live0 + 1, IciBlockPool::slab_allocated());
    // ...and the reaper reclaims it once the lease (deadline + grace)
    // expires — slab live provably returns to baseline.
    EXPECT_GE(block_lease::ReapExpired(monotonic_time_us() +
                                       (int64_t)3600e6),
              (size_t)1);
    EXPECT_EQ(live0, IciBlockPool::slab_allocated());

    // chaos_pool pool_stale=1: every resolve answers the retriable
    // stale-epoch fence; the connection survives.
    ASSERT_TRUE(SetFlagValue("chaos_plan", "pool_stale=1"));
    {
        IOBuf att;
        char* data = nullptr;
        ASSERT_TRUE(
            IciBlockPool::AllocatePoolAttachment(16000, &att, &data));
        memset(data, 's', 16000);
        Controller cntl;
        cntl.set_max_retry(0);
        cntl.set_request_pool_attachment(std::move(att));
        test::EchoRequest req;
        test::EchoResponse res;
        req.set_message("stale");
        stub.Echo(&cntl, &req, &res, nullptr);
        ASSERT_TRUE(cntl.Failed());
        EXPECT_EQ(TERR_STALE_EPOCH, cntl.ErrorCode());
    }
    ASSERT_TRUE(SetFlagValue("chaos_enabled", "0"));
    ASSERT_TRUE(SetFlagValue("chaos_plan", ""));
    // Healed: the same connection carries a clean descriptor echo.
    {
        IOBuf att;
        char* data = nullptr;
        ASSERT_TRUE(
            IciBlockPool::AllocatePoolAttachment(16000, &att, &data));
        memset(data, 'h', 16000);
        const uint32_t crc = crc32c_extend(0, data, 16000);
        Controller cntl;
        cntl.set_request_pool_attachment(std::move(att));
        test::EchoRequest req;
        test::EchoResponse res;
        req.set_message("healed");
        stub.Echo(&cntl, &req, &res, nullptr);
        ASSERT_FALSE(cntl.Failed());
        EXPECT_EQ(std::to_string(crc), res.message());
    }
    EXPECT_EQ(live0, IciBlockPool::slab_allocated());

    SocketUniquePtr cs;
    ASSERT_EQ(0, Socket::AddressSocket(client_sid, &cs));
    cs->SetFailedWithError(TERR_CLOSE);
    cs.reset();
    server.Stop();
    server.Join();
}

TEST(DeviceStagingRing, AbortUnblocksParkedAcquireAndTimeoutHolds) {
    ASSERT_EQ(0, IciBlockPool::Init());
    DeviceStagingRing* ring = DeviceStagingRing::Create(1, 8192);
    ASSERT_TRUE(ring != nullptr);
    ASSERT_EQ(0, ring->Acquire(-1));  // window now full
    // Deadline honored: a bounded Acquire on a full window times out
    // instead of wedging (the lost-completion escape).
    const int64_t t0 = monotonic_time_us();
    EXPECT_EQ(-1, ring->Acquire(50 * 1000));
    EXPECT_GE(monotonic_time_us() - t0, (int64_t)45 * 1000);
    // Non-blocking try.
    EXPECT_EQ(-1, ring->Acquire(0));

    // A parked Acquire is unblocked by Abort with -2 (not a timeout).
    std::atomic<int> parked_result{123};
    std::thread waiter([&] {
        parked_result.store(ring->Acquire(10 * 1000 * 1000));
    });
    usleep(50 * 1000);  // let the waiter park
    ring->Abort();
    waiter.join();
    EXPECT_EQ(-2, parked_result.load());
    EXPECT_TRUE(ring->aborted());
    // Future acquires fail fast; in-flight completes still settle.
    EXPECT_EQ(-2, ring->Acquire(-1));
    EXPECT_EQ(0, ring->Complete(0));
    delete ring;
}

// ---------------- full RPC over the link ----------------

namespace {

class IciEchoServiceImpl : public test::EchoService {
public:
    void Echo(google::protobuf::RpcController* cntl_base,
              const test::EchoRequest* req, test::EchoResponse* res,
              google::protobuf::Closure* done) override {
        Controller* cntl = static_cast<Controller*>(cntl_base);
        res->set_message(req->message());
        cntl->response_attachment().append(cntl->request_attachment());
        done->Run();
    }
};

}  // namespace

TEST(IciRpc, EchoOverIciLink) {
    // Server with no TCP listener: the data plane is the ICI link.
    // service declared BEFORE server: ~Server (Stop+Join) must
    // drain handler fibers while the service object is still alive.
    IciEchoServiceImpl service;
    Server server;
    ASSERT_EQ(0, server.AddService(&service));
    ASSERT_EQ(0, server.StartNoListen(nullptr));

    IciLink& link = *IciLink::Create();
    // Server side socket bound to the server's messenger. The sockets own
    // the endpoints: the link frees itself after both recycle.
    SocketOptions sopts;
    sopts.fd = link.second()->event_fd();
    sopts.transport = link.second();
    sopts.owns_transport = true;
    sopts.on_edge_triggered_events = InputMessenger::OnNewMessages;
    sopts.user = server.messenger();
    SocketId server_sid;
    ASSERT_EQ(0, Socket::Create(sopts, &server_sid));

    // Client side socket bound to the client messenger.
    SocketOptions copts;
    copts.fd = link.first()->event_fd();
    copts.transport = link.first();
    copts.owns_transport = true;
    copts.on_edge_triggered_events = InputMessenger::OnNewMessages;
    copts.user = Channel::client_messenger();
    SocketId client_sid;
    ASSERT_EQ(0, Socket::Create(copts, &client_sid));

    Channel channel;
    ChannelOptions chopts;
    chopts.timeout_ms = 5000;
    ASSERT_EQ(0, channel.InitWithSocketId(client_sid, &chopts));
    test::EchoService_Stub stub(&channel);

    // Small sync echo.
    {
        Controller cntl;
        test::EchoRequest req;
        test::EchoResponse res;
        req.set_message("ici says hi");
        stub.Echo(&cntl, &req, &res, nullptr);
        ASSERT_FALSE(cntl.Failed());
        EXPECT_EQ("ici says hi", res.message());
    }
    // 1MB attachment echo (exercises window recycling through the stack).
    {
        Controller cntl;
        test::EchoRequest req;
        test::EchoResponse res;
        req.set_message("big");
        cntl.request_attachment().append(std::string(1u << 20, 'A'));
        stub.Echo(&cntl, &req, &res, nullptr);
        ASSERT_FALSE(cntl.Failed());
        EXPECT_EQ((size_t)(1u << 20), cntl.response_attachment().size());
    }
    // Many pipelined calls.
    {
        struct AsyncCall {
            Controller cntl;
            test::EchoRequest req;
            test::EchoResponse res;
            std::atomic<int>* ok;
            CountdownEvent* pending;
            static void Done(AsyncCall* c) {
                if (!c->cntl.Failed()) c->ok->fetch_add(1);
                c->pending->signal();
                delete c;
            }
        };
        std::atomic<int> ok{0};
        CountdownEvent pending(64);
        for (int i = 0; i < 64; ++i) {
            auto* call = new AsyncCall;
            call->ok = &ok;
            call->pending = &pending;
            call->req.set_message("m" + std::to_string(i));
            stub.Echo(&call->cntl, &call->req, &call->res,
                      google::protobuf::NewCallback(&AsyncCall::Done, call));
        }
        pending.wait();
        EXPECT_EQ(64, ok.load());
    }

    // Teardown: failing the client socket closes the link; the server
    // socket sees EOF and fails too. Join drains server-side fibers that
    // still touch the Server's method map for stats.
    SocketUniquePtr cs;
    ASSERT_EQ(0, Socket::AddressSocket(client_sid, &cs));
    cs->SetFailedWithError(TERR_CLOSE);
    cs.reset();
    server.Stop();
    server.Join();
}

// ---------------- response-direction descriptors (ISSUE 12) -------------

namespace {

// Handler answering desc_rsp:N:S requests with an N-byte pool-block
// reference (pattern: byte 0 = S, rest 'a'+S%26); "inline_fallback"
// exercises the ineligible-shape path (a multi-block IOBuf must fall
// back to inline response-attachment bytes).
class RspDescEchoService : public test::EchoService {
public:
    void Echo(google::protobuf::RpcController* cntl_base,
              const test::EchoRequest* req, test::EchoResponse* res,
              google::protobuf::Closure* done) override {
        Controller* cntl = static_cast<Controller*>(cntl_base);
        unsigned long long n = 0;
        unsigned seed = 0;
        if (sscanf(req->message().c_str(), "desc_rsp:%llu:%u", &n,
                   &seed) == 2 &&
            n > 0) {
            IOBuf out;
            char* data = nullptr;
            if (IciBlockPool::AllocatePoolAttachment((size_t)n, &out,
                                                     &data)) {
                memset(data, 'a' + (int)(seed % 26), (size_t)n);
                data[0] = (char)seed;
                cntl->set_response_pool_attachment(std::move(out));
                res->set_message("ok");
            } else {
                cntl->SetFailed(TERR_RESPONSE, "alloc failed");
            }
        } else if (req->message() == "inline_fallback") {
            // Multi-block shape: one (offset, len) cannot name it, so
            // the set must fall back to inline bytes.
            IOBuf multi;
            multi.append(std::string(9000, 'x'));
            multi.append(std::string(9000, 'y'));
            cntl->set_response_pool_attachment(std::move(multi));
            res->set_message("ok");
        }
        done->Run();
    }
};

}  // namespace

TEST(RspPoolDescriptor, ZeroCopyAndAckLifecycleOverIciLink) {
    ASSERT_EQ(0, IciBlockPool::Init());
    RspDescEchoService service;
    Server server;
    ASSERT_EQ(0, server.AddService(&service));
    ASSERT_EQ(0, server.StartNoListen(nullptr));

    IciLink& link = *IciLink::Create();
    SocketOptions sopts;
    sopts.fd = link.second()->event_fd();
    sopts.transport = link.second();
    sopts.owns_transport = true;
    sopts.on_edge_triggered_events = InputMessenger::OnNewMessages;
    sopts.user = server.messenger();
    SocketId server_sid;
    ASSERT_EQ(0, Socket::Create(sopts, &server_sid));
    SocketOptions copts;
    copts.fd = link.first()->event_fd();
    copts.transport = link.first();
    copts.owns_transport = true;
    copts.on_edge_triggered_events = InputMessenger::OnNewMessages;
    copts.user = Channel::client_messenger();
    SocketId client_sid;
    ASSERT_EQ(0, Socket::Create(copts, &client_sid));
    Channel channel;
    ChannelOptions chopts;
    chopts.timeout_ms = 5000;
    ASSERT_EQ(0, channel.InitWithSocketId(client_sid, &chopts));
    test::EchoService_Stub stub(&channel);

    // The ici tier is descriptor-capable by registry contract — the one
    // seam both descriptor directions consult.
    {
        SocketUniquePtr cs;
        ASSERT_EQ(0, Socket::AddressSocket(client_sid, &cs));
        ASSERT_EQ(TierIci(), cs->transport_tier());
        ASSERT_TRUE(TransportDescriptorCapable(cs.get()));
    }

    const uint64_t pinned0 = block_lease::pinned();
    const size_t kBytes = 60000;
    {
        Controller cntl;
        cntl.set_timeout_ms(5000);
        test::EchoRequest req;
        test::EchoResponse res;
        char ask[64];
        snprintf(ask, sizeof(ask), "desc_rsp:%zu:%u", kBytes, 7u);
        req.set_message(ask);
        stub.Echo(&cntl, &req, &res, nullptr);
        ASSERT_FALSE(cntl.Failed());
        EXPECT_EQ("ok", res.message());
        const Controller::PoolAttachment& view =
            cntl.response_pool_attachment();
        ASSERT_TRUE(view.data != nullptr);
        EXPECT_EQ((uint64_t)kBytes, view.length);
        // Zero inline payload bytes; the view reads the server's pool
        // in place (one address space here, so Contains sees it).
        EXPECT_EQ((size_t)0, cntl.response_attachment().size());
        EXPECT_TRUE(IciBlockPool::Contains(view.data));
        EXPECT_EQ((char)7, view.data[0]);
        EXPECT_EQ((char)('a' + 7), view.data[1]);
        // Client role: no local lease — the pin lives on the SERVER
        // side of the call, held for exactly as long as this view.
        EXPECT_EQ((uint64_t)0, cntl.response_pool_lease_id());
        EXPECT_EQ(pinned0 + 1, block_lease::pinned());
        // Releasing the view (controller reuse) sends the desc_ack; the
        // server's pin must drop exactly once.
        cntl.Reset();
        bool released = false;
        for (int i = 0; i < 500 && !released; ++i) {
            released = block_lease::pinned() == pinned0;
            if (!released) usleep(10 * 1000);
        }
        EXPECT_TRUE(released);
    }
    // Ineligible multi-block shape: transparent inline fallback — the
    // handler API is transport/shape-agnostic.
    {
        Controller cntl;
        cntl.set_timeout_ms(5000);
        test::EchoRequest req;
        test::EchoResponse res;
        req.set_message("inline_fallback");
        stub.Echo(&cntl, &req, &res, nullptr);
        ASSERT_FALSE(cntl.Failed());
        EXPECT_TRUE(cntl.response_pool_attachment().data == nullptr);
        EXPECT_EQ((size_t)18000, cntl.response_attachment().size());
        EXPECT_EQ(pinned0, block_lease::pinned());
    }

    SocketUniquePtr cs;
    ASSERT_EQ(0, Socket::AddressSocket(client_sid, &cs));
    cs->SetFailedWithError(TERR_CLOSE);
    cs.reset();
    server.Stop();
    server.Join();
}

TEST(RspPoolDescriptor, ClientDeathReleasesServerPins) {
    // The chaos-soak invariant at unit scale: a client that dies
    // mid-view (no ack ever sent) must not strand the server's rsp pin
    // — the socket failure observer releases every lease armed against
    // the dead connection (server_call::OnSocketFailed -> ReleasePeer).
    ASSERT_EQ(0, IciBlockPool::Init());
    RspDescEchoService service;
    Server server;
    ASSERT_EQ(0, server.AddService(&service));
    ASSERT_EQ(0, server.StartNoListen(nullptr));

    IciLink& link = *IciLink::Create();
    SocketOptions sopts;
    sopts.fd = link.second()->event_fd();
    sopts.transport = link.second();
    sopts.owns_transport = true;
    sopts.on_edge_triggered_events = InputMessenger::OnNewMessages;
    sopts.user = server.messenger();
    SocketId server_sid;
    ASSERT_EQ(0, Socket::Create(sopts, &server_sid));
    SocketOptions copts;
    copts.fd = link.first()->event_fd();
    copts.transport = link.first();
    copts.owns_transport = true;
    copts.on_edge_triggered_events = InputMessenger::OnNewMessages;
    copts.user = Channel::client_messenger();
    SocketId client_sid;
    ASSERT_EQ(0, Socket::Create(copts, &client_sid));
    Channel channel;
    ChannelOptions chopts;
    chopts.timeout_ms = 5000;
    ASSERT_EQ(0, channel.InitWithSocketId(client_sid, &chopts));
    test::EchoService_Stub stub(&channel);

    const uint64_t pinned0 = block_lease::pinned();
    auto* cntl = new Controller;  // leaked past the socket death below
    cntl->set_timeout_ms(5000);
    test::EchoRequest req;
    test::EchoResponse res;
    req.set_message("desc_rsp:30000:3");
    stub.Echo(cntl, &req, &res, nullptr);
    ASSERT_FALSE(cntl->Failed());
    ASSERT_EQ(pinned0 + 1, block_lease::pinned());

    // "SIGKILL" the client: fail its socket with the view still held
    // and never run the controller's teardown ack.
    SocketUniquePtr cs;
    ASSERT_EQ(0, Socket::AddressSocket(client_sid, &cs));
    cs->SetFailedWithError(TERR_CLOSE);
    cs.reset();
    bool released = false;
    for (int i = 0; i < 500 && !released; ++i) {
        released = block_lease::pinned() == pinned0;
        if (!released) usleep(10 * 1000);
    }
    EXPECT_TRUE(released);
    const uint64_t peer_released0 = block_lease::peer_released();
    EXPECT_GE(peer_released0, (uint64_t)1);

    // The leaked controller's destructor fires a best-effort ack at a
    // dead socket: must be a harmless no-op, not a crash/double free.
    delete cntl;
    server.Stop();
    server.Join();
}
