// ICI transport tests: the fake-ICI loopback link plays the role loopback
// TCP plays in the reference's tests (SURVEY §4: "a fake/loopback ICI
// endpoint plays the role loopback TCP plays"). Covers the block pool,
// the queue-pair data path, credit flow control, event suppression, EOF,
// and a full RPC echo over the link.
#include <unistd.h>

#include <atomic>
#include <cstring>
#include <string>

#include "echo.pb.h"
#include "tbase/iobuf.h"
#include "tbase/errno.h"
#include "tbase/time.h"
#include "tfiber/fiber.h"
#include "tfiber/fiber_sync.h"
#include "tici/block_pool.h"
#include "tici/ici_link.h"
#include "tnet/socket.h"
#include "trpc/channel.h"
#include "trpc/controller.h"
#include "trpc/server.h"
#include "ttest/ttest.h"

using namespace tpurpc;

namespace {

// Pump endpoint `e` into `portal` until `want` bytes arrived (poll-style,
// for link-level tests that bypass the dispatcher).
ssize_t pump_until(IciEndpoint* e, IOPortal* portal, size_t want) {
    ssize_t total = 0;
    for (int spins = 0; spins < 100000 && (size_t)total < want; ++spins) {
        const ssize_t nr = e->Pump(portal);
        if (nr > 0) {
            total += nr;
        } else if (nr == 0) {
            return total;  // EOF
        }
    }
    return total;
}

}  // namespace

TEST(IciBlockPool, InstallsAndServesRegisteredMemory) {
    ASSERT_EQ(0, IciBlockPool::Init(4u << 20));
    ASSERT_TRUE(IciBlockPool::initialized());
    // New IOBuf blocks now come from registered regions.
    IOBuf buf;
    buf.append(std::string(100, 'x'));
    size_t len = 0;
    const char* p = buf.backing_block_data(0, &len);
    EXPECT_TRUE(IciBlockPool::Contains(p));
    EXPECT_EQ(100u, len);
    // Odd-size direct allocation round-trips too.
    void* odd = IciBlockPool::Allocate(123456);
    ASSERT_TRUE(odd != nullptr);
    IciBlockPool::Deallocate(odd);
}

TEST(IciLink, BytesFlowBothWays) {
    IciLink& link = *IciLink::Create();
    IOBuf msg;
    msg.append("hello over ici");
    IOBuf* pieces[1] = {&msg};
    ASSERT_EQ((ssize_t)14, link.first()->CutFromIOBufList(pieces, 1));
    EXPECT_TRUE(msg.empty());

    IOPortal in;
    ASSERT_EQ((ssize_t)14, pump_until(link.second(), &in, 14));
    EXPECT_TRUE(in.equals("hello over ici"));

    // Reverse direction.
    IOBuf rev;
    rev.append("pong");
    IOBuf* rp[1] = {&rev};
    ASSERT_EQ((ssize_t)4, link.second()->CutFromIOBufList(rp, 1));
    IOPortal rin;
    ASSERT_EQ((ssize_t)4, pump_until(link.first(), &rin, 4));
    EXPECT_TRUE(rin.equals("pong"));
    link.first()->Release();
    link.second()->Release();
}

TEST(IciLink, LargeTransferSurvivesWindowRecycling) {
    // 8MB >> the 256-descriptor window: requires credits to recycle.
    IciLink& link = *IciLink::Create();
    const size_t kTotal = 8u << 20;
    std::string big(kTotal, 0);
    for (size_t i = 0; i < kTotal; ++i) big[i] = (char)(i * 1315423911u >> 7);
    IOBuf src;
    src.append(big);

    std::atomic<bool> done{false};
    std::string got;
    got.reserve(kTotal);
    // Consumer fiber: pump into a portal, drain to string.
    struct Ctx {
        IciLink* link;
        std::string* got;
        size_t want;
        std::atomic<bool>* done;
    } ctx{&link, &got, kTotal, &done};
    fiber_t consumer;
    fiber_start_background(
        &consumer, nullptr,
        [](void* a) -> void* {
            Ctx* c = (Ctx*)a;
            IOPortal in;
            while (c->got->size() < c->want) {
                const ssize_t nr = c->link->second()->Pump(&in);
                if (nr > 0) {
                    std::string chunk;
                    in.cutn(&chunk, in.size());
                    c->got->append(chunk);
                } else if (nr == 0) {
                    break;
                } else {
                    fiber_usleep(100);
                }
            }
            c->done->store(true);
            return nullptr;
        },
        &ctx);

    // Producer: post with window waits.
    IOBuf* pieces[1] = {&src};
    while (!src.empty()) {
        const ssize_t nw = link.first()->CutFromIOBufList(pieces, 1);
        if (nw < 0 && errno == EAGAIN) {
            ASSERT_EQ(0, link.first()->WaitWritable(monotonic_time_us() +
                                                    2 * 1000 * 1000));
        } else {
            ASSERT_GT(nw, 0);
        }
    }
    fiber_join(consumer, nullptr);
    ASSERT_TRUE(done.load());
    ASSERT_EQ(kTotal, got.size());
    EXPECT_EQ(0, memcmp(got.data(), big.data(), kTotal));
    link.first()->Release();
    link.second()->Release();
}

TEST(IciLink, EventSuppressionBatchesDoorbells) {
    IciLink& link = *IciLink::Create();
    // Burst of 50 posts with no consumer arm/drain in between: the
    // doorbell fires once for the burst, not 50 times.
    for (int i = 0; i < 50; ++i) {
        IOBuf m;
        m.append("x");
        IOBuf* p[1] = {&m};
        ASSERT_EQ((ssize_t)1, link.first()->CutFromIOBufList(p, 1));
    }
    EXPECT_EQ(1u, link.first()->signals_sent());
    IOPortal in;
    EXPECT_EQ((ssize_t)50, pump_until(link.second(), &in, 50));
    link.first()->Release();
    link.second()->Release();
}

TEST(IciLink, CloseDeliversEofAfterDrain) {
    IciLink& link = *IciLink::Create();
    IOBuf m;
    m.append("last words");
    IOBuf* p[1] = {&m};
    ASSERT_EQ((ssize_t)10, link.first()->CutFromIOBufList(p, 1));
    link.first()->Close();
    IOPortal in;
    // Data still delivered...
    ASSERT_EQ((ssize_t)10, pump_until(link.second(), &in, 10));
    EXPECT_TRUE(in.equals("last words"));
    // ...then EOF.
    EXPECT_EQ((ssize_t)0, link.second()->Pump(&in));
    // Writes now fail.
    IOBuf m2;
    m2.append("x");
    IOBuf* p2[1] = {&m2};
    EXPECT_EQ((ssize_t)-1, link.second()->CutFromIOBufList(p2, 1));
    link.first()->Release();
    link.second()->Release();
}

// ---------------- full RPC over the link ----------------

namespace {

class IciEchoServiceImpl : public test::EchoService {
public:
    void Echo(google::protobuf::RpcController* cntl_base,
              const test::EchoRequest* req, test::EchoResponse* res,
              google::protobuf::Closure* done) override {
        Controller* cntl = static_cast<Controller*>(cntl_base);
        res->set_message(req->message());
        cntl->response_attachment().append(cntl->request_attachment());
        done->Run();
    }
};

}  // namespace

TEST(IciRpc, EchoOverIciLink) {
    // Server with no TCP listener: the data plane is the ICI link.
    // service declared BEFORE server: ~Server (Stop+Join) must
    // drain handler fibers while the service object is still alive.
    IciEchoServiceImpl service;
    Server server;
    ASSERT_EQ(0, server.AddService(&service));
    ASSERT_EQ(0, server.StartNoListen(nullptr));

    IciLink& link = *IciLink::Create();
    // Server side socket bound to the server's messenger. The sockets own
    // the endpoints: the link frees itself after both recycle.
    SocketOptions sopts;
    sopts.fd = link.second()->event_fd();
    sopts.transport = link.second();
    sopts.owns_transport = true;
    sopts.on_edge_triggered_events = InputMessenger::OnNewMessages;
    sopts.user = server.messenger();
    SocketId server_sid;
    ASSERT_EQ(0, Socket::Create(sopts, &server_sid));

    // Client side socket bound to the client messenger.
    SocketOptions copts;
    copts.fd = link.first()->event_fd();
    copts.transport = link.first();
    copts.owns_transport = true;
    copts.on_edge_triggered_events = InputMessenger::OnNewMessages;
    copts.user = Channel::client_messenger();
    SocketId client_sid;
    ASSERT_EQ(0, Socket::Create(copts, &client_sid));

    Channel channel;
    ChannelOptions chopts;
    chopts.timeout_ms = 5000;
    ASSERT_EQ(0, channel.InitWithSocketId(client_sid, &chopts));
    test::EchoService_Stub stub(&channel);

    // Small sync echo.
    {
        Controller cntl;
        test::EchoRequest req;
        test::EchoResponse res;
        req.set_message("ici says hi");
        stub.Echo(&cntl, &req, &res, nullptr);
        ASSERT_FALSE(cntl.Failed());
        EXPECT_EQ("ici says hi", res.message());
    }
    // 1MB attachment echo (exercises window recycling through the stack).
    {
        Controller cntl;
        test::EchoRequest req;
        test::EchoResponse res;
        req.set_message("big");
        cntl.request_attachment().append(std::string(1u << 20, 'A'));
        stub.Echo(&cntl, &req, &res, nullptr);
        ASSERT_FALSE(cntl.Failed());
        EXPECT_EQ((size_t)(1u << 20), cntl.response_attachment().size());
    }
    // Many pipelined calls.
    {
        struct AsyncCall {
            Controller cntl;
            test::EchoRequest req;
            test::EchoResponse res;
            std::atomic<int>* ok;
            CountdownEvent* pending;
            static void Done(AsyncCall* c) {
                if (!c->cntl.Failed()) c->ok->fetch_add(1);
                c->pending->signal();
                delete c;
            }
        };
        std::atomic<int> ok{0};
        CountdownEvent pending(64);
        for (int i = 0; i < 64; ++i) {
            auto* call = new AsyncCall;
            call->ok = &ok;
            call->pending = &pending;
            call->req.set_message("m" + std::to_string(i));
            stub.Echo(&call->cntl, &call->req, &call->res,
                      google::protobuf::NewCallback(&AsyncCall::Done, call));
        }
        pending.wait();
        EXPECT_EQ(64, ok.load());
    }

    // Teardown: failing the client socket closes the link; the server
    // socket sees EOF and fails too. Join drains server-side fibers that
    // still touch the Server's method map for stats.
    SocketUniquePtr cs;
    ASSERT_EQ(0, Socket::AddressSocket(client_sid, &cs));
    cs->SetFailedWithError(TERR_CLOSE);
    cs.reset();
    server.Stop();
    server.Join();
}
