// Fault-injection layer (tnet/fault_injection.h): determinism, per-peer
// scoping, flag-driven live toggling, and the client-robustness stack
// surviving injected faults end-to-end on a loopback RPC server.
#include <string>
#include <vector>

#include "echo.pb.h"
#include "tbase/endpoint.h"
#include "tbase/flags.h"
#include "tnet/fault_injection.h"
#include "trpc/channel.h"
#include "trpc/controller.h"
#include "trpc/server.h"
#include "ttest/ttest.h"

using namespace tpurpc;

DECLARE_bool(chaos_enabled);
DECLARE_int64(chaos_seed);
DECLARE_string(chaos_plan);
DECLARE_string(chaos_peers);

namespace {

// Every test leaves the process chaos-free (suites share the binary).
struct ChaosOff {
    ~ChaosOff() {
        FLAGS_chaos_plan.set("");
        FLAGS_chaos_peers.set("");
        FLAGS_chaos_seed.set(1);
        FLAGS_chaos_enabled.set(false);
    }
};

std::vector<int> run_sequence(const EndPoint& peer, int n) {
    std::vector<int> kinds;
    kinds.reserve((size_t)n);
    for (int i = 0; i < n; ++i) {
        // Mix ops the way a real transport would; lengths vary to prove
        // the sequence depends on the counter, not the call payload.
        const FaultOp op = i % 7 == 0 ? FaultOp::kConnect : FaultOp::kWrite;
        kinds.push_back(
            (int)FaultInjection::Decide(op, peer, 100 + (size_t)i).kind);
    }
    return kinds;
}

}  // namespace

TEST(FaultInjection, DeterministicReplay) {
    ChaosOff off;
    EndPoint peer;
    str2endpoint("127.0.0.1:7001", &peer);
    FLAGS_chaos_plan.set(
        "drop=0.1,delay=0.1:1,short=0.1,corrupt=0.1,reset=0.1,refuse=0.3");
    // Scope to the fake peer: stray sockets from OTHER suites in this
    // runner (health checkers, lingering connections) must not consume
    // decision ticks mid-replay.
    FLAGS_chaos_peers.set("127.0.0.1:7001");
    FLAGS_chaos_seed.set(424242);
    FLAGS_chaos_enabled.set(true);
    ASSERT_TRUE(fault_injection_enabled());

    const std::vector<int> first = run_sequence(peer, 2000);
    const int64_t d1 = FaultInjection::decisions();
    int64_t c1[FaultAction::kKindCount];
    for (int k = 0; k < FaultAction::kKindCount; ++k) {
        c1[k] = FaultInjection::injected_count((FaultAction::Kind)k);
    }
    EXPECT_EQ(d1, 2000);
    // The plan's probabilities guarantee a healthy injection mix.
    EXPECT_GT(c1[FaultAction::kDrop], 0);
    EXPECT_GT(c1[FaultAction::kReset], 0);
    EXPECT_GT(c1[FaultAction::kRefuse], 0);

    // Replay: re-setting the SEED resets the sequence and the counters.
    FLAGS_chaos_seed.set(424242);
    EXPECT_EQ(FaultInjection::decisions(), 0);
    const std::vector<int> second = run_sequence(peer, 2000);
    EXPECT_TRUE(first == second);  // the exact same injection sequence
    EXPECT_EQ(FaultInjection::decisions(), d1);
    for (int k = 0; k < FaultAction::kKindCount; ++k) {
        EXPECT_EQ(c1[k],
                  FaultInjection::injected_count((FaultAction::Kind)k));
    }

    // A DIFFERENT seed yields a different sequence (same plan, length).
    FLAGS_chaos_seed.set(7);
    const std::vector<int> other = run_sequence(peer, 2000);
    EXPECT_FALSE(first == other);
}

TEST(FaultInjection, PerPeerScopingConsumesNoTicks) {
    ChaosOff off;
    EndPoint scoped, other;
    str2endpoint("127.0.0.1:7001", &scoped);
    str2endpoint("127.0.0.1:7002", &other);
    FLAGS_chaos_plan.set("drop=1.0");
    FLAGS_chaos_peers.set("127.0.0.1:7001");
    FLAGS_chaos_seed.set(5);
    FLAGS_chaos_enabled.set(true);

    // Out-of-scope traffic: no injection AND no decision tick, so it
    // cannot shift a replayed sequence.
    for (int i = 0; i < 50; ++i) {
        EXPECT_EQ((int)FaultAction::kNone,
                  (int)FaultInjection::Decide(FaultOp::kWrite, other, 64)
                      .kind);
    }
    EXPECT_EQ(FaultInjection::decisions(), 0);
    EXPECT_EQ((int)FaultAction::kDrop,
              (int)FaultInjection::Decide(FaultOp::kWrite, scoped, 64).kind);
    EXPECT_EQ(FaultInjection::decisions(), 1);
}

TEST(FaultInjection, UnparsablePlanDisables) {
    ChaosOff off;
    FLAGS_chaos_enabled.set(true);
    FLAGS_chaos_plan.set("drop=0.5");
    EXPECT_TRUE(fault_injection_enabled());
    FLAGS_chaos_plan.set("not-a-plan");
    EXPECT_FALSE(fault_injection_enabled());  // fail closed
    FLAGS_chaos_plan.set("delay=0.1:5ms");  // junk param unit
    EXPECT_FALSE(fault_injection_enabled());
    FLAGS_chaos_plan.set("drop=0.5:123");  // param on a kind without one
    EXPECT_FALSE(fault_injection_enabled());
    FLAGS_chaos_plan.set("drop=1.5");  // probability out of range
    EXPECT_FALSE(fault_injection_enabled());
    FLAGS_chaos_plan.set("drop=0.5");
    EXPECT_TRUE(fault_injection_enabled());  // recovers on a valid plan
    EXPECT_TRUE(FaultInjection::ValidatePlan("delay=0.05:2000"));
    EXPECT_FALSE(FaultInjection::ValidatePlan("delay=0.05:"));
    EXPECT_FALSE(FaultInjection::ValidatePeers("not-an-endpoint"));
}

TEST(FaultInjection, HealKeepsCountersReadable) {
    // enable=0 (the /chaos heal) and peers edits must NOT wipe the
    // run's counters — only seed/plan changes restart the sequence.
    ChaosOff off;
    EndPoint peer;
    str2endpoint("127.0.0.1:7001", &peer);
    FLAGS_chaos_plan.set("drop=1.0");
    FLAGS_chaos_peers.set("127.0.0.1:7001");
    FLAGS_chaos_seed.set(3);
    FLAGS_chaos_enabled.set(true);
    (void)FaultInjection::Decide(FaultOp::kWrite, peer, 64);
    const int64_t d = FaultInjection::decisions();
    EXPECT_GE(d, 1);
    FLAGS_chaos_enabled.set(false);  // heal
    EXPECT_EQ(FaultInjection::decisions(), d);
    FLAGS_chaos_seed.set(3);  // replay: same seed restarts from zero
    EXPECT_EQ(FaultInjection::decisions(), 0);
}

TEST(FaultInjection, DisabledIsInert) {
    ChaosOff off;
    FLAGS_chaos_plan.set("drop=1.0");
    FLAGS_chaos_enabled.set(false);
    EXPECT_FALSE(fault_injection_enabled());
    // The seams gate on fault_injection_enabled(); nothing below them
    // runs. (Decide itself is never called when disabled — this is the
    // whole-plan "zero overhead when disabled" contract.)
}

// ---------------- end-to-end: robustness stack under injected faults ----

namespace {

class ChaosEchoImpl : public test::EchoService {
public:
    void Echo(google::protobuf::RpcController*, const test::EchoRequest* req,
              test::EchoResponse* res,
              google::protobuf::Closure* done) override {
        res->set_message(req->message());
        done->Run();
    }
};

}  // namespace

TEST(FaultInjection, RpcsTerminateUnderConnectionFaults) {
    ChaosOff off;
    ChaosEchoImpl service;
    Server server;
    ASSERT_EQ(0, server.AddService(&service));
    EndPoint listen;
    str2endpoint("127.0.0.1:0", &listen);
    ASSERT_EQ(0, server.Start(listen, nullptr));
    EndPoint ep;
    str2endpoint("127.0.0.1", server.listened_port(), &ep);

    Channel ch;
    ChannelOptions opts;
    opts.timeout_ms = 2000;
    opts.max_retry = 3;
    ASSERT_EQ(0, ch.Init(ep, &opts));
    test::EchoService_Stub stub(&ch);

    // Scope to the server endpoint so only the CLIENT side of the
    // connection (whose remote is the listen address) injects — the
    // deterministic sequence is then independent of server-side reads.
    FLAGS_chaos_peers.set(endpoint2str(ep));
    FLAGS_chaos_plan.set("reset=0.05,short=0.10,delay=0.05:1000");
    FLAGS_chaos_seed.set(99);
    FLAGS_chaos_enabled.set(true);

    int ok = 0, failed = 0;
    for (int i = 0; i < 60; ++i) {
        Controller cntl;
        test::EchoRequest req;
        req.set_message("m" + std::to_string(i));
        test::EchoResponse res;
        stub.Echo(&cntl, &req, &res, nullptr);  // sync: termination proof
        if (cntl.Failed()) {
            ++failed;
        } else {
            ++ok;
            EXPECT_EQ(res.message(), "m" + std::to_string(i));
        }
    }
    // Every call terminated (we got here) and the faults really fired.
    EXPECT_EQ(ok + failed, 60);
    EXPECT_GT(FaultInjection::decisions(), 0);
    // Retries over a revivable connection keep goodput alive: resets
    // kill the socket but reconnect-on-next-write brings it back.
    EXPECT_GT(ok, 30);

    // Chaos off: service is fully healthy again.
    FLAGS_chaos_enabled.set(false);
    for (int i = 0; i < 5; ++i) {
        Controller cntl;
        test::EchoRequest req;
        req.set_message("post");
        test::EchoResponse res;
        stub.Echo(&cntl, &req, &res, nullptr);
        EXPECT_FALSE(cntl.Failed());
    }
}
