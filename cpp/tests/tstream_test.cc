// Streaming RPC tests over a real loopback server (reference analog:
// test/brpc_streaming_rpc_unittest.cpp): establish/accept, ordered
// delivery, window exhaustion blocks the writer, consumption feedback
// resumes it, close during a blocked write, failure on RPC errors, and
// a deterministic fuzz loop over both frame parsers (reference
// test/fuzzing/ fuzz_* harnesses).
#include <netinet/in.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "echo.pb.h"
#include "tbase/endpoint.h"
#include "tbase/errno.h"
#include "tbase/time.h"
#include "tfiber/fiber.h"
#include "tfiber/fiber_sync.h"
#include "thttp/http_message.h"
#include "thttp/progressive_attachment.h"
#include "tnet/protocol.h"
#include "trpc/channel.h"
#include "trpc/controller.h"
#include "trpc/policy_tpu_std.h"
#include "trpc/server.h"
#include "trpc/stream.h"
#include "ttest/ttest.h"

using namespace tpurpc;

namespace {

// Collects received messages; counts closes.
class CollectingHandler : public StreamInputHandler {
public:
    int on_received_messages(StreamId, IOBuf* const messages[],
                             size_t size) override {
        std::lock_guard<std::mutex> g(mu);
        for (size_t i = 0; i < size; ++i) {
            received.push_back(messages[i]->to_string());
            bytes += (int64_t)messages[i]->size();
        }
        if (delay_us > 0) usleep(delay_us);
        return 0;
    }
    void on_closed(StreamId) override { closed.fetch_add(1); }

    std::mutex mu;
    std::vector<std::string> received;
    int64_t bytes = 0;
    int delay_us = 0;
    std::atomic<int> closed{0};
};

// Echo service that accepts a stream with `handler` and window
// `window_size`.
class StreamAcceptService : public test::EchoService {
public:
    void Echo(google::protobuf::RpcController* cntl_base,
              const test::EchoRequest* request, test::EchoResponse* response,
              google::protobuf::Closure* done) override {
        auto* cntl = static_cast<Controller*>(cntl_base);
        response->set_message(request->message());
        if (cntl->has_remote_stream()) {
            StreamOptions opts;
            opts.handler = handler;
            opts.window_size = window_size;
            if (StreamAccept(&server_stream, cntl, &opts) != 0) {
                cntl->SetFailed("StreamAccept failed");
            }
        }
        done->Run();
    }
    StreamInputHandler* handler = nullptr;
    int64_t window_size = 2 * 1024 * 1024;
    StreamId server_stream = INVALID_STREAM_ID;
};

struct StreamedServer {
    CollectingHandler handler;
    StreamAcceptService service;
    Server server;
    EndPoint ep;

    bool start() {
        service.handler = &handler;
        if (server.AddService(&service) != 0) return false;
        EndPoint listen;
        str2endpoint("127.0.0.1:0", &listen);
        if (server.Start(listen, nullptr) != 0) return false;
        str2endpoint("127.0.0.1", server.listened_port(), &ep);
        return true;
    }
};

// Establish a client stream over an RPC; returns 0 on success.
int establish(Channel* ch, StreamId* sid, const StreamOptions* sopts) {
    Controller cntl;
    cntl.set_timeout_ms(3000);
    if (StreamCreate(sid, &cntl, sopts) != 0) return -1;
    test::EchoService_Stub stub(ch);
    test::EchoRequest req;
    req.set_message("open-stream");
    test::EchoResponse res;
    stub.Echo(&cntl, &req, &res, nullptr);
    return cntl.Failed() ? cntl.ErrorCode() : 0;
}

}  // namespace

TEST(Stream, EstablishWriteCloseDelivers) {
    StreamedServer ts;
    ASSERT_TRUE(ts.start());
    Channel ch;
    ASSERT_EQ(0, ch.Init(ts.ep, nullptr));

    StreamId sid;
    ASSERT_EQ(0, establish(&ch, &sid, nullptr));
    for (int i = 0; i < 20; ++i) {
        IOBuf msg;
        msg.append("msg-" + std::to_string(i));
        ASSERT_EQ(0, StreamWrite(sid, &msg));
    }
    // Ordered delivery.
    for (int i = 0; i < 200; ++i) {
        {
            std::lock_guard<std::mutex> g(ts.handler.mu);
            if (ts.handler.received.size() >= 20) break;
        }
        usleep(10000);
    }
    {
        std::lock_guard<std::mutex> g(ts.handler.mu);
        ASSERT_EQ(ts.handler.received.size(), 20u);
        for (int i = 0; i < 20; ++i) {
            EXPECT_EQ(ts.handler.received[(size_t)i],
                      "msg-" + std::to_string(i));
        }
    }
    // Close reaches the server handler.
    ASSERT_EQ(0, StreamClose(sid));
    for (int i = 0; i < 200 && ts.handler.closed.load() == 0; ++i) {
        usleep(10000);
    }
    EXPECT_EQ(ts.handler.closed.load(), 1);
}

TEST(Stream, WindowExhaustionBlocksWriterFeedbackResumes) {
    StreamedServer ts;
    ts.service.window_size = 64 * 1024;   // small server window
    ts.handler.delay_us = 40 * 1000;      // slow consumer: feedback lags
    ASSERT_TRUE(ts.start());
    Channel ch;
    ASSERT_EQ(0, ch.Init(ts.ep, nullptr));

    StreamId sid;
    ASSERT_EQ(0, establish(&ch, &sid, nullptr));

    // Fill the 64KB window with 8KB messages: with the consumer delayed,
    // the window exhausts after ~8 writes and StreamWrite returns EAGAIN.
    // (A fast consumer's feedback legitimately refills the window — hence
    // the injected delay to observe exhaustion deterministically.)
    IOBuf chunk;
    chunk.append(std::string(8 * 1024, 'w'));
    int written = 0;
    int eagain = 0;
    for (int i = 0; i < 64; ++i) {
        IOBuf msg;
        msg.append(chunk);
        if (StreamWrite(sid, &msg) == 0) {
            ++written;
        } else {
            EXPECT_EQ(errno, EAGAIN);
            ++eagain;
            break;
        }
    }
    EXPECT_GT(written, 0);
    EXPECT_GT(eagain, 0);
    // At most the window plus one in-flight feedback's worth.
    EXPECT_LE(written * 8 * 1024, 64 * 1024 + 5 * 8 * 1024);

    // The consumer drains; feedback frames open the window; StreamWait
    // unblocks and the remaining writes go through.
    int64_t total = (int64_t)written * 8 * 1024;
    while (total < 40 * 8 * 1024) {
        if (StreamWait(sid, monotonic_time_us() + 5 * 1000 * 1000) != 0) {
            break;
        }
        IOBuf msg;
        msg.append(chunk);
        if (StreamWrite(sid, &msg) == 0) {
            total += 8 * 1024;
        }
    }
    EXPECT_EQ(total, 40 * 8 * 1024);
    for (int i = 0; i < 500; ++i) {
        {
            std::lock_guard<std::mutex> g(ts.handler.mu);
            if (ts.handler.bytes >= total) break;
        }
        usleep(10000);
    }
    std::lock_guard<std::mutex> g(ts.handler.mu);
    EXPECT_EQ(ts.handler.bytes, total);
}

TEST(Stream, CloseWhileWriterBlockedUnblocksWithEPIPE) {
    StreamedServer ts;
    ts.service.window_size = 32 * 1024;
    ts.handler.delay_us = 30 * 1000;  // slow consumer keeps window shut
    ASSERT_TRUE(ts.start());
    Channel ch;
    ASSERT_EQ(0, ch.Init(ts.ep, nullptr));

    StreamId sid;
    ASSERT_EQ(0, establish(&ch, &sid, nullptr));

    std::atomic<bool> done{false};
    std::atomic<int> result{0};
    std::atomic<int> stage{0};  // 1 = exited via wait, 2 = via write
    struct Ctx {
        StreamId sid;
        std::atomic<bool>* done;
        std::atomic<int>* result;
        std::atomic<int>* stage;
    } ctx{sid, &done, &result, &stage};
    fiber_t tid;
    fiber_start_background(
        &tid, nullptr,
        [](void* arg) -> void* {
            auto* c = (Ctx*)arg;
            IOBuf chunk;
            chunk.append(std::string(8 * 1024, 'x'));
            // Write until blocked, then wait on the window.
            while (true) {
                IOBuf msg;
                msg.append(chunk);
                if (StreamWrite(c->sid, &msg) != 0) {
                    if (errno == EAGAIN) {
                        // StreamWait RETURNS its error code: errno after
                        // a parking call may be the wrong worker's.
                        const int wrc = StreamWait(c->sid, 0);
                        if (wrc != 0) {
                            c->result->store(wrc);
                            c->stage->store(1);
                            break;  // unblocked by close
                        }
                        continue;
                    }
                    c->result->store(errno);
                    c->stage->store(2);
                    break;
                }
            }
            c->done->store(true);
            return nullptr;
        },
        &ctx);
    usleep(100 * 1000);  // let it block on the shut window
    ASSERT_EQ(0, StreamClose(sid));
    fiber_join(tid, nullptr);
    EXPECT_TRUE(done.load());
    // Close destroys the local stream: the blocked writer wakes with
    // EPIPE (peer-close seen first) or EINVAL (id already destroyed).
    EXPECT_TRUE(result.load() == EPIPE || result.load() == EINVAL)
        << "actual errno " << result.load() << " stage " << stage.load();
    // Handler-lifetime contract (same as the reference): the handler must
    // outlive the stream — wait for on_closed before the stack-allocated
    // server/handler go away (the CLOSE frame drains the slow consumer's
    // backlog first).
    for (int i = 0; i < 1000 && ts.handler.closed.load() == 0; ++i) {
        usleep(10000);
    }
    EXPECT_EQ(ts.handler.closed.load(), 1);
}

TEST(Stream, FailedRpcFailsPendingStream) {
    // Establishing RPC hits a dead server: the stream must fail, not leak.
    Channel ch;
    ChannelOptions opts;
    opts.timeout_ms = 500;
    opts.max_retry = 0;
    ASSERT_EQ(0, ch.Init("127.0.0.1:1", &opts));
    StreamId sid;
    const int rc = establish(&ch, &sid, nullptr);
    EXPECT_NE(0, rc);
    // Writes on the failed stream are rejected.
    IOBuf msg;
    msg.append("nope");
    EXPECT_NE(0, StreamWrite(sid, &msg));
}

// ---------------- frame parser fuzzing ----------------
// Deterministic in-suite smoke (the reference keeps libFuzzer harnesses in
// test/fuzzing/; tools/frame_fuzz.cc runs these same mutators for 10^7
// execs). Parsers must never crash and never consume bytes on non-OK.

namespace {

uint64_t fz_rng = 0x9e3779b97f4a7c15ull;
uint64_t fz_next() {
    fz_rng ^= fz_rng << 13;
    fz_rng ^= fz_rng >> 7;
    fz_rng ^= fz_rng << 17;
    return fz_rng;
}

std::string mutate_frame(std::string input) {
    const int nmut = 1 + (int)(fz_next() % 6);
    for (int m = 0; m < nmut; ++m) {
        if (input.empty()) input = "T";
        switch (fz_next() % 4) {
            case 0:
                input[fz_next() % input.size()] = (char)fz_next();
                break;
            case 1:
                input.resize(fz_next() % (input.size() + 1));
                break;
            case 2: {
                const size_t at = fz_next() % input.size();
                input.insert(at, input.substr(0, fz_next() % 24));
                break;
            }
            case 3:
                for (int i = 0; i < 10; ++i) {
                    input.push_back((char)fz_next());
                }
                break;
        }
    }
    return input;
}

}  // namespace

TEST(StreamFuzz, ParsersSurviveMutatedFrames) {
    GlobalInitializeOrDie();
    const Protocol* tpu = GetProtocol(TpuStdProtocolIndex());
    const Protocol* strm =
        GetProtocol(stream_internal::StreamProtocolIndex());
    ASSERT_TRUE(tpu != nullptr && strm != nullptr);

    // Seed: one valid tpu_std frame + one valid STRM data frame.
    IOBuf seed_tpu;
    {
        IOBuf meta, payload, att;
        meta.append("\x08\x01");  // arbitrary pb-ish bytes
        payload.append("hello");
        PackTpuStdFrame(&seed_tpu, meta, payload, att);
    }
    std::string seeds[2];
    seeds[0] = seed_tpu.to_string();
    seeds[1] = std::string("STRM") + std::string("\x00\x00\x00\x05", 4) +
               std::string(8, '\x01') + std::string(1, '\x00') + "hello";

    for (int iter = 0; iter < 30000; ++iter) {
        const std::string input = mutate_frame(seeds[fz_next() % 2]);
        for (const Protocol* p : {tpu, strm}) {
            IOBuf buf;
            buf.append(input);
            const size_t before = buf.size();
            ParseResult r = p->parse(&buf, nullptr, false, p->parse_arg);
            if (r.error == ParseError::OK) {
                EXPECT_LT(buf.size(), before);  // consumed the frame
                delete r.msg;
            } else {
                EXPECT_EQ(buf.size(), before);  // nothing consumed
            }
        }
    }
}

// ---------------- progressive body vs. graceful drain ----------------

TEST(Stream, ProgressiveBodySurvivesGracefulStop) {
    // Regression (zero-downtime lifecycle): a chunked HTTP body still
    // being written AFTER its handler returned must count against
    // Server::Join draining. Before the ProgressiveAttachment close
    // hook fed Server::EndRequest, GracefulStop saw nprocessing == 0
    // the moment the handler returned and hard-closed the connection
    // mid-chunk — the client got a truncated stream instead of the
    // terminating 0-chunk.
    std::atomic<bool> writer_closed{false};
    Server server;
    server.RegisterHttpHandler(
        "/prog",
        [&writer_closed](Server*, const HttpRequest&, HttpResponse* res) {
            res->set_content_type("text/plain");
            res->start_progressive =
                [&writer_closed](std::shared_ptr<ProgressiveAttachment> pa) {
                    struct Args {
                        std::shared_ptr<ProgressiveAttachment> pa;
                        std::atomic<bool>* closed;
                    };
                    auto* a = new Args{std::move(pa), &writer_closed};
                    fiber_t tid;
                    if (fiber_start_background(
                            &tid, nullptr,
                            [](void* raw) -> void* {
                                std::unique_ptr<Args> a((Args*)raw);
                                for (int i = 0; i < 3; ++i) {
                                    fiber_usleep(100 * 1000);
                                    a->pa->Write("chunk-" +
                                                 std::to_string(i) + ";");
                                }
                                a->pa->Close();
                                a->closed->store(
                                    true, std::memory_order_release);
                                return nullptr;
                            },
                            a) != 0) {
                        delete a;
                    }
                };
        });
    EndPoint listen;
    str2endpoint("127.0.0.1:0", &listen);
    ASSERT_EQ(0, server.Start(listen, nullptr));

    // Raw HTTP/1.1 client reading the chunked stream on a thread.
    const int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    ASSERT_GE(fd, 0);
    sockaddr_in addr;
    EndPoint ep;
    str2endpoint("127.0.0.1", server.listened_port(), &ep);
    endpoint2sockaddr(ep, &addr);
    ASSERT_EQ(0, ::connect(fd, (sockaddr*)&addr, sizeof(addr)));
    const std::string get = "GET /prog HTTP/1.1\r\nHost: t\r\n\r\n";
    ASSERT_EQ((ssize_t)get.size(),
              ::send(fd, get.data(), get.size(), MSG_NOSIGNAL));
    std::string received;
    std::mutex received_mu;
    std::thread reader([fd, &received, &received_mu] {
        const int64_t deadline = monotonic_time_us() + 4 * 1000 * 1000;
        char buf[4096];
        while (monotonic_time_us() < deadline) {
            struct pollfd p {
                fd, POLLIN, 0
            };
            if (::poll(&p, 1, 50) != 1) continue;
            const ssize_t n = ::recv(fd, buf, sizeof(buf), 0);
            if (n <= 0) break;
            std::lock_guard<std::mutex> g(received_mu);
            received.append(buf, (size_t)n);
            if (received.find("0\r\n\r\n") != std::string::npos) break;
        }
    });

    usleep(80 * 1000);  // headers are out; the writer fiber is mid-stream
    server.GracefulStop(3000);
    // The drain waited for the progressive writer to Close.
    EXPECT_TRUE(writer_closed.load(std::memory_order_acquire));
    reader.join();
    close(fd);
    std::lock_guard<std::mutex> g(received_mu);
    // Full body delivered: every chunk AND the terminating 0-chunk.
    EXPECT_NE(received.find("chunk-0;"), std::string::npos) << received;
    EXPECT_NE(received.find("chunk-2;"), std::string::npos) << received;
    EXPECT_NE(received.find("0\r\n\r\n"), std::string::npos) << received;
}
