// Redis (RESP) protocol: codec, loopback server+client, pipelined
// correlation under concurrency. Reference parity:
// src/brpc/policy/redis_protocol.cpp + redis.{h,cpp} + the pipelined
// Socket info queue (socket.h:532).
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <map>
#include <string>
#include <vector>

#include "tbase/endpoint.h"
#include "tfiber/fiber.h"
#include "tfiber/fiber_sync.h"
#include "trpc/channel.h"
#include "trpc/controller.h"
#include "trpc/redis.h"
#include "trpc/server.h"
#include "ttest/ttest.h"

using namespace tpurpc;

namespace {

struct RedisTestServer {
    RedisService service;
    Server server;
    EndPoint ep;

    bool start() {
        service.AddBasicKvCommands();
        server.set_redis_service(&service);
        EndPoint listen;
        str2endpoint("127.0.0.1:0", &listen);
        if (server.Start(listen, nullptr) != 0) return false;
        str2endpoint("127.0.0.1", server.listened_port(), &ep);
        return true;
    }
};

ChannelOptions redis_options() {
    ChannelOptions opts;
    opts.protocol = "redis";
    opts.timeout_ms = 10000;
    return opts;
}

}  // namespace

TEST(RedisCodec, ReplyRoundtrip) {
    RedisReply r;
    r.type = RedisReply::ARRAY;
    RedisReply s1;
    s1.type = RedisReply::STATUS;
    s1.str = "OK";
    RedisReply s2;
    s2.type = RedisReply::STRING;
    s2.str = std::string("bin\r\n\x00ary", 9);
    RedisReply s3;
    s3.type = RedisReply::INTEGER;
    s3.integer = -42;
    RedisReply s4;
    s4.type = RedisReply::NIL;
    r.elements = {s1, s2, s3, s4};
    std::string wire;
    RedisSerializeReply(r, &wire);
    IOBuf buf;
    buf.append(wire);
    RedisReply parsed;
    ASSERT_EQ(1, RedisParseReply(&buf, &parsed));
    ASSERT_TRUE(buf.empty());
    ASSERT_EQ(parsed.type, RedisReply::ARRAY);
    ASSERT_EQ(parsed.elements.size(), 4u);
    EXPECT_EQ(parsed.elements[0].str, "OK");
    EXPECT_EQ(parsed.elements[1].str, s2.str);
    EXPECT_EQ(parsed.elements[2].integer, -42);
    EXPECT_EQ(parsed.elements[3].type, RedisReply::NIL);
    // Truncated input: need-more, not corrupt.
    IOBuf half;
    half.append(wire.substr(0, wire.size() / 2));
    RedisReply dummy;
    EXPECT_EQ(0, RedisParseReply(&half, &dummy));
    // Corrupt tag.
    IOBuf bad;
    bad.append("?什么\r\n");
    EXPECT_EQ(-1, RedisParseReply(&bad, &dummy));
}

TEST(Redis, SetGetDelOverLoopback) {
    RedisTestServer ts;
    ASSERT_TRUE(ts.start());
    Channel ch;
    ChannelOptions opts = redis_options();
    ASSERT_EQ(0, ch.Init(ts.ep, &opts));

    RedisRequest req;
    req.AddCommand({"SET", "k1", "v1"});
    req.AddCommand({"GET", "k1"});
    req.AddCommand({"DEL", "k1"});
    req.AddCommand({"GET", "k1"});
    RedisResponse res;
    Controller cntl;
    RedisCall(&ch, &cntl, req, &res);
    ASSERT_FALSE(cntl.Failed());
    ASSERT_EQ(res.reply_count(), 4u);
    EXPECT_EQ(res.reply(0).str, "OK");
    EXPECT_EQ(res.reply(1).str, "v1");
    EXPECT_EQ(res.reply(2).integer, 1);
    EXPECT_EQ(res.reply(3).type, RedisReply::NIL);
}

TEST(Redis, UnknownCommandIsError) {
    RedisTestServer ts;
    ASSERT_TRUE(ts.start());
    Channel ch;
    ChannelOptions opts = redis_options();
    ASSERT_EQ(0, ch.Init(ts.ep, &opts));
    RedisRequest req;
    req.AddCommand({"FLUSHALL"});
    RedisResponse res;
    Controller cntl;
    RedisCall(&ch, &cntl, req, &res);
    ASSERT_FALSE(cntl.Failed());
    ASSERT_EQ(res.reply_count(), 1u);
    EXPECT_TRUE(res.reply(0).is_error());
}

TEST(Redis, PipelinedBatchesStayOrderedUnderConcurrency) {
    // N fibers share ONE connection; each sends a pipelined batch whose
    // replies must come back to the RIGHT caller in the RIGHT order —
    // the Socket pipelined-info FIFO is the correlation.
    RedisTestServer ts;
    ASSERT_TRUE(ts.start());
    Channel ch;
    ChannelOptions opts = redis_options();
    ASSERT_EQ(0, ch.Init(ts.ep, &opts));
    struct Ctx {
        Channel* ch;
        std::atomic<int> ok{0};
        std::atomic<int> bad{0};
    } ctx{&ch, {}, {}};
    std::vector<fiber_t> tids(16);
    for (size_t i = 0; i < tids.size(); ++i) {
        struct Arg {
            Ctx* c;
            int me;
        };
        auto* arg = new Arg{&ctx, (int)i};
        fiber_start_background(
            &tids[i], nullptr,
            [](void* raw) -> void* {
                std::unique_ptr<Arg> a((Arg*)raw);
                for (int round = 0; round < 10; ++round) {
                    const std::string key =
                        "k" + std::to_string(a->me);
                    const std::string val = "v" + std::to_string(a->me) +
                                            "-" + std::to_string(round);
                    RedisRequest req;
                    req.AddCommand({"SET", key, val});
                    req.AddCommand({"ECHO", val});
                    req.AddCommand({"GET", key});
                    RedisResponse res;
                    Controller cntl;
                    RedisCall(a->c->ch, &cntl, req, &res);
                    if (cntl.Failed() || res.reply_count() != 3 ||
                        res.reply(0).str != "OK" ||
                        res.reply(1).str != val ||
                        res.reply(2).str != val) {
                        a->c->bad.fetch_add(1);
                        return nullptr;
                    }
                }
                a->c->ok.fetch_add(1);
                return nullptr;
            },
            arg);
    }
    for (auto tid : tids) fiber_join(tid, nullptr);
    EXPECT_EQ(ctx.ok.load(), 16);
    EXPECT_EQ(ctx.bad.load(), 0);
    // All on one pipelined connection.
    EXPECT_EQ(ts.server.acceptor()->accepted_count(), 1);
}

TEST(Redis, CorruptInputFailsOnlyThatConnection) {
    // Real corrupt bytes over a raw TCP socket (the redis-speaking peer
    // is tests/test_redis_raw.py's job; here we assert the server-side
    // blast radius): the poisoned connection dies, the server lives.
    RedisTestServer ts;
    ASSERT_TRUE(ts.start());
    const int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    ASSERT_GE(fd, 0);
    sockaddr_in addr;
    endpoint2sockaddr(ts.ep, &addr);
    ASSERT_EQ(0, ::connect(fd, (sockaddr*)&addr, sizeof(addr)));
    // Valid command first so the connection settles on the redis
    // protocol, then garbage that scan_command rejects (-1 => ERROR).
    const char good[] = "*1\r\n$4\r\nPING\r\n";
    ASSERT_EQ((ssize_t)sizeof(good) - 1,
              ::send(fd, good, sizeof(good) - 1, 0));
    char buf[64];
    ASSERT_GT(::recv(fd, buf, sizeof(buf), 0), 0);  // +PONG
    const char bad[] = "*2\r\n$4\r\nPING\r\nGARBAGE-NOT-RESP\r\n";
    ::send(fd, bad, sizeof(bad) - 1, 0);
    // Server must close the poisoned connection: recv drains to EOF.
    ssize_t r;
    while ((r = ::recv(fd, buf, sizeof(buf), 0)) > 0) {
    }
    EXPECT_EQ(r, 0);
    ::close(fd);
    // A fresh client still works: the failure stayed on one connection.
    Channel ch;
    ChannelOptions opts = redis_options();
    ASSERT_EQ(0, ch.Init(ts.ep, &opts));
    RedisRequest req;
    req.AddCommand({"PING"});
    RedisResponse res;
    Controller cntl;
    RedisCall(&ch, &cntl, req, &res);
    ASSERT_FALSE(cntl.Failed());
    EXPECT_EQ(res.reply(0).str, "PONG");
}
