// Collective-engine tests (ISSUE 13): in-process multi-rank meshes —
// every "rank" is a Server + CollectiveEngine pair in this process,
// connected over loopback channels — running real chunked all-reduce /
// all-gather / all-to-all rounds, plus the re-form path when a member
// dies mid-collective.
#include <algorithm>
#include <atomic>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "bench_echo.pb.h"
#include "tbase/endpoint.h"
#include "tbase/errno.h"
#include "tfiber/fiber.h"
#include "trpc/channel.h"
#include "tfiber/fiber_sync.h"
#include "tici/block_pool.h"
#include "trpc/collective.h"
#include "trpc/collective_benchpb.h"
#include "trpc/controller.h"
#include "trpc/server.h"
#include "ttest/ttest.h"

using namespace tpurpc;

namespace {

// The wire glue (codec + Exchange body) is the SAME code mesh_node
// serves with — trpc/collective_benchpb.h.
class TestCollService : public benchpb::CollectiveService {
public:
    CollectiveEngine* engine = nullptr;
    void Exchange(google::protobuf::RpcController* cntl_base,
                  const benchpb::CollChunk* req, benchpb::CollAck* res,
                  google::protobuf::Closure* done) override {
        HandleCollectiveExchange(engine,
                                 static_cast<Controller*>(cntl_base), req,
                                 res, done);
    }
};

// One in-process "rank": server + engine + static membership view.
struct TestRank;

class TestMembership : public CollectiveMembership {
public:
    std::vector<TestRank*>* ranks = nullptr;
    TestRank* self = nullptr;
    void GetMembers(std::vector<Member>* out) override;
};

struct TestRank {
    Server server;
    TestCollService service;
    BenchpbCollCodec codec;
    TestMembership membership;
    std::unique_ptr<CollectiveEngine> engine;
    std::shared_ptr<Channel> chan;  // TO this rank's server
    uint64_t key = 0;
    std::string zone;  // pod identity (hier collectives, ISSUE 14)
    std::atomic<bool> dead{false};  // excluded from every membership view
};

void TestMembership::GetMembers(std::vector<Member>* out) {
    for (TestRank* r : *ranks) {
        if (r->dead.load(std::memory_order_relaxed)) continue;
        Member m;
        m.key = r->key;
        m.self = r == self;
        m.zone = r->zone;
        if (!m.self) m.chan = r->chan;
        out->push_back(m);
    }
}

// Builds an N-rank in-process mesh; every rank serves on a loopback
// port and every OTHER rank reaches it through one shared channel.
struct TestMesh {
    std::vector<std::unique_ptr<TestRank>> owned;
    std::vector<TestRank*> ranks;

    explicit TestMesh(int n, const CollectiveOptions& opts) {
        IciBlockPool::Init();  // chunk buffers pool-backed where possible
        for (int i = 0; i < n; ++i) {
            owned.push_back(std::make_unique<TestRank>());
            ranks.push_back(owned.back().get());
        }
        for (TestRank* r : ranks) {
            r->server.AddService(&r->service);
            EndPoint any;
            str2endpoint("127.0.0.1:0", &any);
            r->server.Start(any, nullptr);
            r->key = (uint64_t)r->server.listened_port();
            r->chan = std::make_shared<Channel>();
            ChannelOptions copts;
            copts.timeout_ms = 3000;
            copts.max_retry = 0;  // the engine sets per-call retries
            char addr[32];
            snprintf(addr, sizeof(addr), "127.0.0.1:%d",
                     r->server.listened_port());
            r->chan->Init(addr, &copts);
        }
        for (TestRank* r : ranks) {
            r->membership.ranks = &ranks;
            r->membership.self = r;
            r->engine.reset(
                new CollectiveEngine(&r->membership, &r->codec, opts));
            r->service.engine = r->engine.get();
        }
    }
};

CollectiveOptions SmallOpts() {
    CollectiveOptions o;
    o.chunk_bytes = 4 << 10;  // many chunks from small payloads
    o.step_timeout_ms = 2000;
    o.attempt_timeout_ms = 2500;
    o.op_timeout_ms = 15000;
    return o;
}

// Drive one op on every live rank concurrently (each driver blocks its
// fiber — a collective needs all ranks participating).
struct DriverArg {
    TestRank* rank = nullptr;
    uint64_t seq = 0;
    std::vector<uint32_t> words;
    std::string out;
    std::map<uint64_t, std::string> blocks;
    size_t block_bytes = 0;
    int op = 0;  // 0 allreduce, 1 serial, 2 allgather, 3 alltoall
    CollectiveEngine::Result result;
    int rc = -1;
    CountdownEvent* finished = nullptr;
};

void* DriveOne(void* argp) {
    auto* a = (DriverArg*)argp;
    switch (a->op) {
        case 0:
            a->rc = a->rank->engine->AllReduce(a->seq, a->words.data(),
                                               a->words.size(), &a->result);
            break;
        case 1:
            a->rc = a->rank->engine->SerialAllReduce(
                a->seq, a->words.data(), a->words.size(), &a->result);
            break;
        case 2:
            a->rc = a->rank->engine->AllGather(
                a->seq, a->words.data(), a->words.size() * 4, &a->out,
                &a->result);
            break;
        case 3:
            a->rc = a->rank->engine->AllToAll(a->seq, a->blocks,
                                              a->block_bytes, &a->out,
                                              &a->result);
            break;
        case 4:
            a->rc = a->rank->engine->HierAllReduce(
                a->seq, a->words.data(), a->words.size(), &a->result);
            break;
    }
    a->finished->signal();
    return nullptr;
}

void DriveAll(std::vector<DriverArg>& args) {
    CountdownEvent ev((int)args.size());
    for (DriverArg& a : args) {
        a.finished = &ev;
        fiber_t t;
        if (fiber_start_background(&t, nullptr, DriveOne, &a) != 0) {
            DriveOne(&a);
        }
    }
    ev.wait();
}

std::vector<uint32_t> ExpectedSum(uint64_t seq,
                                  const std::vector<uint64_t>& keys,
                                  size_t nwords) {
    std::vector<uint32_t> expect(nwords, 0), tmp(nwords);
    for (uint64_t k : keys) {
        CollectiveEngine::FillDeterministic(seq, k, tmp.data(), nwords);
        for (size_t i = 0; i < nwords; ++i) expect[i] += tmp[i];
    }
    return expect;
}

}  // namespace

TEST(Collective, ChecksumAndFillAreStable) {
    // Golden value locks the cross-language formula (the numpy/JAX twin
    // in tests/test_collectives.py must match it bit for bit).
    const uint32_t words[3] = {1, 2, 3};
    EXPECT_EQ(1310726u, CollectiveEngine::Checksum(words, 3));
    uint32_t w[4];
    CollectiveEngine::FillDeterministic(7, 9001, w, 4);
    EXPECT_EQ((uint32_t)(0x9E3779B1u * 7 + 0x85EBCA77u * 9001),
              w[0]);
    EXPECT_EQ((uint32_t)(w[0] + 0xC2B2AE35u), w[1]);
}

TEST(Collective, RingAllReduceMatchesSum) {
    TestMesh mesh(4, SmallOpts());
    const size_t nwords = 8192;  // 32 KiB over 4 KiB chunks => pipelined
    std::vector<DriverArg> args(4);
    for (int i = 0; i < 4; ++i) {
        args[i].rank = mesh.ranks[i];
        args[i].seq = 1;
        args[i].op = 0;
        args[i].words.resize(nwords);
        CollectiveEngine::FillDeterministic(1, mesh.ranks[i]->key,
                                            args[i].words.data(), nwords);
    }
    DriveAll(args);
    std::vector<uint64_t> keys;
    for (TestRank* r : mesh.ranks) keys.push_back(r->key);
    std::vector<uint32_t> expect = ExpectedSum(1, keys, nwords);
    for (int i = 0; i < 4; ++i) {
        ASSERT_EQ(0, args[i].rc);
        EXPECT_EQ(4u, args[i].result.nranks);
        EXPECT_TRUE(args[i].words == expect);
    }
}

TEST(Collective, SerialAllReduceMatchesRing) {
    TestMesh mesh(3, SmallOpts());
    const size_t nwords = 1024;
    std::vector<DriverArg> args(3);
    for (int i = 0; i < 3; ++i) {
        args[i].rank = mesh.ranks[i];
        args[i].seq = 1;
        args[i].op = 1;
        args[i].words.resize(nwords);
        CollectiveEngine::FillDeterministic(1, mesh.ranks[i]->key,
                                            args[i].words.data(), nwords);
    }
    DriveAll(args);
    std::vector<uint64_t> keys;
    for (TestRank* r : mesh.ranks) keys.push_back(r->key);
    std::vector<uint32_t> expect = ExpectedSum(1, keys, nwords);
    for (int i = 0; i < 3; ++i) {
        ASSERT_EQ(0, args[i].rc);
        EXPECT_TRUE(args[i].words == expect);
    }
}

TEST(Collective, AllGatherCollectsBlocksInRankOrder) {
    TestMesh mesh(4, SmallOpts());
    const size_t nwords = 3000;  // 12 KB block -> 3 chunks of 4 KiB
    std::vector<DriverArg> args(4);
    for (int i = 0; i < 4; ++i) {
        args[i].rank = mesh.ranks[i];
        args[i].seq = 1;
        args[i].op = 2;
        args[i].words.resize(nwords);
        CollectiveEngine::FillDeterministic(1, mesh.ranks[i]->key,
                                            args[i].words.data(), nwords);
    }
    DriveAll(args);
    // Rank order = key order (ports ascending).
    std::vector<TestRank*> sorted = mesh.ranks;
    std::sort(sorted.begin(), sorted.end(),
              [](TestRank* a, TestRank* b) { return a->key < b->key; });
    std::string expect;
    std::vector<uint32_t> tmp(nwords);
    for (TestRank* r : sorted) {
        CollectiveEngine::FillDeterministic(1, r->key, tmp.data(), nwords);
        expect.append((const char*)tmp.data(), nwords * 4);
    }
    for (int i = 0; i < 4; ++i) {
        ASSERT_EQ(0, args[i].rc);
        EXPECT_TRUE(args[i].out == expect);
    }
}

TEST(Collective, AllToAllExchangesPairBlocks) {
    TestMesh mesh(4, SmallOpts());
    const size_t block = 8 << 10;  // 2 chunks per pair
    std::vector<DriverArg> args(4);
    std::vector<uint32_t> tmp(block / 4);
    for (int i = 0; i < 4; ++i) {
        args[i].rank = mesh.ranks[i];
        args[i].seq = 1;
        args[i].op = 3;
        args[i].block_bytes = block;
        for (TestRank* dst : mesh.ranks) {
            CollectiveEngine::FillDeterministic(
                1, mesh.ranks[i]->key * 1000003ull + dst->key, tmp.data(),
                tmp.size());
            args[i].blocks[dst->key].assign((const char*)tmp.data(),
                                            block);
        }
    }
    DriveAll(args);
    std::vector<TestRank*> sorted = mesh.ranks;
    std::sort(sorted.begin(), sorted.end(),
              [](TestRank* a, TestRank* b) { return a->key < b->key; });
    for (int i = 0; i < 4; ++i) {
        ASSERT_EQ(0, args[i].rc);
        std::string expect;
        for (TestRank* src : sorted) {
            CollectiveEngine::FillDeterministic(
                1, src->key * 1000003ull + mesh.ranks[i]->key, tmp.data(),
                tmp.size());
            expect.append((const char*)tmp.data(), block);
        }
        EXPECT_TRUE(args[i].out == expect);
    }
}

namespace {

struct KillArg {
    TestRank* victim = nullptr;
    std::atomic<bool>* go = nullptr;
};

void* KillAfterDelay(void* argp) {
    auto* a = (KillArg*)argp;
    // Let the survivors' first attempt run into the dead server, then
    // flip the membership view so the next attempt RE-FORMS.
    fiber_usleep(600 * 1000);
    a->victim->dead.store(true, std::memory_order_relaxed);
    a->go->store(true, std::memory_order_relaxed);
    return nullptr;
}

}  // namespace

TEST(Collective, MemberDeathReformsOverSurvivors) {
    CollectiveOptions opts = SmallOpts();
    opts.attempt_timeout_ms = 1200;  // fail into the dead peer quickly
    TestMesh mesh(3, opts);
    const size_t nwords = 2048;

    // Round 1: everyone alive.
    {
        std::vector<DriverArg> args(3);
        for (int i = 0; i < 3; ++i) {
            args[i].rank = mesh.ranks[i];
            args[i].seq = 1;
            args[i].op = 0;
            args[i].words.resize(nwords);
            CollectiveEngine::FillDeterministic(
                1, mesh.ranks[i]->key, args[i].words.data(), nwords);
        }
        DriveAll(args);
        for (int i = 0; i < 3; ++i) ASSERT_EQ(0, args[i].rc);
    }

    // Kill rank 2's server (calls to it now fail) but leave it IN the
    // membership view: the survivors' first round-2 attempt must fail,
    // then re-form over {0, 1} once the view catches up.
    TestRank* victim = mesh.ranks[2];
    victim->engine->Shutdown();
    victim->server.Stop();
    victim->server.Join();
    std::atomic<bool> flipped{false};
    KillArg ka{victim, &flipped};
    fiber_t kt;
    ASSERT_EQ(0, fiber_start_background(&kt, nullptr, KillAfterDelay, &ka));

    std::vector<DriverArg> args(2);
    for (int i = 0; i < 2; ++i) {
        args[i].rank = mesh.ranks[i];
        args[i].seq = 2;
        args[i].op = 0;
        args[i].words.resize(nwords);
        CollectiveEngine::FillDeterministic(2, mesh.ranks[i]->key,
                                            args[i].words.data(), nwords);
    }
    DriveAll(args);
    fiber_join(kt, nullptr);
    ASSERT_TRUE(flipped.load());

    std::vector<uint64_t> survivors{mesh.ranks[0]->key,
                                    mesh.ranks[1]->key};
    std::sort(survivors.begin(), survivors.end());
    std::vector<uint32_t> expect = ExpectedSum(2, survivors, nwords);
    for (int i = 0; i < 2; ++i) {
        if (args[i].rc != 0) {
            fprintf(stderr,
                    "rank %d rc=%d error=%d nranks=%u reforms=%d "
                    "retries=%d\n",
                    i, args[i].rc, args[i].result.error,
                    args[i].result.nranks, args[i].result.reforms,
                    args[i].result.retries);
        }
        ASSERT_EQ(0, args[i].rc);
        EXPECT_EQ(2u, args[i].result.nranks);
        EXPECT_GE(args[i].result.reforms, 1);
        EXPECT_TRUE(args[i].words == expect);
    }
}

// ---------------- hierarchical collectives (ISSUE 14) ----------------

TEST(Collective, HierAllReduceMatchesGlobalSum) {
    // Two "pods" of two ranks each: the hierarchical composition (zone
    // ring -> leader exchange -> zone broadcast-ring) must produce the
    // SAME bits as a flat global all-reduce, and report the full
    // contributing key set.
    TestMesh mesh(4, SmallOpts());
    for (int i = 0; i < 4; ++i) mesh.ranks[i]->zone = i < 2 ? "A" : "B";
    const size_t nwords = 4096;
    std::vector<DriverArg> args(4);
    for (int i = 0; i < 4; ++i) {
        args[i].rank = mesh.ranks[i];
        args[i].seq = 1;
        args[i].op = 4;
        args[i].words.resize(nwords);
        CollectiveEngine::FillDeterministic(1, mesh.ranks[i]->key,
                                            args[i].words.data(), nwords);
    }
    DriveAll(args);
    std::vector<uint64_t> keys;
    for (TestRank* r : mesh.ranks) keys.push_back(r->key);
    std::sort(keys.begin(), keys.end());
    std::vector<uint32_t> expect = ExpectedSum(1, keys, nwords);
    for (int i = 0; i < 4; ++i) {
        if (args[i].rc != 0) {
            fprintf(stderr, "hier rank %d rc=%d error=%d nranks=%u\n", i,
                    args[i].rc, args[i].result.error,
                    args[i].result.nranks);
        }
        ASSERT_EQ(0, args[i].rc);
        EXPECT_EQ(4u, args[i].result.nranks);
        EXPECT_TRUE(args[i].result.member_keys == keys);
        EXPECT_TRUE(args[i].words == expect);
        EXPECT_GT(args[i].result.busbw_mbps, 0.0);
    }
}

TEST(Collective, HierAllReduceZonelessDegradesToSingleZone) {
    // No zones configured: one zone of everything — phase 2 is a
    // single-leader no-op and the result is still the global sum.
    TestMesh mesh(3, SmallOpts());
    const size_t nwords = 1024;
    std::vector<DriverArg> args(3);
    for (int i = 0; i < 3; ++i) {
        args[i].rank = mesh.ranks[i];
        args[i].seq = 1;
        args[i].op = 4;
        args[i].words.resize(nwords);
        CollectiveEngine::FillDeterministic(1, mesh.ranks[i]->key,
                                            args[i].words.data(), nwords);
    }
    DriveAll(args);
    std::vector<uint64_t> keys;
    for (TestRank* r : mesh.ranks) keys.push_back(r->key);
    std::sort(keys.begin(), keys.end());
    std::vector<uint32_t> expect = ExpectedSum(1, keys, nwords);
    for (int i = 0; i < 3; ++i) {
        ASSERT_EQ(0, args[i].rc);
        EXPECT_EQ(3u, args[i].result.nranks);
        EXPECT_TRUE(args[i].words == expect);
    }
}

TEST(Collective, HierAllReduceSurvivesWholePodPartition) {
    // Pod B dies mid-program: pod A's hierarchical round must complete
    // over the SURVIVING pod — the leader exchange degrades to a no-op
    // and the result is pod A's sum, with member_keys reporting exactly
    // the survivors (the mesh driver verifies against that set).
    CollectiveOptions opts = SmallOpts();
    opts.attempt_timeout_ms = 1200;  // fail into the dead pod quickly
    TestMesh mesh(4, opts);
    for (int i = 0; i < 4; ++i) mesh.ranks[i]->zone = i < 2 ? "A" : "B";
    const size_t nwords = 2048;

    // Round 1: both pods alive (warms rounds + proves the topology).
    {
        std::vector<DriverArg> args(4);
        for (int i = 0; i < 4; ++i) {
            args[i].rank = mesh.ranks[i];
            args[i].seq = 1;
            args[i].op = 4;
            args[i].words.resize(nwords);
            CollectiveEngine::FillDeterministic(
                1, mesh.ranks[i]->key, args[i].words.data(), nwords);
        }
        DriveAll(args);
        for (int i = 0; i < 4; ++i) ASSERT_EQ(0, args[i].rc);
    }

    // Whole pod B partitions: its servers stop but stay in the
    // membership view until the detector flips them — pod A's first
    // leader exchange fails into the dead pod, then re-probes.
    for (int i = 2; i < 4; ++i) {
        mesh.ranks[i]->engine->Shutdown();
        mesh.ranks[i]->server.Stop();
        mesh.ranks[i]->server.Join();
    }
    std::atomic<bool> flipped{false};
    KillArg ka2{mesh.ranks[2], &flipped};
    KillArg ka3{mesh.ranks[3], &flipped};
    fiber_t k2, k3;
    ASSERT_EQ(0, fiber_start_background(&k2, nullptr, KillAfterDelay, &ka2));
    ASSERT_EQ(0, fiber_start_background(&k3, nullptr, KillAfterDelay, &ka3));

    std::vector<DriverArg> args(2);
    for (int i = 0; i < 2; ++i) {
        args[i].rank = mesh.ranks[i];
        args[i].seq = 2;
        args[i].op = 4;
        args[i].words.resize(nwords);
        CollectiveEngine::FillDeterministic(2, mesh.ranks[i]->key,
                                            args[i].words.data(), nwords);
    }
    DriveAll(args);
    fiber_join(k2, nullptr);
    fiber_join(k3, nullptr);

    std::vector<uint64_t> survivors{mesh.ranks[0]->key,
                                    mesh.ranks[1]->key};
    std::sort(survivors.begin(), survivors.end());
    std::vector<uint32_t> expect = ExpectedSum(2, survivors, nwords);
    for (int i = 0; i < 2; ++i) {
        if (args[i].rc != 0) {
            fprintf(stderr,
                    "hier-partition rank %d rc=%d error=%d nranks=%u\n",
                    i, args[i].rc, args[i].result.error,
                    args[i].result.nranks);
        }
        ASSERT_EQ(0, args[i].rc);
        EXPECT_EQ(2u, args[i].result.nranks);
        EXPECT_TRUE(args[i].result.member_keys == survivors);
        EXPECT_TRUE(args[i].words == expect);
    }
}
