// Zero-downtime lifecycle tests: graceful drain end to end.
//
// Covered (ISSUE 5 satellite "cpp/tests/tdrain_test.cc"):
//   - tpu_std GOAWAY emit (Server::StartDraining) + parse (client marks
//     the connection draining; in-flight and racing calls still served)
//   - /status shows the draining state; HTTP/1.1 responses carry
//     Connection: close while draining
//   - LB exclusion of draining nodes (policy unit + rr integration,
//     with the all-draining fallback)
//   - h2 client GOAWAY: streams above last-stream-id fail as
//     TERR_DRAINING (retriable elsewhere, budget-free), streams at or
//     below it complete normally
//   - GracefulStop drains in-flight work and is bounded by max_drain_ms
//   - Acceptor pause/resume (accept gate without closing the listen fd)
#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "echo.pb.h"
#include "tbase/endpoint.h"
#include "tbase/errno.h"
#include "tbase/time.h"
#include "tfiber/fiber.h"
#include "tfiber/fiber_sync.h"
#include "thttp/h2_frames.h"
#include "tnet/socket.h"
#include "tnet/socket_map.h"
#include "trpc/channel.h"
#include "trpc/controller.h"
#include "trpc/load_balancer.h"
#include "trpc/server.h"
#include "ttest/ttest.h"

using namespace tpurpc;

namespace {

class DrainEchoImpl : public test::EchoService {
public:
    void Echo(google::protobuf::RpcController*, const test::EchoRequest* req,
              test::EchoResponse* res,
              google::protobuf::Closure* done) override {
        if (req->sleep_us() > 0) fiber_usleep(req->sleep_us());
        res->set_message(req->message());
        ncalls.fetch_add(1, std::memory_order_relaxed);
        done->Run();
    }
    std::atomic<int> ncalls{0};
};

struct TestServer {
    // service declared BEFORE server: ~Server (Stop+Join) must drain
    // handler fibers while the service object is still alive.
    DrainEchoImpl service;
    Server server;
    EndPoint ep;

    bool start() {
        if (server.AddService(&service) != 0) return false;
        EndPoint listen;
        str2endpoint("127.0.0.1:0", &listen);
        if (server.Start(listen, nullptr) != 0) return false;
        str2endpoint("127.0.0.1", server.listened_port(), &ep);
        return true;
    }
};

int call_echo(Channel* ch, const char* msg, int64_t timeout_ms = 2000,
              int max_retry = -1) {
    Controller cntl;
    cntl.set_timeout_ms(timeout_ms);
    if (max_retry >= 0) cntl.set_max_retry(max_retry);
    test::EchoRequest req;
    test::EchoResponse res;
    req.set_message(msg);
    test::EchoService_Stub stub(ch);
    stub.Echo(&cntl, &req, &res, nullptr);
    if (cntl.Failed()) return cntl.ErrorCode();
    return res.message() == msg ? 0 : -1;
}

// A socket that never connects (pure LB policy tests never write to it).
SocketId make_fake_server(int port) {
    SocketOptions opts;
    opts.fd = -1;
    str2endpoint("127.0.0.1", port, &opts.remote_side);
    SocketId id = INVALID_VREF_ID;
    Socket::Create(opts, &id);
    return id;
}

// One short-lived raw HTTP/1.1 request; returns the full response text.
std::string raw_http_get(const EndPoint& ep, const std::string& path,
                         int timeout_ms = 2000) {
    const int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return "";
    sockaddr_in addr;
    endpoint2sockaddr(ep, &addr);
    if (::connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
        close(fd);
        return "";
    }
    const std::string req =
        "GET " + path + " HTTP/1.1\r\nHost: t\r\n\r\n";
    if (::send(fd, req.data(), req.size(), MSG_NOSIGNAL) !=
        (ssize_t)req.size()) {
        close(fd);
        return "";
    }
    std::string out;
    const int64_t deadline = monotonic_time_us() + timeout_ms * 1000ll;
    char buf[4096];
    while (monotonic_time_us() < deadline) {
        pollfd p{fd, POLLIN, 0};
        if (::poll(&p, 1, 50) != 1) {
            // Headers + a short body arrive in one burst on loopback;
            // stop once we have a complete header block.
            if (out.find("\r\n\r\n") != std::string::npos) break;
            continue;
        }
        const ssize_t n = ::recv(fd, buf, sizeof(buf), 0);
        if (n <= 0) break;
        out.append(buf, (size_t)n);
    }
    close(fd);
    return out;
}

}  // namespace

// ---------------- tpu_std GOAWAY: emit + parse ----------------

TEST(Drain, TpuStdGoawayMarksClientAndKeepsServing) {
    TestServer ts;
    ASSERT_TRUE(ts.start());
    Channel ch;
    ASSERT_EQ(0, ch.Init(ts.ep, nullptr));
    ASSERT_EQ(0, call_echo(&ch, "pre-drain"));

    // The single-server channel rides the shared SocketMap connection.
    SocketId sid = INVALID_VREF_ID;
    ASSERT_EQ(0, SocketMap::singleton()->GetOrCreate(
                     ts.ep, Channel::client_messenger(), &sid));

    ts.server.StartDraining();
    EXPECT_TRUE(ts.server.draining());

    // The GOAWAY meta marks the client connection draining.
    bool draining = false;
    const int64_t deadline = monotonic_time_us() + 2 * 1000 * 1000;
    while (monotonic_time_us() < deadline) {
        SocketUniquePtr s;
        if (Socket::AddressSocket(sid, &s) == 0 && s->Draining()) {
            draining = true;
            break;
        }
        fiber_usleep(10 * 1000);
    }
    EXPECT_TRUE(draining);

    // A draining server still SERVES: a single-server channel has
    // nowhere else to go, and calls racing the announcement must not be
    // lost — that is the whole zero-downtime contract.
    EXPECT_EQ(0, call_echo(&ch, "during-drain"));
    EXPECT_GE(ts.service.ncalls.load(), 2);
}

TEST(Drain, StatusShowsDrainingAndHttp1ConnectionClose) {
    TestServer ts;
    ASSERT_TRUE(ts.start());
    std::string before = raw_http_get(ts.ep, "/status");
    EXPECT_NE(before.find("draining: 0"), std::string::npos);

    ts.server.StartDraining();
    std::string during = raw_http_get(ts.ep, "/status");
    // The page reports the drain AND the HTTP/1.1 response announces it
    // the only way HTTP/1 can: Connection: close.
    EXPECT_NE(during.find("draining: 1"), std::string::npos);
    EXPECT_NE(during.find("Connection: close"), std::string::npos);
}

// ---------------- LB exclusion of draining nodes ----------------

TEST(Drain, PolicyUnitSkipsDrainingNodes) {
    for (const char* policy : {"rr", "wrr", "random", "c_murmurhash",
                               "la"}) {
        std::unique_ptr<LoadBalancer> lb(LoadBalancer::New(policy));
        ASSERT_TRUE(lb != nullptr);
        std::vector<SocketId> ids;
        for (int i = 0; i < 3; ++i) {
            SocketId id = make_fake_server(36200 + i);
            ids.push_back(id);
            EndPoint ep;
            str2endpoint("127.0.0.1", 36200 + i, &ep);
            ASSERT_TRUE(lb->AddServer({id, 1, ep}));
        }
        // Mark one draining: it must never be picked while alternatives
        // exist, and picks routed around it report skipped_draining.
        {
            SocketUniquePtr s;
            ASSERT_EQ(0, Socket::AddressSocket(ids[1], &s));
            s->SetDraining();
        }
        bool saw_skip_flag = false;
        for (int i = 0; i < 60; ++i) {
            SelectIn in;
            in.request_code = (uint64_t)i * 0x9e3779b97f4a7c15ULL;
            in.has_request_code = true;
            SelectOut out;
            ASSERT_EQ(0, lb->SelectServer(in, &out));
            EXPECT_NE(out.ptr->id(), ids[1])
                << policy << " picked a draining node";
            saw_skip_flag = saw_skip_flag || out.skipped_draining;
        }
        (void)saw_skip_flag;  // set whenever the walk passed over ids[1]
        // All draining: selection falls back to a draining node rather
        // than failing the call outright.
        for (SocketId id : ids) {
            SocketUniquePtr s;
            ASSERT_EQ(0, Socket::AddressSocket(id, &s));
            s->SetDraining();
        }
        SelectIn in;
        SelectOut out;
        EXPECT_EQ(0, lb->SelectServer(in, &out)) << policy;
        for (SocketId id : ids) {
            Socket::SetFailedById(id);
        }
    }
}

TEST(Drain, LbSteersAwayFromDrainingServer) {
    TestServer a, b;
    ASSERT_TRUE(a.start());
    ASSERT_TRUE(b.start());
    char url[128];
    snprintf(url, sizeof(url), "list://127.0.0.1:%d,127.0.0.1:%d",
             a.server.listened_port(), b.server.listened_port());
    Channel ch;
    ASSERT_EQ(0, ch.Init(url, "rr", nullptr));
    // Warm both (establishes the naming-socket connections that will
    // carry the GOAWAY).
    for (int i = 0; i < 8; ++i) {
        ASSERT_EQ(0, call_echo(&ch, "warm"));
    }
    ASSERT_GT(a.service.ncalls.load(), 0);
    ASSERT_GT(b.service.ncalls.load(), 0);

    a.server.StartDraining();
    // Propagation is one in-flight read away; after it, every call must
    // land on B. Allow a short transition, then require stability.
    int a_calls_after_transition = -1;
    bool steered = false;
    const int64_t deadline = monotonic_time_us() + 3 * 1000 * 1000;
    while (monotonic_time_us() < deadline && !steered) {
        a_calls_after_transition = a.service.ncalls.load();
        bool all_ok = true;
        for (int i = 0; i < 10; ++i) {
            if (call_echo(&ch, "steer") != 0) all_ok = false;
        }
        ASSERT_TRUE(all_ok);  // NO call may fail during the drain
        steered = a.service.ncalls.load() == a_calls_after_transition;
    }
    EXPECT_TRUE(steered) << "calls kept landing on the draining server";

    // Both draining: the fallback still serves (a draining server beats
    // no server).
    b.server.StartDraining();
    fiber_usleep(100 * 1000);
    for (int i = 0; i < 5; ++i) {
        EXPECT_EQ(0, call_echo(&ch, "fallback"));
    }
}

// ---------------- GracefulStop ----------------

TEST(Drain, GracefulStopDrainsInflight) {
    TestServer ts;
    ASSERT_TRUE(ts.start());
    Channel ch;
    ASSERT_EQ(0, ch.Init(ts.ep, nullptr));
    test::EchoService_Stub stub(&ch);

    Controller cntl;
    cntl.set_timeout_ms(5000);
    cntl.set_max_retry(0);
    test::EchoRequest req;
    req.set_message("inflight");
    req.set_sleep_us(300 * 1000);
    test::EchoResponse res;
    CountdownEvent ev{1};
    struct SignalDone : google::protobuf::Closure {
        CountdownEvent* ev;
        void Run() override { ev->signal(); }
    } done;
    done.ev = &ev;
    stub.Echo(&cntl, &req, &res, &done);
    usleep(50 * 1000);  // the call is in the handler now

    const int64_t t0 = monotonic_time_us();
    ts.server.GracefulStop(3000);
    const int64_t elapsed_ms = (monotonic_time_us() - t0) / 1000;
    ev.wait();
    // The in-flight call completed (drained), not killed.
    EXPECT_FALSE(cntl.Failed()) << cntl.ErrorText();
    EXPECT_EQ(res.message(), "inflight");
    // And the drain did not burn anywhere near the full window.
    EXPECT_LT(elapsed_ms, 2500);
}

TEST(Drain, GracefulStopBoundedByMaxDrainMs) {
    TestServer ts;
    ASSERT_TRUE(ts.start());
    Channel ch;
    ASSERT_EQ(0, ch.Init(ts.ep, nullptr));
    test::EchoService_Stub stub(&ch);

    Controller cntl;
    cntl.set_timeout_ms(8000);
    cntl.set_max_retry(0);
    test::EchoRequest req;
    req.set_message("too-slow");
    req.set_sleep_us(1500 * 1000);  // far beyond the drain window
    test::EchoResponse res;
    CountdownEvent ev{1};
    struct SignalDone : google::protobuf::Closure {
        CountdownEvent* ev;
        void Run() override { ev->signal(); }
    } done;
    done.ev = &ev;
    stub.Echo(&cntl, &req, &res, &done);
    usleep(50 * 1000);

    const int64_t t0 = monotonic_time_us();
    ts.server.GracefulStop(200);
    const int64_t elapsed_ms = (monotonic_time_us() - t0) / 1000;
    // The drain window was honored but NOT the handler's 1.5s: after
    // 200ms the server stopped hard (the final Join still waits for the
    // handler fiber — memory safety — so the bound is handler time, not
    // some larger configured drain).
    EXPECT_GE(elapsed_ms, 200);
    EXPECT_LT(elapsed_ms, 3000);
    ev.wait();
    // The connection died under the call: it fails rather than hangs.
    EXPECT_TRUE(cntl.Failed());
}

// ---------------- acceptor pause/resume ----------------

TEST(Drain, AcceptPauseResume) {
    TestServer ts;
    ASSERT_TRUE(ts.start());
    const int64_t accepted0 = ts.server.acceptor()->accepted_count();

    ts.server.acceptor()->PauseAccept();
    EXPECT_TRUE(ts.server.acceptor()->accept_paused());
    // TCP connect still succeeds (kernel backlog — connect-probe health
    // checks keep passing) but no request is served.
    Channel ch;
    ASSERT_EQ(0, ch.Init(ts.ep, nullptr));
    EXPECT_EQ(TERR_RPC_TIMEDOUT, call_echo(&ch, "paused", 300, 0));
    EXPECT_EQ(accepted0, ts.server.acceptor()->accepted_count());

    ts.server.acceptor()->ResumeAccept();
    EXPECT_FALSE(ts.server.acceptor()->accept_paused());
    // The backlogged connection is picked up (ResumeAccept re-kicks the
    // accept loop) and serves.
    EXPECT_EQ(0, call_echo(&ch, "resumed", 2000, 1));
    EXPECT_GT(ts.server.acceptor()->accepted_count(), accepted0);
}

// ---------------- h2 client GOAWAY ----------------

namespace {

// Raw scripted h2 server on a loopback listener (same pattern as
// tgrpc_client_test's EarlyTrailers regression).
struct RawListener {
    int lfd = -1;
    int port = 0;

    bool open() {
        lfd = ::socket(AF_INET, SOCK_STREAM, 0);
        if (lfd < 0) return false;
        int one = 1;
        setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
        sockaddr_in addr;
        memset(&addr, 0, sizeof(addr));
        addr.sin_family = AF_INET;
        addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
        if (::bind(lfd, (sockaddr*)&addr, sizeof(addr)) != 0) return false;
        if (::listen(lfd, 1) != 0) return false;
        socklen_t alen = sizeof(addr);
        if (getsockname(lfd, (sockaddr*)&addr, &alen) != 0) return false;
        port = ntohs(addr.sin_port);
        return true;
    }
    ~RawListener() {
        if (lfd >= 0) close(lfd);
    }
};

std::string h2_goaway_frame(uint32_t last_stream_id,
                            uint32_t error_code = 0) {
    uint32_t payload[2] = {htonl(last_stream_id), htonl(error_code)};
    return h2::BuildFrame(h2::H2_GOAWAY, 0, 0,
                          std::string((const char*)payload, 8));
}

void drain_socket_for(int fd, int ms) {
    const int64_t end = monotonic_time_us() + (int64_t)ms * 1000;
    char buf[16384];
    while (monotonic_time_us() < end) {
        pollfd p{fd, POLLIN, 0};
        if (::poll(&p, 1, 20) == 1) {
            if (::recv(fd, buf, sizeof(buf), 0) == 0) return;
        }
    }
}

}  // namespace

TEST(Drain, H2GoawayFailsUnprocessedStreamsAsDraining) {
    // GOAWAY with last-stream-id = 0: our stream (id 1) was provably
    // never processed — it must fail TERR_DRAINING (retriable on
    // another connection, budget-free) promptly, not hang to the
    // deadline, and not kill every pending call indiscriminately.
    RawListener ln;
    ASSERT_TRUE(ln.open());
    std::thread raw_server([&ln] {
        const int cfd = ::accept(ln.lfd, nullptr, nullptr);
        if (cfd < 0) return;
        drain_socket_for(cfd, 150);  // preface + HEADERS + DATA
        std::string out = h2::BuildFrame(h2::H2_SETTINGS, 0, 0, "");
        out += h2_goaway_frame(0);
        (void)!send(cfd, out.data(), out.size(), MSG_NOSIGNAL);
        drain_socket_for(cfd, 1000);
        close(cfd);
    });

    Channel ch;
    ChannelOptions opts;
    opts.protocol = "grpc";
    opts.timeout_ms = 5000;
    opts.max_retry = 0;
    EndPoint ep;
    str2endpoint("127.0.0.1", ln.port, &ep);
    ASSERT_EQ(0, ch.Init(ep, &opts));
    test::EchoService_Stub stub(&ch);
    Controller cntl;
    test::EchoRequest req;
    req.set_message("goaway-me");
    test::EchoResponse res;
    const int64_t t0 = monotonic_time_us();
    stub.Echo(&cntl, &req, &res, nullptr);
    const int64_t elapsed_ms = (monotonic_time_us() - t0) / 1000;
    EXPECT_TRUE(cntl.Failed());
    EXPECT_EQ(TERR_DRAINING, cntl.ErrorCode()) << cntl.ErrorText();
    EXPECT_LT(elapsed_ms, 3000);  // failed on the GOAWAY, not the deadline
    // The connection is marked draining (new calls re-create the pin),
    // NOT failed (promised streams could still be completing on it).
    {
        SocketUniquePtr s;
        ASSERT_EQ(0, Socket::AddressSocket(ch.pinned_socket(), &s));
        EXPECT_TRUE(s->Draining());
    }
    raw_server.join();
}

TEST(Drain, H2ErrorGoawayIsNotADrain) {
    // GOAWAY with a non-zero error code (ENHANCE_YOUR_CALM = 0xb) is the
    // server REJECTING the connection, not draining politely: the budget-
    // free TERR_DRAINING fast-path must NOT apply (a shedding server
    // must not be hit by free re-issues), and the socket must be failed,
    // not merely marked draining.
    RawListener ln;
    ASSERT_TRUE(ln.open());
    std::thread raw_server([&ln] {
        const int cfd = ::accept(ln.lfd, nullptr, nullptr);
        if (cfd < 0) return;
        drain_socket_for(cfd, 150);
        std::string out = h2::BuildFrame(h2::H2_SETTINGS, 0, 0, "");
        out += h2_goaway_frame(0, 0xb);  // ENHANCE_YOUR_CALM
        (void)!send(cfd, out.data(), out.size(), MSG_NOSIGNAL);
        drain_socket_for(cfd, 1000);
        close(cfd);
    });

    Channel ch;
    ChannelOptions opts;
    opts.protocol = "grpc";
    opts.timeout_ms = 5000;
    opts.max_retry = 0;
    EndPoint ep;
    str2endpoint("127.0.0.1", ln.port, &ep);
    ASSERT_EQ(0, ch.Init(ep, &opts));
    test::EchoService_Stub stub(&ch);
    Controller cntl;
    test::EchoRequest req;
    req.set_message("calm-down");
    test::EchoResponse res;
    stub.Echo(&cntl, &req, &res, nullptr);
    EXPECT_TRUE(cntl.Failed());
    EXPECT_NE(TERR_DRAINING, cntl.ErrorCode()) << cntl.ErrorText();
    {
        SocketUniquePtr s;
        // Failed (or already recycled) — NOT live-and-draining.
        if (Socket::AddressSocket(ch.pinned_socket(), &s) == 0) {
            EXPECT_TRUE(s->Failed());
        }
    }
    raw_server.join();
}

TEST(Drain, H2RefusedStreamFailsAsDraining) {
    // RST_STREAM(REFUSED_STREAM) guarantees no server-side processing
    // (RFC 9113 §8.7) — the server sends it for streams that race its
    // GOAWAY. The client must surface TERR_DRAINING (budget-free
    // retriable) rather than the generic TERR_RESPONSE.
    RawListener ln;
    ASSERT_TRUE(ln.open());
    std::thread raw_server([&ln] {
        const int cfd = ::accept(ln.lfd, nullptr, nullptr);
        if (cfd < 0) return;
        drain_socket_for(cfd, 150);
        std::string out = h2::BuildFrame(h2::H2_SETTINGS, 0, 0, "");
        uint32_t code = htonl(0x7);  // REFUSED_STREAM
        out += h2::BuildFrame(h2::H2_RST_STREAM, 0, 1,
                              std::string((const char*)&code, 4));
        (void)!send(cfd, out.data(), out.size(), MSG_NOSIGNAL);
        drain_socket_for(cfd, 1000);
        close(cfd);
    });

    Channel ch;
    ChannelOptions opts;
    opts.protocol = "grpc";
    opts.timeout_ms = 5000;
    opts.max_retry = 0;
    EndPoint ep;
    str2endpoint("127.0.0.1", ln.port, &ep);
    ASSERT_EQ(0, ch.Init(ep, &opts));
    test::EchoService_Stub stub(&ch);
    Controller cntl;
    test::EchoRequest req;
    req.set_message("refuse-me");
    test::EchoResponse res;
    stub.Echo(&cntl, &req, &res, nullptr);
    EXPECT_TRUE(cntl.Failed());
    EXPECT_EQ(TERR_DRAINING, cntl.ErrorCode()) << cntl.ErrorText();
    raw_server.join();
}

TEST(Drain, H2GoawayKeepsPromisedStreams) {
    // GOAWAY with last-stream-id = 1 while stream 1 is in flight: the
    // server promised to answer it — the call must complete normally.
    RawListener ln;
    ASSERT_TRUE(ln.open());
    std::string resp_pb;
    {
        test::EchoResponse r;
        r.set_message("drained-ok");
        r.SerializeToString(&resp_pb);
    }
    std::thread raw_server([&ln, resp_pb] {
        const int cfd = ::accept(ln.lfd, nullptr, nullptr);
        if (cfd < 0) return;
        drain_socket_for(cfd, 150);
        using namespace tpurpc::h2;
        std::string out = BuildFrame(H2_SETTINGS, 0, 0, "");
        out += h2_goaway_frame(1);  // stream 1 WILL be answered
        // Full grpc unary response for stream 1: headers, one DATA with
        // the 5-byte prefix, grpc-status 0 trailers.
        AppendHeadersFrames(
            &out, kFlagEndHeaders, 1,
            EncodeHeaderBlock({{":status", "200"},
                               {"content-type", "application/grpc"}}));
        std::string body;
        body.push_back('\0');
        const uint32_t len = htonl((uint32_t)resp_pb.size());
        body.append((const char*)&len, 4);
        body += resp_pb;
        AppendFrame(&out, H2_DATA, 0, 1, body.data(), body.size());
        AppendHeadersFrames(&out,
                            (uint8_t)(kFlagEndHeaders | kFlagEndStream), 1,
                            EncodeHeaderBlock({{"grpc-status", "0"}}));
        (void)!send(cfd, out.data(), out.size(), MSG_NOSIGNAL);
        drain_socket_for(cfd, 1000);
        close(cfd);
    });

    Channel ch;
    ChannelOptions opts;
    opts.protocol = "grpc";
    opts.timeout_ms = 5000;
    opts.max_retry = 0;
    EndPoint ep;
    str2endpoint("127.0.0.1", ln.port, &ep);
    ASSERT_EQ(0, ch.Init(ep, &opts));
    test::EchoService_Stub stub(&ch);
    Controller cntl;
    test::EchoRequest req;
    req.set_message("promised");
    test::EchoResponse res;
    stub.Echo(&cntl, &req, &res, nullptr);
    EXPECT_FALSE(cntl.Failed()) << cntl.ErrorText();
    EXPECT_EQ(res.message(), "drained-ok");
    raw_server.join();
}
