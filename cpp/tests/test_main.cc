// Single test runner for all C++ unit tests (reference keeps one gtest main
// per suite, test/butil_unittest_main.cpp:19-41; we link everything into one
// binary because the build host has a single core).
#include "ttest/ttest.h"

// LeakSanitizer cannot scan parked fiber stacks (pooled mmap regions the
// sanitizer runtime does not know about), so heap objects whose ONLY live
// reference sits on a parked fiber's stack at process exit — IOBuf blocks
// pinned by read/write fibers, naming-service node vectors on the sleeping
// refresh fiber — are misreported as leaks. Suppress exactly those
// allocation sites; any other leak stays fatal. (The reference ships ASan
// fiber-switch annotations for the same reason; LSan has no equivalent
// hook for custom stacks.)
extern "C" const char* __lsan_default_suppressions() {
    return "leak:tpurpc::IOPortal::append_from_file_descriptor\n"
           "leak:tpurpc::NSNode\n"
           "leak:tpurpc::ListNamingService\n";
}

int main(int argc, char** argv) { return ttest::run_all(argc, argv); }
