// Single test runner for all C++ unit tests (reference keeps one gtest main
// per suite, test/butil_unittest_main.cpp:19-41; we link everything into one
// binary because the build host has a single core).
#include "ttest/ttest.h"

int main(int argc, char** argv) { return ttest::run_all(argc, argv); }
