// One-sided verb plane unit tests (ISSUE 18): window grant/mode/bounds
// guards, epoch fencing and lease-expiry/peer-death reclamation, the
// loopback scatter-gather round-trip through a doorbell CompletionQueue,
// SIGKILL-mid-verb reclamation, and the CQ exactly-once arbitration
// under an 8-thread duplicate-delivery race.
//
// Everything here is protobuf-free: the suite also links into the
// standalone (toolchain-less container) harness — test_main + this file
// + tici/{verbs,block_pool,block_lease}.cc + tnet/{transport,
// fault_injection}.cc and the tbase/tvar deps — where the race test is
// the ASan/UBSan acceptance gate.
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "tbase/errno.h"
#include "tbase/iobuf.h"
#include "tbase/time.h"
#include "tici/block_lease.h"
#include "tici/block_pool.h"
#include "tici/verbs.h"
#include "ttest/ttest.h"

using namespace tpurpc;

TEST(Verbs, WindowGrantModesBoundsAndStaleEpoch) {
    ASSERT_EQ(0, IciBlockPool::Init());
    const uint64_t pinned0 = block_lease::pinned();
    const size_t wins0 = verbs::window_count();

    verbs::WindowInfo info;
    ASSERT_EQ(0, verbs::GrantWindow(/*peer=*/0, 32768,
                                    verbs::kWinRead | verbs::kWinWrite,
                                    60 * 1000, &info));
    ASSERT_NE(0ull, info.window_id);
    EXPECT_EQ(IciBlockPool::pool_id(), info.pool_id);
    EXPECT_EQ(IciBlockPool::pool_epoch(), info.epoch);
    EXPECT_EQ(32768ull, info.length);
    EXPECT_EQ(pinned0 + 1, block_lease::pinned());
    EXPECT_EQ(wins0 + 1, verbs::window_count());

    // Valid resolve: the span is registered pool memory.
    char* p = nullptr;
    ASSERT_EQ(0, verbs::WindowPtr(info.window_id, 0, 32768, info.epoch,
                                  verbs::kWinWrite, &p));
    ASSERT_TRUE(p != nullptr);
    EXPECT_TRUE(IciBlockPool::Contains(p));

    // Epoch fence: a descriptor minted under another generation is the
    // RETRIABLE stale error, never a pointer.
    EXPECT_EQ(TERR_STALE_EPOCH,
              verbs::WindowPtr(info.window_id, 0, 100, info.epoch + 1,
                               verbs::kWinRead, &p));
    // Bounds: len past the window end.
    EXPECT_EQ(TERR_REQUEST,
              verbs::WindowPtr(info.window_id, 32000, 1000, info.epoch,
                               verbs::kWinRead, &p));
    // Unknown window id: stale (a reclaimed id must NEVER hand out
    // recycled bytes).
    EXPECT_EQ(TERR_STALE_EPOCH,
              verbs::WindowPtr(info.window_id + 999, 0, 100, info.epoch,
                               verbs::kWinRead, &p));

    // Mode enforcement: a read-only grant refuses writes.
    verbs::WindowInfo ro;
    ASSERT_EQ(0,
              verbs::GrantWindow(0, 8192, verbs::kWinRead, 60000, &ro));
    EXPECT_EQ(0, verbs::WindowPtr(ro.window_id, 0, 100, ro.epoch,
                                  verbs::kWinRead, &p));
    EXPECT_EQ(TERR_REQUEST, verbs::WindowPtr(ro.window_id, 0, 100,
                                             ro.epoch, verbs::kWinWrite,
                                             &p));

    // Close releases the pin exactly once.
    EXPECT_TRUE(verbs::CloseWindow(info.window_id));
    EXPECT_FALSE(verbs::CloseWindow(info.window_id));
    EXPECT_EQ(TERR_STALE_EPOCH,
              verbs::WindowPtr(info.window_id, 0, 100, info.epoch,
                               verbs::kWinRead, &p));
    EXPECT_TRUE(verbs::CloseWindow(ro.window_id));
    EXPECT_EQ(pinned0, block_lease::pinned());
    EXPECT_EQ(wins0, verbs::window_count());
}

TEST(Verbs, LeaseExpiryAndPeerDeathReclaimWindows) {
    ASSERT_EQ(0, IciBlockPool::Init());
    const uint64_t pinned0 = block_lease::pinned();
    char* p = nullptr;

    // Peer death reclaims exactly that peer's grants (the SIGKILL path:
    // server_call::OnSocketFailed -> verbs::OnPeerDead).
    verbs::WindowInfo w1, w2, w3;
    ASSERT_EQ(0, verbs::GrantWindow(111, 8192, verbs::kWinWrite, 60000,
                                    &w1));
    ASSERT_EQ(0, verbs::GrantWindow(111, 8192, verbs::kWinWrite, 60000,
                                    &w2));
    ASSERT_EQ(0, verbs::GrantWindow(222, 8192, verbs::kWinWrite, 60000,
                                    &w3));
    EXPECT_EQ(pinned0 + 3, block_lease::pinned());
    verbs::OnPeerDead(111);
    EXPECT_EQ(TERR_STALE_EPOCH,
              verbs::WindowPtr(w1.window_id, 0, 100, w1.epoch,
                               verbs::kWinWrite, &p));
    EXPECT_EQ(TERR_STALE_EPOCH,
              verbs::WindowPtr(w2.window_id, 0, 100, w2.epoch,
                               verbs::kWinWrite, &p));
    EXPECT_EQ(0, verbs::WindowPtr(w3.window_id, 0, 100, w3.epoch,
                                  verbs::kWinWrite, &p));
    EXPECT_EQ(pinned0 + 1, block_lease::pinned());
    EXPECT_TRUE(verbs::CloseWindow(w3.window_id));

    // Lease expiry: the reaper frees the pin through the same lease
    // machinery the descriptor plane uses; the window answers stale
    // from then on (and the stale resolve erases the husk).
    verbs::WindowInfo we;
    ASSERT_EQ(0,
              verbs::GrantWindow(0, 8192, verbs::kWinWrite, 50, &we));
    EXPECT_EQ(0, verbs::WindowPtr(we.window_id, 0, 100, we.epoch,
                                  verbs::kWinWrite, &p));
    EXPECT_GE(block_lease::ReapExpired(monotonic_time_us() +
                                       (int64_t)3600e6),
              (size_t)1);
    EXPECT_EQ(TERR_STALE_EPOCH,
              verbs::WindowPtr(we.window_id, 0, 100, we.epoch,
                               verbs::kWinWrite, &p));
    EXPECT_FALSE(verbs::CloseWindow(we.window_id));  // already gone
    EXPECT_EQ(pinned0, block_lease::pinned());
}

TEST(Verbs, LoopbackSglRoundTripThroughCompletionQueue) {
    ASSERT_EQ(0, IciBlockPool::Init());
    const uint64_t pinned0 = block_lease::pinned();
    constexpr size_t kBytes = 64 * 1024;
    constexpr uint32_t kNsge = 4;

    verbs::WindowInfo info;
    ASSERT_EQ(0, verbs::GrantWindow(0, kBytes,
                                    verbs::kWinRead | verbs::kWinWrite,
                                    60000, &info));
    verbs::RemoteWindow w;
    w.window_id = info.window_id;
    w.pool_id = info.pool_id;
    w.offset = info.offset;
    w.length = info.length;
    w.epoch = info.epoch;
    w.mode = info.mode;
    w.peer = 0;  // loopback: the direct memcpy path
    w.deadline_us = monotonic_time_us() + (int64_t)60e6;

    std::string src(kBytes, 0);
    for (size_t i = 0; i < kBytes; ++i) src[i] = (char)(i * 2654435761u >> 9);
    verbs::CompletionQueue cq;
    verbs::Sge sgl[kNsge];
    const size_t piece = kBytes / kNsge;
    for (uint32_t i = 0; i < kNsge; ++i) {
        sgl[i].addr = &src[i * piece];
        sgl[i].len = piece;
    }
    const int64_t posted0 = verbs::posted();
    ASSERT_EQ(0, verbs::PostWrite(&cq, 71, w, 0, sgl, kNsge));
    verbs::Completion c;
    ASSERT_TRUE(cq.Park(&c, 5 * 1000 * 1000));
    EXPECT_EQ(71ull, c.wr_id);
    EXPECT_EQ(0, c.status);
    EXPECT_EQ((uint64_t)kBytes, c.bytes);
    EXPECT_EQ((int)verbs::kRemoteWrite, c.op);
    EXPECT_EQ(posted0 + 1, verbs::posted());

    // The gathered SGL landed contiguously in the granted window.
    char* wp = nullptr;
    ASSERT_EQ(0, verbs::WindowPtr(info.window_id, 0, kBytes, info.epoch,
                                  verbs::kWinRead, &wp));
    EXPECT_EQ(0, memcmp(wp, src.data(), kBytes));

    // REMOTE_READ scatters the window back across a fresh SGL.
    std::string dst(kBytes, 0);
    for (uint32_t i = 0; i < kNsge; ++i) sgl[i].addr = &dst[i * piece];
    ASSERT_EQ(0, verbs::PostRead(&cq, 72, w, 0, sgl, kNsge));
    ASSERT_TRUE(cq.Park(&c, 5 * 1000 * 1000));
    EXPECT_EQ(72ull, c.wr_id);
    EXPECT_EQ(0, c.status);
    EXPECT_EQ(0, memcmp(dst.data(), src.data(), kBytes));

    // Shape guards: SGL above the cap, span past the window end, and a
    // verb against a mode the grant never gave are refused at post time.
    std::vector<verbs::Sge> many(verbs::kDefaultSglMax + 1);
    for (auto& sg : many) {
        sg.addr = &src[0];
        sg.len = 1;
    }
    EXPECT_EQ(TERR_REQUEST,
              verbs::PostWrite(&cq, 73, w, 0, many.data(),
                               (uint32_t)many.size()));
    EXPECT_EQ(TERR_REQUEST,
              verbs::PostWrite(&cq, 74, w, kBytes - 100, sgl, kNsge));

    // A post under a moved epoch completes TERR_STALE_EPOCH through the
    // CQ — the initiator-side fence, not a wedge and not stale bytes.
    verbs::RemoteWindow stale = w;
    stale.epoch = w.epoch + 1;
    ASSERT_EQ(0, verbs::PostRead(&cq, 75, stale, 0, sgl, kNsge));
    ASSERT_TRUE(cq.Park(&c, 5 * 1000 * 1000));
    EXPECT_EQ(75ull, c.wr_id);
    EXPECT_EQ(TERR_STALE_EPOCH, c.status);

    // A post whose grant lease already ended locally: same fence.
    verbs::RemoteWindow expired = w;
    expired.deadline_us = monotonic_time_us() - 1;
    ASSERT_EQ(0, verbs::PostWrite(&cq, 76, expired, 0, sgl, kNsge));
    ASSERT_TRUE(cq.Park(&c, 5 * 1000 * 1000));
    EXPECT_EQ(TERR_STALE_EPOCH, c.status);

    EXPECT_EQ((size_t)0, verbs::pending_posts());
    EXPECT_TRUE(verbs::CloseWindow(info.window_id));
    EXPECT_EQ(pinned0, block_lease::pinned());
    cq.Shutdown();
}

namespace {

// Wire-sender stub that swallows posts: the verb stays pending until a
// completion (or peer death / the reaper) finishes it — the seam the
// exactly-once and SIGKILL tests race against.
int SwallowVerbSend(uint64_t, int, uint64_t, uint64_t, uint64_t,
                    uint64_t, uint64_t, uint32_t, const IOBuf&) {
    return 0;
}
bool NeverOneSided(uint64_t) { return false; }

}  // namespace

TEST(Verbs, SigkillMidVerbStrandsZeroPinsAndFailsPendingPosts) {
    // The chaos-soak invariant at unit scale: a peer that dies with
    // verbs in flight against its link must strand neither the grantor
    // pins nor the initiator's parked completion.
    ASSERT_EQ(0, IciBlockPool::Init());
    verbs::SetVerbWireSender(&SwallowVerbSend);
    verbs::SetOneSidedProbe(&NeverOneSided);
    const uint64_t pinned0 = block_lease::pinned();

    // Grantor side: two windows leased to the doomed peer.
    verbs::WindowInfo g1, g2;
    ASSERT_EQ(0, verbs::GrantWindow(777, 16384, verbs::kWinWrite, 60000,
                                    &g1));
    ASSERT_EQ(0, verbs::GrantWindow(777, 16384, verbs::kWinRead, 60000,
                                    &g2));
    EXPECT_EQ(pinned0 + 2, block_lease::pinned());

    // Initiator side: a write in flight TOWARD the doomed peer (the
    // swallow sender models the SIGKILL landing mid-verb: posted on the
    // wire, no completion will ever come back).
    char payload[4096];
    memset(payload, 'v', sizeof(payload));
    verbs::Sge sge{payload, sizeof(payload)};
    verbs::RemoteWindow rw;
    rw.window_id = 4242;  // the peer's window; never resolved locally
    rw.pool_id = 0xdead;
    rw.length = sizeof(payload);
    rw.epoch = 1;
    rw.mode = verbs::kWinWrite;
    rw.peer = 777;
    rw.deadline_us = monotonic_time_us() + (int64_t)60e6;
    verbs::CompletionQueue cq;
    ASSERT_EQ(0, verbs::PostWrite(&cq, 91, rw, 0, &sge, 1));
    EXPECT_GE(verbs::pending_posts(), (size_t)1);

    // The socket failure observer fires for the dead peer.
    verbs::OnPeerDead(777);

    // Grantor pins: both reclaimed, staleness fences the ids forever.
    char* p = nullptr;
    EXPECT_EQ(pinned0, block_lease::pinned());
    EXPECT_EQ(TERR_STALE_EPOCH,
              verbs::WindowPtr(g1.window_id, 0, 100, g1.epoch,
                               verbs::kWinWrite, &p));
    // Initiator: the pending post completes with a terminal error
    // instead of wedging its parked poller.
    verbs::Completion c;
    ASSERT_TRUE(cq.Park(&c, 5 * 1000 * 1000));
    EXPECT_EQ(91ull, c.wr_id);
    EXPECT_NE(0, c.status);
    EXPECT_EQ((size_t)0, verbs::pending_posts());
    cq.Shutdown();
}

TEST(Verbs, CqExactlyOnceUnder8ThreadCompletionRace) {
    // Exactly-once arbitration: wire completion, reaper timeout, and
    // peer-death sweep may all race to finish the same wr_id — the
    // pending-erase is the arbitration point, so each post surfaces in
    // its CQ EXACTLY once no matter how many deliverers fire.
    ASSERT_EQ(0, IciBlockPool::Init());
    verbs::SetVerbWireSender(&SwallowVerbSend);
    verbs::SetOneSidedProbe(&NeverOneSided);
    constexpr int kPosts = 100;
    constexpr int kThreads = 8;

    char payload[512];
    memset(payload, 'x', sizeof(payload));
    verbs::Sge sge{payload, sizeof(payload)};
    verbs::RemoteWindow rw;
    rw.window_id = 5151;
    rw.pool_id = 0xbeef;
    rw.length = sizeof(payload);
    rw.epoch = 1;
    rw.mode = verbs::kWinWrite;
    rw.peer = 778;
    rw.deadline_us = monotonic_time_us() + (int64_t)60e6;
    verbs::CompletionQueue cq;
    for (int i = 0; i < kPosts; ++i) {
        ASSERT_EQ(0, verbs::PostWrite(&cq, 1000 + (uint64_t)i, rw, 0,
                                      &sge, 1));
    }
    ASSERT_GE(verbs::pending_posts(), (size_t)kPosts);

    // 8 threads each deliver a completion for EVERY wr_id — 8x
    // duplicate delivery of all 100 posts, concurrently.
    std::vector<std::thread> threads;
    for (int t = 0; t < kThreads; ++t) {
        threads.emplace_back([] {
            for (int i = 0; i < kPosts; ++i) {
                verbs::HandleWireCompletion(1000 + (uint64_t)i, 0,
                                            IOBuf(), 0);
            }
        });
    }
    for (auto& th : threads) th.join();

    // Drain: exactly kPosts completions, all distinct wr_ids.
    std::vector<int> seen(kPosts, 0);
    verbs::Completion c;
    int drained = 0;
    while (cq.Poll(&c)) {
        const int idx = (int)(c.wr_id - 1000);
        ASSERT_GE(idx, 0);
        ASSERT_LT(idx, kPosts);
        seen[idx]++;
        drained++;
    }
    EXPECT_EQ(kPosts, drained);
    for (int i = 0; i < kPosts; ++i) {
        EXPECT_EQ(1, seen[i]);
    }
    EXPECT_EQ((size_t)0, verbs::pending_posts());
    cq.Shutdown();
}
