// Combo-channel tests: ParallelChannel fan-out + merge, fail_limit,
// CallMapper skip, PartitionChannel tag routing, SelectiveChannel
// retry-on-another-channel, DynamicPartitionChannel capacity choice.
// In-process loopback servers, the reference's test style
// (test/brpc_channel_unittest.cpp combo-channel sections).
#include <atomic>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

#include "echo.pb.h"
#include "tbase/errno.h"
#include "tbase/time.h"
#include "tfiber/fiber_sync.h"
#include "trpc/combo_channels.h"
#include "trpc/controller.h"
#include "trpc/server.h"
#include "trpc/server_call.h"
#include "ttest/ttest.h"

using namespace tpurpc;

namespace {

// Echo server whose responses are prefixed with its name (merge order is
// observable) and which can fail on demand.
class NamedEchoService : public test::EchoService {
public:
    explicit NamedEchoService(std::string name) : name_(std::move(name)) {}
    void Echo(google::protobuf::RpcController* cntl_base,
              const test::EchoRequest* req, test::EchoResponse* res,
              google::protobuf::Closure* done) override {
        ncalls.fetch_add(1, std::memory_order_relaxed);
        if (fail.load(std::memory_order_relaxed)) {
            static_cast<Controller*>(cntl_base)
                ->SetFailed(ECONNABORTED, "injected");
        } else {
            res->set_message(name_ + ":" + req->message());
        }
        done->Run();
    }
    std::string name_;
    std::atomic<int> ncalls{0};
    std::atomic<bool> fail{false};
};

struct TestServer {
    explicit TestServer(const std::string& name) : service(name) {
        server.AddService(&service);
        EndPoint any;
        str2endpoint("127.0.0.1:0", &any);
        server.Start(any, nullptr);
    }
    int port() const { return server.listened_port(); }
    std::string addr() const {
        return "127.0.0.1:" + std::to_string(port());
    }
    NamedEchoService service;
    Server server;
};

// Concatenating merger: parent message += "|" + sub message.
class ConcatMerger : public ResponseMerger {
public:
    int Merge(google::protobuf::Message* response,
              const google::protobuf::Message* sub) override {
        auto* r = static_cast<test::EchoResponse*>(response);
        const auto* s = static_cast<const test::EchoResponse*>(sub);
        if (!r->message().empty()) {
            r->set_message(r->message() + "|" + s->message());
        } else {
            r->set_message(s->message());
        }
        return 0;
    }
};

}  // namespace

TEST(ParallelChannel, FanoutAndMergeInOrder) {
    TestServer s1("a"), s2("b"), s3("c");
    Channel c1, c2, c3;
    ChannelOptions copts;
    copts.timeout_ms = 3000;
    ASSERT_EQ(0, c1.Init(s1.addr().c_str(), &copts));
    ASSERT_EQ(0, c2.Init(s2.addr().c_str(), &copts));
    ASSERT_EQ(0, c3.Init(s3.addr().c_str(), &copts));

    ParallelChannel pc;
    ASSERT_EQ(0, pc.AddChannel(&c1, nullptr, new ConcatMerger));
    ASSERT_EQ(0, pc.AddChannel(&c2, nullptr, new ConcatMerger));
    ASSERT_EQ(0, pc.AddChannel(&c3, nullptr, new ConcatMerger));

    test::EchoService_Stub stub(&pc);
    Controller cntl;
    cntl.set_timeout_ms(3000);
    test::EchoRequest req;
    test::EchoResponse res;
    req.set_message("x");
    stub.Echo(&cntl, &req, &res, nullptr);
    ASSERT_FALSE(cntl.Failed());
    // Deterministic sub-channel index order regardless of completion order.
    EXPECT_EQ("a:x|b:x|c:x", res.message());
    EXPECT_EQ(1, s1.service.ncalls.load());
    EXPECT_EQ(1, s2.service.ncalls.load());
    EXPECT_EQ(1, s3.service.ncalls.load());
}

TEST(ParallelChannel, FailLimit) {
    TestServer good("g"), bad("b");
    bad.service.fail = true;
    Channel cg, cb;
    ChannelOptions copts;
    copts.timeout_ms = 3000;
    copts.max_retry = 0;
    ASSERT_EQ(0, cg.Init(good.addr().c_str(), &copts));
    ASSERT_EQ(0, cb.Init(bad.addr().c_str(), &copts));

    // fail_limit=2: one failure tolerated.
    ParallelChannelOptions popts;
    popts.fail_limit = 2;
    {
        ParallelChannel pc(&popts);
        ASSERT_EQ(0, pc.AddChannel(&cg, nullptr, new ConcatMerger));
        ASSERT_EQ(0, pc.AddChannel(&cb, nullptr, new ConcatMerger));
        test::EchoService_Stub stub(&pc);
        Controller cntl;
        cntl.set_max_retry(0);
        test::EchoRequest req;
        test::EchoResponse res;
        req.set_message("y");
        stub.Echo(&cntl, &req, &res, nullptr);
        EXPECT_FALSE(cntl.Failed());
        EXPECT_EQ("g:y", res.message());
    }
    // Default (unset) fail_limit: parent fails only when ALL sub-calls
    // fail (reference parallel_channel.h:165-167) — one failure of two is
    // tolerated and the successful response still merges.
    {
        ParallelChannel pc;
        ASSERT_EQ(0, pc.AddChannel(&cg, nullptr, new ConcatMerger));
        ASSERT_EQ(0, pc.AddChannel(&cb, nullptr, new ConcatMerger));
        test::EchoService_Stub stub(&pc);
        Controller cntl;
        cntl.set_max_retry(0);
        test::EchoRequest req;
        test::EchoResponse res;
        req.set_message("z");
        stub.Echo(&cntl, &req, &res, nullptr);
        EXPECT_FALSE(cntl.Failed()) << cntl.ErrorText();
        EXPECT_EQ("g:z", res.message());
    }
    // fail_limit=1: any failure fails the parent, and the user response
    // stays untouched (no partial merge beside a failed controller).
    {
        ParallelChannelOptions strict;
        strict.fail_limit = 1;
        ParallelChannel pc(&strict);
        ASSERT_EQ(0, pc.AddChannel(&cg, nullptr, new ConcatMerger));
        ASSERT_EQ(0, pc.AddChannel(&cb, nullptr, new ConcatMerger));
        test::EchoService_Stub stub(&pc);
        Controller cntl;
        cntl.set_max_retry(0);
        test::EchoRequest req;
        test::EchoResponse res;
        req.set_message("w");
        stub.Echo(&cntl, &req, &res, nullptr);
        EXPECT_TRUE(cntl.Failed());
        EXPECT_EQ("", res.message());
    }
    // Default fail_limit, every sub-call failing: parent fails.
    {
        ParallelChannel pc;
        ASSERT_EQ(0, pc.AddChannel(&cb, nullptr, new ConcatMerger));
        ASSERT_EQ(0, pc.AddChannel(&cb, nullptr, new ConcatMerger));
        test::EchoService_Stub stub(&pc);
        Controller cntl;
        cntl.set_max_retry(0);
        test::EchoRequest req;
        test::EchoResponse res;
        req.set_message("v");
        stub.Echo(&cntl, &req, &res, nullptr);
        EXPECT_TRUE(cntl.Failed());
    }
}

namespace {

// Maps only even-indexed sub-channels; odd ones are skipped.
class EvenOnlyMapper : public CallMapper {
public:
    SubCall Map(int channel_index, int channel_count,
                const google::protobuf::MethodDescriptor* method,
                const google::protobuf::Message* request,
                google::protobuf::Message* response) override {
        (void)channel_count;
        (void)method;
        (void)request;
        (void)response;
        if (channel_index % 2 != 0) return SubCall::Skip();
        return SubCall{};  // defaults: parent method/request, fresh response
    }
};

}  // namespace

TEST(ParallelChannel, MapperSkipsSubChannels) {
    TestServer s1("a"), s2("b"), s3("c");
    Channel c1, c2, c3;
    ChannelOptions copts;
    copts.timeout_ms = 3000;
    ASSERT_EQ(0, c1.Init(s1.addr().c_str(), &copts));
    ASSERT_EQ(0, c2.Init(s2.addr().c_str(), &copts));
    ASSERT_EQ(0, c3.Init(s3.addr().c_str(), &copts));

    ParallelChannel pc;
    ASSERT_EQ(0, pc.AddChannel(&c1, new EvenOnlyMapper, new ConcatMerger));
    ASSERT_EQ(0, pc.AddChannel(&c2, new EvenOnlyMapper, new ConcatMerger));
    ASSERT_EQ(0, pc.AddChannel(&c3, new EvenOnlyMapper, new ConcatMerger));

    test::EchoService_Stub stub(&pc);
    Controller cntl;
    test::EchoRequest req;
    test::EchoResponse res;
    req.set_message("m");
    stub.Echo(&cntl, &req, &res, nullptr);
    ASSERT_FALSE(cntl.Failed());
    EXPECT_EQ("a:m|c:m", res.message());
    EXPECT_EQ(0, s2.service.ncalls.load());
}

TEST(ParallelChannel, AsyncFanout) {
    TestServer s1("a"), s2("b");
    Channel c1, c2;
    ChannelOptions copts;
    copts.timeout_ms = 3000;
    ASSERT_EQ(0, c1.Init(s1.addr().c_str(), &copts));
    ASSERT_EQ(0, c2.Init(s2.addr().c_str(), &copts));
    ParallelChannel pc;
    ASSERT_EQ(0, pc.AddChannel(&c1, nullptr, new ConcatMerger));
    ASSERT_EQ(0, pc.AddChannel(&c2, nullptr, new ConcatMerger));

    struct Ctx {
        Controller cntl;
        test::EchoRequest req;
        test::EchoResponse res;
        CountdownEvent ev{1};
        static void Done(Ctx* c) { c->ev.signal(); }
    } ctx;
    ctx.req.set_message("q");
    test::EchoService_Stub stub(&pc);
    stub.Echo(&ctx.cntl, &ctx.req, &ctx.res,
              google::protobuf::NewCallback(&Ctx::Done, &ctx));
    ctx.ev.wait();
    ASSERT_FALSE(ctx.cntl.Failed());
    EXPECT_EQ("a:q|b:q", ctx.res.message());
}

TEST(PartitionChannel, RoutesByTag) {
    TestServer p0("p0"), p1("p1");
    char url[256];
    snprintf(url, sizeof(url), "list://%s 0/2,%s 1/2", p0.addr().c_str(),
             p1.addr().c_str());
    PartitionChannel pc;
    PartitionChannelOptions opts;
    opts.timeout_ms = 3000;
    opts.response_merger = new ConcatMerger;
    ASSERT_EQ(0, pc.Init(url, "rr", nullptr, &opts));
    EXPECT_EQ(2, pc.partition_count());

    test::EchoService_Stub stub(&pc);
    Controller cntl;
    test::EchoRequest req;
    test::EchoResponse res;
    req.set_message("k");
    stub.Echo(&cntl, &req, &res, nullptr);
    ASSERT_FALSE(cntl.Failed());
    // Both partitions served the fan-out.
    EXPECT_EQ(1, p0.service.ncalls.load());
    EXPECT_EQ(1, p1.service.ncalls.load());
    EXPECT_EQ("p0:k|p1:k", res.message());
}

TEST(PartitionChannel, IncompleteSchemeFailsInit) {
    TestServer p0("p0");
    char url[128];
    snprintf(url, sizeof(url), "list://%s 0/2", p0.addr().c_str());
    PartitionChannel pc;
    EXPECT_NE(0, pc.Init(url, "rr", nullptr, nullptr));
}

TEST(SelectiveChannel, RetriesOnAnotherChannel) {
    TestServer good("g"), bad("b");
    bad.service.fail = true;
    Channel cg, cb;
    ChannelOptions copts;
    copts.timeout_ms = 3000;
    copts.max_retry = 0;
    ASSERT_EQ(0, cg.Init(good.addr().c_str(), &copts));
    ASSERT_EQ(0, cb.Init(bad.addr().c_str(), &copts));

    SelectiveChannel sc;
    ASSERT_EQ(0, sc.AddChannel(&cb));  // rr starts somewhere; retries cover
    ASSERT_EQ(0, sc.AddChannel(&cg));

    test::EchoService_Stub stub(&sc);
    int ok = 0;
    for (int i = 0; i < 8; ++i) {
        Controller cntl;
        cntl.set_max_retry(2);
        cntl.set_timeout_ms(3000);
        test::EchoRequest req;
        test::EchoResponse res;
        req.set_message("s");
        stub.Echo(&cntl, &req, &res, nullptr);
        if (!cntl.Failed()) {
            ++ok;
            EXPECT_EQ("g:s", res.message());
        }
    }
    // Every call lands on the good server eventually (retry hops away
    // from the failing channel).
    EXPECT_EQ(8, ok);
    EXPECT_GE(good.service.ncalls.load(), 8);
}

TEST(DynamicPartitionChannel, PicksLargestScheme) {
    TestServer a0("a0"), b0("b0"), b1("b1"), b2("b2");
    char url_small[128], url_big[384];
    snprintf(url_small, sizeof(url_small), "list://%s 0/1",
             a0.addr().c_str());
    snprintf(url_big, sizeof(url_big), "list://%s 0/3,%s 1/3,%s 2/3",
             b0.addr().c_str(), b1.addr().c_str(), b2.addr().c_str());
    DynamicPartitionChannel dc;
    PartitionChannelOptions opts;
    opts.timeout_ms = 3000;
    ASSERT_EQ(0, dc.Init({url_small, url_big}, "rr", &opts));
    EXPECT_EQ(1, dc.chosen_scheme());  // 3 servers > 1 server

    test::EchoService_Stub stub(&dc);
    Controller cntl;
    test::EchoRequest req;
    test::EchoResponse res;
    req.set_message("d");
    stub.Echo(&cntl, &req, &res, nullptr);
    ASSERT_FALSE(cntl.Failed());
    EXPECT_EQ(1, b0.service.ncalls.load());
    EXPECT_EQ(1, b1.service.ncalls.load());
    EXPECT_EQ(1, b2.service.ncalls.load());
    EXPECT_EQ(0, a0.service.ncalls.load());
}

// ---------------- ISSUE 13 satellites: sub-call context ----------------

namespace {

// Echoes the QoS/deadline context the SERVER observed, so tests can
// assert what actually crossed the wire for combo sub-calls.
class ContextEchoService : public test::EchoService {
public:
    void Echo(google::protobuf::RpcController* cntl_base,
              const test::EchoRequest* req, test::EchoResponse* res,
              google::protobuf::Closure* done) override {
        Controller* cntl = static_cast<Controller*>(cntl_base);
        ncalls.fetch_add(1, std::memory_order_relaxed);
        if (fail.load(std::memory_order_relaxed)) {
            cntl->SetFailed(ECONNABORTED, "injected");
            done->Run();
            return;
        }
        const long long budget_ms =
            cntl->has_server_deadline()
                ? (long long)(cntl->remaining_server_budget_us() / 1000)
                : -1;
        char buf[128];
        snprintf(buf, sizeof(buf), "tenant=%s;prio=%d;budget_ms=%lld",
                 cntl->tenant().c_str(), cntl->priority(), budget_ms);
        res->set_message(req->message() + "|" + buf);
        cntl->response_attachment().append("att:");
        cntl->response_attachment().append(cntl->request_attachment());
        done->Run();
    }
    std::atomic<int> ncalls{0};
    std::atomic<bool> fail{false};
};

struct ContextServer {
    ContextServer() {
        server.AddService(&service);
        EndPoint any;
        str2endpoint("127.0.0.1:0", &any);
        server.Start(any, nullptr);
    }
    std::string addr() const {
        return "127.0.0.1:" + std::to_string(server.listened_port());
    }
    ContextEchoService service;
    Server server;
};

}  // namespace

TEST(ParallelChannel, SubCallsInheritTenantPriorityAndDeadline) {
    ContextServer s1, s2;
    Channel c1, c2;
    ChannelOptions copts;
    copts.timeout_ms = 5000;
    ASSERT_EQ(0, c1.Init(s1.addr().c_str(), &copts));
    ASSERT_EQ(0, c2.Init(s2.addr().c_str(), &copts));
    ParallelChannel pc;
    ASSERT_EQ(0, pc.AddChannel(&c1, nullptr, new ConcatMerger));
    ASSERT_EQ(0, pc.AddChannel(&c2, nullptr, new ConcatMerger));

    // Simulated upstream server call with 400ms of remaining budget:
    // sub-calls must run under it even though the parent timeout is 5s.
    Controller upstream;
    upstream.set_server_deadline_us(monotonic_time_us() + 400 * 1000);
    ServerCallScope scope(&upstream);

    test::EchoService_Stub stub(&pc);
    Controller cntl;
    cntl.set_timeout_ms(5000);
    cntl.set_tenant("gold-combo");
    cntl.set_priority(6);
    test::EchoRequest req;
    test::EchoResponse res;
    req.set_message("ctx");
    stub.Echo(&cntl, &req, &res, nullptr);
    ASSERT_FALSE(cntl.Failed());
    // Both sub-responses observed the parent's identity and a budget
    // capped at the upstream's remaining 400ms.
    size_t pos = 0;
    int found = 0;
    while ((pos = res.message().find("tenant=", pos)) !=
           std::string::npos) {
        ++found;
        const std::string part = res.message().substr(pos);
        EXPECT_TRUE(part.find("tenant=gold-combo;prio=6;") == 0);
        long long budget = -1;
        sscanf(part.c_str(), "tenant=gold-combo;prio=6;budget_ms=%lld",
               &budget);
        EXPECT_GT(budget, 0);
        EXPECT_LE(budget, 400);
        ++pos;
    }
    EXPECT_EQ(2, found);
}

TEST(SelectiveChannel, RetryHopKeepsTenantPriorityAndDeadline) {
    ContextServer bad, good;
    bad.service.fail = true;
    Channel cb, cg;
    ChannelOptions copts;
    copts.timeout_ms = 5000;
    copts.max_retry = 0;
    ASSERT_EQ(0, cb.Init(bad.addr().c_str(), &copts));
    ASSERT_EQ(0, cg.Init(good.addr().c_str(), &copts));
    SelectiveChannel sc;
    ASSERT_EQ(0, sc.AddChannel(&cb));
    ASSERT_EQ(0, sc.AddChannel(&cg));

    Controller upstream;
    upstream.set_server_deadline_us(monotonic_time_us() + 600 * 1000);
    ServerCallScope scope(&upstream);

    // Every call eventually lands on the good server; the retry hop
    // fires on the completion fiber, where the upstream scope must be
    // REPLAYED for the context to survive (the regression this guards).
    test::EchoService_Stub stub(&sc);
    for (int i = 0; i < 4; ++i) {
        Controller cntl;
        cntl.set_timeout_ms(5000);
        cntl.set_max_retry(2);
        cntl.set_tenant("silver-combo");
        cntl.set_priority(3);
        test::EchoRequest req;
        test::EchoResponse res;
        req.set_message("hop");
        stub.Echo(&cntl, &req, &res, nullptr);
        ASSERT_FALSE(cntl.Failed());
        EXPECT_TRUE(res.message().find(
                        "tenant=silver-combo;prio=3;") !=
                    std::string::npos);
        long long budget = -1;
        const size_t p = res.message().find("budget_ms=");
        ASSERT_TRUE(p != std::string::npos);
        sscanf(res.message().c_str() + p, "budget_ms=%lld", &budget);
        EXPECT_GT(budget, 0);
        EXPECT_LE(budget, 600);
    }
    EXPECT_GE(good.service.ncalls.load(), 4);
}

TEST(SelectiveChannel, CrossChannelRetriesSpendRetryBudget) {
    ContextServer bad1, bad2;
    bad1.service.fail = true;
    bad2.service.fail = true;
    Channel c1, c2;
    ChannelOptions copts;
    copts.timeout_ms = 2000;
    copts.max_retry = 0;
    ASSERT_EQ(0, c1.Init(bad1.addr().c_str(), &copts));
    ASSERT_EQ(0, c2.Init(bad2.addr().c_str(), &copts));
    SelectiveChannel sc;
    ASSERT_EQ(0, sc.AddChannel(&c1));
    ASSERT_EQ(0, sc.AddChannel(&c2));
    // One burst token and no refill: of the 5 permitted hops only ONE
    // cross-channel retry may actually go out.
    sc.ConfigureRetryBudget(1, 0.0);

    test::EchoService_Stub stub(&sc);
    Controller cntl;
    cntl.set_timeout_ms(2000);
    cntl.set_max_retry(5);
    test::EchoRequest req;
    test::EchoResponse res;
    req.set_message("budget");
    stub.Echo(&cntl, &req, &res, nullptr);
    EXPECT_TRUE(cntl.Failed());
    EXPECT_EQ(2, bad1.service.ncalls.load() + bad2.service.ncalls.load());
    EXPECT_EQ(0, (int)sc.retry_budget().tokens());
}

namespace {

// Per-sub-call attachments out, per-sub-call responses observed — the
// combo extension the collective tier fans chunks out through.
class BlockMapper : public CallMapper {
public:
    explicit BlockMapper(SubCallObserver* obs) : obs_(obs) {}
    SubCall Map(int channel_index, int, const
                google::protobuf::MethodDescriptor*,
                const google::protobuf::Message*,
                google::protobuf::Message*) override {
        SubCall s;
        s.request_attachment.append("blk" +
                                    std::to_string(channel_index));
        s.observer = obs_;
        return s;
    }

private:
    SubCallObserver* obs_;
};

class CollectObserver : public SubCallObserver {
public:
    void OnSubCallDone(int channel_index, Controller& sub) override {
        std::lock_guard<std::mutex> g(mu);
        seen[channel_index] = sub.Failed()
                                  ? "FAILED"
                                  : sub.response_attachment().to_string();
    }
    std::mutex mu;
    std::map<int, std::string> seen;
};

}  // namespace

TEST(ParallelChannel, PerSubCallAttachmentsAndObserver) {
    ContextServer s1, s2, s3;
    Channel c1, c2, c3;
    ChannelOptions copts;
    copts.timeout_ms = 3000;
    ASSERT_EQ(0, c1.Init(s1.addr().c_str(), &copts));
    ASSERT_EQ(0, c2.Init(s2.addr().c_str(), &copts));
    ASSERT_EQ(0, c3.Init(s3.addr().c_str(), &copts));
    CollectObserver obs;
    auto mapper = std::make_shared<BlockMapper>(&obs);
    ParallelChannel pc;
    ASSERT_EQ(0, pc.AddChannelShared(&c1, mapper, nullptr));
    ASSERT_EQ(0, pc.AddChannelShared(&c2, mapper, nullptr));
    ASSERT_EQ(0, pc.AddChannelShared(&c3, mapper, nullptr));

    test::EchoService_Stub stub(&pc);
    Controller cntl;
    cntl.set_timeout_ms(3000);
    test::EchoRequest req;
    test::EchoResponse res;
    req.set_message("m");
    stub.Echo(&cntl, &req, &res, nullptr);
    ASSERT_FALSE(cntl.Failed());
    ASSERT_EQ(3u, obs.seen.size());
    for (int i = 0; i < 3; ++i) {
        EXPECT_EQ("att:blk" + std::to_string(i), obs.seen[i]);
    }
}
