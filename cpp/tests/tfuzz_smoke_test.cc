// Fuzz smoke on every test pass (VERDICT r5 item 10): run the existing
// http_fuzz / frame_fuzz corpora for a ~2-second total budget so the
// protocol parsers see fuzz input in CI, not only in ad-hoc runs. The
// fuzz drivers are the sibling tool binaries from the same build (like
// tshm_xproc_test execs echo_bench); each run is deterministic (fixed
// iteration count + seed) so a failure replays exactly.
#include <libgen.h>
#include <sys/wait.h>
#include <unistd.h>

#include <cstdio>
#include <string>

#include "ttest/ttest.h"

namespace {

std::string sibling_binary(const char* name) {
    char self[4096];
    const ssize_t n = readlink("/proc/self/exe", self, sizeof(self) - 1);
    if (n <= 0) return "";
    self[n] = '\0';
    return std::string(dirname(self)) + "/" + name;
}

// Run `bin iters seed`; returns the exit status (-1 on spawn failure).
int run_fuzzer(const std::string& bin, const char* iters, const char* seed) {
    const pid_t pid = fork();
    if (pid < 0) return -1;
    if (pid == 0) {
        // Quiet child: the drivers print a summary line we don't need in
        // test output; invariant violations go to stderr which we keep.
        freopen("/dev/null", "w", stdout);
        execl(bin.c_str(), bin.c_str(), iters, seed, (char*)nullptr);
        _exit(127);
    }
    int status = 0;
    if (waitpid(pid, &status, 0) != pid) return -1;
    if (WIFSIGNALED(status)) return 128 + WTERMSIG(status);
    return WIFEXITED(status) ? WEXITSTATUS(status) : -1;
}

}  // namespace

// Budgets tuned to ~1s each on the 1-core build host (the standalone
// drivers default to 1M/10M iterations for longer soaks).
TEST(FuzzSmoke, HttpParserCorpus) {
    const std::string bin = sibling_binary("http_fuzz");
    ASSERT_FALSE(bin.empty());
    EXPECT_EQ(0, run_fuzzer(bin, "120000", "20260803"));
}

TEST(FuzzSmoke, FrameParserCorpus) {
    const std::string bin = sibling_binary("frame_fuzz");
    ASSERT_FALSE(bin.empty());
    EXPECT_EQ(0, run_fuzzer(bin, "400000", "20260803"));
}
