// HTTP layer tests: request parser (valid / incremental / malformed),
// response serializer, handler routing, the live portal over a real TCP
// socket, and a deterministic fuzz loop over the parser (reference
// analog: test/brpc_http_message_unittest.cpp + test/fuzzing/fuzz_http.cpp).
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstring>
#include <string>

#include "bench_echo.pb.h"
#include "tbase/endpoint.h"
#include "tbase/fast_rand.h"
#include "tbase/flags.h"
#include "trpc/channel.h"
#include "trpc/controller.h"
#include "thttp/http_message.h"
#include "trpc/server.h"
#include "ttest/ttest.h"

using namespace tpurpc;

namespace {

HttpParseStatus feed(const std::string& bytes, HttpRequest* out) {
    IOBuf buf;
    buf.append(bytes);
    return ParseHttpRequest(&buf, out);
}

}  // namespace

TEST(HttpParse, SimpleGet) {
    HttpRequest req;
    ASSERT_EQ(HttpParseStatus::kOk,
              feed("GET /vars?x=1&y=2 HTTP/1.1\r\nHost: a\r\n"
                   "X-Test:  padded value  \r\n\r\n",
                   &req));
    EXPECT_EQ("GET", req.method);
    EXPECT_EQ("/vars", req.path);
    EXPECT_EQ("x=1&y=2", req.query);
    EXPECT_EQ("1", req.QueryParam("x"));
    EXPECT_EQ("2", req.QueryParam("y"));
    EXPECT_EQ("", req.QueryParam("z"));
    ASSERT_TRUE(req.FindHeader("host") != nullptr);  // case-insensitive
    EXPECT_EQ("a", *req.FindHeader("HOST"));
    EXPECT_EQ("padded value", *req.FindHeader("x-test"));
    EXPECT_EQ(1, req.version_major);
    EXPECT_EQ(1, req.version_minor);
}

TEST(HttpParse, PostWithBody) {
    HttpRequest req;
    ASSERT_EQ(HttpParseStatus::kOk,
              feed("POST /flags/x HTTP/1.0\r\nContent-Length: 5\r\n\r\n"
                   "hello",
                   &req));
    EXPECT_EQ("POST", req.method);
    EXPECT_EQ(0, req.version_minor);
    EXPECT_TRUE(req.body.equals("hello"));
}

TEST(HttpParse, UrlDecodeInPath) {
    HttpRequest req;
    ASSERT_EQ(HttpParseStatus::kOk,
              feed("GET /vars/a%20b HTTP/1.1\r\n\r\n", &req));
    EXPECT_EQ("/vars/a b", req.path);
}

TEST(HttpParse, IncrementalFeeding) {
    const std::string full =
        "GET /health HTTP/1.1\r\nHost: x\r\nContent-Length: 3\r\n\r\nabc";
    IOBuf buf;
    HttpRequest req;
    for (size_t i = 0; i < full.size(); ++i) {
        buf.append(&full[i], 1);
        const HttpParseStatus st = ParseHttpRequest(&buf, &req);
        if (i + 1 < full.size()) {
            ASSERT_EQ(HttpParseStatus::kNeedMore, st);
        } else {
            ASSERT_EQ(HttpParseStatus::kOk, st);
        }
    }
    EXPECT_EQ("/health", req.path);
    EXPECT_TRUE(req.body.equals("abc"));
    EXPECT_TRUE(buf.empty());  // fully consumed
}

TEST(HttpParse, NotHttpSniff) {
    HttpRequest req;
    // tpu_std frames start with their own magic: must yield kNotHttp so
    // the messenger tries other protocols.
    EXPECT_EQ(HttpParseStatus::kNotHttp, feed("TRPC\x01\x02\x03\x04", &req));
    EXPECT_EQ(HttpParseStatus::kNotHttp,
              feed(std::string("\x00\x00\x00\x01", 4), &req));
    // A strict prefix of a verb is ambiguous: need more.
    EXPECT_EQ(HttpParseStatus::kNeedMore, feed("GE", &req));
    EXPECT_EQ(HttpParseStatus::kNotHttp, feed("GEX", &req));
}

TEST(HttpParse, Malformed) {
    HttpRequest req;
    EXPECT_EQ(HttpParseStatus::kError,
              feed("GET /x HTTP/9x\r\n\r\n", &req));
    // "GET\r..." fails the verb+SP sniff: classified as another protocol
    // (the messenger fails the connection when nothing else matches).
    EXPECT_EQ(HttpParseStatus::kNotHttp, feed("GET\r\n\r\n", &req));
    EXPECT_EQ(HttpParseStatus::kError,
              feed("GET /x HTTP/1.1\r\nBad Header Name: v\r\n\r\n", &req));
    EXPECT_EQ(HttpParseStatus::kError,
              feed("GET /x HTTP/1.1\r\n: novalue\r\n\r\n", &req));
    EXPECT_EQ(HttpParseStatus::kError,
              feed("GET /x HTTP/1.1\r\nContent-Length: 1e9\r\n\r\n", &req));
    EXPECT_EQ(HttpParseStatus::kError,
              feed("GET /x HTTP/1.1\r\nContent-Length: 99999999999999\r\n"
                   "\r\n",
                   &req));
    EXPECT_EQ(HttpParseStatus::kError,
              feed("POST /x HTTP/1.1\r\nTransfer-Encoding: chunked\r\n\r\n",
                   &req));
    // Differing duplicate Content-Length: smuggling vector, reject.
    EXPECT_EQ(HttpParseStatus::kError,
              feed("POST /x HTTP/1.1\r\nContent-Length: 5\r\n"
                   "Content-Length: 50\r\n\r\nhello",
                   &req));
    // Identical duplicates are tolerated.
    EXPECT_EQ(HttpParseStatus::kOk,
              feed("POST /x HTTP/1.1\r\nContent-Length: 5\r\n"
                   "Content-Length: 5\r\n\r\nhello",
                   &req));
    // Oversized header section.
    std::string big = "GET /x HTTP/1.1\r\n";
    big += "A: " + std::string(70 * 1024, 'v') + "\r\n\r\n";
    EXPECT_EQ(HttpParseStatus::kError, feed(big, &req));
}

TEST(HttpParse, SerializeRoundTrip) {
    HttpResponse res;
    res.status = 404;
    res.set_content_type("text/plain");
    res.Append("gone");
    IOBuf out;
    SerializeHttpResponse(&res, &out);
    const std::string s = out.to_string();
    EXPECT_TRUE(s.find("HTTP/1.1 404 Not Found\r\n") == 0);
    EXPECT_TRUE(s.find("Content-Length: 4\r\n") != std::string::npos);
    EXPECT_TRUE(s.find("\r\n\r\ngone") != std::string::npos);
}

// Deterministic fuzz: seeded mutations of valid requests + raw random
// bytes. The parser must never crash, never loop, and on kOk must leave
// the source smaller (progress). Run harder via tools/http_fuzz.
TEST(HttpParse, FuzzSmoke) {
    const char* seeds[] = {
        "GET / HTTP/1.1\r\nHost: a\r\n\r\n",
        "POST /flags/x?setvalue=1 HTTP/1.1\r\nContent-Length: 4\r\n\r\nbody",
        "HEAD /vars HTTP/1.0\r\nAccept: */*\r\nX: y\r\n\r\n",
    };
    uint64_t rng = 12345;
    auto next = [&rng]() {
        rng ^= rng << 13;
        rng ^= rng >> 7;
        rng ^= rng << 17;
        return rng;
    };
    for (int iter = 0; iter < 20000; ++iter) {
        std::string input = seeds[next() % 3];
        const int nmut = 1 + (int)(next() % 8);
        for (int m = 0; m < nmut; ++m) {
            switch (next() % 4) {
                case 0:  // flip a byte
                    input[next() % input.size()] = (char)next();
                    break;
                case 1:  // truncate
                    input.resize(next() % (input.size() + 1));
                    break;
                case 2:  // duplicate a chunk
                    if (!input.empty()) {
                        const size_t at = next() % input.size();
                        input.insert(at, input.substr(0, next() % 16));
                    }
                    break;
                case 3:  // append garbage
                    for (int i = 0; i < 8; ++i) input.push_back((char)next());
                    break;
            }
            if (input.empty()) input = "G";
        }
        IOBuf buf;
        buf.append(input);
        const size_t before = buf.size();
        HttpRequest req;
        const HttpParseStatus st = ParseHttpRequest(&buf, &req);
        if (st == HttpParseStatus::kOk) {
            EXPECT_TRUE(buf.size() < before);
        } else {
            EXPECT_EQ(before, buf.size());  // nothing consumed on non-OK
        }
    }
}

TEST(HttpPortal, LivePortalOverTcp) {
    Server server;
    static benchpb::EchoService* dummy = nullptr;
    (void)dummy;
    EndPoint listen;
    str2endpoint("127.0.0.1:0", &listen);
    ASSERT_EQ(0, server.Start(listen, nullptr));
    const int port = server.listened_port();

    auto fetch = [&](const std::string& req_str) {
        const int fd = ::socket(AF_INET, SOCK_STREAM, 0);
        sockaddr_in addr;
        EndPoint ep;
        str2endpoint("127.0.0.1", port, &ep);
        endpoint2sockaddr(ep, &addr);
        if (::connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
            close(fd);
            return std::string("connect-failed");
        }
        (void)!write(fd, req_str.data(), req_str.size());
        std::string out;
        char buf[4096];
        // Response ends when Content-Length bytes arrive; read until the
        // header + declared body is complete (bounded loop).
        for (int i = 0; i < 200; ++i) {
            const ssize_t r = read(fd, buf, sizeof(buf));
            if (r <= 0) break;
            out.append(buf, (size_t)r);
            const size_t he = out.find("\r\n\r\n");
            if (he == std::string::npos) continue;
            const size_t cl_at = out.find("Content-Length: ");
            if (cl_at == std::string::npos || cl_at > he) break;
            const size_t cl = strtoul(out.c_str() + cl_at + 16, nullptr, 10);
            if (out.size() >= he + 4 + cl) break;
        }
        close(fd);
        return out;
    };

    const std::string health =
        fetch("GET /health HTTP/1.1\r\nHost: x\r\n\r\n");
    EXPECT_TRUE(health.find("200 OK") != std::string::npos);
    EXPECT_TRUE(health.find("OK\n") != std::string::npos);

    const std::string vars = fetch("GET /vars HTTP/1.1\r\nHost: x\r\n\r\n");
    EXPECT_TRUE(vars.find("200 OK") != std::string::npos);

    const std::string fibers =
        fetch("GET /fibers HTTP/1.1\r\nHost: x\r\n\r\n");
    EXPECT_TRUE(fibers.find("pool tag=0") != std::string::npos);
    EXPECT_TRUE(fibers.find("workers: ") != std::string::npos);
    EXPECT_TRUE(fibers.find("live_fibers: ") != std::string::npos);

    const std::string missing =
        fetch("GET /definitely-not-there HTTP/1.1\r\n\r\n");
    EXPECT_TRUE(missing.find("404") != std::string::npos);

    // Flag set + readback through the portal.
    const std::string setflag = fetch(
        "GET /flags/iobuf_tls_cache_blocks?setvalue=256 HTTP/1.1\r\n\r\n");
    EXPECT_TRUE(setflag.find("= 256") != std::string::npos);
    const std::string setback = fetch(
        "GET /flags/iobuf_tls_cache_blocks?setvalue=512 HTTP/1.1\r\n\r\n");
    EXPECT_TRUE(setback.find("= 512") != std::string::npos);

    // Two requests on ONE connection (keep-alive).
    {
        const int fd = ::socket(AF_INET, SOCK_STREAM, 0);
        sockaddr_in addr;
        EndPoint ep;
        str2endpoint("127.0.0.1", port, &ep);
        endpoint2sockaddr(ep, &addr);
        ASSERT_EQ(0, ::connect(fd, (sockaddr*)&addr, sizeof(addr)));
        const char* two =
            "GET /health HTTP/1.1\r\n\r\nGET /health HTTP/1.1\r\n\r\n";
        ASSERT_EQ((ssize_t)strlen(two), write(fd, two, strlen(two)));
        std::string out;
        char buf[4096];
        for (int i = 0; i < 100; ++i) {
            size_t count = 0, pos = 0;
            while ((pos = out.find("200 OK", pos)) != std::string::npos) {
                ++count;
                pos += 6;
            }
            if (count >= 2) break;
            const ssize_t r = read(fd, buf, sizeof(buf));
            if (r <= 0) break;
            out.append(buf, (size_t)r);
        }
        size_t count = 0, pos = 0;
        while ((pos = out.find("200 OK", pos)) != std::string::npos) {
            ++count;
            pos += 6;
        }
        EXPECT_EQ(2u, count);
        // Responses must be in request order: both were /health here, so
        // instead check ordering with two DIFFERENT paths pipelined.
        close(fd);
    }
    // Pipelined different paths: responses in request order.
    {
        const int fd = ::socket(AF_INET, SOCK_STREAM, 0);
        sockaddr_in addr;
        EndPoint ep;
        str2endpoint("127.0.0.1", port, &ep);
        endpoint2sockaddr(ep, &addr);
        ASSERT_EQ(0, ::connect(fd, (sockaddr*)&addr, sizeof(addr)));
        const char* two =
            "GET /health HTTP/1.1\r\n\r\nGET /nope-404 HTTP/1.1\r\n\r\n";
        ASSERT_EQ((ssize_t)strlen(two), write(fd, two, strlen(two)));
        std::string out;
        char buf[4096];
        for (int i = 0; i < 100; ++i) {
            const ssize_t r = read(fd, buf, sizeof(buf));
            if (r <= 0) break;
            out.append(buf, (size_t)r);
            if (out.find("200 OK") != std::string::npos &&
                out.find("404") != std::string::npos) {
                break;
            }
        }
        const size_t ok_at = out.find("200 OK");
        const size_t nf_at = out.find("404 Not Found");
        ASSERT_TRUE(ok_at != std::string::npos);
        ASSERT_TRUE(nf_at != std::string::npos);
        EXPECT_TRUE(ok_at < nf_at);  // FIFO order preserved
        close(fd);
    }
    // HTTP/1.0 (implicit close): server must actually close the
    // connection so read-until-EOF clients finish.
    {
        const int fd = ::socket(AF_INET, SOCK_STREAM, 0);
        sockaddr_in addr;
        EndPoint ep;
        str2endpoint("127.0.0.1", port, &ep);
        endpoint2sockaddr(ep, &addr);
        ASSERT_EQ(0, ::connect(fd, (sockaddr*)&addr, sizeof(addr)));
        const char* r10 = "GET /health HTTP/1.0\r\n\r\n";
        ASSERT_EQ((ssize_t)strlen(r10), write(fd, r10, strlen(r10)));
        std::string out;
        char buf[4096];
        bool got_eof = false;
        for (int i = 0; i < 300; ++i) {
            const ssize_t r = read(fd, buf, sizeof(buf));
            if (r == 0) {
                got_eof = true;
                break;
            }
            if (r < 0) break;
            out.append(buf, (size_t)r);
        }
        EXPECT_TRUE(got_eof);
        EXPECT_TRUE(out.find("Connection: close") != std::string::npos);
        close(fd);
    }
    // HEAD: headers with the real Content-Length, but no body bytes.
    {
        const std::string head =
            fetch("HEAD /health HTTP/1.1\r\nConnection: close\r\n\r\n");
        EXPECT_TRUE(head.find("Content-Length: 3") != std::string::npos);
        EXPECT_TRUE(head.find("OK\n") == std::string::npos);
    }
    server.Stop();
    server.Join();
}

// ---------------- rpcz ----------------
// Reference: span.h:47-120 + builtin/rpcz_service.cpp — sampled RPCs leave
// a span with a queue/process/write timeline, browsable at /rpcz; trace
// ids propagate client -> server through the request meta.

namespace {

class RpczEchoService : public benchpb::EchoService {
public:
    void Echo(google::protobuf::RpcController* cntl_base,
              const benchpb::EchoRequest* request,
              benchpb::EchoResponse* response,
              google::protobuf::Closure* done) override {
        auto* cntl = static_cast<Controller*>(cntl_base);
        response->set_send_ts_us(request->send_ts_us());
        cntl->response_attachment().append(cntl->request_attachment());
        done->Run();
    }
};

std::string http_get(int port, const std::string& path) {
    const int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in addr;
    EndPoint ep;
    str2endpoint("127.0.0.1", port, &ep);
    endpoint2sockaddr(ep, &addr);
    if (::connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
        close(fd);
        return "";
    }
    const std::string req =
        "GET " + path + " HTTP/1.1\r\nHost: x\r\nConnection: close\r\n\r\n";
    (void)!write(fd, req.data(), req.size());
    std::string out;
    char buf[8192];
    ssize_t r;
    while ((r = read(fd, buf, sizeof(buf))) > 0) out.append(buf, (size_t)r);
    close(fd);
    return out;
}

}  // namespace

TEST(Rpcz, SampledSpansShowTimeline) {
    DECLARE_bool(enable_rpcz);
    FLAGS_enable_rpcz.set(true);
    RpczEchoService service;
    Server server;
    ASSERT_EQ(0, server.AddService(&service));
    EndPoint listen;
    str2endpoint("127.0.0.1:0", &listen);
    ASSERT_EQ(0, server.Start(listen, nullptr));
    const int port = server.listened_port();

    Channel ch;
    EndPoint ep;
    str2endpoint("127.0.0.1", port, &ep);
    ASSERT_EQ(0, ch.Init(ep, nullptr));
    benchpb::EchoService_Stub stub(&ch);
    for (int i = 0; i < 5; ++i) {
        Controller cntl;
        cntl.set_timeout_ms(3000);
        benchpb::EchoRequest req;
        req.set_send_ts_us(1);
        benchpb::EchoResponse res;
        stub.Echo(&cntl, &req, &res, nullptr);
        ASSERT_FALSE(cntl.Failed());
    }
    // The Collector dispatches on a ~50ms cadence; poll /rpcz until the
    // spans land.
    std::string page;
    for (int i = 0; i < 60; ++i) {
        page = http_get(port, "/rpcz");
        if (page.find("SERVER") != std::string::npos &&
            page.find("CLIENT") != std::string::npos) {
            break;
        }
        usleep(50 * 1000);
    }
    FLAGS_enable_rpcz.set(false);
    // Server span with the queue/process/write phase line.
    EXPECT_TRUE(page.find("SERVER benchpb.EchoService.Echo") !=
                std::string::npos);
    EXPECT_TRUE(page.find("received +0us") != std::string::npos);
    EXPECT_TRUE(page.find("process ") != std::string::npos);
    EXPECT_TRUE(page.find("write ") != std::string::npos);
    // Client span with the issue/send/response phases.
    EXPECT_TRUE(page.find("CLIENT benchpb.EchoService.Echo") !=
                std::string::npos);
    EXPECT_TRUE(page.find("issued +0us") != std::string::npos);
    // Trace propagation: the server span's trace id equals some client
    // span's trace id (same trace string appears at least twice).
    const size_t t0 = page.find("trace=");
    ASSERT_TRUE(t0 != std::string::npos);
    const std::string trace_tok = page.substr(t0, page.find(' ', t0) - t0);
    EXPECT_TRUE(page.find(trace_tok, t0 + 1) != std::string::npos);
}

// ---------------- HTTP-as-RPC + json2pb ----------------
// Reference: policy/http_rpc_protocol.cpp:1790 + src/json2pb — POST
// /Service/Method with an application/json body reaches the pb service
// and answers json (`curl -d '{...}' host:port/EchoService/Echo`).

namespace {

std::string http_post(int port, const std::string& path,
                      const std::string& body) {
    const int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in addr;
    EndPoint ep;
    str2endpoint("127.0.0.1", port, &ep);
    endpoint2sockaddr(ep, &addr);
    if (::connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
        close(fd);
        return "";
    }
    char head[256];
    snprintf(head, sizeof(head),
             "POST %s HTTP/1.1\r\nHost: x\r\nContent-Type: application/json"
             "\r\nContent-Length: %zu\r\nConnection: close\r\n\r\n",
             path.c_str(), body.size());
    std::string req = std::string(head) + body;
    (void)!write(fd, req.data(), req.size());
    std::string out;
    char buf[8192];
    ssize_t r;
    while ((r = read(fd, buf, sizeof(buf))) > 0) out.append(buf, (size_t)r);
    close(fd);
    return out;
}

}  // namespace

TEST(HttpRpc, JsonEchoRoundTrip) {
    RpczEchoService service;
    Server server;
    ASSERT_EQ(0, server.AddService(&service));
    EndPoint listen;
    str2endpoint("127.0.0.1:0", &listen);
    ASSERT_EQ(0, server.Start(listen, nullptr));
    const int port = server.listened_port();

    // Full service name and short name both route.
    for (const char* path :
         {"/benchpb.EchoService/Echo", "/EchoService/Echo"}) {
        const std::string rsp =
            http_post(port, path, "{\"send_ts_us\": 4242}");
        EXPECT_TRUE(rsp.find("200 OK") != std::string::npos) << path;
        EXPECT_TRUE(rsp.find("application/json") != std::string::npos);
        EXPECT_TRUE(rsp.find("\"send_ts_us\"") != std::string::npos) << rsp;
        EXPECT_TRUE(rsp.find("4242") != std::string::npos) << rsp;
    }
    // Unknown method: 404.
    EXPECT_TRUE(http_post(port, "/EchoService/Nope", "{}").find("404") !=
                std::string::npos);
    // Malformed json: 400.
    EXPECT_TRUE(http_post(port, "/EchoService/Echo", "{oops")
                    .find("400") != std::string::npos);
    // Empty body = default request: still answers.
    EXPECT_TRUE(http_post(port, "/EchoService/Echo", "").find("200 OK") !=
                std::string::npos);
    // The per-method stats saw the calls.
    const std::string status = http_get(port, "/status");
    EXPECT_TRUE(status.find("benchpb.EchoService.Echo") != std::string::npos);
}

// ---------------- HPACK (RFC 7541 Appendix C vectors) ----------------

#include "thttp/hpack.h"

TEST(Hpack, HuffmanDecodeRfcVectors) {
    // C.4.1: "www.example.com"
    const uint8_t v1[] = {0xf1, 0xe3, 0xc2, 0xe5, 0xf2, 0x3a, 0x6b,
                          0xa0, 0xab, 0x90, 0xf4, 0xff};
    std::string out;
    ASSERT_TRUE(HpackHuffmanDecode(v1, sizeof(v1), &out));
    EXPECT_EQ(out, "www.example.com");
    // C.4.2: "no-cache"
    const uint8_t v2[] = {0xa8, 0xeb, 0x10, 0x64, 0x9c, 0xbf};
    out.clear();
    ASSERT_TRUE(HpackHuffmanDecode(v2, sizeof(v2), &out));
    EXPECT_EQ(out, "no-cache");
    // C.6.1: "302"
    const uint8_t v3[] = {0x64, 0x02};
    out.clear();
    ASSERT_TRUE(HpackHuffmanDecode(v3, sizeof(v3), &out));
    EXPECT_EQ(out, "302");
    // Bad padding (zero bits) must fail.
    const uint8_t bad[] = {0xf1, 0xe3, 0xc2, 0x00};
    out.clear();
    EXPECT_FALSE(HpackHuffmanDecode(bad, sizeof(bad), &out));
}

TEST(Hpack, DecodeRfcHeaderBlocks) {
    // C.2.1: literal with incremental indexing —
    // custom-key: custom-header.
    const uint8_t b1[] = {0x40, 0x0a, 'c', 'u', 's', 't', 'o', 'm', '-',
                          'k',  'e',  'y', 0x0d, 'c', 'u', 's', 't', 'o',
                          'm',  '-',  'h', 'e',  'a', 'd', 'e', 'r'};
    HpackDecoder dec;
    std::vector<HpackHeader> hs;
    ASSERT_TRUE(dec.Decode(b1, sizeof(b1), &hs));
    ASSERT_EQ(hs.size(), 1u);
    EXPECT_EQ(hs[0].name, "custom-key");
    EXPECT_EQ(hs[0].value, "custom-header");
    // The entry was added to the dynamic table: index 62 resolves it.
    const uint8_t b2[] = {0xbe};  // indexed, index 62
    hs.clear();
    ASSERT_TRUE(dec.Decode(b2, sizeof(b2), &hs));
    ASSERT_EQ(hs.size(), 1u);
    EXPECT_EQ(hs[0].name, "custom-key");
    EXPECT_EQ(hs[0].value, "custom-header");
    // C.2.4: indexed static — :method GET (index 2).
    const uint8_t b3[] = {0x82};
    hs.clear();
    ASSERT_TRUE(dec.Decode(b3, sizeof(b3), &hs));
    ASSERT_EQ(hs.size(), 1u);
    EXPECT_EQ(hs[0].name, ":method");
    EXPECT_EQ(hs[0].value, "GET");
    // Garbage index fails.
    const uint8_t b4[] = {0xff, 0xff, 0xff, 0xff, 0x7f};
    hs.clear();
    EXPECT_FALSE(dec.Decode(b4, sizeof(b4), &hs));
    // Round-trip our own encoder through the decoder.
    std::string enc;
    HpackEncodeHeader(":status", "200", &enc);
    HpackEncodeHeader("grpc-status", "0", &enc);
    hs.clear();
    ASSERT_TRUE(dec.Decode((const uint8_t*)enc.data(), enc.size(), &hs));
    ASSERT_EQ(hs.size(), 2u);
    EXPECT_EQ(hs[0].name, ":status");
    EXPECT_EQ(hs[1].name, "grpc-status");
}

TEST(Hpack, FuzzSmoke) {
    // The decoder parses untrusted header blocks: mutate valid blocks +
    // raw noise; must never crash and must reject garbage cleanly
    // (tools/frame_fuzz-style deterministic loop).
    uint64_t rng = 0x2545f4914f6cdd1dull;
    auto next = [&rng]() {
        rng ^= rng << 13;
        rng ^= rng >> 7;
        rng ^= rng << 17;
        return rng;
    };
    std::string seed;
    HpackEncodeHeader(":path", "/benchpb.EchoService/Echo", &seed);
    HpackEncodeHeader("content-type", "application/grpc", &seed);
    seed += "\x82\x86";  // indexed :method GET, :scheme http
    const uint8_t huff_seed[] = {0xf1, 0xe3, 0xc2, 0xe5, 0xf2,
                                 0x3a, 0x6b, 0xa0, 0xab};
    for (int iter = 0; iter < 20000; ++iter) {
        std::string input = seed;
        const int nmut = 1 + (int)(next() % 6);
        for (int m = 0; m < nmut; ++m) {
            if (input.empty()) input = "\x82";
            switch (next() % 3) {
                case 0:
                    input[next() % input.size()] = (char)next();
                    break;
                case 1:
                    input.resize(next() % (input.size() + 1));
                    break;
                case 2:
                    for (int i = 0; i < 6; ++i) {
                        input.push_back((char)next());
                    }
                    break;
            }
        }
        HpackDecoder dec;
        std::vector<HpackHeader> hs;
        dec.Decode((const uint8_t*)input.data(), input.size(), &hs);
        std::string out;
        HpackHuffmanDecode(huff_seed, sizeof(huff_seed), &out);
        std::string mutated(input);
        HpackHuffmanDecode((const uint8_t*)mutated.data(),
                           std::min<size_t>(mutated.size(), 64), &out);
    }
}

// ---------------- /hotspots (reference hotspots_service.cpp) ----------------

#include "tfiber/fiber_sync.h"

namespace {

// Minimal portal server + blocking HTTP fetch for the hotspots tests.
struct PortalServer {
    Server server;
    int port = 0;

    bool start() {
        EndPoint listen;
        str2endpoint("127.0.0.1:0", &listen);
        if (server.Start(listen, nullptr) != 0) return false;
        port = server.listened_port();
        return true;
    }

    std::string fetch(const std::string& req_str,
                      bool read_chunked = false) {
        const int fd = ::socket(AF_INET, SOCK_STREAM, 0);
        sockaddr_in addr;
        EndPoint ep;
        str2endpoint("127.0.0.1", port, &ep);
        endpoint2sockaddr(ep, &addr);
        if (::connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
            close(fd);
            return "connect-failed";
        }
        timeval tv{5, 0};  // a wedged server fails the test, not hangs it
        setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
        (void)!write(fd, req_str.data(), req_str.size());
        std::string out;
        char buf[4096];
        for (int i = 0; i < 2000; ++i) {
            const ssize_t r = read(fd, buf, sizeof(buf));
            // /threads SIGURGs every task in the process, including this
            // test thread: a timed socket read returns EINTR then.
            if (r < 0 && errno == EINTR) continue;
            if (r <= 0) break;
            out.append(buf, (size_t)r);
            if (read_chunked) {
                if (out.find("0\r\n\r\n") != std::string::npos) break;
                continue;
            }
            const size_t he = out.find("\r\n\r\n");
            if (he == std::string::npos) continue;
            const size_t cl_at = out.find("Content-Length: ");
            if (cl_at == std::string::npos || cl_at > he) break;
            const size_t cl =
                strtoul(out.c_str() + cl_at + 16, nullptr, 10);
            if (out.size() >= he + 4 + cl) break;
        }
        close(fd);
        return out;
    }
};

}  // namespace

TEST(Hotspots, CpuProfileNamesRealFunctions) {
    // Portal load + a 1s in-server profile: the symbolized flat profile
    // must name real code (tpurpc:: frames / libc / syscalls), proving
    // the portal path samples AND symbolizes without offline tooling.
    PortalServer ps;
    ASSERT_TRUE(ps.start());
    // Load from PLAIN threads: a fiber blocking in raw read() would pin
    // a worker, and enough of them starves the server's own fibers.
    std::atomic<bool> stop{false};
    std::vector<std::thread> load;
    for (int i = 0; i < 2; ++i) {
        load.emplace_back([&] {
            while (!stop.load()) {
                ps.fetch("GET /vars HTTP/1.1\r\nHost: x\r\n\r\n");
            }
        });
    }
    const std::string prof = ps.fetch(
        "GET /hotspots/cpu?seconds=1 HTTP/1.1\r\nHost: x\r\n\r\n");
    stop.store(true);
    for (auto& t : load) t.join();
    EXPECT_NE(prof.find("cpu profile:"), std::string::npos);
    // At least one sample symbolized to a real name: the framework's
    // own namespace, or any resolved symbol (no all-hex output).
    const bool named = prof.find("tpurpc::") != std::string::npos ||
                       prof.find("+0x") != std::string::npos;
    EXPECT_TRUE(named);
}

TEST(Hotspots, ContentionProfileShowsWaitSites) {
    PortalServer ps;
    ASSERT_TRUE(ps.start());
    // Manufacture contention: fibers hammer one FiberMutex with held
    // sections spanning yields.
    FiberMutex mu;
    std::atomic<bool> stop{false};
    struct CtnCtx {
        FiberMutex* mu;
        std::atomic<bool>* stop;
    } cctx{&mu, &stop};
    std::vector<fiber_t> tids(8);
    for (auto& tid : tids) {
        fiber_start_background(
            &tid, nullptr,
            [](void* arg) -> void* {
                auto* c = (CtnCtx*)arg;
                while (!c->stop->load()) {
                    c->mu->lock();
                    fiber_yield();  // hold across a reschedule
                    c->mu->unlock();
                }
                return nullptr;
            },
            &cctx);
    }
    fiber_usleep(100 * 1000);
    stop.store(true);
    for (auto tid : tids) fiber_join(tid, nullptr);
    const std::string page = ps.fetch(
        "GET /hotspots/contention HTTP/1.1\r\nHost: x\r\n\r\n");
    EXPECT_NE(page.find("contended acquisitions"), std::string::npos);
    // The hammer loop's lock() call site must appear with nonzero count.
    EXPECT_EQ(page.find(" 0 contended acquisitions"), std::string::npos);
}

// ---------------- ProgressiveAttachment (reference progressive_attachment.*) ----------------

#include "thttp/progressive_attachment.h"

TEST(Progressive, ChunkedBodyStreamsAfterHandlerReturns) {
    PortalServer ps;
    std::atomic<int> chunks_written{0};
    ps.server.RegisterHttpHandler(
        "/stream", [&](Server*, const HttpRequest&, HttpResponse* res) {
            res->set_content_type("text/plain");
            res->start_progressive = [&](ProgressiveAttachmentPtr pa) {
                struct Arg {
                    ProgressiveAttachmentPtr pa;
                    std::atomic<int>* n;
                };
                auto* arg = new Arg{std::move(pa), &chunks_written};
                fiber_t tid;
                fiber_start_background(
                    &tid, nullptr,
                    [](void* raw) -> void* {
                        std::unique_ptr<Arg> a((Arg*)raw);
                        for (int i = 0; i < 5; ++i) {
                            fiber_usleep(5 * 1000);
                            a->pa->Write("chunk-" + std::to_string(i) +
                                         ";");
                            a->n->fetch_add(1);
                        }
                        a->pa->Close();
                        return nullptr;
                    },
                    arg);
            };
        });
    ASSERT_TRUE(ps.start());
    const std::string resp = ps.fetch(
        "GET /stream HTTP/1.1\r\nHost: x\r\n\r\n", /*read_chunked=*/true);
    EXPECT_NE(resp.find("Transfer-Encoding: chunked"), std::string::npos);
    EXPECT_EQ(resp.find("Content-Length"), std::string::npos);
    for (int i = 0; i < 5; ++i) {
        EXPECT_NE(resp.find("chunk-" + std::to_string(i) + ";"),
                  std::string::npos);
    }
    EXPECT_NE(resp.find("0\r\n\r\n"), std::string::npos);  // terminator
    EXPECT_EQ(chunks_written.load(), 5);
    // The connection survived (keep-alive after the terminator): a
    // second request on a FRESH connection also works, proving the
    // server is healthy.
    const std::string health =
        ps.fetch("GET /health HTTP/1.1\r\nHost: x\r\n\r\n");
    EXPECT_NE(health.find("OK"), std::string::npos);
}

TEST(Progressive, Http10AndHeadGetFailedWriterNotSilence) {
    // HTTP/1.0 (and HEAD) can't carry chunked streams. The handler that
    // committed to one must LEARN that — it gets its callback invoked
    // with an already-dead writer whose Write returns -1 — instead of
    // the server silently answering 200 with an empty body and leaking
    // the handler's expectation.
    PortalServer ps;
    std::atomic<int> cb_invoked{0};
    std::atomic<int> write_rc{0};
    ps.server.RegisterHttpHandler(
        "/stream10", [&](Server*, const HttpRequest&, HttpResponse* res) {
            res->set_content_type("text/plain");
            res->start_progressive = [&](ProgressiveAttachmentPtr pa) {
                cb_invoked.fetch_add(1);
                write_rc.store(pa->Write("never-delivered"));
            };
        });
    ASSERT_TRUE(ps.start());
    const std::string resp =
        ps.fetch("GET /stream10 HTTP/1.0\r\nHost: x\r\n\r\n");
    // Callback ran inline (ProcessHttp invokes it before responding).
    EXPECT_EQ(cb_invoked.load(), 1);
    EXPECT_EQ(write_rc.load(), -1);  // the writer is stillborn
    // The response is a plain (non-chunked) answer, not a hung stream.
    EXPECT_EQ(resp.find("Transfer-Encoding: chunked"), std::string::npos);
    EXPECT_NE(resp.find("HTTP/1.1 200"), std::string::npos);

    // HEAD to the same handler: same notification, headers-only reply.
    const std::string head =
        ps.fetch("HEAD /stream10 HTTP/1.1\r\nHost: x\r\n\r\n");
    EXPECT_EQ(cb_invoked.load(), 2);
    EXPECT_EQ(head.find("Transfer-Encoding: chunked"), std::string::npos);
}

TEST(Threads, PortalDumpsRealPthreadStacks) {
    PortalServer ps;
    ASSERT_TRUE(ps.start());
    const std::string page =
        ps.fetch("GET /threads HTTP/1.1\r\nHost: x\r\n\r\n");
    EXPECT_NE(page.find("thread("), std::string::npos);
    EXPECT_NE(page.find("--- thread"), std::string::npos);
    // At least one stack symbolized into real code: worker loops and the
    // epoll loop are always parked somewhere recognizable.
    const bool named = page.find("tpurpc::") != std::string::npos ||
                       page.find("+0x") != std::string::npos;
    EXPECT_TRUE(named);
}
