// Mesh-wide observability (ISSUE 4): SeriesRing rollover under a fake
// clock (append() IS the clock), prometheus summary exposition for
// LatencyRecorder (+ labelled families), the flag->var bridge, and span
// annotation attachment on the shed/cancel/retry paths.
// Performance attribution (ISSUE 6): heap-profiler determinism (fixed
// seed + same allocation sequence -> stable stack set), scheduler
// counters, dispatcher telemetry, and per-tuple series fields of
// labelled families.
#include <unistd.h>

#include <atomic>
#include <cstdlib>
#include <cstring>
#include <functional>
#include <string>
#include <vector>

#include "echo.pb.h"
#include "tbase/endpoint.h"
#include "tbase/flags.h"
#include "tbase/heap_profiler.h"
#include "tbase/time.h"
#include "tfiber/fiber.h"
#include "tfiber/fiber_sync.h"
#include "tfiber/task_group.h"
#include "tnet/event_dispatcher.h"
#include "trpc/channel.h"
#include "trpc/controller.h"
#include "trpc/server.h"
#include "trpc/server_call.h"
#include "trpc/span.h"
#include "ttest/ttest.h"
#include "tvar/default_variables.h"
#include "tvar/latency_recorder.h"
#include "tvar/multi_dimension.h"
#include "tvar/reducer.h"
#include "tvar/series.h"
#include "tvar/variable.h"

using namespace tpurpc;

DECLARE_bool(enable_rpcz);
DECLARE_int64(heap_profiler_sample_bytes);

namespace {

bool WaitUntil(const std::function<bool()>& pred, int64_t timeout_ms) {
    const int64_t deadline = monotonic_time_us() + timeout_ms * 1000;
    while (monotonic_time_us() < deadline) {
        if (pred()) return true;
        usleep(5 * 1000);
    }
    return pred();
}

}  // namespace

// ---------------- SeriesRing: fake-clock rollover ----------------
// append() is the clock (1 call = 1 second), so boundary behavior is
// driven deterministically — no sleeping, no real time.

TEST(SeriesRing, SecondBoundaryRollsIntoMinute) {
    SeriesRing r;
    for (int i = 0; i < 59; ++i) r.append(10.0);
    // 59 ticks: second ring filling, minute ring untouched.
    EXPECT_EQ(r.ticks(), 59);
    std::vector<double> m = r.minutes();
    for (double v : m) EXPECT_EQ(v, 0.0);
    // The 60th tick folds mean(last 60 seconds) into the minute ring.
    r.append(70.0);  // 59x10 + 1x70 -> mean 11
    m = r.minutes();
    EXPECT_EQ(m.back(), 11.0);
    // Second ring keeps rolling: 60 more ticks -> second minute entry.
    for (int i = 0; i < 60; ++i) r.append(5.0);
    m = r.minutes();
    EXPECT_EQ(m.back(), 5.0);
    EXPECT_EQ(m[m.size() - 2], 11.0);
}

TEST(SeriesRing, MinuteBoundaryRollsIntoHour) {
    SeriesRing r;
    // One full hour of ticks at a constant value.
    for (int i = 0; i < 3600; ++i) r.append(3.0);
    std::vector<double> h = r.hours();
    EXPECT_EQ(h.back(), 3.0);
    for (size_t i = 0; i + 1 < h.size(); ++i) EXPECT_EQ(h[i], 0.0);
    // A second hour at a different value: second hour entry, first keeps.
    for (int i = 0; i < 3600; ++i) r.append(9.0);
    h = r.hours();
    EXPECT_EQ(h.back(), 9.0);
    EXPECT_EQ(h[h.size() - 2], 3.0);
}

TEST(SeriesRing, UnrollIsOldestFirstAndZeroPadded) {
    SeriesRing r;
    for (int i = 1; i <= 70; ++i) r.append((double)i);
    const std::vector<double> s = r.seconds();
    ASSERT_EQ((int)s.size(), SeriesRing::kSeconds);
    // 70 ticks through a 60-slot ring: oldest surviving value is 11.
    EXPECT_EQ(s.front(), 11.0);
    EXPECT_EQ(s.back(), 70.0);
    for (size_t i = 1; i < s.size(); ++i) EXPECT_EQ(s[i], s[i - 1] + 1.0);
    // A short series zero-pads at the FRONT (fixed 60-point shape).
    SeriesRing fresh;
    fresh.append(42.0);
    const std::vector<double> f = fresh.seconds();
    ASSERT_EQ((int)f.size(), SeriesRing::kSeconds);
    EXPECT_EQ(f.front(), 0.0);
    EXPECT_EQ(f.back(), 42.0);
}

TEST(SeriesCollector, ExposedVarGrowsARing) {
    Status<int64_t> st(7);
    st.expose("obs_series_probe");
    auto* sc = SeriesCollector::singleton();
    sc->Tick();
    sc->Tick();
    const std::string json = sc->SeriesJson("obs_series_probe");
    ASSERT_TRUE(!json.empty());
    EXPECT_TRUE(json.find("\"name\":\"obs_series_probe\"") !=
                std::string::npos);
    // The per-second ring is always exactly 60 points; the probe's
    // constant value occupies the tail.
    const size_t sec = json.find("\"second\":[");
    ASSERT_TRUE(sec != std::string::npos);
    const size_t end = json.find("]", sec);
    const std::string ring = json.substr(sec + 10, end - sec - 10);
    int commas = 0;
    for (char c : ring) commas += c == ',';
    EXPECT_EQ(commas, 59);
    EXPECT_TRUE(ring.size() >= 2 &&
                ring.compare(ring.size() - 2, 2, ",7") == 0)
        << ring;
    st.hide();
}

// ---------------- heap profiler (ISSUE 6) ----------------

namespace {

__attribute__((noinline)) char* HeapProbeAlloc(size_t n) {
    char* p = new char[n];
    p[0] = 1;  // keep the allocation un-elidable
    return p;
}

// One deterministic round: reset the profiler, run a fixed allocation
// sequence, dump, free. Returns the raw-dump row of the probe site
// (the line whose stack the two rounds must agree on).
__attribute__((noinline)) std::string HeapProbeRound() {
    ResetHeapProfilerForTest();
    std::vector<char*> blocks;
    blocks.reserve(64);
    for (int i = 0; i < 64; ++i) blocks.push_back(HeapProbeAlloc(8191));
    // 64 * 8191 bytes through a 64KiB countdown -> exactly 7 samples of
    // the probe site: the row reads "57337 7 @ <pcs>".
    const std::string raw = HeapProfileRaw(/*growth=*/false);
    for (char* p : blocks) delete[] p;
    const size_t pos = raw.find("57337 7 @");
    if (pos == std::string::npos) return "";
    return raw.substr(pos, raw.find('\n', pos) - pos);
}

}  // namespace

TEST(HeapProfiler, DeterministicSampleSet) {
    if (!HeapProfilerActive() &&
        FLAGS_heap_profiler_sample_bytes.get() > 0) {
        return;  // ASan build: interposition compiled out by design
    }
    const int64_t old = FLAGS_heap_profiler_sample_bytes.get();
    FLAGS_heap_profiler_sample_bytes.set(64 * 1024);
    // Same call site both rounds: the captured stacks must be
    // IDENTICAL — the deterministic-countdown contract.
    std::string row[2];
    for (int i = 0; i < 2; ++i) row[i] = HeapProbeRound();
    EXPECT_TRUE(!row[0].empty());
    EXPECT_EQ(row[0], row[1]);
    FLAGS_heap_profiler_sample_bytes.set(old);
    ResetHeapProfilerForTest();
}

TEST(HeapProfiler, LiveVsGrowthAccounting) {
    if (!HeapProfilerActive() &&
        FLAGS_heap_profiler_sample_bytes.get() > 0) {
        return;  // ASan build
    }
    const int64_t old = FLAGS_heap_profiler_sample_bytes.get();
    FLAGS_heap_profiler_sample_bytes.set(32 * 1024);
    ResetHeapProfilerForTest();
    std::vector<char*> blocks;
    blocks.reserve(32);
    for (int i = 0; i < 32; ++i) blocks.push_back(HeapProbeAlloc(8191));
    HeapProfilerStats live = GetHeapProfilerStats();
    // 32 * 8191 bytes through a 32KiB countdown = 6 deterministic
    // samples of the probe site (one per 5 allocations after the
    // vector's reserve eats into the first window); other threads can
    // only ADD samples, so a floor of 5 is race-proof slack.
    EXPECT_GE(live.live_count, 5);
    EXPECT_GT(live.live_bytes, 0);
    EXPECT_GE(live.growth_count, live.live_count);
    for (char* p : blocks) delete[] p;
    // Frees clear LIVE attribution; growth (churn) is cumulative...
    HeapProfilerStats freed = GetHeapProfilerStats();
    EXPECT_LT(freed.live_count, live.live_count);
    EXPECT_GE(freed.growth_count, live.growth_count);
    // ...until an explicit reset.
    ResetHeapGrowth();
    HeapProfilerStats reset = GetHeapProfilerStats();
    EXPECT_EQ(reset.growth_count, 0);
    const std::string sym = HeapProfileSymbolized(/*growth=*/false, 10);
    EXPECT_TRUE(sym.find("heap profile:") == 0);
    FLAGS_heap_profiler_sample_bytes.set(old);
    ResetHeapProfilerForTest();
}

// ---------------- scheduler + dispatcher telemetry (ISSUE 6) ----------------

namespace {

void* NopFiber(void*) { return nullptr; }

struct UrgentSpawner {
    CountdownEvent done{1};
    static void* Run(void* arg) {
        auto* self = (UrgentSpawner*)arg;
        fiber_t child;
        fiber_start_urgent(&child, nullptr, NopFiber, nullptr);
        fiber_join(child, nullptr);
        self->done.signal();
        return nullptr;
    }
};

}  // namespace

TEST(SchedulerTelemetry, CountersAdvance) {
    TaskControl* c = TaskControl::singleton();
    c->ensure_started();
    const int64_t urgent0 = c->urgent_handoffs();
    // An urgent spawn from ON a worker fiber takes the run-now path.
    UrgentSpawner sp;
    fiber_t tid;
    ASSERT_EQ(
        fiber_start_background(&tid, nullptr, UrgentSpawner::Run, &sp), 0);
    sp.done.wait();
    fiber_join(tid, nullptr);
    EXPECT_GT(c->urgent_handoffs(), urgent0);
    // A burst of background fibers pushes the run queues: the high-water
    // gauge must have seen at least depth 1 somewhere.
    std::vector<fiber_t> tids(256);
    for (auto& t : tids) {
        ASSERT_EQ(fiber_start_background(&t, nullptr, NopFiber, nullptr),
                  0);
    }
    for (auto& t : tids) fiber_join(t, nullptr);
    EXPECT_GE(c->runqueue_highwater(), 1);
    // Counters are visible as labelled families on the registry (the
    // /metrics + /vars?series= surface).
    std::string desc;
    ASSERT_TRUE(Variable::describe_exposed("rpc_scheduler_steals", &desc));
    ASSERT_TRUE(
        Variable::describe_exposed("rpc_scheduler_urgent_handoffs", &desc));
    EXPECT_TRUE(desc.find("pool=\"0\"") != std::string::npos);
}

TEST(DispatcherTelemetry, LoopsCountWakes) {
    // A live echo round-trip guarantees at least one dispatcher exists
    // and delivered events.
    Server server;
    class EchoImpl : public test::EchoService {
    public:
        void Echo(google::protobuf::RpcController*,
                  const test::EchoRequest* request,
                  test::EchoResponse* response,
                  google::protobuf::Closure* done) override {
            response->set_message(request->message());
            done->Run();
        }
    } service;
    ASSERT_EQ(server.AddService(&service), 0);
    EndPoint listen;
    str2endpoint("127.0.0.1:0", &listen);
    ASSERT_EQ(server.Start(listen, nullptr), 0);
    EndPoint ep;
    str2endpoint("127.0.0.1", server.listened_port(), &ep);
    Channel channel;
    ASSERT_EQ(channel.Init(ep, nullptr), 0);
    test::EchoService_Stub stub(&channel);
    Controller cntl;
    test::EchoRequest req;
    req.set_message("loops");
    test::EchoResponse res;
    stub.Echo(&cntl, &req, &res, nullptr);
    ASSERT_TRUE(!cntl.Failed());
    EXPECT_GT(EventDispatcher::TotalEpollWaits(), 0);
    int64_t events = 0;
    EventDispatcher::ForEachLoop(
        [](int, const EventDispatcher::LoopStats& st, void* arg) {
            *(int64_t*)arg += st.events;
        },
        &events);
    EXPECT_GT(events, 0);
    server.Stop();
    server.Join();
}

TEST(MultiDimensionSeries, PerTupleNumericFields) {
    // Labelled families feed the series rings through flattened
    // per-tuple suffixes (ISSUE 6) — the /vars?series=<family>_loop_0
    // contract.
    MultiDimension<Adder<int64_t>> m({"loop"});
    *m.get_stats({"0"}) << 5;
    *m.get_stats({"1"}) << 7;
    const auto fields = m.numeric_fields();
    ASSERT_EQ(fields.size(), (size_t)2);
    bool saw0 = false, saw1 = false;
    for (const auto& f : fields) {
        if (f.first == "_loop_0" && f.second == 5.0) saw0 = true;
        if (f.first == "_loop_1" && f.second == 7.0) saw1 = true;
    }
    EXPECT_TRUE(saw0);
    EXPECT_TRUE(saw1);
}

// ---------------- prometheus exposition ----------------

TEST(Prometheus, LatencyRecorderIsARealSummary) {
    LatencyRecorder lat;
    for (int i = 1; i <= 1000; ++i) lat << i;
    lat.expose("obs_test_latency");
    const std::string dump = Variable::dump_prometheus();
    EXPECT_TRUE(dump.find("# TYPE obs_test_latency summary\n") !=
                std::string::npos);
    EXPECT_TRUE(dump.find("obs_test_latency{quantile=\"0.5\"} ") !=
                std::string::npos);
    EXPECT_TRUE(dump.find("obs_test_latency{quantile=\"0.999\"} ") !=
                std::string::npos);
    EXPECT_TRUE(dump.find("obs_test_latency_count 1000\n") !=
                std::string::npos);
    // _sum is the cumulative sum of recorded values: 1+..+1000.
    EXPECT_TRUE(dump.find("obs_test_latency_sum 500500\n") !=
                std::string::npos);
    // The flat JSON-parsed gauges are gone.
    EXPECT_TRUE(dump.find("obs_test_latency_avg_us") == std::string::npos);
    lat.hide();
}

TEST(Prometheus, PlainCountersStayGauges) {
    Adder<int64_t> a;
    a << 12345678;
    a.expose("obs_test_counter");
    const std::string dump = Variable::dump_prometheus();
    EXPECT_TRUE(dump.find("# TYPE obs_test_counter gauge\n"
                          "obs_test_counter 12345678\n") !=
                std::string::npos);
    a.hide();
}

TEST(Prometheus, LabelledLatencyKeepsLabelsAndSummaryShape) {
    LabelledMetric<LatencyRecorder> lat("obs_req_latency", {"method"});
    *lat.get_stats({"Echo"}) << 100 << 200 << 300;
    *lat.get_stats({"Stats"}) << 50;
    const std::string text = lat.prometheus_text("obs_req_latency");
    EXPECT_TRUE(text.find("# TYPE obs_req_latency summary\n") == 0) << text;
    EXPECT_TRUE(text.find("obs_req_latency{method=\"Echo\","
                          "quantile=\"0.5\"} ") != std::string::npos);
    EXPECT_TRUE(text.find("obs_req_latency_count{method=\"Echo\"} 3") !=
                std::string::npos);
    EXPECT_TRUE(text.find("obs_req_latency_count{method=\"Stats\"} 1") !=
                std::string::npos);
    // Exactly ONE TYPE line for the whole family.
    EXPECT_EQ((int)std::string::npos, (int)text.find("# TYPE", 7));
}

// ---------------- flag -> var bridge ----------------

TEST(FlagBridge, FlagsAreScrapeableVars) {
    ExposeFlagVariables();
    std::string v;
    // Bool flags render 0/1 (scrapeable), reflecting live mutation.
    ASSERT_TRUE(Variable::describe_exposed("flag_enable_rpcz", &v));
    const std::string before = v;
    EXPECT_TRUE(v == "0" || v == "1");
    const bool old = FLAGS_enable_rpcz.get();
    ASSERT_TRUE(SetFlagValue("enable_rpcz", old ? "false" : "true"));
    ASSERT_TRUE(Variable::describe_exposed("flag_enable_rpcz", &v));
    EXPECT_NE(v, before);
    FLAGS_enable_rpcz.set(old);
    // Numeric flags pass through as numbers -> gauges at /metrics.
    ASSERT_TRUE(Variable::describe_exposed("flag_rpcz_stitch_timeout_ms",
                                           &v));
    EXPECT_GT(atoll(v.c_str()), 0);
    const std::string dump = Variable::dump_prometheus();
    EXPECT_TRUE(dump.find("# TYPE flag_rpcz_stitch_timeout_ms gauge") !=
                std::string::npos);
}

// ---------------- span annotations (shed / cancel / retry) ----------------

namespace {

// All notes of the spans matching `trace` currently in the SpanDB.
std::string NotesForTrace(uint64_t trace, Span::Kind* kind_of_first_match,
                          const char* needle) {
    std::string all;
    for (const Span& s : SpanDB::singleton()->Recent(256, trace)) {
        for (const Span::Note& n : s.notes) {
            all += n.text + "\n";
            if (kind_of_first_match != nullptr &&
                strstr(n.text.c_str(), needle) != nullptr) {
                *kind_of_first_match = s.kind;
                kind_of_first_match = nullptr;  // keep the first
            }
        }
    }
    return all;
}

bool TraceHasNote(uint64_t trace, const char* needle) {
    return NotesForTrace(trace, nullptr, needle).find(needle) !=
           std::string::npos;
}

struct RpczOn {
    bool old;
    RpczOn() : old(FLAGS_enable_rpcz.get()) {
        FLAGS_enable_rpcz.set(true);
        // A prior test may have drained the Collector's 1000/s sampling
        // window this very second; idle past it so the first sample()
        // here opens a fresh window and the span is deterministic.
        usleep(1100 * 1000);
    }
    ~RpczOn() { FLAGS_enable_rpcz.set(old); }
};

class ParkUntilCanceledImpl : public test::EchoService {
public:
    void Echo(google::protobuf::RpcController* cntl_base,
              const test::EchoRequest* request, test::EchoResponse* response,
              google::protobuf::Closure* done) override {
        Controller* cntl = static_cast<Controller*>(cntl_base);
        entered.fetch_add(1, std::memory_order_release);
        for (int i = 0; i < 400; ++i) {
            if (cntl->IsCanceled()) break;
            fiber_usleep(5 * 1000);
        }
        response->set_message(request->message());
        done->Run();
    }
    std::atomic<int> entered{0};
};

struct SignalDone : google::protobuf::Closure {
    CountdownEvent ev{1};
    void Run() override { ev.signal(); }
};

}  // namespace

TEST(SpanAnnotations, RetryAndBudgetExhaustionLandOnTheSpan) {
    RpczOn rpcz;
    // Dead port: retryable failures. Budget of 1 -> one re-issue, then
    // the bucket runs dry and the exhaustion is annotated.
    Channel channel;
    ChannelOptions opts;
    opts.timeout_ms = 2000;
    opts.max_retry = 5;
    opts.retry_budget_tokens = 1;
    opts.retry_budget_ratio = 0.0;
    ASSERT_EQ(channel.Init("127.0.0.1:1", &opts), 0);
    test::EchoService_Stub stub(&channel);
    Controller cntl;
    test::EchoRequest req;
    req.set_message("doomed");
    test::EchoResponse res;
    stub.Echo(&cntl, &req, &res, nullptr);
    EXPECT_TRUE(cntl.Failed());
    const uint64_t trace = cntl.trace_id();
    ASSERT_NE(trace, 0u);
    // Spans flow through the Collector's background dispatcher.
    ASSERT_TRUE(WaitUntil(
        [&] { return !SpanDB::singleton()->Recent(8, trace).empty(); },
        3000));
    EXPECT_TRUE(TraceHasNote(trace, "re-issued try 1"))
        << NotesForTrace(trace, nullptr, "");
    EXPECT_TRUE(TraceHasNote(trace, "retry budget exhausted"))
        << NotesForTrace(trace, nullptr, "");
    EXPECT_TRUE(TraceHasNote(trace, "failed: "))
        << NotesForTrace(trace, nullptr, "");
}

TEST(SpanAnnotations, CanceledServerCallAnnotated) {
    RpczOn rpcz;
    ParkUntilCanceledImpl service;
    Server server;
    ASSERT_EQ(server.AddService(&service), 0);
    EndPoint listen;
    str2endpoint("127.0.0.1:0", &listen);
    ASSERT_EQ(server.Start(listen, nullptr), 0);
    EndPoint ep;
    str2endpoint("127.0.0.1", server.listened_port(), &ep);

    Channel channel;
    ChannelOptions opts;
    opts.timeout_ms = 5000;
    ASSERT_EQ(channel.Init(ep, &opts), 0);
    test::EchoService_Stub stub(&channel);
    Controller cntl;
    test::EchoRequest req;
    req.set_message("cancel-me");
    test::EchoResponse res;
    SignalDone done;
    stub.Echo(&cntl, &req, &res, &done);
    const uint64_t trace = cntl.trace_id();
    ASSERT_NE(trace, 0u);
    ASSERT_TRUE(
        WaitUntil([&] { return service.entered.load() >= 1; }, 3000));
    cntl.StartCancel();
    done.ev.wait();
    // Client span: the cancel verdict; server span: the delivered
    // cascade — both under ONE trace id.
    ASSERT_TRUE(WaitUntil(
        [&] {
            return TraceHasNote(trace, "canceled: upstream gave up") &&
                   TraceHasNote(trace, "canceled: wire CANCEL");
        },
        3000));
    Span::Kind kind = Span::CLIENT;
    NotesForTrace(trace, &kind, "canceled: upstream gave up");
    EXPECT_EQ(kind, Span::SERVER);
    server.Stop();
    server.Join();
}

TEST(SpanAnnotations, ExpiredDownstreamShedAnnotatedOnClientSpan) {
    RpczOn rpcz;
    // A healthy echo server...
    ParkUntilCanceledImpl service;  // parks only until canceled/400 loops
    Server server;
    ASSERT_EQ(server.AddService(&service), 0);
    EndPoint listen;
    str2endpoint("127.0.0.1:0", &listen);
    ASSERT_EQ(server.Start(listen, nullptr), 0);
    EndPoint ep;
    str2endpoint("127.0.0.1", server.listened_port(), &ep);

    // ...called under an upstream server context whose budget is ALREADY
    // spent: the downstream request is stamped timeout_ms=0, the server
    // sheds it on arrival, and the verdict is annotated on the client
    // span (the shed hop itself never allocates one — that is the point:
    // the stitched view still shows WHY).
    Controller upstream;
    upstream.InitServerSide(nullptr, EndPoint());
    upstream.set_server_deadline_us(monotonic_time_us() - 50 * 1000);
    uint64_t trace = 0;
    {
        ServerCallScope scope(&upstream);
        Channel channel;
        ChannelOptions opts;
        opts.timeout_ms = 2000;
        opts.max_retry = 0;
        ASSERT_EQ(channel.Init(ep, &opts), 0);
        test::EchoService_Stub stub(&channel);
        Controller cntl;
        test::EchoRequest req;
        req.set_message("stale");
        test::EchoResponse res;
        stub.Echo(&cntl, &req, &res, nullptr);
        EXPECT_TRUE(cntl.Failed());
        trace = cntl.trace_id();
    }
    ASSERT_NE(trace, 0u);
    ASSERT_TRUE(WaitUntil([&] { return TraceHasNote(trace, "failed: "); },
                          3000));
    server.Stop();
    server.Join();
}
