// Unit tests for tbase, mirroring the reference's butil test coverage
// (test/iobuf_unittest.cpp, test/resource_pool_unittest.cpp,
// test/flat_map_unittest.cpp, test/endpoint_unittest.cpp et al).
#include <unistd.h>

#include <atomic>
#include <thread>
#include <vector>

#include "tbase/doubly_buffered_data.h"
#include "tbase/endpoint.h"
#include "tbase/fast_rand.h"
#include "tbase/flags.h"
#include "tbase/flat_map.h"
#include "tbase/iobuf.h"
#include "tbase/logging.h"
#include "tbase/resource_pool.h"
#include "tbase/time.h"
#include "tbase/versioned_ref.h"
#include "ttest/ttest.h"

using namespace tpurpc;

TEST(IOBuf, AppendAndRead) {
    IOBuf buf;
    EXPECT_TRUE(buf.empty());
    buf.append("hello ");
    buf.append(std::string("world"));
    EXPECT_EQ(buf.size(), 11u);
    EXPECT_EQ(buf.to_string(), "hello world");
    EXPECT_TRUE(buf.equals("hello world"));
    EXPECT_EQ(buf.front_byte(), 'h');
}

TEST(IOBuf, LargeAppendSpansBlocks) {
    IOBuf buf;
    std::string big(100000, 'x');
    for (size_t i = 0; i < big.size(); ++i) big[i] = (char)('a' + i % 26);
    buf.append(big);
    EXPECT_EQ(buf.size(), big.size());
    EXPECT_GT(buf.backing_block_num(), 1u);
    EXPECT_EQ(buf.to_string(), big);
}

TEST(IOBuf, CutnZeroCopy) {
    IOBuf buf;
    std::string data(50000, 'q');
    buf.append(data);
    IOBuf head;
    size_t moved = buf.cutn(&head, 20000);
    EXPECT_EQ(moved, 20000u);
    EXPECT_EQ(head.size(), 20000u);
    EXPECT_EQ(buf.size(), 30000u);
    EXPECT_EQ(head.to_string(), std::string(20000, 'q'));
    EXPECT_EQ(buf.to_string(), std::string(30000, 'q'));
}

TEST(IOBuf, CutIntoBuffer) {
    IOBuf buf;
    buf.append("abcdefgh");
    char tmp[4];
    EXPECT_EQ(buf.cutn(tmp, 4), 4u);
    EXPECT_EQ(std::string(tmp, 4), "abcd");
    EXPECT_EQ(buf.to_string(), "efgh");
    char c;
    EXPECT_EQ(buf.cut1(&c), 0);
    EXPECT_EQ(c, 'e');
}

TEST(IOBuf, PopFrontBack) {
    IOBuf buf;
    buf.append("0123456789");
    EXPECT_EQ(buf.pop_front(3), 3u);
    EXPECT_EQ(buf.pop_back(2), 2u);
    EXPECT_EQ(buf.to_string(), "34567");
}

TEST(IOBuf, ZeroCopyAppendSharesBlocks) {
    IOBuf a;
    a.append(std::string(10000, 'z'));
    IOBuf b;
    b.append(a);  // zero-copy ref share
    EXPECT_EQ(a.size(), b.size());
    a.clear();
    EXPECT_EQ(b.to_string(), std::string(10000, 'z'));  // b keeps blocks alive
}

TEST(IOBuf, CopyToWithOffset) {
    IOBuf buf;
    buf.append("hello world");
    std::string s;
    buf.copy_to(&s, 5, 6);
    EXPECT_EQ(s, "world");
    EXPECT_EQ(buf.size(), 11u);  // copy_to doesn't consume
}

TEST(IOBuf, MoveSemantics) {
    IOBuf a;
    a.append("data");
    IOBuf b(std::move(a));
    EXPECT_EQ(b.to_string(), "data");
    EXPECT_TRUE(a.empty());
    IOBuf c;
    c = std::move(b);
    EXPECT_EQ(c.to_string(), "data");
}

TEST(IOBuf, ManyRefsGrowToBigView) {
    IOBuf buf;
    IOBuf scraps;
    // Force many non-mergeable refs by cutting from different bufs.
    std::string expect;
    for (int i = 0; i < 50; ++i) {
        IOBuf tmp;
        std::string piece(100, (char)('a' + i % 26));
        tmp.append(piece);
        expect += piece;
        buf.append(tmp);
    }
    EXPECT_EQ(buf.to_string(), expect);
    IOBuf out;
    buf.cutn(&out, expect.size() / 2);
    EXPECT_EQ(out.to_string() + buf.to_string(), expect);
}

TEST(IOBuf, FdRoundTrip) {
    int fds[2];
    ASSERT_EQ(pipe(fds), 0);
    IOBuf out;
    std::string payload(60000, 'p');
    out.append(payload);
    size_t total_written = 0;
    while (total_written < payload.size()) {
        // Drain concurrently to avoid pipe-buffer deadlock.
        ssize_t w = out.cut_into_file_descriptor(fds[1], 16384);
        ASSERT_GT(w, 0);
        total_written += (size_t)w;
        IOPortal in;
        ssize_t r = in.append_from_file_descriptor(fds[0], 65536);
        ASSERT_GT(r, 0);
        EXPECT_EQ(in.to_string(), std::string((size_t)r, 'p'));
    }
    close(fds[0]);
    close(fds[1]);
}

TEST(IOBuf, PortalAccumulates) {
    int fds[2];
    ASSERT_EQ(pipe(fds), 0);
    IOPortal in;
    std::string sent;
    for (int i = 0; i < 10; ++i) {
        std::string chunk(1000, (char)('0' + i));
        ASSERT_EQ(write(fds[1], chunk.data(), chunk.size()),
                  (ssize_t)chunk.size());
        sent += chunk;
        ASSERT_GT(in.append_from_file_descriptor(fds[0], 65536), 0);
    }
    EXPECT_EQ(in.to_string(), sent);
    close(fds[0]);
    close(fds[1]);
}

TEST(ResourcePool, GetAddressReturn) {
    struct Obj {
        int x;
    };
    ResourceId id1, id2;
    Obj* o1 = get_resource<Obj>(&id1);
    ASSERT_TRUE(o1 != nullptr);
    o1->x = 42;
    Obj* o2 = get_resource<Obj>(&id2);
    ASSERT_TRUE(o2 != nullptr);
    EXPECT_NE(o1, o2);
    EXPECT_EQ(address_resource<Obj>(id1), o1);
    EXPECT_EQ(address_resource<Obj>(id1)->x, 42);
    return_resource<Obj>(id1);
    // Slot gets recycled.
    ResourceId id3;
    Obj* o3 = get_resource<Obj>(&id3);
    EXPECT_EQ(o3, o1);
    return_resource<Obj>(id2);
    return_resource<Obj>(id3);
}

struct TestVRef : public VersionedRefWithId<TestVRef> {
    int failed_count = 0;
    int recycled_count = 0;
    void OnFailed() { ++failed_count; }
    void OnRecycle() { ++recycled_count; }
};

TEST(VersionedRef, Lifecycle) {
    VRefId id;
    TestVRef* obj = nullptr;
    ASSERT_EQ(TestVRef::Create(&id, &obj), 0);
    obj->failed_count = 0;
    obj->recycled_count = 0;
    EXPECT_EQ(obj->nref(), 1);

    TestVRef* addr = TestVRef::Address(id);
    ASSERT_TRUE(addr == obj);
    EXPECT_EQ(obj->nref(), 2);

    EXPECT_EQ(obj->SetFailed(), 0);
    EXPECT_EQ(obj->failed_count, 1);
    EXPECT_EQ(obj->SetFailed(), -1);  // second failure is a no-op
    EXPECT_TRUE(obj->Failed());

    // Stale address after failure.
    EXPECT_TRUE(TestVRef::Address(id) == nullptr);

    EXPECT_EQ(obj->recycled_count, 0);
    obj->Dereference();  // drop our Address ref -> recycle
    EXPECT_EQ(obj->recycled_count, 1);

    // Slot is reusable with a new even version; old id stays dead.
    VRefId id2;
    TestVRef* obj2 = nullptr;
    ASSERT_EQ(TestVRef::Create(&id2, &obj2), 0);
    EXPECT_NE(id2, id);
    EXPECT_TRUE(TestVRef::Address(id) == nullptr);
    TestVRef* a2 = TestVRef::Address(id2);
    EXPECT_TRUE(a2 == obj2);
    a2->Dereference();
    obj2->SetFailed();
}

TEST(FlatMap, Basics) {
    FlatMap<std::string, int> m;
    EXPECT_TRUE(m.seek("a") == nullptr);
    m["a"] = 1;
    m["b"] = 2;
    EXPECT_EQ(m.size(), 2u);
    EXPECT_EQ(*m.seek("a"), 1);
    m["a"] = 10;
    EXPECT_EQ(*m.seek("a"), 10);
    EXPECT_EQ(m.erase("a"), 1u);
    EXPECT_TRUE(m.seek("a") == nullptr);
    EXPECT_EQ(m.size(), 1u);
}

TEST(FlatMap, GrowthKeepsEntries) {
    FlatMap<int, int> m;
    for (int i = 0; i < 1000; ++i) m[i] = i * 7;
    EXPECT_EQ(m.size(), 1000u);
    for (int i = 0; i < 1000; ++i) {
        int* v = m.seek(i);
        ASSERT_TRUE(v != nullptr);
        EXPECT_EQ(*v, i * 7);
    }
}

TEST(FlatMap, EraseChurnDoesNotDegrade) {
    // Regression: tombstone accumulation must trigger rehash, not an
    // unbounded/never-ending probe loop.
    FlatMap<int, int> m;
    for (int round = 0; round < 200; ++round) {
        for (int i = 0; i < 10; ++i) m[round * 10 + i] = i;
        for (int i = 0; i < 10; ++i) {
            EXPECT_EQ(m.erase(round * 10 + i), 1u);
        }
    }
    EXPECT_EQ(m.size(), 0u);
    m[12345] = 1;
    EXPECT_EQ(*m.seek(12345), 1);
}

TEST(FlatMap, CaseIgnored) {
    CaseIgnoredFlatMap<int> m;
    m["Content-Type"] = 5;
    EXPECT_TRUE(m.seek("content-type") != nullptr);
    EXPECT_EQ(*m.seek("CONTENT-TYPE"), 5);
}

TEST(EndPoint, ParseFormat) {
    EndPoint ep;
    ASSERT_EQ(str2endpoint("127.0.0.1:8080", &ep), 0);
    EXPECT_EQ(ep.port, 8080);
    EXPECT_EQ(endpoint2str(ep), "127.0.0.1:8080");
    EXPECT_NE(str2endpoint("not an endpoint", &ep), 0);
    EXPECT_NE(str2endpoint("1.2.3.4:99999", &ep), 0);
    ASSERT_EQ(hostname2endpoint("localhost:80", &ep), 0);
    EXPECT_EQ(ep.port, 80);
}

TEST(DoublyBufferedData, ReadModify) {
    DoublyBufferedData<std::vector<int>> dbd;
    dbd.Modify([](std::vector<int>& v) {
        v.push_back(42);
        return true;
    });
    {
        DoublyBufferedData<std::vector<int>>::ScopedPtr ptr;
        ASSERT_EQ(dbd.Read(&ptr), 0);
        ASSERT_EQ(ptr->size(), 1u);
        EXPECT_EQ((*ptr)[0], 42);
    }
    // Concurrent readers while modifying.
    std::atomic<bool> stop{false};
    std::thread reader([&] {
        while (!stop.load()) {
            DoublyBufferedData<std::vector<int>>::ScopedPtr ptr;
            dbd.Read(&ptr);
            if (!ptr->empty()) {
                volatile int x = (*ptr)[0];
                (void)x;
            }
        }
    });
    for (int i = 0; i < 100; ++i) {
        dbd.Modify([i](std::vector<int>& v) {
            v.assign(3, i);
            return true;
        });
    }
    stop = true;
    reader.join();
    DoublyBufferedData<std::vector<int>>::ScopedPtr ptr;
    dbd.Read(&ptr);
    EXPECT_EQ(ptr->size(), 3u);
}

DEFINE_int32(test_flag_int, 7, "test flag");
DEFINE_bool(test_flag_bool, false, "test flag");
DEFINE_string(test_flag_str, "abc", "test flag");

TEST(Flags, DefineFindSet) {
    EXPECT_EQ(FLAGS_test_flag_int.get(), 7);
    EXPECT_TRUE(SetFlagValue("test_flag_int", "99"));
    EXPECT_EQ(FLAGS_test_flag_int.get(), 99);
    EXPECT_FALSE(SetFlagValue("test_flag_int", "not_a_number"));
    EXPECT_EQ(FLAGS_test_flag_int.get(), 99);
    EXPECT_FALSE(SetFlagValue("no_such_flag", "1"));
    EXPECT_TRUE(SetFlagValue("test_flag_bool", "true"));
    EXPECT_TRUE(FLAGS_test_flag_bool.get());
    EXPECT_TRUE(SetFlagValue("test_flag_str", "xyz"));
    EXPECT_EQ(FLAGS_test_flag_str.get(), "xyz");
    FLAGS_test_flag_int.set_validator([](int32_t v) { return v < 100; });
    EXPECT_FALSE(SetFlagValue("test_flag_int", "500"));
    EXPECT_TRUE(SetFlagValue("test_flag_int", "50"));
    EXPECT_EQ(FLAGS_test_flag_int.get(), 50);
}

TEST(Misc, FastRandAndTime) {
    uint64_t a = fast_rand();
    uint64_t b = fast_rand();
    EXPECT_NE(a, b);
    for (int i = 0; i < 100; ++i) {
        EXPECT_LT(fast_rand_less_than(10), 10u);
        double d = fast_rand_double();
        EXPECT_GE(d, 0.0);
        EXPECT_LT(d, 1.0);
    }
    int64_t t0 = monotonic_time_us();
    int64_t w0 = gettimeofday_us();
    EXPECT_GT(t0, 0);
    EXPECT_GT(w0, 0);
    EXPECT_GT(ticks_per_us(), 0.0);
}

// ---------------- ResourcePool TLS free chunks ----------------
// Reference resource_pool_inl.h: per-thread free chunks; a live id is
// never handed to two owners concurrently.

namespace {
struct PoolItem {
    std::atomic<int> owner{0};
};
}  // namespace

TEST(ResourcePool, TlsChunksNoDoubleOwnership) {
    std::atomic<bool> stop{false};
    std::atomic<int> violations{0};
    auto worker = [&](int me) {
        std::vector<ResourceId> held;
        uint64_t rng = (uint64_t)me * 2654435761u + 1;
        while (!stop.load(std::memory_order_relaxed)) {
            rng = rng * 6364136223846793005ull + 1442695040888963407ull;
            if ((rng >> 33) % 2 == 0 || held.empty()) {
                ResourceId id;
                PoolItem* it = get_resource<PoolItem>(&id);
                if (it == nullptr) continue;
                int expected = 0;
                if (!it->owner.compare_exchange_strong(expected, me)) {
                    violations.fetch_add(1);  // someone else owns this slot!
                }
                held.push_back(id);
            } else {
                const ResourceId id = held.back();
                held.pop_back();
                PoolItem* it = address_resource<PoolItem>(id);
                it->owner.store(0);
                return_resource<PoolItem>(id);
            }
            if (held.size() > 300) {
                for (ResourceId id : held) {
                    address_resource<PoolItem>(id)->owner.store(0);
                    return_resource<PoolItem>(id);
                }
                held.clear();
            }
        }
        for (ResourceId id : held) {
            address_resource<PoolItem>(id)->owner.store(0);
            return_resource<PoolItem>(id);
        }
    };
    std::vector<std::thread> threads;
    for (int i = 1; i <= 4; ++i) threads.emplace_back(worker, i);
    usleep(300 * 1000);
    stop.store(true);
    for (auto& t : threads) t.join();
    EXPECT_EQ(violations.load(), 0);
}

TEST(Logging, RateLimitedMacros) {
    // Compile + semantics: LOG_EVERY_N passes on iterations 0, n, 2n...
    // and LOG_EVERY_SECOND at most once per second (asserted via the
    // sink capture).
    std::atomic<int> captured{0};
    SetLogSink([&](int, const char*, int, const std::string&) {
        captured.fetch_add(1);
        return true;  // suppress stderr
    });
    for (int i = 0; i < 10; ++i) {
        LOG_EVERY_N(ERROR, 5) << "every-5 " << i;
    }
    EXPECT_EQ(captured.load(), 2);  // i=0 and i=5
    captured.store(0);
    for (int i = 0; i < 100; ++i) {
        LOG_EVERY_SECOND(ERROR) << "every-second " << i;
    }
    EXPECT_EQ(captured.load(), 1);
    SetLogSink(nullptr);
}
